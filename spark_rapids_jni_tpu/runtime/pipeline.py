"""Pipelined out-of-core execution: overlap host IO/decode with device compute.

The serial out-of-core executor (outofcore.py) reads, decodes, transfers
and computes one chunk at a time: the device idles through every Parquet/
ORC decode and the host idles through every device step. The reference
stack hides exactly this latency by feeding the GPU from cuDF's chunked
readers asynchronously; this module is the TPU-side equivalent — a
bounded-queue multi-stage executor:

    read/decode      host staging       device transfer     merge
    (thread pool) -> (seq-ordered    -> (+compute, the   -> (consumer,
                      exact-bytes        consumer side)      outofcore
                      admission)                             merge window)

Design points, in contract order:

* **Determinism** — chunks are delivered to the consumer in source order
  regardless of decode completion order, so the partial->merge algebra
  sees exactly the serial sequence and results are bit-identical.
* **Backpressure through the MemoryLimiter** — each chunk's admission
  reserves its EXACT device bytes (decode produces a host-side
  ``HostTableChunk`` first, so the size is known) before the
  host->device copy runs. Admissions happen in sequence order through a
  turnstile: a blocked admission can only ever be waiting on releases
  from already-delivered chunks, never on a later chunk — which is what
  makes a minimum budget degrade to effectively-serial instead of
  deadlocking.
* **Prompt error propagation** — a stage failure surfaces at that
  chunk's position in the output order (the consumer is never handed a
  later chunk first); the generator's cleanup cancels the pump and
  workers, drains the queue, and releases every undelivered reservation
  (the no-phantom-usage contract ``prefetch_chunks`` established).
* **Instrumentation** — ``pipeline.*`` counters/gauges in the telemetry
  registry (chunks, decode/transfer time, producer/consumer stall time,
  queue depth, chunks in flight), ``trace_range`` spans per stage, and
  ``inject_fault`` (tests) to delay or fail any stage by name.

Config: ``pipeline.enabled`` switches the out-of-core executor onto this
path (the serial path remains the reference implementation);
``pipeline.prefetch_depth`` — also via the short env var
``SPARK_RAPIDS_TPU_PIPELINE_PREFETCH`` — bounds how far the producer
runs ahead; ``pipeline.decode_threads`` sizes the decode pool (native
decode releases the GIL, so threads genuinely overlap).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from contextlib import contextmanager
from typing import Callable, Iterable, Iterator, Union

from spark_rapids_jni_tpu import telemetry
from spark_rapids_jni_tpu.runtime import faults
from spark_rapids_jni_tpu.telemetry import spans
from spark_rapids_jni_tpu.runtime.memory import (
    HostTableChunk,
    MemoryLimiter,
    _table_nbytes,
)
from spark_rapids_jni_tpu.utils.config import get_option
from spark_rapids_jni_tpu.utils.log import get_logger
from spark_rapids_jni_tpu.utils.tracing import trace_range

_log = get_logger(__name__)

#: Stage names, in execution order, as seen by ``inject_fault`` hooks.
STAGES = ("decode", "staging", "transfer", "compute", "merge")

#: One pipeline work item: an already-materialized device Table, or a
#: zero-arg thunk producing either a HostTableChunk (preferred: exact
#: admission before the device copy) or a device Table.
ChunkSource = Union[Callable[[], object], object]


def pipeline_enabled() -> bool:
    return bool(get_option("pipeline.enabled"))


def configured_prefetch_depth() -> int:
    """Prefetch depth: the short env var SPARK_RAPIDS_TPU_PIPELINE_PREFETCH
    wins over the ``pipeline.prefetch_depth`` option (same pattern as
    SPARK_RAPIDS_TPU_DISPATCH_CACHE for the dispatch layer)."""
    env = os.environ.get("SPARK_RAPIDS_TPU_PIPELINE_PREFETCH")
    if env is not None and env.strip():
        return max(int(env), 1)
    return max(int(get_option("pipeline.prefetch_depth")), 1)


def configured_decode_threads() -> int:
    return max(int(get_option("pipeline.decode_threads")), 1)


# ---- shared decode pool -----------------------------------------------------
#
# Concurrent pipelines (and the multi-query serving runtime) would each spin
# a private ThreadPoolExecutor, oversubscribing the host decode threads N
# ways. The shared pool is one process-wide executor every concurrent user
# can borrow; pipeline_chunks accepts it via ``pool=`` and never shuts a
# borrowed pool down.

_shared_pool: ThreadPoolExecutor | None = None
_shared_pool_lock = threading.Lock()


def shared_decode_pool() -> ThreadPoolExecutor:
    """The process-wide host decode/staging pool, created lazily at
    ``pipeline.decode_threads`` workers. Callers submit work but never
    shut it down; ``reset_shared_decode_pool`` exists for test isolation."""
    global _shared_pool
    with _shared_pool_lock:
        if _shared_pool is None:
            _shared_pool = ThreadPoolExecutor(
                max_workers=configured_decode_threads(),
                thread_name_prefix="tpu-pipeline-decode-shared")
        return _shared_pool


def reset_shared_decode_pool() -> None:
    """Shut down and drop the shared pool (test isolation / re-config)."""
    global _shared_pool
    with _shared_pool_lock:
        pool, _shared_pool = _shared_pool, None
    if pool is not None:
        pool.shutdown(wait=True)


# ---- fault injection (tests) ------------------------------------------------
#
# Pipeline stages now fire through the global runtime/faults.py registry as
# seams "pipeline.<stage>". inject_fault below is kept as a thin DEPRECATED
# alias for existing callers; new code uses faults.inject with a FaultSpec /
# FaultScript (or any injector callable) targeting the "pipeline.*" seams.


@contextmanager
def inject_fault(hook):
    """DEPRECATED alias over :func:`runtime.faults.inject`.

    ``hook(stage, seq)`` is invoked at each stage entry with the bare stage
    name (one of ``STAGES``) and the chunk sequence number; it may sleep
    (injected delay) or raise (injected failure). Only ``pipeline.*`` seam
    firings reach the hook — legacy hooks never see the registry's other
    seams. Prefer ``faults.inject`` with the ``pipeline.<stage>`` seam
    names."""

    def _adapter(seam, seq, ctx):
        if seam.startswith("pipeline."):
            hook(seam[len("pipeline."):], seq)

    with faults.inject(_adapter):
        yield


def _maybe_fault(stage: str, seq: int) -> None:
    try:
        faults.fire("pipeline." + stage, seq)
    except BaseException:
        # legacy counter: tests and the bench assert on it by name
        telemetry.REGISTRY.counter("pipeline.faults_injected").inc()
        raise


class _Cancelled(Exception):
    """Internal: a worker observed the cancel flag mid-stage."""


def _us(seconds: float) -> int:
    return max(int(seconds * 1e6), 0)


def pipeline_chunks(
    sources: Iterable[ChunkSource],
    *,
    limiter: MemoryLimiter | None = None,
    depth: int | None = None,
    decode_threads: int | None = None,
    pool: ThreadPoolExecutor | None = None,
    cancel_token=None,
) -> Iterator:
    """Run chunk sources through the async pipeline; yield device Tables
    in source order.

    ``sources`` iterates work items: zero-arg decode thunks returning a
    ``HostTableChunk`` (the chunked readers' ``chunk_sources()``) or a
    device ``Table``; already-materialized Tables are accepted directly
    for drop-in compatibility with ``prefetch_chunks`` call sites.

    Reservation contract (same as ``prefetch_chunks``): when ``limiter``
    is given the pipeline reserves each chunk before delivering it and
    the CALLER must release ``_table_nbytes(chunk)`` after use. For
    thunks that decode to ``HostTableChunk`` the reservation is exact and
    taken BEFORE the host->device copy — blocking until budget frees, so
    budgets below the overlap window serialize instead of raising. For
    sources that materialize device Tables directly the bytes are already
    resident when their size is learned, so the admission still blocks
    for budget but the residency window is ``depth + decode_threads``
    chunks (the documented ``prefetch_chunks`` posture) — size the budget
    accordingly or use host-staged thunks.

    On error or early close all undelivered reservations are released:
    no hangs, no orphaned reservations.

    ``pool`` lends an external decode executor (e.g.
    ``shared_decode_pool()``, so N concurrent pipelines share one set of
    decode threads instead of oversubscribing the host N ways); a lent
    pool is never shut down here — cleanup waits on this run's own
    futures only.

    ``cancel_token`` (a ``resilience.CancelToken``) makes the run
    cooperatively cancellable: the token is checked inside the decode
    pool before each chunk decodes and at each delivery, and a blocked
    admission wakes when the token fires. Cancellation (or deadline
    expiry) raises ``QueryCancelled`` to the consumer through the same
    cleanup path as any stage failure, so every undelivered reservation
    is released in the generator's ``finally``.
    """
    depth = configured_prefetch_depth() if depth is None \
        else max(int(depth), 1)
    workers = configured_decode_threads() if decode_threads is None \
        else max(int(decode_threads), 1)

    reg = telemetry.REGISTRY
    reg.counter("pipeline.runs").inc()
    cancel = threading.Event()
    # the consumer thread's open span (e.g. the query root or an
    # out-of-core rung): pool threads have empty span stacks, so each
    # chunk span names it as an EXPLICIT parent to stay in the tree
    span_parent = spans.current_span()

    class _either_cancel:
        """Duck-typed Event for reserve_blocking: set when the pipeline's
        internal cancel OR the caller's cancel token fired (cancelled()
        also latches deadline expiry, so a blocked admission wakes on it)."""

        @staticmethod
        def is_set() -> bool:
            return cancel.is_set() or (
                cancel_token is not None and cancel_token.cancelled())

    out_q: "queue.Queue" = queue.Queue(maxsize=depth)
    # admission turnstile: the next sequence number allowed to reserve
    admit = threading.Condition()
    admit_seq = [0]

    def _advance_turnstile(seq: int) -> None:
        with admit:
            admit_seq[0] = seq + 1
            admit.notify_all()

    def _admission(seq: int, nbytes: int) -> bool:
        """Stage 2, host staging: seq-ordered budget admission. Returns
        False when cancelled (caller raises _Cancelled)."""
        t0 = time.perf_counter()
        with admit:
            while admit_seq[0] != seq:
                if _either_cancel.is_set():
                    return False
                admit.wait(0.05)
        ok = True
        try:
            if limiter is not None:
                ok = limiter.reserve_blocking(nbytes, cancel=_either_cancel)
        finally:
            # advance even on failure/cancel so later workers see the
            # cancel flag instead of waiting on a dead turn
            _advance_turnstile(seq)
        reg.counter("pipeline.producer_stall_us").inc(
            _us(time.perf_counter() - t0))
        if ok:
            reg.gauge("pipeline.chunks_in_flight").add(1)
        return ok

    def _work(seq: int, src):
        """Stages 1-3 for one chunk, on a pool thread. Returns
        (device_table, reserved_nbytes); ownership of the reservation
        passes to whoever consumes the future."""
        if cancel.is_set():
            raise _Cancelled()
        if cancel_token is not None:
            # the decode-pool checkpoint: a cancelled/expired query stops
            # before decoding its next chunk, not after
            cancel_token.check("pipeline.decode")
        _maybe_fault("decode", seq)
        # explicit parent: this runs on a pool thread whose own span
        # stack is empty; the stage trace_ranges below nest under the
        # chunk span through this thread's stack
        with spans.child("pipeline.chunk", parent=span_parent, seq=seq):
            t0 = time.perf_counter()
            with trace_range("pipeline.decode"):
                payload = src() if callable(src) else src
            reg.counter("pipeline.decode_us").inc(
                _us(time.perf_counter() - t0))
            host_staged = isinstance(payload, HostTableChunk)
            nb = payload.nbytes if host_staged else _table_nbytes(payload)
            _maybe_fault("staging", seq)
            with trace_range("pipeline.staging"):
                if not _admission(seq, nb):
                    if cancel_token is not None and cancel_token.cancelled():
                        # surface the classified QueryCancelled, not the
                        # internal teardown marker
                        cancel_token.check("pipeline.staging")
                    raise _Cancelled()
            held = nb if limiter is not None else 0
            try:
                _maybe_fault("transfer", seq)
                if host_staged:
                    t1 = time.perf_counter()
                    with trace_range("pipeline.transfer"):
                        table = payload.stage()
                    reg.counter("pipeline.transfer_us").inc(
                        _us(time.perf_counter() - t1))
                    # true-up: the consumer releases _table_nbytes(chunk),
                    # so the held reservation must equal it exactly (it
                    # does by construction; this guards the accounting
                    # invariant)
                    actual = _table_nbytes(table)
                    if limiter is not None and actual != held:
                        if actual > held:
                            limiter.reserve(actual - held)
                        else:
                            limiter.release(held - actual)
                        held = actual
                    nb = actual
                else:
                    table = payload
                return table, nb
            except BaseException:
                if limiter is not None and held:
                    limiter.release(held)
                reg.gauge("pipeline.chunks_in_flight").add(-1)
                raise

    owns_pool = pool is None
    if pool is None:
        pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="tpu-pipeline-decode")
    submitted: list = []
    pump_exc: list = []

    def _put_cancellable(item) -> bool:
        while not cancel.is_set():
            try:
                out_q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _pump():
        try:
            seq = 0
            for src in sources:
                if _either_cancel.is_set():
                    return
                fut = pool.submit(_work, seq, src)
                submitted.append(fut)
                if not _put_cancellable(("ok", fut)):
                    return
                seq += 1
        except BaseException as exc:  # noqa: BLE001 — re-raised at consumer
            pump_exc.append(exc)
            _put_cancellable(("err", exc))
            return
        _put_cancellable(("end", None))

    pump = threading.Thread(target=_pump, daemon=True,
                            name="tpu-pipeline-pump")
    pump.start()
    delivered = 0
    try:
        while True:
            t0 = time.perf_counter()
            kind, payload = out_q.get()
            if kind == "err":
                raise payload
            if kind == "end":
                break
            if cancel_token is not None:
                # delivery checkpoint: raising BEFORE result() leaves the
                # future's reservation to the finally-drain below
                cancel_token.check("pipeline.deliver")
            table, nb = payload.result()  # raises the worker's exception
            reg.counter("pipeline.consumer_stall_us").inc(
                _us(time.perf_counter() - t0))
            reg.gauge("pipeline.queue_depth").set(out_q.qsize())
            reg.gauge("pipeline.chunks_in_flight").add(-1)
            reg.counter("pipeline.chunks").inc()
            delivered += 1
            yield table
    finally:
        cancel.set()
        pump.join()
        if owns_pool:
            pool.shutdown(wait=True)
        # drain: every submitted-but-undelivered chunk that completed
        # holds a reservation nobody will ever release — release them
        # here (the no-phantom-usage contract). Failed/cancelled workers
        # released their own in _work.
        for fut in submitted[delivered:]:
            try:
                _table, nb = fut.result()
            except BaseException:  # noqa: BLE001 — already propagated
                continue
            reg.gauge("pipeline.chunks_in_flight").add(-1)
            if limiter is not None and nb:
                limiter.release(nb)
