"""Fault-tolerant serving fleet: supervised QueryServer replicas with
health-checked routing and bit-identical query failover.

The serving substrate is hardened *inside* one process (classified
retries, the degradation ladder, sealed spill/wire paths) but one process
is still the whole blast radius: a wedged or SIGKILLed replica takes
every session with it. This module turns "a server" into "a service":

- :class:`QueryFleet` (the supervisor + router) boots N
  :class:`~.server.QueryServer` replicas as worker subprocesses
  (``python -m spark_rapids_jni_tpu.runtime.fleet --worker``), each on
  its own end of a local socketpair carrying length-prefixed,
  integrity-sealed pickle frames — the same seal/verify discipline as
  ``parallel/dcn.py``'s wire path (table payloads inside a frame are
  codec-framed by ``dcn.serialize_table`` under the ``compress.wire``
  seam, so the trailer is the outermost wrapper over already-compressed
  bytes).
- The **router** places each submit on the healthy replica with the
  lowest outstanding cost: a supervisor-side EMA of measured per-plan-
  signature wall time over that replica's in-flight set, tie-broken by
  the live queue depth each liveness pong reports.
- The **supervisor** pings every replica each
  ``fleet.heartbeat_interval_s``; a replica silent past
  ``fleet.heartbeat_timeout_s``, exiting nonzero, or dying by signal is
  a *classified* event — :func:`~.resilience.classify_worker_exit` maps
  the exit shape into :class:`~.resilience.ReplicaDeadError` (transient
  at the ``fleet.dispatch`` seam ONLY, where re-placement is the
  structural recovery).
- **Failover**: the dead replica's in-flight queries re-dispatch to a
  healthy replica under the bounded ``fleet.failover_budget``.
  Determinism + the result-cache idempotency pair (plan signature,
  input fingerprint) make this safe: a failed-over query must come back
  bit-identical (fingerprints compared against the supervisor's result
  memo), and a late duplicate result from a kill-raced replica is
  fingerprint-checked then dropped — never silently served twice.
- **Circuit breaker**: a replica that crashes
  ``fleet.quarantine_after`` times in a row (no successfully served
  query in between) is quarantined — no restarts, no placements — and
  every death before that restarts with exponential backoff
  (``fleet.restart_backoff_s`` × ``fleet.restart_backoff_multiplier``).
- **Drain/recycle** (:meth:`QueryFleet.recycle`): stop admitting on one
  replica, let its in-flight queries finish, flush its learned
  estimates (merged into the shared ``server.estimate_path`` state
  file), then restart it warm off the shared JAX persistent compile
  cache — a planned exit, not a classified death.

Every supervision decision is observable: unconditional ``fleet.*``
counters, ``record_fleet`` events, replica-tagged telemetry (workers
stamp ``replica=`` on every record and span via ``telemetry.replica``),
and a flight-record artifact dumped on every replica death.
"""

from __future__ import annotations

import collections
import itertools
import os
import pickle
import socket
import struct
import subprocess
import sys
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

from spark_rapids_jni_tpu.runtime import compress, faults, fusion, resilience
from spark_rapids_jni_tpu.runtime import resultcache
from spark_rapids_jni_tpu.telemetry import spans
from spark_rapids_jni_tpu.telemetry.events import record_fleet
from spark_rapids_jni_tpu.telemetry.registry import REGISTRY
from spark_rapids_jni_tpu.utils.config import get_option
from spark_rapids_jni_tpu.utils.log import get_logger

__all__ = ["QueryFleet", "FleetTicket", "live_fleets", "main"]

_log = get_logger("fleet")

# test hooks (environment of ONE replica, set via per_replica_env):
# crash immediately at boot (crash-loop drills), and a fixed pre-serve
# delay that keeps a query deterministically in flight for kill-mid-query
# chaos tests
_ENV_BOOT_CRASH = "SPARK_RAPIDS_TPU_FLEET_TEST_BOOT_CRASH"
_ENV_SERVE_DELAY = "SPARK_RAPIDS_TPU_FLEET_TEST_SERVE_DELAY_MS"

_LIVE_FLEETS: "weakref.WeakSet[QueryFleet]" = weakref.WeakSet()


def live_fleets() -> List["QueryFleet"]:
    """Every open fleet in this process (telemetry ``top`` fleet view)."""
    return [f for f in list(_LIVE_FLEETS) if not f._closed]


# ---------------------------------------------------------------------------
# framing: length-prefixed, integrity-sealed pickle frames on a socketpair
# ---------------------------------------------------------------------------


class _FrameChannel:
    """One control channel: 8-byte little-endian length prefix + an
    integrity-sealed pickle payload per frame (``integrity.enabled()``
    gates the seal/verify pair exactly like the DCN wire path; off is
    byte-for-byte raw pickle frames). Table payloads inside a message
    travel as ``dcn.serialize_table`` blobs, which the columnar codec
    already framed under ``compress.wire`` — compress -> seal ordering.

    Sends are serialized by a lock (worker query threads and the
    worker's control loop share one socket); a corrupt frame raises the
    classified :class:`~.resilience.CorruptDataError` out of ``recv``
    and the caller treats the channel — and therefore the replica — as
    dead."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()

    def send(self, msg: Dict[str, Any]) -> None:
        from spark_rapids_jni_tpu.runtime import integrity

        blob = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
        if integrity.enabled():
            blob = integrity.seal(blob)
        REGISTRY.counter("fleet.link_bytes").inc(8 + len(blob))
        with self._send_lock:
            self._sock.sendall(struct.pack("<Q", len(blob)) + blob)

    def recv(self) -> Dict[str, Any]:
        from spark_rapids_jni_tpu.runtime import integrity

        with self._recv_lock:
            # _recv_lock exists ONLY to serialize whole-frame reads on
            # this one socket: it guards no other state, so blocking in
            # recv wedges nothing but the channel's other readers, who
            # must wait for the frame boundary anyway.
            # tpulint: disable=blocking-call-under-lock
            hdr = self._recv_exact(8)
            (length,) = struct.unpack("<Q", hdr)
            # same deliberate frame read  # tpulint: disable=blocking-call-under-lock
            framed = self._recv_exact(length)
        REGISTRY.counter("fleet.link_bytes").inc(8 + length)
        if integrity.enabled():
            framed = integrity.verify(framed, seam="integrity.wire",
                                      op="fleet.recv")
        return pickle.loads(framed)

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        got = 0
        while got < n:
            # runs under _recv_lock by design: the lock serializes frame
            # reads on this socket and guards nothing else (see recv()).
            # tpulint: disable=blocking-call-under-lock
            chunk = self._sock.recv(min(n - got, 1 << 20))
            if not chunk:
                raise ConnectionError("fleet peer closed the control socket")
            chunks.append(chunk)
            got += len(chunk)
        return b"".join(chunks)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _encode_table(table) -> bytes:
    from spark_rapids_jni_tpu.parallel import dcn

    if compress.seam_enabled("integrity.wire"):
        # payload rides the columnar codec inside serialize_table; count
        # it so the fleet's share of wire codec work is attributable
        REGISTRY.counter("fleet.codec_framed_tables").inc()
    return dcn.serialize_table(table)


def _decode_table(blob: bytes):
    from spark_rapids_jni_tpu.parallel import dcn

    return dcn.deserialize_table(blob)


# ---------------------------------------------------------------------------
# client surface
# ---------------------------------------------------------------------------


class FleetTicket:
    """One fleet-submitted query's future. Resolves to the plan's
    ``FusedResult`` (:meth:`result`), or raises the classified failure
    (:class:`~.resilience.ReplicaDeadError` when every failover died,
    the replica-reported classified error otherwise). ``status`` walks
    queued -> dispatched -> served | failed; ``dispatches`` counts
    placements (> 1 means the query failed over)."""

    def __init__(self, qid: int, session: str, plan_name: str):
        self.qid = qid
        self.session = session
        self.plan_name = plan_name
        self.status = "queued"
        self.replica: Optional[str] = None
        self.dispatches = 0
        self.wall_ms: Optional[float] = None
        self.fingerprint: Optional[str] = None
        self._value: Any = None
        self._exc: Optional[BaseException] = None
        self._done = threading.Event()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"fleet query {self.plan_name!r} (session {self.session}) "
                f"not done within {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._value

    def _resolve(self, status: str, value: Any = None,
                 exc: Optional[BaseException] = None) -> None:
        if self._done.is_set():
            return
        self.status = status
        self._value = value
        self._exc = exc
        self._done.set()


class _Query:
    """Supervisor-side record of one submitted query: the serialized
    submit payload (built once, reused verbatim on failover) plus the
    idempotency key that makes re-dispatch safe. ``shard`` pins a
    partitioned query to its owning (table, part) — the mesh router
    (runtime/cluster.py) routes those to the shard's host instead of
    the cheapest replica."""

    __slots__ = ("qid", "session", "signature", "cost_sig", "key",
                 "payload", "ticket", "deadline_ms", "shard")

    def __init__(self, qid: int, session: str, signature: str,
                 cost_sig: str, key, payload: Dict[str, Any],
                 ticket: FleetTicket, deadline_ms: int,
                 shard=None):
        self.qid = qid
        self.session = session
        self.signature = signature
        self.cost_sig = cost_sig
        self.key = key  # resultcache.CacheKey or None (unfingerprintable)
        self.payload = payload
        self.ticket = ticket
        self.deadline_ms = deadline_ms
        self.shard = shard  # (table name, part index) or None


class _Replica:
    """One supervised worker subprocess and its control-channel state."""

    def __init__(self, rid: str):
        self.rid = rid
        self.state = "booting"  # booting|live|draining|dead|quarantined
        self.generation = 0
        self.proc: Optional[subprocess.Popen] = None
        self.chan: Optional[_FrameChannel] = None
        self.inflight: Dict[int, _Query] = {}
        self.consecutive_crashes = 0
        self.crashes_total = 0
        self.served_total = 0
        self.restart_at: Optional[float] = None
        self.boot_deadline: Optional[float] = None
        self.last_pong: Optional[float] = None
        self.load: Dict[str, Any] = {}
        self.hb_seq = 0
        self.expected_exit = False
        self.live_evt = threading.Event()
        self.drained_evt = threading.Event()
        self.env_extra: Dict[str, str] = {}


class QueryFleet:
    """Supervisor + router over N QueryServer replica subprocesses.

    ``replicas`` overrides ``fleet.replicas``; ``worker_env`` adds
    environment variables to every worker; ``per_replica_env`` maps a
    replica id (``"r0"``, ``"r1"``, ...) to extra env for that replica
    only (chaos tests: boot-crash one replica, slow another).

    Construction returns immediately (workers boot in the background,
    ~seconds each under JAX); :meth:`wait_live` blocks until a quorum is
    serving. Use as a context manager — :meth:`close` shuts every
    worker down and fails any unresolved tickets classified.

    The supervision core (heartbeat, classified deaths, bounded
    failover, quarantine, memo/duplicate discipline) is transport- and
    identity-agnostic: subclasses override :meth:`_launch_worker` (how
    a worker process and its control channel come up), :meth:`_route`
    (which replica a query lands on) and :meth:`_extra` (identity
    context stamped into supervision events and classified errors) —
    the cross-host mesh (runtime/cluster.py) reuses everything else."""

    _ID_PREFIX = "r"  # replica id prefix ("h" for mesh host workers)
    is_cluster = False

    def __init__(self, replicas: Optional[int] = None, *,
                 worker_env: Optional[Dict[str, str]] = None,
                 per_replica_env: Optional[Dict[str, Dict[str, str]]] = None):
        self.n_replicas = max(1, int(replicas if replicas is not None
                                     else get_option("fleet.replicas")))
        self._worker_env = dict(worker_env or {})
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._qid = itertools.count(1)
        self._queries: Dict[int, _Query] = {}
        # resolved queries kept (bounded) for late-duplicate fingerprint
        # checks after the payload is released
        self._done_fp: "collections.OrderedDict[int, Optional[str]]" = \
            collections.OrderedDict()
        # (signature, fingerprint) -> (table, meta, table_fingerprint):
        # failover dedup / bit-identity verification, and the fleet-level
        # warm path a recycled replica serves cached signatures from
        self._memo: "collections.OrderedDict[Any, tuple]" = \
            collections.OrderedDict()
        # supervisor-side learned cost: plan signature -> EMA wall ms
        self._cost: Dict[str, float] = {}
        self._replicas: List[_Replica] = []
        for i in range(self.n_replicas):
            r = _Replica(f"{self._ID_PREFIX}{i}")
            r.env_extra = dict((per_replica_env or {}).get(r.rid, {}))
            self._replicas.append(r)
        _LIVE_FLEETS.add(self)
        for r in self._replicas:
            self._spawn(r)
        self._hb_stop = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True, name="fleet-heartbeat")
        self._hb_thread.start()

    # -- worker lifecycle ----------------------------------------------------

    def _worker_environment(self, r: _Replica) -> Dict[str, str]:
        from spark_rapids_jni_tpu.runtime import integrity

        env = dict(os.environ)
        # workers must land on the supervisor's backend even when it was
        # forced programmatically rather than via the environment
        if "JAX_PLATFORMS" not in env:
            jax = sys.modules.get("jax")
            if jax is not None:
                try:
                    env["JAX_PLATFORMS"] = str(jax.default_backend())
                except RuntimeError:
                    pass  # backend not initialized; worker picks its own
        # propagate option state that lives in this process's overrides
        # (env-set options are already inherited)
        env["SPARK_RAPIDS_TPU_TELEMETRY_REPLICA"] = r.rid
        env["SPARK_RAPIDS_TPU_INTEGRITY"] = "1" if integrity.enabled() else "0"
        for opt, var in (
            ("telemetry.enabled", "SPARK_RAPIDS_TPU_TELEMETRY_ENABLED"),
            ("telemetry.path", "SPARK_RAPIDS_TPU_TELEMETRY_PATH"),
            ("server.estimate_path", "SPARK_RAPIDS_TPU_SERVER_ESTIMATE_PATH"),
        ):
            val = get_option(opt)
            if val:
                env[var] = "1" if val is True else str(val)
        env.update(self._worker_env)
        env.update(r.env_extra)
        return env

    def _extra(self, r: _Replica) -> Dict[str, Any]:
        """Identity context merged into supervision events and
        classified errors (the mesh stamps ``host=`` here)."""
        return {}

    def _launch_worker(self, r: _Replica):
        """Transport hook: create the worker process and its control
        channel. Returns ``(proc, chan)``; ``chan`` may be None when the
        channel attaches asynchronously (the mesh's TCP dial-back calls
        :meth:`_attach_channel` from its accept loop instead)."""
        parent_sock, child_sock = socket.socketpair()
        child_fd = child_sock.fileno()
        os.set_inheritable(child_fd, True)
        cmd = [sys.executable, "-m", "spark_rapids_jni_tpu.runtime.fleet",
               "--worker", "--fd", str(child_fd), "--replica", r.rid]
        proc = subprocess.Popen(cmd, pass_fds=(child_fd,),
                                env=self._worker_environment(r))
        child_sock.close()
        return proc, _FrameChannel(parent_sock)

    def _attach_channel(self, r: _Replica, chan: _FrameChannel,
                        gen: int) -> None:
        """Bind a live control channel to a replica generation and start
        its receive loop (called from _spawn, or from the mesh accept
        loop once the remote worker dials back)."""
        r.chan = chan
        threading.Thread(
            target=self._recv_loop, args=(r, chan, gen), daemon=True,
            name=f"fleet-recv-{r.rid}-g{gen}").start()

    def _spawn(self, r: _Replica) -> None:
        """Boot (or re-boot) one worker subprocess on a fresh channel."""
        r.generation += 1
        gen = r.generation
        r.state = "booting"
        r.expected_exit = False
        r.live_evt.clear()
        r.drained_evt.clear()
        r.last_pong = None
        r.load = {}
        r.chan = None
        r.boot_deadline = (time.monotonic()
                           + float(get_option("fleet.worker_boot_timeout_s")))
        r.proc, chan = self._launch_worker(r)
        REGISTRY.counter("fleet.boots").inc()
        record_fleet("fleet.spawn", "boot", replica=r.rid, pid=r.proc.pid,
                     generation=gen, **self._extra(r))
        if chan is not None:
            self._attach_channel(r, chan, gen)

    def _restart(self, r: _Replica) -> None:
        REGISTRY.counter("fleet.restarts").inc()
        record_fleet("fleet.restart", "restart", replica=r.rid,
                     crashes=r.consecutive_crashes, **self._extra(r))
        self._spawn(r)

    # -- receive path --------------------------------------------------------

    def _recv_loop(self, r: _Replica, chan: _FrameChannel, gen: int) -> None:
        while True:
            try:
                msg = chan.recv()
            except BaseException as exc:
                self._reap(r, gen, exc)
                return
            t = msg.get("t")
            if t == "boot_ok":
                with self._cond:
                    if r.generation == gen and r.state == "booting":
                        r.state = "live"
                        r.last_pong = time.monotonic()
                        r.live_evt.set()
                        self._cond.notify_all()
                record_fleet("fleet.spawn", "live", replica=r.rid,
                             pid=msg.get("pid", 0), **self._extra(r))
            elif t == "pong":
                with self._lock:
                    r.last_pong = time.monotonic()
                    r.load = dict(msg.get("load") or {})
            elif t == "result":
                self._on_result(r, gen, msg)
            elif t == "drained":
                r.drained_evt.set()
            elif t == "bye":
                pass  # shutdown ack needs no action: the exit is expected
            else:
                # subclass protocol extension point (the mesh handles
                # shard-registration acks here)
                self._on_worker_msg(r, gen, msg)

    def _on_worker_msg(self, r: _Replica, gen: int,
                       msg: Dict[str, Any]) -> None:
        """Hook for control messages beyond the base protocol."""

    def _reap(self, r: _Replica, gen: int, exc: BaseException) -> None:
        """Control channel closed: reap the worker's exit status and
        route it through the resilience taxonomy (tpulint rule 18: a
        reaped exit must classify or visibly account — this is the
        classify)."""
        with self._lock:
            if r.generation != gen:
                return  # a stale receiver from before a restart
            expected = r.expected_exit
        rc: Optional[int] = None
        if r.proc is not None:
            try:
                rc = r.proc.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                rc = None
        if expected and (rc is None or rc == 0):
            return  # planned recycle/shutdown, not a death
        try:
            faults.fire("fleet.worker_exit", gen, replica=r.rid,
                        returncode=-1 if rc is None else rc)
        except BaseException as injected:
            exc = injected
        classified = (exc if isinstance(exc, resilience.ResilienceError)
                      else resilience.classify_worker_exit(
                          rc, replica=r.rid, **self._extra(r)))
        if classified is not exc and classified.__cause__ is None:
            classified.__cause__ = exc
        self._on_replica_death(r, gen, classified)

    def _on_result(self, r: _Replica, gen: int, msg: Dict[str, Any]) -> None:
        qid = int(msg.get("qid", 0))
        with self._lock:
            q = r.inflight.pop(qid, None) if r.generation == gen else None
            if q is None:
                q = self._queries.get(qid)
        if q is None:
            # the query resolved while this replica raced its kill: a
            # LATE DUPLICATE. Verify bit-identity against the recorded
            # fingerprint, then drop — never silently serve twice.
            self._drop_duplicate(r, qid, msg)
            return
        status = str(msg.get("status", "failed"))
        if status == "served":
            try:
                table = _decode_table(msg["table"])
                fp = resultcache.table_fingerprint(table)
            except BaseException as exc:
                self._fail_query(q, resilience.classify(
                    exc, seam="fleet.dispatch")(
                        f"fleet: result decode failed for query "
                        f"{q.ticket.plan_name}: {exc}", qid=qid,
                        replica=r.rid))
                return
            result = fusion.FusedResult(table, dict(msg.get("meta") or {}))
            wall_ms = float(msg.get("wall_ms") or 0.0)
            mismatch = False
            with self._lock:
                r.served_total += 1
                r.consecutive_crashes = 0
                self._learn_cost(q.cost_sig, wall_ms)
                if q.key is not None:
                    prev = self._memo.get(q.key)
                    if prev is not None and prev[2] != fp:
                        mismatch = True
                    else:
                        self._memo_put(q.key, (table, result.meta, fp))
            if mismatch:
                REGISTRY.counter("fleet.identity_mismatch").inc()
                record_fleet("fleet.result", "identity_mismatch",
                             replica=r.rid, qid=qid,
                             signature=q.key.signature)
                self._fail_query(q, resilience.CorruptDataError(
                    f"fleet: replica {r.rid} returned a result whose "
                    f"fingerprint differs from the recorded one for the "
                    f"same (signature, input fingerprint) key — "
                    f"determinism violated", qid=qid, replica=r.rid,
                    signature=q.key.signature))
                return
            q.ticket.replica = r.rid
            q.ticket.wall_ms = wall_ms
            q.ticket.fingerprint = fp
            REGISTRY.counter("fleet.served").inc()
            REGISTRY.counter(f"fleet.served.{r.rid}").inc()
            record_fleet("fleet.result", "served", replica=r.rid, qid=qid,
                         wall_ms=wall_ms, compiles=msg.get("compiles", 0))
            self._finish_query(q, "served", value=result, fp=fp)
        else:
            # a replica-reported QUERY failure (rejected / cancelled /
            # classified execution error): deterministic, so never failed
            # over — reconstruct the classified error and resolve
            exc = self._rebuild_error(msg, r.rid)
            REGISTRY.counter("fleet.failed").inc()
            record_fleet("fleet.result", "failed", replica=r.rid, qid=qid,
                         error_kind=str(msg.get("error_kind", "?")))
            self._finish_query(q, status, exc=exc)

    def _drop_duplicate(self, r: _Replica, qid: int,
                        msg: Dict[str, Any]) -> None:
        REGISTRY.counter("fleet.duplicate_drops").inc()
        record_fleet("fleet.result", "duplicate_drop", replica=r.rid,
                     qid=qid)
        if str(msg.get("status")) != "served":
            return
        with self._lock:
            want = self._done_fp.get(qid)
        if want is None:
            return
        try:
            fp = resultcache.table_fingerprint(_decode_table(msg["table"]))
        except BaseException:
            return  # a torn duplicate from a dying replica proves nothing
        if fp != want:
            REGISTRY.counter("fleet.identity_mismatch").inc()
            record_fleet("fleet.result", "identity_mismatch",
                         replica=r.rid, qid=qid)

    @staticmethod
    def _rebuild_error(msg: Dict[str, Any], rid: str) -> BaseException:
        kind = str(msg.get("error_kind", "FatalExecutionError"))
        message = str(msg.get("message", "replica reported failure"))
        if kind == "QueryRejected":
            from spark_rapids_jni_tpu.runtime.server import QueryRejected

            return QueryRejected(message,
                                 reason=str(msg.get("reason", "")),
                                 retry_after_s=msg.get("retry_after_s"))
        cls = getattr(resilience, kind, None)
        if not (isinstance(cls, type)
                and issubclass(cls, resilience.ResilienceError)):
            cls = resilience.FatalExecutionError
        return cls(message, replica=rid)

    def _finish_query(self, q: _Query, status: str, *, value: Any = None,
                      exc: Optional[BaseException] = None,
                      fp: Optional[str] = None) -> None:
        with self._lock:
            self._queries.pop(q.qid, None)
            self._done_fp[q.qid] = fp
            while len(self._done_fp) > 4096:
                self._done_fp.popitem(last=False)
            q.payload = None  # free the serialized bindings
        q.ticket._resolve(status, value=value, exc=exc)

    def _fail_query(self, q: _Query, exc: BaseException) -> None:
        REGISTRY.counter("fleet.failed").inc()
        self._finish_query(q, "failed", exc=exc)

    # -- death, failover, quarantine ----------------------------------------

    def _on_replica_death(self, r: _Replica, gen: int,
                          classified: BaseException) -> None:
        with self._lock:
            if r.generation != gen or r.state in ("dead", "quarantined"):
                return
            if r.expected_exit:
                return  # planned recycle/shutdown racing the supervisor
            r.state = "dead"
            r.live_evt.clear()
            r.consecutive_crashes += 1
            r.crashes_total += 1
            crashes = r.consecutive_crashes
            orphans = list(r.inflight.values())
            r.inflight.clear()
        REGISTRY.counter("fleet.replica_deaths").inc()
        REGISTRY.counter(f"fleet.replica_deaths.{r.rid}").inc()
        flight = spans.dump_flight_record(
            "replica_death",
            state={"replica": r.rid, "cause": str(classified),
                   "error_kind": type(classified).__name__,
                   "consecutive_crashes": crashes,
                   "inflight_qids": [q.qid for q in orphans],
                   **self._extra(r)})
        record_fleet("fleet.supervise", "replica_death", replica=r.rid,
                     error_kind=type(classified).__name__,
                     cause=str(classified), inflight=len(orphans),
                     **self._extra(r),
                     **({"flight_record": flight} if flight else {}))
        _log.warning("fleet: replica %s died (%s); %d in-flight to fail "
                     "over", r.rid, classified, len(orphans))
        if r.chan is not None:
            r.chan.close()
        if r.proc is not None and r.proc.poll() is None:
            r.proc.kill()
        quarantine_after = max(1, int(get_option("fleet.quarantine_after")))
        with self._lock:
            if crashes >= quarantine_after:
                r.state = "quarantined"
                r.restart_at = None
            else:
                backoff = (float(get_option("fleet.restart_backoff_s"))
                           * float(get_option(
                               "fleet.restart_backoff_multiplier"))
                           ** (crashes - 1))
                r.restart_at = time.monotonic() + backoff
        if r.state == "quarantined":
            REGISTRY.counter("fleet.quarantines").inc()
            record_fleet("fleet.supervise", "quarantine", replica=r.rid,
                         crashes=crashes, **self._extra(r))
            _log.warning("fleet: replica %s quarantined after %d "
                         "consecutive crashes", r.rid, crashes)
        if orphans:
            # failover off the supervision thread: re-dispatch can block
            # on a booting replacement, and the heartbeat loop must not
            threading.Thread(
                target=self._failover_batch, args=(r.rid, orphans, classified),
                daemon=True, name=f"fleet-failover-{r.rid}").start()

    def _failover_batch(self, dead_rid: str, orphans: List[_Query],
                        cause: BaseException) -> None:
        budget = max(0, int(get_option("fleet.failover_budget")))
        for q in orphans:
            if q.ticket.done():
                continue
            if q.ticket.dispatches > budget:
                self._fail_query(q, resilience.ReplicaDeadError(
                    f"fleet: query {q.ticket.plan_name} lost its replica "
                    f"{q.ticket.dispatches} times — failover budget "
                    f"({budget}) exhausted", qid=q.qid,
                    dispatches=q.ticket.dispatches))
                continue
            REGISTRY.counter("fleet.failovers").inc()
            record_fleet("fleet.supervise", "failover", replica=dead_rid,
                         qid=q.qid, attempt=q.ticket.dispatches)
            try:
                self._dispatch(q)
            except BaseException as exc:
                self._fail_query(q, exc if isinstance(
                    exc, resilience.ResilienceError)
                    else resilience.classify(exc, seam="fleet.dispatch")(
                        f"fleet: failover dispatch failed: {exc}",
                        qid=q.qid))

    # -- heartbeat / supervision loop ---------------------------------------

    def _heartbeat_loop(self) -> None:
        interval = max(0.05, float(get_option("fleet.heartbeat_interval_s")))
        while not self._hb_stop.wait(interval):
            timeout = float(get_option("fleet.heartbeat_timeout_s"))
            now = time.monotonic()
            for r in list(self._replicas):
                with self._lock:
                    state, gen = r.state, r.generation
                # draining replicas are exempt from liveness: the worker
                # main loop is blocked inside srv.drain() and legitimately
                # not answering pings; recycle() owns its fate
                if state == "live":
                    r.hb_seq += 1
                    try:
                        faults.fire("fleet.heartbeat", r.hb_seq,
                                    replica=r.rid)
                        r.chan.send({"t": "ping", "seq": r.hb_seq})
                    except BaseException as exc:
                        self._declare_dead(r, gen, exc)
                        continue
                    last = r.last_pong
                    if last is not None and now - last > timeout:
                        REGISTRY.counter("fleet.heartbeats_missed").inc()
                        self._declare_dead(r, gen, None)
                elif state == "booting":
                    if (r.boot_deadline is not None
                            and now > r.boot_deadline):
                        self._declare_dead(r, gen, None)
                elif state == "dead":
                    with self._lock:
                        due = (r.restart_at is not None
                               and now >= r.restart_at)
                        if due:
                            r.restart_at = None
                    if due:
                        self._restart(r)

    def _declare_dead(self, r: _Replica, gen: int,
                      exc: Optional[BaseException]) -> None:
        """A liveness verdict from the supervisor's side (missed pongs,
        failed ping send, boot timeout): classify, then kill the process
        so its receiver thread reaps deterministically."""
        if exc is None or not isinstance(exc, resilience.ResilienceError):
            rc = r.proc.poll() if r.proc is not None else None
            classified = resilience.classify_worker_exit(
                rc, replica=r.rid, seam="fleet.heartbeat", **self._extra(r))
            if exc is not None and classified.__cause__ is None:
                classified.__cause__ = exc
        else:
            classified = exc
        self._on_replica_death(r, gen, classified)

    # -- routing -------------------------------------------------------------

    def _learn_cost(self, sig: str, wall_ms: float) -> None:
        if wall_ms <= 0:
            return
        prev = self._cost.get(sig)
        self._cost[sig] = wall_ms if prev is None \
            else 0.6 * prev + 0.4 * wall_ms

    def _placement_cost(self, r: _Replica) -> float:
        default = (sum(self._cost.values()) / len(self._cost)
                   if self._cost else 50.0)
        cost = sum(self._cost.get(q.cost_sig, default)
                   for q in r.inflight.values())
        # the replica's own view of its backlog (from its last pong)
        # covers work the supervisor did not place (direct sessions)
        cost += default * float(r.load.get("queued", 0) or 0)
        return cost

    def _pick_replica(self, deadline: float) -> Optional[_Replica]:
        while True:
            with self._cond:
                live = [r for r in self._replicas if r.state == "live"]
                if live:
                    picked = min(live, key=lambda r: (
                        self._placement_cost(r), r.rid))
                    # every routing decision is counted (tpulint rule 23:
                    # a placement choice must be visible in telemetry)
                    REGISTRY.counter("fleet.placements").inc()
                    REGISTRY.counter(
                        f"fleet.placements.{picked.rid}").inc()
                    return picked
                if self._closed or time.monotonic() >= deadline:
                    return None
                self._cond.wait(timeout=min(
                    0.05, max(0.0, deadline - time.monotonic())) or 0.01)

    def _route(self, q: _Query, deadline: float) -> Optional[_Replica]:
        """Routing hook: which replica this placement round lands on.
        The base fleet load-balances; the mesh router overrides with
        partition-map locality for shard-pinned queries."""
        return self._pick_replica(deadline)

    def _dispatch(self, q: _Query) -> None:
        """Place one query on the routed healthy replica and send its
        frame; raises classified when no replica can take it in time."""
        deadline = time.monotonic() + float(
            get_option("fleet.dispatch_timeout_s"))
        while True:
            r = self._route(q, deadline)
            if r is None:
                raise resilience.ReplicaDeadError(
                    "fleet: no healthy replica to dispatch to within "
                    f"{get_option('fleet.dispatch_timeout_s')}s",
                    qid=q.qid, seam="fleet.dispatch")
            with self._lock:
                gen = r.generation
                if r.state != "live":
                    continue
                r.inflight[q.qid] = q
                q.ticket.dispatches += 1
                q.ticket.replica = r.rid
                q.ticket.status = "dispatched"
            try:
                with spans.span("fleet.dispatch", replica=r.rid,
                                plan=q.ticket.plan_name, qid=q.qid):
                    faults.fire("fleet.dispatch", q.ticket.dispatches,
                                replica=r.rid, qid=q.qid)
                    r.chan.send(q.payload)
            except BaseException as exc:
                with self._lock:
                    r.inflight.pop(q.qid, None)
                classified = (exc if isinstance(
                    exc, resilience.ResilienceError)
                    else resilience.classify(exc, seam="fleet.dispatch")(
                        f"fleet: dispatch to {r.rid} failed: {exc}",
                        qid=q.qid, replica=r.rid))
                # a failed send means the replica is gone: declare it so
                # its other in-flight queries fail over too
                self._declare_dead(r, gen, classified)
                if not resilience.is_transient(classified,
                                               seam="fleet.dispatch"):
                    raise classified
                budget = max(0, int(get_option("fleet.failover_budget")))
                if q.ticket.dispatches > budget:
                    raise resilience.ReplicaDeadError(
                        f"fleet: query {q.ticket.plan_name} lost "
                        f"{q.ticket.dispatches} replicas at dispatch — "
                        f"failover budget ({budget}) exhausted",
                        qid=q.qid) from classified
                continue
            REGISTRY.counter("fleet.dispatched").inc()
            REGISTRY.counter(f"fleet.dispatched.{r.rid}").inc()
            return

    # -- client surface ------------------------------------------------------

    def wait_live(self, n: Optional[int] = None,
                  timeout: float = 120.0) -> int:
        """Block until ``n`` (default: all) replicas are serving; returns
        the live count (may be short on timeout or quarantine)."""
        want = self.n_replicas if n is None else int(n)
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                live = sum(1 for r in self._replicas if r.state == "live")
                dead_forever = sum(1 for r in self._replicas
                                   if r.state == "quarantined")
                if live >= want or live >= self.n_replicas - dead_forever:
                    if live >= want or time.monotonic() >= deadline:
                        return live
                if time.monotonic() >= deadline:
                    return live
                self._cond.wait(timeout=0.1)

    def submit(self, session_id: str, plan: fusion.Plan, bindings: dict, *,
               deadline_ms: Optional[int] = None,
               cache_fingerprint: Optional[str] = None) -> FleetTicket:
        """Route one query to a replica. Returns immediately with a
        :class:`FleetTicket`; placement failures, replica deaths past
        the failover budget, and replica-reported failures all resolve
        the ticket classified."""
        return self._submit(session_id, plan, bindings,
                            deadline_ms=deadline_ms,
                            cache_fingerprint=cache_fingerprint)

    def _submit(self, session_id: str, plan: fusion.Plan, bindings: dict, *,
                binding_refs: Optional[Dict[str, str]] = None,
                shard=None,
                sig_bindings: Optional[Dict[str, Any]] = None,
                deadline_ms: Optional[int] = None,
                cache_fingerprint: Optional[str] = None) -> FleetTicket:
        """Shared submit core. ``binding_refs`` maps plan binding names
        to worker-resident registered tables (the mesh's ship-the-query
        path: the shard's bytes never ride the submit frame); ``shard``
        pins the query to its owning (table, part) for locality routing
        and re-homing failover; ``sig_bindings`` supplies stand-ins for
        ref-bound tables when deriving the memo key and cost signature
        (both read only ``num_rows``), so the idempotency pair survives
        without the shard's bytes ever being supervisor-resident."""
        if self._closed:
            raise RuntimeError("fleet is closed")
        qid = next(self._qid)
        sid = str(session_id)
        ticket = FleetTicket(qid, sid, plan.name)
        REGISTRY.counter("fleet.submitted").inc()
        key_bindings = (bindings if not sig_bindings
                        else {**bindings, **sig_bindings})
        key = None
        if int(get_option("fleet.result_memo_entries")) > 0:
            try:
                key = resultcache.cache_key(
                    plan, key_bindings, fingerprint=cache_fingerprint)
            except (ValueError, KeyError, TypeError):
                key = None  # unfingerprintable: serve, never memoize
        if key is not None:
            with self._lock:
                hit = self._memo.get(key)
                if hit is not None:
                    self._memo.move_to_end(key)
            if hit is not None:
                table, meta, fp = hit
                REGISTRY.counter("fleet.memo_hits").inc()
                record_fleet("fleet.submit", "memo_hit",
                             replica="supervisor", qid=qid,
                             signature=key.signature)
                ticket.fingerprint = fp
                ticket.replica = "supervisor"
                ticket._resolve("served",
                                value=fusion.FusedResult(table, dict(meta)))
                return ticket
        try:
            payload = {
                "t": "submit", "qid": qid, "session": sid,
                "plan": pickle.dumps(plan,
                                     protocol=pickle.HIGHEST_PROTOCOL),
                "bindings": {k: _encode_table(v)
                             for k, v in bindings.items()},
                "binding_refs": dict(binding_refs or {}),
                "deadline_ms": deadline_ms,
                "cache_fingerprint": cache_fingerprint,
            }
        except BaseException as exc:
            ticket._resolve("failed", exc=resilience.MalformedInputError(
                f"fleet: query {plan.name} is not shippable to a replica "
                f"(plan or bindings failed to serialize): {exc}", qid=qid))
            return ticket
        from spark_rapids_jni_tpu.runtime.server import QueryServer

        q = _Query(qid, sid, key.signature if key is not None else "",
                   QueryServer._plan_signature(plan, key_bindings), key,
                   payload, ticket,
                   int(deadline_ms or 0), shard=shard)
        with self._lock:
            self._queries[qid] = q
        try:
            self._dispatch(q)
        except BaseException as exc:
            self._fail_query(q, exc if isinstance(
                exc, resilience.ResilienceError)
                else resilience.classify(exc, seam="fleet.dispatch")(
                    f"fleet: dispatch failed: {exc}", qid=qid))
        return ticket

    def recycle(self, rid: str, timeout: float = 60.0) -> bool:
        """Graceful drain + warm restart of one replica: stop admitting,
        finish in-flight, flush learned estimates (merged into the
        shared state file), exit cleanly, boot a successor off the
        shared JAX persistent compile cache. A planned exit — no crash
        counted, no backoff. Returns True when the successor is live."""
        r = self._find(rid)
        with self._lock:
            if r.state != "live":
                return False
            r.state = "draining"
            gen = r.generation
        record_fleet("fleet.supervise", "drain", replica=rid)
        REGISTRY.counter("fleet.drains").inc()
        try:
            r.chan.send({"t": "drain", "timeout": timeout})
            if not r.drained_evt.wait(timeout):
                self._declare_dead(r, gen, None)
                return False
            with self._lock:
                r.expected_exit = True
            r.chan.send({"t": "shutdown"})
        except BaseException as exc:
            self._declare_dead(r, gen, exc)
            return False
        try:
            r.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            r.proc.kill()
        with self._lock:
            r.state = "dead"  # planned; not a crash (counter untouched)
        self._restart(r)
        return bool(r.live_evt.wait(
            float(get_option("fleet.worker_boot_timeout_s"))))

    def _find(self, rid: str) -> _Replica:
        for r in self._replicas:
            if r.rid == rid:
                return r
        raise KeyError(f"no replica {rid!r}")

    def inspect(self) -> dict:
        """Live fleet introspection (telemetry ``top`` fleet view): every
        replica's state, load and supervision history, plus router and
        memo state. Pure host-side reads."""
        with self._lock:
            replicas = []
            for r in self._replicas:
                age = (None if r.last_pong is None
                       else time.monotonic() - r.last_pong)
                replicas.append({
                    "replica": r.rid, "state": r.state,
                    "pid": r.proc.pid if r.proc is not None else None,
                    "generation": r.generation,
                    "inflight": len(r.inflight),
                    "served": r.served_total,
                    "crashes": r.crashes_total,
                    "consecutive_crashes": r.consecutive_crashes,
                    "last_pong_age_s": age,
                    "restart_in_s": (
                        None if r.restart_at is None
                        else max(0.0, r.restart_at - time.monotonic())),
                    "load": dict(r.load),
                })
            c = REGISTRY.counters("fleet.")
            return {
                "fleet": True,
                "replicas": replicas,
                "pending_queries": len(self._queries),
                "memo_entries": len(self._memo),
                "learned_signatures": len(self._cost),
                "counters": {k: v for k, v in sorted(c.items())
                             if k.count(".") == 1},
            }

    def leaked_bytes(self) -> int:
        """Sum of the live replicas' last-reported leaked reservation
        bytes (limiter usage beyond the result cache's resident charge)
        — zero once every query has resolved and released (chaos/CI
        leak check). Reads each replica's latest liveness pong; wait at
        least one ``fleet.heartbeat_interval_s`` after the final result
        for a fresh report."""
        with self._lock:
            return sum(int(r.load.get("leaked", 0) or 0)
                       for r in self._replicas if r.state == "live")

    def _memo_put(self, key, entry: tuple) -> None:
        cap = int(get_option("fleet.result_memo_entries"))
        if cap <= 0:
            return
        self._memo[key] = entry
        self._memo.move_to_end(key)
        while len(self._memo) > cap:
            self._memo.popitem(last=False)

    def close(self, timeout: float = 30.0) -> None:
        """Shut every worker down; unresolved tickets fail classified."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._hb_stop.set()
        self._hb_thread.join(timeout=5.0)
        for r in self._replicas:
            with self._lock:
                r.expected_exit = True
            if r.chan is not None and r.state in ("live", "draining"):
                try:
                    r.chan.send({"t": "shutdown"})
                except OSError:
                    pass
        for r in self._replicas:
            if r.proc is not None:
                try:
                    r.proc.wait(timeout=timeout)
                except subprocess.TimeoutExpired:
                    r.proc.kill()
                    r.proc.wait(timeout=5.0)
            if r.chan is not None:
                r.chan.close()
            with self._lock:
                r.state = "dead"
        with self._lock:
            pending = list(self._queries.values())
        for q in pending:
            self._finish_query(q, "failed", exc=resilience.ReplicaDeadError(
                "fleet closed before the query completed", qid=q.qid))

    def __enter__(self) -> "QueryFleet":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------


def _worker_load(srv) -> Dict[str, Any]:
    with srv._inflight_lock:
        inflight = len(srv._inflight)
    with srv._cond:
        queued = sum(len(dq) for dq in srv._queues.values())
    used = srv.limiter.used
    # the server-level leak invariant: at idle, limiter.used must equal
    # exactly the result cache's resident (evictable) charge — anything
    # beyond that is a reservation some query failed to release
    return {"inflight": inflight, "queued": queued, "used": used,
            "leaked": max(0, used - srv.result_cache.evictable_bytes)}


def _serve_one(chan: _FrameChannel, srv, msg: Dict[str, Any],
               replica: str) -> None:
    qid = msg["qid"]
    out: Dict[str, Any] = {"t": "result", "qid": qid}
    try:
        delay_ms = float(os.environ.get(_ENV_SERVE_DELAY, "0") or 0.0)
        if delay_ms:
            # chaos hook: hold the query in flight long enough for the
            # test to SIGKILL this worker mid-query deterministically
            time.sleep(delay_ms / 1e3)
        plan = pickle.loads(msg["plan"])
        bindings = {k: _decode_table(v)
                    for k, v in (msg.get("bindings") or {}).items()}
        # the mesh's ship-the-query path: bindings resolved from tables
        # registered on THIS worker (the shard lives here; only the
        # plan crossed the wire)
        for name, reg in (msg.get("binding_refs") or {}).items():
            try:
                bindings[name] = srv.registered_table(reg)
            except KeyError:
                raise resilience.MalformedInputError(
                    f"fleet: submit references registered table {reg!r} "
                    f"which is not resident on replica {replica}",
                    replica=replica, binding=name) from None
        compiles_before = REGISTRY.counters("dispatch.").get(
            "dispatch.compile", 0)
        t0 = time.monotonic()
        ticket = srv.submit(
            msg["session"], plan, bindings,
            deadline_ms=msg.get("deadline_ms"),
            cache_fingerprint=msg.get("cache_fingerprint"))
        result = ticket.result()
        wall_ms = (time.monotonic() - t0) * 1e3
        out.update({
            "status": "served",
            "table": _encode_table(result.table),
            "meta": resultcache._snap_meta(result.meta),
            "wall_ms": wall_ms,
            "compiles": REGISTRY.counters("dispatch.").get(
                "dispatch.compile", 0) - compiles_before,
        })
    except BaseException as exc:
        kind = type(exc).__name__
        if not isinstance(exc, resilience.ResilienceError) \
                and kind != "QueryRejected":
            kind = resilience.classify(exc).__name__
        out.update({
            "status": {"QueryRejected": "rejected",
                       "QueryCancelled": "cancelled"}.get(kind, "failed"),
            "error_kind": kind,
            "message": str(exc),
            "reason": str(getattr(exc, "reason", "") or ""),
            "retry_after_s": getattr(exc, "retry_after_s", None),
        })
    try:
        chan.send(out)
    except OSError:
        pass  # supervisor gone; this worker is about to be reaped anyway


def _register_one(chan: _FrameChannel, srv, msg: Dict[str, Any],
                  replica: str) -> None:
    """Install one shipped shard into this worker's registered-table
    store and acknowledge with its fingerprint (the supervisor verifies
    it against the fingerprint taken before the shard crossed the wire
    — the cross-host half of the idempotency pair)."""
    name = str(msg.get("name", ""))
    out: Dict[str, Any] = {"t": "registered", "name": name}
    try:
        table = _decode_table(msg["table"])
        out["fingerprint"] = srv.register_table(name, table)
        out["rows"] = int(table.num_rows)
    except BaseException as exc:
        kind = type(exc).__name__
        if not isinstance(exc, resilience.ResilienceError):
            kind = resilience.classify(exc).__name__
        out.update({"error_kind": kind, "message": str(exc)})
    try:
        chan.send(out)
    except OSError:
        pass  # supervisor gone; this worker is about to be reaped anyway


def _worker_main(fd: int, replica: str) -> int:
    """Replica entrypoint: one in-process QueryServer behind the frame
    channel."""
    if os.environ.get(_ENV_BOOT_CRASH):
        return 3  # chaos hook: crash-loop at boot
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM, fileno=fd)
    return _worker_loop(_FrameChannel(sock), replica)


def _worker_loop(chan: _FrameChannel, replica: str,
                 extensions=None) -> int:
    """The worker control loop behind any connected frame channel (a
    socketpair fd for the local fleet, a dialed-back TCP socket for the
    mesh's remote hosts). The main thread stays in the control loop
    (pings answered inline, so liveness tracks control-plane
    responsiveness); each submit serves on its own thread.

    ``extensions`` maps extra frame types to handlers
    ``fn(chan, srv, msg, replica)``; each runs on its own daemon thread
    (extension frames — e.g. the cluster's direct-exchange pack/merge —
    block on compute and peer flights, and must not stall the ping
    loop). Unknown frame types without a handler are dropped, as
    before."""
    from spark_rapids_jni_tpu.runtime.server import QueryServer

    srv = QueryServer()
    # AOT warmup BEFORE boot_ok (gated by server.warmup_top_n, default
    # off): the supervisor routes no traffic here until the costliest
    # learned plan signatures are precompiled, so a recycled replica
    # rejoins without first-query compile stalls. warmup() never raises.
    if int(get_option("server.warmup_top_n")) > 0:
        from spark_rapids_jni_tpu.models import tpch  # noqa: F401  (registers warmup builders)
        srv.warmup()
    chan.send({"t": "boot_ok", "pid": os.getpid()})
    frozen = False
    try:
        while True:
            try:
                msg = chan.recv()
            except (ConnectionError, EOFError):
                return 0  # supervisor went away: exit quietly
            t = msg.get("t")
            if t == "ping":
                if not frozen:
                    chan.send({"t": "pong", "seq": msg.get("seq", 0),
                               "load": _worker_load(srv)})
            elif t == "submit":
                threading.Thread(
                    target=_serve_one, args=(chan, srv, msg, replica),
                    daemon=True,
                    name=f"fleet-serve-{msg.get('qid')}").start()
            elif t == "register":
                # inline, not threaded: registration must complete (and
                # ack) before any submit that references the shard, and
                # the control loop's ordering guarantees exactly that
                _register_one(chan, srv, msg, replica)
            elif t == "drain":
                state = srv.drain(timeout=msg.get("timeout"))
                chan.send({"t": "drained", **state})
            elif t == "freeze":
                # chaos hook: stop answering pings (simulates a wedged
                # control plane) while query threads keep running
                frozen = True
            elif t == "shutdown":
                srv.close()
                chan.send({"t": "bye"})
                return 0
            elif extensions is not None and t in extensions:
                threading.Thread(
                    target=extensions[t], args=(chan, srv, msg, replica),
                    daemon=True, name=f"fleet-ext-{t}").start()
    finally:
        srv.close()  # idempotent: a no-op after the shutdown path ran


def main(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if "--worker" not in args:
        print("usage: python -m spark_rapids_jni_tpu.runtime.fleet "
              "--worker --fd <fd> --replica <rid>", file=sys.stderr)
        return 2
    fd = replica = None
    for i, a in enumerate(args):
        if a == "--fd" and i + 1 < len(args):
            fd = int(args[i + 1])
        elif a == "--replica" and i + 1 < len(args):
            replica = args[i + 1]
    if fd is None or replica is None:
        print("fleet worker: --fd and --replica are required",
              file=sys.stderr)
        return 2
    return _worker_main(fd, replica)


if __name__ == "__main__":
    sys.exit(main())
