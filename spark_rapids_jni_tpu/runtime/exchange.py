"""General-cardinality distributed exchange — hash-partitioned all-to-all
repartitioning over the cluster mesh.

The ICI shuffle (parallel/shuffle.py) moves rows between devices of ONE
host's mesh program with an XLA ``all_to_all``; the serving mesh
(runtime/cluster.py) moves whole tables between HOSTS but only along a
static partition-for-slices layout. This module is the missing middle —
the Spark exchange operator: repartition a device-resident table by
arbitrary key columns so that every key lands on exactly one destination,
with no static slot table anywhere.

Three halves, each reusing an existing discipline:

* **Device half** — ``partition_hash`` -> destination-sorted pack into a
  contiguous ``(parts, capacity)`` send buffer, via the SAME
  searchsorted-inversion gather the ICI shuffle uses (``_plan_send`` /
  ``_pack_send`` are imported, not copied). Capacities are quantized
  through the dispatch bucket schedule so ragged partition sizes share
  executables; destination p's rows are exactly the first ``counts[p]``
  slots of its capacity run, so the host trims real rows with plain
  slices, never a compaction pass.

* **Wire half** — per-destination buffers ship as TPCZ codec frames under
  the integrity seal via ``dcn.send_framed`` / ``dcn.recv_framed`` (the
  one shared seal-ordering helper): verify-then-decode with NAK-driven
  ARQ refetch comes for free, and injected corruption is scoped to the
  ``exchange.wire`` seam so chaos scripts can target shuffle traffic
  without touching registration frames. Inside the cluster the wire form
  is ONE concatenated table per source (flight-major, part-major slices)
  whose ``row_counts`` ride as plain meta — it survives the fleet's
  result frames unchanged.

* **Overflow half** — the one-shot doubled-capacity retry is replaced by
  a spill-aware ladder: overflowing packs escalate geometrically through
  ``resilience.escalate`` (rung ``grow_capacity``) up to
  ``exchange.max_capacity_rows``, then demote to multi-flight chunking
  (each chunk packed at a capacity that provably cannot overflow), and
  the receive side merges flights through ``outofcore.
  run_chunked_aggregate`` with a SpillStore so skewed keys degrade into
  host spill instead of dying. Every overflow that escapes the ladder is
  classified (``shuffle.classify_overflow`` -> ``CapacityOverflow`` with
  partition/capacity context) — never a bare boolean.

On top sit the general plan steps: ``partitioned_groupby`` /
``partitioned_join`` (hash co-partition, per-partition op, concat —
output keys are disjoint across partitions so the concat IS the result)
and the ``Exchange`` plan-root node (runtime/fusion.py) the cluster's
``submit_exchange`` drives end-to-end.
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import telemetry
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.hash import partition_hash
from spark_rapids_jni_tpu.ops.table_ops import _slice_rows, concatenate
from spark_rapids_jni_tpu.parallel.shuffle import (
    _pack_send,
    _plan_send,
    classify_overflow,
)
from spark_rapids_jni_tpu.runtime import dispatch, resilience
from spark_rapids_jni_tpu.runtime.memory import (
    MemoryLimiter,
    SpillStore,
    _table_nbytes,
)
from spark_rapids_jni_tpu.telemetry import spans
from spark_rapids_jni_tpu.telemetry.registry import REGISTRY
from spark_rapids_jni_tpu.types import TypeId
from spark_rapids_jni_tpu.utils.config import get_option
from spark_rapids_jni_tpu.utils.log import get_logger
from spark_rapids_jni_tpu.utils.tracing import func_range

_log = get_logger(__name__)


class PackResult(NamedTuple):
    """One packed flight: ``parts * capacity`` destination-sorted rows.

    ``counts[p]`` is destination p's TRUE row count; in a returned (non-
    overflowed) flight ``counts[p] <= capacity`` and p's rows are exactly
    slots ``[p * capacity, p * capacity + counts[p])`` — contiguous, so
    per-destination send buffers are plain slices."""

    table: Table
    counts: np.ndarray
    capacity: int


def _make_pack_fn(keys: tuple, parts: int, capacity: int) -> Callable:
    """The dispatchable pack: mirror of ``shuffle_by_partition``'s slot
    math with the mesh axis replaced by a host-level destination dim (no
    ``all_to_all`` — the wire half moves the buffers). The closure's
    variation is fully captured by the caller's ``statics``."""

    def pack(row_args, aux_args, row_valids):
        (table,) = row_args
        rv = None if row_valids is None else row_valids[0]
        n = table.num_rows
        part = partition_hash(table, list(keys), parts)
        order = jnp.argsort(part, stable=True)
        part_sorted = part[order]
        if rv is None:
            real_sorted = jnp.ones((n,), dtype=jnp.bool_)
        else:
            real_sorted = rv.astype(jnp.bool_)[order]
        real_i32 = real_sorted.astype(jnp.int32)
        rank_excl = jnp.cumsum(real_i32) - real_i32
        total_real = jnp.sum(real_i32).astype(jnp.int32)
        if n:
            part_start = jnp.searchsorted(
                part_sorted, jnp.arange(parts, dtype=part_sorted.dtype),
                side="left")
            base = rank_excl[jnp.clip(part_start, 0, n - 1)]
            base = jnp.where(part_start < n, base, total_real)
            offsets = base.astype(jnp.int32)
        else:
            offsets = jnp.zeros((parts,), jnp.int32)
        slot = rank_excl.astype(jnp.int32) - offsets[part_sorted]
        in_cap = (slot < capacity) & real_sorted
        size = parts * capacity
        dst_mono = part_sorted * capacity + jnp.clip(slot, 0, capacity)
        plan = _plan_send(dst_mono, in_cap, size)
        occupied = plan.hit
        # full real count per destination (including overflow past the
        # capacity) — the escalation ladder's exact `required`
        ext = jnp.concatenate([offsets, total_real[None]])
        counts = ext[1:] - ext[:-1]
        overflowed = jnp.any(counts > capacity)

        out_cols = []
        for col in table.columns:
            if col.dtype.is_string:
                if not col.is_padded_string:
                    raise NotImplementedError(
                        "exchange pack needs string columns in the padded "
                        "device layout (ops.strings.pad_strings)")
                lens = _pack_send(col.data, order, plan)
                chars = _pack_send(col.chars, order, plan)
                valid = _pack_send(col.valid_mask(), order, plan) & occupied
                out_cols.append(Column(col.dtype, lens, valid, chars=chars))
                continue
            if col.dtype.type_id == TypeId.LIST:
                if not col.is_padded_list:
                    raise NotImplementedError(
                        "exchange pack needs LIST columns in the padded "
                        "wire layout (ops.lists.pad_lists)")
                elem = col.children[0]
                lens = _pack_send(col.data, order, plan)
                emat = _pack_send(elem.data, order, plan)
                ev = _pack_send(elem.valid_mask(), order, plan)
                valid = _pack_send(col.valid_mask(), order, plan) & occupied
                # unoccupied slots must read as EMPTY lists
                lens = jnp.where(occupied, lens, 0)
                ev = ev & occupied[:, None]
                out_cols.append(Column(
                    col.dtype, lens, valid,
                    children=[Column(elem.dtype, emat, ev)]))
                continue
            if not (col.dtype.is_fixed_width or col.dtype.is_decimal128):
                raise NotImplementedError(
                    "exchange pack supports fixed-width columns only "
                    "(the ICI shuffle shares this restriction)")
            data = _pack_send(col.data, order, plan)
            valid = _pack_send(col.valid_mask(), order, plan) & occupied
            out_cols.append(Column(col.dtype, data, valid))
        return Table(out_cols), counts, overflowed

    return pack


def _pack_once(table: Table, keys: Sequence[int], parts: int,
               capacity: int) -> tuple[PackResult, bool]:
    keys = tuple(int(k) for k in keys)
    parts = int(parts)
    capacity = int(capacity)
    fn = _make_pack_fn(keys, parts, capacity)
    packed, counts, overflowed = dispatch.call(
        "exchange.pack", fn, (table,),
        statics=(keys, parts, capacity), slice_rows=False)
    res = PackResult(packed, np.asarray(counts).astype(np.int64), capacity)
    return res, bool(np.asarray(overflowed))


@func_range("exchange_pack")
def pack_flights(table: Table, keys: Sequence[int], parts: int, *,
                 capacity: Optional[int] = None, op: str = "exchange",
                 cancel_token=None) -> list[PackResult]:
    """Pack ``table`` into per-destination send buffers — the spill-aware
    overflow ladder.

    Rung 1: geometric capacity escalation through ``resilience.escalate``
    (start ``ceil(n/parts) * 2`` quantized, or the caller's planned
    capacity), each overflow naming its exact requirement so the schedule
    jumps there. Rung 2: at ``exchange.max_capacity_rows`` the pack
    demotes to MULTI-FLIGHT chunking — the source is host-sliced into
    chunks no larger than the cap and each chunk packs at a capacity that
    cannot overflow (a chunk's hottest destination holds at most the
    chunk's rows), so arbitrarily skewed keys always ship; the receive
    side absorbs the extra flights through the SpillStore merge
    (:func:`merge_flights`). Exhaustion inside a rung raises classified
    (``CapacityOverflow`` with partition/capacity context), never a bare
    boolean."""
    if cancel_token is not None:
        cancel_token.check(op)
    n = table.num_rows
    parts = int(parts)
    if parts < 1:
        raise ValueError(f"{op}: parts must be >= 1, got {parts}")
    max_cap = max(1, dispatch.quantize_capacity(
        int(get_option("exchange.max_capacity_rows"))))
    if capacity is None:
        initial = dispatch.quantize_capacity(
            max(1, math.ceil(max(n, 1) / parts) * 2))
    else:
        initial = max(1, int(capacity))
    initial = min(initial, max_cap)

    def attempt(cap: int):
        res, overflowed = _pack_once(table, keys, parts, cap)
        if overflowed:
            REGISTRY.counter("exchange.overflow_escalations").inc()
            telemetry.record_exchange(
                op, "overflow_escalate", rows=n, capacity=cap,
                partition=int(res.counts.argmax()),
                required=int(res.counts.max()))
            return None, True, int(res.counts.max())
        return res, False, None

    try:
        return [resilience.escalate(
            f"{op}.pack", attempt, seam="exchange.pack",
            initial=initial, max_capacity=max_cap,
            quantize=dispatch.quantize_capacity,
            exhaust=lambda cap, steps: classify_overflow(
                op=f"{op}.pack", capacity=cap, rows=n,
                seam="exchange.pack", steps=steps),
            rows=n)]
    except resilience.CapacityOverflow:
        # rung 2: chunked flights. Each chunk's hottest destination can
        # receive at most the chunk's row count, and the chunk is at most
        # max_cap rows packed at capacity >= chunk rows — overflow is
        # structurally impossible, so this rung always terminates.
        if cancel_token is not None:
            cancel_token.check(op)
        flights: list[PackResult] = []
        for lo in range(0, n, max_cap):
            chunk = _slice_rows(table, lo, min(lo + max_cap, n))
            cap = max(chunk.num_rows,
                      dispatch.quantize_capacity(chunk.num_rows))
            res, overflowed = _pack_once(chunk, keys, parts, cap)
            if overflowed:  # pragma: no cover - see invariant above
                raise classify_overflow(
                    op=f"{op}.pack", capacity=cap, rows=chunk.num_rows,
                    seam="exchange.pack")
            flights.append(res)
        REGISTRY.counter("exchange.chunked_flights").inc()
        telemetry.record_exchange(
            op, "chunked_flights", rows=n, flights=len(flights),
            capacity=max_cap)
        _log.info("%s: demoted to %d chunked flights (max capacity %d)",
                  op, len(flights), max_cap)
        return flights


def flight_slices(res: PackResult) -> list[Table]:
    """Per-destination trim of one packed flight: destination p's real
    rows are exactly the first ``counts[p]`` slots of its capacity run
    (contiguous by construction — plain slices, no compaction)."""
    return [
        _slice_rows(res.table, p * res.capacity,
                    p * res.capacity + int(c))
        for p, c in enumerate(res.counts)
    ]


def build_wire(flights: Sequence[PackResult]) -> tuple[Table, list]:
    """Flatten flights into the cluster wire form: ONE table — the
    per-destination slices concatenated flight-major then part-major —
    plus the flat ``row_counts`` list (length ``flights * parts``) that
    inverts it. ``row_counts`` is plain Python, so it rides result-frame
    meta through the fleet codec unchanged."""
    slices: list[Table] = []
    row_counts: list[int] = []
    for res in flights:
        for s in flight_slices(res):
            row_counts.append(int(s.num_rows))
            slices.append(s)
    nonempty = [s for s in slices if s.num_rows]
    if nonempty:
        wire = nonempty[0] if len(nonempty) == 1 else concatenate(nonempty)
    else:
        wire = _slice_rows(flights[0].table, 0, 0)
    return wire, row_counts


def split_wire(wire: Table, row_counts: Sequence[int],
               parts: int) -> list[list[Table]]:
    """Supervisor-side inverse of :func:`build_wire`: slice a source's
    wire table back into per-destination flight tables. Returns
    ``parts`` lists (destination-indexed), each holding that
    destination's non-empty flights in flight order."""
    parts = int(parts)
    if len(row_counts) % parts:
        raise resilience.MalformedInputError(
            f"exchange wire row_counts length {len(row_counts)} is not a "
            f"multiple of parts={parts}", seam="exchange.wire")
    per_dest: list[list[Table]] = [[] for _ in range(parts)]
    lo = 0
    for i, c in enumerate(row_counts):
        hi = lo + int(c)
        if hi > lo:
            per_dest[i % parts].append(_slice_rows(wire, lo, hi))
        lo = hi
    if lo != wire.num_rows:
        raise resilience.MalformedInputError(
            f"exchange wire table has {wire.num_rows} rows but row_counts "
            f"sum to {lo}", seam="exchange.wire")
    return per_dest


def choose_parts(plan_name: str, label: str, rows: int, *,
                 fallback: int = 1) -> int:
    """Pick a partition count for an auto-parts (``parts=0``) Exchange
    from the learned-selectivity store: the store's EMA for this
    (plan, exchange label) signature is the observed fraction of the
    region's input rows that actually enter the exchange (a partial
    groupby's group density), so ``rows x ema / target_rows_per_part``
    estimates how many destinations the packed output warrants. No
    history falls back to ``fallback``. Every choice is recorded with
    its reason (an unexplained partition count is an unexplainable plan
    change, same contract as the rtfilter gate)."""
    from spark_rapids_jni_tpu.runtime import rtfilter

    rows = int(rows)
    ema = rtfilter.learned_pass_frac(plan_name, f"xparts.{label}")
    if ema is None:
        parts, reason = int(fallback), "no_history"
    else:
        target = max(1, int(get_option("exchange.target_rows_per_part")))
        est = max(1, int(rows * float(ema)))
        parts = max(1, min(int(get_option("exchange.max_parts")),
                           -(-est // target)))
        reason = "learned_density"
    REGISTRY.counter("exchange.parts_chosen").inc()
    telemetry.record_exchange(
        f"exchange.{label}", "parts_decision", parts=parts, rows=rows,
        reason=reason, pass_frac_ema=ema)
    return parts


def resolve_auto_parts(plan_name: str, node, bindings: dict):
    """Resolve an Exchange node's ``parts=0`` auto sentinel into a
    concrete partition count (:func:`choose_parts` over the bound input
    rows). Returns the node unchanged when parts is already concrete —
    fingerprints and plan signatures only ever see resolved counts."""
    if int(node.parts) != 0:
        return node
    rows = sum(int(t.num_rows) for t in bindings.values())
    return node._replace(parts=choose_parts(plan_name, node.label, rows))


def execute_exchange_root(plan, bindings: dict, *,
                          donate_inputs: bool = False,
                          force_staged: bool = False,
                          surface_pressure: bool = False,
                          cancel_token=None):
    """Run a Plan whose root is an ``Exchange`` node: execute the child
    region normally (fused or staged — ``fusion.execute`` decides), trim
    budget-padding phantoms via ``valid_meta``, pack through the overflow
    ladder, and return the wire form with routing meta
    (``<label>.parts/.capacity/.flights/.row_counts/.rows``) merged over
    the child's. Called by ``fusion.execute`` itself — an Exchange root
    is the one node that is a genuine host boundary."""
    from spark_rapids_jni_tpu.runtime import fusion, rtfilter

    root = resolve_auto_parts(plan.name, plan.root, bindings)
    inner = fusion.execute(
        fusion.Plan(plan.name, root.child), bindings,
        donate_inputs=donate_inputs, force_staged=force_staged,
        surface_pressure=surface_pressure, cancel_token=cancel_token)
    tbl = inner.table
    if root.valid_meta is not None:
        if root.valid_meta not in inner.meta:
            raise KeyError(
                f"exchange {root.label!r}: valid_meta {root.valid_meta!r} "
                f"is not a child meta key (have {sorted(inner.meta)})")
        tbl = _slice_rows(
            tbl, 0, int(np.asarray(inner.meta[root.valid_meta])))
    rows = tbl.num_rows
    # harvest the region's group density into the learned store: the
    # signal choose_parts() sizes future auto-parts exchanges from
    rtfilter.observe(plan.name, f"xparts.{root.label}",
                     sum(int(t.num_rows) for t in bindings.values()), rows)
    cap = fusion._resolve(
        root.capacity, {k: v.num_rows for k, v in bindings.items()})
    op = f"exchange.{root.label}"
    with spans.span(op, parts=int(root.parts), rows=rows):
        flights = pack_flights(
            tbl, root.keys, root.parts, capacity=cap, op=op,
            cancel_token=cancel_token)
        wire, row_counts = build_wire(flights)
    REGISTRY.counter("exchange.rows_routed").inc(int(sum(row_counts)))
    telemetry.record_exchange(
        op, "pack", rows=rows, parts=int(root.parts),
        flights=len(flights), capacity=int(flights[0].capacity))
    meta = dict(inner.meta)
    meta[f"{root.label}.parts"] = int(root.parts)
    meta[f"{root.label}.capacity"] = int(flights[0].capacity)
    meta[f"{root.label}.flights"] = len(flights)
    meta[f"{root.label}.row_counts"] = [int(c) for c in row_counts]
    meta[f"{root.label}.rows"] = int(rows)
    return fusion.FusedResult(wire, meta)


@func_range("exchange_local")
def exchange_local(table: Table, keys: Sequence[int], parts: int, *,
                   capacity: Optional[int] = None,
                   op: str = "exchange.local") -> list[Table]:
    """Single-host exchange — the bit-identity oracle for the
    distributed path and the building block of the local partitioned plan
    steps. Returns ``parts`` tables: destination p holds exactly the rows
    whose key hash lands on p, in stable (flight, input) order — the same
    rows, in the same order, the distributed exchange delivers."""
    flights = pack_flights(table, keys, parts, capacity=capacity, op=op)
    per_dest: list[list[Table]] = [[] for _ in range(int(parts))]
    for res in flights:
        for p, s in enumerate(flight_slices(res)):
            if s.num_rows:
                per_dest[p].append(s)
    empty = _slice_rows(flights[0].table, 0, 0)
    return [
        ds[0] if len(ds) == 1 else (concatenate(ds) if ds else empty)
        for ds in per_dest
    ]


def merge_flights(flights: Sequence[Table],
                  partial_fn: Callable[[Table], Table],
                  merge_fn: Callable[[Table], Table], *,
                  budget_bytes: Optional[int] = None,
                  limiter: Optional[MemoryLimiter] = None,
                  spill: Optional[SpillStore] = None,
                  op: str = "exchange.merge", cancel_token=None):
    """Receive-side spill-aware merge: stream a destination's flights
    through the out-of-core chunked aggregator under a device budget
    (``exchange.merge_budget_bytes``), demoting partials into the
    SpillStore when they exceed it — how a skewed destination absorbs a
    multi-flight exchange without holding every flight in HBM at once.
    Zero-leak contract inherited from ``run_chunked_aggregate``. Returns
    its ``OutOfCoreResult``."""
    from spark_rapids_jni_tpu.runtime import outofcore

    flights = list(flights)
    if not flights:
        raise ValueError(f"{op}: no flights to merge")
    budget = int(budget_bytes if budget_bytes is not None
                 else get_option("exchange.merge_budget_bytes"))
    if limiter is None:
        limiter = MemoryLimiter(budget)
    if spill is None:
        spill = SpillStore(budget)
    res = outofcore.run_chunked_aggregate(
        flights, partial_fn, merge_fn,
        limiter=limiter, spill=spill, cancel_token=cancel_token)
    spilled = int(res.spill_stats.get("spills", 0))
    if spilled:
        REGISTRY.counter("exchange.spill_demotions").inc(spilled)
        telemetry.record_exchange(
            op, "spill_demote", spilled=spilled, chunks=res.chunks,
            peak_bytes=res.peak_bytes)
    telemetry.record_exchange(
        op, "merge", rows=res.table.num_rows, chunks=res.chunks,
        peak_bytes=res.peak_bytes)
    return res


def serialize_flight(table: Table, *,
                     op: str = "exchange.serialize_flight", **ctx) -> bytes:
    """Serialize one flight (TPCZ codec via ``dcn.serialize_table``) and
    account for it ONCE, at first seal: ``exchange.flights`` /
    ``bytes_raw`` / ``bytes_wire`` count unique flight payloads, so ARQ
    refetch resends, a direct attempt that falls back to the routed
    rung, or any other re-send of the same pristine blob never double
    counts the wire ledger. Per-attempt transport bytes are the lane
    counters' job (:func:`send_flight_blob`)."""
    from spark_rapids_jni_tpu.parallel import dcn

    blob = dcn.serialize_table(table)
    REGISTRY.counter("exchange.flights").inc()
    REGISTRY.counter("exchange.bytes_raw").inc(int(_table_nbytes(table)))
    REGISTRY.counter("exchange.bytes_wire").inc(len(blob))
    telemetry.record_exchange(
        op, "flight", rows=table.num_rows, wire_bytes=len(blob),
        raw_bytes=int(_table_nbytes(table)), **ctx)
    return blob


def send_flight_blob(sock, blob: bytes, seq: int, *,
                     lane: str = "direct",
                     op: str = "exchange.send_flight", **ctx) -> int:
    """Ship one already-serialized flight blob through the ONE shared
    seal-ordering helper (``dcn.send_framed``) with corruption faults
    scoped to the ``exchange.wire`` seam. ``lane`` names the topology
    the bytes actually took — ``"direct"`` (host-to-host peer dial) or
    ``"routed"`` (via the supervisor) — splitting the transport ledger
    (``exchange.bytes_direct`` / ``exchange.bytes_routed``) so the
    direct path's supervisor-link win is measurable from telemetry
    alone; ``bytes_wire`` was already counted at first seal."""
    from spark_rapids_jni_tpu.parallel import dcn

    lane = str(lane)
    if lane not in ("direct", "routed"):
        raise ValueError(f"exchange flight lane must be 'direct' or "
                         f"'routed', got {lane!r}")
    REGISTRY.counter(f"exchange.bytes_{lane}").inc(len(blob))
    return dcn.send_framed(sock, blob, seq, op=op,
                           corrupt_seam="exchange.wire", lane=lane, **ctx)


def send_flight(sock, table: Table, seq: int, *,
                lane: str = "direct",
                op: str = "exchange.send_flight", **ctx) -> int:
    """Serialize-and-ship convenience: :func:`serialize_flight` (counts
    the wire ledger once) then :func:`send_flight_blob` (counts the
    lane). Callers that may send the same flight on more than one lane
    (direct attempt, routed fallback) call the two halves themselves so
    ``bytes_wire`` stays a unique-payload ledger."""
    blob = serialize_flight(table, op=op, **ctx)
    return send_flight_blob(sock, blob, seq, lane=lane, op=op,
                            rows=table.num_rows, **ctx)


def recv_flight(sock, seq: int, *, op: str = "exchange.recv_flight") -> Table:
    """Receive one flight under verify-then-decode: the trailer is
    checked (NAK-driven refetch on corruption) BEFORE the codec decode
    ever sees the bytes."""
    from spark_rapids_jni_tpu.parallel import dcn

    return dcn.deserialize_table(dcn.recv_framed(sock, seq, op=op))


@func_range("partitioned_groupby")
def partitioned_groupby(table: Table, keys: Sequence[int],
                        aggs: Sequence[tuple], *, parts: int,
                        capacity: Optional[int] = None) -> Table:
    """General hash-partitioned groupby — NO static slot table: exchange
    rows by key hash so every key lives on exactly one partition, then
    run the unbounded per-partition groupby (``max_groups=None`` pads to
    the partition's row count, which can never overflow). Output keys are
    disjoint across partitions, so the concatenation IS the global
    result (order: partition-major, then key-sorted within)."""
    from spark_rapids_jni_tpu.ops.groupby import groupby_aggregate

    out: list[Table] = []
    for dest in exchange_local(table, keys, parts, capacity=capacity):
        if not dest.num_rows:
            continue
        g = groupby_aggregate(dest, list(keys), list(aggs), max_groups=None)
        out.append(_slice_rows(g.table, 0, int(np.asarray(g.num_groups))))
    if not out:
        g = groupby_aggregate(table, list(keys), list(aggs), max_groups=None)
        return _slice_rows(g.table, 0, 0)
    return out[0] if len(out) == 1 else concatenate(out)


@func_range("partitioned_join")
def partitioned_join(left: Table, right: Table,
                     left_on, right_on, *, parts: int,
                     how: str = "inner") -> Table:
    """General hash-partitioned equi-join — co-partition both sides with
    the SAME key hash (matching keys land on the same partition by
    construction), join per partition with the grow-and-retry output
    bound, and concatenate: the per-partition results are disjoint over
    the key space, so the concat is the global join."""
    from spark_rapids_jni_tpu.ops.join import join_auto

    lks = [left_on] if isinstance(left_on, int) else list(left_on)
    rks = [right_on] if isinstance(right_on, int) else list(right_on)
    ldests = exchange_local(left, lks, parts, op="exchange.join_left")
    rdests = exchange_local(right, rks, parts, op="exchange.join_right")
    out: list[Table] = []
    for ld, rd in zip(ldests, rdests):
        if not ld.num_rows:
            continue
        if not rd.num_rows and how == "inner":
            continue
        maps, joined = join_auto(ld, rd, left_on, right_on, how=how)
        # join_auto materializes at the escalated CAPACITY; the real
        # matches are the first maps.total rows
        joined = _slice_rows(joined, 0, int(np.asarray(maps.total)))
        if joined.num_rows:
            out.append(joined)
    if not out:
        maps, joined = join_auto(left, right, left_on, right_on, how=how)
        return _slice_rows(joined, 0, 0)
    return out[0] if len(out) == 1 else concatenate(out)


def stats() -> dict:
    """Snapshot of the ``exchange.*`` transport counters (bench + CI
    smoke): rows routed, flights, raw vs wire bytes, overflow
    escalations, chunked-flight demotions, spill demotions."""
    counters = REGISTRY.counters("exchange.")
    return {
        "rows_routed": counters.get("exchange.rows_routed", 0),
        "flights": counters.get("exchange.flights", 0),
        "bytes_raw": counters.get("exchange.bytes_raw", 0),
        "bytes_wire": counters.get("exchange.bytes_wire", 0),
        "bytes_direct": counters.get("exchange.bytes_direct", 0),
        "bytes_routed": counters.get("exchange.bytes_routed", 0),
        "overflow_escalations":
            counters.get("exchange.overflow_escalations", 0),
        "chunked_flights": counters.get("exchange.chunked_flights", 0),
        "spill_demotions": counters.get("exchange.spill_demotions", 0),
    }
