"""Cross-host serving mesh: remote replicas and data-partitioned query
routing over the sealed DCN transport.

The fleet (runtime/fleet.py) made one *process* survivable; this module
makes one *host* survivable, and moves the queries instead of the data
while doing it:

- :class:`QueryCluster` boots one :class:`~.server.QueryServer` worker
  per simulated host as a subprocess that **dials back** over TCP
  (``dcn.dial`` → the supervisor's :class:`~.dcn.SliceServer` gateway)
  instead of inheriting a socketpair fd — the only transport shape that
  survives an actual network hop. CI runs every host on localhost; the
  control frames are the fleet's integrity-sealed ``_FrameChannel``
  pickle frames, and every table payload inside them is a
  ``dcn.serialize_table`` blob (columnar codec under ``compress.wire``,
  integrity trailer outermost) — the exact wire discipline of the
  two-slice DCN exchange.
- Supervision is the fleet's, unmodified: heartbeat liveness, classified
  worker exits (now stamped ``host=``), bounded failover, crash-loop
  quarantine, the (plan signature, input fingerprint) idempotency pair,
  and fingerprint-checked late-duplicate drops. The mesh plugs into the
  supervision core's hooks (``_launch_worker`` / ``_attach_channel`` /
  ``_route`` / ``_extra``) rather than forking it.
- **Partitioned serving**: :meth:`QueryCluster.register_table` splits a
  table by key hash (``dcn.partition_for_slices``), ships each shard to
  its owning host once, and keeps a supervisor-side partition map plus
  the encoded shard blobs and fingerprints. From then on
  :meth:`submit_to_shard` ships only the *plan* — the query travels to
  the shard, not the shard to the query — and the worker resolves the
  binding from its registered-table store. :meth:`submit_merge` fans a
  partial plan out across every shard and merges on the router, with
  the merged fingerprint memoized so repeated fan-outs must agree
  bit-for-bit.
- **Host failover re-homes data**: when a shard's owner dies, the
  router re-ships the retained shard blob to a healthy host, updates
  the partition map, and re-dispatches — the registration fingerprint
  is verified against the one taken before the bytes crossed the wire,
  so a re-homed query is provably running against the same shard and
  its result is checked against the same memo entry. Bit-identical
  failover, now across hosts.

Every routing decision is visible (tpulint rule 23): ``cluster.*``
counters (``route_local`` / ``route_rehomed`` / ``fanouts`` /
``merges`` / ``host_deaths``) and ``cluster.*`` telemetry events with
``host=`` stamps, rendered by ``telemetry top``'s cluster view and the
report's cluster/hosts sections.
"""

from __future__ import annotations

import collections
import os
import socket
import subprocess
import sys
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

import numpy as np

from spark_rapids_jni_tpu.parallel import dcn
from spark_rapids_jni_tpu.runtime import fleet as fleetmod
from spark_rapids_jni_tpu.runtime import fusion, resilience, resultcache
from spark_rapids_jni_tpu.runtime.fleet import (
    FleetTicket, QueryFleet, _encode_table, _FrameChannel, _Replica)
from spark_rapids_jni_tpu.telemetry import spans
from spark_rapids_jni_tpu.telemetry.events import record_fleet
from spark_rapids_jni_tpu.telemetry.registry import REGISTRY
from spark_rapids_jni_tpu.utils.config import get_option
from spark_rapids_jni_tpu.utils.log import get_logger

__all__ = ["QueryCluster", "MergeTicket", "ExchangeTicket",
           "live_clusters", "main"]

_log = get_logger("cluster")

# the dial-back handshake credential: the supervisor mints one per
# worker launch and only a dial-in presenting a currently-pending token
# is admitted as that host's control channel
_ENV_TOKEN = "SPARK_RAPIDS_TPU_CLUSTER_TOKEN"

# the per-boot peer secret: minted once per supervisor construction and
# shipped to every worker's launch environment. Workers derive the
# grant HMAC key from it (dcn.grant_key) and refuse any direct
# host-to-host flight whose dial grant the supervisor didn't sign.
_ENV_PEER_SECRET = "SPARK_RAPIDS_TPU_CLUSTER_PEER_SECRET"

_LIVE_CLUSTERS: "weakref.WeakSet[QueryCluster]" = weakref.WeakSet()


def live_clusters() -> List["QueryCluster"]:
    """Every open cluster in this process (telemetry ``top`` view)."""
    return [c for c in list(_LIVE_CLUSTERS) if not c._closed]


class _ShardRows:
    """Row-count stand-in for a worker-resident shard: the memo key and
    cost signature both read only ``num_rows``, so the supervisor never
    needs the shard's bytes to derive the idempotency pair."""

    __slots__ = ("num_rows",)

    def __init__(self, num_rows: int):
        self.num_rows = int(num_rows)


class _ShardSet:
    """Supervisor-side record of one partitioned table: the partition
    map (part -> owning host) plus, per part, the encoded shard blob
    (retained for re-homing), its fingerprint (verified on every
    registration — the cross-host half of the idempotency pair) and its
    row count (memo-key stand-in)."""

    __slots__ = ("name", "keys", "parts", "rows", "blobs", "fps", "owners")

    def __init__(self, name: str, keys: tuple, parts: int):
        self.name = name
        self.keys = keys
        self.parts = parts
        self.rows: List[int] = []
        self.blobs: List[bytes] = []
        self.fps: List[str] = []
        self.owners: List[Optional[str]] = [None] * parts


class MergeTicket:
    """Future for one fan-out/fan-in query: every shard's partial ticket
    plus the router-side merge. :meth:`result` blocks for all partials
    (in part order — the merge input order is deterministic), merges on
    the caller's thread under a ``cluster.merge`` span, and memo-checks
    the merged fingerprint so a repeated fan-out — including one whose
    partials failed over to re-homed shards — must come back
    bit-identical or die :class:`~.resilience.CorruptDataError`."""

    def __init__(self, cluster: "QueryCluster", table: str, plan_name: str,
                 tickets: List[FleetTicket], merge_fn):
        self.table = table
        self.plan_name = plan_name
        self.tickets = tickets
        self.fingerprint: Optional[str] = None
        self._cluster = cluster
        self._merge_fn = merge_fn
        self._lock = threading.Lock()
        self._resolved = False
        self._value: Any = None
        self._exc: Optional[BaseException] = None

    def done(self) -> bool:
        return self._resolved or all(t.done() for t in self.tickets)

    def result(self, timeout: Optional[float] = None):
        with self._lock:
            if self._resolved:
                if self._exc is not None:
                    raise self._exc
                return self._value
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            # a timeout leaves the ticket unresolved (retryable wait);
            # any other failure — a failed partial, a merge mismatch —
            # is permanent and resolves the ticket failed
            partials = []
            for t in self.tickets:
                left = (None if deadline is None
                        else max(0.0, deadline - time.monotonic()))
                partials.append(t.result(left))
            try:
                value = self._cluster._merge(self, partials)
            except BaseException as exc:
                self._resolved, self._exc = True, exc
                raise
            self._resolved, self._value = True, value
            return value


class ExchangeTicket:
    """Future for one general-cardinality distributed exchange query.

    Phase 1 (already in flight when this ticket exists): the pack plan —
    an ``Exchange``-rooted plan — fanned out to every shard's host; each
    worker runs its partial locally and returns the WIRE FORM (one
    concatenated table of per-destination slices plus plain
    ``row_counts`` meta). Phase 2 (:meth:`result`): the router splits
    each source's wire table, regroups the slices by destination, and
    per destination either ships the reassembled rows to the
    destination's owning host to run the merge plan there (the normal
    all-to-all path), or — when a skewed destination's flights exceed
    the merge budget — runs the spill-aware chunked merge on the router
    (``exchange.merge_flights``: partials demote into the SpillStore,
    zero leaked reservations). Destination key spaces are disjoint by
    construction, so the part-ordered concatenation of destination
    results is the global answer; its fingerprint is memo-checked like
    :class:`MergeTicket`'s, so a repeated exchange — including one whose
    packs failed over — must come back bit-identical.

    The merge plan must be RE-APPLICABLE (``merge(merge(a) + merge(b))
    == merge(a + b)`` — sum/count-style merge algebra): the spill path
    applies it per chunk and once more over the concatenated partials.
    """

    def __init__(self, cluster: "QueryCluster", session_id: str,
                 table: str, pack_plan: fusion.Plan,
                 merge_plan: fusion.Plan, merge_binding: str,
                 merge_valid_meta: Optional[str],
                 tickets: List[FleetTicket],
                 deadline_ms: Optional[int],
                 merge_budget_bytes: Optional[int],
                 *, direct: bool = False, binding: str = "",
                 bindings: Optional[dict] = None):
        self.table = table
        self.pack_plan = pack_plan
        self.merge_plan = merge_plan
        self.merge_binding = merge_binding
        self.merge_valid_meta = merge_valid_meta
        self.label = str(pack_plan.root.label)
        self.parts = int(pack_plan.root.parts)
        self.tickets = tickets
        self.session_id = session_id
        self.deadline_ms = deadline_ms
        self.merge_budget_bytes = merge_budget_bytes
        self.fingerprint: Optional[str] = None
        # direct mode: the pack fan-out is DEFERRED — phase 1 runs as
        # xpack frames when result() drives the exchange, and the pack
        # binding/broadcast bindings are retained for the routed
        # fallback rung's submit_to_shard fan-out
        self.direct = bool(direct)
        self.binding = str(binding)
        self.bindings = dict(bindings or {})
        self._cluster = cluster
        self._lock = threading.Lock()
        self._claimed = False
        self._done = threading.Event()
        self._value: Any = None
        self._exc: Optional[BaseException] = None

    def done(self) -> bool:
        if self._done.is_set():
            return True
        return bool(self.tickets) and all(t.done() for t in self.tickets)

    def _trim(self, fused: fusion.FusedResult):
        """Slice a merge result back to its true rows (the merge plan's
        unbounded groupby pads to its input row count)."""
        if self.merge_valid_meta is None:
            return fused.table
        from spark_rapids_jni_tpu.ops.table_ops import _slice_rows

        return _slice_rows(
            fused.table, 0,
            int(np.asarray(fused.meta[self.merge_valid_meta])))

    def _run_merge_local(self, tbl):
        """Router-side merge step (the spill path's partial AND merge
        fn — re-applicable algebra makes them the same plan)."""
        return self._trim(fusion.execute(
            self.merge_plan, {self.merge_binding: tbl}))

    def result(self, timeout: Optional[float] = None):
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        # one caller claims the resolution; the phase-1 waits, worker
        # merges and spill ladder all run OUTSIDE the ticket lock (they
        # block on sockets/queues), so concurrent callers park on the
        # event, never on a held lock
        with self._lock:
            claimed = not self._claimed and not self._done.is_set()
            if claimed:
                self._claimed = True
        if not claimed:
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            if not self._done.wait(left):
                raise TimeoutError(
                    f"exchange {self.pack_plan.name!r} (session "
                    f"{self.session_id}) not done within {timeout}s")
            if self._exc is not None:
                raise self._exc
            return self._value
        try:
            value = self._cluster._exchange_run(self, deadline)
        except TimeoutError:
            # a timeout leaves the ticket unresolved (retryable wait);
            # re-driving is idempotent through the fleet memos
            with self._lock:
                self._claimed = False
            raise
        except BaseException as exc:
            # any other failure — a failed partial, a merge mismatch —
            # is permanent and resolves the ticket failed
            self._exc = exc
            self._done.set()
            raise
        self._value = value
        self._done.set()
        return value


class QueryCluster(QueryFleet):
    """Mesh supervisor: the fleet's supervision core over dial-back TCP
    host workers, plus the partition map and locality router.

    ``hosts`` overrides ``cluster.hosts``. Construction binds the
    gateway listener (``dcn.bind_host``, ephemeral port), launches one
    worker per host and returns immediately; :meth:`wait_live` blocks
    until the hosts dialed back and booted. Use as a context manager."""

    _ID_PREFIX = "h"  # host workers: h0, h1, ...
    is_cluster = True

    def __init__(self, hosts: Optional[int] = None, *,
                 worker_env: Optional[Dict[str, str]] = None,
                 per_replica_env: Optional[Dict[str, Dict[str, str]]] = None):
        # gateway + handshake state first: the base ctor spawns workers
        # through our _launch_worker, which needs both
        self._gateway = dcn.SliceServer()
        self._boot_lock = threading.Lock()
        self._pending_boots: Dict[str, tuple] = {}
        self._reg_waits: Dict[tuple, tuple] = {}
        # direct-exchange state: the per-boot peer secret (workers sign
        # peer dial-ins against it), each host's flight-gateway address
        # (reported in its hello), and the pending xpack/xmerge waits
        self._peer_secret = os.urandom(16).hex()
        self._peer_key = dcn.grant_key(self._peer_secret)
        self._peer_addrs: Dict[str, tuple] = {}
        self._x_waits: Dict[tuple, tuple] = {}
        self._tables: Dict[str, _ShardSet] = {}
        self._merge_memo: "collections.OrderedDict[tuple, str]" = \
            collections.OrderedDict()
        self._accept_stop = threading.Event()
        super().__init__(
            hosts if hosts is not None else int(get_option("cluster.hosts")),
            worker_env=worker_env, per_replica_env=per_replica_env)
        _LIVE_CLUSTERS.add(self)
        # dials queue in the listener backlog until this thread starts,
        # so launching before accepting loses no worker
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="cluster-gateway")
        self._accept_thread.start()

    # -- transport: dial-back workers over the DCN gateway -------------------

    def _worker_environment(self, r: _Replica) -> Dict[str, str]:
        env = super()._worker_environment(r)
        # workers stamp host= on every record and span they emit
        env["SPARK_RAPIDS_TPU_TELEMETRY_HOST"] = r.rid
        # the grant key for direct peer flights derives from this; it
        # rides the launch environment, never the data path
        env[_ENV_PEER_SECRET] = self._peer_secret
        return env

    def _extra(self, r: _Replica) -> Dict[str, Any]:
        return {"host": r.rid}

    def _launch_worker(self, r: _Replica):
        token = os.urandom(16).hex()
        with self._boot_lock:
            # a relaunch obsoletes the dead generation's credential
            for tok in [t for t, (rr, g) in self._pending_boots.items()
                        if rr is r and g < r.generation]:
                del self._pending_boots[tok]
            self._pending_boots[token] = (r, r.generation)
        env = self._worker_environment(r)
        env[_ENV_TOKEN] = token
        cmd = [sys.executable, "-m", "spark_rapids_jni_tpu.runtime.cluster",
               "--worker", "--connect",
               f"{self._gateway.host}:{self._gateway.port}",
               "--host", r.rid]
        proc = subprocess.Popen(cmd, env=env)
        # the control channel attaches asynchronously when the worker
        # dials back with its token (the accept loop calls
        # _attach_channel); until then the boot deadline supervises it
        return proc, None

    def _accept_loop(self) -> None:
        while not self._accept_stop.is_set():
            try:
                conn, _addr = self._gateway.accept(timeout=0.2)
            except TimeoutError:
                continue
            except OSError:
                if self._accept_stop.is_set():
                    return
                continue
            # handshake off the accept thread: a stalled dialer must not
            # block other hosts' dial-ins
            threading.Thread(target=self._admit, args=(conn,), daemon=True,
                             name="cluster-admit").start()

    def _admit(self, conn: socket.socket) -> None:
        chan = _FrameChannel(conn)
        try:
            conn.settimeout(10.0)
            hello = chan.recv()
            conn.settimeout(None)
        except BaseException:
            chan.close()
            return
        token = str(hello.get("token", ""))
        with self._boot_lock:
            ent = self._pending_boots.pop(token, None)
        if ent is None:
            # unknown or stale credential: not one of ours (or a boot
            # superseded by a restart) — refuse the channel, visibly
            REGISTRY.counter("cluster.rejected_dials").inc()
            record_fleet("cluster.gateway", "rejected_dial",
                         replica="supervisor",
                         peer=str(hello.get("host", "?")))
            chan.close()
            return
        r, gen = ent
        with self._lock:
            stale = r.generation != gen
        if stale:
            chan.close()
            return
        peer_port = hello.get("peer_port")
        if peer_port:
            # the worker's direct-flight gateway: where OTHER hosts dial
            # it with exchange flights (latest generation wins)
            with self._lock:
                self._peer_addrs[r.rid] = (
                    str(hello.get("peer_host") or self._gateway.host),
                    int(peer_port))
        record_fleet("cluster.gateway", "host_dialed_in", replica=r.rid,
                     host=r.rid, generation=gen,
                     peer_port=int(peer_port or 0))
        self._attach_channel(r, chan, gen)

    # -- partitioned serving: register, route, fan out -----------------------

    def register_table(self, name: str, table, keys,
                       *, parts: Optional[int] = None) -> Dict[str, Any]:
        """Partition ``table`` by the key columns ``keys`` and ship each
        shard to its owning host (round-robin over the live set). The
        supervisor retains each shard's encoded blob and fingerprint —
        the re-homing reserve — and the partition map the router
        consults. Returns ``{table, parts, rows, owners}``."""
        if self._closed:
            raise RuntimeError("cluster is closed")
        name = str(name)
        n = int(parts if parts is not None else self.n_replicas)
        if n < 1:
            raise ValueError("register_table needs at least one partition")
        boot = float(get_option("fleet.worker_boot_timeout_s"))
        if self.wait_live(1, timeout=boot) < 1:
            raise resilience.ReplicaDeadError(
                "cluster: no live host to place shards on", table=name,
                seam="fleet.dispatch")
        with spans.span("cluster.partition", table=name, parts=n):
            shards = dcn.partition_for_slices(table, list(keys), n)
            ss = _ShardSet(name, tuple(int(k) for k in keys), n)
            for shard in shards:
                ss.rows.append(int(shard.num_rows))
                ss.blobs.append(_encode_table(shard))
                ss.fps.append(resultcache.table_fingerprint(shard))
        with self._lock:
            live = [r for r in self._replicas if r.state == "live"]
        for part in range(n):
            r = live[part % len(live)]
            self._register_shard(r, ss, part)
            with self._lock:
                ss.owners[part] = r.rid
        with self._lock:
            self._tables[name] = ss
        record_fleet("cluster.partition_map", "table_registered",
                     replica="supervisor", table=name, parts=n,
                     rows=sum(ss.rows), owners=list(ss.owners))
        return {"table": name, "parts": n, "rows": sum(ss.rows),
                "owners": list(ss.owners)}

    def _register_shard(self, r: _Replica, ss: _ShardSet, part: int) -> None:
        """Ship one retained shard blob to ``r`` and block for its
        acknowledgement; the returned fingerprint must equal the one
        taken before the bytes crossed the wire (CorruptDataError
        otherwise — a shard that mutated in transit must never serve)."""
        reg = f"{ss.name}/p{part}"
        timeout = float(get_option("cluster.register_timeout_s"))
        with self._lock:
            gen, chan = r.generation, r.chan
        if chan is None or r.state != "live":
            raise resilience.ReplicaDeadError(
                f"cluster: host {r.rid} has no live control channel to "
                f"register shard {reg} on", host=r.rid, table=ss.name,
                part=part, seam="fleet.dispatch")
        evt = threading.Event()
        slot: Dict[str, Any] = {}
        key = (r.rid, gen, reg)
        with self._lock:
            self._reg_waits[key] = (evt, slot)
        try:
            with spans.span("cluster.register", replica=r.rid, host=r.rid,
                            table=ss.name, part=part):
                try:
                    chan.send({"t": "register", "name": reg,
                               "table": ss.blobs[part]})
                except BaseException as exc:
                    raise (exc if isinstance(exc, resilience.ResilienceError)
                           else resilience.classify(
                               exc, seam="fleet.dispatch")(
                               f"cluster: shard registration send to "
                               f"{r.rid} failed: {exc}", host=r.rid,
                               table=ss.name, part=part))
                if not evt.wait(timeout):
                    raise resilience.ReplicaDeadError(
                        f"cluster: host {r.rid} did not acknowledge shard "
                        f"{reg} within {timeout}s", host=r.rid,
                        table=ss.name, part=part, seam="fleet.dispatch")
            if "error_kind" in slot:
                raise self._rebuild_error(slot, r.rid)
            if slot.get("fingerprint") != ss.fps[part]:
                REGISTRY.counter("fleet.identity_mismatch").inc()
                record_fleet("cluster.register", "identity_mismatch",
                             replica=r.rid, host=r.rid, table=ss.name,
                             part=part)
                raise resilience.CorruptDataError(
                    f"cluster: shard {reg} registered on {r.rid} with "
                    f"fingerprint {slot.get('fingerprint')!r} but left the "
                    f"supervisor as {ss.fps[part]!r} — shard mutated in "
                    f"transit", host=r.rid, table=ss.name, part=part)
            REGISTRY.counter("cluster.shards_registered").inc()
            record_fleet("cluster.register", "registered", replica=r.rid,
                         host=r.rid, table=ss.name, part=part,
                         rows=slot.get("rows", 0),
                         fingerprint=ss.fps[part])
        finally:
            with self._lock:
                self._reg_waits.pop(key, None)

    def _on_worker_msg(self, r: _Replica, gen: int,
                       msg: Dict[str, Any]) -> None:
        t = msg.get("t")
        if t == "registered":
            key = (r.rid, gen, str(msg.get("name", "")))
            with self._lock:
                ent = self._reg_waits.get(key)
            if ent is None:
                return  # ack for a wait that timed out or a stale gen
            evt, slot = ent
            slot.update(msg)
            evt.set()
        elif t in ("xpack_done", "xmerge_done"):
            key = (str(msg.get("xid", "")), t, int(msg.get("part", -1)))
            with self._lock:
                ent = self._x_waits.get(key)
            if ent is None:
                return  # reply for an abandoned exchange run
            evt, slot, rid, wgen = ent
            if rid != r.rid or wgen != gen:
                return  # stale generation's straggler
            slot.update(msg)
            evt.set()

    def _host(self, rid: Optional[str]) -> Optional[_Replica]:
        if rid is None:
            return None
        for r in self._replicas:
            if r.rid == rid:
                return r
        return None

    def _route(self, q, deadline: float) -> Optional[_Replica]:
        """Locality routing: a shard-pinned query goes to its owning
        host ("ship the query to the shard"); a dead owner triggers
        re-homing — the retained blob re-ships to the cheapest live
        host and the partition map is updated — before dispatch.
        Unpinned queries load-balance exactly like the fleet."""
        if q.shard is None:
            return super()._route(q, deadline)
        name, part = q.shard
        with self._lock:
            ss = self._tables.get(name)
            owner_id = ss.owners[part] if ss is not None else None
        if ss is None:
            raise resilience.MalformedInputError(
                f"cluster: query pinned to unregistered table {name!r}",
                qid=q.qid)
        owner = self._host(owner_id)
        if owner is not None and owner.state == "live":
            REGISTRY.counter("cluster.route_local").inc()
            record_fleet("cluster.route", "local", replica=owner.rid,
                         host=owner.rid, table=name, part=part, qid=q.qid)
            return owner
        r2 = self._pick_replica(deadline)
        if r2 is None:
            return None
        self._register_shard(r2, ss, part)
        with self._lock:
            # first re-homer wins the map; a concurrent failover that
            # also re-registered merely duplicated an idempotent install
            if ss.owners[part] == owner_id:
                ss.owners[part] = r2.rid
        REGISTRY.counter("cluster.route_rehomed").inc()
        record_fleet("cluster.route", "rehomed", replica=r2.rid,
                     host=r2.rid, table=name, part=part, qid=q.qid,
                     from_host=owner_id)
        _log.warning("cluster: shard %s/p%d re-homed %s -> %s",
                     name, part, owner_id, r2.rid)
        return r2

    def shard_for_key(self, name: str, key_table) -> int:
        """Owning partition of one key: hash a single-row table holding
        the key columns (in partition-key order, matching dtypes) with
        the same ``partition_hash`` that sharded the table."""
        from spark_rapids_jni_tpu.ops.hash import partition_hash

        with self._lock:
            ss = self._tables[str(name)]
        ncols = len(key_table.columns)
        if ncols != len(ss.keys):
            raise ValueError(
                f"cluster: table {ss.name!r} partitions on {len(ss.keys)} "
                f"key column(s), got a {ncols}-column key table")
        dest = np.asarray(
            partition_hash(key_table, list(range(ncols)), ss.parts))
        if dest.size != 1:
            raise ValueError("shard_for_key takes exactly one key row, "
                             f"got {dest.size}")
        return int(dest[0])

    def submit_to_shard(self, session_id: str, plan: fusion.Plan, *,
                        table: str, binding: str,
                        part: Optional[int] = None, key_table=None,
                        bindings: Optional[dict] = None,
                        deadline_ms: Optional[int] = None) -> FleetTicket:
        """Route one single-shard query to the host owning the shard.
        Only the plan crosses the wire: ``binding`` resolves on the
        worker from its registered shard. Pass ``part`` directly or
        ``key_table`` (one key row) to look the partition up. The memo
        key pairs the plan signature (derived against the shard's row
        count) with the shard's registration fingerprint, so cross-host
        failover and duplicate drops keep their bit-identity check.

        ``bindings`` optionally ships additional SMALL tables inline on
        the submit frame (sealed DCN transport) — replicated dimension
        sides and runtime-filter ``to_packed`` bloom bits, the
        broadcast half of a fan-out join; the registered shard stays
        resident and never rides the wire."""
        with self._lock:
            ss = self._tables.get(str(table))
        if ss is None:
            raise KeyError(f"cluster: table {table!r} is not registered")
        if part is None:
            if key_table is None:
                raise ValueError("submit_to_shard needs part= or key_table=")
            part = self.shard_for_key(table, key_table)
        part = int(part)
        if not 0 <= part < ss.parts:
            raise IndexError(f"cluster: table {ss.name!r} has {ss.parts} "
                             f"partitions, no p{part}")
        binding = str(binding)
        return self._submit(
            str(session_id), plan, dict(bindings or {}),
            binding_refs={binding: f"{ss.name}/p{part}"},
            shard=(ss.name, part),
            sig_bindings={binding: _ShardRows(ss.rows[part])},
            deadline_ms=deadline_ms,
            cache_fingerprint=ss.fps[part])

    def submit_merge(self, session_id: str, partial_plan: fusion.Plan,
                     merge_fn, *, table: str, binding: str,
                     bindings: Optional[dict] = None,
                     deadline_ms: Optional[int] = None) -> MergeTicket:
        """Fan a partial plan out to every shard's host and merge on the
        router: ``merge_fn(partial_results)`` runs on the caller's
        thread once every partial lands (``MergeTicket.result``), its
        input ordered by part index so the merge is deterministic.
        ``bindings`` (inline broadcast tables — dims, packed bloom
        bits) ship with every per-shard submit."""
        with self._lock:
            ss = self._tables.get(str(table))
        if ss is None:
            raise KeyError(f"cluster: table {table!r} is not registered")
        REGISTRY.counter("cluster.fanouts").inc()
        record_fleet("cluster.fanout", "fanout", replica="supervisor",
                     table=ss.name, parts=ss.parts, plan=partial_plan.name)
        tickets = [
            self.submit_to_shard(session_id, partial_plan, table=table,
                                 binding=binding, part=p,
                                 bindings=bindings,
                                 deadline_ms=deadline_ms)
            for p in range(ss.parts)]
        return MergeTicket(self, ss.name, partial_plan.name, tickets,
                           merge_fn)

    def _merge(self, mt: MergeTicket, partials: List[Any]):
        fps = tuple(t.fingerprint or "" for t in mt.tickets)
        mkey = (mt.plan_name, mt.table, fps)
        with spans.span("cluster.merge", table=mt.table,
                        parts=len(partials), plan=mt.plan_name):
            merged = mt._merge_fn(partials)
        fp = resultcache.table_fingerprint(getattr(merged, "table", merged))
        with self._lock:
            prev = self._merge_memo.get(mkey)
            if prev is None:
                self._merge_memo[mkey] = fp
                while len(self._merge_memo) > 512:
                    self._merge_memo.popitem(last=False)
        if prev is not None and prev != fp:
            REGISTRY.counter("fleet.identity_mismatch").inc()
            record_fleet("cluster.merge", "identity_mismatch",
                         replica="supervisor", table=mt.table,
                         plan=mt.plan_name)
            raise resilience.CorruptDataError(
                f"cluster: merged result for {mt.plan_name} over "
                f"{mt.table} differs from the memoized fingerprint for "
                f"the same partial set — merge determinism violated",
                table=mt.table)
        mt.fingerprint = fp
        REGISTRY.counter("cluster.merges").inc()
        record_fleet("cluster.merge", "merged", replica="supervisor",
                     table=mt.table, parts=len(partials), fingerprint=fp)
        return merged

    def submit_exchange(self, session_id: str, pack_plan: fusion.Plan,
                        merge_plan: Optional[fusion.Plan] = None, *,
                        table: str, binding: str,
                        merge_binding: Optional[str] = None,
                        merge_valid_meta: Optional[str] = None,
                        bindings: Optional[dict] = None,
                        deadline_ms: Optional[int] = None,
                        merge_budget_bytes: Optional[int] = None,
                        direct: Optional[bool] = None
                        ) -> ExchangeTicket:
        """General-cardinality distributed groupby/join fan-out: the
        hash-partitioned all-to-all (``runtime/exchange.py``) over the
        mesh, with NO static slot table anywhere.

        Two plan forms. The classic pair: ``pack_plan`` rooted at an
        ``Exchange`` node whose ``parts`` equals the registered table's
        partition count, plus a ``merge_plan`` scanning
        ``merge_binding``. Or ONE plan with a planner-placed interior
        ``Exchange`` (``merge_plan=None``): the supervisor derives the
        pair with :func:`fusion.split_at_exchange` — ``parts=0`` in the
        plan resolves to the table's partition count, and
        ``merge_valid_meta`` defaults to the merge root's
        ``<label>.num_groups`` when it is an unbounded groupby.

        Each shard's host runs the Exchange child (the partial plan)
        locally, then repartitions its output by the exchange keys into
        per-destination wire buffers (TPCZ codec + integrity seal on
        every hop, like all fleet frames); the merge plan runs on each
        destination's owning host over the rows that hashed there.
        ``direct`` (default ``exchange.direct_enabled``) ships the
        flights host-to-host through each worker's peer gateway — the
        supervisor link carries only the routing manifest and acks —
        with the router-mediated path as the classified fallback rung.
        The returned ticket's :meth:`~ExchangeTicket.result` finishes
        the all-to-all and returns the part-ordered concatenation of
        destination results — bit-identical to the single-host oracle
        (the same plans run over ``exchange.exchange_local``), direct
        or routed."""
        with self._lock:
            ss = self._tables.get(str(table))
        if ss is None:
            raise KeyError(f"cluster: table {table!r} is not registered")
        if merge_plan is None:
            # single mid-plan-Exchange form: derive the pair
            split = fusion.split_at_exchange(pack_plan)
            if split is None:
                raise TypeError(
                    "submit_exchange with merge_plan=None needs a plan "
                    "with an interior Exchange node (see "
                    f"fusion.split_at_exchange), got {pack_plan.name!r}")
            pack_plan, merge_plan, merge_binding, x = split
            if int(x.parts) == 0:
                # auto-parts on a mesh: one destination per shard owner
                x = x._replace(parts=ss.parts)
                pack_plan = fusion.Plan(pack_plan.name, x)
            mroot = merge_plan.root
            if (merge_valid_meta is None
                    and isinstance(mroot, fusion.GroupBy)
                    and mroot.max_groups is None):
                merge_valid_meta = f"{mroot.label}.num_groups"
        if merge_binding is None:
            raise ValueError(
                "submit_exchange needs merge_binding= with an explicit "
                "merge plan")
        root = pack_plan.root
        if not isinstance(root, fusion.Exchange):
            raise TypeError(
                "submit_exchange needs a pack plan rooted at an Exchange "
                f"node, got {type(root).__name__}")
        if int(root.parts) != ss.parts:
            raise ValueError(
                f"cluster: exchange routes to {int(root.parts)} "
                f"destinations but table {ss.name!r} has {ss.parts} "
                f"partitions — they must match (one destination per "
                f"shard owner)")
        direct = (bool(get_option("exchange.direct_enabled"))
                  if direct is None else bool(direct))
        REGISTRY.counter("cluster.fanouts").inc()
        REGISTRY.counter("cluster.exchanges").inc()
        record_fleet("cluster.exchange", "fanout", replica="supervisor",
                     table=ss.name, parts=ss.parts, plan=pack_plan.name,
                     direct=direct)
        if direct:
            # phase 1 is deferred: result() drives the xpack fan-out so
            # grants/manifests bind to one exchange run (a retried wait
            # mints a fresh xid); the routed fallback rung fans out
            # through submit_to_shard like the classic path
            tickets: List[FleetTicket] = []
        else:
            tickets = [
                self.submit_to_shard(session_id, pack_plan, table=table,
                                     binding=binding, part=p,
                                     bindings=bindings,
                                     deadline_ms=deadline_ms)
                for p in range(ss.parts)]
        return ExchangeTicket(self, str(session_id), ss.name, pack_plan,
                              merge_plan, str(merge_binding),
                              merge_valid_meta, tickets, deadline_ms,
                              merge_budget_bytes, direct=direct,
                              binding=str(binding), bindings=bindings)

    def _exchange_merge(self, xt: ExchangeTicket, partials: List[Any],
                        deadline: Optional[float]):
        """Phase 2 of the all-to-all: split every source's wire table,
        regroup by destination, merge each destination (on its owning
        host, or router-side through the spill ladder when its flights
        exceed the budget), and concatenate in part order."""
        from spark_rapids_jni_tpu.ops.table_ops import (
            _slice_rows, concatenate)
        from spark_rapids_jni_tpu.runtime import exchange as xch
        from spark_rapids_jni_tpu.runtime.memory import _table_nbytes
        from spark_rapids_jni_tpu.utils.config import get_option as _opt

        label, parts = xt.label, xt.parts
        per_dest: List[List[Any]] = [[] for _ in range(parts)]
        for fused in partials:
            rc = fused.meta.get(f"{label}.row_counts")
            if rc is None:
                raise resilience.MalformedInputError(
                    f"cluster: exchange partial for {xt.pack_plan.name} "
                    f"carries no {label}.row_counts meta — not an "
                    "Exchange-rooted plan result", table=xt.table,
                    seam="exchange.wire")
            for p, fls in enumerate(xch.split_wire(fused.table, rc, parts)):
                per_dest[p].extend(fls)
        budget = int(xt.merge_budget_bytes
                     if xt.merge_budget_bytes is not None
                     else _opt("exchange.merge_budget_bytes"))
        with spans.span("cluster.exchange_merge", table=xt.table,
                        parts=parts, plan=xt.merge_plan.name):
            # dispatch every host-merged destination first (they run
            # concurrently on their owners), then run any router-side
            # spill merges while the workers compute
            pending: List[Optional[FleetTicket]] = [None] * parts
            spill_parts: List[int] = []
            for p, flights in enumerate(per_dest):
                if not flights:
                    continue
                if (len(flights) > 1
                        and sum(_table_nbytes(f) for f in flights) > budget):
                    spill_parts.append(p)
                    continue
                dest_in = (flights[0] if len(flights) == 1
                           else concatenate(flights))
                pending[p] = self._submit(
                    xt.session_id, xt.merge_plan,
                    {xt.merge_binding: dest_in},
                    shard=(xt.table, p), deadline_ms=xt.deadline_ms)
            spilled: Dict[int, Any] = {}
            for p in spill_parts:
                # a skewed destination: too many flight bytes to reship
                # inline — the spill-aware chunked merge absorbs them
                # through the SpillStore on the router, zero leaks
                REGISTRY.counter("cluster.exchange_spill_merges").inc()
                record_fleet("cluster.exchange", "spill_merge",
                             replica="supervisor", table=xt.table,
                             part=p, flights=len(per_dest[p]))
                res = xch.merge_flights(
                    per_dest[p], xt._run_merge_local, xt._run_merge_local,
                    budget_bytes=budget,
                    op=f"exchange.{label}.merge")
                spilled[p] = res.table
            dest_results: List[Any] = []
            for p in range(parts):
                if p in spilled:
                    dest_results.append(spilled[p])
                elif pending[p] is not None:
                    left = (None if deadline is None
                            else max(0.0, deadline - time.monotonic()))
                    dest_results.append(xt._trim(pending[p].result(left)))
            if dest_results:
                merged = (dest_results[0] if len(dest_results) == 1
                          else concatenate(dest_results))
            else:
                merged = xt._run_merge_local(
                    _slice_rows(partials[0].table, 0, 0))
        fps = tuple(t.fingerprint or "" for t in xt.tickets)
        mkey = ("exchange", xt.pack_plan.name, xt.merge_plan.name,
                xt.table, fps)
        return self._exchange_finish(xt, mkey, merged, parts, "routed")

    def _exchange_finish(self, xt: ExchangeTicket, mkey: tuple, merged,
                         parts: int, mode: str):
        """Shared exchange epilogue: memo-check the concatenated result's
        fingerprint — a repeated exchange over the same input set must
        come back bit-identical whether it ran direct, routed, or fell
        back mid-way — then count and record the merge."""
        fp = resultcache.table_fingerprint(merged)
        with self._lock:
            prev = self._merge_memo.get(mkey)
            if prev is None:
                self._merge_memo[mkey] = fp
                while len(self._merge_memo) > 512:
                    self._merge_memo.popitem(last=False)
        if prev is not None and prev != fp:
            REGISTRY.counter("fleet.identity_mismatch").inc()
            record_fleet("cluster.exchange", "identity_mismatch",
                         replica="supervisor", table=xt.table,
                         plan=xt.merge_plan.name, mode=mode)
            raise resilience.CorruptDataError(
                f"cluster: exchange result for {xt.pack_plan.name} -> "
                f"{xt.merge_plan.name} over {xt.table} differs from the "
                "memoized fingerprint for the same partial set — "
                "exchange determinism violated", table=xt.table)
        xt.fingerprint = fp
        REGISTRY.counter("cluster.exchange_merges").inc()
        record_fleet("cluster.exchange", "merged", replica="supervisor",
                     table=xt.table, parts=parts, fingerprint=fp,
                     mode=mode)
        return merged

    # -- direct flights: host-to-host exchange over the peer gateways --------

    def _exchange_run(self, xt: ExchangeTicket, deadline: Optional[float]):
        """Drive one claimed exchange to its value: the direct
        host-to-host path first (for tickets submitted direct), with the
        router-mediated path as the classified fallback rung — and the
        only path for ``direct=False`` tickets. A fallback re-fans the
        pack out through ``submit_to_shard`` (re-homing dead owners'
        shards on the way), so chaos semantics and SIGKILL failover
        carry over unchanged."""
        if xt.direct:
            try:
                return self._exchange_direct(xt, deadline)
            except TimeoutError:
                raise  # retryable wait: the ticket unclaims
            except BaseException as exc:
                REGISTRY.counter("cluster.exchange_direct_fallbacks").inc()
                record_fleet("cluster.exchange", "direct_fallback",
                             replica="supervisor", table=xt.table,
                             plan=xt.pack_plan.name,
                             error_kind=type(exc).__name__)
                _log.warning(
                    "cluster: direct exchange %s over %s fell back to "
                    "the routed path: %s",
                    xt.pack_plan.name, xt.table, exc)
        if not xt.tickets:
            xt.tickets = [
                self.submit_to_shard(xt.session_id, xt.pack_plan,
                                     table=xt.table, binding=xt.binding,
                                     part=p, bindings=xt.bindings,
                                     deadline_ms=xt.deadline_ms)
                for p in range(xt.parts)]
        partials = []
        for t in xt.tickets:
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            partials.append(t.result(left))
        return self._exchange_merge(xt, partials, deadline)

    def _x_collect(self, wait: tuple, deadline: Optional[float],
                   cap: float, what: str) -> Dict[str, Any]:
        """Block for one xpack/xmerge reply slot. A caller-deadline
        expiry raises ``TimeoutError`` (retryable — the ticket
        unclaims); a per-phase stall or an error reply raises the
        classified ``TransportError`` that trips the routed fallback."""
        key, evt, slot, rid = wait
        left = (cap if deadline is None
                else min(cap, deadline - time.monotonic()))
        ok = evt.wait(max(0.0, left))
        with self._lock:
            self._x_waits.pop(key, None)
        if not ok:
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"cluster: direct exchange {what} on {rid} not done "
                    "before the caller deadline")
            raise resilience.TransportError(
                f"cluster: direct exchange {what} on {rid} did not "
                f"complete within {cap}s", host=rid,
                seam="exchange.wire")
        if slot.get("status") != "ok":
            raise resilience.TransportError(
                f"cluster: direct exchange {what} on {rid} failed: "
                f"{slot.get('error_kind')}: {slot.get('error')}",
                host=rid, seam="exchange.wire")
        return slot

    def _exchange_direct(self, xt: ExchangeTicket,
                         deadline: Optional[float]):
        """The direct all-to-all: phase 1 ships each source owner an
        ``xpack`` frame (plan + per-destination HMAC grants); workers
        pack locally and fly their sealed blobs host-to-host through the
        peer gateways, reporting only fingerprints (plus any blobs whose
        peer dial failed — the per-flight fallback rung). Phase 2 ships
        each destination owner the manifest (source-ordered fingerprint
        list + the supervisor-routed stragglers); workers verify every
        blob against it before decoding, merge, and return the trimmed
        result. The supervisor link carries manifests, acks and merge
        results — never a healthy flight."""
        import pickle

        from spark_rapids_jni_tpu.ops.table_ops import concatenate

        parts = xt.parts
        cap = float(get_option("exchange.direct_timeout_s"))
        owners: List[tuple] = []
        with self._lock:
            ss = self._tables.get(xt.table)
            if ss is None:
                raise KeyError(
                    f"cluster: table {xt.table!r} is not registered")
            for p in range(parts):
                r = self._host(ss.owners[p])
                if r is None or r.state != "live" or r.chan is None:
                    raise resilience.ReplicaDeadError(
                        f"cluster: shard {xt.table}/p{p} owner "
                        f"{ss.owners[p]} is not live for a direct "
                        "exchange", host=str(ss.owners[p]), part=p,
                        seam="fleet.dispatch")
                peer = self._peer_addrs.get(r.rid)
                if peer is None:
                    raise resilience.TransportError(
                        f"cluster: host {r.rid} reported no peer flight "
                        "gateway", host=r.rid, seam="exchange.wire")
                owners.append((r, r.generation, r.chan, peer))
        xid = os.urandom(8).hex()
        plan_blob = pickle.dumps(xt.pack_plan,
                                 protocol=pickle.HIGHEST_PROTOCOL)
        merge_blob = pickle.dumps(xt.merge_plan,
                                  protocol=pickle.HIGHEST_PROTOCOL)
        enc_bindings = {k: _encode_table(v)
                        for k, v in xt.bindings.items()}
        record_fleet("cluster.exchange", "direct_fanout",
                     replica="supervisor", table=xt.table, parts=parts,
                     plan=xt.pack_plan.name, xid=xid)
        try:
            with spans.span("cluster.exchange_direct", table=xt.table,
                            parts=parts, plan=xt.pack_plan.name):
                waits = []
                for sp in range(parts):
                    r, gen, chan, _peer = owners[sp]
                    dests = []
                    for dp in range(parts):
                        rd, _gd, _cd, peerd = owners[dp]
                        dests.append({
                            "part": dp, "host": rd.rid,
                            "addr": list(peerd),
                            "grant": dcn.sign_grant(
                                self._peer_key, xid=xid, src=f"p{sp}",
                                dest=rd.rid, part=dp)})
                    key = (xid, "xpack_done", sp)
                    evt, slot = threading.Event(), {}
                    with self._lock:
                        self._x_waits[key] = (evt, slot, r.rid, gen)
                    waits.append((key, evt, slot, r.rid))
                    chan.send({"t": "xpack", "xid": xid, "part": sp,
                               "plan": plan_blob, "binding": xt.binding,
                               "binding_ref": f"{xt.table}/p{sp}",
                               "bindings": enc_bindings, "dests": dests,
                               "timeout_s": cap})
                packs = [self._x_collect(w, deadline, cap, "xpack")
                         for w in waits]
                # manifests stay SOURCE-ORDERED (sp ascending): the
                # destination concatenates in manifest order, which is
                # the routed path's source-major flight order — the
                # bit-identity contract
                manifests: List[list] = [[] for _ in range(parts)]
                routed: List[dict] = [dict() for _ in range(parts)]
                bytes_direct = bytes_routed = 0
                for sp, res in enumerate(packs):
                    sid = f"p{sp}"
                    for dp, fpv in (res.get("fps") or {}).items():
                        manifests[int(dp)].append([sid, str(fpv)])
                    for dp, blob in (res.get("routed") or {}).items():
                        routed[int(dp)][sid] = blob
                    bytes_direct += int(res.get("bytes_direct", 0))
                    bytes_routed += int(res.get("bytes_routed", 0))
                # workers counted their own lanes in their own
                # processes; re-increment here so the split is
                # measurable from the supervisor's telemetry alone
                REGISTRY.counter("exchange.bytes_direct").inc(bytes_direct)
                REGISTRY.counter("exchange.bytes_routed").inc(bytes_routed)
                budget = int(xt.merge_budget_bytes
                             if xt.merge_budget_bytes is not None
                             else get_option("exchange.merge_budget_bytes"))
                mwaits = []
                for dp in range(parts):
                    if not manifests[dp]:
                        continue
                    r, gen, chan, _peer = owners[dp]
                    key = (xid, "xmerge_done", dp)
                    evt, slot = threading.Event(), {}
                    with self._lock:
                        self._x_waits[key] = (evt, slot, r.rid, gen)
                    mwaits.append(((key, evt, slot, r.rid), dp))
                    chan.send({"t": "xmerge", "xid": xid, "part": dp,
                               "plan": merge_blob,
                               "binding": xt.merge_binding,
                               "valid_meta": xt.merge_valid_meta,
                               "manifest": manifests[dp],
                               "routed": routed[dp], "budget": budget,
                               "timeout_s": cap})
                dest_results = []
                for w, dp in mwaits:
                    slot = self._x_collect(w, deadline, cap, "xmerge")
                    tbl = fleetmod._decode_table(slot["table"])
                    if (resultcache.table_fingerprint(tbl)
                            != slot.get("fingerprint")):
                        REGISTRY.counter("fleet.identity_mismatch").inc()
                        record_fleet("cluster.exchange",
                                     "identity_mismatch",
                                     replica="supervisor",
                                     table=xt.table, part=dp,
                                     mode="direct")
                        raise resilience.CorruptDataError(
                            f"cluster: direct merge result for part {dp} "
                            "mutated crossing the supervisor link",
                            table=xt.table, part=dp)
                    dest_results.append(tbl)
                if not dest_results:
                    raise resilience.TransportError(
                        "cluster: direct exchange produced no "
                        "destination results", seam="exchange.wire")
                merged = (dest_results[0] if len(dest_results) == 1
                          else concatenate(dest_results))
        finally:
            with self._lock:
                for k in [k for k in self._x_waits if k[0] == xid]:
                    self._x_waits.pop(k, None)
        REGISTRY.counter("cluster.exchanges_direct").inc()
        # keyed by the SHARD fingerprints (the direct path has no pack
        # tickets): a repeated direct exchange over the same registered
        # input set must come back bit-identical
        mkey = ("exchange-direct", xt.pack_plan.name, xt.merge_plan.name,
                xt.table, tuple(ss.fps))
        return self._exchange_finish(xt, mkey, merged, parts, "direct")

    # -- supervision overrides ----------------------------------------------

    def _on_replica_death(self, r: _Replica, gen: int,
                          classified: BaseException) -> None:
        before = r.crashes_total
        super()._on_replica_death(r, gen, classified)
        # fail this generation's pending direct-exchange waits FAST: a
        # host killed mid-flight must trip the routed fallback rung, not
        # stall the exchange until its phase timeout
        with self._lock:
            dead = [v for k, v in self._x_waits.items()
                    if v[2] == r.rid and v[3] == gen]
        for evt, slot, _rid, _g in dead:
            slot.setdefault("status", "error")
            slot.setdefault("error_kind", type(classified).__name__)
            slot.setdefault("error",
                            f"host {r.rid} died mid-exchange")
            evt.set()
        if r.crashes_total != before:
            # the base counted a real (non-stale, unplanned) death: that
            # is a HOST death here, with shards to re-home on demand
            REGISTRY.counter("cluster.host_deaths").inc()
            record_fleet("cluster.supervise", "host_death", replica=r.rid,
                         host=r.rid,
                         error_kind=type(classified).__name__)

    def inspect(self) -> dict:
        snap = super().inspect()
        snap["cluster"] = True
        with self._lock:
            snap["tables"] = {
                name: {"parts": ss.parts, "keys": list(ss.keys),
                       "rows": sum(ss.rows), "owners": list(ss.owners)}
                for name, ss in self._tables.items()}
        c = REGISTRY.counters("cluster.")
        snap["counters"].update(
            {k: v for k, v in sorted(c.items()) if k.count(".") == 1})
        return snap

    def close(self, timeout: float = 30.0) -> None:
        super().close(timeout)
        self._accept_stop.set()
        self._gateway.close()
        if getattr(self, "_accept_thread", None) is not None:
            self._accept_thread.join(timeout=2.0)


# ---------------------------------------------------------------------------
# worker side: dial back, authenticate, run the fleet worker loop
# ---------------------------------------------------------------------------


def _handle_xpack(chan: _FrameChannel, srv, msg: Dict[str, Any],
                  hid: str, peer) -> None:
    """Worker-side phase 1 of a direct exchange: run the pack plan over
    the registered shard, split its wire table per destination, and fly
    each destination's blob host-to-host through that destination's
    peer gateway (self-deliveries skip the dial). A failed peer dial is
    the per-flight fallback rung: the blob rides the reply frame back to
    the supervisor, recorded and counted — the exchange completes
    either way. The reply carries only fingerprints, lane byte counts
    and any routed blobs."""
    import pickle

    from spark_rapids_jni_tpu.ops.table_ops import concatenate
    from spark_rapids_jni_tpu.runtime import exchange as xch

    xid, sp = str(msg.get("xid", "")), int(msg.get("part", -1))
    src_id = f"p{sp}"
    try:
        delay_ms = float(
            os.environ.get(fleetmod._ENV_SERVE_DELAY, "0") or 0.0)
        if delay_ms > 0:
            time.sleep(delay_ms / 1000.0)  # chaos hold (SIGKILL tests)
        plan = pickle.loads(msg["plan"])
        bindings = {k: fleetmod._decode_table(v)
                    for k, v in (msg.get("bindings") or {}).items()}
        ref = msg.get("binding_ref")
        if ref:
            try:
                bindings[str(msg.get("binding"))] = \
                    srv.registered_table(ref)
            except KeyError:
                raise resilience.MalformedInputError(
                    f"direct pack references unregistered shard "
                    f"{ref!r}", host=hid)
        fused = fusion.execute(plan, bindings)
        label = str(plan.root.label)
        parts = int(plan.root.parts)
        rc = fused.meta[f"{label}.row_counts"]
        per_dest = xch.split_wire(fused.table, rc, parts)
        dests = {int(d["part"]): d for d in msg.get("dests", [])}
        fps: Dict[int, str] = {}
        routed: Dict[int, bytes] = {}
        sent: List[int] = []
        bytes_direct = bytes_routed = 0
        for dp, flights in enumerate(per_dest):
            if not flights:
                continue
            dest_in = (flights[0] if len(flights) == 1
                       else concatenate(flights))
            blob = xch.serialize_flight(
                dest_in, op="exchange.direct_pack", xid=xid,
                src=src_id, dest=dp)
            fp = dcn.flight_fingerprint(blob)
            fps[dp] = fp
            d = dests[dp]
            header = {"xid": xid, "src": src_id, "part": dp,
                      "grant": d.get("grant", ""), "fp": fp}
            if str(d.get("host")) == hid and peer is not None:
                # self-flight: the destination is this host — straight
                # into the local mailbox, no dial
                peer.deliver(xid, dp, src_id, blob)
                REGISTRY.counter("exchange.bytes_direct").inc(len(blob))
                bytes_direct += len(blob)
                sent.append(dp)
                continue
            try:
                dcn.send_peer_flight(
                    tuple(d["addr"]), header, blob,
                    op="exchange.direct_flight", xid=xid, src=src_id)
            except (resilience.ResilienceError, ConnectionError,
                    OSError) as exc:
                # peer unreachable (or it refused the grant): this
                # flight routes via the supervisor, recorded — the
                # classified fallback rung
                REGISTRY.counter("exchange.peer_dial_fallbacks").inc()
                record_fleet("cluster.peer_flight", "dial_fallback",
                             replica=hid, host=hid, xid=xid, dest=dp,
                             error_kind=type(exc).__name__)
                routed[dp] = blob
                REGISTRY.counter("exchange.bytes_routed").inc(len(blob))
                bytes_routed += len(blob)
                continue
            REGISTRY.counter("exchange.bytes_direct").inc(len(blob))
            bytes_direct += len(blob)
            sent.append(dp)
        chan.send({"t": "xpack_done", "xid": xid, "part": sp,
                   "status": "ok", "fps": fps, "routed": routed,
                   "sent": sent, "bytes_direct": bytes_direct,
                   "bytes_routed": bytes_routed,
                   "rows": int(fused.meta[f"{label}.rows"])})
    except BaseException as exc:
        err = (exc if isinstance(exc, resilience.ResilienceError)
               else resilience.classify(exc, seam="exchange.wire")(
                   f"direct pack failed on {hid}: {exc}", host=hid))
        chan.send({"t": "xpack_done", "xid": xid, "part": sp,
                   "status": "error", "error_kind": type(err).__name__,
                   "error": str(err)})


def _handle_xmerge(chan: _FrameChannel, srv, msg: Dict[str, Any],
                   hid: str, peer) -> None:
    """Worker-side phase 2 of a direct exchange: collect this
    destination's flights from the peer mailbox (plus any
    supervisor-routed stragglers off the frame), verify EVERY blob
    against the manifest fingerprint before decoding (tpulint rule 26 —
    an unverified flight must never merge), run the merge plan over the
    manifest-ordered concatenation (or the spill-aware chunked merge
    when the flights exceed the budget), and reply with the trimmed
    result."""
    import pickle

    from spark_rapids_jni_tpu.ops.table_ops import (
        _slice_rows, concatenate)
    from spark_rapids_jni_tpu.runtime import exchange as xch
    from spark_rapids_jni_tpu.runtime.memory import _table_nbytes

    xid, dp = str(msg.get("xid", "")), int(msg.get("part", -1))
    try:
        try:
            plan = pickle.loads(msg["plan"])
            binding = str(msg.get("binding"))
            vm = msg.get("valid_meta")
            manifest = list(msg.get("manifest") or [])
            routed = dict(msg.get("routed") or {})
            timeout = float(msg.get("timeout_s") or 30.0)
            direct_srcs = [s for s, _fp in manifest if s not in routed]
            flights: Dict[str, bytes] = {}
            if direct_srcs:
                if peer is None:
                    raise resilience.TransportError(
                        "no peer flight gateway on this worker",
                        host=hid, seam="exchange.wire")
                flights = peer.wait_flights(xid, dp, direct_srcs,
                                            timeout=timeout)
            tables = []
            for src_id, want_fp in manifest:
                blob = routed.get(src_id)
                if blob is None:
                    blob = flights.get(src_id)
                if blob is None or dcn.flight_fingerprint(blob) != want_fp:
                    # a flight that does not match the supervisor's
                    # manifest must never decode, let alone merge
                    REGISTRY.counter("fleet.identity_mismatch").inc()
                    record_fleet("cluster.peer_flight",
                                 "manifest_mismatch", replica=hid,
                                 host=hid, xid=xid, part=dp, src=src_id)
                    raise resilience.CorruptDataError(
                        f"direct flight {src_id} -> p{dp} of exchange "
                        f"{xid} does not match the manifest "
                        "fingerprint — refusing to decode", host=hid,
                        part=dp)
                tables.append(dcn.deserialize_table(blob))

            def step(tbl):
                res = fusion.execute(plan, {binding: tbl})
                if vm is None:
                    return res.table
                return _slice_rows(
                    res.table, 0, int(np.asarray(res.meta[vm])))

            budget = int(msg.get("budget")
                         or get_option("exchange.merge_budget_bytes"))
            if (len(tables) > 1
                    and sum(_table_nbytes(t) for t in tables) > budget):
                # a skewed destination on the DIRECT path spills on its
                # own host — the router never sees the flights at all
                REGISTRY.counter("cluster.exchange_spill_merges").inc()
                record_fleet("cluster.exchange", "spill_merge",
                             replica=hid, host=hid, part=dp,
                             flights=len(tables))
                out = xch.merge_flights(
                    tables, step, step, budget_bytes=budget,
                    op="exchange.direct_merge").table
            else:
                dest_in = (tables[0] if len(tables) == 1
                           else concatenate(tables))
                out = step(dest_in)
            chan.send({"t": "xmerge_done", "xid": xid, "part": dp,
                       "status": "ok",
                       "table": fleetmod._encode_table(out),
                       "fingerprint": resultcache.table_fingerprint(out),
                       "rows": int(out.num_rows)})
        finally:
            if peer is not None:
                peer.discard(xid, dp)
    except BaseException as exc:
        err = (exc if isinstance(exc, resilience.ResilienceError)
               else resilience.classify(exc, seam="exchange.wire")(
                   f"direct merge failed on {hid}: {exc}", host=hid))
        chan.send({"t": "xmerge_done", "xid": xid, "part": dp,
                   "status": "error", "error_kind": type(err).__name__,
                   "error": str(err)})


def _worker_main(connect: str, hid: str) -> int:
    """Host-worker entrypoint: dial the supervisor's gateway (bounded
    classified retry via ``dcn.dial``), present the launch token — and
    the port of this worker's own peer flight gateway, booted from the
    per-boot peer secret — then hand the connected channel to the
    fleet's worker loop with the direct-exchange frame handlers
    installed. The control protocol is the fleet's from here on."""
    if os.environ.get(fleetmod._ENV_BOOT_CRASH):
        return 3  # chaos hook: crash-loop at boot
    host, _, port = connect.rpartition(":")
    secret = os.environ.get(_ENV_PEER_SECRET, "")
    peer = (dcn.PeerFlightServer(dcn.grant_key(secret), dest=hid)
            if secret else None)
    sock = dcn.dial(int(port), host or None)
    chan = _FrameChannel(sock)
    hello: Dict[str, Any] = {"t": "hello", "host": hid,
                             "token": os.environ.get(_ENV_TOKEN, "")}
    if peer is not None:
        hello["peer_host"] = peer.host
        hello["peer_port"] = peer.port
    chan.send(hello)
    exts = {
        "xpack": lambda ch, srv, m, rid: _handle_xpack(
            ch, srv, m, rid, peer),
        "xmerge": lambda ch, srv, m, rid: _handle_xmerge(
            ch, srv, m, rid, peer),
    }
    try:
        return fleetmod._worker_loop(chan, hid, extensions=exts)
    finally:
        if peer is not None:
            peer.close()


def main(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if "--worker" not in args:
        print("usage: python -m spark_rapids_jni_tpu.runtime.cluster "
              "--worker --connect <host:port> --host <hid>",
              file=sys.stderr)
        return 2
    connect = hid = None
    for i, a in enumerate(args):
        if a == "--connect" and i + 1 < len(args):
            connect = args[i + 1]
        elif a == "--host" and i + 1 < len(args):
            hid = args[i + 1]
    if connect is None or hid is None:
        print("cluster worker: --connect and --host are required",
              file=sys.stderr)
        return 2
    return _worker_main(connect, hid)


if __name__ == "__main__":
    sys.exit(main())
