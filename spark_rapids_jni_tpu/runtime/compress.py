"""Columnar compression for every managed byte path — the codec UNDER
the integrity seal.

The integrity layer (runtime/integrity.py) made every spill, wire frame,
checkpoint and cached result verifiable; this module makes the same
bytes *small*. Thallus (PAPERS.md) argues the transport win for columnar
data comes from re-encoding columns before they hit the wire, and
Sparkle shows shared materialized intermediates only pay when their
resident footprint is small — both land here: one zero-hard-dependency
codec threaded through the SpillStore host/disk tiers
(``runtime/memory.py``), DCN frames (``parallel/dcn.py``), out-of-core
checkpoints (``runtime/outofcore.py``) and result-cache entries
(``runtime/resultcache.py``).

Schemes, chosen per buffer from a cheap sampled estimate:

- **DICT** — low-cardinality columns (TPC-H returnflag/linestatus: 2-3
  distinct byte values) re-encode as a value dictionary plus
  smallest-width indices;
- **RLE** — sorted / runny columns re-encode as (run length, run value)
  pairs;
- **BITPACK** — boolean validity masks pack 8 flags per byte
  (``np.packbits``);
- **RAW** — passthrough when re-encode doesn't pay (the estimate is a
  strided ~1k-element sample, so a high-entropy float column costs one
  cheap scan, not a wasted encode).

``zstandard``, when importable, runs as an optional *final* stage over
whichever scheme won (and is the single shared availability guard —
``zstd_codec``/``zstd_available`` here replace the copy ``parallel/
dcn.py`` used to carry). Absent zstd, DICT/RLE/BITPACK still carry the
measured ratio; nothing in this module hard-imports it.

Every encoded buffer is a self-describing frame (magic ``TPCZ`` |
version | scheme | zstd flag | dtype | shape | payload length |
payload) so decode needs no side channel. The ordering contract at
every seam is **compress -> seal** on write and **verify -> decompress
-> post-decode length/shape check** on read: a corrupt compressed
payload is detected-and-classified by the trailer before any byte is
interpreted, and a payload whose *seal* verifies but whose codec frame
is inconsistent (the corrupt-after-decompress shape) still raises the
classified :class:`CorruptDataError` from the frame checks here —
never garbage decoded, never an unclassified crash. tpulint rule 17
(``compress-inside-seal``) enforces the ordering statically.

Config: ``compress.enabled`` gates everything; ``compress.spill`` /
``compress.wire`` / ``compress.checkpoint`` / ``compress.cache`` gate
one seam each; ``compress.zstd_level`` sets the final-stage level (used
only when zstandard is importable). Env ``SPARK_RAPIDS_TPU_COMPRESS_*``.
Disabled — globally or per seam — every byte path is byte-for-byte the
legacy framing (pinned by disabled-parity tests at every seam).

Zero dependencies beyond numpy + the stdlib; no jax imports (this
module runs on the control plane, same hygiene as integrity.py).
"""

from __future__ import annotations

import struct
import time
from typing import Any, Optional, Tuple

from spark_rapids_jni_tpu import telemetry
from spark_rapids_jni_tpu.runtime.resilience import CorruptDataError
from spark_rapids_jni_tpu.telemetry import REGISTRY
from spark_rapids_jni_tpu.utils.config import get_option

import numpy as np

__all__ = [
    "FRAME_MAGIC",
    "FRAME_VERSION",
    "PACK_TAG",
    "SCHEME_RAW",
    "SCHEME_RLE",
    "SCHEME_DICT",
    "SCHEME_BITPACK",
    "SEAM_OPTIONS",
    "corrupt",
    "decode_array",
    "enabled",
    "encode_array",
    "is_codec_pack",
    "pack_array",
    "seam_enabled",
    "seam_key",
    "unpack_array",
    "zstd_available",
    "zstd_codec",
]

FRAME_MAGIC = b"TPCZ"
FRAME_VERSION = 1

SCHEME_RAW = 0
SCHEME_RLE = 1
SCHEME_DICT = 2
SCHEME_BITPACK = 3
_SCHEME_NAMES = {
    SCHEME_RAW: "raw",
    SCHEME_RLE: "rle",
    SCHEME_DICT: "dict",
    SCHEME_BITPACK: "bitpack",
}

# Snapshot-pack tag: codec-framed buffers travel through SpillStore
# snapshots as ("tpcc", dtype_str, shape, frame_bytes) — the same
# 4-tuple shape as the legacy ("zstd", ...) pack, deliberately, so
# snaps_checksum / corruption injection / fingerprint hashing all fold
# the blob at index 3 without knowing which codec produced it.
PACK_TAG = "tpcc"

# integrity seam -> the per-seam config option that gates the codec there
SEAM_OPTIONS = {
    "integrity.spill": "compress.spill",
    "integrity.wire": "compress.wire",
    "integrity.checkpoint": "compress.checkpoint",
    "integrity.cache": "compress.cache",
}

# ratio histogram bounds: 1.0 = incompressible, 16x+ = constant columns
_RATIO_BOUNDS = (1.0, 1.25, 1.5, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 32.0)

# encode below this size cannot pay for its own header
_MIN_ENCODE_BYTES = 64
# a re-encode must beat raw by at least this factor to be worth the
# decode work on the read side (raw passthrough otherwise)
_PAY_FRACTION = 0.9
# strided sample size for the scheme estimate
_SAMPLE = 1024

# ---------------------------------------------------------------------------
# the shared zstandard guard (hoisted from parallel/dcn.py)
# ---------------------------------------------------------------------------


def zstd_codec(level: int):
    """The one optional-``zstandard`` import in the tree: returns
    ``(ZstdCompressor(level), ZstdDecompressor())`` or raises
    ``ModuleNotFoundError`` when the package is absent. ``parallel/
    dcn.py`` and ``runtime/memory.py`` re-use this so wire and codec can
    never disagree on availability."""
    import zstandard as zstd

    return zstd.ZstdCompressor(level=level), zstd.ZstdDecompressor()


def zstd_available() -> bool:
    """True when the optional final stage can run (cached)."""
    global _ZSTD_OK
    if _ZSTD_OK is None:
        try:
            zstd_codec(1)
            _ZSTD_OK = True
        except ModuleNotFoundError:
            _ZSTD_OK = False
    return _ZSTD_OK


_ZSTD_OK: Optional[bool] = None


# ---------------------------------------------------------------------------
# config gates
# ---------------------------------------------------------------------------


def enabled() -> bool:
    """Master gate: ``compress.enabled`` (env
    ``SPARK_RAPIDS_TPU_COMPRESS_ENABLED``)."""
    return bool(get_option("compress.enabled"))


def seam_key(seam: str) -> str:
    """Short seam label for telemetry ("integrity.spill" -> "spill")."""
    return str(seam).rsplit(".", 1)[-1]


def seam_enabled(seam: str) -> bool:
    """Is the codec on for one integrity seam? False for the master
    gate off, the per-seam gate off, or an unknown seam (unknown byte
    paths stay legacy until they are explicitly given a gate)."""
    if not enabled():
        return False
    option = SEAM_OPTIONS.get(str(seam))
    if option is None:
        return False
    return bool(get_option(option))


# ---------------------------------------------------------------------------
# classified decode failures
# ---------------------------------------------------------------------------


def _corrupt(reason: str, *, seam: str, op: str, **context: Any) -> CorruptDataError:
    """Count + record one codec-frame mismatch and return the classified
    exception — same accounting shape as integrity's ``_mismatch`` so a
    corrupt-after-decompress frame shows up beside trailer mismatches in
    every report."""
    REGISTRY.counter("integrity.mismatch").inc()
    REGISTRY.counter(f"integrity.mismatch.{seam}").inc()
    REGISTRY.counter("compress.mismatch").inc()
    telemetry.record_integrity(op, "mismatch", seam=seam, reason=reason, **context)
    return CorruptDataError(reason, seam=seam, op=op, **context)


# ---------------------------------------------------------------------------
# scheme encoders — each returns the raw scheme payload bytes
# ---------------------------------------------------------------------------


def _rle_split(flat: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(run_values, run_lengths) of a 1-D array."""
    if flat.size == 0:
        return flat[:0], np.zeros(0, dtype=np.uint32)
    boundaries = np.flatnonzero(flat[1:] != flat[:-1]) + 1
    starts = np.concatenate(([0], boundaries))
    ends = np.concatenate((boundaries, [flat.size]))
    return flat[starts], (ends - starts).astype(np.uint32)


def _encode_rle(flat: np.ndarray) -> bytes:
    values, lengths = _rle_split(flat)
    return b"".join((
        struct.pack("<I", values.size),
        lengths.tobytes(),
        np.ascontiguousarray(values).tobytes(),
    ))


def _index_bits(k: int) -> int:
    """Bits per dictionary index for cardinality ``k`` — sub-byte for the
    low-cardinality columns that motivate the scheme (TPC-H flags at 2-3
    distinct values pack 4-8 indices per byte)."""
    for bits in (1, 2, 4, 8, 16):
        if k <= (1 << bits):
            return bits
    return 32


def _index_nbytes(n: int, bits: int) -> int:
    if bits >= 8:
        return n * (bits // 8)
    return (n * bits + 7) // 8


def _pack_indices(idx: np.ndarray, bits: int) -> bytes:
    if bits >= 8:
        return idx.astype(np.dtype(f"<u{bits // 8}")).tobytes()
    per = 8 // bits
    pad = (-idx.size) % per
    if pad:
        idx = np.concatenate([idx, np.zeros(pad, dtype=idx.dtype)])
    m = idx.reshape(-1, per).astype(np.uint8)
    shifts = np.arange(per, dtype=np.uint8) * np.uint8(bits)
    return np.bitwise_or.reduce(m << shifts, axis=1).astype(np.uint8).tobytes()


def _unpack_indices(buf: bytes, bits: int, n: int) -> np.ndarray:
    if bits >= 8:
        return np.frombuffer(buf, dtype=np.dtype(f"<u{bits // 8}"), count=n)
    per = 8 // bits
    b = np.frombuffer(buf, dtype=np.uint8)
    shifts = np.arange(per, dtype=np.uint8) * np.uint8(bits)
    mask = np.uint8((1 << bits) - 1)
    return ((b[:, None] >> shifts) & mask).reshape(-1)[:n]


def _encode_dict(flat: np.ndarray, values: np.ndarray,
                 indices: np.ndarray) -> bytes:
    bits = _index_bits(values.size)
    return b"".join((
        struct.pack("<IB", values.size, bits),
        np.ascontiguousarray(values).tobytes(),
        _pack_indices(indices, bits),
    ))


def _encode_bitpack(flat: np.ndarray) -> bytes:
    return np.packbits(flat.astype(np.uint8, copy=False)).tobytes()


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------


def _choose_scheme(flat: np.ndarray) -> Tuple[int, bytes]:
    """Pick the cheapest scheme for one flattened buffer. The decision
    runs on a strided ~1k-element sample (one cheap scan); only schemes
    the sample says are promising pay for a full-column encode, and the
    winner must beat raw by ``_PAY_FRACTION`` to displace passthrough."""
    raw_nbytes = flat.nbytes
    if flat.dtype == np.bool_:
        # validity masks: 8 flags per byte always pays past header size
        return SCHEME_BITPACK, _encode_bitpack(flat)
    if (raw_nbytes < _MIN_ENCODE_BYTES or flat.dtype.kind not in "iufb"
            or flat.dtype.itemsize == 0):
        return SCHEME_RAW, flat.tobytes()

    step = max(1, flat.size // _SAMPLE)
    sample = flat[::step]
    item = flat.dtype.itemsize

    best_scheme = SCHEME_RAW
    best_payload = None
    best_size = int(raw_nbytes * _PAY_FRACTION)

    # dictionary: promising when the strided sample's cardinality is
    # small both absolutely and relative to the sample
    uniq = np.unique(sample)
    if uniq.size <= 0xFFFF and uniq.size <= max(2, sample.size // 4):
        if item == 1 and flat.dtype.kind in "iu":
            # 1-byte columns (the TPC-H flag/status targets) skip the
            # O(n log n) unique sort: 256-bucket bincount + LUT gather
            u8 = flat.view(np.uint8)
            present = np.flatnonzero(np.bincount(u8, minlength=256))
            values = present.astype(np.uint8).view(flat.dtype)
            lut = np.zeros(256, dtype=np.uint16)
            lut[present] = np.arange(present.size, dtype=np.uint16)
            indices = lut[u8]
        else:
            values, indices = np.unique(flat, return_inverse=True)
        if values.size <= 0xFFFF:
            bits = _index_bits(values.size)
            est = (values.size * item
                   + _index_nbytes(flat.size, bits) + 5)
            if est < best_size:
                payload = _encode_dict(flat, values, indices)
                if len(payload) < best_size:
                    best_scheme, best_payload = SCHEME_DICT, payload
                    best_size = len(payload)

    # run length: run DENSITY must come from contiguous windows — a
    # strided sample of a sorted column transitions at nearly every
    # sampled step even when real runs span hundreds of rows
    win = 256
    if flat.size <= 4 * win:
        est_runs = _rle_split(flat)[1].size
    else:
        transitions = 0
        seen = 0
        for start in np.linspace(0, flat.size - win, 4).astype(np.int64):
            w = flat[start:start + win]
            transitions += int(np.count_nonzero(w[1:] != w[:-1]))
            seen += w.size
        est_runs = max(int(flat.size * (transitions / max(seen, 1))), 1)
    est = est_runs * (4 + item) + 4
    if est < best_size:
        payload = _encode_rle(flat)
        if len(payload) < best_size:
            best_scheme, best_payload = SCHEME_RLE, payload
            best_size = len(payload)

    if best_payload is None:
        return SCHEME_RAW, flat.tobytes()
    return best_scheme, best_payload


def encode_array(arr: np.ndarray, *, seam: str = "integrity.spill",
                 level: Optional[int] = None) -> bytes:
    """One host buffer -> one self-describing codec frame.

    Scheme is chosen per buffer (see :func:`_choose_scheme`); when
    ``zstandard`` is importable and ``level`` (default
    ``compress.zstd_level``) is positive, the winning payload is
    additionally zstd-compressed iff that shrinks it. The frame header
    records dtype and shape so :func:`decode_array` needs no side
    channel."""
    t0 = time.perf_counter()
    a = np.ascontiguousarray(arr)
    flat = a.reshape(-1)
    scheme, payload = _choose_scheme(flat)
    zflag = 0
    if level is None:
        level = int(get_option("compress.zstd_level"))
    if level > 0 and len(payload) >= _MIN_ENCODE_BYTES and zstd_available():
        cctx, _ = zstd_codec(level)
        z = cctx.compress(payload)
        if len(z) < len(payload):
            payload, zflag = z, 1
    dts = a.dtype.str.encode()
    frame = b"".join((
        FRAME_MAGIC,
        struct.pack("<BBBB", FRAME_VERSION, scheme, zflag, len(dts)),
        dts,
        struct.pack("<B", a.ndim),
        struct.pack(f"<{a.ndim}Q", *a.shape),
        struct.pack("<Q", len(payload)),
        payload,
    ))
    key = seam_key(seam)
    REGISTRY.counter("compress.bytes_in").inc(a.nbytes)
    REGISTRY.counter("compress.bytes_out").inc(len(frame))
    REGISTRY.counter(f"compress.{key}.bytes_in").inc(a.nbytes)
    REGISTRY.counter(f"compress.{key}.bytes_out").inc(len(frame))
    REGISTRY.counter(f"compress.scheme.{_SCHEME_NAMES[scheme]}").inc()
    REGISTRY.counter("compress.encode_us").inc(
        int((time.perf_counter() - t0) * 1e6))
    if a.nbytes:
        REGISTRY.histogram("compress.ratio", _RATIO_BOUNDS).observe(
            a.nbytes / max(len(frame), 1))
    return frame


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------


def _decode_payload(scheme: int, payload: bytes, dtype: np.dtype,
                    n: int, *, seam: str, op: str) -> np.ndarray:
    if scheme == SCHEME_RAW:
        if len(payload) != n * dtype.itemsize:
            raise _corrupt("raw payload length disagrees with frame shape",
                           seam=seam, op=op, declared=n * dtype.itemsize,
                           actual=len(payload))
        return np.frombuffer(payload, dtype=dtype)
    if scheme == SCHEME_BITPACK:
        if dtype != np.bool_:
            raise _corrupt("bitpack frame with non-bool dtype",
                           seam=seam, op=op, dtype=dtype.str)
        if len(payload) * 8 < n:
            raise _corrupt("bitpack payload shorter than frame shape",
                           seam=seam, op=op, size=len(payload), count=n)
        return np.unpackbits(
            np.frombuffer(payload, dtype=np.uint8), count=n).astype(np.bool_)
    if scheme == SCHEME_RLE:
        if len(payload) < 4:
            raise _corrupt("rle frame truncated before run count",
                           seam=seam, op=op, size=len(payload))
        (nruns,) = struct.unpack_from("<I", payload)
        need = 4 + nruns * (4 + dtype.itemsize)
        if len(payload) != need:
            raise _corrupt("rle payload length disagrees with run count",
                           seam=seam, op=op, declared=need,
                           actual=len(payload))
        lengths = np.frombuffer(payload, dtype=np.uint32, count=nruns,
                                offset=4)
        values = np.frombuffer(payload, dtype=dtype, count=nruns,
                               offset=4 + nruns * 4)
        if int(lengths.sum()) != n:
            raise _corrupt("rle run lengths disagree with frame shape",
                           seam=seam, op=op, declared=n,
                           actual=int(lengths.sum()))
        return np.repeat(values, lengths)
    if scheme == SCHEME_DICT:
        if len(payload) < 5:
            raise _corrupt("dict frame truncated before header",
                           seam=seam, op=op, size=len(payload))
        k, bits = struct.unpack_from("<IB", payload)
        if bits not in (1, 2, 4, 8, 16, 32):
            raise _corrupt("dict index width clobbered", seam=seam, op=op,
                           width=bits)
        need = 5 + k * dtype.itemsize + _index_nbytes(n, bits)
        if len(payload) != need:
            raise _corrupt("dict payload length disagrees with header",
                           seam=seam, op=op, declared=need,
                           actual=len(payload))
        values = np.frombuffer(payload, dtype=dtype, count=k, offset=5)
        idx = _unpack_indices(payload[5 + k * dtype.itemsize:], bits, n)
        if n and (k == 0 or int(idx.max()) >= k):
            raise _corrupt("dict index out of range", seam=seam, op=op,
                           cardinality=k)
        return values[idx]
    raise _corrupt("unknown codec scheme", seam=seam, op=op, scheme=scheme)


def decode_array(frame: bytes, *, seam: str = "integrity.spill",
                 op: str = "compress.decode") -> np.ndarray:
    """One codec frame -> the original numpy buffer, bit-identical.

    Runs strictly AFTER the integrity trailer verified (the seam's
    ordering contract), but trusts nothing: magic, version, scheme,
    header arithmetic, payload length, run/dict consistency and the
    decoded element count are all checked, and every inconsistency — the
    corrupt-after-decompress shape a valid seal cannot rule out — raises
    the classified :class:`CorruptDataError` instead of decoding
    garbage."""
    t0 = time.perf_counter()
    try:
        if len(frame) < 8 or frame[:4] != FRAME_MAGIC:
            raise _corrupt("codec frame magic clobbered", seam=seam, op=op,
                           size=len(frame))
        version, scheme, zflag, dlen = struct.unpack_from("<BBBB", frame, 4)
        if version != FRAME_VERSION:
            raise _corrupt("codec frame version unknown", seam=seam, op=op,
                           version=version)
        i = 8
        if len(frame) < i + dlen + 1:
            raise _corrupt("codec frame truncated in dtype", seam=seam,
                           op=op, size=len(frame))
        try:
            dtype = np.dtype(frame[i:i + dlen].decode())
        except (TypeError, UnicodeDecodeError) as exc:
            raise _corrupt(f"codec frame dtype clobbered: {exc}", seam=seam,
                           op=op) from exc
        i += dlen
        ndim = frame[i]
        i += 1
        if ndim > 8 or len(frame) < i + 8 * ndim + 8:
            raise _corrupt("codec frame truncated in shape", seam=seam,
                           op=op, size=len(frame), ndim=ndim)
        shape = struct.unpack_from(f"<{ndim}Q", frame, i)
        i += 8 * ndim
        (plen,) = struct.unpack_from("<Q", frame, i)
        i += 8
        if len(frame) != i + plen:
            raise _corrupt("codec payload length disagrees with frame",
                           seam=seam, op=op, declared=plen,
                           actual=len(frame) - i)
        payload = frame[i:]
        n = 1
        for d in shape:
            n *= int(d)
        if n > (1 << 40):
            raise _corrupt("codec frame shape implausibly large",
                           seam=seam, op=op, count=n)
        if zflag:
            if not zstd_available():
                raise ModuleNotFoundError(
                    "zstandard is required to decode a zstd-compressed "
                    "codec frame")
            _, dctx = zstd_codec(1)
            try:
                payload = dctx.decompress(payload)
            except Exception as exc:
                raise _corrupt(f"zstd stage failed to decompress: {exc}",
                               seam=seam, op=op) from exc
        flat = _decode_payload(scheme, payload, dtype, n, seam=seam, op=op)
        if flat.size != n:  # pragma: no cover - scheme decoders check first
            raise _corrupt("decoded element count disagrees with frame "
                           "shape", seam=seam, op=op, declared=n,
                           actual=flat.size)
        out = flat.reshape(shape)
    except (CorruptDataError, ModuleNotFoundError):
        raise
    except Exception as exc:
        # untrusted bytes: any decoder failure is corruption, classified
        raise _corrupt(f"codec frame failed to decode: "
                       f"{type(exc).__name__}: {exc}", seam=seam,
                       op=op) from exc
    REGISTRY.counter("compress.decode_us").inc(
        int((time.perf_counter() - t0) * 1e6))
    REGISTRY.counter("compress.bytes_decoded").inc(out.nbytes)
    return out


# ---------------------------------------------------------------------------
# snapshot packs — SpillStore / result-cache integration
# ---------------------------------------------------------------------------


def is_codec_pack(obj: Any) -> bool:
    return isinstance(obj, tuple) and len(obj) == 4 and obj[0] == PACK_TAG


def pack_array(arr: Optional[np.ndarray], seam: str):
    """Host buffer -> ``("tpcc", dtype_str, shape, frame)`` snapshot
    pack (None passes through). The tuple mirrors the legacy
    ``("zstd", ...)`` pack layout so checksum folding, corruption
    injection and fingerprint hashing stay codec-agnostic; the frame at
    index 3 is fully self-describing, the tuple's dtype/shape are the
    redundant copies those generic consumers read."""
    if arr is None:
        return None
    a = np.ascontiguousarray(arr)
    return (PACK_TAG, a.dtype.str, a.shape, encode_array(a, seam=seam))


def unpack_array(obj: Any, *, seam: str = "integrity.spill",
                 op: str = "compress.unpack") -> np.ndarray:
    """Snapshot pack -> numpy buffer, with the post-decode shape check
    against the pack's redundant header."""
    out = decode_array(obj[3], seam=seam, op=op)
    if out.dtype.str != obj[1] or tuple(out.shape) != tuple(obj[2]):
        raise _corrupt(
            "decoded buffer disagrees with snapshot pack header",
            seam=seam, op=op, declared=f"{obj[1]}{tuple(obj[2])}",
            actual=f"{out.dtype.str}{tuple(out.shape)}")
    return out


# public name for seam integrations' own post-decode checks (dcn's wire
# buffer header comparison raises through the same classified counter)
corrupt = _corrupt
