"""End-to-end data integrity: checksum trailers on every managed byte
boundary.

The serving stack retries, degrades, checkpoints and replays bytes
through spill entries, DCN wire frames and out-of-core partials — and
until this layer it trusted every byte it read back. A torn spill
write, a flipped bit on the interconnect, or a malformed customer file
produced silently wrong results or an unclassified crash. The
reference's defensive posture is its hardened Thrift footer parsing
(NativeParquetJni.cpp); this module is the TPU runtime's generalization
of that posture to every at-rest and on-wire payload:

- ``seal``/``verify`` wrap a payload in a 16-byte trailer
  (magic + u64 length + masked crc32) so truncation, bit flips and
  length-field lies are all detected before any byte is decoded.
- ``write_payload_file``/``read_payload_file`` are the crash-safe
  binary analogue of utils/atomic_io: tmp file + fsync + ``os.replace``
  + read-back compare, so a crash mid-write can never leave a
  half-written payload a later read trusts.
- ``snaps_checksum``/``verify_snaps`` checksum in-memory host column
  snapshots (SpillStore's packed ``_col_to_host`` tuples) without
  materializing a serialized copy.
- Verification failure raises the classified
  :class:`~spark_rapids_jni_tpu.runtime.resilience.CorruptDataError` —
  refetchable at transport seams (a fresh copy exists on the peer),
  fatal at rest (the bytes are gone; the caller replays or dies with a
  flight record). Malformed *untrusted input* is the separate
  :class:`MalformedInputError` so the server rejects that one query
  cleanly.

The checksum is crc32c-style masking over ``zlib.crc32``: the raw crc
is rotated and offset (the classic LevelDB/crc32c mask) so a payload
that happens to embed its own crc32 — or a trailer fed back through
``checksum`` — never verifies by accident. Zero dependencies beyond
the stdlib; no jax imports (this module runs on the control plane).

Ordering contract with the columnar codec (``runtime/compress.py``):
**compress → seal** on every write, **verify → decompress →
post-decode length/shape check** on every read. The trailer always
covers the stored (compressed) bytes — the seal is the OUTERMOST wrapper
— so verification never spends decode work on bytes that fail the crc,
and a corruption injected after a successful verify (a bad codec frame)
is still a classified ``CorruptDataError`` from the codec's own header
and per-scheme length checks. ARQ refetch at the wire seam re-seals the
pristine compressed blob per resend; nothing is recompressed.

Disabled (``integrity.enabled=false`` or ``SPARK_RAPIDS_TPU_INTEGRITY=0``)
every seam is byte-for-byte today's behavior: no trailer, no
verification, no wire acknowledgements.
"""

from __future__ import annotations

import os
import struct
import tempfile
import zlib
from typing import Any, List, Optional, Sequence

from spark_rapids_jni_tpu import telemetry
from spark_rapids_jni_tpu.runtime.resilience import (
    CorruptDataError,
    MalformedInputError,
)
from spark_rapids_jni_tpu.telemetry import REGISTRY

__all__ = [
    "TRAILER_MAGIC",
    "TRAILER_SIZE",
    "checksum",
    "enabled",
    "read_payload_file",
    "reject_malformed",
    "seal",
    "snaps_checksum",
    "verify",
    "verify_snaps",
    "write_payload_file",
]

# Trailer layout: 4-byte magic + u64 payload length + u32 masked crc.
TRAILER_MAGIC = b"TPIC"
_TRAILER_FMT = "<4sQI"
TRAILER_SIZE = struct.calcsize(_TRAILER_FMT)

# crc32c-style mask constant (LevelDB's): rotate the raw crc and add a
# fixed offset so checksum(x) never equals zlib.crc32(x) and nested
# checksums of checksum-bearing blobs don't collide with the payload's.
_MASK_DELTA = 0xA282EAD8
_ENV = "SPARK_RAPIDS_TPU_INTEGRITY"


def enabled() -> bool:
    """Is integrity verification on? The short env var
    SPARK_RAPIDS_TPU_INTEGRITY is checked first (same precedence pattern
    as SPARK_RAPIDS_TPU_DISPATCH_CACHE), then the ``integrity.enabled``
    option."""
    env = os.environ.get(_ENV)
    if env is not None:
        return env.strip().lower() in ("1", "true", "yes", "on")
    from spark_rapids_jni_tpu.utils.config import get_option

    return bool(get_option("integrity.enabled"))


def checksum(data: Any) -> int:
    """Masked crc32 of ``data`` (anything supporting the buffer
    protocol). Always available regardless of :func:`enabled` — callers
    gate, the primitive doesn't."""
    crc = zlib.crc32(data) & 0xFFFFFFFF
    return (((crc >> 15) | (crc << 17)) + _MASK_DELTA) & 0xFFFFFFFF


def seal(payload: bytes) -> bytes:
    """Append the length+checksum trailer to ``payload``."""
    return payload + struct.pack(
        _TRAILER_FMT, TRAILER_MAGIC, len(payload), checksum(payload)
    )


def _mismatch(reason: str, *, seam: str, op: str, **context: Any) -> CorruptDataError:
    REGISTRY.counter("integrity.mismatch").inc()
    REGISTRY.counter(f"integrity.mismatch.{seam}").inc()
    telemetry.record_integrity(op, "mismatch", seam=seam, reason=reason, **context)
    return CorruptDataError(reason, seam=seam, op=op, **context)


def verify(blob: bytes, *, seam: str, op: str = "verify", **context: Any) -> bytes:
    """Strip and check the trailer of a sealed ``blob``; return the
    payload. Raises the classified :class:`CorruptDataError` (with the
    seam and caller context embedded) on truncation, magic clobber,
    length-field lies, or checksum mismatch — before a single payload
    byte reaches a decoder."""
    n = len(blob)
    if n < TRAILER_SIZE:
        raise _mismatch(
            "payload shorter than integrity trailer", seam=seam, op=op, size=n, **context
        )
    magic, length, crc = struct.unpack(_TRAILER_FMT, blob[n - TRAILER_SIZE :])
    if magic != TRAILER_MAGIC:
        raise _mismatch(
            "integrity trailer magic clobbered", seam=seam, op=op, size=n, **context
        )
    if length != n - TRAILER_SIZE:
        raise _mismatch(
            "payload length disagrees with trailer",
            seam=seam,
            op=op,
            declared=length,
            actual=n - TRAILER_SIZE,
            **context,
        )
    payload = blob[: n - TRAILER_SIZE]
    actual = checksum(payload)
    if actual != crc:
        raise _mismatch(
            "payload checksum mismatch",
            seam=seam,
            op=op,
            declared=crc,
            actual=actual,
            **context,
        )
    REGISTRY.counter("integrity.bytes_verified").inc(len(payload))
    REGISTRY.counter(f"integrity.verified.{seam}").inc()
    return payload


def snaps_checksum(snaps: Sequence[Any]) -> int:
    """Checksum a list of packed host column snapshots (SpillStore's
    ``_col_to_host`` tuples: (dtype, data, validity, chars, children),
    where each buffer is a contiguous numpy array, a
    ("zstd", dtype, shape, blob) pack, or None). Folds every buffer into
    one running crc without serializing the snapshot."""
    crc = 0

    def _fold(buf: Any) -> None:
        nonlocal crc
        if buf is None:
            return
        if isinstance(buf, tuple):  # ("zstd", dtype_str, shape, blob)
            crc = zlib.crc32(buf[3], crc)
            return
        crc = zlib.crc32(memoryview(buf).cast("B"), crc)

    def _walk(snap: Any) -> None:
        _dtype, data, validity, chars, children = snap
        _fold(data)
        _fold(validity)
        _fold(chars)
        for child in children or ():
            _walk(child)

    for snap in snaps:
        _walk(snap)
    crc &= 0xFFFFFFFF
    return (((crc >> 15) | (crc << 17)) + _MASK_DELTA) & 0xFFFFFFFF


def verify_snaps(
    snaps: Sequence[Any], expected: int, *, seam: str, op: str = "verify_snaps", **context: Any
) -> None:
    """Check an in-memory snapshot list against the checksum taken when
    it was spilled; raise classified CorruptDataError on drift."""
    nbytes = 0
    for snap in snaps:
        for buf in (snap[1], snap[2], snap[3]):
            if isinstance(buf, tuple):
                nbytes += len(buf[3])
            elif buf is not None:
                nbytes += memoryview(buf).nbytes
    actual = snaps_checksum(snaps)
    if actual != expected:
        raise _mismatch(
            "host snapshot checksum mismatch",
            seam=seam,
            op=op,
            declared=expected,
            actual=actual,
            **context,
        )
    REGISTRY.counter("integrity.bytes_verified").inc(nbytes)
    REGISTRY.counter(f"integrity.verified.{seam}").inc()


def write_payload_file(path: str, blob: bytes) -> int:
    """Crash-safe binary payload write: tmp file in the same directory +
    flush + fsync + atomic ``os.replace`` + directory fsync, then a
    read-back compare of length and checksum against exactly the bytes
    handed in. A crash at any point leaves either the old file or the
    new one — never a torn hybrid — and a write the storage silently
    dropped or mangled is detected *now*, not at unspill time.

    ``blob`` is written verbatim (callers seal before calling when
    integrity is enabled), so the write-verify holds even when a fault
    script injected latent corruption upstream: the check is "did the
    bytes I was given land on disk", not "are the bytes valid"."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".integrity-", suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        dfd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # platform without directory fsync
    with open(path, "rb") as fh:
        landed = fh.read()
    if len(landed) != len(blob) or zlib.crc32(landed) != zlib.crc32(blob):
        raise _mismatch(
            "write-verify failed: bytes on disk differ from bytes written",
            seam="integrity.spill",
            op="write_payload_file",
            path=path,
            written=len(blob),
            landed=len(landed),
        )
    return len(blob)


def read_payload_file(
    path: str, *, seam: str, sealed: bool, op: str = "read_payload_file", **context: Any
) -> bytes:
    """Read a managed payload file back; when it was written sealed,
    verify the trailer before returning a single payload byte."""
    with open(path, "rb") as fh:
        blob = fh.read()
    if not sealed:
        return blob
    return verify(blob, seam=seam, op=op, path=path, **context)


def reject_malformed(
    op: str,
    message: str,
    *,
    exc_type: Optional[type] = None,
    **context: Any,
) -> MalformedInputError:
    """Count + record one malformed-input rejection and return the
    classified exception for the caller to raise
    (``raise integrity.reject_malformed(...)``). ``exc_type`` lets file
    readers substitute their NativeError-compatible subclass."""
    REGISTRY.counter("integrity.malformed").inc()
    REGISTRY.counter(f"integrity.malformed.{op}").inc()
    telemetry.record_integrity(op, "malformed", seam="integrity.ingest", reason=message, **context)
    cls = exc_type or MalformedInputError
    return cls(message, op=op, **context)
