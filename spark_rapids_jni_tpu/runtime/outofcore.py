"""Out-of-core chunked query execution under a device-memory budget.

The driver's north-star metric is TPC-DS SF1000 (BASELINE.json): at that
scale a fact table does not fit one chip's HBM, and the reference covers
it with cuDF's chunked Parquet reader (vendored capability,
/root/reference/build-libcudf.xml:34-60 + BASELINE.json north star). The
TPU-native equivalent composes pieces that already exist:

* ``ParquetChunkedReader`` / ``OrcChunkedReader`` — row-group/stripe-
  granularity chunks under an on-disk byte budget;
* ``MemoryLimiter`` — the RMM-role accounting that turns "would OOM" into
  a fail-loud reservation contract;
* ``SpillStore`` — LRU device->host spill for intermediates that
  outlive their chunk; spilled snapshots and on-disk checkpoints ride
  the ``runtime/compress.py`` columnar codec (dictionary/RLE/bit-pack,
  compressed before the integrity seal), so checkpoint bytes shrink
  with no changes in this module;
* mergeable partial aggregates — the distributed plans already reduce
  partials after the shuffle (``q1_distributed_step``); out-of-core runs
  the same partial->merge shape over TIME (chunk sequence) instead of
  SPACE (device mesh).

The executor here is deliberately host-driven: chunk iteration, spill
decisions and compaction happen between jitted regions (XLA needs static
shapes inside; chunk boundaries are where dynamic sizes are free).
"""

from __future__ import annotations

from typing import Callable, Iterable, NamedTuple

from spark_rapids_jni_tpu import telemetry
from spark_rapids_jni_tpu.columnar import Table
from spark_rapids_jni_tpu.runtime import faults, resilience
from spark_rapids_jni_tpu.runtime.memory import (
    MemoryLimiter,
    SpillStore,
    _table_nbytes,
)
from spark_rapids_jni_tpu.telemetry import spans
from spark_rapids_jni_tpu.utils.log import get_logger
from spark_rapids_jni_tpu.utils.tracing import func_range, trace_range

_log = get_logger(__name__)


def prefetch_chunks(chunks, depth: int = 1,
                    limiter: MemoryLimiter | None = None):
    """Overlap the NEXT chunk's storage faulting + host decode + device
    staging with the CURRENT chunk's compute — the async-staging role
    of the reference's cuFile/GDS path (ref CMakeLists.txt:200-222;
    VERDICT r4 weak #6: the mmap route was synchronous single-threaded).

    A producer thread drains the inner iterator ``depth`` chunks ahead
    (the ctypes reader releases the GIL during native decode, so decode
    genuinely overlaps host-side Python and device dispatch). When a
    ``limiter`` is given, each chunk is reserved AT PREFETCH TIME in
    the producer thread and the caller must release it after use.
    Concurrent-residency window: up to ``depth + 2`` chunks are
    reserved at once — ``depth`` queued, one in the producer's hand
    (reserved before its put can block on a full queue), one in the
    consumer's — so size the budget for ``depth + 2`` chunks or pass
    depth=0. Iterator exceptions (including MemoryLimitExceeded from
    the producer's reserve) re-raise at the consumer."""
    import queue
    import threading

    if depth <= 0:
        yield from chunks
        return
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    cancel = threading.Event()

    def producer():
        try:
            for chunk in chunks:
                if limiter is not None:
                    limiter.reserve(_table_nbytes(chunk))
                placed = False
                while not cancel.is_set():
                    try:
                        q.put(("ok", chunk), timeout=0.1)
                        placed = True
                        break
                    except queue.Full:
                        continue
                if not placed:
                    # cancelled before the put landed: nobody will ever
                    # release this chunk — undo its reservation here.
                    # (A chunk that DID land is the drain's to release;
                    # checking cancel alone double-released it.)
                    if limiter is not None:
                        limiter.release(_table_nbytes(chunk))
                    return
        except BaseException as exc:  # noqa: BLE001 — re-raised at consumer
            _put_cancellable(("err", exc))
            return
        _put_cancellable(("end", None))

    def _put_cancellable(item):
        # never block forever against a consumer that already left (a
        # blocking put here would deadlock its join in the finally)
        while not cancel.is_set():
            try:
                q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    th = threading.Thread(target=producer, daemon=True)
    th.start()
    try:
        while True:
            kind, payload = q.get()
            if kind == "err":
                raise payload
            if kind == "end":
                break
            yield payload
    finally:
        # error or early exit: stop the producer, then release anything
        # it reserved that will never be consumed (no phantom usage in a
        # caller-injected limiter — the merge-window contract)
        cancel.set()
        th.join()
        while True:
            try:
                kind, payload = q.get_nowait()
            except queue.Empty:
                break
            if kind == "ok" and limiter is not None:
                limiter.release(_table_nbytes(payload))


class OutOfCoreResult(NamedTuple):
    table: Table
    chunks: int           # chunks streamed
    peak_bytes: int       # limiter high-water mark over the whole run
    spill_stats: dict     # SpillStore counters (spilled/restored/...)


@func_range("run_chunked_aggregate", record=True)
def run_chunked_aggregate(
    chunks: Iterable[Table],
    partial_fn: Callable[[Table], Table],
    merge_fn: Callable[[Table], Table],
    *,
    limiter: MemoryLimiter,
    spill: SpillStore | None = None,
    spill_budget_bytes: int | None = None,
    prefetch_depth: int = 0,
    pipeline: bool | None = None,
    cancel_token=None,
) -> OutOfCoreResult:
    """Stream an aggregation over table chunks under a memory budget.

    Contract: with ``prefetch_depth == 0`` at no point are two chunks
    resident together — each chunk is reserved against ``limiter`` while
    its partial is computed and released before the next chunk is
    faulted in. With ``prefetch_depth > 0`` up to ``prefetch_depth + 2``
    chunks are resident (the overlap window; see ``prefetch_chunks``)
    and the budget must cover them. Either way, exceeding the budget
    raises ``MemoryLimitExceeded`` (fail loud, never silently
    over-commit — the narrowing_overflow posture). Partials go
    through the SpillStore: they stay on device while its budget allows
    and LRU-spill to (compressed) host memory otherwise, so the merge
    input never holds un-accounted device bytes either.

    ``pipeline`` selects the async multi-stage executor
    (runtime/pipeline.py): None follows the ``pipeline.enabled`` option,
    True/False force it. When pipelined, ``chunks`` may ALSO be a
    chunked reader exposing ``chunk_sources()`` (parquet/orc) or an
    iterable of zero-arg decode thunks; host decode then runs in a small
    thread pool and each chunk's exact device bytes are reserved at the
    staging boundary BEFORE its host->device copy, so backpressure
    blocks (degrading toward serial) instead of over-committing. Results
    are bit-identical to the serial path and chunk-order-deterministic
    either way. ``prefetch_depth`` doubles as the pipeline queue depth
    when > 0; otherwise ``pipeline.prefetch_depth`` applies.

    ``partial_fn`` maps one chunk to a small table of mergeable partial
    rows (sums/counts, NOT averages); ``merge_fn`` maps the concatenation
    of all partials to the final table. The partial->merge algebra is
    identical to the distributed two-phase aggregation
    (models/tpch.py q1_distributed_step), which is what makes the same
    query plan work over chunks, devices, or both.

    ``cancel_token`` (a ``resilience.CancelToken``) is checked at every
    chunk boundary, before each partial restore and before the merge —
    plus inside the pipeline decode pool when pipelined. Cancellation or
    deadline expiry raises ``QueryCancelled`` through the same release
    paths as any failure, leaving zero reservations behind; it is never
    retried or resumed (deliberate stops are not transient faults).
    """
    from spark_rapids_jni_tpu.ops.table_ops import concatenate
    from spark_rapids_jni_tpu.runtime import pipeline as pl

    use_pipeline = pl.pipeline_enabled() if pipeline is None \
        else bool(pipeline)
    own_spill = spill is None
    if own_spill:
        spill = SpillStore(
            spill_budget_bytes if spill_budget_bytes is not None
            else limiter.budget)
    handles: list[int] = []
    nchunks = 0
    pol = resilience.policy()
    # pipeline mode: decode in a pool, exact-bytes admission, ordered
    # delivery; prefetch mode: single producer thread, depth+2 window;
    # serial mode: one chunk resident at a time. In the first two the
    # producer owns each chunk's reservation and this loop releases it.
    producer_owns = use_pipeline or prefetch_depth > 0
    sources = None
    if use_pipeline:
        sources = chunks.chunk_sources() \
            if hasattr(chunks, "chunk_sources") else chunks
        if pol.enabled:
            # checkpoint/resume needs a re-enterable source list: chunks
            # 0..nchunks-1 are checkpointed as spill handles (in-order
            # delivery guarantees them complete), so after a transient
            # mid-query fault a fresh pipeline replays sources[nchunks:]
            # only. Materializing is cheap for decode thunks (the
            # pipelined norm) — it holds closures, not data.
            sources = list(sources)

    def _make_stream():
        if use_pipeline:
            src = sources[nchunks:] if pol.enabled else sources
            return pl.pipeline_chunks(
                src, limiter=limiter,
                depth=prefetch_depth if prefetch_depth > 0 else None,
                cancel_token=cancel_token)
        if prefetch_depth > 0:
            return prefetch_chunks(chunks, prefetch_depth, limiter)
        return chunks

    def _process(chunk, seq, nb):
        """One chunk's partial: reserve (serial mode), compute, checkpoint
        into the spill store. Self-contained so the replay_chunk ladder
        rung can re-run it with no reservation carried between attempts."""
        if not producer_owns:
            limiter.reserve(nb)
        try:
            with spans.child("outofcore.chunk", seq=seq, nbytes=nb):
                faults.fire("outofcore.chunk", seq, nbytes=nb)
                if use_pipeline:
                    # stage 4 of the pipeline: device compute — faults
                    # injectable, span-traced like the producer stages
                    pl._maybe_fault("compute", seq)
                    with trace_range("pipeline.compute"):
                        partial = partial_fn(chunk)
                else:
                    partial = partial_fn(chunk)
                # checkpoint-tagged: a later verification mismatch on
                # this entry classifies (and recovers) as a corrupt
                # CHECKPOINT — discard + replay — not a corrupt spill
                return spill.put(partial,
                                 integrity_seam="integrity.checkpoint")
        finally:
            if not producer_owns:
                limiter.release(nb)

    run_attempt = 1
    while True:
        stream = _make_stream()
        resumed = False
        try:
            for chunk in stream:
                nb = _table_nbytes(chunk)
                try:
                    if cancel_token is not None:
                        # chunk-boundary checkpoint: the raise unwinds
                        # through this try's finally (releasing the
                        # producer-owned reservation) and the stream's
                        # close below — zero leaked budget
                        cancel_token.check("outofcore.chunk")
                    if pol.enabled:
                        handles.append(resilience.retrying(
                            "run_chunked_aggregate",
                            lambda: _process(chunk, nchunks, nb),
                            seam="outofcore.chunk", rung="replay_chunk",
                            pol=pol, chunk=nchunks))
                    else:
                        handles.append(_process(chunk, nchunks, nb))
                finally:
                    if producer_owns:
                        limiter.release(nb)
                del chunk
                nchunks += 1
        except BaseException as exc:
            # chunk-level checkpoint/resume: a transient fault inside the
            # pipelined stream (decode/staging/transfer workers) tears the
            # stream down with every reservation released; chunks
            # 0..nchunks-1 are already checkpointed, so replay restarts a
            # fresh pipeline at the failed chunk only.
            if not (use_pipeline and pol.enabled
                    and resilience.is_transient(exc)):
                raise
            if run_attempt >= pol.max_attempts:
                telemetry.record_resilience(
                    "run_chunked_aggregate", "fatal", seam="outofcore.chunk",
                    attempt=run_attempt, rung="replay_chunk", chunk=nchunks)
                raise resilience.FatalExecutionError(
                    f"run_chunked_aggregate: resume retries exhausted after "
                    f"{run_attempt} attempts at chunk {nchunks}: {exc}",
                    chunk=nchunks, attempts=run_attempt) from exc
            telemetry.record_resilience(
                "run_chunked_aggregate", "retry", seam="outofcore.chunk",
                attempt=run_attempt, rung="replay_chunk", chunk=nchunks)
            run_attempt += 1
            resumed = True
        finally:
            # a partial_fn failure must stop the producer and release its
            # in-flight reservations (the no-phantom-usage contract) — the
            # generator's own finally does both on close
            if producer_owns:
                stream.close()
        if not resumed:
            break
    if run_attempt > 1:
        telemetry.record_resilience(
            "run_chunked_aggregate", "recovered", seam="outofcore.chunk",
            attempt=run_attempt, rung="replay_chunk", chunk=nchunks)
    if not handles:
        raise ValueError("no chunks: empty input stream")
    stream_stats = spill.stats()
    _log.info("out-of-core: %d chunks streamed, spill=%s",
              nchunks, stream_stats)
    if stream_stats["spills"]:
        # per-table byte movement is recorded by SpillStore itself; this
        # marks the RUN as having left the all-device residency path
        telemetry.record_fallback(
            "run_chunked_aggregate",
            "partials exceeded the device spill budget during chunk "
            "streaming: LRU-spilled to host",
            rows=nchunks, spills=stream_stats["spills"],
            spilled_bytes=stream_stats["spilled_bytes"])
    # merge window: restoring a partial stages it back to device, so every
    # restored partial is reserved before the next one comes up — a partial
    # set that alone exceeds the budget raises instead of over-committing.
    # During the concatenate both the partials and the merged table are
    # resident (reserved together); the partials release the moment the
    # concat result exists.
    def _replay_chunk(idx: int):
        """Recovery for a corrupt checkpoint: the spilled partial failed
        integrity verification, so resuming from it would resume from
        garbage — recompute chunk ``idx``'s partial from its
        materialized source instead (in-order delivery guarantees
        ``handles[idx] <-> sources[idx]``). Returns ``(partial, nbytes)``
        with the partial's bytes reserved — the same ownership contract
        ``get_reserved`` hands the restore loop."""
        src = sources[idx]
        obj = src() if callable(src) else src
        staged = hasattr(obj, "stage")
        nb_c = obj.nbytes if staged else _table_nbytes(obj)
        limiter.reserve(nb_c)
        try:
            chunk_tbl = obj.stage() if staged else obj
            partial = partial_fn(chunk_tbl)
            nb_p = _table_nbytes(partial)
            limiter.reserve(nb_p)
            return partial, nb_p
        finally:
            limiter.release(nb_c)

    partials: list[Table] = []
    partial_bytes = 0
    try:
        for idx, h in enumerate(handles):
            if cancel_token is not None:
                cancel_token.check("outofcore.restore")
            # reserve BEFORE staging: a partial set that exceeds the
            # budget must raise before its bytes are device-resident
            # (get_reserved orders the reservation ahead of the
            # host->device copy — the pipelined-unspill contract).
            # get_reserved leaves no reservation behind on failure, so a
            # transient unspill fault retries with zero carried state.
            try:
                if pol.enabled:
                    tbl, nb_p = resilience.retrying(
                        "run_chunked_aggregate",
                        lambda: spill.get_reserved(h, limiter),
                        seam="spill.unspill", rung="replay_chunk",
                        pol=pol, handle=h)
                else:
                    tbl, nb_p = spill.get_reserved(h, limiter)
            except resilience.CorruptDataError:
                # a corrupt checkpoint is deterministic (not retried
                # above: CorruptDataError is non-transient at rest) —
                # discard the partial and replay the chunk when the
                # source list survives; serial/generator streams are
                # consumed, so there the classified error propagates
                if sources is None:
                    raise
                telemetry.record_integrity(
                    "run_chunked_aggregate", "replay",
                    seam="integrity.checkpoint", chunk=idx)
                spill.drop(h)
                tbl, nb_p = _replay_chunk(idx)
                telemetry.record_integrity(
                    "run_chunked_aggregate", "recovered",
                    seam="integrity.checkpoint", chunk=idx)
            partial_bytes += nb_p
            partials.append(tbl)
            spill.drop(h)
        if len(partials) > 1:
            merged_in = concatenate(partials)
            nb = _table_nbytes(merged_in)
            limiter.reserve(nb)
            del partials
            limiter.release(partial_bytes)
            partial_bytes = 0
        else:
            merged_in = partials[0]
            nb = partial_bytes
            partial_bytes = 0
    except BaseException:
        # the limiter may be caller-injected and reused: leave no
        # phantom usage behind a raised MemoryLimitExceeded
        limiter.release(partial_bytes)
        raise
    def _merge():
        if cancel_token is not None:
            cancel_token.check("outofcore.merge")
        with spans.child("outofcore.merge", nchunks=nchunks):
            faults.fire("outofcore.merge", nchunks)
            if use_pipeline:
                pl._maybe_fault("merge", nchunks)
                with trace_range("pipeline.merge"):
                    return merge_fn(merged_in)
            return merge_fn(merged_in)

    try:
        if pol.enabled:
            # the merged-input reservation is held across merge retries
            # and released exactly once below — replaying the merge
            # neither re-reserves nor leaks
            out = resilience.retrying(
                "run_chunked_aggregate", _merge,
                seam="outofcore.merge", rung="replay_chunk", pol=pol)
        else:
            out = _merge()
    finally:
        limiter.release(nb)
    return OutOfCoreResult(out, nchunks, limiter.peak, spill.stats())
