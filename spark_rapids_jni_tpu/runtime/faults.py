"""Global fault-injection registry: named seams across the whole runtime.

Generalizes the pipeline-only ``runtime/pipeline.py:inject_fault`` hook into
one registry every runtime boundary fires through. A *seam* is a named point
where a real deployment can fail — device compile/execute, memory
reservation, spill IO, chunk boundaries, network transport, fused-region
dispatch. Production code calls ``fire(seam, seq)`` at each seam; with no
injector installed that is one module-global ``is None`` check (the fault-free
overhead budget is ≈0). Tests install an injector with ``inject(...)`` and
schedule deterministic (:class:`FaultSpec`) or seeded-random
(:class:`FaultScript`) fault scripts at any seam.

Zero third-party deps and no jax import (same import-hygiene contract as
telemetry): this module must be loadable before any backend initializes.
"""

from __future__ import annotations

import contextlib
import random
import threading
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

from spark_rapids_jni_tpu.telemetry import REGISTRY

__all__ = [
    "SEAMS",
    "FaultSpec",
    "CorruptionSpec",
    "FaultScript",
    "fire",
    "fire_corrupt",
    "inject",
    "active_injector",
]

# Every instrumented boundary, by layer. fire() rejects unknown seam names so
# a typo in production code or a test script fails loudly instead of silently
# never firing. Pipeline stages keep their legacy stage names under a
# "pipeline." prefix so pipeline.inject_fault can stay a thin alias.
SEAMS: Tuple[str, ...] = (
    # dispatch (runtime/dispatch.py)
    "dispatch.compile",
    "dispatch.execute",
    # memory layer (runtime/memory.py)
    "memory.reserve",
    "spill.spill",
    "spill.unspill",
    # out-of-core chunk boundaries (runtime/outofcore.py)
    "outofcore.chunk",
    "outofcore.merge",
    # pipelined executor stages (runtime/pipeline.py)
    "pipeline.decode",
    "pipeline.staging",
    "pipeline.transfer",
    "pipeline.compute",
    "pipeline.merge",
    # distributed transport (parallel/distributed.py, parallel/dcn.py)
    "shuffle.transport",
    "dcn.transport",
    # whole-stage fusion region dispatch (runtime/fusion.py)
    "fusion.region",
    # multi-query serving runtime (runtime/server.py)
    "server.admit",
    "server.execute",
    # cooperative cancellation checkpoints (runtime/server.py, degrade.py)
    "server.cancel",
    # graceful-degradation ladder steps (runtime/degrade.py)
    "degrade.step",
    # watermark crossings on the memory limiter (runtime/memory.py)
    "memory.pressure",
    # integrity verification boundaries (runtime/integrity.py call sites):
    # payload-*corruption* seams — fired through fire_corrupt(), which
    # mutates managed bytes in flight instead of raising, so the chaos
    # suite can drill detection -> classified recovery end to end.
    "integrity.spill",
    "integrity.wire",
    "integrity.checkpoint",
    "integrity.ingest",
    # general-cardinality exchange (runtime/exchange.py): exchange.pack is the
    # device-side escalating pack attempt (a raise here drills the overflow
    # ladder), exchange.wire corrupts a sealed flight frame in transit the
    # same way integrity.wire corrupts a sealed table frame — detection at
    # recv_framed classifies it and the ARQ loop refetches the flight.
    "exchange.pack",
    "exchange.wire",
    # result/subplan cache payloads (runtime/resultcache.py): cache entries
    # ride the SpillStore tiers, so this seam corrupts a cached payload the
    # same way integrity.spill corrupts a live query's spilled working set.
    "integrity.cache",
    # serving fleet (runtime/fleet.py): supervisor -> replica dispatch of a
    # framed submit, the liveness ping loop, and worker exit-status reaping.
    # An injected raise at fleet.dispatch is a failed send (the replica is
    # treated as dead and the query fails over); at fleet.heartbeat it is a
    # missed liveness deadline; at fleet.worker_exit it drills the reap path
    # (rule 18: must route through the resilience taxonomy).
    "fleet.dispatch",
    "fleet.heartbeat",
    "fleet.worker_exit",
)

_SEAM_SET = frozenset(SEAMS)

# The installed injector: a callable (seam, seq, ctx) -> None that raises to
# inject a fault. None (the common case) short-circuits fire() to a single
# attribute load + comparison.
_active: Optional[Callable[[str, int, dict], None]] = None
_lock = threading.Lock()


def active_injector() -> Optional[Callable[[str, int, dict], None]]:
    """The currently installed injector, or None (introspection/tests)."""
    return _active


def fire(seam: str, seq: int = 0, **ctx: Any) -> None:
    """Production seam hook: no-op unless a test installed an injector.

    ``seq`` is the seam-local sequence number (chunk index, attempt number,
    message ordinal); ``ctx`` carries whatever the seam knows (rows, nbytes,
    op). When the injector raises, the raise is counted under
    ``faults.injected`` / ``faults.injected.<seam>`` and propagates to the
    seam's recovery path exactly like a real failure would.
    """
    hook = _active
    if hook is None:
        return
    if seam not in _SEAM_SET:
        raise ValueError(f"unknown fault seam {seam!r}; registered: {sorted(_SEAM_SET)}")
    try:
        hook(seam, int(seq), ctx)
    except BaseException:
        REGISTRY.counter("faults.injected").inc()
        REGISTRY.counter(f"faults.injected.{seam}").inc()
        raise


def fire_corrupt(seam: str, seq: int, payload: bytes, **ctx: Any) -> bytes:
    """Corruption seam hook: give the installed injector a chance to
    mutate a managed payload (spill blob, wire frame, checkpoint bytes,
    ingested file) before it is written/sent/decoded.

    With no injector installed this is the same single ``is None`` check
    as :func:`fire`. An injector participates by exposing a
    ``corrupt_payload(seam, seq, payload, ctx) -> Optional[bytes]``
    method (:class:`FaultScript` does, when built with ``corruptions``);
    returning None or the payload unchanged leaves the bytes alone.
    Mutations are counted under ``faults.corrupted`` /
    ``faults.corrupted.<seam>`` — corruption is *injected silently* (no
    raise); detection is the integrity layer's job, which is exactly
    what the chaos suite is drilling.
    """
    hook = _active
    if hook is None:
        return payload
    if seam not in _SEAM_SET:
        raise ValueError(f"unknown fault seam {seam!r}; registered: {sorted(_SEAM_SET)}")
    corrupt = getattr(hook, "corrupt_payload", None)
    if corrupt is None:
        return payload
    mutated = corrupt(seam, int(seq), payload, ctx)
    if mutated is None or mutated is payload:
        return payload
    REGISTRY.counter("faults.corrupted").inc()
    REGISTRY.counter(f"faults.corrupted.{seam}").inc()
    return mutated


@contextlib.contextmanager
def inject(injector: Callable[[str, int, dict], None]) -> Iterator[None]:
    """Install ``injector`` for the duration of the with-block.

    The injector is called at every seam firing as ``injector(seam, seq,
    ctx)``; raising injects the fault. Nested installs stack (inner wins,
    outer restored on exit). :class:`FaultSpec` lists and
    :class:`FaultScript` objects are callable and slot in directly.
    """
    global _active
    with _lock:
        prev = _active
        _active = injector
    try:
        yield
    finally:
        with _lock:
            _active = prev


def _raise_fault(exc) -> None:
    """``exc`` may be an exception class, a zero-arg factory, or a ready
    instance. Classes get a standard message (the taxonomy requires one)."""
    if isinstance(exc, BaseException):
        raise exc
    if isinstance(exc, type) and issubclass(exc, BaseException):
        raise exc("injected fault")
    raise exc()


class FaultSpec:
    """One deterministic scheduled fault: raise ``exc`` at a seam firing.

    ``exc`` is an exception class (or zero-arg factory) or a pre-built
    exception instance. ``seq=None`` matches any sequence number; ``times``
    bounds how often the spec fires (default once — the transient-fault
    shape).
    """

    def __init__(
        self,
        seam: str,
        exc,
        *,
        seq: Optional[int] = None,
        times: int = 1,
    ) -> None:
        if seam not in _SEAM_SET:
            raise ValueError(f"unknown fault seam {seam!r}; registered: {sorted(_SEAM_SET)}")
        self.seam = seam
        self.exc = exc
        self.seq = seq
        self.times = int(times)
        self.fired = 0

    def matches(self, seam: str, seq: int) -> bool:
        if seam != self.seam or self.fired >= self.times:
            return False
        return self.seq is None or int(seq) == self.seq

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"FaultSpec(seam={self.seam!r}, seq={self.seq}, "
            f"times={self.times}, fired={self.fired})"
        )


class CorruptionSpec:
    """One scheduled payload corruption at an ``integrity.*`` seam.

    ``mode`` picks the mutation:

    - ``"flip"`` — XOR one random bit of one random byte (link/bitrot
      shape; length-preserving, so it also works on in-memory spill
      snapshots where live arrays cannot shrink),
    - ``"truncate"`` — cut the payload short (torn-write shape),
    - ``"trailer"`` — clobber the final 16 bytes, i.e. the integrity
      trailer itself (metadata-corruption shape).

    The mutation is derived from ``(seed, seam, seq, fired)`` — never a
    shared generator — so a corpus of corruptions is reproducible
    case-by-case regardless of thread interleaving, and every mutation
    is guaranteed to actually change the bytes (XOR with a nonzero
    mask / a strictly shorter slice). ``seq=None`` matches any sequence
    number; ``times`` bounds firings (default once).
    """

    MODES = ("flip", "truncate", "trailer")

    def __init__(
        self,
        seam: str,
        mode: str = "flip",
        *,
        seq: Optional[int] = None,
        times: int = 1,
        seed: int = 0,
    ) -> None:
        if seam not in _SEAM_SET:
            raise ValueError(f"unknown fault seam {seam!r}; registered: {sorted(_SEAM_SET)}")
        if mode not in self.MODES:
            raise ValueError(f"unknown corruption mode {mode!r}; one of {self.MODES}")
        self.seam = seam
        self.mode = mode
        self.seq = seq
        self.times = int(times)
        self.seed = int(seed)
        self.fired = 0

    def matches(self, seam: str, seq: int) -> bool:
        if seam != self.seam or self.fired >= self.times:
            return False
        return self.seq is None or int(seq) == self.seq

    def apply(self, payload: bytes, seq: int) -> bytes:
        rng = random.Random(f"{self.seed}|{self.seam}|{int(seq)}|{self.fired}")
        if not payload:
            return payload
        buf = bytearray(payload)
        if self.mode == "flip":
            buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
        elif self.mode == "truncate":
            return bytes(buf[: rng.randrange(len(buf))])
        else:  # trailer
            for i in range(max(0, len(buf) - 16), len(buf)):
                buf[i] ^= rng.randrange(1, 256)
        return bytes(buf)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"CorruptionSpec(seam={self.seam!r}, mode={self.mode!r}, "
            f"seq={self.seq}, times={self.times}, fired={self.fired})"
        )


class FaultScript:
    """A schedule of faults: deterministic specs and/or seeded-random chaos.

    Deterministic: pass ``specs`` (a list of :class:`FaultSpec`); each fires
    at its matching seam/seq up to its ``times`` budget.

    Seeded-random: pass ``seed`` + ``rate`` (+ optionally ``seams`` to
    restrict); each firing of an eligible seam then injects with probability
    ``rate``. The random decision is derived from ``(seed, seam, seq, nth)``
    — NOT from a shared generator — so it is reproducible regardless of how
    pipeline/producer threads interleave seam firings.

    Corruption: pass ``corruptions`` (a list of :class:`CorruptionSpec`);
    each silently mutates the payload at its matching ``integrity.*``
    seam when production code routes bytes through
    :func:`fire_corrupt` — the detection/recovery drill for the
    integrity layer.

    ``max_faults`` bounds total injections across the whole script (default
    unlimited); ``fired`` records ``(seam, seq)`` history for assertions.
    The script object is the injector: ``with faults.inject(script): ...``.
    """

    def __init__(
        self,
        specs: Optional[Sequence[FaultSpec]] = None,
        *,
        corruptions: Optional[Sequence[CorruptionSpec]] = None,
        seed: Optional[int] = None,
        rate: float = 0.0,
        seams: Optional[Sequence[str]] = None,
        exc=RuntimeError,
        max_faults: Optional[int] = None,
    ) -> None:
        self.specs: List[FaultSpec] = list(specs or [])
        self.corruptions: List[CorruptionSpec] = list(corruptions or [])
        if seams is not None:
            unknown = set(seams) - _SEAM_SET
            if unknown:
                raise ValueError(f"unknown fault seams {sorted(unknown)}")
        self.seed = seed
        self.rate = float(rate)
        self.seams = frozenset(seams) if seams is not None else None
        self.exc = exc
        self.max_faults = max_faults
        self.fired: List[Tuple[str, int]] = []
        self._counts: dict = {}
        self._lock = threading.Lock()

    def __call__(self, seam: str, seq: int, ctx: dict) -> None:
        with self._lock:
            if self.max_faults is not None and len(self.fired) >= self.max_faults:
                return
            for spec in self.specs:
                if spec.matches(seam, seq):
                    spec.fired += 1
                    self.fired.append((seam, seq))
                    _raise_fault(spec.exc)
            if self.rate > 0.0 and self.seed is not None:
                if self.seams is not None and seam not in self.seams:
                    return
                # nth firing of this exact (seam, seq) — keeps retries of the
                # same chunk from deterministically re-hitting the same fault
                nth = self._counts.get((seam, seq), 0)
                self._counts[(seam, seq)] = nth + 1
                rng = random.Random(f"{self.seed}|{seam}|{int(seq)}|{nth}")
                if rng.random() < self.rate:
                    self.fired.append((seam, seq))
                    _raise_fault(self.exc)

    def corrupt_payload(self, seam: str, seq: int, payload: bytes, ctx: dict) -> Optional[bytes]:
        """The :func:`fire_corrupt` capability: apply the first matching
        :class:`CorruptionSpec`, or leave the payload alone."""
        with self._lock:
            if self.max_faults is not None and len(self.fired) >= self.max_faults:
                return None
            for spec in self.corruptions:
                if spec.matches(seam, seq):
                    mutated = spec.apply(payload, seq)
                    spec.fired += 1
                    self.fired.append((seam, seq))
                    return mutated
        return None

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"FaultScript(specs={len(self.specs)}, seed={self.seed}, "
            f"rate={self.rate}, fired={len(self.fired)})"
        )
