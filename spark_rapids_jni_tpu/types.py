"""Spark/cuDF-compatible column type system.

Mirrors the ``ai.rapids.cudf.DType`` surface the reference's Java API exposes
(used by RowConversion.convertFromRows, reference
src/main/java/com/nvidia/spark/rapids/jni/RowConversion.java:110-121, which
marshals each column as ``(native type id, scale)`` int pairs across JNI).
Type ids follow the cuDF ``type_id`` enum ordering so handles round-trip
unchanged through the native bridge.

Fixed-width sizes drive the packed row layout (reference
src/main/cpp/src/row_conversion.cu:432-456): each fixed-width type's
alignment equals its size.

Decimal columns are stored as their integer backing type (int32/int64) plus a
``scale`` — matching cuDF, where DECIMAL32(scale=-3) stores unscaled ints and
the value is ``unscaled * 10**scale``.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np


class TypeId(enum.IntEnum):
    """Native type ids (cuDF type_id enum order, branch-22.06 era)."""

    EMPTY = 0
    INT8 = 1
    INT16 = 2
    INT32 = 3
    INT64 = 4
    UINT8 = 5
    UINT16 = 6
    UINT32 = 7
    UINT64 = 8
    FLOAT32 = 9
    FLOAT64 = 10
    BOOL8 = 11
    TIMESTAMP_DAYS = 12
    TIMESTAMP_SECONDS = 13
    TIMESTAMP_MILLISECONDS = 14
    TIMESTAMP_MICROSECONDS = 15
    TIMESTAMP_NANOSECONDS = 16
    DURATION_DAYS = 17
    DURATION_SECONDS = 18
    DURATION_MILLISECONDS = 19
    DURATION_MICROSECONDS = 20
    DURATION_NANOSECONDS = 21
    DICTIONARY32 = 22
    STRING = 23
    LIST = 24
    DECIMAL32 = 25
    DECIMAL64 = 26
    DECIMAL128 = 27
    STRUCT = 28


# Storage dtype (numpy) for each fixed-width type id.
_STORAGE: dict[TypeId, np.dtype] = {
    TypeId.INT8: np.dtype(np.int8),
    TypeId.INT16: np.dtype(np.int16),
    TypeId.INT32: np.dtype(np.int32),
    TypeId.INT64: np.dtype(np.int64),
    TypeId.UINT8: np.dtype(np.uint8),
    TypeId.UINT16: np.dtype(np.uint16),
    TypeId.UINT32: np.dtype(np.uint32),
    TypeId.UINT64: np.dtype(np.uint64),
    TypeId.FLOAT32: np.dtype(np.float32),
    TypeId.FLOAT64: np.dtype(np.float64),
    TypeId.BOOL8: np.dtype(np.uint8),
    TypeId.TIMESTAMP_DAYS: np.dtype(np.int32),
    TypeId.TIMESTAMP_SECONDS: np.dtype(np.int64),
    TypeId.TIMESTAMP_MILLISECONDS: np.dtype(np.int64),
    TypeId.TIMESTAMP_MICROSECONDS: np.dtype(np.int64),
    TypeId.TIMESTAMP_NANOSECONDS: np.dtype(np.int64),
    TypeId.DURATION_DAYS: np.dtype(np.int32),
    TypeId.DURATION_SECONDS: np.dtype(np.int64),
    TypeId.DURATION_MILLISECONDS: np.dtype(np.int64),
    TypeId.DURATION_MICROSECONDS: np.dtype(np.int64),
    TypeId.DURATION_NANOSECONDS: np.dtype(np.int64),
    TypeId.DECIMAL32: np.dtype(np.int32),
    TypeId.DECIMAL64: np.dtype(np.int64),
}

_FROM_NUMPY: dict[np.dtype, TypeId] = {
    np.dtype(np.int8): TypeId.INT8,
    np.dtype(np.int16): TypeId.INT16,
    np.dtype(np.int32): TypeId.INT32,
    np.dtype(np.int64): TypeId.INT64,
    np.dtype(np.uint8): TypeId.UINT8,
    np.dtype(np.uint16): TypeId.UINT16,
    np.dtype(np.uint32): TypeId.UINT32,
    np.dtype(np.uint64): TypeId.UINT64,
    np.dtype(np.float32): TypeId.FLOAT32,
    np.dtype(np.float64): TypeId.FLOAT64,
    np.dtype(np.bool_): TypeId.BOOL8,
}


@dataclass(frozen=True)
class DType:
    """A column data type: native type id + decimal scale.

    Matches the ``(typeId, scale)`` pair the reference marshals across JNI
    (RowConversion.java:113-118). ``scale`` is only meaningful for decimals
    and follows cuDF convention: value = unscaled * 10**scale (so scale is
    usually negative).
    """

    type_id: TypeId
    scale: int = 0

    def __post_init__(self) -> None:
        if self.scale != 0 and self.type_id not in (
            TypeId.DECIMAL32,
            TypeId.DECIMAL64,
            TypeId.DECIMAL128,
        ):
            raise ValueError(f"scale only valid for decimal types, got {self.type_id}")

    @property
    def is_fixed_width(self) -> bool:
        return self.type_id in _STORAGE

    @property
    def is_decimal(self) -> bool:
        return self.type_id in (TypeId.DECIMAL32, TypeId.DECIMAL64, TypeId.DECIMAL128)

    @property
    def is_string(self) -> bool:
        return self.type_id == TypeId.STRING

    @property
    def is_decimal128(self) -> bool:
        """128-bit decimal: stored as int64[n, 2] limb pairs (lo unsigned,
        hi signed, little-endian limb order) — the TPU has no native int128,
        so the storage IS the pair (cuDF stores __int128_t)."""
        return self.type_id == TypeId.DECIMAL128

    @property
    def storage_dtype(self) -> np.dtype:
        """Physical element dtype backing this type on device."""
        try:
            return _STORAGE[self.type_id]
        except KeyError:
            raise TypeError(f"{self.type_id.name} is not fixed-width") from None

    @property
    def size_bytes(self) -> int:
        """Fixed-width element size; also its required alignment in a packed
        row (reference row_conversion.cu:439-443). DECIMAL128 is 16 — the
        sizeof(__int128_t) the reference's generic fixed-width layout sees
        (row_conversion.cu:462-468 via cudf::size_of)."""
        if self.is_decimal128:
            return 16
        return self.storage_dtype.itemsize

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.storage_dtype)

    @classmethod
    def from_numpy(cls, dt: np.dtype) -> "DType":
        try:
            return cls(_FROM_NUMPY[np.dtype(dt)])
        except KeyError:
            raise TypeError(f"no column type for numpy dtype {dt}") from None

    def __repr__(self) -> str:
        if self.is_decimal:
            return f"DType({self.type_id.name}, scale={self.scale})"
        return f"DType({self.type_id.name})"


# Convenience singletons mirroring ai.rapids.cudf.DType statics.
INT8 = DType(TypeId.INT8)
INT16 = DType(TypeId.INT16)
INT32 = DType(TypeId.INT32)
INT64 = DType(TypeId.INT64)
UINT8 = DType(TypeId.UINT8)
UINT16 = DType(TypeId.UINT16)
UINT32 = DType(TypeId.UINT32)
UINT64 = DType(TypeId.UINT64)
FLOAT32 = DType(TypeId.FLOAT32)
FLOAT64 = DType(TypeId.FLOAT64)
BOOL8 = DType(TypeId.BOOL8)
STRING = DType(TypeId.STRING)
TIMESTAMP_DAYS = DType(TypeId.TIMESTAMP_DAYS)
TIMESTAMP_MICROSECONDS = DType(TypeId.TIMESTAMP_MICROSECONDS)
DURATION_DAYS = DType(TypeId.DURATION_DAYS)


def decimal32(scale: int) -> DType:
    return DType(TypeId.DECIMAL32, scale)


def decimal64(scale: int) -> DType:
    return DType(TypeId.DECIMAL64, scale)


def decimal128(scale: int) -> DType:
    return DType(TypeId.DECIMAL128, scale)
