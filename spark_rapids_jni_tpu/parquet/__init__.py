from spark_rapids_jni_tpu.parquet.footer import ParquetFooter
from spark_rapids_jni_tpu.parquet.reader import (
    ParquetChunkedReader,
    read_table,
    row_group_info,
)

__all__ = [
    "ParquetChunkedReader",
    "ParquetFooter",
    "read_table",
    "row_group_info",
]
