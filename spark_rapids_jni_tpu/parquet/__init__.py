from spark_rapids_jni_tpu.parquet.footer import ParquetFooter

__all__ = ["ParquetFooter"]
