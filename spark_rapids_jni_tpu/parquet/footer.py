"""Parquet footer prune/filter — Python surface over the native engine.

API parity with com.nvidia.spark.rapids.jni.ParquetFooter (reference
src/main/java/.../ParquetFooter.java:24-114): readAndFilter, getNumRows,
getNumColumns, serializeThriftFile, AutoCloseable semantics. The heavy
lifting is C++ (src/native/src/parquet_footer.cpp); objects cross the
boundary as int64 handles like the reference's jlong handles.
"""

from __future__ import annotations

import ctypes
from typing import Sequence

from spark_rapids_jni_tpu.runtime import load_native
from spark_rapids_jni_tpu.runtime.resilience import MalformedInputError
from spark_rapids_jni_tpu.utils.tracing import func_range


class NativeError(RuntimeError):
    """Raised when the native core reports a failure — the CudfException
    equivalent of the reference's CATCH_STD bridge."""


class MalformedFileError(MalformedInputError, NativeError):
    """Untrusted Parquet/ORC input failed structural validation.

    Dual-parented on purpose: :class:`MalformedInputError` classifies it
    for the serving stack (the server rejects that one query cleanly —
    never retried, never degraded, zero leaked reservations), while the
    :class:`NativeError` base keeps every legacy ``except NativeError``
    caller working — hardening the readers reclassifies failures, it
    does not change who catches them."""


class ParquetFooter:
    def __init__(self, handle: int):
        if handle == 0:
            raise ValueError("null footer handle")
        self._handle = handle

    @classmethod
    @func_range("ParquetFooter.readAndFilter")
    def read_and_filter(
        cls,
        buffer: bytes,
        part_offset: int,
        part_length: int,
        names: Sequence[str],
        num_children: Sequence[int],
        parent_num_children: int,
        ignore_case: bool = False,
    ) -> "ParquetFooter":
        """Parse a raw thrift footer (no PAR1 framing), prune to the
        requested depth-first column tree, and filter row groups to the
        partition byte range (negative part_length keeps all groups).
        Names should be pre-lowercased by the caller when ignore_case is
        set, as the reference documents (ParquetFooter.java:78-79)."""
        from spark_rapids_jni_tpu.runtime import integrity

        if len(names) != len(num_children):
            raise ValueError("names and num_children must have equal length")
        if integrity.enabled():
            # untrusted-input preflight, before any native parse
            if len(buffer) == 0:
                raise integrity.reject_malformed(
                    "parquet.footer", "empty thrift footer buffer",
                    exc_type=MalformedFileError)
            if part_offset < 0:
                raise integrity.reject_malformed(
                    "parquet.footer",
                    "negative partition offset",
                    exc_type=MalformedFileError, part_offset=part_offset)
        lib = load_native()
        c_names = (ctypes.c_char_p * len(names))(
            *[n.encode() for n in names]
        )
        c_children = (ctypes.c_int32 * len(num_children))(*num_children)
        handle = lib.tpudf_footer_read_and_filter(
            buffer,
            len(buffer),
            part_offset,
            part_length,
            c_names,
            c_children,
            len(names),
            parent_num_children,
            1 if ignore_case else 0,
        )
        if handle == 0:
            # the native thrift parser rejected the bytes: malformed
            # input, classified for the server, NativeError for legacy
            raise integrity.reject_malformed(
                "parquet.footer", lib.last_error(),
                exc_type=MalformedFileError)
        return cls(handle)

    def _require_open(self) -> int:
        if self._handle == 0:
            raise ValueError("footer is closed")
        return self._handle

    @property
    def num_rows(self) -> int:
        lib = load_native()
        out = lib.tpudf_footer_num_rows(self._require_open())
        if out < 0:
            raise NativeError(lib.last_error())
        return out

    @property
    def num_columns(self) -> int:
        lib = load_native()
        out = lib.tpudf_footer_num_columns(self._require_open())
        if out < 0:
            raise NativeError(lib.last_error())
        return out

    @func_range("ParquetFooter.serializeThriftFile")
    def serialize_thrift_file(self) -> bytes:
        """Emit a legal footer file image: PAR1 + thrift + length + PAR1
        (reference NativeParquetJni.cpp:603-620)."""
        lib = load_native()
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint64()
        rc = lib.tpudf_footer_serialize(
            self._require_open(), ctypes.byref(out), ctypes.byref(out_len)
        )
        if rc != 0:
            raise NativeError(lib.last_error())
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            lib.tpudf_free_buffer(out)

    def close(self) -> None:
        if self._handle != 0:
            load_native().tpudf_footer_close(self._handle)
            self._handle = 0

    def __enter__(self) -> "ParquetFooter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
