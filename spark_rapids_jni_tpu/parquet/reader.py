"""Parquet data reader — native page decode staged into device tables.

The capability-surface equivalent of cuDF's (chunked) Parquet reader, which
the reference links statically and surfaces through ai.rapids.cudf
(build-libcudf.xml:45, CMakeLists.txt:104-119; "Parquet chunked reader" in
BASELINE.json's north star). Pages are decoded by libtpudf (C++,
src/native/src/parquet_reader.cpp) into Arrow-layout host buffers, then
staged to HBM as a columnar Table. Chunked reads iterate row-group batches
bounded by a byte budget — the same external contract as cuDF's chunked
reader (chunk boundaries at row-group granularity).

Type mapping (parquet physical + converted type -> DType) follows Spark's
Parquet vectorized reader:

  BOOLEAN              -> BOOL8
  INT32                -> INT32 | INT8/16 (INT_8/INT_16) | UINT_8.. |
                          TIMESTAMP_DAYS (DATE) | DECIMAL32
  INT64                -> INT64 | UINT_64 | TIMESTAMP_MILLIS/MICROS | DECIMAL64
  FLOAT / DOUBLE       -> FLOAT32 / FLOAT64
  BYTE_ARRAY           -> STRING
  FIXED_LEN_BYTE_ARRAY -> DECIMAL64 for DECIMAL with type_length <= 8
                          (big-endian two's-complement unscaled)
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.parquet.footer import MalformedFileError, NativeError
from spark_rapids_jni_tpu.runtime import faults, integrity
from spark_rapids_jni_tpu.runtime.native import load_native
from spark_rapids_jni_tpu.utils.fspath import as_fs_path
from spark_rapids_jni_tpu.types import DType, TypeId
from spark_rapids_jni_tpu.utils.tracing import func_range

# parquet.thrift enums (public spec)
_PHYS_BOOLEAN, _PHYS_INT32, _PHYS_INT64 = 0, 1, 2
_PHYS_INT96, _PHYS_FLOAT, _PHYS_DOUBLE = 3, 4, 5
_PHYS_BYTE_ARRAY, _PHYS_FLBA = 6, 7
_CONV_UTF8, _CONV_DECIMAL, _CONV_DATE = 0, 5, 6
_CONV_TS_MILLIS, _CONV_TS_MICROS = 9, 10
_CONV_UINT8, _CONV_UINT16, _CONV_UINT32, _CONV_UINT64 = 11, 12, 13, 14
_CONV_INT8, _CONV_INT16, _CONV_INT32, _CONV_INT64 = 15, 16, 17, 18

_PHYS_WIDTH = {_PHYS_BOOLEAN: 1, _PHYS_INT32: 4, _PHYS_INT64: 8,
               _PHYS_FLOAT: 4, _PHYS_DOUBLE: 8}
_PHYS_NP = {_PHYS_BOOLEAN: np.uint8, _PHYS_INT32: np.int32,
            _PHYS_INT64: np.int64, _PHYS_FLOAT: np.float32,
            _PHYS_DOUBLE: np.float64}


def _map_dtype(phys: int, conv: int, scale: int, type_length: int) -> DType:
    if phys == _PHYS_BOOLEAN:
        return t.BOOL8
    if phys == _PHYS_FLOAT:
        return t.FLOAT32
    if phys == _PHYS_DOUBLE:
        return t.FLOAT64
    if phys == _PHYS_BYTE_ARRAY:
        return t.STRING
    if phys == _PHYS_INT32:
        if conv == _CONV_DATE:
            return t.TIMESTAMP_DAYS
        if conv == _CONV_DECIMAL:
            return t.decimal32(-scale)
        if conv == _CONV_INT8:
            return t.INT8
        if conv == _CONV_INT16:
            return t.INT16
        if conv == _CONV_UINT8:
            return t.UINT8
        if conv == _CONV_UINT16:
            return t.UINT16
        if conv == _CONV_UINT32:
            return t.UINT32
        return t.INT32
    if phys == _PHYS_INT64:
        if conv == _CONV_DECIMAL:
            return t.decimal64(-scale)
        if conv == _CONV_TS_MILLIS:
            return DType(TypeId.TIMESTAMP_MILLISECONDS)
        if conv == _CONV_TS_MICROS:
            return DType(TypeId.TIMESTAMP_MICROSECONDS)
        if conv == _CONV_UINT64:
            return t.UINT64
        return t.INT64
    if phys == _PHYS_FLBA:
        if conv == _CONV_DECIMAL and 0 < type_length <= 8:
            return t.decimal64(-scale)
        if conv == _CONV_DECIMAL and 8 < type_length <= 16:
            return t.decimal128(-scale)
        raise NotImplementedError(
            "FIXED_LEN_BYTE_ARRAY is only supported as DECIMAL with "
            "type_length <= 16"
        )
    raise NotImplementedError(f"unsupported parquet physical type {phys}")


def _flba_to_int64(raw: np.ndarray, width: int) -> np.ndarray:
    """Big-endian two's-complement unscaled decimal -> int64[n]."""
    m = raw.reshape(-1, width).astype(np.int64)
    out = np.where(m[:, 0] >= 128, np.int64(-1), np.int64(0))
    for k in range(width):
        out = (out << 8) | m[:, k]
    return out


def _flba_to_int128(raw: np.ndarray, width: int) -> np.ndarray:
    """Big-endian two's-complement unscaled decimal (9..16 bytes) ->
    int64[n, 2] limb pairs (lo, hi little-endian limb order)."""
    m = raw.reshape(-1, width).astype(np.uint64)
    lo = np.zeros(m.shape[0], dtype=np.uint64)
    hi = np.zeros(m.shape[0], dtype=np.uint64)
    for k in range(width):  # big-endian: shift the 128-bit value left 8
        hi = (hi << np.uint64(8)) | (lo >> np.uint64(56))
        lo = (lo << np.uint64(8)) | m[:, k]
    # sign-extend bits [8*width, 128) for negative values
    if width < 16:
        neg = m[:, 0] >= 128
        shift = 8 * width - 64  # in (0, 64) for widths 9..15
        mask = np.uint64((0xFFFFFFFFFFFFFFFF << shift) & 0xFFFFFFFFFFFFFFFF)
        hi = np.where(neg, hi | mask, hi)
    return np.stack([lo.view(np.int64), hi.view(np.int64)], axis=1)


def _check(lib, ok: bool, what: str) -> None:
    # decode failures on untrusted bytes classify as malformed input
    # (MalformedFileError is-a NativeError, so legacy catches still work)
    if not ok:
        raise integrity.reject_malformed(
            f"parquet.{what}", f"{what}: {lib.last_error()}",
            exc_type=MalformedFileError)


_PAR1 = b"PAR1"


def _validate_parquet_envelope(data: "bytes | str | os.PathLike") -> None:
    """Untrusted-input preflight: check the Parquet file envelope —
    leading/trailing magic and the footer length field against the file
    size — BEFORE any decoder touches the bytes. Pure Python (no native
    lib needed), so a truncated or clobbered file is rejected classified
    even where the native engine is absent. The deep structural checks
    (thrift schema, page bounds, dictionary indices vs cardinality) run
    inside the hardened native parse behind the same classification."""
    if not integrity.enabled():
        return
    path = as_fs_path(data)
    if path is None:
        n = len(data)
        head, tail = bytes(data[:4]), bytes(data[-12:])
    else:
        try:
            n = os.path.getsize(path)
            with open(path, "rb") as fh:
                head = fh.read(4)
                fh.seek(max(0, n - 12))
                tail = fh.read(12)
        except OSError:
            return  # unreadable path: let the native open report it
    if n < 12:
        raise integrity.reject_malformed(
            "parquet.envelope",
            "file too short to be parquet",
            exc_type=MalformedFileError, size=n)
    if head != _PAR1:
        raise integrity.reject_malformed(
            "parquet.envelope",
            "bad leading magic (not a parquet file)",
            exc_type=MalformedFileError, size=n)
    if tail[-4:] != _PAR1:
        raise integrity.reject_malformed(
            "parquet.envelope",
            "bad trailing magic (truncated or clobbered file)",
            exc_type=MalformedFileError, size=n)
    import struct as _struct

    (footer_len,) = _struct.unpack("<I", tail[-8:-4])
    if footer_len == 0 or footer_len + 12 > n:
        raise integrity.reject_malformed(
            "parquet.envelope",
            "footer length field points outside the file",
            exc_type=MalformedFileError, footer_len=footer_len, size=n)


def _validate_flat_snap(snap, num_rows: int, phys: int,
                        data_bytes: int, chars_bytes: int) -> None:
    """Post-decode bounds checks on one flat column: declared row count
    vs actual payload, string offsets monotone and inside the character
    buffer. Catches a decoder handing back internally inconsistent
    buffers before they are staged (and before downstream gathers index
    out of bounds on device, where there is no fault to catch)."""
    if not integrity.enabled():
        return
    _dtype, values, _validity, chars, _children = snap
    if num_rows < 0 or data_bytes < 0 or chars_bytes < 0:
        raise integrity.reject_malformed(
            "parquet.column", "negative size from decoder",
            exc_type=MalformedFileError, rows=num_rows,
            data_bytes=data_bytes, chars_bytes=chars_bytes)
    if phys == _PHYS_BYTE_ARRAY:
        offsets = values
        if offsets.shape[0] != num_rows + 1:
            raise integrity.reject_malformed(
                "parquet.column",
                "string offsets disagree with declared row count",
                exc_type=MalformedFileError, rows=num_rows,
                offsets=int(offsets.shape[0]))
        if num_rows >= 0 and (
                int(offsets[0]) != 0
                or int(offsets[-1]) != int(chars.shape[0])
                or (num_rows > 0 and bool(np.any(np.diff(offsets) < 0)))):
            raise integrity.reject_malformed(
                "parquet.column",
                "string offsets inconsistent with character payload",
                exc_type=MalformedFileError, rows=num_rows,
                chars_bytes=int(chars.shape[0]))
    elif phys in _PHYS_WIDTH and data_bytes != num_rows * _PHYS_WIDTH[phys]:
        raise integrity.reject_malformed(
            "parquet.column",
            "column payload size disagrees with declared row count",
            exc_type=MalformedFileError, rows=num_rows,
            data_bytes=data_bytes, width=_PHYS_WIDTH[phys])


def _i32_array(vals: Optional[Sequence[int]]):
    """None -> null pointer (= select all); an explicit empty list stays a
    non-null zero-length selection (= select none)."""
    if vals is None:
        return None, 0
    arr = (ctypes.c_int32 * len(vals))(*vals)
    return arr, len(vals)


def row_group_info(data: "bytes | str | os.PathLike") -> list[tuple[int, int]]:
    """[(num_rows, byte_size)] per row group — the chunk-planning probe.
    Accepts in-memory bytes or a path (mmap; only footer pages fault in)."""
    _validate_parquet_envelope(data)
    lib = load_native()
    cap = 4096
    while True:
        nr = (ctypes.c_int64 * cap)()
        bs = (ctypes.c_int64 * cap)()
        path = as_fs_path(data)
        if path is not None:
            n = lib.tpudf_parquet_row_groups_path(path, nr, bs, cap)
        else:
            n = lib.tpudf_parquet_row_groups(data, len(data), nr, bs, cap)
        _check(lib, n >= 0, "row_group_info")
        if n <= cap:
            return [(nr[i], bs[i]) for i in range(n)]
        cap = n


def _read_flat_column_host(lib, handle: int, i: int):
    """One flat (non-nested) leaf decoded to a HOST column snapshot
    (the ``memory._col_to_host`` tuple format: dtype, data, validity,
    chars, children — all numpy, row count known, zero device bytes).
    The pipelined executor decodes through this form so the
    MemoryLimiter reservation can precede the host->device copy."""
    meta = (ctypes.c_int32 * 7)()
    sizes = (ctypes.c_int64 * 3)()
    _check(lib, lib.tpudf_read_col_meta(handle, i, meta, sizes) == 0,
           "col_meta")
    phys, conv, scale, _prec, tlen, _opt, has_valid = list(meta)
    data_bytes, chars_bytes, num_rows = list(sizes)
    dtype = _map_dtype(phys, conv, scale, tlen)

    vbuf = np.empty(num_rows, dtype=np.uint8) if has_valid else None
    if phys == _PHYS_BYTE_ARRAY:
        offsets = np.empty(num_rows + 1, dtype=np.int32)
        chars = np.empty(max(chars_bytes, 1), dtype=np.uint8)
        _check(
            lib,
            lib.tpudf_read_col_copy(
                handle, i, None,
                offsets.ctypes.data_as(ctypes.c_void_p),
                chars.ctypes.data_as(ctypes.c_void_p),
                None if vbuf is None
                else vbuf.ctypes.data_as(ctypes.c_void_p),
            ) == 0,
            "col_copy",
        )
        validity = None if vbuf is None else vbuf.astype(bool)
        snap = (dtype, offsets, validity, chars[:chars_bytes], None)
        _validate_flat_snap(snap, num_rows, phys, data_bytes, chars_bytes)
        return snap, num_rows

    raw = np.empty(max(data_bytes, 1), dtype=np.uint8)
    _check(
        lib,
        lib.tpudf_read_col_copy(
            handle, i, raw.ctypes.data_as(ctypes.c_void_p), None, None,
            None if vbuf is None
            else vbuf.ctypes.data_as(ctypes.c_void_p),
        ) == 0,
        "col_copy",
    )
    validity = None if vbuf is None else vbuf.astype(bool)
    if phys == _PHYS_FLBA and dtype.is_decimal128:
        values = _flba_to_int128(raw[:data_bytes], tlen)
        return (dtype, values, validity, None, None), num_rows
    if phys == _PHYS_FLBA:
        values = _flba_to_int64(raw[:data_bytes], tlen)
    else:
        values = raw[:data_bytes].view(_PHYS_NP[phys])
    values = values.astype(dtype.storage_dtype, copy=False)
    snap = (dtype, values, validity, None, None)
    _validate_flat_snap(snap, num_rows, phys, data_bytes, chars_bytes)
    return snap, num_rows


def _check_row_agreement(prev: "int | None", rows: int, col: int) -> None:
    """Every column of one read must agree on the row count — a file
    whose columns disagree would otherwise build a ragged Table that
    downstream kernels silently broadcast or truncate."""
    if prev is None or not integrity.enabled():
        return
    if rows != prev:
        raise integrity.reject_malformed(
            "parquet.table", "columns disagree on row count",
            exc_type=MalformedFileError, column=col,
            rows=rows, expected=prev)


def _read_flat_column(lib, handle: int, i: int) -> Column:
    """One flat (non-nested) leaf: row-aligned values + optional validity."""
    from spark_rapids_jni_tpu.runtime.memory import _col_from_host

    snap, _num_rows = _read_flat_column_host(lib, handle, i)
    return _col_from_host(snap)


def _read_leaf_data(lib, handle: int, leaf_index: int):
    """Copy one nested leaf's compact values + levels off the native reader."""
    from spark_rapids_jni_tpu.parquet.nested import LeafData

    meta = (ctypes.c_int32 * 10)()
    sizes = (ctypes.c_int64 * 5)()
    _check(lib, lib.tpudf_read_col_meta2(handle, leaf_index, meta, sizes) == 0,
           "col_meta2")
    phys, conv, scale, _prec, tlen = meta[0], meta[1], meta[2], meta[3], meta[4]
    max_rep = meta[8]
    data_bytes, chars_bytes, _num_rows, n_levels, n_present = list(sizes)
    dtype = _map_dtype(phys, conv, scale, tlen)

    defs = np.empty(max(n_levels, 1), dtype=np.uint8)
    reps = np.empty(max(n_levels, 1), dtype=np.uint8) if max_rep else None
    _check(
        lib,
        lib.tpudf_read_col_levels(
            handle, leaf_index, defs.ctypes.data_as(ctypes.c_void_p),
            None if reps is None else reps.ctypes.data_as(ctypes.c_void_p),
        ) == 0,
        "col_levels",
    )
    defs = defs[:n_levels]
    reps = None if reps is None else reps[:n_levels]

    values = offsets = chars = None
    if phys == _PHYS_BYTE_ARRAY:
        offsets = np.empty(n_present + 1, dtype=np.int32)
        chars = np.empty(max(chars_bytes, 1), dtype=np.uint8)
        _check(
            lib,
            lib.tpudf_read_col_copy(
                handle, leaf_index, None,
                offsets.ctypes.data_as(ctypes.c_void_p),
                chars.ctypes.data_as(ctypes.c_void_p), None,
            ) == 0,
            "col_copy",
        )
        chars = chars[:chars_bytes]
    else:
        raw = np.empty(max(data_bytes, 1), dtype=np.uint8)
        _check(
            lib,
            lib.tpudf_read_col_copy(
                handle, leaf_index,
                raw.ctypes.data_as(ctypes.c_void_p), None, None, None,
            ) == 0,
            "col_copy",
        )
        if phys == _PHYS_FLBA:
            if dtype.is_decimal128:
                raise NotImplementedError(
                    "DECIMAL128 inside nested columns is not supported yet"
                )
            values = _flba_to_int64(raw[:data_bytes], tlen)
        else:
            values = raw[:data_bytes].view(_PHYS_NP[phys])
        values = values.astype(dtype.storage_dtype, copy=False)
    return LeafData(values, offsets, chars, defs, reps, dtype)


def _read_nested(lib, handle: int, tree) -> Table:
    """Assemble a table whose schema contains struct/list columns."""
    from spark_rapids_jni_tpu.parquet import nested as nst

    leaf_data = {}
    for nd in tree:
        if nd.is_leaf:
            continue  # top-level flat leaves use the row-aligned path
        for lf in nst.leaves_of(nd):
            leaf_data[lf.leaf_index] = _read_leaf_data(lib, handle,
                                                       lf.leaf_index)
    out = []
    for nd in tree:
        if nd.is_leaf:
            out.append(_read_flat_column(lib, handle, nd.leaf_index))
        elif nd.converted == nst._CONV_LIST or (
            len(nd.children) == 1 and nd.children[0].repetition == 2
        ):
            out.append(nst.assemble_list(nd, leaf_data))
        else:
            out.append(nst.assemble_struct(nd, leaf_data))
    return Table(out)


@func_range("parquet_read_table")
def read_table(
    data: "bytes | str | os.PathLike",
    columns: Optional[Sequence[int]] = None,
    row_groups: Optional[Sequence[int]] = None,
    stage: str = "device",
) -> Table:
    """Decode a Parquet file into a device Table.

    ``data`` may be in-memory bytes OR a filesystem path: paths decode
    through a native mmap (the cuFile/GDS-role storage path, reference
    CMakeLists.txt:200-222) — only the byte ranges of the selected row
    groups are ever faulted in, so chunked reads of large files never
    materialize the file through Python.

    ``stage="host"`` stops at the host boundary and returns a
    ``HostTableChunk`` (flat schemas only): the pipelined executor
    decodes there so the device-budget reservation can be taken on exact
    bytes BEFORE the host->device copy. ``stage()``-ing the chunk yields
    a Table bit-identical to the default path."""
    if stage not in ("device", "host"):
        raise ValueError(f"unknown stage {stage!r}")
    if as_fs_path(data) is None:
        # chaos window for untrusted ingestion: in-memory file bytes can
        # be corrupted by a fault script before any validation runs —
        # the preflight + hardened decode below must classify, never
        # crash unclassified or return garbage
        data = faults.fire_corrupt("integrity.ingest", 0, data)
    _validate_parquet_envelope(data)
    lib = load_native()
    cols, n_cols = _i32_array(columns)
    rgs, n_rgs = _i32_array(row_groups)
    path = as_fs_path(data)
    if path is not None:
        handle = lib.tpudf_parquet_read_path(path, cols, n_cols, rgs, n_rgs)
    else:
        handle = lib.tpudf_parquet_read(
            data, len(data), cols, n_cols, rgs, n_rgs
        )
    _check(lib, handle != 0, "parquet read")
    try:
        n_columns = lib.tpudf_read_num_columns(handle)
        _check(lib, n_columns >= 0, "num_columns")

        desc_raw = lib.tpudf_read_schema_desc(handle)
        _check(lib, desc_raw is not None, "schema_desc")
        from spark_rapids_jni_tpu.parquet import nested as nst

        tree = nst.parse_schema_desc(desc_raw.decode())
        for nd in tree:
            if nd.is_leaf and nd.repetition == 2:
                raise NotImplementedError(
                    f"legacy 1-level repeated field {nd.name!r} is not "
                    "supported (rewrite as a 3-level LIST)"
                )
        if any(not nd.is_leaf for nd in tree):
            if stage == "host":
                raise NotImplementedError(
                    "host-staged decode (stage='host') supports flat "
                    "schemas only; nested columns assemble on device"
                )
            if columns is not None:
                raise NotImplementedError(
                    "column selection over nested schemas is not supported "
                    "yet; read all columns"
                )
            return _read_nested(lib, handle, tree)

        if stage == "host":
            from spark_rapids_jni_tpu.runtime.memory import host_table_chunk

            snaps = []
            num_rows = 0
            for i in range(n_columns):
                snap, nr = _read_flat_column_host(lib, handle, i)
                _check_row_agreement(num_rows if i else None, nr, i)
                num_rows = nr
                snaps.append(snap)
            return host_table_chunk(snaps, num_rows)

        cols_out = []
        rows_seen = None
        for i in range(n_columns):
            snap, nr = _read_flat_column_host(lib, handle, i)
            _check_row_agreement(rows_seen, nr, i)
            rows_seen = nr
            cols_out.append(snap)
        from spark_rapids_jni_tpu.runtime.memory import _col_from_host

        return Table([_col_from_host(snap) for snap in cols_out])
    finally:
        lib.tpudf_read_close(handle)


class ParquetChunkedReader:
    """Iterate a Parquet file as a sequence of Tables bounded by a byte
    budget — cuDF chunked-reader contract at row-group granularity: each
    chunk is the longest run of row groups whose summed on-disk size fits
    ``chunk_read_limit`` (always at least one row group)."""

    def __init__(
        self,
        data: bytes,
        chunk_read_limit: int,
        columns: Optional[Sequence[int]] = None,
    ):
        self._data = data
        self._columns = list(columns) if columns is not None else None
        self._limit = max(int(chunk_read_limit), 1)
        self._infos = row_group_info(data)
        self._next_rg = 0

    def has_next(self) -> bool:
        return self._next_rg < len(self._infos)

    def _chunk_end(self, start: int) -> int:
        total = 0
        end = start
        while end < len(self._infos):
            total += self._infos[end][1]
            if end > start and total > self._limit:
                break
            end += 1
        return end

    def read_chunk(self) -> Table:
        if not self.has_next():
            raise StopIteration
        start = self._next_rg
        end = self._chunk_end(start)
        self._next_rg = end
        return read_table(
            self._data, self._columns, list(range(start, end))
        )

    def chunk_plan(self) -> list[list[int]]:
        """Row-group index runs, one per REMAINING chunk. Pure planning:
        does not decode or advance the iteration cursor."""
        plans = []
        start = self._next_rg
        while start < len(self._infos):
            end = self._chunk_end(start)
            plans.append(list(range(start, end)))
            start = end
        return plans

    def chunk_sources(self, stage: str = "host") -> list:
        """Zero-arg decode thunks, one per remaining chunk — the
        pipeline's read/decode-stage contract. Each thunk decodes its
        row-group run independently (safe to call from pool threads; the
        native decode releases the GIL). The default ``stage="host"``
        decodes to ``HostTableChunk`` so the device copy can wait for
        its MemoryLimiter reservation; pass ``stage="device"`` for
        schemas the host path does not cover (nested)."""
        data, columns = self._data, self._columns
        return [
            (lambda rgs=rgs: read_table(data, columns, rgs, stage=stage))
            for rgs in self.chunk_plan()
        ]

    def __iter__(self) -> Iterator[Table]:
        while self.has_next():
            yield self.read_chunk()
