"""Nested Parquet column assembly — Dremel record reconstruction.

The native reader (src/native/src/parquet_reader.cpp) decodes nested leaves
into compact present values plus raw definition/repetition levels and dumps
the schema tree as text. This module rebuilds the tree and assembles
cuDF-shaped nested columns (STRUCT with children; LIST as offsets + child),
the record-shredding inverse described by the Dremel paper and implemented
on device memory by cuDF's reader (reference capability surface,
build-libcudf.xml:45).

Supported shapes this round (explicit errors otherwise):
  * arbitrarily nested STRUCTs of primitives/strings (no lists inside);
  * top-level LIST of a primitive/string element (the standard 3-level
    ``optional group (LIST) { repeated group list { element } }``);
  * everything flat handled by reader.read_table's existing fast path.

All assembly math is vectorized numpy on host buffers (the level streams
are host-side by construction; the assembled children stage to device).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column
from spark_rapids_jni_tpu.types import DType, TypeId

_CONV_LIST = 3  # parquet ConvertedType.LIST


@dataclass
class SchemaNode:
    name: str
    num_children: int
    repetition: int  # 0 REQUIRED, 1 OPTIONAL, 2 REPEATED
    physical: int
    converted: int
    scale: int
    precision: int
    type_length: int
    def_level: int = 0   # cumulative def level at this node
    rep_level: int = 0
    children: list = field(default_factory=list)
    leaf_index: int = -1  # preorder leaf ordinal (chunks order), -1 = group

    @property
    def is_leaf(self) -> bool:
        return self.num_children == 0


def _unescape_name(s: str) -> str:
    """Inverse of the reader's dump escaping (\\t, \\n, \\\\ in field names)."""
    out = []
    i = 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            out.append({"t": "\t", "n": "\n", "\\": "\\"}.get(nxt, nxt))
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def parse_schema_desc(desc: str) -> list[SchemaNode]:
    """Rebuild the top-level fields from the reader's preorder dump."""
    lines = [ln for ln in desc.split("\n") if ln]
    nodes = []
    for ln in lines:
        parts = ln.rsplit("\t", 7)  # name is escaped; split from the right
        nodes.append(SchemaNode(
            name=_unescape_name(parts[0]), num_children=int(parts[1]),
            repetition=int(parts[2]), physical=int(parts[3]),
            converted=int(parts[4]), scale=int(parts[5]),
            precision=int(parts[6]), type_length=int(parts[7]),
        ))
    pos = 0
    leaf_counter = [0]

    def build(def_level: int, rep_level: int) -> SchemaNode:
        nonlocal pos
        node = nodes[pos]
        pos += 1
        if node.repetition != 0:
            def_level += 1
        if node.repetition == 2:
            rep_level += 1
        node.def_level = def_level
        node.rep_level = rep_level
        if node.is_leaf:
            node.leaf_index = leaf_counter[0]
            leaf_counter[0] += 1
        else:
            node.children = [
                build(def_level, rep_level) for _ in range(node.num_children)
            ]
        return node

    top = []
    while pos < len(nodes):
        top.append(build(0, 0))
    return top


def leaves_of(node: SchemaNode) -> list[SchemaNode]:
    if node.is_leaf:
        return [node]
    out = []
    for c in node.children:
        out.extend(leaves_of(c))
    return out


@dataclass
class LeafData:
    """Compact decoded leaf + levels, as copied from the native reader."""

    values: np.ndarray | None          # fixed-width values (n_present,)
    offsets: np.ndarray | None         # BYTE_ARRAY: int32[n_present+1]
    chars: np.ndarray | None
    defs: np.ndarray                   # uint8[n_levels]
    reps: np.ndarray | None            # uint8[n_levels] when max_rep > 0
    dtype: DType                       # mapped leaf dtype


def _expand_leaf(leaf: LeafData, positions_valid: np.ndarray,
                 max_def: int) -> Column:
    """Compact present values -> a full-length leaf column over the level
    positions selected by ``positions_valid`` already restricted to entry
    positions (length = output rows)."""
    n = positions_valid.shape[0]
    validity = jnp.asarray(positions_valid)
    if leaf.dtype.is_string:
        lengths = (leaf.offsets[1:] - leaf.offsets[:-1]) if leaf.offsets is not None else np.zeros(0, np.int32)
        out_len = np.zeros(n, dtype=np.int64)
        out_len[positions_valid] = lengths
        offsets = np.zeros(n + 1, dtype=np.int32)
        np.cumsum(out_len, out=offsets[1:])
        # chars are already in present-row order == output order
        chars = leaf.chars if leaf.chars is not None else np.zeros(0, np.uint8)
        return Column(t.STRING, jnp.asarray(offsets), validity,
                      chars=jnp.asarray(chars))
    storage = leaf.dtype.storage_dtype
    out = np.zeros(n, dtype=storage)
    if leaf.values is not None and leaf.values.size:
        out[positions_valid] = leaf.values
    return Column(leaf.dtype, jnp.asarray(out), validity)


def assemble_struct(node: SchemaNode, leaf_data: dict[int, LeafData]) -> Column:
    """STRUCT (no repeated fields beneath): children share the row count;
    per-level presence comes straight off the def levels."""
    for lf in leaves_of(node):
        if lf.rep_level > 0:
            raise NotImplementedError(
                f"lists inside structs are not supported yet ({lf.name})"
            )
    first = leaf_data[leaves_of(node)[0].leaf_index]
    n = first.defs.shape[0]
    # struct present at a row iff def >= its own def level
    validity = jnp.asarray(first.defs >= node.def_level)

    def build(nd: SchemaNode) -> Column:
        if nd.is_leaf:
            ld = leaf_data[nd.leaf_index]
            present = ld.defs == nd.def_level
            return _expand_leaf(ld, present, nd.def_level)
        kids = [build(c) for c in nd.children]
        ld = leaf_data[leaves_of(nd)[0].leaf_index]
        valid = jnp.asarray(ld.defs >= nd.def_level)
        return Column(DType(TypeId.STRUCT), jnp.zeros((n,), jnp.uint8),
                      valid, children=kids)

    kids = [build(c) for c in node.children]
    return Column(DType(TypeId.STRUCT), jnp.zeros((n,), jnp.uint8),
                  validity, children=kids)


def assemble_list(node: SchemaNode, leaf_data: dict[int, LeafData]) -> Column:
    """Standard 3-level LIST of a primitive/string element."""
    lvs = leaves_of(node)
    if len(lvs) != 1:
        raise NotImplementedError(
            f"only LIST of a single leaf element is supported ({node.name})"
        )
    # the element must BE a leaf, not a single-field struct: walk down the
    # repeated group and require its child to be the leaf itself
    rep_group = node.children[0] if node.children else None
    if rep_group is None or rep_group.repetition != 2:
        raise NotImplementedError(
            f"unrecognized LIST encoding for {node.name}"
        )
    elem_node = rep_group if rep_group.is_leaf else (
        rep_group.children[0] if len(rep_group.children) == 1 else None
    )
    if elem_node is None or not elem_node.is_leaf:
        raise NotImplementedError(
            f"LIST of struct elements is not supported yet ({node.name})"
        )
    elem = lvs[0]
    if elem.rep_level != 1:
        raise NotImplementedError("nested lists are not supported")
    ld = leaf_data[elem.leaf_index]
    defs = ld.defs
    reps = ld.reps
    if reps is None:
        raise ValueError("list leaf decoded without repetition levels")
    # the repeated group sits one def level above the list group
    def_list = node.def_level          # list group present (may be empty)
    def_entry = def_list + 1           # an element slot exists
    row_start = reps == 0              # each top row begins at rep 0
    n_rows = int(row_start.sum())
    row_id = np.cumsum(row_start) - 1
    entry = defs >= def_entry
    counts = np.zeros(n_rows, dtype=np.int64)
    np.add.at(counts, row_id[entry], 1)  # small host op, rows-bounded
    offsets = np.zeros(n_rows + 1, dtype=np.int32)
    np.cumsum(counts, out=offsets[1:])
    # list null iff def < def_list at the row's (single) start entry
    list_valid = jnp.asarray(defs[row_start] >= def_list)
    elem_present = defs[entry] == elem.def_level
    child = _expand_leaf(
        LeafData(ld.values, ld.offsets, ld.chars, defs[entry], None,
                 ld.dtype),
        elem_present, elem.def_level,
    )
    return Column(DType(TypeId.LIST), jnp.asarray(offsets), list_valid,
                  children=[child])
