"""Device-resident column — the ``cudf::column`` / ``ai.rapids.cudf.ColumnVector``
equivalent of the substrate (reference SURVEY.md section 2.2).

A fixed-width column is (data: jnp[n], validity: bool jnp[n] | None).
A string column is (offsets: int32 jnp[n+1], chars: uint8 jnp[m], validity) —
Arrow string layout, consumed by ops.cast_strings.

``validity is None`` means "no null mask allocated — all rows valid", the
same tri-state cuDF uses (null_mask() == nullptr, reference
row_conversion.cu:263-272 special-cases it in the kernel).

Null slots in ``data`` hold unspecified values (cuDF semantics); comparisons
and host materialization always consult validity.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.types import DType, TypeId


@dataclass
class Column:
    dtype: DType
    data: jnp.ndarray
    validity: Optional[jnp.ndarray] = None  # bool[n], True = valid
    # String columns only: data is the int32[n+1] offsets, chars the bytes.
    chars: Optional[jnp.ndarray] = None
    # Nested columns only (cuDF lists_column_view/structs_column_view roles):
    # LIST   -> data = int32[n+1] element offsets, children = [element]
    # STRUCT -> data = uint8[n] placeholder,      children = fields
    children: Optional[list] = None

    def __post_init__(self) -> None:
        if self.validity is not None and self.validity.dtype != jnp.bool_:
            raise TypeError("validity must be bool")
        if self.dtype.type_id == TypeId.LIST:
            if not self.children or len(self.children) != 1:
                raise ValueError("LIST column requires exactly one child")
            if self.data.dtype != jnp.int32:
                raise TypeError("LIST offsets must be int32")
            return
        if self.dtype.type_id == TypeId.STRUCT:
            if not self.children:
                raise ValueError("STRUCT column requires children")
            return
        if self.dtype.is_string:
            if self.chars is None:
                raise ValueError("string column requires chars buffer")
            if self.data.dtype != jnp.int32:
                raise TypeError("string offsets/lengths must be int32")
        elif self.dtype.is_decimal128:
            if self.data.dtype != jnp.int64 or self.data.ndim != 2 \
                    or self.data.shape[-1] != 2:
                raise TypeError(
                    "DECIMAL128 columns store int64[n, 2] limb pairs "
                    "(lo, hi little-endian)"
                )
        elif self.dtype.is_fixed_width:
            expect = self.dtype.jnp_dtype
            if self.data.dtype != expect:
                raise TypeError(
                    f"column data dtype {self.data.dtype} != storage dtype "
                    f"{expect} for {self.dtype}"
                )

    @property
    def is_padded_string(self) -> bool:
        """String column in the padded device layout: data = int32 lengths,
        chars = uint8 (n, W) matrix (ops.strings converts both ways)."""
        return (
            self.dtype.is_string
            and self.chars is not None
            and self.chars.ndim == 2
        )

    @property
    def is_padded_list(self) -> bool:
        """LIST column in the padded wire layout: data = int32 per-row
        LENGTHS and children[0] an (n, L) element matrix with MANDATORY
        (n, L) element validity — the 2-D validity is the layout marker
        (no offsets-layout child carries one; child data shape alone
        would collide with DECIMAL128's (m, 2) limb pairs)."""
        return (
            self.dtype.type_id == TypeId.LIST
            and self.children is not None
            and self.children[0].validity is not None
            and getattr(self.children[0].validity, "ndim", 1) == 2
        )

    @property
    def size(self) -> int:
        if self.dtype.type_id == TypeId.LIST:
            if self.is_padded_list:
                # padded wire layout: data = per-row lengths, not offsets
                return int(self.data.shape[0])
            return int(self.data.shape[0]) - 1
        if self.dtype.is_string and not self.is_padded_string:
            return int(self.data.shape[0]) - 1
        return int(self.data.shape[0])

    @property
    def null_count(self) -> int:
        if self.validity is None:
            return 0
        return int(self.size - jnp.sum(self.validity))

    @property
    def has_nulls(self) -> bool:
        return self.null_count > 0

    def valid_mask(self) -> jnp.ndarray:
        """Validity as a concrete bool[n] (materializes all-true if absent)."""
        if self.validity is not None:
            return self.validity
        return jnp.ones((self.size,), dtype=jnp.bool_)

    # ---- host interop -------------------------------------------------

    @classmethod
    def from_numpy(
        cls,
        values: np.ndarray,
        dtype: Optional[DType] = None,
        validity: Optional[np.ndarray] = None,
    ) -> "Column":
        values = np.asarray(values)
        if dtype is None:
            dtype = DType.from_numpy(values.dtype)
        data = jnp.asarray(values.astype(dtype.storage_dtype, copy=False))
        vmask = None if validity is None else jnp.asarray(validity, dtype=jnp.bool_)
        return cls(dtype, data, vmask)

    @classmethod
    def from_pylist(cls, values: Sequence, dtype: DType) -> "Column":
        """Build from a python list where ``None`` marks nulls — the shape of
        the reference's Table.TestBuilder columns (RowConversionTest.java:30-39)."""
        if dtype.is_string:
            valid = np.array([v is not None for v in values], dtype=bool)
            chunks = [(v.encode() if isinstance(v, str) else (v or b"")) for v in values]
            offsets = np.zeros(len(values) + 1, dtype=np.int32)
            np.cumsum([len(c) for c in chunks], out=offsets[1:])
            chars = np.frombuffer(b"".join(chunks), dtype=np.uint8)
            return cls(
                dtype,
                jnp.asarray(offsets),
                None if valid.all() else jnp.asarray(valid),
                chars=jnp.asarray(chars.copy()),
            )
        valid = np.array([v is not None for v in values], dtype=bool)
        if dtype.is_decimal128:
            limbs = np.zeros((len(values), 2), dtype=np.int64)
            for i, v in enumerate(values):
                if v is None:
                    continue
                limbs[i, 0] = np.int64(np.uint64(int(v) & 0xFFFFFFFFFFFFFFFF))
                limbs[i, 1] = int(v) >> 64
            vmask = None if valid.all() else jnp.asarray(valid)
            return cls(dtype, jnp.asarray(limbs), vmask)
        storage = dtype.storage_dtype
        filled = np.zeros(len(values), dtype=storage)
        for i, v in enumerate(values):
            if v is None:
                continue
            if dtype.type_id == TypeId.BOOL8:
                filled[i] = 1 if v else 0
            else:
                filled[i] = v
        vmask = None if valid.all() else jnp.asarray(valid)
        return cls(dtype, jnp.asarray(filled), vmask)

    def to_numpy(self) -> tuple[np.ndarray, Optional[np.ndarray]]:
        """Return (data, validity) as host arrays; validity None = all valid."""
        data = np.asarray(self.data)
        mask = None if self.validity is None else np.asarray(self.validity)
        return data, mask

    def to_pylist(self) -> list:
        if self.dtype.type_id == TypeId.LIST:
            offsets = np.asarray(self.data)
            child = self.children[0].to_pylist()
            mask = None if self.validity is None else np.asarray(self.validity)
            out = []
            for i in range(self.size):
                if mask is not None and not mask[i]:
                    out.append(None)
                else:
                    out.append(child[offsets[i]:offsets[i + 1]])
            return out
        if self.dtype.type_id == TypeId.STRUCT:
            fields = [c.to_pylist() for c in self.children]
            mask = None if self.validity is None else np.asarray(self.validity)
            out = []
            for i in range(self.size):
                if mask is not None and not mask[i]:
                    out.append(None)
                else:
                    out.append(tuple(f[i] for f in fields))
            return out
        if self.is_padded_string:
            lengths = np.asarray(self.data)
            mat = np.asarray(self.chars)
            mask = None if self.validity is None else np.asarray(self.validity)
            out = []
            for i in range(self.size):
                if mask is not None and not mask[i]:
                    out.append(None)
                else:
                    out.append(mat[i, : lengths[i]].tobytes().decode())
            return out
        if self.dtype.is_string:
            offsets = np.asarray(self.data)
            chars = np.asarray(self.chars).tobytes()
            mask = None if self.validity is None else np.asarray(self.validity)
            out = []
            for i in range(self.size):
                if mask is not None and not mask[i]:
                    out.append(None)
                else:
                    out.append(chars[offsets[i] : offsets[i + 1]].decode())
            return out
        data, mask = self.to_numpy()
        out = []
        for i in range(self.size):
            if mask is not None and not mask[i]:
                out.append(None)
            elif self.dtype.type_id == TypeId.BOOL8:
                out.append(bool(data[i]))
            elif self.dtype.is_decimal128:
                lo = int(np.uint64(data[i, 0]))
                out.append((int(data[i, 1]) << 64) | lo)
            else:
                out.append(data[i].item())
        return out

    # ---- comparison (test oracle) -------------------------------------

    def equals(self, other: "Column") -> bool:
        """Null-aware equality — the AssertUtils.assertTablesAreEqual oracle
        (reference RowConversionTest.java:51)."""
        if self.dtype != other.dtype or self.size != other.size:
            return False
        a_valid = np.asarray(self.valid_mask())
        b_valid = np.asarray(other.valid_mask())
        if not np.array_equal(a_valid, b_valid):
            return False
        if self.dtype.is_string:
            return self.to_pylist() == other.to_pylist()
        a, b = np.asarray(self.data), np.asarray(other.data)
        nan_ok = np.issubdtype(a.dtype, np.floating)
        return bool(np.array_equal(a[a_valid], b[b_valid], equal_nan=nan_ok))

    def __repr__(self) -> str:
        return f"Column({self.dtype}, size={self.size}, nulls={self.null_count})"


def string_column(values: Sequence[Optional[str]]) -> Column:
    return Column.from_pylist(values, t.STRING)
