from spark_rapids_jni_tpu.columnar.column import Column
from spark_rapids_jni_tpu.columnar.table import Table
from spark_rapids_jni_tpu.columnar.bitmask import pack_validity, unpack_validity
from spark_rapids_jni_tpu.columnar import pytree as _pytree  # noqa: F401 (registers)

__all__ = ["Column", "Table", "pack_validity", "unpack_validity"]
