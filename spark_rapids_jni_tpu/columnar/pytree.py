"""Pytree registration for the columnar substrate.

Column and Table become jax pytrees so whole tables flow through ``jit``,
``shard_map`` and the ICI shuffle as first-class arguments — the TPU-native
replacement for the reference's raw ``jlong`` native-view handles crossing
JNI (reference RowConversionJni.cpp:31-36). DType is static aux data (it
participates in the jit cache key exactly like the reference's
``(typeId, scale)`` JNI marshaling, RowConversion.java:113-118).

Unflattening bypasses ``__post_init__`` validation: jax substitutes
non-array placeholders for leaves during tracing/transforms, and the
equal-length / storage-dtype checks only make sense on real arrays.
"""

from __future__ import annotations

import jax

from spark_rapids_jni_tpu.columnar.column import Column
from spark_rapids_jni_tpu.columnar.table import Table


def _column_flatten(col: Column):
    # nested children (LIST/STRUCT) are pytrees themselves — they MUST
    # ride the leaves tuple or jit/shard_map would silently drop a LIST
    # column's child buffer (the dataclass default would resurface as
    # children=None after unflattening)
    return (col.data, col.validity, col.chars, col.children), col.dtype


def _column_unflatten(dtype, children) -> Column:
    data, validity, chars, nested = children
    col = object.__new__(Column)
    col.dtype = dtype
    col.data = data
    col.validity = validity
    col.chars = chars
    col.children = nested
    return col


def _table_flatten(tbl: Table):
    return tuple(tbl.columns), None


def _table_unflatten(_, children) -> Table:
    tbl = object.__new__(Table)
    tbl.columns = list(children)
    return tbl


jax.tree_util.register_pytree_node(Column, _column_flatten, _column_unflatten)
jax.tree_util.register_pytree_node(Table, _table_flatten, _table_unflatten)
