"""Validity bitmask pack/unpack.

Arrow and cuDF keep validity as a packed little-endian bitmask (bit i of word
i//32 set => row i valid; cuDF's ``bitmask_type`` is uint32, see reference
row_conversion.cu:158-165 where a 32-lane ballot writes one mask word).

On TPU we keep validity *unpacked* on device — one bool per row — because the
VPU operates on (8,128) vector registers of elements, not bits; select/where
on a bool vector fuses into adjacent ops for free, while packed bits would
force serializing shift/or chains. Packed form is used only at the host/Arrow
boundary and inside the packed-row format, via the helpers here. Both are
pure XLA (reshape + matmul-free bit ops) so they run on device too.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# numpy constant: folded into traced computations without forcing device
# initialization at import time
_BIT_WEIGHTS = np.asarray([1, 2, 4, 8, 16, 32, 64, 128], dtype=np.uint8)


def pack_bits_last_axis(bits: jnp.ndarray) -> jnp.ndarray:
    """Pack bool[..., k] into uint8[..., ceil(k/8)], bit i%8 of byte i//8
    set <=> bits[..., i]. Trailing pad bits are 0. Shared by the validity
    bitmask (Arrow/cuDF order) and the packed-row validity tail, which use
    the same little-endian-within-byte convention."""
    k = bits.shape[-1]
    n_bytes = (k + 7) // 8
    lead = bits.shape[:-1]
    padded = jnp.zeros((*lead, n_bytes * 8), dtype=jnp.uint8)
    padded = padded.at[..., :k].set(bits.astype(jnp.uint8))
    return (padded.reshape(*lead, n_bytes, 8) * _BIT_WEIGHTS).sum(
        axis=-1, dtype=jnp.uint8
    )


def pack_validity(valid: jnp.ndarray) -> jnp.ndarray:
    """Pack a bool[n] validity vector into a little-endian uint8 bitmask.

    Output length is ceil(n/8); trailing pad bits are 0.
    """
    return pack_bits_last_axis(valid)


def unpack_validity(mask: jnp.ndarray, n: int) -> jnp.ndarray:
    """Unpack a little-endian uint8 bitmask into bool[n]."""
    bits = (mask[:, None] >> np.arange(8, dtype=np.uint8)) & np.uint8(1)
    return bits.reshape(-1)[:n].astype(jnp.bool_)
