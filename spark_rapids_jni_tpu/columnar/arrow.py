"""Arrow interop — the cuDF ``to_arrow``/``from_arrow`` surface (vendored
capability, SURVEY.md section 2.2: cuDF builds against Arrow and converts
both ways). Host-boundary API: pyarrow tables are host data, so these run
outside jit; device columns round-trip through numpy views.

Type mapping is the Spark/cuDF one: Arrow decimal128(p<=18) lands in
DECIMAL64 storage, wider in limb-pair DECIMAL128; date32 ->
TIMESTAMP_DAYS; timestamp(us) -> TIMESTAMP_MICROSECONDS; strings/binary
keep their bytes.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar.column import Column
from spark_rapids_jni_tpu.columnar.table import Table

def from_arrow(table) -> Table:
    """pyarrow.Table -> device Table (one host->device copy per buffer)."""
    import pyarrow as pa

    cols = []
    # positional iteration: duplicate column names (which to_arrow's
    # positional pa.table form deliberately supports) must round-trip —
    # fetching by name would raise or pick the wrong column
    for col_idx in range(table.num_columns):
        arr = table.column(col_idx).combine_chunks()
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.chunk(0) if arr.num_chunks else pa.array(
                [], type=arr.type)
        ty = arr.type
        mask = None if arr.null_count == 0 else np.asarray(
            arr.is_valid())
        if pa.types.is_string(ty) or pa.types.is_large_string(ty) or \
                pa.types.is_binary(ty):
            cols.append(Column.from_pylist(
                [None if v is None else (
                    v.decode("utf-8", "surrogateescape")
                    if isinstance(v, bytes) else v)
                 for v in arr.to_pylist()],
                t.STRING))
            continue
        if pa.types.is_decimal(ty):
            import decimal as _d

            with _d.localcontext(_d.Context(prec=60)):
                vals = [None if v is None else int(v.scaleb(ty.scale))
                        for v in arr.to_pylist()]
            dt = (t.decimal128(-ty.scale) if ty.precision > 18
                  else t.decimal64(-ty.scale))
            cols.append(Column.from_pylist(vals, dt))
            continue
        # Nulls must be filled IN ARROW before the numpy conversion:
        # np.asarray of a null-bearing integer array goes through float64
        # (NaN for nulls), silently corrupting values beyond 2^53. The
        # validity mask was captured above; filled cells are don't-care.
        def _np_exact(a, pa_type):
            import pyarrow.compute as pc

            if a.null_count:
                a = pc.fill_null(a, 0)
            return np.ascontiguousarray(np.asarray(a.cast(pa_type)))

        if pa.types.is_date32(ty):
            cols.append(Column.from_numpy(
                _np_exact(arr, pa.int32()), t.TIMESTAMP_DAYS,
                validity=mask))
            continue
        if pa.types.is_timestamp(ty):
            if ty.unit != "us":
                arr = arr.cast(pa.timestamp("us"))
            cols.append(Column.from_numpy(
                _np_exact(arr, pa.int64()), t.TIMESTAMP_MICROSECONDS,
                validity=mask))
            continue
        cols.append(Column.from_numpy(_np_exact(arr, ty), validity=mask))
    return Table(cols)


def to_arrow(table: Table, names: list[str] | None = None):
    """device Table -> pyarrow.Table (one device->host copy per buffer)."""
    import pyarrow as pa

    arrays, out_names = [], []
    for i, c in enumerate(table.columns):
        name = names[i] if names else f"c{i}"
        out_names.append(name)
        valid = np.asarray(c.valid_mask())
        mask = None if valid.all() else ~valid
        if c.dtype.is_string:
            vals = c.to_pylist()
            arrays.append(pa.array(vals, type=pa.string()))
            continue
        if c.dtype.is_decimal128:
            import decimal as _d

            vals = c.to_pylist()
            with _d.localcontext(_d.Context(prec=60)):
                arrays.append(pa.array(
                    [None if v is None
                     else _d.Decimal(v).scaleb(c.dtype.scale)
                     for v in vals],
                    type=pa.decimal128(38, -c.dtype.scale)))
            continue
        if c.dtype.is_decimal:
            import decimal as _d

            vals = c.to_pylist()
            arrays.append(pa.array(
                [None if v is None else _d.Decimal(v).scaleb(c.dtype.scale)
                 for v in vals],
                type=pa.decimal128(18, -c.dtype.scale)))
            continue
        data = np.asarray(c.data)
        if c.dtype.type_id == t.TypeId.TIMESTAMP_DAYS:
            arrays.append(pa.array(data, type=pa.date32(), from_pandas=False)
                          if mask is None else
                          pa.array(data.astype("datetime64[D]"),
                                   mask=mask))
            continue
        if c.dtype.type_id == t.TypeId.TIMESTAMP_MICROSECONDS:
            arrays.append(pa.array(data.view("datetime64[us]"), mask=mask))
            continue
        arrays.append(pa.array(data, mask=mask))
    # positional form: duplicate caller-supplied names must not drop columns
    return pa.table(arrays, names=out_names)
