"""Device-resident table — the ``cudf::table`` / ``ai.rapids.cudf.Table``
equivalent: an ordered set of equal-length columns.

Unlike the reference, which passes tables across JNI as raw ``jlong`` native
views (reference RowConversionJni.cpp:31-36), on the Python side a Table is a
lightweight pytree of device arrays; the int64-handle model lives in the
native bridge (runtime/handles) for the Java surface.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

from spark_rapids_jni_tpu.columnar.column import Column


@dataclass
class Table:
    columns: list[Column]

    def __post_init__(self) -> None:
        if self.columns:
            n = self.columns[0].size
            for c in self.columns:
                if c.size != n:
                    raise ValueError("all columns in a table must have equal size")

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    @property
    def num_rows(self) -> int:
        return self.columns[0].size if self.columns else 0

    def column(self, i: int) -> Column:
        return self.columns[i]

    def schema(self) -> list:
        return [c.dtype for c in self.columns]

    @classmethod
    def from_pylists(cls, columns: Sequence[tuple[Sequence, object]]) -> "Table":
        """Build from [(values, dtype), ...] — TestBuilder-style."""
        return cls([Column.from_pylist(v, d) for v, d in columns])

    def equals(self, other: "Table") -> bool:
        return self.num_columns == other.num_columns and all(
            a.equals(b) for a, b in zip(self.columns, other.columns)
        )

    def __repr__(self) -> str:
        return f"Table(rows={self.num_rows}, columns={[c.dtype for c in self.columns]})"
