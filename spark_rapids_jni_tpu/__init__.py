"""spark_rapids_jni_tpu — TPU-native columnar acceleration layer for Apache Spark.

A from-scratch, TPU-first rebuild of the capability surface of
``com.nvidia:spark-rapids-jni`` (the native layer of the RAPIDS Accelerator
for Apache Spark): HBM-resident columnar tables, fully vectorized XLA
programs for the JNI-exposed operators (row<->column transpose, casts,
hashing, bloom filters, a vectorized device JSONPath engine) and the cuDF
operator substrate (sort, groupby-aggregate incl. exact DECIMAL128
variance/covariance and percentiles, exact multi-key join across all six
join types, window functions with rolling frames, LIST operators —
explode/collect/array algebra, concatenate/distinct/compaction,
EXCEPT/INTERSECT, reductions, the elementwise SQL family, string
predicates incl. a device byte-DFA regex engine with capture-tracking
regexp_extract/replace, device Unicode case mapping, string transforms
and split, datetime arithmetic — all incl. STRING and DECIMAL128
columns), pure C++ Parquet/ORC read engines, out-of-core chunked
execution under a memory budget with prefetch overlap, an ICI
all-to-all shuffle transport for multi-chip slices, and a host-staged
zstd DCN transport across slices.

Planner layer (ops/planner.py): declared knowledge is the performance
model — key Domains lower groupbys to the sort-free bounded
masked-reduction pass (125x over sort-based grouping at 16M rows on
hardware), dense clustered primary keys collapse joins to arithmetic +
gather (whole TPC-H queries compile sort-free), dense-id counts put
mid-cardinality groupbys on a blocked one-hot path, and exact rewrites
(q64's count-product join elimination) remove joins outright; every
declaration is runtime-verified (domain_miss / pk_violation) so a lie
re-plans instead of corrupting. Distributed, the bounded plans merge
with m-row collectives instead of row shuffles (zero-shuffle q72,
one-exchange broadcast q3).

Pallas posture: the shipped hot paths are XLA-emitted (the measured hot
spots are layout transforms, scans, sorts, and gathers the compiler
already fuses; scatter-heavy forms were redesigned scatter-free —
BASELINE.md); one experimental Pallas kernel (ops/pallas_q1.py) probes
the residual headroom.

Layer map (TPU equivalent of reference SURVEY.md section 1):
  L4' Java API parity sources  -> java/ (build-gated; no JVM in this image)
  L3' native bridge            -> src/native C API via ctypes (JNI-compatible
                                  handle model: objects cross as int64 handles)
  L2' operator layer           -> spark_rapids_jni_tpu.ops
  L1' columnar substrate       -> spark_rapids_jni_tpu.columnar
  L0' device/runtime           -> JAX/XLA on TPU (+ runtime/ arena & handles)

The whole package requires 64-bit dtypes (int64 columns, decimal64, xxhash64)
so jax x64 mode is enabled at import, before any jax array is created.
Opt out with SPARK_RAPIDS_TPU_NO_X64=1 (not recommended).
"""

import os as _os

# INVARIANT (tests/test_import_hygiene.py): importing this package must not
# initialize any jax backend — only config updates. Callers pin the platform
# (utils.platform.force_cpu_platform) AFTER importing us; a module-level
# array/device query anywhere in the import graph would break that.
if not _os.environ.get("SPARK_RAPIDS_TPU_NO_X64"):
    import jax as _jax

    _jax.config.update("jax_enable_x64", True)

from spark_rapids_jni_tpu.types import DType, TypeId  # noqa: E402
from spark_rapids_jni_tpu.columnar import Column, Table  # noqa: E402

__version__ = "0.1.0"

__all__ = ["DType", "TypeId", "Column", "Table", "__version__"]
