from spark_rapids_jni_tpu.models.tpch import lineitem_table, tpch_q1

__all__ = ["lineitem_table", "tpch_q1"]
