"""TPC-DS workload pipelines — the join-heavy model family (BASELINE.json
config #4: "hash-join + Parquet chunked reader (TPC-DS q64/q72)").

These are structurally faithful, predicate-trimmed versions of the two
headline queries: the join graphs and aggregation shapes match the spec
queries, while the long tails of scalar predicates (promo windows,
demographics buckets, address joins) are trimmed so the pipelines stay
readable. What each exercises:

  q72-style: fact x dimension chain — catalog_sales |x| date_dim (year
  filter) |x| item |x| inventory on a composite (item, week) key with an
  inequality post-filter (inv_quantity_on_hand < cs_quantity), then
  group-count per item. The composite key is packed exactly
  (item_sk * WEEKS + week) rather than hashed, so no collision handling.

  q64-style: self-join — store_sales(year1) |x| store_sales(year2) on a
  composite (item, customer) key (customers who bought the same item in
  two consecutive years), then group-count per item.

All joins use the masking idiom for filters: a WHERE clause before a join
nulls the join key (null keys never match, ops/join.py); a WHERE after a
join nulls validity so the row falls out of the aggregate. Shapes stay
static throughout — the XLA discipline.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.groupby import GroupByResult, groupby_aggregate
from spark_rapids_jni_tpu.ops.sort import sort_table
from spark_rapids_jni_tpu.runtime import fusion
from spark_rapids_jni_tpu.utils.tracing import func_range

# Composite-key packing bounds (data generators respect these).
MAX_WEEKS = 512
MAX_CUSTOMERS = 1 << 20


# ---- synthetic data (TPC-DS-flavored distributions) ------------------------


def date_dim_table(num_days: int = 730, start_year: int = 2000) -> Table:
    """d_date_sk, d_week_seq, d_year."""
    sk = np.arange(1, num_days + 1, dtype=np.int64)
    week = ((sk - 1) // 7 + 1).astype(np.int64)
    year = (start_year + (sk - 1) // 365).astype(np.int32)
    return Table(
        [
            Column.from_numpy(sk, t.INT64),
            Column.from_numpy(week, t.INT64),
            Column.from_numpy(year, t.INT32),
        ]
    )


D_DATE_SK, D_WEEK_SEQ, D_YEAR = 0, 1, 2


def item_table(num_items: int = 1000, seed: int = 0) -> Table:
    """i_item_sk, i_brand_id, i_category_id."""
    rng = np.random.default_rng(seed)
    sk = np.arange(1, num_items + 1, dtype=np.int64)
    brand = rng.integers(1, 100, num_items).astype(np.int32)
    cat = rng.integers(1, 11, num_items).astype(np.int32)
    return Table(
        [
            Column.from_numpy(sk, t.INT64),
            Column.from_numpy(brand, t.INT32),
            Column.from_numpy(cat, t.INT32),
        ]
    )


I_ITEM_SK, I_BRAND_ID, I_CATEGORY_ID = 0, 1, 2


def catalog_sales_table(
    num_rows: int, num_items: int = 1000, num_days: int = 730, seed: int = 1
) -> Table:
    """cs_item_sk, cs_sold_date_sk, cs_quantity, cs_order_number."""
    rng = np.random.default_rng(seed)
    item = rng.integers(1, num_items + 1, num_rows).astype(np.int64)
    date = rng.integers(1, num_days + 1, num_rows).astype(np.int64)
    qty = rng.integers(1, 100, num_rows).astype(np.int64)
    order = np.arange(num_rows, dtype=np.int64)
    return Table(
        [
            Column.from_numpy(item, t.INT64),
            Column.from_numpy(date, t.INT64),
            Column.from_numpy(qty, t.INT64),
            Column.from_numpy(order, t.INT64),
        ]
    )


CS_ITEM_SK, CS_SOLD_DATE_SK, CS_QUANTITY, CS_ORDER_NUMBER = 0, 1, 2, 3


def inventory_table(
    num_items: int = 1000, num_weeks: int = 105, seed: int = 2
) -> Table:
    """inv_item_sk, inv_week_seq, inv_quantity_on_hand — one row per
    (item, week), the TPC-DS inventory grain at one warehouse."""
    rng = np.random.default_rng(seed)
    item = np.repeat(np.arange(1, num_items + 1, dtype=np.int64), num_weeks)
    week = np.tile(np.arange(1, num_weeks + 1, dtype=np.int64), num_items)
    qty = rng.integers(0, 120, num_items * num_weeks).astype(np.int64)
    return Table(
        [
            Column.from_numpy(item, t.INT64),
            Column.from_numpy(week, t.INT64),
            Column.from_numpy(qty, t.INT64),
        ]
    )


INV_ITEM_SK, INV_WEEK_SEQ, INV_QTY = 0, 1, 2


def store_sales_table(
    num_rows: int,
    num_items: int = 1000,
    num_customers: int = 5000,
    num_days: int = 730,
    seed: int = 3,
) -> Table:
    """ss_item_sk, ss_customer_sk, ss_sold_date_sk."""
    rng = np.random.default_rng(seed)
    item = rng.integers(1, num_items + 1, num_rows).astype(np.int64)
    cust = rng.integers(1, num_customers + 1, num_rows).astype(np.int64)
    date = rng.integers(1, num_days + 1, num_rows).astype(np.int64)
    return Table(
        [
            Column.from_numpy(item, t.INT64),
            Column.from_numpy(cust, t.INT64),
            Column.from_numpy(date, t.INT64),
        ]
    )


SS_ITEM_SK, SS_CUSTOMER_SK, SS_SOLD_DATE_SK = 0, 1, 2


def _pack_key(a: Column, b: Column, b_bound: int) -> Column:
    """Exact composite int64 key a*b_bound + b; null if either side null."""
    data = a.data * jnp.int64(b_bound) + b.data
    return Column(t.INT64, data, a.valid_mask() & b.valid_mask())


def _null_keys_where(col: Column, drop: jnp.ndarray) -> Column:
    """WHERE-before-join: null out the join key where `drop` (null keys
    never match)."""
    return Column(col.dtype, col.data, col.valid_mask() & ~drop)


# ---- q72-style -------------------------------------------------------------


def _q72_dd_fn(date_dim: Table, year: int) -> Table:
    """date_dim build side with WHERE d_year = year pushed into the key
    (wrong-year dates get null keys and never match)."""
    dd_key = _null_keys_where(
        date_dim.column(D_DATE_SK),
        jnp.asarray(np.int32(year)) != date_dim.column(D_YEAR).data,
    )
    return Table([dd_key, date_dim.column(D_WEEK_SEQ)])


def _q72_probe_fn(j2: Table) -> Table:
    """sales x dates x items -> the composite (item, week) probe against
    the inventory grain: [key, cs_item, cs_qty, i_item_sk, i_brand_id]."""
    # j2: [cs_item, cs_date, cs_qty, cs_order, d_date_sk, d_week_seq,
    #      i_item_sk, i_brand_id, i_category_id]
    probe_key = _pack_key(
        Column(t.INT64, j2.column(0).data, j2.column(0).valid_mask()),
        Column(t.INT64, j2.column(5).data, j2.column(5).valid_mask()),
        MAX_WEEKS,
    )
    return Table([probe_key] + [j2.column(i) for i in (0, 2, 6, 7)])


def _q72_inv_fn(inventory: Table) -> Table:
    """Inventory keyed by the packed (item, week) composite."""
    inv_key = _pack_key(
        inventory.column(INV_ITEM_SK), inventory.column(INV_WEEK_SEQ),
        MAX_WEEKS,
    )
    return Table([inv_key, inventory.column(INV_QTY)])


def _q72_keyed_fn(j3: Table) -> Table:
    """WHERE inv_quantity_on_hand < cs_quantity, after the join."""
    # j3: [key, cs_item, cs_qty, i_item_sk, i_brand, inv_key, inv_qty]
    short = j3.column(6).data < j3.column(2).data
    keep = j3.column(6).valid_mask() & j3.column(2).valid_mask() & short
    return Table(
        [
            _null_keys_where(j3.column(3), ~keep),
            _null_keys_where(j3.column(4), ~keep),
            Column(t.INT64, j3.column(1).data, keep),
        ]
    )


def _q72_plan(year: int, out_factor: int) -> fusion.Plan:
    """q72 as ONE fused region: three joins + post-filter + group-count +
    order-by (the staged path compiled each join and the groupby/sort as
    separate executables)."""
    cs = fusion.Scan("catalog_sales")
    dd = fusion.Project(fusion.Scan("date_dim"), _q72_dd_fn, (year,))
    j1 = fusion.Join(cs, dd, (CS_SOLD_DATE_SK,), (0,),
                     fusion.rows_of("catalog_sales"), label="join1")
    j2 = fusion.Join(j1, fusion.Scan("item"), (0,), (I_ITEM_SK,),
                     fusion.rows_of("catalog_sales"), label="join2")
    probe = fusion.Project(j2, _q72_probe_fn)
    inv = fusion.Project(fusion.Scan("inventory"), _q72_inv_fn)
    j3 = fusion.Join(probe, inv, (0,), (0,),
                     fusion.rows_of("catalog_sales", out_factor),
                     label="join3")
    g = fusion.GroupBy(fusion.Project(j3, _q72_keyed_fn), (0, 1),
                       ((2, "count"),), label="groupby")
    # ORDER BY count desc, item asc — q72's shape
    return fusion.Plan("tpcds_q72", fusion.Sort(
        g, (2, 0), ascending=(False, True), nulls_first=(False, False)))


@func_range("tpcds_q72")
def tpcds_q72(
    catalog_sales: Table,
    date_dim: Table,
    item: Table,
    inventory: Table,
    year: int = 2000,
    out_factor: int = 2,
) -> GroupByResult:
    """Count, per item, catalog sales in `year` where on-hand inventory in
    the sale's week was below the ordered quantity (the q72 core: does the
    warehouse run short). Returns groups (i_item_sk, i_brand_id, count)
    padded; callers compact() on host."""
    res = fusion.execute(
        _q72_plan(year, out_factor),
        {"catalog_sales": catalog_sales, "date_dim": date_dim,
         "item": item, "inventory": inventory})
    return GroupByResult(res.table, res.meta["groupby.num_groups"])


class Q72PlannedResult(NamedTuple):
    table: "Table"            # [i_item_sk, i_brand_id, count], count desc
    present: jnp.ndarray      # bool[num_items] — item had short sales
    pk_violation: jnp.ndarray


@func_range("tpcds_q72_planned")
def tpcds_q72_planned(
    catalog_sales: Table,
    date_dim: Table,
    item: Table,
    inventory: Table,
    year: int = 2000,
) -> Q72PlannedResult:
    """q72 with every n-sized stage on planner-declared fast paths:

    * all three joins are dense clustered PK lookups (d_date_sk and
      i_item_sk are 1..N load-ordered; the inventory grain is a dense
      (item, week) grid, so its row index is pure arithmetic
      ``(item-1)*num_weeks + (week-1)``) — arithmetic + gather, zero
      sorts, probe-aligned outputs (no join-maps, no capacity);
    * the GROUP BY item is a dense-id COUNT (``dense_id_counts``) — the
      key IS the slot, no sort, no scatter;
    * brands attach by one static gather against the clustered item
      table; only the final ORDER BY count runs a sort, over num_items
      rows instead of n.

    The declarations are verified (pk_violation) — on the synthetic
    generators they hold by construction; a real loader asserts them
    from load order + PK constraints.
    """
    from spark_rapids_jni_tpu.ops.planner import (
        dense_id_counts,
        dense_pk_join,
    )

    num_days = date_dim.num_rows
    num_items = item.num_rows
    if inventory.num_rows % num_items:
        raise ValueError(
            "inventory is not a dense (item, week) grid — use tpcds_q72")
    num_weeks = inventory.num_rows // num_items

    # join 1: sale -> its date row (clustered d_date_sk), year filter
    # pushed into the build key (the general plan's own idiom)
    dd_key = _null_keys_where(
        date_dim.column(D_DATE_SK),
        jnp.asarray(np.int32(year)) != date_dim.column(D_YEAR).data,
    )
    dd = Table([dd_key, date_dim.column(D_WEEK_SEQ)])
    j1 = dense_pk_join(catalog_sales, dd, CS_SOLD_DATE_SK, 0,
                       1, num_days, clustered=True)
    # j1: [cs_item, cs_date, cs_qty, cs_order, d_date_sk, d_week_seq]
    m1 = j1.matched

    # join 2: sale -> its item row (clustered i_item_sk)
    j2 = dense_pk_join(j1.table, item, CS_ITEM_SK, I_ITEM_SK,
                       1, num_items, clustered=True)
    # j2: [...j1..., i_item_sk, i_brand_id, i_category_id]
    m2 = j2.matched

    # join 3: (item, week) -> the inventory grid row, purely arithmetic
    # (a direct index gather — there is no key column to search at all;
    # the grid contract is verified against the landed item/week below)
    cs_item = j2.table.column(0)
    week = j2.table.column(5)
    grid = ((cs_item.data - 1) * num_weeks
            + (week.data.astype(cs_item.data.dtype) - 1))
    week_ok = (week.data >= 1) & (week.data <= num_weeks)
    in_grid = (m1 & m2 & cs_item.valid_mask() & week.valid_mask()
               & week_ok & (grid >= 0)
               & (grid < inventory.num_rows))
    pos = jnp.clip(grid, 0, inventory.num_rows - 1).astype(jnp.int32)
    inv_item_at = inventory.column(INV_ITEM_SK).data[pos]
    inv_week_at = inventory.column(INV_WEEK_SEQ).data[pos]
    inv_qty_c = inventory.column(INV_QTY)
    inv_qty_at = inv_qty_c.data[pos]
    inv_qty_ok = inv_qty_c.valid_mask()[pos] & in_grid
    # grid-contract verification: the landed inventory row must be the
    # (item, week) the probe meant (a non-grid layout would alias)
    grid_lie = jnp.any(
        in_grid & ((inv_item_at != cs_item.data)
                   | (inv_week_at != week.data.astype(jnp.int64))))

    qty = j2.table.column(CS_QUANTITY)
    short = (inv_qty_ok & qty.valid_mask() & (inv_qty_at < qty.data))
    gid = jnp.where(short, cs_item.data - 1,
                    jnp.int64(num_items)).astype(jnp.int32)
    counts = dense_id_counts(gid, num_items)
    present = counts > 0

    # static keys + brand via one clustered gather over the item table
    item_sk = jnp.arange(1, num_items + 1, dtype=jnp.int64)
    brand_c = item.column(I_BRAND_ID)
    brands = brand_c.data
    out = Table([
        Column(t.INT64, item_sk, present),
        Column(brand_c.dtype, brands, brand_c.valid_mask() & present),
        Column(t.INT64, counts, present),
    ])
    srt = sort_table(out, [2, 0], ascending=[False, True],
                     nulls_first=[False, False])
    return Q72PlannedResult(
        srt, present,
        j1.pk_violation | j2.pk_violation | grid_lie)


def tpcds_q72_numpy(
    catalog_sales: Table, date_dim: Table, item: Table, inventory: Table,
    year: int = 2000,
) -> dict:
    """Host oracle: {(item_sk, brand_id): count}."""
    cs_item = np.asarray(catalog_sales.column(CS_ITEM_SK).data)
    cs_date = np.asarray(catalog_sales.column(CS_SOLD_DATE_SK).data)
    cs_qty = np.asarray(catalog_sales.column(CS_QUANTITY).data)
    d_sk = np.asarray(date_dim.column(D_DATE_SK).data)
    d_week = np.asarray(date_dim.column(D_WEEK_SEQ).data)
    d_year = np.asarray(date_dim.column(D_YEAR).data)
    i_sk = np.asarray(item.column(I_ITEM_SK).data)
    i_brand = np.asarray(item.column(I_BRAND_ID).data)
    inv_item = np.asarray(inventory.column(INV_ITEM_SK).data)
    inv_week = np.asarray(inventory.column(INV_WEEK_SEQ).data)
    inv_qty = np.asarray(inventory.column(INV_QTY).data)

    week_of_date = dict(zip(d_sk[d_year == year], d_week[d_year == year]))
    brand_of_item = dict(zip(i_sk, i_brand))
    onhand = dict(zip(zip(inv_item, inv_week), inv_qty))
    out: dict = {}
    for k in range(len(cs_item)):
        wk = week_of_date.get(cs_date[k])
        if wk is None:
            continue
        br = brand_of_item.get(cs_item[k])
        if br is None:
            continue
        oh = onhand.get((cs_item[k], wk))
        if oh is None or not (oh < cs_qty[k]):
            continue
        key = (int(cs_item[k]), int(br))
        out[key] = out.get(key, 0) + 1
    return out


def _compact_valid_keys(result: Table, num_key_cols: int,
                        order_keys, ascending) -> Table:
    """Drop the shuffle's phantom null-key group(s) from a collected
    result and apply the final ORDER BY — shared tail of the distributed
    q72/q64 plans."""
    keys_valid = np.asarray(result.column(0).valid_mask()).copy()
    for k in range(1, num_key_cols):
        keys_valid &= np.asarray(result.column(k).valid_mask())
    cols = [
        Column(c.dtype, jnp.asarray(np.asarray(c.data)[keys_valid]),
               jnp.asarray(np.asarray(c.valid_mask())[keys_valid]))
        for c in result.columns
    ]
    return sort_table(Table(cols), order_keys, ascending=ascending,
                      nulls_first=[False] * len(order_keys))


# ---- distributed q72 (broadcast-join plan) ---------------------------------

# padded groupby outputs shuffle under a static per-device group budget;
# the item dimension bounds distinct (item, brand) groups
_Q72_GROUP_BUDGET = 4096


def tpcds_q72_distributed(
    catalog_sales: Table,
    date_dim: Table,
    item: Table,
    inventory: Table,
    mesh,
    year: int = 2000,
    out_factor: int = 2,
    group_budget: int = _Q72_GROUP_BUDGET,
) -> Table:
    """Multi-executor q72 with Spark's broadcast-join plan: the fact table
    shards row-wise over the mesh, the three dimension tables replicate to
    every device (they are small — the broadcast side of a broadcast hash
    join), each executor runs the whole join chain + partial group-count
    locally, and the partial counts merge through the ICI shuffle exactly
    like distributed q1. Returns the compacted global (item, brand, count)
    table, count-desc/item-asc ordered."""
    import jax as _jax
    from jax.sharding import PartitionSpec as P

    from spark_rapids_jni_tpu.parallel.distributed import (
        _mesh_fingerprint,
        collect,
        head_table,
        shard_table,
    )
    from spark_rapids_jni_tpu.parallel.mesh import EXEC_AXIS
    from spark_rapids_jni_tpu.parallel.shuffle import hash_shuffle
    from spark_rapids_jni_tpu.runtime import dispatch

    sharded = shard_table(catalog_sales, mesh)

    def step(local_cs: Table, dd: Table, it: Table, inv: Table):
        # padding rows carry null join keys (shard_table nulls validity),
        # so they fall out of the first join and never reach the count
        partial = tpcds_q72(local_cs, dd, it, inv, year=year,
                            out_factor=out_factor)
        pt = head_table(
            partial.table, min(group_budget, partial.table.num_rows)
        )
        sh = hash_shuffle(pt, [0, 1], EXEC_AXIS, capacity=pt.num_rows)
        merged = groupby_aggregate(sh.table, keys=[0, 1], aggs=[(2, "sum")])
        return (merged.table, merged.num_groups.reshape(1),
                partial.num_groups.reshape(1))

    out, num_groups, partial_groups = dispatch.sharded_call(
        "tpcds_q72_distributed.step",
        lambda: _jax.shard_map(
            step, mesh=mesh,
            in_specs=(P(EXEC_AXIS), P(), P(), P()),
            out_specs=(P(EXEC_AXIS), P(EXEC_AXIS), P(EXEC_AXIS)),
        ),
        (sharded, date_dim, item, inventory),
        statics=(year, out_factor, group_budget, _mesh_fingerprint(mesh)),
    )
    if int(np.max(np.asarray(partial_groups))) > group_budget:
        raise ValueError(
            "per-device q72 group count exceeded the shuffle budget "
            f"({group_budget}); pass a larger group_budget"
        )
    result = collect(out, num_groups, mesh)
    return _compact_valid_keys(result, 2, [2, 0], [False, True])


# ---- cluster q72 (cross-host fan-out with runtime-filter pushdown) ---------


def _q72_partial_plan(year: int, out_factor: int,
                      rtf=None) -> fusion.Plan:
    """Per-shard q72 partial: the full join chain + group-count over one
    catalog_sales shard, NO final sort (the router merges and orders).

    ``rtf`` is an optional ``(num_bits, num_hashes)`` pair: when set, the
    shard's fact scan is wrapped in a ``BloomProbe`` against the packed
    bloom bits the router shipped inline under the ``rtf_bits`` binding
    (built from join1's date_dim build keys), so every host prunes its
    own shard locally before the join chain runs. Null-key rows never
    match join1 anyway, so the partial stays bit-identical with the
    filter on or off. The geometry is part of the plan fingerprint, so
    filtered and unfiltered partials never alias in any cache."""
    cs = fusion.Scan("catalog_sales")
    if rtf is not None:
        cs = fusion.BloomProbe(
            cs, fusion.Scan("rtf_bits", bucket=False), CS_SOLD_DATE_SK,
            int(rtf[0]), int(rtf[1]), packed=True, label="rtf_join1")
    dd = fusion.Project(fusion.Scan("date_dim"), _q72_dd_fn, (year,))
    j1 = fusion.Join(cs, dd, (CS_SOLD_DATE_SK,), (0,),
                     fusion.rows_of("catalog_sales"), label="join1")
    j2 = fusion.Join(j1, fusion.Scan("item"), (0,), (I_ITEM_SK,),
                     fusion.rows_of("catalog_sales"), label="join2")
    probe = fusion.Project(j2, _q72_probe_fn)
    inv = fusion.Project(fusion.Scan("inventory"), _q72_inv_fn)
    j3 = fusion.Join(probe, inv, (0,), (0,),
                     fusion.rows_of("catalog_sales", out_factor),
                     label="join3")
    g = fusion.GroupBy(fusion.Project(j3, _q72_keyed_fn), (0, 1),
                       ((2, "count"),), label="partial")
    return fusion.Plan("tpcds_q72_partial", g)


def tpcds_q72_cluster(
    c,
    session_id: str,
    date_dim: Table,
    item: Table,
    inventory: Table,
    year: int = 2000,
    out_factor: int = 2,
    deadline_ms=None,
    merge_timeout_s: float = 300.0,
) -> Table:
    """q72 over a cross-host cluster: catalog_sales is registered and
    hash-sharded across the mesh hosts (``c.register_table``), the three
    dimension tables broadcast inline on each submit frame, and every
    host runs ``_q72_partial_plan`` over its resident shard. The router
    merges the partials (concat -> regroup-sum -> compact -> order).

    Runtime-filter pushdown: the router asks ``rtfilter.decide`` whether
    join1's build side (date_dim, year-filtered) is worth a bloom
    filter. On apply it builds the filter ONCE router-side, serializes
    it via ``to_packed`` into the ``rtf_bits`` binding (sealed DCN
    wire), and the per-shard plan probes it so each host prunes fact
    rows that cannot match any in-year date before the join chain —
    rows-scanned drops shard-locally without a second fan-out round."""
    from spark_rapids_jni_tpu.ops.table_ops import concatenate, trim_table
    from spark_rapids_jni_tpu.runtime import rtfilter

    decision = rtfilter.decide("tpcds_q72_cluster", "join1",
                               date_dim.num_rows)
    bindings = {"date_dim": date_dim, "item": item, "inventory": inventory}
    rtf = None
    if decision.apply:
        # Build keys = date_dim PKs with wrong-year rows nulled; the
        # bloom set is exactly join1's match set.
        dk = _q72_dd_fn(date_dim, year).column(0)
        bf = rtfilter.build_filter(dk.data, dk.valid_mask(),
                                   expected_items=date_dim.num_rows)
        bindings["rtf_bits"] = rtfilter.packed_table(bf)
        rtf = (bf.num_bits, bf.num_hashes)

    def merge_fn(partials):
        parts = [
            trim_table(p.table,
                       int(np.asarray(p.meta["partial.num_groups"])))
            for p in partials
        ]
        merged = groupby_aggregate(concatenate(parts), keys=[0, 1],
                                   aggs=[(2, "sum")])
        out = trim_table(merged.table, int(np.asarray(merged.num_groups)))
        return _compact_valid_keys(out, 2, [2, 0], [False, True])

    mt = c.submit_merge(session_id,
                        _q72_partial_plan(year, out_factor, rtf=rtf),
                        merge_fn, table="catalog_sales",
                        binding="catalog_sales", bindings=bindings,
                        deadline_ms=deadline_ms)
    return mt.result(timeout=merge_timeout_s)


# ---- q64-style -------------------------------------------------------------


def _q64_year_slice(store_sales: Table, year: int, num_days_per_year: int,
                    base_year: int, keep_item: bool) -> Table:
    """One side of the cross-year self-join: the packed (item, customer)
    composite key, nulled outside ``year``."""
    date = store_sales.column(SS_SOLD_DATE_SK).data
    yr = (date - 1) // jnp.int64(num_days_per_year)
    key = _pack_key(
        store_sales.column(SS_ITEM_SK), store_sales.column(SS_CUSTOMER_SK),
        MAX_CUSTOMERS,
    )
    cols = [_null_keys_where(key, yr != (year - base_year))]
    if keep_item:
        cols.append(store_sales.column(SS_ITEM_SK))
    return Table(cols)


def _q64_left_fn(store_sales: Table, year1: int, num_days_per_year: int,
                 base_year: int) -> Table:
    return _q64_year_slice(store_sales, year1, num_days_per_year,
                           base_year, keep_item=True)


def _q64_right_fn(store_sales: Table, year2: int, num_days_per_year: int,
                  base_year: int) -> Table:
    return _q64_year_slice(store_sales, year2, num_days_per_year,
                           base_year, keep_item=False)


def _q64_keyed_fn(joined: Table) -> Table:
    # joined: [key_y1, ss_item, key_y2]; matched rows = repeat purchases
    keep = joined.column(2).valid_mask()
    return Table(
        [
            _null_keys_where(joined.column(1), ~keep),
            Column(t.INT64, joined.column(0).data, keep),
        ]
    )


def _q64_plan(year1: int, year2: int, num_days_per_year: int,
              base_year: int, out_factor: int) -> fusion.Plan:
    """q64's cross-year self-join as one fused region. Both Projects hang
    off the SAME Scan node — the store_sales table binds (and buckets)
    once, which the staged path could not express."""
    ss = fusion.Scan("store_sales")
    left = fusion.Project(ss, _q64_left_fn,
                          (year1, num_days_per_year, base_year))
    right = fusion.Project(ss, _q64_right_fn,
                           (year2, num_days_per_year, base_year))
    j = fusion.Join(left, right, (0,), (0,),
                    fusion.rows_of("store_sales", out_factor), label="join")
    g = fusion.GroupBy(fusion.Project(j, _q64_keyed_fn), (0,),
                       ((1, "count"),), label="groupby")
    return fusion.Plan("tpcds_q64", fusion.Sort(
        g, (1, 0), ascending=(False, True), nulls_first=(False, False)))


class Q64Result(NamedTuple):
    result: GroupByResult
    join_total: jnp.ndarray  # true self-join match count (scalar)
    out_size: int            # static cap — if join_total > out_size the
                             # join truncated and counts are unreliable


@func_range("tpcds_q64")
def tpcds_q64(
    store_sales: Table,
    year1: int = 2000,
    year2: int = 2001,
    num_days_per_year: int = 365,
    base_year: int = 2000,
    out_factor: int = 4,
) -> Q64Result:
    """Count, per item, (year1 purchase, year2 purchase) pairs by the same
    customer (q64's cross-year self-join core). Groups are
    (ss_item_sk, count), padded. ``base_year`` anchors the generator's
    date_sk=1 (store_sales_table emits days 1..num_days); check
    ``join_total <= out_size`` on host — duplicate (item, customer) pairs
    multiply, so the self-join is not structurally bounded."""
    res = fusion.execute(
        _q64_plan(year1, year2, num_days_per_year, base_year, out_factor),
        {"store_sales": store_sales})
    return Q64Result(
        GroupByResult(res.table, res.meta["groupby.num_groups"]),
        res.meta["join.total"], store_sales.num_rows * out_factor,
    )


def tpcds_q64_distributed(
    store_sales: Table,
    mesh,
    year1: int = 2000,
    year2: int = 2001,
    num_days_per_year: int = 365,
    base_year: int = 2000,
    out_factor: int = 4,
    group_budget: int = _Q72_GROUP_BUDGET,
) -> Table:
    """Multi-executor q64: the cross-year self-join is big x big, so it
    takes the REPARTITIONED plan (unlike q72's broadcast): both year-slices
    exchange rows by composite-key hash over ICI (distributed_join), equal
    keys co-locate, each device joins and partial-counts locally, and
    partial counts merge through a second shuffle. Returns the compacted
    global (item, count) table, count-desc/item-asc."""
    import jax as _jax
    from jax.sharding import PartitionSpec as P

    from spark_rapids_jni_tpu.parallel.distributed import (
        _mesh_fingerprint,
        collect,
        distributed_join,
        head_table,
        shard_table,
    )
    from spark_rapids_jni_tpu.parallel.mesh import EXEC_AXIS
    from spark_rapids_jni_tpu.parallel.shuffle import hash_shuffle
    from spark_rapids_jni_tpu.runtime import dispatch

    n = store_sales.num_rows
    date = np.asarray(store_sales.column(SS_SOLD_DATE_SK).data)
    yr = (date - 1) // num_days_per_year

    key = _pack_key(
        store_sales.column(SS_ITEM_SK), store_sales.column(SS_CUSTOMER_SK),
        MAX_CUSTOMERS,
    )
    left = Table([
        _null_keys_where(key, jnp.asarray(yr != (year1 - base_year))),
        store_sales.column(SS_ITEM_SK),
    ])
    right = Table([
        _null_keys_where(key, jnp.asarray(yr != (year2 - base_year))),
    ])
    sl, lrv = shard_table(left, mesh, return_row_valid=True)
    sr, rrv = shard_table(right, mesh, return_row_valid=True)
    d = mesh.shape[EXEC_AXIS]
    out_cap = max(1, n * out_factor // max(d // 2, 1))
    res = distributed_join(
        sl, sr, 0, 0, mesh,
        out_size_per_device=out_cap,
        left_capacity=max(1, n // d * 2), right_capacity=max(1, n // d * 2),
        left_row_valid=lrv, right_row_valid=rrv,
    )
    if np.asarray(res.overflowed).any():
        raise ValueError("q64 join shuffle overflowed; raise capacities")
    if int(np.max(np.asarray(res.total))) > out_cap:
        raise ValueError(
            "q64 device-local join output exceeded out_size_per_device "
            f"({out_cap}); raise out_factor (counts would silently truncate)"
        )

    def count_step(joined: Table):
        # joined: [key_y1, ss_item, key_y2]; matched rows = repeat buys
        keep = joined.column(2).valid_mask()
        keyed = Table([
            _null_keys_where(joined.column(1), ~keep),
            Column(t.INT64, joined.column(0).data, keep),
        ])
        partial = groupby_aggregate(keyed, keys=[0], aggs=[(1, "count")])
        pt = head_table(
            partial.table, min(group_budget, partial.table.num_rows)
        )
        sh = hash_shuffle(pt, [0], EXEC_AXIS, capacity=pt.num_rows)
        merged = groupby_aggregate(sh.table, keys=[0], aggs=[(1, "sum")])
        return (merged.table, merged.num_groups.reshape(1),
                partial.num_groups.reshape(1))

    out, num_groups, partial_groups = dispatch.sharded_call(
        "tpcds_q64_distributed.count_step",
        lambda: _jax.shard_map(
            count_step, mesh=mesh, in_specs=(P(EXEC_AXIS),),
            out_specs=(P(EXEC_AXIS),) * 3,
        ),
        (res.table,),
        statics=(group_budget, _mesh_fingerprint(mesh)),
    )
    if int(np.max(np.asarray(partial_groups))) > group_budget:
        raise ValueError(
            "per-device q64 group count exceeded the shuffle budget "
            f"({group_budget}); pass a larger group_budget"
        )
    result = collect(out, num_groups, mesh)
    return _compact_valid_keys(result, 1, [1, 0], [False, True])


@func_range("tpcds_q72_planned_distributed")
def tpcds_q72_planned_distributed(
    catalog_sales: Table,
    date_dim: Table,
    item: Table,
    inventory: Table,
    mesh,
    year: int = 2000,
):
    """Multi-executor planned q72 with ZERO shuffles: catalog_sales
    shards row-wise, the three dimension tables replicate (the
    broadcast-join plan — they are the small sides), every device runs
    the dense-PK/grid lookups + dense-id COUNT on its shard, and the
    global merge is one psum over the num_items count vector. Bytes on
    the wire: num_items * 8 per device, vs the general distributed
    q72's row exchange.

    Returns (table, present, pk_violation) with the same schema as
    tpcds_q72_planned; the result is REPLICATED (identical on every
    device)."""
    import jax as _jax
    from jax.sharding import PartitionSpec as P

    from spark_rapids_jni_tpu.ops.planner import (
        dense_id_counts,
        dense_pk_join,
    )
    from spark_rapids_jni_tpu.parallel.distributed import (
        _mesh_fingerprint,
        shard_table,
    )
    from spark_rapids_jni_tpu.parallel.mesh import EXEC_AXIS
    from spark_rapids_jni_tpu.runtime import dispatch

    num_days = date_dim.num_rows
    num_items = item.num_rows
    if inventory.num_rows % num_items:
        raise ValueError(
            "inventory is not a dense (item, week) grid — use tpcds_q72")
    num_weeks = inventory.num_rows // num_items

    dd_key = _null_keys_where(
        date_dim.column(D_DATE_SK),
        jnp.asarray(np.int32(year)) != date_dim.column(D_YEAR).data,
    )
    dd = Table([dd_key, date_dim.column(D_WEEK_SEQ)])

    sharded, rv = shard_table(catalog_sales, mesh, return_row_valid=True)

    def step(local: Table, local_rv, dd_r: Table, item_r: Table,
             inv_r: Table):
        j1 = dense_pk_join(local, dd_r, CS_SOLD_DATE_SK, 0,
                           1, num_days, clustered=True)
        j2 = dense_pk_join(j1.table, item_r, CS_ITEM_SK, I_ITEM_SK,
                           1, num_items, clustered=True)
        cs_item = j2.table.column(0)
        week = j2.table.column(5)
        grid = ((cs_item.data - 1) * num_weeks
                + (week.data.astype(cs_item.data.dtype) - 1))
        week_ok = (week.data >= 1) & (week.data <= num_weeks)
        in_grid = (local_rv & j1.matched & j2.matched
                   & cs_item.valid_mask() & week.valid_mask() & week_ok
                   & (grid >= 0) & (grid < inv_r.num_rows))
        pos = jnp.clip(grid, 0, inv_r.num_rows - 1).astype(jnp.int32)
        inv_item_at = inv_r.column(INV_ITEM_SK).data[pos]
        inv_week_at = inv_r.column(INV_WEEK_SEQ).data[pos]
        inv_qty_c = inv_r.column(INV_QTY)
        grid_lie = jnp.any(
            in_grid & ((inv_item_at != cs_item.data)
                       | (inv_week_at != week.data.astype(jnp.int64))))
        qty = j2.table.column(CS_QUANTITY)
        short = (in_grid & inv_qty_c.valid_mask()[pos]
                 & qty.valid_mask()
                 & (inv_qty_c.data[pos] < qty.data))
        gid = jnp.where(short, cs_item.data - 1,
                        jnp.int64(num_items)).astype(jnp.int32)
        counts = _jax.lax.psum(
            dense_id_counts(gid, num_items), EXEC_AXIS)
        viol = _jax.lax.psum(
            (j1.pk_violation | j2.pk_violation | grid_lie)
            .astype(jnp.int32), EXEC_AXIS) > 0
        return counts, viol

    counts, viol = dispatch.sharded_call(
        "tpcds_q72_planned_distributed.step",
        lambda: _jax.shard_map(
            step,
            mesh=mesh,
            in_specs=(P(EXEC_AXIS), P(EXEC_AXIS), P(), P(), P()),
            out_specs=(P(), P()),
        ),
        (sharded, rv, dd, item, inventory),
        statics=(num_days, num_items, num_weeks, _mesh_fingerprint(mesh)),
    )

    present = counts > 0
    item_sk = jnp.arange(1, num_items + 1, dtype=jnp.int64)
    brand_c = item.column(I_BRAND_ID)
    out = Table([
        Column(t.INT64, item_sk, present),
        Column(brand_c.dtype, brand_c.data,
               brand_c.valid_mask() & present),
        Column(t.INT64, counts, present),
    ])
    srt = sort_table(out, [2, 0], ascending=[False, True],
                     nulls_first=[False, False])
    return Q72PlannedResult(srt, present, viol)


# ---- TPC-DS q3 (brand revenue by year/month) -------------------------------
#
#   SELECT d_year, i_brand_id, sum(ss_ext_sales_price)
#   FROM date_dim, store_sales, item
#   WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
#     AND i_manufact_id = :m AND d_moy = :month
#   GROUP BY d_year, i_brand_id ORDER BY d_year, sum desc

SS3_SOLD_DATE_SK, SS3_ITEM_SK, SS3_EXT_SALES_PRICE = 0, 1, 2
I3_ITEM_SK, I3_BRAND_ID, I3_MANUFACT_ID = 0, 1, 2


def item_q3_table(num_items: int = 1000, seed: int = 4) -> Table:
    """i_item_sk, i_brand_id, i_manufact_id."""
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(np.arange(1, num_items + 1, dtype=np.int64)),
        Column.from_numpy(rng.integers(1, 100, num_items).astype(np.int64)),
        Column.from_numpy(rng.integers(1, 50, num_items).astype(np.int64)),
    ])


def store_sales_q3_table(num_rows: int, num_items: int = 1000,
                         num_days: int = 730, seed: int = 5) -> Table:
    """ss_sold_date_sk, ss_item_sk, ss_ext_sales_price (decimal -2)."""
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(
            rng.integers(1, num_days + 1, num_rows).astype(np.int64)),
        Column.from_numpy(
            rng.integers(1, num_items + 1, num_rows).astype(np.int64)),
        Column.from_numpy(
            rng.integers(100, 100_000, num_rows).astype(np.int64),
            t.decimal64(-2)),
    ])


class Q3dsResult(NamedTuple):
    table: "Table"            # [d_year, i_brand_id, revenue], rev desc
    present: jnp.ndarray
    pk_violation: jnp.ndarray
    # a kept row's brand id fell outside the declared [1, num_brands]
    # domain — its revenue is NOT in the output; re-plan (the
    # domain_miss posture, never a silent wrong answer)
    brand_domain_miss: jnp.ndarray


@func_range("tpcds_q3")
def tpcds_q3(date_dim: Table, store_sales: Table, item: Table,
             manufact_id: int = 7, moy: int = 11,
             num_brands: int = 100,
             num_days_per_year: int = 365) -> Q3dsResult:
    """TPC-DS q3 as the all-planner-facts star plan: both dim joins are
    dense clustered-PK lookups with the predicates pushed into the
    build-side keys (month filter into date_dim, manufacturer filter
    into item), and the (d_year, i_brand_id) groupby is a TWO-LEVEL
    dense-id exact SUM (``dense_id_sums`` over year*num_brands+brand —
    both dimensions are small dense DDL domains). No n-sized sort
    anywhere; only the group-table final ORDER BY sorts.

    ``num_brands`` is the planner-declared brand domain bound; a kept
    row whose brand falls outside it raises ``brand_domain_miss``
    instead of silently dropping revenue."""
    from spark_rapids_jni_tpu.ops.planner import (
        dense_id_counts,
        dense_id_sums,
        dense_pk_join,
    )

    num_days = date_dim.num_rows
    num_years = (num_days + num_days_per_year - 1) // num_days_per_year

    # d_moy derives from the date grid; push the month filter into keys
    sk = date_dim.column(D_DATE_SK).data
    moy_of = ((sk - 1) % num_days_per_year) // 31 + 1
    dd_key = _null_keys_where(
        date_dim.column(D_DATE_SK), moy_of != jnp.int64(moy))
    dd = Table([dd_key, date_dim.column(D_YEAR)])
    j1 = dense_pk_join(store_sales, dd, SS3_SOLD_DATE_SK, 0,
                       1, num_days, clustered=True)
    year = j1.table.column(store_sales.num_columns + 1)
    base_year = date_dim.column(D_YEAR).data[0]
    year_idx = year.data.astype(jnp.int64) - base_year

    it_key = _null_keys_where(
        item.column(I3_ITEM_SK),
        item.column(I3_MANUFACT_ID).data != jnp.int64(manufact_id))
    it = Table([it_key, item.column(I3_BRAND_ID)])
    j2 = dense_pk_join(store_sales, it, SS3_ITEM_SK, 0,
                       1, item.num_rows, clustered=True)
    brand = j2.table.column(store_sales.num_columns + 1)

    price = store_sales.column(SS3_EXT_SALES_PRICE)
    keep = (j1.matched & j2.matched & brand.valid_mask()
            & price.valid_mask())
    brand_ok = (brand.data >= 1) & (brand.data <= num_brands)
    brand_domain_miss = jnp.any(keep & ~brand_ok)
    year_ok = (year_idx >= 0) & (year_idx < num_years)
    m = num_years * num_brands
    gid = jnp.where(keep & brand_ok & year_ok,
                    year_idx * num_brands + (brand.data - 1),
                    jnp.int64(m)).astype(jnp.int32)
    vals = jnp.where(keep, price.data, 0)
    sums = dense_id_sums(gid, vals, m)
    # presence is row COUNT, not sum: a group whose revenue nets to
    # exactly zero (refunds / negative amounts) must still be emitted
    present = dense_id_counts(gid, m) > 0
    slot = jnp.arange(m, dtype=jnp.int64)
    out = Table([
        Column(t.INT64, base_year + slot // num_brands, present),
        Column(t.INT64, 1 + slot % num_brands, present),
        Column(t.decimal64(-2), sums, present),
    ])
    srt = sort_table(out, [2], ascending=[False], nulls_first=[False])
    return Q3dsResult(srt, srt.column(0).valid_mask(),
                      j1.pk_violation | j2.pk_violation,
                      brand_domain_miss)


def tpcds_q3_numpy(date_dim: Table, store_sales: Table, item: Table,
                   manufact_id: int = 7, moy: int = 11,
                   num_days_per_year: int = 365) -> dict:
    """Host oracle: {(d_year, i_brand_id): revenue}."""
    sk = np.asarray(date_dim.column(D_DATE_SK).data)
    yr = np.asarray(date_dim.column(D_YEAR).data)
    moy_of = ((sk - 1) % num_days_per_year) // 31 + 1
    day_year = {int(k): int(y) for k, y, m in zip(sk, yr, moy_of)
                if m == moy}
    brand_of = {}
    for k, b, mf in zip(np.asarray(item.column(I3_ITEM_SK).data),
                        np.asarray(item.column(I3_BRAND_ID).data),
                        np.asarray(item.column(I3_MANUFACT_ID).data)):
        if int(mf) == manufact_id:
            brand_of[int(k)] = int(b)
    out: dict = {}
    for d, i, p in zip(
            np.asarray(store_sales.column(SS3_SOLD_DATE_SK).data),
            np.asarray(store_sales.column(SS3_ITEM_SK).data),
            np.asarray(store_sales.column(SS3_EXT_SALES_PRICE).data)):
        y = day_year.get(int(d))
        if y is None:
            continue
        b = brand_of.get(int(i))
        if b is None:
            continue
        out[(y, b)] = out.get((y, b), 0) + int(p)
    return out


class Q64PlannedResult(NamedTuple):
    result: GroupByResult    # [ss_item_sk, pair_count], count desc
    join_total: jnp.ndarray  # the pair count the general plan materializes


@func_range("tpcds_q64_planned")
def tpcds_q64_planned(
    store_sales: Table,
    year1: int = 2000,
    year2: int = 2001,
    num_days_per_year: int = 365,
    base_year: int = 2000,
) -> Q64PlannedResult:
    """q64's cross-year self-join ELIMINATED by an exact aggregate
    rewrite: COUNT over the (item,customer) self-join is
    sum_{(i,c)} cnt_y1(i,c) * cnt_y2(i,c) — two conditional counts per
    pair and a product, no join at all.

    Unlike the bounded/dense plans this needs NO declared facts: the
    rewrite is unconditionally exact (a COUNT-over-equi-self-join is a
    sum of per-key count products — the optimizer transformation Spark
    performs as partial aggregation pushdown). What it buys: the
    general plan pays a build-side sort + join materialization at
    out_factor*n rows (with truncation risk the caller must check) +
    a groupby sort over that blown-up output; this plan pays ONE
    groupby over n rows + one over the distinct pairs, with no
    capacity estimate and no truncation mode at all."""
    date = store_sales.column(SS_SOLD_DATE_SK).data
    yr = (date - 1) // jnp.int64(num_days_per_year)
    in_y1 = yr == (year1 - base_year)
    in_y2 = yr == (year2 - base_year)
    key = _pack_key(
        store_sales.column(SS_ITEM_SK), store_sales.column(SS_CUSTOMER_SK),
        MAX_CUSTOMERS,
    )
    valid = key.valid_mask() & (in_y1 | in_y2)
    pair = Table([
        _null_keys_where(key, ~valid),
        Column(t.INT64, in_y1.astype(jnp.int64), valid),
        Column(t.INT64, in_y2.astype(jnp.int64), valid),
    ])
    per_pair = groupby_aggregate(pair, keys=[0],
                                 aggs=[(1, "sum"), (2, "sum")])
    pk = per_pair.table.column(0)
    a = per_pair.table.column(1)
    b = per_pair.table.column(2)
    pairs = a.data * b.data  # cnt_y1 * cnt_y2 per (item, customer)
    pvalid = (pk.valid_mask() & a.valid_mask() & b.valid_mask()
              & (pairs > 0))
    item_of = Table([
        Column(t.INT64, pk.data // jnp.int64(MAX_CUSTOMERS), pvalid),
        Column(t.INT64, jnp.where(pvalid, pairs, 0), pvalid),
    ])
    grouped = groupby_aggregate(item_of, keys=[0], aggs=[(1, "sum")])
    srt = sort_table(
        grouped.table, [1, 0], ascending=[False, True],
        nulls_first=[False, False],
    )
    total = jnp.sum(jnp.where(pvalid, pairs, 0))
    return Q64PlannedResult(GroupByResult(srt, grouped.num_groups), total)


def tpcds_q64_numpy(
    store_sales: Table, year1: int = 2000, year2: int = 2001,
    num_days_per_year: int = 365,
) -> dict:
    """Host oracle: {item_sk: pair count} over (item, customer) pairs."""
    item = np.asarray(store_sales.column(SS_ITEM_SK).data)
    cust = np.asarray(store_sales.column(SS_CUSTOMER_SK).data)
    date = np.asarray(store_sales.column(SS_SOLD_DATE_SK).data)
    yr = (date - 1) // num_days_per_year + 2000
    out: dict = {}
    y2_pairs: dict = {}
    for k in np.flatnonzero(yr == year2):
        p = (int(item[k]), int(cust[k]))
        y2_pairs[p] = y2_pairs.get(p, 0) + 1
    for k in np.flatnonzero(yr == year1):
        p = (int(item[k]), int(cust[k]))
        c2 = y2_pairs.get(p, 0)
        if c2:
            out[p[0]] = out.get(p[0], 0) + c2
    return out
