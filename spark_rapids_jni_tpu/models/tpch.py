"""TPC-H workload pipelines — the "model family" layer of this framework.

The reference's flagship workloads are Spark SQL queries running through the
RAPIDS accelerator (BASELINE.json configs: RowConversion on the lineitem
schema; TPC-H q1 groupby-aggregate + sort). Here the same queries are
expressed directly against the operator substrate, serving three roles:
benchmark pipelines (bench.py), the driver's compile-check entry
(__graft_entry__.py), and integration tests of the operator stack.

TPC-H q1 (pricing summary report):

    SELECT l_returnflag, l_linestatus,
           sum(l_quantity), sum(l_extendedprice),
           sum(l_extendedprice*(1-l_discount)),
           sum(l_extendedprice*(1-l_discount)*(1+l_tax)),
           avg(l_quantity), avg(l_extendedprice), avg(l_discount), count(*)
    FROM lineitem WHERE l_shipdate <= date '1998-12-01' - 90 days
    GROUP BY l_returnflag, l_linestatus ORDER BY l_returnflag, l_linestatus

Money columns use decimal64(-2) (the TPC-H spec's DECIMAL(12,2)) — integer
backing, which is exactly what the TPU wants (the MXU/VPU have no fast f64;
int64 arithmetic is emulated but exact).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.groupby import GroupByResult, groupby_aggregate
from spark_rapids_jni_tpu.ops.sort import sort_table
from spark_rapids_jni_tpu.runtime import fusion, rtfilter
from spark_rapids_jni_tpu.utils.tracing import func_range

# lineitem columns used by q1 (positions in the table below)
L_QUANTITY = 0
L_EXTENDEDPRICE = 1
L_DISCOUNT = 2
L_TAX = 3
L_RETURNFLAG = 4
L_LINESTATUS = 5
L_SHIPDATE = 6

# 1998-12-01 minus 90 days, in days since epoch (Spark DateType encoding)
_Q1_CUTOFF_DAYS = 10560

# q1 groups by two one-byte flags: at most 3*2 real groups plus the null-key
# pseudo-group. A tiny static group budget keeps every downstream shape
# (groupby output, final ORDER BY, shuffle payload) at m rows instead of n —
# and switches groupby_aggregate onto its small-m boundary path (no
# full-length scans).
_Q1_GROUP_BUDGET = 64

# The q1 aggregate plan over _q1_work_table's column layout, shared by the
# jitted pipeline and the checked host wrapper so they cannot diverge.
_Q1_AGGS = [
    (2, "sum"),    # sum_qty
    (3, "sum"),    # sum_base_price
    (5, "sum"),    # sum_disc_price
    (6, "sum"),    # sum_charge
    (2, "mean"),   # avg_qty
    (3, "mean"),   # avg_price
    (4, "mean"),   # avg_disc
    (2, "count"),  # count_order
]

LINEITEM_SCHEMA = [
    t.decimal64(-2),      # l_quantity  DECIMAL(12,2)
    t.decimal64(-2),      # l_extendedprice
    t.decimal64(-2),      # l_discount
    t.decimal64(-2),      # l_tax
    t.INT8,               # l_returnflag  ('A','N','R' as bytes)
    t.INT8,               # l_linestatus  ('F','O')
    t.TIMESTAMP_DAYS,     # l_shipdate
]


def lineitem_table(num_rows: int, seed: int = 0) -> Table:
    """Synthetic lineitem batch with TPC-H-like value distributions."""
    rng = np.random.default_rng(seed)
    qty = rng.integers(100, 51_00, num_rows).astype(np.int64)       # 1..50 qty
    price = rng.integers(90_000, 10_500_000, num_rows).astype(np.int64)
    disc = rng.integers(0, 11, num_rows).astype(np.int64)           # 0.00-0.10
    tax = rng.integers(0, 9, num_rows).astype(np.int64)             # 0.00-0.08
    rflag = rng.choice(np.frombuffer(b"ANR", dtype=np.int8), num_rows)
    lstatus = rng.choice(np.frombuffer(b"FO", dtype=np.int8), num_rows)
    shipdate = rng.integers(8400, 10957, num_rows).astype(np.int32)
    return Table(
        [
            Column.from_numpy(qty, t.decimal64(-2)),
            Column.from_numpy(price, t.decimal64(-2)),
            Column.from_numpy(disc, t.decimal64(-2)),
            Column.from_numpy(tax, t.decimal64(-2)),
            Column.from_numpy(rflag, t.INT8),
            Column.from_numpy(lstatus, t.INT8),
            Column.from_numpy(shipdate, t.TIMESTAMP_DAYS),
        ]
    )


def lineitem_table_strings(num_rows: int, seed: int = 0) -> Table:
    """Lineitem variant with REAL STRING returnflag/linestatus columns —
    the schema shape Spark actually has before dictionary tricks (flags are
    CHAR(1) STRINGs in TPC-H). Runs through the same q1 pipeline: string
    keys sort, group, and shuffle natively."""
    base = lineitem_table(num_rows, seed)
    rf = np.asarray(base.column(L_RETURNFLAG).data).astype(np.uint8)
    ls = np.asarray(base.column(L_LINESTATUS).data).astype(np.uint8)
    cols = list(base.columns)
    cols[L_RETURNFLAG] = Column.from_pylist(
        [chr(b) for b in rf], t.STRING
    )
    cols[L_LINESTATUS] = Column.from_pylist(
        [chr(b) for b in ls], t.STRING
    )
    return Table(cols)


class Q1Result(NamedTuple):
    result: GroupByResult  # grouped aggregates, padded; sorted by flag/status


def _q1_work_table(lineitem: Table) -> Table:
    """Shared q1 front half: WHERE filter + derived decimal columns.

    The filter keeps static shapes by masking validity instead of compacting
    rows (masked rows fall out of every null-skipping aggregate), the
    standard XLA trick for data-dependent filtering.
    """
    ship = lineitem.column(L_SHIPDATE)
    keep = (ship.data <= _Q1_CUTOFF_DAYS) & ship.valid_mask()

    def masked(col: Column) -> Column:
        return Column(col.dtype, col.data, col.valid_mask() & keep)

    qty = masked(lineitem.column(L_QUANTITY))
    price = masked(lineitem.column(L_EXTENDEDPRICE))
    disc = masked(lineitem.column(L_DISCOUNT))
    tax = masked(lineitem.column(L_TAX))

    # disc_price = price * (1 - disc): decimal multiply at scale -4.
    # Null in any operand nulls the product (SQL three-valued arithmetic).
    dp_valid = price.valid_mask() & disc.valid_mask()
    disc_price = Column(
        t.decimal64(-4), price.data * (100 - disc.data), dp_valid
    )
    # charge = disc_price * (1 + tax): scale -6
    charge = Column(
        t.decimal64(-6), disc_price.data * (100 + tax.data),
        dp_valid & tax.valid_mask(),
    )

    # Masked rows must not create key groups: zero out key bytes for them.
    def masked_key(c: Column) -> Column:
        if c.dtype.is_string:
            from spark_rapids_jni_tpu.ops.strings import pad_strings

            p = pad_strings(c)
            return Column(
                p.dtype,
                jnp.where(keep, p.data, 0),
                keep,
                chars=jnp.where(keep[:, None], p.chars, jnp.uint8(0)),
            )
        return Column(c.dtype, jnp.where(keep, c.data, 0), keep)

    return Table(
        [
            masked_key(lineitem.column(L_RETURNFLAG)),
            masked_key(lineitem.column(L_LINESTATUS)),
            qty,
            price,
            disc,
            disc_price,
            charge,
        ]
    )


def _q1_plan() -> fusion.Plan:
    """q1 as ONE fusible region: filter+derive -> groupby -> sort. The
    filtered-out pseudo-group has null keys; q1's ORDER BY puts real
    groups first (nulls last) so the compacted head is the answer."""
    return fusion.Plan("tpch_q1", fusion.Sort(
        fusion.GroupBy(
            fusion.Project(fusion.Scan("lineitem"), _q1_work_table),
            (0, 1), tuple(_Q1_AGGS), max_groups=_Q1_GROUP_BUDGET,
            label="groupby"),
        (0, 1), nulls_first=(False, False)))


@func_range("tpch_q1")
def tpch_q1(lineitem: Table) -> Table:
    """Single-executor q1: filter -> derived columns -> groupby -> sort,
    compiled as one fused executable (runtime/fusion.py).

    The group budget is part of the query plan, the way Spark's planner
    carries a cardinality estimate: q1 groups by two CHAR(1) flags, <= 7
    groups including the null-key pseudo-group, so 64 is a 9x margin. On
    data outside that contract (>=64 distinct byte pairs) the excess
    groups are dropped — jitted code cannot raise on a device predicate;
    use ``tpch_q1_checked`` from host code to turn overflow into an error.
    """
    return fusion.execute(_q1_plan(), {"lineitem": lineitem}).table


# TPC-H DDL domains for the q1 flags (the spec fixes returnflag to
# 'A'/'N'/'R' and linestatus to 'F'/'O'); a real planner gets the same
# facts from dictionary/column statistics.
_Q1_RF_DOMAIN = (ord("A"), ord("N"), ord("R"))
_Q1_LS_DOMAIN = (ord("F"), ord("O"))


@func_range("tpch_q1_planned_result")
def tpch_q1_planned_result(lineitem: Table):
    """q1 with PLANNER-DECLARED key domains: the flag domains come from
    the TPC-H DDL (CHAR(1) check constraints / dictionary stats), so
    grouping needs no sort, no gather, no scan — one streaming masked-
    reduction pass (groupby_aggregate_bounded), and the output order is
    static (real groups lexicographic, null groups last), so the final
    ORDER BY costs nothing. Returns the planner result so jitted callers
    can observe ``domain_miss``; the single shared call path for the
    checked and unchecked wrappers below. Lowered through the general
    planner facility (ops/planner.plan_groupby) — q1 is just the first
    client of the declared-domain plan, not a special case."""
    from spark_rapids_jni_tpu.ops.planner import PlannedGroupBy, scalar_domain

    plan = fusion.Plan("tpch_q1_planned", fusion.GroupBy(
        fusion.Project(fusion.Scan("lineitem"), _q1_work_table),
        (0, 1), tuple(_Q1_AGGS),
        domains=(scalar_domain(_Q1_RF_DOMAIN),
                 scalar_domain(_Q1_LS_DOMAIN)),
        label="plan"))
    out = fusion.execute(plan, {"lineitem": lineitem})
    res = PlannedGroupBy(out.table, out.meta["plan.present"],
                         out.meta["plan.domain_miss"],
                         out.meta["plan.lowered"],
                         out.meta["plan.overflowed"])
    assert res.lowered == "bounded"  # static plan fact, not a data check
    return res


def tpch_q1_planned(lineitem: Table) -> Table:
    """Planned q1, table only — same output schema as ``tpch_q1``.
    Out-of-domain key bytes fold into the null-key group WITHOUT signal
    here (jitted code cannot raise); callers that must detect that use
    ``tpch_q1_planned_result().domain_miss`` or the checked wrapper."""
    return tpch_q1_planned_result(lineitem).table


def tpch_q1_planned_checked(lineitem: Table) -> Table:
    """Host wrapper for the planned q1: domain misses re-plan onto the
    general sort-based pipeline instead of dropping rows."""
    res = tpch_q1_planned_result(lineitem)
    if bool(res.domain_miss):
        return tpch_q1_checked(lineitem)
    return res.table


def tpch_q1_checked(lineitem: Table) -> Table:
    """Host-side q1 wrapper that enforces the plan's group-budget contract
    (raises instead of silently dropping groups on out-of-contract data)."""
    res = fusion.execute(_q1_plan(), {"lineitem": lineitem})
    if bool(res.meta["groupby.overflowed"]):
        raise ValueError(
            f"q1 key domain exceeded the plan's group budget "
            f"({int(res.meta['groupby.num_groups'])} > {_Q1_GROUP_BUDGET}): "
            "the returnflag/linestatus bytes are outside the TPC-H contract"
        )
    return res.table


# TPC-H q6 predicate constants: shipdate in [1994-01-01, 1995-01-01) as
# days since epoch (8766 = 24*365 + 6 leap days; 9131 = 8766 + 365),
# discount in [0.05, 0.07] at scale -2, quantity < 24 at scale -2.
_Q6_DATE_LO = 8766
_Q6_DATE_HI = 9131
_Q6_DISC_LO = 5
_Q6_DISC_HI = 7
_Q6_QTY_HI = 2400


def _q6_reduce(lineitem: Table, row_valid) -> Table:
    """q6's masked multiply-accumulate as a fusion Project (rowwise=False
    — the 1-row output is its own space). Region-padded phantom rows have
    null validity everywhere, so ``sel`` already excludes them and
    ``row_valid`` needs no explicit fold."""
    qty = lineitem.column(L_QUANTITY)
    price = lineitem.column(L_EXTENDEDPRICE)
    disc = lineitem.column(L_DISCOUNT)
    ship = lineitem.column(L_SHIPDATE)
    sel = (
        qty.valid_mask() & price.valid_mask() & disc.valid_mask()
        & ship.valid_mask()
        & (ship.data >= _Q6_DATE_LO) & (ship.data < _Q6_DATE_HI)
        & (disc.data >= _Q6_DISC_LO) & (disc.data <= _Q6_DISC_HI)
        & (qty.data < _Q6_QTY_HI)
    )
    prod = jnp.where(sel, price.data * disc.data, jnp.int64(0))
    total = jnp.sum(prod).reshape(1)
    any_row = jnp.any(sel).reshape(1)
    return Table([Column(t.decimal64(-4), total, any_row)])


@func_range("tpch_q6")
def tpch_q6(lineitem: Table) -> Column:
    """TPC-H q6: SELECT sum(l_extendedprice * l_discount) WHERE shipdate
    in a year AND discount BETWEEN 0.05 AND 0.07 AND quantity < 24.

    The pure streaming query: no groupby, no sort, no join — ONE masked
    multiply-accumulate over three predicate columns, the shape that
    exposes raw HBM bandwidth (the cuDF/libcudf capability family's
    filter+reduce fast path, SURVEY.md section 2.2). The product of two
    scale -2 decimals is scale -4; the int64 accumulator is exact up to
    ~9e18, i.e. ~8.7e10 matched rows at TPC-H value ranges — far beyond
    any single-chip batch, so no 128-bit lanes are needed (unlike the
    general DECIMAL128 SUM path, which this plan deliberately avoids).
    As a one-node fused region the whole scan+reduce is a single bucketed
    executable instead of a chain of eager XLA calls.

    Returns a 1-row DECIMAL64(scale -4) column (null iff no row matched).
    """
    plan = fusion.Plan("tpch_q6", fusion.Project(
        fusion.Scan("lineitem"), _q6_reduce, rowwise=False))
    return fusion.execute(plan, {"lineitem": lineitem}).table.column(0)


def tpch_q6_numpy(lineitem: Table) -> int:
    """Host oracle for q6 (exact int arithmetic, scale -4 result)."""
    qty = np.asarray(lineitem.column(L_QUANTITY).data)
    price = np.asarray(lineitem.column(L_EXTENDEDPRICE).data)
    disc = np.asarray(lineitem.column(L_DISCOUNT).data)
    ship = np.asarray(lineitem.column(L_SHIPDATE).data)
    valid = np.ones(lineitem.num_rows, dtype=bool)
    for c in (L_QUANTITY, L_EXTENDEDPRICE, L_DISCOUNT, L_SHIPDATE):
        valid &= np.asarray(lineitem.column(c).valid_mask())
    sel = (valid & (ship >= _Q6_DATE_LO) & (ship < _Q6_DATE_HI)
           & (disc >= _Q6_DISC_LO) & (disc <= _Q6_DISC_HI)
           & (qty < _Q6_QTY_HI))
    return int((price[sel].astype(object) * disc[sel].astype(object)).sum())


def tpch_q1_numpy(lineitem: Table) -> dict:
    """Host oracle: same query in numpy, keyed by (returnflag, linestatus)."""
    qty = np.asarray(lineitem.column(L_QUANTITY).data)
    price = np.asarray(lineitem.column(L_EXTENDEDPRICE).data)
    disc = np.asarray(lineitem.column(L_DISCOUNT).data)
    tax = np.asarray(lineitem.column(L_TAX).data)
    rf = np.asarray(lineitem.column(L_RETURNFLAG).data)
    ls = np.asarray(lineitem.column(L_LINESTATUS).data)
    ship = np.asarray(lineitem.column(L_SHIPDATE).data)
    keep = ship <= _Q1_CUTOFF_DAYS
    out = {}
    for f in np.unique(rf[keep]):
        for s in np.unique(ls[keep]):
            m = keep & (rf == f) & (ls == s)
            if not m.any():
                continue
            dp = price[m] * (100 - disc[m])
            out[(int(f), int(s))] = {
                "sum_qty": int(qty[m].sum()),
                "sum_base_price": int(price[m].sum()),
                "sum_disc_price": int(dp.sum()),
                "sum_charge": int((dp * (100 + tax[m])).sum()),
                # true values: unscaled decimal(scale -2) means x 10^-2
                "avg_qty": qty[m].mean() * 1e-2,
                "avg_price": price[m].mean() * 1e-2,
                "avg_disc": disc[m].mean() * 1e-2,
                "count": int(m.sum()),
            }
    return out


# ---- distributed q1 over the executor mesh --------------------------------

# Partial (per-executor) aggregates: SUMs and COUNTs only, because those
# merge associatively across the shuffle; AVGs are finalized from the merged
# sums/counts. Indices refer to the work-table layout in _q1_work_table.
_Q1_PARTIAL_AGGS = [
    (2, "sum"),    # sum_qty
    (3, "sum"),    # sum_base_price
    (5, "sum"),    # sum_disc_price
    (6, "sum"),    # sum_charge
    (2, "count"),  # count_qty (also count_order)
    (3, "count"),  # count_price
    (4, "sum"),    # sum_disc
    (4, "count"),  # count_disc
]



def _q1_finalize(merged: Table) -> Table:
    """Merged sums/counts -> the q1 output schema (avgs = sum/count)."""
    rf, ls, sq, sp, sdp, sch, cq, cp, sd, cd = merged.columns

    def avg(total: Column, count: Column) -> Column:
        denom = jnp.maximum(count.data, 1).astype(jnp.float64)
        # 10^scale rescale so the FLOAT64 avg carries the true value, same
        # contract as groupby_aggregate's decimal mean.
        return Column(
            t.FLOAT64,
            total.data.astype(jnp.float64) / denom * (10.0 ** total.dtype.scale),
            count.valid_mask() & (count.data > 0),
        )

    return Table(
        [rf, ls, sq, sp, sdp, sch, avg(sq, cq), avg(sp, cp), avg(sd, cd), cq]
    )


# Merge-side aggregates over the partial layout: every partial lane sums
# associatively across the shuffle / chunk axis.
_Q1_MERGE_AGGS = tuple((i, "sum") for i in range(2, 10))


def _q1_partial_plan() -> fusion.Plan:
    """Per-chunk / per-executor q1 partial: work-table projection + the
    budget-bounded partial groupby, fused. ``min_rows_of`` reproduces the
    staged ``min(_Q1_GROUP_BUDGET, work.num_rows)`` budget from the TRUE
    chunk row count (never the bucket)."""
    return fusion.Plan("tpch_q1_partial", fusion.GroupBy(
        fusion.Project(fusion.Scan("chunk"), _q1_work_table),
        (0, 1), tuple(_Q1_PARTIAL_AGGS),
        max_groups=fusion.min_rows_of("chunk", _Q1_GROUP_BUDGET),
        label="partial"))


def _q1_merge_plan() -> fusion.Plan:
    """Merge the stacked partials: sum-merge groupby -> finalize
    (avgs = sum/count) -> output order, fused."""
    return fusion.Plan("tpch_q1_merge", fusion.Sort(
        fusion.Project(
            fusion.GroupBy(fusion.Scan("partials"), (0, 1), _Q1_MERGE_AGGS,
                           label="merge"),
            _q1_finalize),
        (0, 1), nulls_first=(False, False)))


def q1_row_chunked_fns():
    """The (partial_fn, merge_fn) pair for running q1 over IN-MEMORY row
    chunks of a lineitem table — the algebra ``run_chunked_aggregate``
    (and the degradation ladder's out-of-core rung, runtime/degrade.py)
    consumes. Same plans as :func:`tpch_q1_outofcore`, minus the Parquet
    retype: ``lineitem_table`` chunks already carry the decimal dtypes.
    """
    from spark_rapids_jni_tpu.ops.table_ops import trim_table

    def partial_fn(chunk: Table) -> Table:
        res = fusion.execute(_q1_partial_plan(), {"chunk": chunk},
                             donate_inputs=True)
        if bool(res.meta["partial.overflowed"]):
            raise ValueError(
                "q1 chunk exceeded the plan's group budget "
                f"({_Q1_GROUP_BUDGET}): flag bytes outside the contract")
        return trim_table(res.table, int(res.meta["partial.num_groups"]))

    def merge_fn(partials: Table) -> Table:
        # NOT donated: the SpillStore may still hold the partials buffer
        return fusion.execute(_q1_merge_plan(), {"partials": partials}).table

    return partial_fn, merge_fn


def q1_distributed_step(local: Table):
    """Per-executor q1 step; must run inside shard_map over EXEC_AXIS.

    local partial groupby -> head-truncate to the group budget -> ICI
    all-to-all shuffle by (returnflag, linestatus) -> merge groupby.
    Afterward each executor owns a disjoint slice of the key space. Both
    halves are the SAME fusion plans the out-of-core path runs; inside
    the shard_map trace ``fusion.execute`` takes its staged walk (tracer
    inputs), so the region boundary at the shuffle is explicit.
    """
    from spark_rapids_jni_tpu.parallel.mesh import EXEC_AXIS
    from spark_rapids_jni_tpu.parallel.shuffle import hash_shuffle

    budget = min(_Q1_GROUP_BUDGET, local.num_rows)
    # the budget-bounded partial IS the head truncation: its output is
    # padded to exactly `budget` rows, real groups first
    partial = fusion.execute(_q1_partial_plan(), {"chunk": local})
    # only the real groups cross the wire: the budget-padding rows (null
    # keys, zero aggregates) would all hash to one partition and waste the
    # null-key receiver's capacity on ~90% phantom payload
    real = (jnp.arange(budget, dtype=jnp.int32)
            < partial.meta["partial.num_groups"])
    sh = hash_shuffle(partial.table, [0, 1], EXEC_AXIS, capacity=budget,
                      row_valid=real)
    # merge with max_groups=None: m = the shuffle buffer size (every sender
    # contributed <= budget rows), which can never overflow — the receiving
    # device may own up to sender_count * budget distinct partial groups
    merged = fusion.execute(_q1_merge_plan(), {"partials": sh.table})
    return merged.table, merged.meta["merge.num_groups"].reshape(1)


def tpch_q1_distributed(lineitem: Table, mesh) -> Table:
    """Multi-executor q1: shard rows over the mesh, run the shuffle-backed
    step jitted across it, then collect + globally sort the (tiny) result —
    the driver-side collect of the Spark job."""
    import jax as _jax
    from jax.sharding import PartitionSpec as P

    from spark_rapids_jni_tpu.parallel.distributed import (
        _mesh_fingerprint,
        collect,
        shard_table,
    )
    from spark_rapids_jni_tpu.parallel.mesh import EXEC_AXIS
    from spark_rapids_jni_tpu.runtime import dispatch

    sharded = shard_table(lineitem, mesh)
    per_dev, num_groups = dispatch.sharded_call(
        "tpch_q1_distributed.step",
        lambda: _jax.shard_map(
            q1_distributed_step,
            mesh=mesh,
            in_specs=(P(EXEC_AXIS),),
            out_specs=(P(EXEC_AXIS), P(EXEC_AXIS)),
        ),
        (sharded,),
        statics=(_mesh_fingerprint(mesh),),
    )
    result = collect(per_dev, num_groups, mesh)
    return sort_table(result, [0, 1], nulls_first=[False, False])


def tpch_q1_outofcore(path, *, budget_bytes: int,
                      chunk_read_limit: int,
                      spill_budget_bytes: int | None = None,
                      compress_spill: bool = False,
                      prefetch_depth: int = 0,
                      pipeline: bool | None = None):
    """q1 over a Parquet file LARGER than the device budget: chunked
    row-group reads -> per-chunk partial aggregates -> SpillStore'd
    partials -> merge -> finalize. The partial->merge algebra is the
    distributed q1's (q1_distributed_step), run over the chunk sequence
    instead of the device mesh — same plan, different axis.

    File schema: the 7 q1 lineitem columns with the 4 money columns as
    unscaled int64 (the bench parquet_q1 layout); they are re-typed to
    DECIMAL64(-2) on read. Returns OutOfCoreResult; ``.table`` matches
    ``tpch_q1`` of the fully-materialized file.

    ``budget_bytes`` must cover one chunk (plus the merge window) when
    ``prefetch_depth == 0``; with prefetch, ``prefetch_depth + 2``
    chunks are resident at once (the read/compute overlap window) and
    the budget must cover them. ``pipeline`` selects the async
    multi-stage executor (None follows ``pipeline.enabled``): host
    decode overlaps device compute through the reader's chunk thunks,
    exact-bytes admission blocks instead of raising, and results stay
    bit-identical to the serial path.

    Both device halves are fused regions (the q1 partial / merge plans
    shared with the distributed step); the host-side ``trim_table``
    compaction between them is the genuine region boundary. Chunk tables
    are DEAD after their partial (nothing else reads them), so the
    partial region donates them back to XLA.
    """
    from spark_rapids_jni_tpu.parquet.reader import ParquetChunkedReader
    from spark_rapids_jni_tpu.runtime.memory import MemoryLimiter, SpillStore
    from spark_rapids_jni_tpu.runtime.outofcore import run_chunked_aggregate

    money = t.decimal64(-2)
    limiter = MemoryLimiter(budget_bytes)
    spill = SpillStore(
        spill_budget_bytes if spill_budget_bytes is not None
        else budget_bytes, compress_spill=compress_spill)

    def _retype(chunk: Table) -> Table:
        cols = list(chunk.columns)
        for i in range(4):
            cols[i] = Column(money, cols[i].data, cols[i].validity)
        return Table(cols)

    def partial_fn(chunk: Table) -> Table:
        from spark_rapids_jni_tpu.ops.table_ops import trim_table

        res = fusion.execute(_q1_partial_plan(),
                             {"chunk": _retype(chunk)},
                             donate_inputs=True)
        if bool(res.meta["partial.overflowed"]):
            raise ValueError(
                "q1 chunk exceeded the plan's group budget "
                f"({_Q1_GROUP_BUDGET}): flag bytes outside the contract")
        # host-side compaction between fused regions: only real groups
        # cross into the merge (chunk boundaries are where dynamic
        # shapes cost nothing — the q1_distributed_step row_valid idea)
        return trim_table(res.table, int(res.meta["partial.num_groups"]))

    def merge_fn(partials: Table) -> Table:
        # NOT donated: the SpillStore may still hold the partials buffer
        return fusion.execute(_q1_merge_plan(), {"partials": partials}).table

    reader = ParquetChunkedReader(path, chunk_read_limit=chunk_read_limit)
    # the reader (not iter(reader)) so the pipelined executor can pick up
    # its per-chunk decode thunks; the serial path just iterates it
    return run_chunked_aggregate(
        reader, partial_fn, merge_fn, limiter=limiter, spill=spill,
        prefetch_depth=prefetch_depth, pipeline=pipeline)


# ---- TPC-H q3 (shipping priority): join + groupby + order-by ---------------
#
#   SELECT l_orderkey, sum(l_extendedprice*(1-l_discount)) AS revenue,
#          o_orderdate, o_shippriority
#   FROM customer, orders, lineitem
#   WHERE c_mktsegment = :seg AND c_custkey = o_custkey
#     AND l_orderkey = o_orderkey
#     AND o_orderdate < :cutoff AND l_shipdate > :cutoff
#   GROUP BY l_orderkey, o_orderdate, o_shippriority
#   ORDER BY revenue DESC, o_orderdate LIMIT 10

_Q3_CUTOFF_DAYS = 9204  # 1995-03-15
N_SEGMENTS = 5          # TPC-H market segments

# orders columns
O_ORDERKEY, O_CUSTKEY, O_ORDERDATE, O_SHIPPRIORITY = 0, 1, 2, 3
# customer columns
C_CUSTKEY, C_MKTSEGMENT = 0, 1
# q3 lineitem columns
L3_ORDERKEY, L3_EXTENDEDPRICE, L3_DISCOUNT, L3_SHIPDATE = 0, 1, 2, 3


def customer_table(num_rows: int, seed: int = 0) -> Table:
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(np.arange(1, num_rows + 1, dtype=np.int64)),
        Column.from_numpy(
            rng.integers(0, N_SEGMENTS, num_rows).astype(np.int8), t.INT8
        ),
    ])


def orders_table(num_rows: int, num_customers: int, seed: int = 1) -> Table:
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(np.arange(1, num_rows + 1, dtype=np.int64)),
        Column.from_numpy(
            rng.integers(1, num_customers + 1, num_rows).astype(np.int64)
        ),
        Column.from_numpy(
            rng.integers(8400, 10957, num_rows).astype(np.int32),
            t.TIMESTAMP_DAYS,
        ),
        Column.from_numpy(rng.integers(0, 2, num_rows).astype(np.int32)),
    ])


def lineitem_q3_table(num_rows: int, num_orders: int, seed: int = 2) -> Table:
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(
            rng.integers(1, num_orders + 1, num_rows).astype(np.int64)
        ),
        Column.from_numpy(
            rng.integers(90_000, 10_500_000, num_rows).astype(np.int64),
            t.decimal64(-2),
        ),
        Column.from_numpy(
            rng.integers(0, 11, num_rows).astype(np.int64), t.decimal64(-2)
        ),
        Column.from_numpy(
            rng.integers(8400, 10957, num_rows).astype(np.int32),
            t.TIMESTAMP_DAYS,
        ),
    ])


def _null_where(c: Column, drop: jnp.ndarray) -> Column:
    return Column(c.dtype, c.data, c.valid_mask() & ~drop,
                  chars=c.chars, children=c.children)


def _q3_cust_fn(customer: Table, segment: int) -> Table:
    """Segment-filtered customer keys (q3 plan Project node)."""
    return Table([_null_where(
        customer.column(C_CUSTKEY),
        customer.column(C_MKTSEGMENT).data != jnp.int8(segment),
    )])


def _q3_orders_fn(orders: Table, cutoff: int) -> Table:
    """Date-filtered orders with custkey join lane (q3 plan Project)."""
    okey = _null_where(
        orders.column(O_CUSTKEY),
        orders.column(O_ORDERDATE).data >= jnp.int32(cutoff),
    )
    return Table([okey, orders.column(O_ORDERKEY),
                  orders.column(O_ORDERDATE),
                  orders.column(O_SHIPPRIORITY)])


def _q3_probe_fn(lineitem: Table, cutoff: int) -> Table:
    """Shipdate-filtered lineitem probe with its revenue lane."""
    lkey = _null_where(
        lineitem.column(L3_ORDERKEY),
        lineitem.column(L3_SHIPDATE).data <= jnp.int32(cutoff),
    )
    price = lineitem.column(L3_EXTENDEDPRICE)
    disc = lineitem.column(L3_DISCOUNT)
    revenue = Column(
        t.decimal64(-4), price.data * (100 - disc.data),
        price.valid_mask() & disc.valid_mask(),
    )
    return Table([lkey, revenue])


def _q3_inputs(customer: Table, orders: Table, lineitem: Table,
               segment: int, cutoff: int):
    """Shared q3 filtered inputs for BOTH plans (single change point for
    predicates/scales): segment-filtered customer keys, date-filtered
    orders, and the shipdate-filtered lineitem probe with its revenue
    lane. Returns (cust, ord_t, probe). The per-table pieces are the
    module-level fns above so the fusion plans can reference them as
    Project nodes."""
    return (_q3_cust_fn(customer, segment),
            _q3_orders_fn(orders, cutoff),
            _q3_probe_fn(lineitem, cutoff))


def _q3_build_fn(oc: Table) -> Table:
    """orders x customer join output -> second-join build side:
    [orderkey (nulled where unmatched), orderdate, shippriority]."""
    # oc: [o_custkey, o_orderkey, o_orderdate, o_shippriority, c_custkey]
    matched = oc.column(4).valid_mask()
    oc_key = _null_where(oc.column(1), ~matched)
    return Table([oc_key, oc.column(2), oc.column(3)])


def _q3_keyed_fn(j: Table) -> Table:
    """lineitem x orders join output -> groupby-keyed table
    [l_orderkey, o_orderdate, o_shippriority, revenue], unmatched rows
    nulled in every lane."""
    # j: [l_orderkey, revenue, o_orderkey, o_orderdate, o_shippriority]
    matched = j.column(2).valid_mask()
    return Table([
        _null_where(j.column(0), ~matched),
        _null_where(j.column(3), ~matched),
        _null_where(j.column(4), ~matched),
        Column(j.column(1).dtype, j.column(1).data,
               j.column(1).valid_mask() & matched),
    ])


def _q3_plan(segment: int, cutoff: int, out_factor: int) -> fusion.Plan:
    """Single-executor q3 as ONE fused region: filter all three inputs,
    orders x customer join, lineitem x orders join, groupby, order-by —
    nine nodes, one executable (the staged path compiled five)."""
    cust = fusion.Project(fusion.Scan("customer"), _q3_cust_fn, (segment,))
    ord_n = fusion.Project(fusion.Scan("orders"), _q3_orders_fn, (cutoff,))
    probe = fusion.Project(fusion.Scan("lineitem"), _q3_probe_fn, (cutoff,))
    j1 = fusion.Join(ord_n, cust, (0,), (0,), fusion.rows_of("orders"),
                     label="join1")
    build = fusion.Project(j1, _q3_build_fn)
    j2 = fusion.Join(probe, build, (0,), (0,),
                     fusion.rows_of("lineitem", out_factor), label="join2")
    g = fusion.GroupBy(fusion.Project(j2, _q3_keyed_fn), (0, 1, 2),
                       ((3, "sum"),), label="groupby")
    return fusion.Plan("tpch_q3", fusion.Sort(
        g, (3, 1), ascending=(False, True), nulls_first=(False, False)))


class Q3Result(NamedTuple):
    result: GroupByResult  # [l_orderkey, o_orderdate, o_shippriority, rev]
    join_total: jnp.ndarray  # true lineitem-x-orders match count
    out_cap: int             # static join output bound (check total <= cap)


@func_range("tpch_q3")
def tpch_q3(customer: Table, orders: Table, lineitem: Table,
            segment: int = 0, cutoff: int = _Q3_CUTOFF_DAYS,
            out_factor: int = 2) -> Q3Result:
    """Single-executor q3. Grouped rows
    [l_orderkey, o_orderdate, o_shippriority, revenue] padded; callers
    compact + head for the LIMIT, and check ``join_total <= out_cap`` on
    host (join_auto pattern) — exceeding it means matches were dropped."""
    res = fusion.execute(
        _q3_plan(segment, cutoff, out_factor),
        {"customer": customer, "orders": orders, "lineitem": lineitem})
    return Q3Result(
        GroupByResult(res.table, res.meta["groupby.num_groups"]),
        res.meta["join2.total"], lineitem.num_rows * out_factor)


class Q3PlannedResult(NamedTuple):
    result: GroupByResult  # [l_orderkey, o_orderdate, o_shippriority, rev]
    join_total: jnp.ndarray
    # planner-contract check: any dense-PK declaration violated (caller
    # re-plans on tpch_q3 — the domain_miss posture)
    pk_violation: jnp.ndarray


def _q3_build2_fn(j1t: Table) -> Table:
    """orders-x-customer dense-PK output -> second-lookup build side.
    dense_pk_join folds its matched mask into the gathered build column's
    validity, so column 4's validity IS ``matched1``."""
    # j1t: [o_custkey, o_orderkey, o_orderdate, o_shippriority, c_custkey]
    matched1 = j1t.column(4).valid_mask()
    return Table([
        _null_where(j1t.column(1), ~matched1),  # orderkey
        j1t.column(2),                          # orderdate
        j1t.column(3),                          # shippriority
    ])


def _q3_planned_keyed_fn(jt: Table) -> Table:
    """Dense-PK lineitem x orders output -> groupby-keyed table. Build
    columns already carry the matched mask from the gather."""
    # jt: [l_orderkey, revenue, o_orderkey, o_orderdate, o_shippriority]
    matched = jt.column(2).valid_mask()
    return Table([
        _null_where(jt.column(0), ~matched),
        jt.column(3),
        jt.column(4),
        Column(jt.column(1).dtype, jt.column(1).data,
               jt.column(1).valid_mask() & matched),
    ])


def _q3_planned_plan(segment: int, cutoff: int) -> fusion.Plan:
    """q3 with planner-declared dense clustered PKs, as one fused region.
    The clustered build sides (customer, the orders-aligned lookup table)
    ride UNBUCKETED scans: dense_pk_join's clustered layout declares
    ``build rows == key_hi - key_lo + 1``, which padding would break."""
    cust = fusion.Project(fusion.Scan("customer", bucket=False),
                          _q3_cust_fn, (segment,))
    ord_n = fusion.Project(fusion.Scan("orders", bucket=False),
                           _q3_orders_fn, (cutoff,))
    probe = fusion.Project(fusion.Scan("lineitem"), _q3_probe_fn, (cutoff,))
    # join 1: each ORDER row looks up its customer (clustered custkey);
    # ord_n rows are orders rows in load order, custkey domain 1..|C|
    j1 = fusion.DensePkJoin(ord_n, cust, 0, 0, 1,
                            fusion.rows_of("customer"), clustered=True,
                            label="pk1")
    build2 = fusion.Project(j1, _q3_build2_fn)
    # join 2: each LINEITEM row looks up its order (clustered orderkey,
    # build2 rows still in orders load order = orderkey order)
    j2 = fusion.DensePkJoin(probe, build2, 0, 0, 1,
                            fusion.rows_of("orders"), clustered=True,
                            label="pk2")
    g = fusion.GroupBy(fusion.Project(j2, _q3_planned_keyed_fn), (0, 1, 2),
                       ((3, "sum"),), label="groupby")
    return fusion.Plan("tpch_q3_planned", fusion.Sort(
        g, (3, 1), ascending=(False, True), nulls_first=(False, False)))


@func_range("tpch_q3_planned")
def tpch_q3_planned(customer: Table, orders: Table, lineitem: Table,
                    segment: int = 0,
                    cutoff: int = _Q3_CUTOFF_DAYS) -> Q3PlannedResult:
    """q3 with PLANNER-DECLARED dense clustered PKs: custkey = 1..|C|
    clustered in customer, orderkey = 1..|O| clustered in orders (the
    TPC-H DDL + load-order facts). Both joins collapse to arithmetic +
    gather — the join phase compiles with ZERO sorts (HLO-pinned in
    tests), where the general q3 pays two build-side lexsorts + probe
    searchsorteds on the 230 ns/row machinery (BASELINE.md). The
    orderkey groupby stays on the general (sort-based) path: its
    cardinality is data-dependent, which is exactly the boundary of
    what a planner can declare.

    Output rows are one per LINEITEM row (PK fanout <= 1): no join
    capacity estimate, no overflow retry — the static shape is the
    probe's.
    """
    res = fusion.execute(
        _q3_planned_plan(segment, cutoff),
        {"customer": customer, "orders": orders, "lineitem": lineitem})
    return Q3PlannedResult(
        GroupByResult(res.table, res.meta["groupby.num_groups"]),
        res.meta["pk2.total"],
        res.meta["pk1.pk_violation"] | res.meta["pk2.pk_violation"])


def tpch_q3_numpy(customer: Table, orders: Table, lineitem: Table,
                  segment: int = 0, cutoff: int = _Q3_CUTOFF_DAYS) -> dict:
    """Host oracle: {orderkey: (revenue, orderdate, shippriority)}."""
    seg = np.asarray(customer.column(C_MKTSEGMENT).data)
    ckey = np.asarray(customer.column(C_CUSTKEY).data)
    good_cust = set(ckey[seg == segment].tolist())
    okey = np.asarray(orders.column(O_ORDERKEY).data)
    ocust = np.asarray(orders.column(O_CUSTKEY).data)
    odate = np.asarray(orders.column(O_ORDERDATE).data)
    oprio = np.asarray(orders.column(O_SHIPPRIORITY).data)
    good_orders = {}
    for k, c, d, p in zip(okey, ocust, odate, oprio):
        if d < cutoff and int(c) in good_cust:
            good_orders[int(k)] = (int(d), int(p))
    lkey = np.asarray(lineitem.column(L3_ORDERKEY).data)
    price = np.asarray(lineitem.column(L3_EXTENDEDPRICE).data)
    disc = np.asarray(lineitem.column(L3_DISCOUNT).data)
    ldate = np.asarray(lineitem.column(L3_SHIPDATE).data)
    out = {}
    for k, p, dc, d in zip(lkey, price, disc, ldate):
        k = int(k)
        if d > cutoff and k in good_orders:
            rev = int(p) * (100 - int(dc))
            date, prio = good_orders[k]
            if k in out:
                out[k] = (out[k][0] + rev, date, prio)
            else:
                out[k] = (rev, date, prio)
    return out


def _q3_group_plan() -> fusion.Plan:
    """Per-device q3 group step (exchange-2 output -> keyed groupby)."""
    return fusion.Plan("tpch_q3_group", fusion.GroupBy(
        fusion.Project(fusion.Scan("joined"), _q3_keyed_fn), (0, 1, 2),
        ((3, "sum"),), label="groupby"))


def _q3_group_step(j: Table):
    """Shard-local tail of the distributed q3 (runs inside shard_map, so
    fusion.execute takes its staged walk on the tracer input — the plan
    still pins the node structure shared with the fused single-chip q3)."""
    res = fusion.execute(_q3_group_plan(), {"joined": j})
    return res.table, res.meta["groupby.num_groups"].reshape(1)


def _q3_partial_plan(cutoff: int) -> fusion.Plan:
    """Out-of-core q3 per-chunk region: probe projection + clustered-PK
    lookup against the resident build2 (an exact scan — the clustered
    layout declares build rows == declared key range, which padding would
    break) + revenue partial groupby. ``rows_of`` specs resolve from TRUE
    row counts: the groupby budget is the chunk's row count (the staged
    ``max_groups=keyed.num_rows`` shape) and key_hi is |orders|."""
    probe = fusion.Project(fusion.Scan("chunk"), _q3_probe_fn, (cutoff,))
    j2 = fusion.DensePkJoin(probe, fusion.Scan("build2", bucket=False),
                            0, 0, 1, fusion.rows_of("build2"),
                            clustered=True, label="pk2")
    return fusion.Plan("tpch_q3_partial", fusion.GroupBy(
        fusion.Project(j2, _q3_planned_keyed_fn), (0, 1, 2), ((3, "sum"),),
        max_groups=fusion.rows_of("chunk"), label="partial"))


def _q3_merge_plan() -> fusion.Plan:
    """Merge the stacked q3 partials: sum-merge + output order, fused.
    (Final null-key compaction happens on host — dynamic shape.)"""
    return fusion.Plan("tpch_q3_merge", fusion.Sort(
        fusion.GroupBy(fusion.Scan("partials"), (0, 1, 2), ((3, "sum"),),
                       label="merge"),
        (3, 1), ascending=(False, True), nulls_first=(False, False)))


def tpch_q3_distributed(customer: Table, orders: Table, lineitem: Table,
                        mesh, segment: int = 0,
                        cutoff: int = _Q3_CUTOFF_DAYS,
                        out_factor: int = 4) -> Table:
    """Multi-executor q3: the REPARTITIONED two-exchange plan. Exchange 1
    co-locates orders and customers by custkey hash; exchange 2 co-locates
    the qualifying orders with lineitem by orderkey hash. After exchange 2
    every orderkey lives on exactly one device, so the per-device groupby
    partitions the global answer; collect + one tiny host sort finishes."""
    import jax as _jax
    from jax.sharding import PartitionSpec as P

    from spark_rapids_jni_tpu.parallel.distributed import (
        _mesh_fingerprint,
        collect,
        distributed_join,
        shard_table,
    )
    from spark_rapids_jni_tpu.parallel.mesh import EXEC_AXIS
    from spark_rapids_jni_tpu.runtime import dispatch

    d = int(np.prod(list(mesh.shape.values())))
    n_ord, n_li = orders.num_rows, lineitem.num_rows

    cust, ord_t, probe = _q3_inputs(customer, orders, lineitem, segment,
                                    cutoff)

    so, orv = shard_table(ord_t, mesh, return_row_valid=True)
    sc, crv = shard_table(cust, mesh, return_row_valid=True)
    res1 = distributed_join(
        so, sc, 0, 0, mesh,
        out_size_per_device=max(1, n_ord // max(d // 2, 1)),
        left_capacity=max(1, n_ord // d * 2),
        right_capacity=max(1, customer.num_rows // d * 2),
        left_row_valid=orv, right_row_valid=crv,
    )
    if np.asarray(res1.overflowed).any():
        raise ValueError("q3 exchange 1 overflowed; raise capacities")
    oc = res1.table  # sharded: [o_custkey, o_orderkey, o_date, o_prio, c_custkey]
    matched = oc.column(4).valid_mask()
    build = Table([
        Column(oc.column(1).dtype, oc.column(1).data,
               oc.column(1).valid_mask() & matched),
        oc.column(2), oc.column(3),
    ])

    sp, prv = shard_table(probe, mesh, return_row_valid=True)
    # inner join: null-key build rows never match, so key validity doubles
    # as the row mask (saves shuffle capacity on exchange-1 padding)
    res2 = distributed_join(
        sp, build, 0, 0, mesh,
        out_size_per_device=max(1, n_li * out_factor // max(d // 2, 1)),
        left_capacity=max(1, n_li // d * 2),
        right_capacity=max(1, build.num_rows // d * 2),
        left_row_valid=prv, right_row_valid=build.column(0).valid_mask(),
    )
    if np.asarray(res2.overflowed).any():
        raise ValueError("q3 exchange 2 overflowed; raise capacities")

    out, num_groups = dispatch.sharded_call(
        "tpch_q3_distributed.group_step",
        lambda: _jax.shard_map(
            _q3_group_step, mesh=mesh, in_specs=(P(EXEC_AXIS),),
            out_specs=(P(EXEC_AXIS), P(EXEC_AXIS)),
        ),
        (res2.table,),
        statics=(_mesh_fingerprint(mesh),),
    )
    result = collect(out, num_groups, mesh)
    srt = sort_table(result, [3, 1], ascending=[False, True],
                     nulls_first=[False, False])
    # drop the null-key pseudo-groups (unmatched/padding)
    kv = np.asarray(srt.column(0).valid_mask())
    k = int(kv.sum())
    return Table([
        Column(c.dtype, c.data[:k],
               None if c.validity is None else c.validity[:k])
        for c in srt.columns
    ])


class Q10Result(NamedTuple):
    result: GroupByResult   # [c_custkey, c_nationkey, revenue] rev desc
    join_total: jnp.ndarray
    pk_violation: jnp.ndarray


_Q10_QTR_START = 8582   # 1993-07-01
_Q10_QTR_END = 8674     # 1993-10-01


@func_range("tpch_q10")
def tpch_q10(customer: Table, orders: Table, lineitem: Table,
             qtr_start: int = _Q10_QTR_START,
             qtr_end: int = _Q10_QTR_END) -> Q10Result:
    """q10 (returned-item reporting): lineitem filtered to returns,
    joined through orders (quarter filter pushed into the build keys)
    to the customer, grouped by customer, revenue-desc — the LIMIT 20
    head is the caller's compact+head.

    The plan mixes both machineries deliberately: the joins are dense
    clustered-PK lookups (sort-free, probe-aligned), while the
    customer groupby is HIGH-cardinality — outside every declared-
    domain trick — so it rides the general sort-based groupby. This is
    the realistic SF-scale shape: planner facts kill the join costs,
    the one irreducible data-dependent grouping remains.

    ``lineitem`` here is the q3 layout + a returnflag column appended:
    [l_orderkey, l_extendedprice, l_discount, l_shipdate,
    l_returnflag]."""
    from spark_rapids_jni_tpu.ops.planner import dense_pk_join

    n_cust, n_ord = customer.num_rows, orders.num_rows
    rf = lineitem.column(4)
    returned = rf.valid_mask() & (rf.data == jnp.int8(ord("R")))
    price = lineitem.column(L3_EXTENDEDPRICE)
    disc = lineitem.column(L3_DISCOUNT)
    revenue = Column(
        t.decimal64(-4), price.data * (100 - disc.data),
        price.valid_mask() & disc.valid_mask() & returned)
    probe = Table([
        _null_where(lineitem.column(L3_ORDERKEY), ~returned),
        revenue,
    ])
    od = orders.column(O_ORDERDATE)
    in_qtr = (od.valid_mask() & (od.data >= jnp.int32(qtr_start))
              & (od.data < jnp.int32(qtr_end)))
    ord_build = Table([
        _null_where(orders.column(O_ORDERKEY), ~in_qtr),
        orders.column(O_CUSTKEY),
    ])
    j_o = dense_pk_join(probe, ord_build, 0, 0, 1, n_ord,
                        clustered=True)
    o_cust = j_o.table.column(3)
    j_c = dense_pk_join(Table([o_cust]), customer, 0, C5_CUSTKEY,
                        1, n_cust, clustered=True)
    c_key = j_c.table.column(1)
    c_nat = j_c.table.column(2)
    keep = j_o.matched & j_c.matched
    keyed = Table([
        _null_where(c_key, ~keep),
        c_nat,
        Column(revenue.dtype, revenue.data,
               revenue.valid_mask() & keep),
    ])
    g = groupby_aggregate(keyed, keys=[0, 1], aggs=[(2, "sum")])
    srt = sort_table(g.table, [2], ascending=[False],
                     nulls_first=[False])
    return Q10Result(
        GroupByResult(srt, g.num_groups),
        jnp.sum(keep.astype(jnp.int64)),
        j_o.pk_violation | j_c.pk_violation)


def tpch_q10_numpy(customer: Table, orders: Table, lineitem: Table,
                   qtr_start: int = _Q10_QTR_START,
                   qtr_end: int = _Q10_QTR_END) -> dict:
    """Host oracle: {c_custkey: (nationkey, revenue)}."""
    c_nat = {int(k): int(v) for k, v in zip(
        np.asarray(customer.column(C5_CUSTKEY).data),
        np.asarray(customer.column(C5_NATIONKEY).data))}
    o_cust = {}
    for k, c, d in zip(np.asarray(orders.column(O_ORDERKEY).data),
                       np.asarray(orders.column(O_CUSTKEY).data),
                       np.asarray(orders.column(O_ORDERDATE).data)):
        if qtr_start <= int(d) < qtr_end:
            o_cust[int(k)] = int(c)
    out: dict = {}
    lkey = np.asarray(lineitem.column(L3_ORDERKEY).data)
    price = np.asarray(lineitem.column(L3_EXTENDEDPRICE).data)
    disc = np.asarray(lineitem.column(L3_DISCOUNT).data)
    rf = np.asarray(lineitem.column(4).data)
    for i in range(lineitem.num_rows):
        if rf[i] != ord("R"):
            continue
        cu = o_cust.get(int(lkey[i]))
        if cu is None or cu not in c_nat:
            continue
        rev = int(price[i]) * (100 - int(disc[i]))
        prev = out.get(cu, (c_nat[cu], 0))
        out[cu] = (c_nat[cu], prev[1] + rev)
    return out


def tpch_q3_outofcore(path, customer: Table, orders: Table, *,
                      budget_bytes: int, chunk_read_limit: int,
                      segment: int = 0, cutoff: int = _Q3_CUTOFF_DAYS,
                      prefetch_depth: int = 0,
                      pipeline: bool | None = None):
    """q3 over a lineitem Parquet file larger than the device budget:
    the JOIN side of the SF-scale story (q1 covered pure aggregation).
    customer and orders stay resident (the small sides — the broadcast
    plan's premise); lineitem streams in row-group chunks, each chunk
    joins through the dense clustered-PK lookups (probe-aligned, no
    join machinery to size) and partial-aggregates revenue by orderkey;
    host-compacted partials merge at the end. The partial->merge
    algebra is tpch_q3_planned_distributed's, run over TIME instead of
    the mesh.

    File schema: [l_orderkey int64, l_extendedprice int64,
    l_discount int64, l_shipdate date32]. Returns OutOfCoreResult;
    ``.table`` matches tpch_q3's compacted output of the materialized
    file.

    The per-chunk device step is ONE fused region (probe projection +
    clustered-PK lookup + partial groupby); the resident build2 rides
    the region as an exact (unbucketed) scan, and the dead chunk tables
    are donated. The host ``trim_table`` compaction and the final merge
    plan are the region boundaries."""
    from spark_rapids_jni_tpu.ops.planner import dense_pk_join
    from spark_rapids_jni_tpu.parquet.reader import ParquetChunkedReader
    from spark_rapids_jni_tpu.runtime.memory import MemoryLimiter, SpillStore
    from spark_rapids_jni_tpu.runtime.outofcore import run_chunked_aggregate

    n_cust = customer.num_rows
    limiter = MemoryLimiter(budget_bytes)
    spill = SpillStore(budget_bytes)

    # the resident build side, computed once: orders |x| customer via
    # the clustered custkey lookup, date/segment predicates pushed in
    cust = _q3_cust_fn(customer, segment)
    ord_t = _q3_orders_fn(orders, cutoff)
    j1 = dense_pk_join(ord_t, cust, 0, 0, 1, n_cust, clustered=True)
    if bool(j1.pk_violation):
        raise ValueError("customer PK declaration violated")
    build2 = _q3_build2_fn(j1.table)

    # runtime bloom filter: the resident build side's orderkeys, built
    # once, prune every streamed lineitem chunk on the HOST side before
    # the chunk is reserved/staged (compaction is free at the chunk
    # boundary) — fewer bytes reserved and spilled, bit-identical bytes
    # out. Gated per plan signature by the learned selectivity EMA.
    decision = rtfilter.decide("tpch_q3_outofcore", "pk2",
                               build2.num_rows)
    chunk_filter = None
    if decision.apply:
        bcol = build2.column(0)
        chunk_filter = rtfilter.build_filter(
            bcol.data, bcol.valid_mask(),
            expected_items=build2.num_rows)

    def partial_fn(chunk: Table) -> Table:
        from spark_rapids_jni_tpu.ops.table_ops import trim_table

        cols = list(chunk.columns)
        cols[1] = Column(t.decimal64(-2), cols[1].data, cols[1].validity)
        cols[2] = Column(t.decimal64(-2), cols[2].data, cols[2].validity)
        res = fusion.execute(
            _q3_partial_plan(cutoff),
            {"chunk": Table(cols), "build2": build2},
            donate_inputs=True)
        if bool(res.meta["pk2.pk_violation"]):
            raise ValueError("orders PK declaration violated")
        return trim_table(res.table, int(res.meta["partial.num_groups"]))

    def merge_fn(partials: Table) -> Table:
        srt = fusion.execute(_q3_merge_plan(), {"partials": partials}).table
        kv = np.asarray(srt.column(0).valid_mask())
        k = int(kv.sum())
        return Table([
            Column(c.dtype, c.data[:k],
                   None if c.validity is None else c.validity[:k])
            for c in srt.columns
        ])

    reader = ParquetChunkedReader(path, chunk_read_limit=chunk_read_limit)
    chunks = reader if chunk_filter is None else rtfilter.pruned_chunks(
        reader, chunk_filter, 0, plan_name="tpch_q3_outofcore",
        label="pk2")
    return run_chunked_aggregate(
        chunks, partial_fn, merge_fn, limiter=limiter, spill=spill,
        prefetch_depth=prefetch_depth, pipeline=pipeline)


def tpch_q3_planned_distributed(customer: Table, orders: Table,
                                lineitem: Table, mesh, segment: int = 0,
                                cutoff: int = _Q3_CUTOFF_DAYS) -> Table:
    """Multi-executor planned q3: the BROADCAST plan the dense-PK
    declarations unlock. customer and orders replicate to every device
    (they are the small sides); each device runs both clustered-PK
    lookups on its lineitem shard — sort-free, no join exchange at all —
    then partial-aggregates revenue by orderkey locally. The ONLY
    exchange in the whole plan is the partial-aggregate shuffle (m
    partial rows per device, not n), where the general distributed q3
    pays two full row exchanges before it even reaches that point.
    Returns the collected, sorted, compacted global result (same
    contract as tpch_q3_distributed)."""
    import jax as _jax
    from jax.sharding import PartitionSpec as P

    from spark_rapids_jni_tpu.ops.planner import dense_pk_join
    from spark_rapids_jni_tpu.parallel.distributed import (
        _mesh_fingerprint,
        collect,
        shard_table,
    )
    from spark_rapids_jni_tpu.parallel.mesh import EXEC_AXIS
    from spark_rapids_jni_tpu.parallel.shuffle import hash_shuffle
    from spark_rapids_jni_tpu.runtime import dispatch

    cust, ord_t, probe = _q3_inputs(customer, orders, lineitem, segment,
                                    cutoff)
    sp, prv = shard_table(probe, mesh, return_row_valid=True)
    n_cust, n_ord = customer.num_rows, orders.num_rows

    def step(local: Table, rv, cust_r: Table, ord_r: Table):
        j1 = dense_pk_join(ord_r, cust_r, 0, 0, 1, n_cust,
                           clustered=True)
        build2 = Table([
            _null_where(j1.table.column(1), ~j1.matched),
            j1.table.column(2), j1.table.column(3),
        ])
        j2 = dense_pk_join(local, build2, 0, 0, 1, n_ord,
                           clustered=True)
        jt = j2.table
        matched = j2.matched & rv
        keyed = Table([
            _null_where(jt.column(0), ~matched),
            jt.column(3), jt.column(4),
            Column(jt.column(1).dtype, jt.column(1).data,
                   jt.column(1).valid_mask() & matched),
        ])
        local_n = keyed.num_rows
        partial = groupby_aggregate(keyed, keys=[0, 1, 2],
                                    aggs=[(3, "sum")],
                                    max_groups=local_n)
        real = (jnp.arange(local_n, dtype=jnp.int32)
                < partial.num_groups)
        # a sender holds <= local_n real partial rows total, so the
        # per-receiver lane capacity local_n can never overflow
        sh = hash_shuffle(partial.table, [0], EXEC_AXIS,
                          capacity=local_n, row_valid=real)
        merged = groupby_aggregate(sh.table, keys=[0, 1, 2],
                                   aggs=[(3, "sum")])
        viol = (j1.pk_violation | j2.pk_violation)
        return (merged.table, merged.num_groups.reshape(1),
                viol.reshape(1))

    out, num_groups, viol = dispatch.sharded_call(
        "tpch_q3_planned_distributed.step",
        lambda: _jax.shard_map(
            step, mesh=mesh,
            in_specs=(P(EXEC_AXIS), P(EXEC_AXIS), P(), P()),
            out_specs=(P(EXEC_AXIS), P(EXEC_AXIS), P(EXEC_AXIS)),
        ),
        (sp, prv, cust, ord_t),
        statics=(n_cust, n_ord, _mesh_fingerprint(mesh)),
    )
    if bool(np.asarray(viol).any()):
        raise ValueError(
            "dense-PK declaration violated — re-plan with "
            "tpch_q3_distributed")
    result = collect(out, num_groups, mesh)
    srt = sort_table(result, [3, 1], ascending=[False, True],
                     nulls_first=[False, False])
    kv = np.asarray(srt.column(0).valid_mask())
    k = int(kv.sum())
    return Table([
        Column(c.dtype, c.data[:k],
               None if c.validity is None else c.validity[:k])
        for c in srt.columns
    ])


# ---------------------------------------------------------------------------
# q5 — local supplier volume: the six-table join (customer, orders,
# lineitem, supplier, nation, region) grouped by nation. The TPU plan is
# built ENTIRELY from planner facts: every join is a dense clustered-PK
# lookup, the region predicate pushes into the nation build side, the
# c_nationkey = s_nationkey condition is a post-lookup filter, and the
# GROUP BY nation is the bounded masked-reduction over the 25-value DDL
# domain — no sort touches an n-sized array anywhere.
# ---------------------------------------------------------------------------

_Q5_NATIONS = (
    "ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT", "ETHIOPIA",
    "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN", "IRAQ", "JAPAN",
    "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE", "PERU", "CHINA",
    "ROMANIA", "SAUDI ARABIA", "VIETNAM", "RUSSIA", "UNITED KINGDOM",
    "UNITED STATES",
)
_Q5_N_REGIONS = 5
_Q5_YEAR_START = 8766   # 1994-01-01
_Q5_YEAR_END = 9131     # 1995-01-01

# nation columns
N_NATIONKEY, N_REGIONKEY = 0, 1
# supplier columns
S_SUPPKEY, S_NATIONKEY = 0, 1
# q5 customer columns
C5_CUSTKEY, C5_NATIONKEY = 0, 1
# q5 lineitem columns
L5_ORDERKEY, L5_SUPPKEY, L5_EXTENDEDPRICE, L5_DISCOUNT = 0, 1, 2, 3


def nation_table(seed: int = 0) -> Table:
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(np.arange(1, 26, dtype=np.int64)),
        Column.from_numpy(
            rng.integers(1, _Q5_N_REGIONS + 1, 25).astype(np.int64)),
    ])


def supplier_table(num_rows: int, seed: int = 9) -> Table:
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(np.arange(1, num_rows + 1, dtype=np.int64)),
        Column.from_numpy(rng.integers(1, 26, num_rows).astype(np.int64)),
    ])


def customer_q5_table(num_rows: int, seed: int = 10) -> Table:
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(np.arange(1, num_rows + 1, dtype=np.int64)),
        Column.from_numpy(rng.integers(1, 26, num_rows).astype(np.int64)),
    ])


def lineitem_q5_table(num_rows: int, num_orders: int,
                      num_suppliers: int, seed: int = 11) -> Table:
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(
            rng.integers(1, num_orders + 1, num_rows).astype(np.int64)),
        Column.from_numpy(
            rng.integers(1, num_suppliers + 1, num_rows).astype(np.int64)),
        Column.from_numpy(
            rng.integers(90_000, 10_500_000, num_rows).astype(np.int64),
            t.decimal64(-2)),
        Column.from_numpy(
            rng.integers(0, 11, num_rows).astype(np.int64),
            t.decimal64(-2)),
    ])


class Q5Result(NamedTuple):
    table: Table              # [n_nationkey, revenue, n_name], rev desc
    present: jnp.ndarray
    pk_violation: jnp.ndarray
    domain_miss: jnp.ndarray


@func_range("tpch_q5")
def tpch_q5(customer: Table, orders: Table, lineitem: Table,
            supplier: Table, nation: Table, region_of_interest: int = 1,
            year_start: int = _Q5_YEAR_START,
            year_end: int = _Q5_YEAR_END) -> Q5Result:
    """q5 as the all-planner-facts plan (module header). Row flow, one
    output row per LINEITEM row at every stage (PK fanout <= 1):

    lineitem -> supplier (suppkey lookup) -> s_nationkey
             -> orders   (orderkey lookup; date filter pushed into the
                          build key) -> o_custkey
             -> customer (custkey lookup on the gathered o_custkey)
                          -> c_nationkey
             -> nation   (s_nationkey lookup; region filter pushed into
                          the build key) -> survives iff in region
    keep = all matches & c_nationkey == s_nationkey; revenue sums into
    the 25-slot bounded nation groupby.
    """
    from spark_rapids_jni_tpu.ops.planner import (
        dense_pk_join,
        plan_groupby,
        scalar_domain,
    )

    n_supp = supplier.num_rows
    n_ord = orders.num_rows
    n_cust = customer.num_rows

    j_s = dense_pk_join(lineitem, supplier, L5_SUPPKEY, S_SUPPKEY,
                        1, n_supp, clustered=True)
    s_nation = j_s.table.column(lineitem.num_columns + 1)

    od = orders.column(O_ORDERDATE)
    date_ok = (od.valid_mask() & (od.data >= jnp.int32(year_start))
               & (od.data < jnp.int32(year_end)))
    ord_build = Table([
        _null_where(orders.column(O_ORDERKEY), ~date_ok),
        orders.column(O_CUSTKEY),
    ])
    j_o = dense_pk_join(lineitem, ord_build, L5_ORDERKEY, 0,
                        1, n_ord, clustered=True)
    o_cust = j_o.table.column(lineitem.num_columns + 1)

    # dense_pk_join already folded `matched` into the gathered column's
    # validity — the mask is ready to re-probe with
    cust_probe = Table([o_cust])
    j_c = dense_pk_join(cust_probe, customer, 0, C5_CUSTKEY,
                        1, n_cust, clustered=True)
    c_nation = j_c.table.column(2)

    nat_build = Table([
        _null_where(nation.column(N_NATIONKEY),
                    nation.column(N_REGIONKEY).data
                    != jnp.int64(region_of_interest)),
    ])
    nat_probe = Table([s_nation])
    j_n = dense_pk_join(nat_probe, nat_build, 0, 0, 1, 25,
                        clustered=True)

    keep = (j_s.matched & j_o.matched & j_c.matched & j_n.matched
            & (c_nation.data == s_nation.data))
    price = lineitem.column(L5_EXTENDEDPRICE)
    disc = lineitem.column(L5_DISCOUNT)
    rev_ok = keep & price.valid_mask() & disc.valid_mask()
    revenue = Column(
        t.decimal64(-4),
        jnp.where(rev_ok, price.data * (100 - disc.data), 0), rev_ok)
    keyed = Table([
        Column(s_nation.dtype,
               jnp.where(keep, s_nation.data, 0), keep),
        revenue,
    ])
    g = plan_groupby(keyed, [0], [(1, "sum")],
                     [scalar_domain(range(1, 26))])
    assert g.lowered == "bounded"
    # n_name attaches statically BEFORE the tiny ORDER BY: bounded slot
    # i (< 25) is nation key i+1 -> _Q5_NATIONS[i]; the string column
    # then rides the 26-row sort like any other column
    name_w = max(len(nm) for nm in _Q5_NATIONS)
    name_mat = np.zeros((g.table.num_rows, name_w), np.uint8)
    name_len = np.zeros(g.table.num_rows, np.int32)
    for i, nm in enumerate(_Q5_NATIONS):
        b = nm.encode()
        name_mat[i, : len(b)] = np.frombuffer(b, np.uint8)
        name_len[i] = len(b)
    names = Column(t.STRING, jnp.asarray(name_len),
                   g.table.column(0).valid_mask(),
                   chars=jnp.asarray(name_mat))
    srt = sort_table(Table(list(g.table.columns) + [names]),
                     [1], ascending=[False], nulls_first=[False])
    # the 26-row ORDER BY permutes the slot table; present travels as
    # the key validity (bounded output: key valid <=> slot present)
    present = srt.column(0).valid_mask()
    pk_viol = (j_s.pk_violation | j_o.pk_violation | j_c.pk_violation
               | j_n.pk_violation)
    return Q5Result(srt, present, pk_viol, g.domain_miss)


def tpch_q5_numpy(customer: Table, orders: Table, lineitem: Table,
                  supplier: Table, nation: Table,
                  region_of_interest: int = 1,
                  year_start: int = _Q5_YEAR_START,
                  year_end: int = _Q5_YEAR_END) -> dict:
    """Host oracle: {n_nationkey: revenue}."""
    s_nat = {int(k): int(v) for k, v in zip(
        np.asarray(supplier.column(S_SUPPKEY).data),
        np.asarray(supplier.column(S_NATIONKEY).data))}
    c_nat = {int(k): int(v) for k, v in zip(
        np.asarray(customer.column(C5_CUSTKEY).data),
        np.asarray(customer.column(C5_NATIONKEY).data))}
    in_region = {int(k) for k, r in zip(
        np.asarray(nation.column(N_NATIONKEY).data),
        np.asarray(nation.column(N_REGIONKEY).data))
        if int(r) == region_of_interest}
    o_info = {}
    for k, c, d in zip(np.asarray(orders.column(O_ORDERKEY).data),
                       np.asarray(orders.column(O_CUSTKEY).data),
                       np.asarray(orders.column(O_ORDERDATE).data)):
        if year_start <= int(d) < year_end:
            o_info[int(k)] = int(c)
    out: dict = {}
    lkey = np.asarray(lineitem.column(L5_ORDERKEY).data)
    lsupp = np.asarray(lineitem.column(L5_SUPPKEY).data)
    price = np.asarray(lineitem.column(L5_EXTENDEDPRICE).data)
    disc = np.asarray(lineitem.column(L5_DISCOUNT).data)
    for i in range(lineitem.num_rows):
        ok = int(lkey[i])
        if ok not in o_info:
            continue
        sn = s_nat.get(int(lsupp[i]))
        if sn is None or sn not in in_region:
            continue
        if c_nat.get(o_info[ok]) != sn:
            continue
        out[sn] = out.get(sn, 0) + int(price[i]) * (100 - int(disc[i]))
    return out


def tpch_q5_distributed(customer: Table, orders: Table, lineitem: Table,
                        supplier: Table, nation: Table, mesh,
                        region_of_interest: int = 1,
                        year_start: int = _Q5_YEAR_START,
                        year_end: int = _Q5_YEAR_END) -> Q5Result:
    """Multi-executor q5 with ZERO shuffles: lineitem shards row-wise,
    all four dimension tables replicate, each device runs the five
    dense-PK lookups + the 25-slot bounded nation groupby on its shard,
    and the global merge is one psum over the 26-slot sum vector —
    208 bytes on the wire per device. The single-device tpch_q5 IS the
    per-device step; only the merge differs (the bounded-slot
    associativity that makes distributed_groupby_bounded shuffle-free).
    Result is replicated; same schema as tpch_q5."""
    import jax as _jax
    from jax.sharding import PartitionSpec as P

    from spark_rapids_jni_tpu.ops.planner import (
        dense_pk_join,
        plan_groupby,
        scalar_domain,
    )
    from spark_rapids_jni_tpu.parallel.distributed import shard_table
    from spark_rapids_jni_tpu.parallel.mesh import EXEC_AXIS

    n_supp, n_ord = supplier.num_rows, orders.num_rows
    n_cust = customer.num_rows
    sl, rv = shard_table(lineitem, mesh, return_row_valid=True)

    def step(local: Table, lrv, cust_r, ord_r, supp_r, nat_r):
        j_s = dense_pk_join(local, supp_r, L5_SUPPKEY, S_SUPPKEY,
                            1, n_supp, clustered=True)
        s_nation = j_s.table.column(local.num_columns + 1)
        od = ord_r.column(O_ORDERDATE)
        date_ok = (od.valid_mask() & (od.data >= jnp.int32(year_start))
                   & (od.data < jnp.int32(year_end)))
        ord_build = Table([
            _null_where(ord_r.column(O_ORDERKEY), ~date_ok),
            ord_r.column(O_CUSTKEY),
        ])
        j_o = dense_pk_join(local, ord_build, L5_ORDERKEY, 0,
                            1, n_ord, clustered=True)
        o_cust = j_o.table.column(local.num_columns + 1)
        j_c = dense_pk_join(Table([o_cust]), cust_r, 0, C5_CUSTKEY,
                            1, n_cust, clustered=True)
        c_nation = j_c.table.column(2)
        nat_build = Table([
            _null_where(nat_r.column(N_NATIONKEY),
                        nat_r.column(N_REGIONKEY).data
                        != jnp.int64(region_of_interest)),
        ])
        j_n = dense_pk_join(Table([s_nation]), nat_build, 0, 0, 1, 25,
                            clustered=True)
        keep = (lrv & j_s.matched & j_o.matched & j_c.matched
                & j_n.matched & (c_nation.data == s_nation.data))
        price = local.column(L5_EXTENDEDPRICE)
        disc = local.column(L5_DISCOUNT)
        rev_ok = keep & price.valid_mask() & disc.valid_mask()
        keyed = Table([
            Column(s_nation.dtype,
                   jnp.where(keep, s_nation.data, 0), keep),
            Column(t.decimal64(-4),
                   jnp.where(rev_ok, price.data * (100 - disc.data), 0),
                   rev_ok),
        ])
        g = plan_groupby(keyed, [0], [(1, "sum")],
                         [scalar_domain(range(1, 26))], row_valid=lrv)
        # the 26-slot partials merge with ONE collective
        sums = _jax.lax.psum(
            jnp.where(g.table.column(1).valid_mask(),
                      g.table.column(1).data, 0), EXEC_AXIS)
        valid_g = _jax.lax.psum(
            g.table.column(1).valid_mask().astype(jnp.int32),
            EXEC_AXIS) > 0
        viol = _jax.lax.psum(
            (j_s.pk_violation | j_o.pk_violation | j_c.pk_violation
             | j_n.pk_violation).astype(jnp.int32), EXEC_AXIS) > 0
        miss = _jax.lax.psum(
            g.domain_miss.astype(jnp.int32), EXEC_AXIS) > 0
        return (g.table.column(0).data, sums, valid_g,
                viol, miss)

    keys, sums, valid_g, viol, miss = _jax.jit(_jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(EXEC_AXIS), P(EXEC_AXIS), P(), P(), P(), P()),
        out_specs=(P(), P(), P(), P(), P()),
    ))(sl, rv, customer, orders, supplier, nation)

    out = Table([
        Column(t.INT64, keys, valid_g),
        Column(t.decimal64(-4), sums, valid_g),
    ])
    name_w = max(len(nm) for nm in _Q5_NATIONS)
    name_mat = np.zeros((out.num_rows, name_w), np.uint8)
    name_len = np.zeros(out.num_rows, np.int32)
    for i, nm in enumerate(_Q5_NATIONS):
        b = nm.encode()
        name_mat[i, : len(b)] = np.frombuffer(b, np.uint8)
        name_len[i] = len(b)
    names = Column(t.STRING, jnp.asarray(name_len), valid_g,
                   chars=jnp.asarray(name_mat))
    srt = sort_table(Table(list(out.columns) + [names]), [1],
                     ascending=[False], nulls_first=[False])
    return Q5Result(srt, srt.column(0).valid_mask(), viol, miss)


# ---------------------------------------------------------------------------
# q12 — shipping modes and order priority (join + string-key groupby with
# conditional counts). Reference workload family: BASELINE.json config #4's
# "hash-join + reader" shape; predicates are Spark CASE WHEN lowering onto
# masked integer lanes.
# ---------------------------------------------------------------------------

# q12 lineitem columns
L12_ORDERKEY, L12_SHIPMODE, L12_COMMITDATE = 0, 1, 2
L12_RECEIPTDATE, L12_SHIPDATE = 3, 4
# q12 orders columns
O12_ORDERKEY, O12_ORDERPRIORITY = 0, 1

_Q12_MODES = ("MAIL", "SHIP", "AIR", "RAIL", "TRUCK", "FOB", "REG AIR")
_Q12_PRIORITIES = ("1-URGENT", "2-HIGH", "3-MEDIUM",
                   "4-NOT SPECIFIED", "5-LOW")
_Q12_YEAR_START = 8766   # 1994-01-01 in days
_Q12_YEAR_END = 9131     # 1995-01-01


def lineitem_q12_table(num_rows: int, num_orders: int,
                       seed: int = 3) -> Table:
    rng = np.random.default_rng(seed)
    ship = rng.integers(8400, 10957, num_rows).astype(np.int32)
    commit = ship + rng.integers(-30, 60, num_rows).astype(np.int32)
    receipt = commit + rng.integers(-20, 40, num_rows).astype(np.int32)
    return Table([
        Column.from_numpy(
            rng.integers(1, num_orders + 1, num_rows).astype(np.int64)),
        Column.from_pylist(
            [_Q12_MODES[i] for i in rng.integers(0, len(_Q12_MODES),
                                                 num_rows)], t.STRING),
        Column.from_numpy(commit, t.TIMESTAMP_DAYS),
        Column.from_numpy(receipt, t.TIMESTAMP_DAYS),
        Column.from_numpy(ship, t.TIMESTAMP_DAYS),
    ])


def orders_q12_table(num_rows: int, seed: int = 4) -> Table:
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(np.arange(1, num_rows + 1, dtype=np.int64)),
        Column.from_pylist(
            [_Q12_PRIORITIES[i]
             for i in rng.integers(0, len(_Q12_PRIORITIES), num_rows)],
            t.STRING),
    ])


def _q12_keep(lineitem: Table, mode_c: Column, modes: tuple,
              year_start: int, year_end: int) -> jnp.ndarray:
    """Shared q12 WHERE (single change point for single-device and
    distributed plans, the _q3_inputs convention): mode IN list + date
    sanity predicates, null operands not-TRUE (every valid_mask ANDed)."""
    from spark_rapids_jni_tpu.ops import strings as s

    in_modes = jnp.zeros((lineitem.num_rows,), jnp.bool_)
    for mname in modes:
        in_modes = in_modes | (s.like(mode_c, mname).data != 0)
    commit_c = lineitem.column(L12_COMMITDATE)
    receipt_c = lineitem.column(L12_RECEIPTDATE)
    ship_c = lineitem.column(L12_SHIPDATE)
    return (in_modes & mode_c.valid_mask() & commit_c.valid_mask()
            & receipt_c.valid_mask() & ship_c.valid_mask()
            & (commit_c.data < receipt_c.data)
            & (ship_c.data < commit_c.data)
            & (receipt_c.data >= jnp.int32(year_start))
            & (receipt_c.data < jnp.int32(year_end)))


def _q12_priority_lanes(prio: Column, matched: jnp.ndarray):
    """Shared CASE WHEN o_orderpriority IN ('1-URGENT','2-HIGH') lanes."""
    from spark_rapids_jni_tpu.ops import strings as s

    urgent = ((s.like(prio, "1-URGENT").data != 0)
              | (s.like(prio, "2-HIGH").data != 0))
    high = Column(t.INT64,
                  jnp.where(matched & urgent, jnp.int64(1), jnp.int64(0)),
                  matched)
    low = Column(t.INT64,
                 jnp.where(matched & ~urgent, jnp.int64(1), jnp.int64(0)),
                 matched)
    return high, low


class Q12Result(NamedTuple):
    result: GroupByResult    # [l_shipmode, high_line_count, low_line_count]
    join_total: jnp.ndarray


@func_range("tpch_q12")
def tpch_q12(orders: Table, lineitem: Table,
             modes: tuple = ("MAIL", "SHIP"),
             year_start: int = _Q12_YEAR_START,
             year_end: int = _Q12_YEAR_END) -> Q12Result:
    """q12: lineitem filtered on mode/date sanity predicates, joined to
    orders on orderkey, grouped by shipmode with CASE-WHEN priority
    counts. Static shapes: the WHERE lowers to a nulled join key (the
    q3 idiom), CASE WHEN to masked int lanes."""
    from spark_rapids_jni_tpu.ops import strings as s
    from spark_rapids_jni_tpu.ops.join import apply_join_maps, join

    mode_c = lineitem.column(L12_SHIPMODE)
    keep = _q12_keep(lineitem, mode_c, modes, year_start, year_end)
    probe = Table([
        _null_where(lineitem.column(L12_ORDERKEY), ~keep),
        mode_c,
    ])
    maps = join(probe, orders, 0, 0, out_size=lineitem.num_rows)
    j = apply_join_maps(probe, orders, maps)
    # j: [l_orderkey, l_shipmode, o_orderkey, o_orderpriority]
    matched = j.column(2).valid_mask()
    high, low = _q12_priority_lanes(j.column(3), matched)
    keyed = Table([
        _null_where(j.column(1), ~matched), high, low,
    ])
    g = groupby_aggregate(keyed, keys=[0], aggs=[(1, "sum"), (2, "sum")])
    srt = sort_table(g.table, [0], nulls_first=[False])
    return Q12Result(GroupByResult(srt, g.num_groups), maps.total)


def tpch_q12_numpy(orders: Table, lineitem: Table,
                   modes: tuple = ("MAIL", "SHIP"),
                   year_start: int = _Q12_YEAR_START,
                   year_end: int = _Q12_YEAR_END) -> dict:
    prio = {int(k): p for k, p in zip(
        np.asarray(orders.column(O12_ORDERKEY).data).tolist(),
        orders.column(O12_ORDERPRIORITY).to_pylist())}
    out: dict = {}
    lmode = lineitem.column(L12_SHIPMODE).to_pylist()
    lkey = np.asarray(lineitem.column(L12_ORDERKEY).data).tolist()
    commit = np.asarray(lineitem.column(L12_COMMITDATE).data).tolist()
    receipt = np.asarray(lineitem.column(L12_RECEIPTDATE).data).tolist()
    ship = np.asarray(lineitem.column(L12_SHIPDATE).data).tolist()
    for i in range(lineitem.num_rows):
        if lmode[i] not in modes:
            continue
        if not (commit[i] < receipt[i] and ship[i] < commit[i]
                and year_start <= receipt[i] < year_end):
            continue
        p = prio.get(lkey[i])
        if p is None:
            continue
        hi, lo = out.setdefault(lmode[i], [0, 0])
        if p in ("1-URGENT", "2-HIGH"):
            out[lmode[i]][0] += 1
        else:
            out[lmode[i]][1] += 1
    return out


@func_range("tpch_q12_planned_result")
def tpch_q12_planned_result(orders: Table, lineitem: Table,
                            modes: tuple = ("MAIL", "SHIP"),
                            year_start: int = _Q12_YEAR_START,
                            year_end: int = _Q12_YEAR_END):
    """q12 on the sort-free plan: the l_shipmode GROUP BY key's domain is
    the query's own IN-list (a planner fact, like q1's DDL flag domains),
    so the post-join aggregation lowers to the bounded masked-reduction
    pass — the shipmode strings are dictionary-encoded on device and the
    output keys decode to static strings at trace time. Join unchanged
    (it is the sort-based machinery); the groupby stage carries no sort,
    scan, or scatter (HLO-pinned in tests)."""
    from spark_rapids_jni_tpu.ops import strings as s
    from spark_rapids_jni_tpu.ops.join import apply_join_maps, join
    from spark_rapids_jni_tpu.ops.planner import plan_groupby, string_domain

    mode_c = s.pad_strings(lineitem.column(L12_SHIPMODE))
    keep = _q12_keep(lineitem, mode_c, modes, year_start, year_end)
    probe = Table([
        _null_where(lineitem.column(L12_ORDERKEY), ~keep),
        mode_c,
    ])
    maps = join(probe, orders, 0, 0, out_size=lineitem.num_rows)
    j = apply_join_maps(probe, orders, maps)
    # j: [l_orderkey, l_shipmode, o_orderkey, o_orderpriority]
    matched = j.column(2).valid_mask()
    high, low = _q12_priority_lanes(j.column(3), matched)
    mode_j = j.column(1)
    keyed = Table([
        Column(mode_j.dtype,
               jnp.where(matched, mode_j.data, 0), matched,
               chars=jnp.where(matched[:, None], mode_j.chars,
                               jnp.uint8(0))),
        high, low,
    ])
    return plan_groupby(keyed, keys=[0], aggs=[(1, "sum"), (2, "sum")],
                        domains=[string_domain(modes)])


def tpch_q12_planned(orders: Table, lineitem: Table,
                     modes: tuple = ("MAIL", "SHIP"),
                     year_start: int = _Q12_YEAR_START,
                     year_end: int = _Q12_YEAR_END) -> Table:
    """Planned q12, table only — [l_shipmode, high_line_count,
    low_line_count], mode-sorted with the null pseudo-group last (the
    bounded plan's static order; same ordering contract as tpch_q12)."""
    return tpch_q12_planned_result(
        orders, lineitem, modes, year_start, year_end).table


# ---------------------------------------------------------------------------
# q14 — promotion effect (join + LIKE + global conditional ratio)
# ---------------------------------------------------------------------------

P_PARTKEY, P_TYPE, P_BRAND, P_CONTAINER, P_SIZE = 0, 1, 2, 3, 4

_P_TYPES = ("PROMO BURNISHED COPPER", "PROMO PLATED BRASS",
            "STANDARD POLISHED TIN", "MEDIUM BRUSHED NICKEL",
            "ECONOMY ANODIZED STEEL", "SMALL PLATED COPPER")
_P_BRANDS = ("Brand#11", "Brand#12", "Brand#23", "Brand#34", "Brand#55")
_P_CONTAINERS = ("SM CASE", "SM BOX", "SM PACK", "SM PKG",
                 "MED BAG", "MED BOX", "MED PKG", "MED PACK",
                 "LG CASE", "LG BOX", "LG PACK", "LG PKG")


def part_table(num_rows: int, seed: int = 5) -> Table:
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(np.arange(1, num_rows + 1, dtype=np.int64)),
        Column.from_pylist(
            [_P_TYPES[i] for i in rng.integers(0, len(_P_TYPES),
                                               num_rows)], t.STRING),
        Column.from_pylist(
            [_P_BRANDS[i] for i in rng.integers(0, len(_P_BRANDS),
                                                num_rows)], t.STRING),
        Column.from_pylist(
            [_P_CONTAINERS[i]
             for i in rng.integers(0, len(_P_CONTAINERS), num_rows)],
            t.STRING),
        Column.from_numpy(rng.integers(1, 51, num_rows).astype(np.int32)),
    ])


# q14/q19 lineitem columns
L14_PARTKEY, L14_EXTENDEDPRICE, L14_DISCOUNT, L14_SHIPDATE = 0, 1, 2, 3


def lineitem_q14_table(num_rows: int, num_parts: int,
                       seed: int = 6) -> Table:
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(
            rng.integers(1, num_parts + 1, num_rows).astype(np.int64)),
        Column.from_numpy(
            rng.integers(90_000, 10_500_000, num_rows).astype(np.int64),
            t.decimal64(-2)),
        Column.from_numpy(
            rng.integers(0, 11, num_rows).astype(np.int64),
            t.decimal64(-2)),
        Column.from_numpy(
            rng.integers(8400, 10957, num_rows).astype(np.int32),
            t.TIMESTAMP_DAYS),
    ])


_Q14_MONTH_START = 9374  # 1995-09-01
_Q14_MONTH_END = 9404    # 1995-10-01


class Q14Result(NamedTuple):
    promo_revenue: jnp.ndarray   # int64 unscaled decimal(-4)
    total_revenue: jnp.ndarray   # int64 unscaled decimal(-4)
    join_total: jnp.ndarray

    def ratio(self) -> float:
        """100 * promo/total (the published q14 metric), host-side."""
        tot = int(self.total_revenue)
        return 100.0 * int(self.promo_revenue) / tot if tot else 0.0


@func_range("tpch_q14")
def tpch_q14(part: Table, lineitem: Table,
             month_start: int = _Q14_MONTH_START,
             month_end: int = _Q14_MONTH_END) -> Q14Result:
    """q14: shipdate-month lineitem joined to part; promo share of
    revenue. The CASE WHEN p_type LIKE 'PROMO%' lane runs the device
    LIKE engine on the join-gathered strings; revenue stays exact
    int64 decimal(-4) to the end (the q6 posture)."""
    from spark_rapids_jni_tpu.ops import strings as s
    from spark_rapids_jni_tpu.ops.join import apply_join_maps, join

    ship_c = lineitem.column(L14_SHIPDATE)
    ship = ship_c.data
    keep = (ship_c.valid_mask()
            & (ship >= jnp.int32(month_start))
            & (ship < jnp.int32(month_end)))
    price = lineitem.column(L14_EXTENDEDPRICE)
    disc = lineitem.column(L14_DISCOUNT)
    revenue = price.data * (100 - disc.data)   # decimal(-4), exact
    rev_ok = price.valid_mask() & disc.valid_mask() & keep
    probe = Table([
        _null_where(lineitem.column(L14_PARTKEY), ~keep),
    ])
    build = Table([part.column(P_PARTKEY), part.column(P_TYPE)])
    maps = join(probe, build, 0, 0, out_size=lineitem.num_rows)
    # gather the probe-side revenue lanes by the join's left map instead
    # of materializing them as table columns (they are derived, not data)
    li = jnp.clip(maps.left_index, 0, max(lineitem.num_rows - 1, 0))
    j = apply_join_maps(probe, build, maps)
    matched = j.column(1).valid_mask() & maps.row_valid
    rev_j = jnp.where(matched & rev_ok[li], revenue[li], 0)
    promo = s.like(j.column(2), "PROMO%").data != 0
    return Q14Result(
        jnp.sum(jnp.where(promo, rev_j, 0)),
        jnp.sum(rev_j),
        maps.total,
    )


class Q14PlannedResult(NamedTuple):
    promo_revenue: jnp.ndarray   # int64 unscaled decimal(-4)
    total_revenue: jnp.ndarray   # int64 unscaled decimal(-4)
    join_total: jnp.ndarray
    pk_violation: jnp.ndarray    # declared clustered PK was a lie

    def ratio(self) -> float:
        tot = int(self.total_revenue)
        return 100.0 * int(self.promo_revenue) / tot if tot else 0.0


@func_range("tpch_q14_planned")
def tpch_q14_planned(part: Table, lineitem: Table,
                     month_start: int = _Q14_MONTH_START,
                     month_end: int = _Q14_MONTH_END) -> Q14PlannedResult:
    """q14 with the part join as a planner-declared dense clustered PK
    lookup: the WHOLE query compiles sort-free (HLO-pinned) — the join
    is arithmetic + gather, the aggregate is two global masked sums.
    Bonus simplification over the general plan: dense-PK output rows
    are probe-aligned (row i IS lineitem row i), so the revenue lanes
    need no left-map gather at all."""
    from spark_rapids_jni_tpu.ops import strings as s
    from spark_rapids_jni_tpu.ops.planner import dense_pk_join

    ship_c = lineitem.column(L14_SHIPDATE)
    ship = ship_c.data
    keep = (ship_c.valid_mask()
            & (ship >= jnp.int32(month_start))
            & (ship < jnp.int32(month_end)))
    price = lineitem.column(L14_EXTENDEDPRICE)
    disc = lineitem.column(L14_DISCOUNT)
    revenue = price.data * (100 - disc.data)   # decimal(-4), exact
    rev_ok = price.valid_mask() & disc.valid_mask() & keep
    probe = Table([
        _null_where(lineitem.column(L14_PARTKEY), ~keep),
    ])
    build = Table([part.column(P_PARTKEY),
                   s.pad_strings(part.column(P_TYPE))])
    j = dense_pk_join(probe, build, 0, 0, 1, part.num_rows,
                      clustered=True)
    # j.table: [l_partkey, p_partkey, p_type] — probe-aligned
    matched = j.matched
    rev_j = jnp.where(matched & rev_ok, revenue, 0)
    promo = s.like(j.table.column(2), "PROMO%").data != 0
    return Q14PlannedResult(
        jnp.sum(jnp.where(promo, rev_j, 0)),
        jnp.sum(rev_j),
        j.total,
        j.pk_violation,
    )


def tpch_q14_numpy(part: Table, lineitem: Table,
                   month_start: int = _Q14_MONTH_START,
                   month_end: int = _Q14_MONTH_END) -> tuple:
    ptype = {int(k): v for k, v in zip(
        np.asarray(part.column(P_PARTKEY).data).tolist(),
        part.column(P_TYPE).to_pylist())}
    lkey = np.asarray(lineitem.column(L14_PARTKEY).data).tolist()
    price = np.asarray(lineitem.column(L14_EXTENDEDPRICE).data).tolist()
    disc = np.asarray(lineitem.column(L14_DISCOUNT).data).tolist()
    ship = np.asarray(lineitem.column(L14_SHIPDATE).data).tolist()
    promo = total = 0
    for i in range(lineitem.num_rows):
        if not month_start <= ship[i] < month_end:
            continue
        tp = ptype.get(lkey[i])
        if tp is None:
            continue
        rev = price[i] * (100 - disc[i])
        total += rev
        if tp.startswith("PROMO"):
            promo += rev
    return promo, total


# ---------------------------------------------------------------------------
# q19 — discounted revenue (join + OR-of-ANDs compound predicate)
# ---------------------------------------------------------------------------

L19_PARTKEY, L19_QUANTITY, L19_EXTENDEDPRICE = 0, 1, 2
L19_DISCOUNT, L19_SHIPMODE, L19_SHIPINSTRUCT = 3, 4, 5

_Q19_INSTRUCTS = ("DELIVER IN PERSON", "COLLECT COD", "NONE",
                  "TAKE BACK RETURN")


def lineitem_q19_table(num_rows: int, num_parts: int,
                       seed: int = 7) -> Table:
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(
            rng.integers(1, num_parts + 1, num_rows).astype(np.int64)),
        Column.from_numpy(
            rng.integers(100, 51_00, num_rows).astype(np.int64),
            t.decimal64(-2)),
        Column.from_numpy(
            rng.integers(90_000, 10_500_000, num_rows).astype(np.int64),
            t.decimal64(-2)),
        Column.from_numpy(
            rng.integers(0, 11, num_rows).astype(np.int64),
            t.decimal64(-2)),
        Column.from_pylist(
            ["AIR" if i == 0 else ("AIR REG" if i == 1 else "TRUCK")
             for i in rng.integers(0, 3, num_rows)], t.STRING),
        Column.from_pylist(
            [_Q19_INSTRUCTS[i]
             for i in rng.integers(0, len(_Q19_INSTRUCTS), num_rows)],
            t.STRING),
    ])


# (brand, container prefix, qty_lo in whole units, size_hi)
_Q19_BRANCHES = (
    ("Brand#12", "SM", 1, 5),
    ("Brand#23", "MED", 10, 10),
    ("Brand#34", "LG", 20, 15),
)


class Q19Result(NamedTuple):
    revenue: jnp.ndarray     # int64 unscaled decimal(-4)
    join_total: jnp.ndarray


@func_range("tpch_q19")
def tpch_q19(part: Table, lineitem: Table,
             branches: tuple = _Q19_BRANCHES) -> Q19Result:
    """q19: the OR-of-ANDs predicate over joined lineitem x part —
    every branch is a vectorized mask over join-gathered part columns
    and probe-side lanes; revenue is the exact int64 masked sum."""
    from spark_rapids_jni_tpu.ops import strings as s
    from spark_rapids_jni_tpu.ops.join import apply_join_maps, join

    n = lineitem.num_rows
    probe = Table([lineitem.column(L19_PARTKEY)])
    build = Table([part.column(P_PARTKEY), part.column(P_BRAND),
                   part.column(P_CONTAINER), part.column(P_SIZE)])
    maps = join(probe, build, 0, 0, out_size=n)
    li = jnp.clip(maps.left_index, 0, max(n - 1, 0))
    j = apply_join_maps(probe, build, maps)
    # j: [l_partkey, p_partkey, p_brand, p_container, p_size]
    matched = j.column(1).valid_mask() & maps.row_valid

    qty_c = lineitem.column(L19_QUANTITY)
    price_c = lineitem.column(L19_EXTENDEDPRICE)
    disc_c = lineitem.column(L19_DISCOUNT)
    qty = qty_c.data[li]                              # decimal(-2)
    price = price_c.data[li]
    disc = disc_c.data[li]
    lane_ok = (qty_c.valid_mask() & price_c.valid_mask()
               & disc_c.valid_mask()
               & lineitem.column(L19_SHIPMODE).valid_mask()
               & lineitem.column(L19_SHIPINSTRUCT).valid_mask())[li]
    mode = s.gather_strings(
        s.pad_strings(lineitem.column(L19_SHIPMODE)), li)
    instr = s.gather_strings(
        s.pad_strings(lineitem.column(L19_SHIPINSTRUCT)), li)
    mode_c = Column(t.STRING, mode.data, None, chars=mode.chars)
    instr_c = Column(t.STRING, instr.data, None, chars=instr.chars)

    air = ((s.like(mode_c, "AIR").data != 0)
           | (s.like(mode_c, "AIR REG").data != 0))
    person = s.like(instr_c, "DELIVER IN PERSON").data != 0
    brand_c, cont_c, size = j.column(2), j.column(3), j.column(4).data

    pred = jnp.zeros((j.num_rows,), jnp.bool_)
    for brand, cont_prefix, qty_lo, size_hi in branches:
        b = (s.like(brand_c, brand).data != 0)
        cont = s.like(cont_c, cont_prefix + "%").data != 0
        qlo = jnp.int64(qty_lo * 100)
        qhi = jnp.int64((qty_lo + 10) * 100)
        qok = (qty >= qlo) & (qty <= qhi)
        sok = (size >= 1) & (size <= jnp.int32(size_hi))
        pred = pred | (b & cont & qok & sok)
    pred = pred & air & person & matched & lane_ok
    revenue = jnp.where(pred, price * (100 - disc), 0)
    return Q19Result(jnp.sum(revenue), maps.total)


class Q19PlannedResult(NamedTuple):
    revenue: jnp.ndarray     # int64 unscaled decimal(-4)
    join_total: jnp.ndarray
    pk_violation: jnp.ndarray


@func_range("tpch_q19_planned")
def tpch_q19_planned(part: Table, lineitem: Table,
                     branches: tuple = _Q19_BRANCHES) -> Q19PlannedResult:
    """q19 with the part join as a dense clustered PK lookup: whole
    query sort-free, and the probe-aligned output removes every
    left-map gather the general plan pays for the lineitem lanes
    (qty/price/disc/shipmode/shipinstruct read directly)."""
    from spark_rapids_jni_tpu.ops import strings as s
    from spark_rapids_jni_tpu.ops.planner import dense_pk_join

    probe = Table([lineitem.column(L19_PARTKEY)])
    build = Table([
        part.column(P_PARTKEY),
        s.pad_strings(part.column(P_BRAND)),
        s.pad_strings(part.column(P_CONTAINER)),
        part.column(P_SIZE),
    ])
    j = dense_pk_join(probe, build, 0, 0, 1, part.num_rows,
                      clustered=True)
    # j: [l_partkey, p_partkey, p_brand, p_container, p_size] — row i
    # IS lineitem row i
    matched = j.matched

    qty_c = lineitem.column(L19_QUANTITY)
    price_c = lineitem.column(L19_EXTENDEDPRICE)
    disc_c = lineitem.column(L19_DISCOUNT)
    lane_ok = (qty_c.valid_mask() & price_c.valid_mask()
               & disc_c.valid_mask()
               & lineitem.column(L19_SHIPMODE).valid_mask()
               & lineitem.column(L19_SHIPINSTRUCT).valid_mask())
    mode_c = s.pad_strings(lineitem.column(L19_SHIPMODE))
    instr_c = s.pad_strings(lineitem.column(L19_SHIPINSTRUCT))

    air = ((s.like(mode_c, "AIR").data != 0)
           | (s.like(mode_c, "AIR REG").data != 0))
    person = s.like(instr_c, "DELIVER IN PERSON").data != 0
    brand_c = j.table.column(2)
    cont_c = j.table.column(3)
    size = j.table.column(4).data

    pred = jnp.zeros((lineitem.num_rows,), jnp.bool_)
    for brand, cont_prefix, qty_lo, size_hi in branches:
        b = (s.like(brand_c, brand).data != 0)
        cont = s.like(cont_c, cont_prefix + "%").data != 0
        qlo = jnp.int64(qty_lo * 100)
        qhi = jnp.int64((qty_lo + 10) * 100)
        qok = (qty_c.data >= qlo) & (qty_c.data <= qhi)
        sok = (size >= 1) & (size <= jnp.int32(size_hi))
        pred = pred | (b & cont & qok & sok)
    pred = pred & air & person & matched & lane_ok
    revenue = jnp.where(pred, price_c.data * (100 - disc_c.data), 0)
    return Q19PlannedResult(jnp.sum(revenue), j.total, j.pk_violation)


def tpch_q19_numpy(part: Table, lineitem: Table,
                   branches: tuple = _Q19_BRANCHES) -> int:
    pinfo = {}
    pk = np.asarray(part.column(P_PARTKEY).data).tolist()
    pb = part.column(P_BRAND).to_pylist()
    pc = part.column(P_CONTAINER).to_pylist()
    ps = np.asarray(part.column(P_SIZE).data).tolist()
    for i in range(part.num_rows):
        pinfo[pk[i]] = (pb[i], pc[i], ps[i])
    lkey = np.asarray(lineitem.column(L19_PARTKEY).data).tolist()
    qty = np.asarray(lineitem.column(L19_QUANTITY).data).tolist()
    price = np.asarray(lineitem.column(L19_EXTENDEDPRICE).data).tolist()
    disc = np.asarray(lineitem.column(L19_DISCOUNT).data).tolist()
    mode = lineitem.column(L19_SHIPMODE).to_pylist()
    instr = lineitem.column(L19_SHIPINSTRUCT).to_pylist()
    total = 0
    for i in range(lineitem.num_rows):
        info = pinfo.get(lkey[i])
        if info is None:
            continue
        if mode[i] not in ("AIR", "AIR REG"):
            continue
        if instr[i] != "DELIVER IN PERSON":
            continue
        ok = False
        for brand, cont_prefix, qty_lo, size_hi in branches:
            if (info[0] == brand and info[1].startswith(cont_prefix)
                    and qty_lo * 100 <= qty[i] <= (qty_lo + 10) * 100
                    and 1 <= info[2] <= size_hi):
                ok = True
                break
        if ok:
            total += price[i] * (100 - disc[i])
    return total


_Q12_GROUP_BUDGET = 16  # |shipmode domain| = 7 plus the null pseudo-group


def tpch_q12_distributed(orders: Table, lineitem: Table, mesh,
                         modes: tuple = ("MAIL", "SHIP"),
                         year_start: int = _Q12_YEAR_START,
                         year_end: int = _Q12_YEAR_END) -> Table:
    """Multi-executor q12: repartitioned orderkey join, then the classic
    two-phase aggregation — per-device partial groupby on the (tiny)
    shipmode domain, partial rows shuffled by key hash, merged, collected
    and shipmode-sorted on the driver. The partial->shuffle->merge shape
    is the q1 distributed plan; the join is the q3 repartition exchange —
    q12 composes both."""
    import jax as _jax
    from jax.sharding import PartitionSpec as P

    from spark_rapids_jni_tpu.ops import strings as s
    from spark_rapids_jni_tpu.parallel.distributed import (
        collect,
        distributed_join,
        shard_table,
    )
    from spark_rapids_jni_tpu.parallel.mesh import EXEC_AXIS
    from spark_rapids_jni_tpu.parallel.shuffle import hash_shuffle

    if len(modes) + 1 > _Q12_GROUP_BUDGET:
        raise ValueError(
            f"q12 mode domain {len(modes)} exceeds the partial-groupby "
            f"budget {_Q12_GROUP_BUDGET}")
    # WHERE -> nulled join key (shared predicate helper, single change
    # point with the single-device plan)
    mode_c = s.pad_strings(lineitem.column(L12_SHIPMODE))
    keep = _q12_keep(lineitem, mode_c, modes, year_start, year_end)
    probe = Table([
        _null_where(lineitem.column(L12_ORDERKEY), ~keep),
        mode_c,
    ])
    build = Table([
        orders.column(O12_ORDERKEY),
        s.pad_strings(orders.column(O12_ORDERPRIORITY)),
    ])
    sl, lrv = shard_table(probe, mesh, return_row_valid=True)
    sr, rrv = shard_table(build, mesh, return_row_valid=True)
    nl = probe.num_rows
    d = mesh.devices.size
    # per-device capacities (the q3 sizing): 2x skew headroom; overflow
    # is checked below and is the caller's retry signal
    res = distributed_join(
        sl, sr, [0], [0], mesh,
        out_size_per_device=max(1, nl // d * 2),
        left_capacity=max(1, nl // d * 2),
        right_capacity=max(1, orders.num_rows // d * 2),
        left_row_valid=lrv, right_row_valid=rrv,
    )
    if bool(np.asarray(res.overflowed).any()):
        raise ValueError(
            "q12 join exchange overflowed its per-device capacity "
            "(key skew); retry with a larger capacity factor")

    def agg_step(j: Table):
        # j: [l_orderkey, l_shipmode, o_orderkey, o_orderpriority]
        matched = j.column(2).valid_mask()
        high, low = _q12_priority_lanes(j.column(3), matched)
        mode_j = j.column(1)
        keyed = Table([
            Column(mode_j.dtype,
                   jnp.where(matched, mode_j.data, 0),
                   matched,
                   chars=jnp.where(matched[:, None], mode_j.chars,
                                   jnp.uint8(0))),
            high, low,
        ])
        budget = min(_Q12_GROUP_BUDGET, keyed.num_rows)
        partial = groupby_aggregate(
            keyed, keys=[0], aggs=[(1, "sum"), (2, "sum")],
            max_groups=budget)
        real = jnp.arange(budget, dtype=jnp.int32) < partial.num_groups
        sh = hash_shuffle(partial.table, [0], EXEC_AXIS, capacity=budget,
                          row_valid=real)
        merged = groupby_aggregate(
            sh.table, keys=[0], aggs=[(1, "sum"), (2, "sum")])
        return merged.table, merged.num_groups.reshape(1)

    per_dev, num_groups = _jax.jit(_jax.shard_map(
        agg_step, mesh=mesh, in_specs=(P(EXEC_AXIS),),
        out_specs=(P(EXEC_AXIS), P(EXEC_AXIS)),
    ))(res.table)
    result = collect(per_dev, num_groups, mesh)
    srt = sort_table(result, [0], nulls_first=[False])
    kv = np.asarray(srt.column(0).valid_mask())
    k = int(kv.sum())
    from spark_rapids_jni_tpu.ops.table_ops import trim_table

    return trim_table(srt, k)


# ---------------------------------------------------------------------------
# q4 — order priority checking (EXISTS -> left-semi join + groupby)
# ---------------------------------------------------------------------------

# q4 orders columns
O4_ORDERKEY, O4_ORDERDATE, O4_ORDERPRIORITY = 0, 1, 2
_Q4_QTR_START = 8582   # 1993-07-01
_Q4_QTR_END = 8674     # 1993-10-01


def orders_q4_table(num_rows: int, seed: int = 8) -> Table:
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(np.arange(1, num_rows + 1, dtype=np.int64)),
        Column.from_numpy(
            rng.integers(8400, 8800, num_rows).astype(np.int32),
            t.TIMESTAMP_DAYS),
        Column.from_pylist(
            [_Q12_PRIORITIES[i]
             for i in rng.integers(0, len(_Q12_PRIORITIES), num_rows)],
            t.STRING),
    ])


class Q4Result(NamedTuple):
    result: GroupByResult   # [o_orderpriority, order_count]
    join_total: jnp.ndarray


@func_range("tpch_q4")
def tpch_q4(orders: Table, lineitem: Table,
            qtr_start: int = _Q4_QTR_START,
            qtr_end: int = _Q4_QTR_END) -> Q4Result:
    """q4: orders in the quarter with EXISTS(lineitem late delivery),
    counted per priority — the EXISTS lowers to a LEFT-SEMI join (the
    round-4 join surface), then a string-key groupby."""
    from spark_rapids_jni_tpu.ops.join import apply_join_maps, join

    od = orders.column(O4_ORDERDATE)
    keep_o = (od.valid_mask()
              & (od.data >= jnp.int32(qtr_start))
              & (od.data < jnp.int32(qtr_end)))
    probe = Table([
        _null_where(orders.column(O4_ORDERKEY), ~keep_o),
        orders.column(O4_ORDERPRIORITY),
    ])
    commit_c = lineitem.column(L12_COMMITDATE)
    receipt_c = lineitem.column(L12_RECEIPTDATE)
    late = (commit_c.valid_mask() & receipt_c.valid_mask()
            & (commit_c.data < receipt_c.data))
    build = Table([
        _null_where(lineitem.column(L12_ORDERKEY), ~late),
    ])
    maps = join(probe, build, 0, 0, out_size=orders.num_rows,
                how="left_semi")
    j = apply_join_maps(probe, build, maps)
    matched = maps.row_valid
    keyed = Table([
        _null_where(j.column(1), ~matched),
        Column(t.INT64, jnp.where(matched, jnp.int64(1), jnp.int64(0)),
               matched),
    ])
    g = groupby_aggregate(keyed, keys=[0], aggs=[(1, "sum")])
    srt = sort_table(g.table, [0], nulls_first=[False])
    return Q4Result(GroupByResult(srt, g.num_groups), maps.total)


def tpch_q4_numpy(orders: Table, lineitem: Table,
                  qtr_start: int = _Q4_QTR_START,
                  qtr_end: int = _Q4_QTR_END) -> dict:
    late_keys = set()
    lkey = np.asarray(lineitem.column(L12_ORDERKEY).data).tolist()
    commit = np.asarray(lineitem.column(L12_COMMITDATE).data).tolist()
    receipt = np.asarray(lineitem.column(L12_RECEIPTDATE).data).tolist()
    for i in range(lineitem.num_rows):
        if commit[i] < receipt[i]:
            late_keys.add(lkey[i])
    out: dict = {}
    okey = np.asarray(orders.column(O4_ORDERKEY).data).tolist()
    odate = np.asarray(orders.column(O4_ORDERDATE).data).tolist()
    prio = orders.column(O4_ORDERPRIORITY).to_pylist()
    for i in range(orders.num_rows):
        if not qtr_start <= odate[i] < qtr_end:
            continue
        if okey[i] in late_keys:
            out[prio[i]] = out.get(prio[i], 0) + 1
    return out


@func_range("tpch_q4_planned_result")
def tpch_q4_planned_result(orders: Table, lineitem: Table,
                           qtr_start: int = _Q4_QTR_START,
                           qtr_end: int = _Q4_QTR_END):
    """q4 on the sort-free plan: o_orderpriority is a 5-value DDL enum
    ('1-URGENT'..'5-LOW' — the dictionary a real planner reads from
    column stats), so the post-semi-join COUNT(*) GROUP BY lowers to the
    bounded masked-reduction pass with on-device dictionary encoding.
    The EXISTS stays a LEFT-SEMI join; only the aggregation changes."""
    from spark_rapids_jni_tpu.ops import strings as s
    from spark_rapids_jni_tpu.ops.join import apply_join_maps, join
    from spark_rapids_jni_tpu.ops.planner import plan_groupby, string_domain

    od = orders.column(O4_ORDERDATE)
    keep_o = (od.valid_mask()
              & (od.data >= jnp.int32(qtr_start))
              & (od.data < jnp.int32(qtr_end)))
    prio_c = s.pad_strings(orders.column(O4_ORDERPRIORITY))
    probe = Table([
        _null_where(orders.column(O4_ORDERKEY), ~keep_o),
        prio_c,
    ])
    commit_c = lineitem.column(L12_COMMITDATE)
    receipt_c = lineitem.column(L12_RECEIPTDATE)
    late = (commit_c.valid_mask() & receipt_c.valid_mask()
            & (commit_c.data < receipt_c.data))
    build = Table([
        _null_where(lineitem.column(L12_ORDERKEY), ~late),
    ])
    maps = join(probe, build, 0, 0, out_size=orders.num_rows,
                how="left_semi")
    j = apply_join_maps(probe, build, maps)
    matched = maps.row_valid
    prio_j = j.column(1)
    keyed = Table([
        Column(prio_j.dtype,
               jnp.where(matched, prio_j.data, 0), matched,
               chars=jnp.where(matched[:, None], prio_j.chars,
                               jnp.uint8(0))),
        Column(t.INT64, jnp.where(matched, jnp.int64(1), jnp.int64(0)),
               matched),
    ])
    return plan_groupby(keyed, keys=[0], aggs=[(1, "sum")],
                        domains=[string_domain(_Q12_PRIORITIES)])


def tpch_q4_planned(orders: Table, lineitem: Table,
                    qtr_start: int = _Q4_QTR_START,
                    qtr_end: int = _Q4_QTR_END) -> Table:
    """Planned q4, table only — [o_orderpriority, order_count] in
    priority order, null pseudo-group last (same contract as tpch_q4)."""
    return tpch_q4_planned_result(
        orders, lineitem, qtr_start, qtr_end).table


# ---------------------------------------------------------------------------
# q17 — small-quantity-order revenue (correlated AVG subquery ->
# groupby mean + join + filtered exact sum)
# ---------------------------------------------------------------------------


class Q17Result(NamedTuple):
    yearly_total: jnp.ndarray    # int64 unscaled decimal(-2) * 10 (sum/0.7... see ratio)
    join_total: jnp.ndarray

    def avg_yearly(self) -> float:
        """sum(l_extendedprice)/7.0 in display units."""
        return int(self.yearly_total) / 100.0 / 7.0


@func_range("tpch_q17")
def tpch_q17(part: Table, lineitem: Table,
             brand: str = "Brand#23", container: str = "MED BOX") -> Q17Result:
    """q17: lineitem x part filtered to one brand/container, keeping rows
    with l_quantity < 0.2 * avg(l_quantity) OVER the part — the
    correlated subquery lowers to a per-part groupby mean joined back
    (two joins on partkey share the rank encoding), then an exact sum."""
    from spark_rapids_jni_tpu.ops import strings as s
    from spark_rapids_jni_tpu.ops.join import apply_join_maps, join

    sel_part = ((s.like(part.column(P_BRAND), brand).data != 0)
                & (s.like(part.column(P_CONTAINER), container).data != 0)
                & part.column(P_PARTKEY).valid_mask())
    build = Table([
        _null_where(part.column(P_PARTKEY), ~sel_part),
    ])
    n = lineitem.num_rows
    probe = Table([lineitem.column(L19_PARTKEY)])
    maps = join(probe, build, 0, 0, out_size=n)
    li = jnp.clip(maps.left_index, 0, max(n - 1, 0))
    j = apply_join_maps(probe, build, maps)
    matched = j.column(1).valid_mask() & maps.row_valid

    qty_c = lineitem.column(L19_QUANTITY)
    price_c = lineitem.column(L19_EXTENDEDPRICE)
    qty = qty_c.data[li]
    price = price_c.data[li]
    # the correlated AVG(l_quantity) is over every selected row with a
    # non-null QUANTITY — price nulls only drop rows from the final sum
    avg_ok = qty_c.valid_mask()[li] & matched

    # per-part avg quantity over the SELECTED rows: groupby mean on the
    # joined rows (keys = partkey), then gathered back via a second
    # join (the correlated-subquery lowering)
    keyed = Table([
        _null_where(Column(j.column(0).dtype, j.column(0).data,
                           j.column(0).valid_mask()), ~avg_ok),
        Column(qty_c.dtype, qty, avg_ok),
    ])
    g = groupby_aggregate(keyed, keys=[0], aggs=[(1, "mean")])
    # map each row to its group's mean: join rows back on partkey
    gt = g.table
    m2 = join(keyed, gt, 0, 0, out_size=n)
    li2 = jnp.clip(m2.left_index, 0, max(n - 1, 0))
    j2 = apply_join_maps(keyed, gt, m2)
    # j2: [l_partkey, l_quantity, g_partkey, g_mean]
    ok2 = j2.column(2).valid_mask() & m2.row_valid
    q2 = j2.column(1)
    mean2 = j2.column(3)
    # l_quantity < 0.2 * avg: quantity is decimal(-2) -> value*100;
    # mean is FLOAT64 in VALUE units
    pred = (q2.data.astype(jnp.float64)
            < 0.2 * mean2.data * 100.0) & ok2 & q2.valid_mask()
    price2 = price_c.data[li][li2]
    price_ok = price_c.valid_mask()[li][li2]
    total = jnp.sum(jnp.where(pred & price_ok, price2, 0))
    return Q17Result(total, maps.total)


def tpch_q17_numpy(part: Table, lineitem: Table,
                   brand: str = "Brand#23",
                   container: str = "MED BOX") -> int:
    sel = set()
    pk = np.asarray(part.column(P_PARTKEY).data).tolist()
    pb = part.column(P_BRAND).to_pylist()
    pc = part.column(P_CONTAINER).to_pylist()
    for i in range(part.num_rows):
        if pb[i] == brand and pc[i] == container:
            sel.add(pk[i])
    lkey = np.asarray(lineitem.column(L19_PARTKEY).data).tolist()
    qty = np.asarray(lineitem.column(L19_QUANTITY).data).tolist()
    price = np.asarray(lineitem.column(L19_EXTENDEDPRICE).data).tolist()
    by_part: dict = {}
    for i in range(lineitem.num_rows):
        if lkey[i] in sel:
            by_part.setdefault(lkey[i], []).append(i)
    total = 0
    for k, rows in by_part.items():
        avg = sum(qty[i] for i in rows) / len(rows)
        for i in rows:
            if qty[i] < 0.2 * avg:
                total += price[i]
    return total


# ---- TPC-H q13-shaped customer-key aggregation: the general-cardinality ----
# distributed groupby over the exchange
#
#   SELECT o_custkey, count(o_orderkey) FROM orders GROUP BY o_custkey
#
# The inner aggregation of q13 (customer distribution): order counts per
# customer key. Customer keys are HIGH cardinality — no slot table, no
# domain plan, no psum merge can cover them — which is exactly the query
# shape the bounded-slot distributed plans could not run. The distributed
# form is partial-counts per shard -> hash-partitioned all-to-all exchange
# by custkey (runtime/exchange.py) -> per-destination sum-merge; the merge
# algebra is re-applicable (sum of counts), so the exchange's spill-aware
# chunked merge composes with it unchanged.


def q13_partial_plan() -> fusion.Plan:
    """Per-shard q13 partial: order counts per customer key, general
    cardinality (``max_groups=None`` pads to the shard's row count and
    can never overflow — no static slot table)."""
    return fusion.Plan("tpch_q13_partial", fusion.GroupBy(
        fusion.Scan("orders"), (O_CUSTKEY,), ((O_ORDERKEY, "count"),),
        max_groups=None, label="partial"))


def q13_merge_plan() -> fusion.Plan:
    """Per-destination q13 merge: sum the partial counts per customer
    key — re-applicable (``merge(merge(a) + merge(b)) == merge(a + b)``),
    the property the exchange's chunked spill merge relies on."""
    return fusion.Plan("tpch_q13_merge", fusion.GroupBy(
        fusion.Scan("partials"), (0,), ((1, "sum"),),
        max_groups=None, label="merge"))


def q13_exchange_plans(parts: int):
    """The (pack_plan, merge_plan) pair for the distributed q13-shaped
    aggregation: the pack plan roots an ``Exchange`` node over the
    partial (keys = the custkey output column, ``valid_meta`` trims the
    unbounded groupby's padding before any row rides the wire); the
    merge plan scans ``partials``. Drive through
    ``QueryCluster.submit_exchange`` — or locally via
    :func:`tpch_q13_local`, which is the bit-identity oracle."""
    pack = fusion.Plan("tpch_q13_pack", fusion.Exchange(
        q13_partial_plan().root, keys=(0,), parts=int(parts),
        valid_meta="partial.num_groups", label="exchange"))
    return pack, q13_merge_plan()


def q13_midplan_plan(parts: int) -> fusion.Plan:
    """The q13-shaped aggregation as ONE plan with a planner-placed
    interior ``Exchange``: partial groupby -> exchange by custkey ->
    sum-merge, the region -> exchange -> region shape
    ``fusion.split_at_exchange`` breaks into exactly the hand-split
    (pack, merge) plan pair of :func:`q13_exchange_plans`. ``parts=0``
    defers the partition count to the learned-selectivity store
    (``exchange.choose_parts``)."""
    return fusion.Plan("tpch_q13_midplan", fusion.GroupBy(
        fusion.Exchange(
            q13_partial_plan().root, keys=(0,), parts=int(parts),
            valid_meta="partial.num_groups", label="exchange"),
        (0,), ((1, "sum"),), max_groups=None, label="merge"))


def tpch_q13_local(orders: Table, parts: int = 1, *,
                   shard_keys=(O_ORDERKEY,)) -> Table:
    """Single-host oracle for the distributed q13-shaped aggregation:
    the SAME plans over the SAME shard split (``shard_keys`` must match
    the cluster's ``register_table`` keys) and the same
    source-then-flight regroup order — bit-identical to what
    ``submit_exchange(...).result()`` returns over a live mesh."""
    from spark_rapids_jni_tpu.ops.table_ops import _slice_rows, concatenate
    from spark_rapids_jni_tpu.parallel import dcn
    from spark_rapids_jni_tpu.runtime import exchange as xch

    parts = int(parts)
    pack, merge = q13_exchange_plans(parts)
    shards = (dcn.partition_for_slices(orders, list(shard_keys), parts)
              if parts > 1 else [orders])
    per_dest: list = [[] for _ in range(parts)]
    empty = None
    for shard in shards:
        fused = fusion.execute(pack, {"orders": shard})
        rc = fused.meta["exchange.row_counts"]
        empty = _slice_rows(fused.table, 0, 0)
        for p, fls in enumerate(xch.split_wire(fused.table, rc, parts)):
            per_dest[p].extend(fls)
    outs = []
    for flights in per_dest:
        if not flights:
            continue
        dest_in = (flights[0] if len(flights) == 1
                   else concatenate(flights))
        res = fusion.execute(merge, {"partials": dest_in})
        outs.append(_slice_rows(
            res.table, 0, int(np.asarray(res.meta["merge.num_groups"]))))
    if not outs:
        res = fusion.execute(merge, {"partials": empty})
        return _slice_rows(res.table, 0, 0)
    return outs[0] if len(outs) == 1 else concatenate(outs)


def tpch_q13_reference(orders: Table) -> Table:
    """Naive single-pass reference (one global groupby): the value-level
    check behind the oracle — same groups and counts as
    :func:`tpch_q13_local` up to row order."""
    from spark_rapids_jni_tpu.ops.table_ops import trim_table

    g = groupby_aggregate(orders, [O_CUSTKEY], [(O_ORDERKEY, "count")],
                          max_groups=None)
    return trim_table(g.table, int(np.asarray(g.num_groups)))


# ---------------------------------------------------------------------------
# AOT warmup registration (runtime/server.QueryServer.warmup)
# ---------------------------------------------------------------------------
#
# The learned-estimate file records plan signatures ``<plan>@<bucket>``;
# a booting replica replays the costliest ones through these builders at
# the signature's bucket rows so the first real query finds its
# executables already compiled. Only single-table plans register here:
# their signature bucket maps 1:1 onto synthetic input rows, so the
# warmed executable IS the one live traffic will hit (a multi-table plan
# like q3 has no unique rows-per-table split for a total-row bucket, and
# a wrong split would warm a bucket nobody queries).

def _register_warmup_builders() -> None:
    from spark_rapids_jni_tpu.runtime.server import register_warmup_builder

    register_warmup_builder(
        "tpch_q1", lambda rows: tpch_q1(lineitem_table(rows)))
    register_warmup_builder(
        "tpch_q1_planned",
        lambda rows: tpch_q1_planned(lineitem_table(rows)))
    register_warmup_builder(
        "tpch_q6", lambda rows: tpch_q6(lineitem_table(rows)))


_register_warmup_builders()
