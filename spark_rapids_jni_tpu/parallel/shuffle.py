"""ICI all-to-all shuffle transport — repartition a device-resident table by
key hash across the executor mesh axis.

This is the RapidsShuffleManager replacement (BASELINE.json north_star;
SURVEY.md section 2.3 "distributed comm backend — must be built"): where the
GPU stack serializes partition blocks and moves them over UCX between
executor processes, here every executor's batch stays in HBM and one XLA
``all_to_all`` collective performs the full D x D partition exchange over
ICI in a single fused step.

TPU-first shape discipline: ``all_to_all`` needs a static per-destination
capacity, so each device packs its rows into a ``(D, capacity)`` send
buffer (rows sorted by destination partition — one gather, radix-friendly)
with an occupancy mask; unoccupied receive slots surface as null rows,
which every downstream operator already skips (the same masked-row trick
the local operators use for static-shape filtering). The capacity default
``ceil(n/D) * 2`` covers 2x skew; overflow is detected and reported
per-call (`ShuffleResult.overflowed`) rather than silently dropped —
the moral equivalent of the reference's hard 2^31-byte batch bound
(reference row_conversion.cu:476-479).

String columns travel in the padded device layout (ops.strings): their
int32 lengths ride the fixed-width path and the (n, W) char matrix is
exchanged as W parallel byte lanes of the same all_to_all — variable-length
data over a static-shape collective. Arrow-layout string columns must be
padded before entering the mesh program (shard_table does this).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.types import TypeId
from spark_rapids_jni_tpu.ops.hash import partition_hash
from spark_rapids_jni_tpu.parallel.wire import BitPack, pack_bits, unpack_bits
from spark_rapids_jni_tpu.utils.tracing import func_range


class ShuffleResult(NamedTuple):
    table: Table            # D*capacity local rows, null-masked where empty
    row_valid: jnp.ndarray  # bool[D*capacity]: slot holds a real row
    overflowed: jnp.ndarray  # bool scalar: this device dropped rows
    # bool scalar: a wire-narrowed value did not survive the round trip
    # (planner declared a too-narrow wire type) — data arrived truncated
    narrowing_overflow: jnp.ndarray


class _SendPlan(NamedTuple):
    """Inverted send-buffer mapping: for output slot s, take sorted row
    ``src[s]`` when ``hit[s]`` (else the slot is empty). Computed ONCE per
    shuffle and reused by every column."""

    src: jnp.ndarray  # int32[size] into destination-sorted rows
    hit: jnp.ndarray  # bool[size]


def _plan_send(dst_mono: jnp.ndarray, in_cap: jnp.ndarray,
               size: int) -> _SendPlan:
    """Invert the (monotone) row->slot map into a slot->row gather.

    ``dst_mono`` is non-decreasing over the partition-sorted rows (slots
    increase within a partition, partitions increase across runs; dropped
    rows are capped at the partition boundary so monotonicity survives
    overflow). A scatter would serialize on the TPU; searchsorted + gather
    streams. Ties (capped overflow rows, phantom rows sharing a slot) are
    broken by taking the LAST row of a tie group — the real in-capacity row
    always sorts after its capped/phantom shadows — and ``in_cap[src]``
    rejects groups with no real member.
    """
    n = dst_mono.shape[0]
    slots = jnp.arange(size, dtype=dst_mono.dtype)
    pos = jnp.searchsorted(dst_mono, slots, side="right").astype(jnp.int32) - 1
    src = jnp.clip(pos, 0, max(n - 1, 0))
    hit = (pos >= 0) & (dst_mono[src] == slots) & in_cap[src] if n else (
        jnp.zeros((size,), jnp.bool_)
    )
    return _SendPlan(src, hit)


def _pack_send(
    data: jnp.ndarray, order: jnp.ndarray, plan: _SendPlan
) -> jnp.ndarray:
    """Lay rows out in send-buffer order via the inverted plan (pure
    gathers, zero scatters). Works for 1-D columns and 2-D row matrices
    (padded string chars)."""
    g = data[order][plan.src]
    zeros = jnp.zeros((), dtype=data.dtype)
    if g.ndim == 1:
        return jnp.where(plan.hit, g, zeros)
    return jnp.where(plan.hit[:, None], g, zeros)


@func_range("hash_shuffle")
def hash_shuffle(
    table: Table,
    keys: Sequence[int],
    axis_name: str,
    capacity: Optional[int] = None,
    row_valid: Optional[jnp.ndarray] = None,
    wire_dtypes: Optional[Sequence] = None,
) -> ShuffleResult:
    """Exchange rows so row r lands on device ``hash(keys(r)) % D``.

    Must run inside ``shard_map`` over a mesh with ``axis_name``; ``table``
    is the caller's local batch. Returns the rows this device owns after
    the exchange (padded to ``D * capacity`` with null rows).

    ``row_valid`` marks which local rows exist at all (False = padding from
    shard_table etc.); non-rows are dropped before the exchange rather than
    shipped, and never count as overflow. Distinct from column validity — a
    real row with NULL key still shuffles (to the null-hash partition).
    """
    part = partition_hash(table, list(keys), jax.lax.axis_size(axis_name))
    return shuffle_by_partition(table, part, axis_name, capacity=capacity,
                                row_valid=row_valid, wire_dtypes=wire_dtypes)


@func_range("shuffle_by_partition")
def shuffle_by_partition(
    table: Table,
    part: jnp.ndarray,
    axis_name: str,
    capacity: Optional[int] = None,
    row_valid: Optional[jnp.ndarray] = None,
    wire_dtypes: Optional[Sequence] = None,
) -> ShuffleResult:
    """Exchange rows by a caller-computed partition id (int32[n] in [0, D)).
    ``hash_shuffle`` routes by key hash; range shuffles (distributed sort)
    route by splitter bucket — same transport, different ``part``."""
    D = jax.lax.axis_size(axis_name)
    n = table.num_rows
    if capacity is None:
        # Bucket-quantize the derived capacity so nearby batch sizes trace
        # to the same (D, capacity) exchange shapes and share executables
        # (extra slots are row_valid=False padding downstream already
        # skips). Caller-specified capacities are honored exactly — they
        # are part of the caller's planned output contract.
        from spark_rapids_jni_tpu.runtime import dispatch

        capacity = dispatch.quantize_capacity(max(1, math.ceil(n / D) * 2))

    # Sort rows by destination partition; compute each row's slot within
    # its partition run. Stable sort keeps within-partition input order.
    order = jnp.argsort(part, stable=True)
    part_sorted = part[order]
    if row_valid is None:
        real_sorted = jnp.ones((n,), dtype=jnp.bool_)
    else:
        real_sorted = row_valid[order]
    real_i32 = real_sorted.astype(jnp.int32)
    # real rows in earlier partitions (per-partition slot base), scatter-free:
    # partitions are contiguous after the sort, so the base of partition p is
    # the exclusive real-row rank at p's first row
    rank_excl = jnp.cumsum(real_i32) - real_i32  # reals strictly before row
    if n:
        part_start = jnp.searchsorted(
            part_sorted, jnp.arange(D, dtype=part_sorted.dtype), side="left"
        )
        base = rank_excl[jnp.clip(part_start, 0, n - 1)]
        base = jnp.where(part_start < n, base, jnp.cumsum(real_i32)[-1])
        offsets = base.astype(jnp.int32)
    else:
        offsets = jnp.zeros((D,), jnp.int32)
    # Slot = count of real rows of the same partition preceding this row.
    # Exclusive rank makes a phantom row tie with the NEXT real row (and
    # sort BEFORE it) — the send-plan inversion picks the last row of a tie
    # group, which is then always the real one.
    slot = rank_excl.astype(jnp.int32) - offsets[part_sorted]
    in_cap = (slot < capacity) & real_sorted
    overflowed = jnp.any((slot >= capacity) & real_sorted)
    size = D * capacity
    # Monotone destination key over the sorted rows (overflow rows capped at
    # the partition boundary slot, which is never queried as in-capacity).
    dst_mono = part_sorted * capacity + jnp.clip(slot, 0, capacity)
    plan = _plan_send(dst_mono, in_cap, size)

    occupied = plan.hit

    def exchange(flat: jnp.ndarray) -> jnp.ndarray:
        """(D*C, ...) send layout -> (D*C, ...) receive layout over ICI."""
        return jax.lax.all_to_all(
            flat.reshape((D, capacity) + flat.shape[1:]),
            axis_name, 0, 0, tiled=True,
        ).reshape((size,) + flat.shape[1:])

    recv_occupied = exchange(occupied)

    if wire_dtypes is not None and len(wire_dtypes) != table.num_columns:
        raise ValueError("wire_dtypes must match the column count")

    out_cols = []
    narrowing_overflow = jnp.zeros((), jnp.bool_)
    for i, col in enumerate(table.columns):
        if col.dtype.is_string:
            if not col.is_padded_string:
                raise NotImplementedError(
                    "hash_shuffle needs string columns in the padded device "
                    "layout (ops.strings.pad_strings / shard_table do this)"
                )
            if wire_dtypes is not None and wire_dtypes[i] is not None:
                raise ValueError(
                    "wire narrowing does not apply to string columns "
                    f"(column {i}); pass None for its wire dtype"
                )
            recv_len = exchange(_pack_send(col.data, order, plan))
            recv_mat = exchange(_pack_send(col.chars, order, plan))
            valid_flat = _pack_send(col.valid_mask(), order, plan)
            recv_valid = exchange(valid_flat) & recv_occupied
            out_cols.append(
                Column(col.dtype, recv_len, recv_valid, chars=recv_mat)
            )
            continue
        if col.dtype.type_id == TypeId.LIST:
            if not col.is_padded_list:
                raise NotImplementedError(
                    "hash_shuffle needs LIST columns in the padded wire "
                    "layout (ops.lists.pad_lists before the shuffle)")
            if wire_dtypes is not None and wire_dtypes[i] is not None:
                raise ValueError(
                    "wire narrowing does not apply to LIST columns "
                    f"(column {i}); pass None for its wire dtype")
            elem = col.children[0]
            recv_len = exchange(_pack_send(col.data, order, plan))
            recv_mat = exchange(_pack_send(elem.data, order, plan))
            recv_ev = exchange(_pack_send(elem.valid_mask(), order, plan))
            recv_valid = exchange(
                _pack_send(col.valid_mask(), order, plan)) & recv_occupied
            # unoccupied slots must read as EMPTY lists, not stale rows
            recv_len = jnp.where(recv_occupied, recv_len, 0)
            recv_ev = recv_ev & recv_occupied[:, None]
            out_cols.append(Column(
                col.dtype, recv_len, recv_valid,
                children=[Column(elem.dtype, recv_mat, recv_ev)]))
            continue
        if not (col.dtype.is_fixed_width or col.dtype.is_decimal128):
            raise NotImplementedError(
                "hash_shuffle supports fixed-width columns only (reference "
                "row_conversion.cu:515 has the same restriction)"
            )
        wire = None if wire_dtypes is None else wire_dtypes[i]
        if wire is not None and col.dtype.is_decimal128:
            raise ValueError(
                f"wire narrowing does not apply to DECIMAL128 (column {i}); "
                "pass None for its wire dtype"
            )
        if isinstance(wire, BitPack):
            # nvcomp-equivalent transport compression, stage 2: frame-of-
            # reference + bit-packing (parallel.wire). Null slots and
            # unoccupied send slots are cleaned to the reference value so
            # they always pack; out-of-range real values set
            # narrowing_overflow — detection, not silent truncation.
            if col.dtype.storage_dtype.kind not in ("i", "u"):
                raise TypeError(
                    f"BitPack wire spec needs integral storage (column {i})"
                )
            ref = jnp.asarray(wire.reference, col.data.dtype)
            clean = jnp.where(col.valid_mask(), col.data, ref)
            sent = _pack_send(clean, order, plan)
            sent = jnp.where(occupied, sent, ref)
            packed, ovf = pack_bits(sent.reshape(D, capacity), wire)
            narrowing_overflow = narrowing_overflow | ovf
            recv_words = jax.lax.all_to_all(packed, axis_name, 0, 0,
                                            tiled=True)
            recv = unpack_bits(
                recv_words, capacity, wire, col.data.dtype
            ).reshape(size)
        elif wire is not None:
            # Null slots hold unspecified data (Column contract) — zero them
            # so garbage payloads can't trip the narrowing check (and the
            # wire bytes become deterministic).
            clean = jnp.where(
                col.valid_mask(), col.data, jnp.zeros_like(col.data)
            )
            sent = _pack_send(clean, order, plan)
            # nvcomp-equivalent transport compression, stage 1: the planner
            # declares a narrower integral wire type (dates in int32,
            # quantities in int16, ...) and the exchange moves 2-4x fewer
            # bytes over ICI. A value that does not survive the down/up
            # cast sets narrowing_overflow.
            narrow = sent.astype(wire.jnp_dtype)
            widened = narrow.astype(col.data.dtype)
            # unoccupied slots hold zeros, which always survive narrowing
            narrowing_overflow = narrowing_overflow | jnp.any(widened != sent)
            recv = exchange(narrow).astype(col.data.dtype)
        else:
            recv = exchange(_pack_send(col.data, order, plan))
        valid_flat = _pack_send(col.valid_mask(), order, plan)
        recv_valid = exchange(valid_flat) & recv_occupied
        out_cols.append(Column(col.dtype, recv, recv_valid))

    return ShuffleResult(
        Table(out_cols), recv_occupied, overflowed, narrowing_overflow
    )


def classify_overflow(*, op: str = "hash_shuffle",
                      capacity: int | None = None,
                      rows: int | None = None,
                      partition: int | None = None,
                      required: int | None = None,
                      seam: str = "shuffle.transport",
                      **context):
    """Build the classified taxonomy error for a tripped shuffle/exchange
    capacity-overflow flag: a :class:`~.resilience.CapacityOverflow`
    carrying partition/capacity context, so the host boundary that syncs
    the device flag raises something ``resilience.escalate`` (and every
    classified handler above it) can act on — never a bare boolean."""
    from spark_rapids_jni_tpu.runtime import resilience

    where = "" if partition is None else f" (hot partition {partition})"
    need = "" if required is None else f"; {required} slots required"
    return resilience.CapacityOverflow(
        f"{op}: partition capacity overflow{where}: a destination "
        f"received more rows than its "
        f"{capacity if capacity is not None else 'derived'} send-buffer "
        f"slots{need}",
        seam=seam,
        **{k: v for k, v in dict(
            capacity=capacity, rows=rows, partition=partition,
            required=required, **context).items() if v is not None})


def report_shuffle_telemetry(result: ShuffleResult | None = None,
                             op: str = "hash_shuffle",
                             rows: int | None = None, *,
                             overflowed=None,
                             narrowing_overflow=None,
                             capacity: int | None = None,
                             partition: int | None = None,
                             raise_on_overflow: bool = False) -> None:
    """Host-side overflow accounting for a CONCRETE shuffle result.

    The shuffle itself runs inside shard_map/jit where telemetry calls are
    forbidden (they would be host transfers in a traced region — the tpulint
    no-host-transfer rule); callers that have the materialized result invoke
    this at the jit boundary — either a full ``ShuffleResult`` or just the
    two flag arrays for callers whose jitted step returns flags alone (the
    shuffle_wire bench).

    A tripped capacity flag is classified through the resilience taxonomy
    (:func:`classify_overflow` -> ``CapacityOverflow`` with
    partition/capacity context): recorded as a fallback event stamped with
    the classified kind, and RAISED when ``raise_on_overflow`` so callers
    without their own escalation ladder fail classified instead of
    carrying a bare boolean upward. A tripped narrowing flag classifies
    ``MalformedInputError`` (the planner declared a too-narrow wire type —
    a contract breach, not a capacity problem). Telemetry-off only mutes
    the event records; classification still raises when asked."""
    from spark_rapids_jni_tpu import telemetry
    from spark_rapids_jni_tpu.runtime import resilience

    if result is not None:
        overflowed = result.overflowed
        narrowing_overflow = result.narrowing_overflow
    ovf = overflowed is not None and bool(np.asarray(overflowed).any())
    nvf = (narrowing_overflow is not None
           and bool(np.asarray(narrowing_overflow).any()))
    if telemetry.enabled():
        if ovf:
            telemetry.record_fallback(
                op, "partition capacity overflow: a device dropped rows "
                "(re-plan with larger capacity)", rows=rows,
                error_kind="CapacityOverflow",
                **({} if capacity is None else {"capacity": capacity}))
        if nvf:
            telemetry.record_fallback(
                op, "wire narrowing overflow: a narrowed value did not "
                "survive the round trip (planner declared too-narrow wire "
                "type)", rows=rows, error_kind="MalformedInputError")
        if not (ovf or nvf):
            telemetry.record_dispatch(op, rows=rows)
    if raise_on_overflow:
        if ovf:
            raise classify_overflow(op=op, capacity=capacity, rows=rows,
                                    partition=partition)
        if nvf:
            raise resilience.MalformedInputError(
                f"{op}: wire narrowing overflow: a narrowed value did not "
                "survive the round trip (planner declared a too-narrow "
                "wire type)", seam="shuffle.transport",
                **({} if rows is None else {"rows": rows}))
