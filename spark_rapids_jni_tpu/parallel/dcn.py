"""Cross-slice shuffle transport — the DCN-role host-staged path.

SURVEY.md §2.3 specifies the distributed backend as "ICI all-to-all for
shuffle, DCN fallback across slices"; BASELINE.json's north-star config
names the cross-slice hop explicitly. Inside one slice, `hash_shuffle`
rides XLA's all_to_all over ICI. ACROSS slices there is no single mesh:
each slice is its own process group with its own PJRT clients, and rows
change slices over the data-center network. This module is that hop,
prototyped host-staged:

* rows are partitioned to their owner slice with the SAME Spark-style
  ``partition_hash`` the intra-slice shuffle uses (two-level
  partitioning: ``hash % n_slices`` picks the slice, the intra-slice
  shuffle then spreads ``hash`` over the slice's devices);
* out-of-slice rows are snapshotted to host and zstd-compressed per
  buffer into an explicit versioned little-endian wire format (below),
  then moved over a byte stream (TCP in the prototype — the
  jax.distributed coordinator plays no role in the data path). The
  codec role is the same one ``runtime/memory.py`` plays for spill
  (``_pack_array``), but the wire needs self-describing framing a
  Python-tuple snapshot cannot provide, so the format here is its own
  — versioned precisely so the two can evolve independently;
* the receiver decompresses, restores device columns, and concatenates
  them into its local batch ahead of the intra-slice shuffle.

Design note — why host-staged, and what real DCN changes
--------------------------------------------------------
ICI moves ~100s of GB/s per link and is lossless inside a slice; DCN is
1-2 orders slower per host and shared, so the cross-slice hop is
bandwidth-precious in exactly the way ICI is not. That asymmetry drives
three choices a production path keeps:

1. **Compress only the DCN hop.** zstd at level 3 costs ~GB/s of host
   CPU and typically halves relational payloads (sorted-ish int64 key
   columns compress far better than that); at DCN bandwidth the codec
   pays for itself, at ICI bandwidth it never does — which is why the
   intra-slice shuffle uses narrowing/BitPack wire specs on device
   instead (parallel/wire.py).
2. **Two-level partitioning, slice first.** Rows cross DCN at most
   once: slice ownership is decided before any intra-slice exchange, so
   the expensive hop carries only rows that truly change slices
   (expected fraction (S-1)/S), never re-shuffles.
3. **Host staging is the fallback, not the ideal.** On hardware where
   XLA exposes cross-slice collectives (megascale / multi-slice
   jax.distributed), the same two-level plan lowers the outer hop onto
   those collectives and the host path remains the portability/recovery
   route (and the only route between heterogeneous slices). The wire
   format below is transport-agnostic for that reason: any byte stream
   (TCP, RDMA verbs, an object store for elastic retry) carries it.

Wire format (version 1, all little-endian):
  "TPDC" | u32 version | u32 ncols | u64 nrows | ncols x column
  column: i32 type_id | i32 scale | u8 flags (1=validity, 2=chars,
          4=children) | [u32 nchildren] | buffers (data, [validity],
          [chars]) | [children...]
  buffer: u8 dtype_str_len | dtype_str | u8 ndim | ndim x u64 shape |
          u8 compressed | u64 payload_len | payload

The ``compressed`` flag takes three values: 0 = raw bytes, 1 = legacy
whole-buffer zstd, 2 = a self-describing runtime/compress.py codec frame
(dictionary/RLE/bit-pack + optional zstd final stage) — the default
whenever ``compress.enabled`` + ``compress.wire`` are on. Flag-2 decode
re-checks the decoded dtype and shape against this buffer header (the
post-decode check of the compress -> seal contract); with the codec off
the stream is byte-for-byte the legacy 0/1 framing. The optional
``zstandard`` import guard this module used to carry is hoisted into
``runtime/compress.py`` (``zstd_codec``) and re-exported here as
``_zstd``, so wire and codec can never disagree on availability.

With ``integrity.enabled`` every framed payload additionally carries the
runtime/integrity.py length+checksum trailer and the link runs a
stop-and-wait ACK/NAK handshake (see :class:`SliceLink`) so a corrupt
frame is refetched from the sender instead of decoded into garbage.
Ordering per frame is compress -> seal on send and verify -> decompress
-> post-decode check on receive; an ARQ resend re-seals the pristine
compressed blob (the codec runs once per table, not per attempt).
"""

from __future__ import annotations

import struct
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.runtime import compress
from spark_rapids_jni_tpu.types import DType, TypeId
from spark_rapids_jni_tpu.utils.config import get_option
from spark_rapids_jni_tpu.utils.tracing import func_range

_MAGIC = b"TPDC"
_VERSION = 1

# the shared optional-zstandard guard, re-exported under its old name
_zstd = compress.zstd_codec


def _write_buffer(out: list, arr: Optional[np.ndarray], cctx,
                  codec: bool = False) -> None:
    a = np.ascontiguousarray(arr)
    dts = a.dtype.str.encode()
    out.append(struct.pack("<B", len(dts)))
    out.append(dts)
    out.append(struct.pack("<B", a.ndim))
    out.append(struct.pack(f"<{a.ndim}Q", *a.shape))
    if codec:
        flag, payload = 2, compress.encode_array(a, seam="integrity.wire")
    elif cctx is not None:
        flag, payload = 1, cctx.compress(a)
    else:
        flag, payload = 0, a.tobytes()
    out.append(struct.pack("<BQ", flag, len(payload)))
    out.append(payload)


class _Reader:
    def __init__(self, blob: bytes):
        self.b = blob
        self.i = 0

    def take(self, n: int) -> bytes:
        if self.i + n > len(self.b):
            raise ValueError("truncated DCN frame")
        v = self.b[self.i: self.i + n]
        self.i += n
        return v

    def unpack(self, fmt: str):
        size = struct.calcsize(fmt)
        return struct.unpack(fmt, self.take(size))


def _read_buffer(r: _Reader, dctx) -> np.ndarray:
    (dlen,) = r.unpack("<B")
    dts = r.take(dlen).decode()
    (ndim,) = r.unpack("<B")
    shape = r.unpack(f"<{ndim}Q") if ndim else ()
    compressed, plen = r.unpack("<BQ")
    payload = r.take(plen)
    if compressed == 2:
        # codec frame: decode failures raise classified CorruptDataError
        # (the seal already verified upstream — this is the corrupt-
        # after-decompress net), then the buffer header is the
        # post-decode length/shape oracle
        arr = compress.decode_array(payload, seam="integrity.wire",
                                    op="dcn.read_buffer")
        if arr.dtype.str != dts or tuple(arr.shape) != tuple(shape):
            raise compress.corrupt(
                "decoded wire buffer disagrees with frame header",
                seam="integrity.wire", op="dcn.read_buffer",
                declared=f"{dts}{tuple(shape)}",
                actual=f"{arr.dtype.str}{tuple(arr.shape)}")
        return arr
    if compressed:
        if dctx is None:
            raise ModuleNotFoundError(
                "zstandard is required to decode a compressed DCN frame")
        payload = dctx.decompress(payload)
    return np.frombuffer(payload, dtype=np.dtype(dts)).reshape(shape)


def _write_column(out: list, c: Column, cctx, codec: bool = False) -> None:
    flags = ((1 if c.validity is not None else 0)
             | (2 if c.chars is not None else 0)
             | (4 if c.children else 0))
    out.append(struct.pack("<iiB", int(c.dtype.type_id),
                           c.dtype.scale or 0, flags))
    if c.children:
        out.append(struct.pack("<I", len(c.children)))
    _write_buffer(out, np.asarray(c.data), cctx, codec)
    if c.validity is not None:
        _write_buffer(out, np.asarray(c.validity), cctx, codec)
    if c.chars is not None:
        _write_buffer(out, np.asarray(c.chars), cctx, codec)
    for ch in (c.children or ()):
        _write_column(out, ch, cctx, codec)


def _read_column(r: _Reader, dctx) -> Column:
    type_id, scale, flags = r.unpack("<iiB")
    nchildren = r.unpack("<I")[0] if flags & 4 else 0
    data = jnp.asarray(_read_buffer(r, dctx))
    validity = jnp.asarray(_read_buffer(r, dctx)) if flags & 1 else None
    chars = jnp.asarray(_read_buffer(r, dctx)) if flags & 2 else None
    children = [_read_column(r, dctx) for _ in range(nchildren)] or None
    tid = TypeId(type_id)
    dt = DType(tid, scale) if DType(tid).is_decimal else DType(tid)
    return Column(dt, data, validity, chars=chars, children=children)


@func_range("dcn_serialize_table")
def serialize_table(table: Table, compress_level: int = 3) -> bytes:
    """Device table -> one self-describing compressed wire frame.

    With ``compress.enabled`` + ``compress.wire`` every buffer rides the
    columnar codec (flag-2 framing; ``compress_level`` is superseded by
    ``compress.zstd_level`` inside the codec). Codec off restores the
    legacy path exactly: whole-buffer zstd at ``compress_level`` > 0
    (which hard-requires zstandard, as before), raw flag-0 buffers at
    level 0."""
    codec = compress.seam_enabled("integrity.wire")
    cctx = None
    if not codec and compress_level:
        cctx, _ = _zstd(compress_level)
    out: list = [
        _MAGIC,
        struct.pack("<IIQ", _VERSION, table.num_columns, table.num_rows),
    ]
    for c in table.columns:
        _write_column(out, c, cctx, codec)
    return b"".join(out)


@func_range("dcn_deserialize_table")
def deserialize_table(blob: bytes) -> Table:
    r = _Reader(blob)
    if r.take(4) != _MAGIC:
        raise ValueError("not a DCN table frame")
    version, ncols, _nrows = r.unpack("<IIQ")
    if version != _VERSION:
        raise ValueError(f"DCN frame version {version} != {_VERSION}")
    try:
        _, dctx = _zstd(1)
    except ModuleNotFoundError:
        dctx = None  # uncompressed frames decode without the codec
    return Table([_read_column(r, dctx) for _ in range(ncols)])


@func_range("partition_for_slices")
def partition_for_slices(table: Table, keys: Sequence[int],
                         n_slices: int) -> list[Table]:
    """Split local rows by owner slice: ``partition_hash(keys) %
    n_slices`` — the outer level of the two-level partitioning (the
    intra-slice shuffle spreads the same hash over the slice's
    devices). Host-side compaction is free here: the DCN hop stages
    through host memory anyway, so dynamic result shapes cost nothing
    (the out-of-core chunk-boundary argument)."""
    from spark_rapids_jni_tpu.ops.hash import partition_hash

    from spark_rapids_jni_tpu.ops.sort import gather

    dest = np.asarray(partition_hash(table, list(keys), n_slices))
    out = []
    for s in range(n_slices):
        idx = jnp.asarray(np.flatnonzero(dest == s).astype(np.int32))
        out.append(gather(table, idx))
    return out


def _bind_listener(port: int, host: Optional[str], backlog: int):
    """Bound, listening TCP socket on the configurable DCN interface
    (``dcn.bind_host`` when ``host`` is None — never a hardcoded
    loopback literal in the callers)."""
    import socket as pysock

    srv = pysock.socket()
    srv.setsockopt(pysock.SOL_SOCKET, pysock.SO_REUSEADDR, 1)
    srv.bind((host or str(get_option("dcn.bind_host")), port))
    srv.listen(backlog)
    return srv


def dial(port: int, host: Optional[str] = None, *,
         retries: int = 100, delay_s: float = 0.1):
    """Dial a DCN peer with bounded, classified connect retry.

    The peer's listener usually races the dialer (a booting worker, a
    slice that has not reached its exchange yet), so refusal is the
    expected first answer: each failed attempt is classified
    :class:`~.resilience.TransportError` (the ``dcn.transport`` seam's
    shape for socket errors, transient -> retried under
    ``resilience.retrying`` with the caller's attempt/backoff bounds,
    visible as ``resilience.*`` retry events). Exhaustion surfaces the
    classified chain — never a raw ``OSError``. Returns the connected
    socket; ``host`` defaults to ``dcn.bind_host``."""
    import socket as pysock

    from spark_rapids_jni_tpu.runtime import resilience

    peer = host or str(get_option("dcn.bind_host"))

    def _attempt():
        s = pysock.socket()
        try:
            s.connect((peer, port))
            return s
        except OSError as exc:
            s.close()
            raise resilience.TransportError(
                f"dcn.dial: connect to {peer}:{port} failed: {exc}",
                seam="dcn.transport", host=peer, port=port) from exc

    if resilience.enabled():
        pol = resilience.policy()
        pol.max_attempts = max(1, int(retries))
        pol.backoff_ms = max(0, int(delay_s * 1000))
        pol.backoff_multiplier = 1.0
        return resilience.retrying("dcn.dial", _attempt,
                                   seam="dcn.transport", pol=pol,
                                   host=peer, port=port)
    for attempt in range(max(1, int(retries))):
        try:
            return _attempt()
        except resilience.TransportError:
            if attempt == max(1, int(retries)) - 1:
                raise
            import time

            time.sleep(delay_s)


class SliceServer:
    """Multi-peer accept side of the DCN transport: one listener many
    :class:`SliceLink`-style peers dial into. ``SliceLink.listen``
    serves exactly one lockstep peer (the two-slice exchange); a mesh
    supervisor instead keeps the listener open and accepts each host
    worker as it dials back, so this class owns the bound socket and
    hands out one connected socket per :meth:`accept`.

    ``port=0`` binds an ephemeral port (read it back from ``.port``);
    ``host`` defaults to ``dcn.bind_host``. Frames on the accepted
    sockets carry whatever discipline the caller wraps them in (the
    cluster wraps each in the fleet's sealed ``_FrameChannel``; table
    payloads inside stay ``serialize_table`` blobs, so compression and
    the integrity trailer remain outermost)."""

    def __init__(self, port: int = 0, host: Optional[str] = None,
                 backlog: int = 16):
        self.host = host or str(get_option("dcn.bind_host"))
        self._sock = _bind_listener(port, self.host, backlog)
        self.port = int(self._sock.getsockname()[1])
        self._closed = False

    def accept(self, timeout: Optional[float] = None):
        """Block for the next peer dial-in; returns ``(sock, addr)``.
        Raises ``TimeoutError`` on timeout and ``OSError`` once closed."""
        self._sock.settimeout(timeout)
        try:
            return self._sock.accept()
        except TimeoutError:
            raise
        except OSError:
            if self._closed:
                raise OSError("SliceServer is closed")
            raise

    def accept_link(self, timeout: Optional[float] = None) -> "SliceLink":
        """Accept one peer and wrap it as a table-frame SliceLink."""
        conn, _ = self.accept(timeout)
        return SliceLink(conn)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "SliceServer":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


_ACK = b"\x06"
_NAK = b"\x15"


def _recv_exact(sock, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionError("peer slice closed the DCN link")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_framed(sock, blob: bytes, seq: int, *,
                op: str = "dcn.send_table",
                corrupt_seam: str = "integrity.wire",
                **ctx) -> int:
    """Ship one length-prefixed payload under the shared seal-ordering
    discipline — THE frame-encode helper for every per-destination send
    loop (SliceLink table frames and the exchange's per-destination
    flight buffers alike; there is exactly one copy of this ordering).

    Integrity off: bare 8-byte length prefix, no trailer, no ack.
    Integrity on: seal -> injected-corruption window (``corrupt_seam``,
    the link-corruption shape the trailer exists to catch) -> send ->
    await ACK; each NAK re-seals the PRISTINE blob and resends, bounded
    by ``resilience.max_attempts``; exhaustion dies classified with a
    flight record. ``ctx`` flows into the corruption seam's context."""
    from spark_rapids_jni_tpu.runtime import faults, integrity, resilience

    if not integrity.enabled():
        sock.sendall(struct.pack("<Q", len(blob)) + blob)
        return len(blob)
    attempts = max(1, resilience.policy().max_attempts)
    for attempt in range(1, attempts + 1):
        framed = integrity.seal(blob)
        # the corruption window sits BETWEEN seal and send — each resend
        # re-seals the pristine blob, so a refetch recovers
        framed = faults.fire_corrupt(corrupt_seam, seq, framed,
                                     attempt=attempt, **ctx)
        sock.sendall(struct.pack("<Q", len(framed)) + framed)
        if _recv_exact(sock, 1) == _ACK:
            return len(framed)
    from spark_rapids_jni_tpu.telemetry import spans

    flight = spans.dump_flight_record(
        "wire_corruption", state={"attempts": attempts, "frame": seq})
    raise resilience.FatalExecutionError(
        f"{op}: peer rejected frame {seq} as corrupt after "
        f"{attempts} resends",
        seam="dcn.transport", attempts=attempts,
        **({"flight_record": flight} if flight else {}))


def recv_framed(sock, seq: int, *, op: str = "dcn.recv_table") -> bytes:
    """Receive one framed payload under the shared verify-then-decode
    discipline: length prefix, then (with integrity on) trailer
    verification with NAK-driven refetch from the sender's pristine
    copy — the receive half of :func:`send_framed`'s ARQ. Returns the
    verified payload bytes; the caller decodes (``deserialize_table``
    or the exchange's flight decode) AFTER verification, never before."""
    from spark_rapids_jni_tpu import telemetry
    from spark_rapids_jni_tpu.runtime import integrity, resilience

    verified = integrity.enabled()
    attempts = max(1, resilience.policy().max_attempts)
    attempt = 1
    while True:
        hdr = _recv_exact(sock, 8)
        (length,) = struct.unpack("<Q", hdr)
        framed = _recv_exact(sock, length)
        if not verified:
            return framed
        try:
            blob = integrity.verify(
                framed, seam="integrity.wire", op=op,
                frame=seq, attempt=attempt)
        except resilience.CorruptDataError as exc:
            # refetch: the sender still holds the pristine payload, so
            # NAK asks for a fresh frame. NAK even on the final
            # attempt — the sender's loop shares the attempt budget,
            # so both sides die classified instead of deadlocking on
            # a half-acknowledged frame.
            telemetry.REGISTRY.counter("integrity.refetch").inc()
            telemetry.record_integrity(
                op, "refetch", seam="integrity.wire",
                nbytes=length, attempt=attempt, frame=seq)
            sock.sendall(_NAK)
            if attempt >= attempts:
                from spark_rapids_jni_tpu.telemetry import spans

                flight = spans.dump_flight_record(
                    "wire_corruption",
                    state={"attempts": attempts, "frame": seq})
                raise resilience.FatalExecutionError(
                    f"{op}: frame {seq} corrupt "
                    f"after {attempts} refetches: {exc}",
                    seam="dcn.transport", attempts=attempts,
                    **({"flight_record": flight} if flight else {}),
                ) from exc
            attempt += 1
            continue
        sock.sendall(_ACK)
        if attempt > 1:
            telemetry.record_integrity(
                op, "recovered", seam="integrity.wire",
                nbytes=length, attempt=attempt, frame=seq)
        return blob


class SliceLink:
    """One reliable byte stream to a peer slice (TCP prototype; the
    format is transport-agnostic — see the module design note). Frames
    are 8-byte-length-prefixed serialize_table payloads.

    With ``integrity.enabled`` each frame additionally carries the
    integrity layer's length+checksum trailer and the receiver answers
    every frame with one acknowledgement byte: ACK (0x06) accepts, NAK
    (0x15) reports a verification mismatch and asks the sender — which
    still holds a pristine copy — to re-seal and resend (stop-and-wait
    ARQ; the lockstep two-slice exchange is already half-duplex, so the
    ack adds half a round trip, not a pipeline stall). Both sides bound
    refetches by ``resilience.max_attempts``; exhaustion dies classified
    with a flight record. Disabled, the byte stream is exactly the
    legacy framing: no trailer, no acknowledgements. The seal-ordering
    itself lives in the module-level :func:`send_framed` /
    :func:`recv_framed` pair this class delegates to."""

    _ACK = _ACK
    _NAK = _NAK

    def __init__(self, sock):
        self._sock = sock
        self._send_seq = 0
        self._recv_seq = 0

    @classmethod
    def listen(cls, port: int, host: Optional[str] = None) -> "SliceLink":
        srv = _bind_listener(port, host, backlog=1)
        conn, _ = srv.accept()
        srv.close()
        return cls(conn)

    @classmethod
    def connect(cls, port: int, host: Optional[str] = None,
                retries: int = 100, delay_s: float = 0.1) -> "SliceLink":
        return cls(dial(port, host, retries=retries, delay_s=delay_s))

    def send_table(self, table: Table, compress_level: int = 3) -> int:
        from spark_rapids_jni_tpu.runtime import faults, resilience

        def _frame():
            # seam + retry cover serialization only: once sendall starts,
            # bytes on the wire make a blind replay corrupt the stream —
            # transport-level resend belongs below this framing layer
            faults.fire("dcn.transport", 0, direction="send",
                        rows=table.num_rows)
            return serialize_table(table, compress_level)

        if resilience.enabled():
            blob = resilience.retrying(
                "dcn.send_table", _frame, seam="dcn.transport",
                rows=table.num_rows)
        else:
            blob = _frame()
        from spark_rapids_jni_tpu.runtime import integrity

        if integrity.enabled():
            self._send_seq += 1
        return send_framed(self._sock, blob, self._send_seq,
                           op="dcn.send_table", rows=table.num_rows)

    def recv_table(self) -> Table:
        from spark_rapids_jni_tpu.runtime import faults, resilience

        def _entry():
            # fires before any read: an injected fault must not desync
            # framing, so the retryable window closes at the first recv
            faults.fire("dcn.transport", 0, direction="recv")

        if resilience.enabled():
            resilience.retrying("dcn.recv_table", _entry,
                                seam="dcn.transport")
        else:
            _entry()
        from spark_rapids_jni_tpu.runtime import integrity

        if integrity.enabled():
            self._recv_seq += 1
        return deserialize_table(
            recv_framed(self._sock, self._recv_seq, op="dcn.recv_table"))

    def _recv_exact(self, n: int) -> bytes:
        return _recv_exact(self._sock, n)

    def close(self) -> None:
        self._sock.close()


@func_range("exchange_across_slices")
def exchange_across_slices(table: Table, keys: Sequence[int],
                           link: SliceLink, slice_id: int,
                           n_slices: int = 2,
                           compress_level: int = 3) -> Table:
    """Two-slice repartition: keep the rows this slice owns, ship the
    rest over the link, receive the peer's shipment, concatenate.
    Deadlock-free by role: the lower slice id sends first (prototype —
    a >2-slice ring would pipeline sends/recvs).

    Returns the slice-owned local batch, ready for the intra-slice
    ICI shuffle."""
    if n_slices != 2:
        raise NotImplementedError("prototype models exactly two slices")
    from spark_rapids_jni_tpu.ops.table_ops import concatenate

    parts = partition_for_slices(table, keys, n_slices)
    mine, theirs = parts[slice_id], parts[1 - slice_id]
    if slice_id == 0:
        link.send_table(theirs, compress_level)
        received = link.recv_table()
    else:
        received = link.recv_table()
        link.send_table(theirs, compress_level)
    if received.num_rows == 0:
        return mine
    if mine.num_rows == 0:
        return received
    return concatenate([mine, received])


# ---------------------------------------------------------------------------
# direct peer flights: HMAC-signed dial grants + the worker flight gateway
# ---------------------------------------------------------------------------
#
# The cluster's dial-back gateway generalized: not only does every host
# worker dial the SUPERVISOR back at boot, every worker also runs a
# :class:`PeerFlightServer` so other hosts can dial IT with exchange
# flights — the supervisor ships only the routing manifest
# (per-destination flight list + fingerprints + token grants) and the
# flight bytes move host-to-host over the same sealed ``send_framed``
# ARQ discipline as every other DCN payload. A peer dial is only
# accepted with a grant HMAC-signed by the supervisor (key derived from
# the cluster's per-boot secret), so an unauthenticated peer cannot
# inject rows into a merge; rejections are counted and recorded exactly
# like rejected supervisor dial-ins.

_GRANT_INFO = b"spark-rapids-tpu/peer-grant/v1"


def grant_key(boot_secret: str) -> bytes:
    """Derive the per-boot peer-grant HMAC key from the cluster's boot
    secret (minted fresh every supervisor construction, shipped to each
    worker in its launch environment — never over the data path)."""
    import hashlib
    import hmac as _hmac

    return _hmac.new(boot_secret.encode("utf-8"), _GRANT_INFO,
                     hashlib.sha256).digest()


def sign_grant(key: bytes, *, xid: str, src: str, dest: str,
               part: int) -> str:
    """Sign one peer-dial grant: the supervisor authorizes exactly one
    (exchange, source host, destination host, destination part) flight."""
    import hashlib
    import hmac as _hmac

    msg = f"{xid}|{src}|{dest}|{int(part)}".encode("utf-8")
    return _hmac.new(key, msg, hashlib.sha256).hexdigest()


def verify_grant(key: bytes, grant: str, *, xid: str, src: str,
                 dest: str, part: int) -> bool:
    """Constant-time check of a presented grant against the per-boot
    key; a False return means the dial is refused before any flight
    bytes are read."""
    import hmac as _hmac

    want = sign_grant(key, xid=xid, src=src, dest=dest, part=part)
    return _hmac.compare_digest(want, str(grant))


def flight_fingerprint(blob: bytes) -> str:
    """Content fingerprint of one serialized flight blob — what the
    manifest carries and what a destination verifies before any byte is
    decoded (the cross-host half of verify-then-decode for the direct
    path)."""
    import hashlib

    return hashlib.sha256(blob).hexdigest()


def send_peer_flight(addr, header: dict, blob: bytes, *,
                     retries: Optional[int] = None,
                     delay_s: Optional[float] = None,
                     op: str = "exchange.peer_flight", **ctx) -> int:
    """Dial a destination's :class:`PeerFlightServer` and ship one
    flight: a pickled header frame (xid/src/part/grant/fingerprint),
    then the flight blob, both under :func:`send_framed`'s seal + ARQ
    discipline on the ``exchange.wire`` corruption seam. The dial uses
    a SHORT bounded retry (``exchange.peer_dial_retries``): a dead peer
    must fail fast into the routed fallback rung, not stall the
    exchange. Raises the classified ``TransportError`` chain on dial
    exhaustion and ``ConnectionError`` if the peer refuses the grant."""
    import pickle

    host, port = addr
    n = int(retries if retries is not None
            else get_option("exchange.peer_dial_retries"))
    d = float(delay_s if delay_s is not None
              else get_option("exchange.peer_dial_delay_s"))
    sock = dial(int(port), host or None, retries=n, delay_s=d)
    try:
        send_framed(sock, pickle.dumps(header, protocol=4), 0,
                    op="dcn.peer_hello", corrupt_seam="integrity.wire")
        sent = send_framed(sock, blob, 1, op=op,
                           corrupt_seam="exchange.wire", **ctx)
        return sent
    finally:
        try:
            sock.close()
        except OSError:
            pass


class PeerFlightServer:
    """Worker-side flight gateway: one listener per cluster worker that
    other hosts dial DIRECTLY with exchange flights, so the supervisor
    link carries only manifests and acks.

    Each accepted connection is served off-thread: header frame first
    (grant verified against the per-boot key BEFORE any flight bytes
    are read; a bad grant counts ``cluster.rejected_dials`` and closes
    the socket), then the sealed flight blob, which lands in the
    mailbox keyed ``(xid, part)`` by source host. The destination's
    merge step collects with :meth:`wait_flights` and verifies each
    blob against the supervisor's manifest fingerprints before it
    decodes (tpulint rule 26). The mailbox is bounded
    (``max_entries``): overflow evicts the oldest flight with a counter
    so an abandoned exchange cannot pin worker memory forever."""

    def __init__(self, key: bytes, *, dest: str,
                 host: Optional[str] = None, max_entries: int = 256):
        import threading

        self._key = key
        self._dest = str(dest)
        self._srv = SliceServer(host=host)
        self.host, self.port = self._srv.host, self._srv.port
        self._max_entries = int(max_entries)
        self._lock = threading.Lock()
        self._mail: "dict[tuple, dict]" = {}
        self._order: "list[tuple]" = []  # (xid, part, src) arrival order
        self._arrived = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"peer-flights-{self._dest}")
        self._thread.start()

    def _accept_loop(self) -> None:
        import threading

        while not self._stop.is_set():
            try:
                conn, _addr = self._srv.accept(timeout=0.2)
            except TimeoutError:
                continue
            except OSError:
                if self._stop.is_set():
                    return
                continue
            threading.Thread(target=self._serve_peer, args=(conn,),
                             daemon=True,
                             name=f"peer-flight-{self._dest}").start()

    def _serve_peer(self, conn) -> None:
        """One peer dial-in: verify the grant, then receive the flight
        into the mailbox (header and payload both framed/ARQ'd)."""
        import pickle

        from spark_rapids_jni_tpu.telemetry.events import record_fleet
        from spark_rapids_jni_tpu.telemetry.registry import REGISTRY

        try:
            try:
                hdr = pickle.loads(recv_framed(conn, 0,
                                               op="dcn.peer_hello"))
                xid = str(hdr.get("xid", ""))
                src = str(hdr.get("src", ""))
                part = int(hdr.get("part", -1))
                ok = verify_grant(self._key, str(hdr.get("grant", "")),
                                  xid=xid, src=src, dest=self._dest,
                                  part=part)
                if not ok:
                    # unauthenticated peer: refuse BEFORE any flight
                    # bytes are read, visibly — same counter as a
                    # rejected supervisor dial-in
                    REGISTRY.counter("cluster.rejected_dials").inc()
                    record_fleet("cluster.peer_gateway", "rejected_dial",
                                 replica=self._dest, peer=src, xid=xid,
                                 part=part)
                    return
                blob = recv_framed(conn, 1, op="exchange.peer_flight")
            except Exception as exc:
                # a half-dial (peer died mid-flight, corrupt beyond the
                # ARQ budget): account for the swallow — the exchange's
                # own timeout surfaces the missing flight classified
                REGISTRY.counter("exchange.peer_recv_failures").inc()
                record_fleet("cluster.peer_gateway", "peer_recv_failed",
                             replica=self._dest,
                             error_kind=type(exc).__name__)
                return
            REGISTRY.counter("exchange.peer_flights_recv").inc()
            self.deliver(xid, part, src, blob)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def deliver(self, xid: str, part: int, src: str, blob: bytes) -> None:
        """Land one flight in the mailbox (also the self-delivery path:
        a source whose destination is itself skips the dial)."""
        from spark_rapids_jni_tpu.telemetry.registry import REGISTRY

        key = (str(xid), int(part))
        with self._lock:
            self._mail.setdefault(key, {})[str(src)] = blob
            self._order.append((key[0], key[1], str(src)))
            while len(self._order) > self._max_entries:
                oxid, opart, osrc = self._order.pop(0)
                box = self._mail.get((oxid, opart))
                if box is not None and box.pop(osrc, None) is not None:
                    REGISTRY.counter("exchange.peer_mail_evicted").inc()
                if box is not None and not box:
                    self._mail.pop((oxid, opart), None)
            self._arrived.set()

    def wait_flights(self, xid: str, part: int, srcs,
                     timeout: Optional[float] = None) -> dict:
        """Block until every source in ``srcs`` has delivered its flight
        for ``(xid, part)``; returns ``{src: blob}``. The caller MUST
        verify each blob against the manifest fingerprint before
        decoding. Raises ``TimeoutError`` naming the missing sources."""
        import time

        want = {str(s) for s in srcs}
        deadline = None if timeout is None else time.monotonic() + timeout
        key = (str(xid), int(part))
        while True:
            with self._lock:
                box = dict(self._mail.get(key) or {})
                self._arrived.clear()
            if want <= set(box):
                return {s: box[s] for s in want}
            left = (None if deadline is None
                    else deadline - time.monotonic())
            if left is not None and left <= 0:
                raise TimeoutError(
                    f"peer flights for exchange {xid!r} part {part} "
                    f"missing from {sorted(want - set(box))} after "
                    f"{timeout}s")
            self._arrived.wait(0.05 if left is None else min(left, 0.05))

    def discard(self, xid: str, part: Optional[int] = None) -> None:
        """Drop mailbox state for a finished (or abandoned) exchange."""
        with self._lock:
            keys = [k for k in self._mail
                    if k[0] == str(xid)
                    and (part is None or k[1] == int(part))]
            for k in keys:
                self._mail.pop(k, None)
            self._order = [o for o in self._order
                           if (o[0], o[1]) not in set(keys)]

    def close(self) -> None:
        self._stop.set()
        self._srv.close()
        self._thread.join(timeout=2.0)
