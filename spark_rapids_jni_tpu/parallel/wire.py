"""Device-side wire codec for the shuffle transport — the nvcomp role
(the reference ships nvcomp in the jar for UCX shuffle compression,
reference pom.xml:410-416).

TPU-first constraint: everything under jit has static shapes, so a codec
whose output size depends on the data (entropy coding) cannot ride the
collective. What can: **planner-declared transforms with static output
size and dynamic overflow detection** — the same contract as wire-type
narrowing. This module adds frame-of-reference + bit-packing:

    BitPack(bits=12, reference=8400)

packs each value' = value - reference into ``bits`` bits, 32 values per
``bits`` uint32 words — e.g. date columns (int32, ~15k distinct days)
cross the wire at 14 bits/row instead of 32, a 2.3x reduction, composing
with narrowing (the planner picks whichever is smaller). A value outside
[0, 2^bits) sets the shuffle's ``narrowing_overflow`` flag — detection,
not silent truncation, exactly like the reference's hard batch bounds
(reference row_conversion.cu:476-479).

Scope note: this module is the DEVICE-side codec (value transforms that
ride the collective). The host-side byte frames — serialization, the
``runtime/compress.py`` columnar codec (dictionary/RLE/bit-pack per
buffer, compressed BEFORE the integrity seal), the runtime/integrity.py
checksum trailer, and the NAK/refetch protocol for corrupt frames — all
live in ``parallel/dcn.py``; nothing here touches raw wire bytes, so
neither the compression nor the integrity seam passes through this file.

Pack layout: value j of a block occupies bits [j*bits, (j+1)*bits) of the
little-endian uint32 word stream — FOR/bit-pack order compatible with the
classic Parquet/ORC bitpacking definition, so the same math later backs
the DELTA_BINARY_PACKED reader.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class BitPack:
    """Planner-declared wire spec: k-bit frame-of-reference packing."""

    bits: int
    reference: int = 0

    def __post_init__(self) -> None:
        if not 1 <= self.bits <= 32:
            raise ValueError("bits must be in [1, 32]")

    def words_for(self, n: int) -> int:
        """uint32 words needed for n values (static)."""
        return (n * self.bits + 31) // 32


def pack_bits(values: jnp.ndarray, spec: BitPack) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Pack integer ``values`` (any integral dtype, trailing axis = values)
    into uint32 words. Returns (packed[..., W], overflowed scalar bool).

    Leading axes (e.g. the per-destination blocks of a shuffle send buffer)
    pack independently so the word stream splits cleanly per destination.

    TPU-first formulation: gather-based, not scatter-based. Each output
    word OR-combines the <= ceil(32/bits)+1 values whose bit fields overlap
    it — a static unrolled loop of dense gathers the VPU tiles cleanly
    (the scatter-add formulation measured ~3x slower than CPU on v5e; see
    BASELINE.md).
    """
    bits = spec.bits
    n = int(values.shape[-1])
    w = spec.words_for(n)
    v64 = values.astype(jnp.int64) - spec.reference
    overflow = jnp.any((v64 < 0) | (v64 >= (1 << bits)))
    v = v64.astype(jnp.uint32) & jnp.uint32((1 << bits) - 1)

    # word w covers bits [32w, 32w+32); contributing values j satisfy
    # j*bits < 32w+32 and (j+1)*bits > 32w
    word_bit0 = np.arange(w, dtype=np.int64) * 32
    j_min = word_bit0 // bits
    k_max = int(np.max((word_bit0 + 31) // bits - j_min)) if w else 0

    shape = values.shape[:-1] + (w,)
    packed = jnp.zeros(shape, jnp.uint32)
    base = jnp.asarray(word_bit0, dtype=jnp.int64)
    for k in range(k_max + 1):
        j = j_min + k
        valid_j = j < n
        jc = jnp.asarray(np.minimum(j, max(n - 1, 0)), dtype=jnp.int32)
        vj = v[..., jc]
        # shift of value j relative to word start: j*bits - 32w, in
        # (-32, 32); negative = the value started in an earlier word
        shift = jnp.asarray(j * bits, dtype=jnp.int64) - base
        left = jnp.where(shift > 0, shift, 0).astype(jnp.uint32)
        right = jnp.where(shift < 0, -shift, 0).astype(jnp.uint32)
        contrib = (vj << left) >> right
        contrib = jnp.where(jnp.asarray(valid_j), contrib, jnp.uint32(0))
        packed = packed | contrib
    return packed, overflow


def unpack_bits(packed: jnp.ndarray, n: int, spec: BitPack,
                dtype) -> jnp.ndarray:
    """Inverse of pack_bits: uint32 words -> n values of ``dtype``."""
    bits = spec.bits
    w = int(packed.shape[-1])
    bit0 = np.arange(n, dtype=np.int64) * bits
    word = jnp.asarray(bit0 // 32, dtype=jnp.int32)
    off = jnp.asarray(bit0 % 32, dtype=jnp.uint32)

    low = packed[..., word] >> off
    spill = off.astype(jnp.int64) + bits > 32
    nxt = packed[..., jnp.minimum(word + 1, w - 1)]
    high = jnp.where(
        spill,
        nxt << jnp.where(spill, jnp.uint32(32) - off, jnp.uint32(1)),
        jnp.uint32(0),
    )
    v = (low | high) & jnp.uint32((1 << bits) - 1)
    return (v.astype(jnp.int64) + spec.reference).astype(dtype)


def shuffle_wire_bytes(table, wire_dtypes, capacity: int,
                       num_devices: int) -> dict:
    """Planner accounting: bytes one device sends into the all_to_all per
    hash_shuffle call, per column plus masks, with and without the declared
    wire specs. Static — usable for bench lines and planner decisions."""
    size = num_devices * capacity
    per_col_raw: list[int] = []
    per_col_wire: list[int] = []
    for i, col in enumerate(table.columns):
        wire = None if wire_dtypes is None else wire_dtypes[i]
        if col.dtype.is_string:
            from spark_rapids_jni_tpu.ops.strings import pad_strings

            width = int(pad_strings(col).chars.shape[1])
            raw = size * (4 + width)  # int32 lengths + char matrix
            per_col_raw.append(raw)
            per_col_wire.append(raw)
            continue
        elem = col.dtype.size_bytes
        per_col_raw.append(size * elem)
        if isinstance(wire, BitPack):
            per_col_wire.append(num_devices * wire.words_for(capacity) * 4)
        elif wire is not None:
            per_col_wire.append(size * wire.size_bytes)
        else:
            per_col_wire.append(size * elem)
    mask_bytes = size * (1 + len(table.columns))  # occupied + per-col validity
    return {
        "raw_bytes": sum(per_col_raw) + mask_bytes,
        "wire_bytes": sum(per_col_wire) + mask_bytes,
        "per_column_raw": per_col_raw,
        "per_column_wire": per_col_wire,
        "mask_bytes": mask_bytes,
    }
