"""Distributed operators: shard tables over the executor mesh and run
shuffle-backed relational ops across it.

The execution model mirrors the Spark plugin's (SURVEY.md section 2.3): each
executor owns a partition of rows and runs the same operator pipeline; the
only inter-executor step is the repartition-by-key exchange, which here is
the ICI all_to_all in parallel.shuffle instead of the UCX shuffle manager.

Phantom rows (unoccupied shuffle slots) carry null keys and null values, so
aggregates skip them by construction; their only observable artifact is a
possible all-null key group in the padded output, which callers discard the
same way they discard local groupby padding.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_jni_tpu import telemetry
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.groupby import groupby_aggregate
from spark_rapids_jni_tpu.parallel.mesh import EXEC_AXIS
from spark_rapids_jni_tpu.parallel.shuffle import hash_shuffle
from spark_rapids_jni_tpu.utils.tracing import func_range


def _mesh_fingerprint(mesh: Mesh) -> tuple:
    """Hashable mesh identity for the dispatch executable cache: axis
    layout plus the concrete device assignment — a compiled shard_map
    program is specialized to both."""
    return (tuple(mesh.shape.items()),
            tuple(str(d) for d in mesh.devices.flat))


def head_table(table: Table, k: int) -> Table:
    """First k rows (static slice) — groupby outputs put real groups first."""
    cols = []
    for c in table.columns:
        if c.dtype.is_string and not c.is_padded_string:
            raise NotImplementedError(
                "head_table needs string columns in the padded device layout "
                "(ops.strings.pad_strings); Arrow offsets cannot be sliced "
                "like row data"
            )
        validity = None if c.validity is None else c.validity[:k]
        chars = c.chars[:k] if c.is_padded_string else None
        cols.append(Column(c.dtype, c.data[:k], validity, chars=chars))
    return Table(cols)


def shard_table(
    table: Table,
    mesh: Mesh,
    axis: str = EXEC_AXIS,
    return_row_valid: bool = False,
):
    """Distribute a host-built table row-wise across the mesh axis.

    Rows are padded to a multiple of the axis size with null rows (null
    rows fall out of every aggregate, the framework-wide masking idiom).
    With ``return_row_valid=True`` also returns the sharded bool[n] mask
    marking real rows — needed by operators where a padding row is not
    equivalent to a null-key row (left joins emit unmatched null-key rows
    but must not emit padding)."""
    d = mesh.shape[axis]
    n = table.num_rows
    pad = (-n) % d
    sharding = NamedSharding(mesh, P(axis))
    out = []
    for c in table.columns:
        if c.dtype.is_string:
            # strings shard in the padded device layout: int32 lengths ride
            # the fixed-width path, the (n, W) char matrix shards by rows
            from spark_rapids_jni_tpu.ops.strings import pad_strings

            p = pad_strings(c)
            lengths, mat = p.data, p.chars
            valid = p.valid_mask()
            if pad:
                lengths = jnp.concatenate([lengths, jnp.zeros((pad,), jnp.int32)])
                mat = jnp.concatenate(
                    [mat, jnp.zeros((pad, mat.shape[1]), jnp.uint8)]
                )
                valid = jnp.concatenate([valid, jnp.zeros((pad,), jnp.bool_)])
            out.append(Column(
                c.dtype,
                jax.device_put(lengths, sharding),
                jax.device_put(valid, sharding),
                chars=jax.device_put(mat, sharding),
            ))
            continue
        if not (c.dtype.is_fixed_width or c.dtype.is_decimal128):
            raise NotImplementedError(
                "shard_table: fixed-width and string columns only"
            )
        if pad:
            data = jnp.concatenate(
                [c.data, jnp.zeros((pad,) + c.data.shape[1:], c.data.dtype)]
            )
        else:
            data = c.data
        valid = c.valid_mask()
        valid = jnp.concatenate([valid, jnp.zeros((pad,), jnp.bool_)]) if pad else valid
        out.append(
            Column(
                c.dtype,
                jax.device_put(data, sharding),
                jax.device_put(valid, sharding),
            )
        )
    sharded = Table(out)
    if not return_row_valid:
        return sharded
    row_valid = jnp.concatenate(
        [jnp.ones((n,), jnp.bool_), jnp.zeros((pad,), jnp.bool_)]
    )
    return sharded, jax.device_put(row_valid, sharding)


def shard_table_multiprocess(
    local: Table,
    mesh: Mesh,
    axis: str = EXEC_AXIS,
) -> Table:
    """Multi-process variant of ``shard_table``: every participating
    process contributes its own local row chunk and gets back a GLOBAL
    sharded Table spanning all processes' devices (the
    one-PJRT-client-per-executor-JVM model, SURVEY.md section 7's
    riskiest piece).

    Requires ``jax.distributed.initialize`` to have run; ``mesh`` must
    span the global device list. Every process must call this
    collectively with the SAME number of local rows, a multiple of its
    local device count (pad with null rows first if needed — static
    shapes make uniform partitions a hard requirement, the same
    bucketed-padding discipline as everywhere else; verified here with
    an allgather so a mismatch fails loudly instead of hanging in the
    next collective). String columns are padded to the GLOBAL max char
    width (also allgathered) so every process builds the same program.

    What changes for Spark executor JVMs: each executor's embedded
    runtime calls ``jax.distributed.initialize(coordinator, n_execs,
    exec_id)`` once at startup (the coordinator address comes from the
    driver, like the UCX shuffle manager's handshake), builds the same
    global mesh from ``jax.devices()``, and builds global arrays from
    its local partitions exactly like this function. The jitted shuffle
    step is then identical to the single-process path — XLA's CPU/TPU
    collectives carry cross-process traffic (ICI on a slice, DCN across
    slices) without any operator-level change."""
    from jax.experimental import multihost_utils

    sharding = NamedSharding(mesh, P(axis))
    n_procs = jax.process_count()
    counts = np.asarray(multihost_utils.process_allgather(
        jnp.asarray([local.num_rows], jnp.int64), tiled=True))
    if not (counts == local.num_rows).all():
        raise ValueError(
            f"shard_table_multiprocess needs the SAME row count in every "
            f"process (static shapes); got per-process counts "
            f"{counts.tolist()} — pad with null rows to a common size "
            f"first")
    global_rows = local.num_rows * n_procs

    def make_global(arr):
        np_arr = np.asarray(arr)
        return jax.make_array_from_process_local_data(
            sharding, np_arr, (global_rows,) + np_arr.shape[1:])

    out = []
    for c in local.columns:
        if c.dtype.is_string:
            from spark_rapids_jni_tpu.ops.strings import pad_strings

            # pad to the GLOBAL max width: a process-local width would
            # compile a different program per process and wedge the
            # collectives on a shape mismatch
            if not c.is_padded_string:
                c = pad_strings(c)
            local_w = int(c.chars.shape[1])
            widths = np.asarray(multihost_utils.process_allgather(
                jnp.asarray([local_w], jnp.int64), tiled=True))
            target_w = int(widths.max())
            if local_w < target_w:  # pad_strings no-ops on padded input
                c = Column(c.dtype, c.data, c.validity, chars=jnp.pad(
                    c.chars, ((0, 0), (0, target_w - local_w))))
        chars = make_global(c.chars) if c.is_padded_string else None
        out.append(Column(
            c.dtype, make_global(c.data), make_global(c.valid_mask()),
            chars=chars,
        ))
    return Table(out)


class DistributedGroupBy(NamedTuple):
    table: Table             # per-device padded results, sharded over EXEC_AXIS
    num_groups: jnp.ndarray  # int32[D] groups owned by each device
    overflowed: jnp.ndarray  # bool[D] shuffle capacity overflow per device
    # bool[D] per-device DECIMAL128 SUM 128-bit overflow (the group is
    # nulled locally; this flag is how the caller tells an overflowed
    # group from an all-null-input group — Spark ANSI posture)
    sum_overflow: jnp.ndarray | bool = False


@func_range("distributed_groupby_aggregate")
def distributed_groupby_aggregate(
    table: Table,
    keys: Sequence[int],
    aggs: Sequence[tuple[int, str]],
    mesh: Mesh,
    capacity: Optional[int] = None,
) -> DistributedGroupBy:
    """Global groupby: shuffle rows by key hash, then one local groupby per
    device. After the exchange each device owns a disjoint key range, so the
    per-device results ARE the global answer, partitioned.

    ``table`` must already be sharded row-wise over ``mesh`` (shard_table).
    """
    aggs = list(aggs)
    aggs_fp = tuple(
        (int(c), tuple(op) if isinstance(op, tuple) else op)
        for c, op in aggs)
    return _distributed_groupby(
        table, list(keys), mesh, capacity,
        lambda sh_tbl, ks: groupby_aggregate(sh_tbl, ks, aggs),
        cache_key=("aggregate", aggs_fp))


class DistributedBoundedGroupBy(NamedTuple):
    """Replicated global result of the shuffle-free bounded plan: the
    same m-slot table on every device."""

    table: Table
    present: jnp.ndarray      # bool[m] — some row anywhere hit the slot
    domain_miss: jnp.ndarray  # scalar bool — any device saw an OOD key


@func_range("distributed_groupby_bounded")
def distributed_groupby_bounded(
    table: Table,
    keys: Sequence[int],
    aggs: Sequence[tuple[int, str]],
    domains: Sequence,
    mesh: Mesh,
    budget: int = 4096,
    row_valid: Optional[jnp.ndarray] = None,
) -> DistributedBoundedGroupBy:
    """SHUFFLE-FREE distributed groupby for planner-bounded keys.

    The bounded plan's output is a STATIC slot table (one row per domain
    combination) whose sum/count/min/max aggregates are associative per
    slot — so the cross-device merge is one collective over the m-row
    partials (psum / pmin / pmax), never a row shuffle. Where
    ``distributed_groupby_aggregate`` pays hash_shuffle (all_to_all of
    whole rows over ICI) plus per-device sort machinery, this path pays
    a per-device streaming masked-reduction pass plus an m-row
    collective: the single-chip 125x win (BASELINE.md round-4) composes
    with an m-vs-n bytes-on-wire win on the mesh.

    ``table`` must already be sharded row-wise over ``mesh``. Output is
    REPLICATED (every device holds the global m-slot answer) — m is
    small by construction, and replication is what lets the next
    pipeline stage consume it without a broadcast.

    Scope: sum/count/min/max (mean decomposes to sum+count — the q1
    partial-aggregate convention); no DECIMAL128 aggregate columns
    (limb-pair psum has no carry propagation — use the shuffle path).
    String KEYS are fine (on-device dictionary encode, static decode).
    """
    from spark_rapids_jni_tpu.ops.planner import plan_groupby

    aggs = list(aggs)
    for _, op in aggs:
        if op not in ("sum", "count", "min", "max"):
            raise ValueError(
                f"distributed bounded groupby supports sum/count/min/max "
                f"(decompose mean to sum+count), not {op!r}")
    for col_idx, _ in aggs:
        if table.column(col_idx).dtype.is_decimal128:
            raise NotImplementedError(
                "DECIMAL128 aggregates need carry-aware merges — use "
                "distributed_groupby_aggregate")
    # eager lowering validation (NOT an assert: an un-bounded plan
    # psummed across devices would sum rows of DIFFERENT keys —
    # silently wrong, so it must raise even under python -O)
    domains = list(domains)
    if any(d is None for d in domains):
        raise ValueError(
            "every key needs a declared Domain for the shuffle-free "
            "bounded plan; use distributed_groupby_aggregate otherwise")
    slots = int(np.prod([len(d.values) + 1 for d in domains]))
    if slots > budget:
        raise ValueError(
            f"domain cross product ({slots} slots) exceeds the bounded "
            f"budget ({budget}); use distributed_groupby_aggregate")
    nk = len(keys)

    def step(local: Table, rv):
        res = plan_groupby(local, list(keys), aggs, domains,
                           budget=budget, row_valid=rv)
        assert res.lowered == "bounded"  # guaranteed by the checks above
        present_g = jax.lax.psum(
            res.present.astype(jnp.int32), EXEC_AXIS) > 0
        miss_g = jax.lax.psum(
            res.domain_miss.astype(jnp.int32), EXEC_AXIS) > 0
        out_cols: list[Column] = []
        for pos, c in enumerate(res.table.columns):
            valid_g = jax.lax.psum(
                c.valid_mask().astype(jnp.int32), EXEC_AXIS) > 0
            if pos < nk:
                # key data is a trace-time constant, identical on every
                # device — only the validity needs combining
                out_cols.append(Column(c.dtype, c.data, valid_g,
                                       chars=c.chars))
                continue
            op = aggs[pos - nk][1]
            if op in ("sum", "count"):
                # absent slots hold the 0 neutral already
                data = jax.lax.psum(c.data, EXEC_AXIS)
            else:
                from spark_rapids_jni_tpu.ops.groupby import minmax_sentinel

                sentinel = minmax_sentinel(c.dtype, op)
                guarded = jnp.where(
                    c.valid_mask(), c.data,
                    jnp.asarray(sentinel, c.data.dtype))
                data = (jax.lax.pmin(guarded, EXEC_AXIS) if op == "min"
                        else jax.lax.pmax(guarded, EXEC_AXIS))
            out_cols.append(Column(c.dtype, data, valid_g))
        return Table(out_cols), present_g, miss_g

    if row_valid is None:
        row_valid = jax.device_put(
            jnp.ones((table.num_rows,), jnp.bool_),
            NamedSharding(mesh, P(EXEC_AXIS)))
    from spark_rapids_jni_tpu.runtime import dispatch

    out_tbl, present, miss = dispatch.sharded_call(
        "distributed_groupby_bounded",
        lambda: jax.shard_map(
            step,
            mesh=mesh,
            in_specs=(P(EXEC_AXIS), P(EXEC_AXIS)),
            out_specs=(P(), P(), P()),
        ),
        (table, row_valid),
        statics=(tuple(keys),
                 tuple((int(c), op) for c, op in aggs),
                 tuple((tuple(d.values), d.kind) for d in domains),
                 int(budget), _mesh_fingerprint(mesh)),
    )
    return DistributedBoundedGroupBy(out_tbl, present, miss)


def _shuffle_retry_capacity(table: Table, mesh: Mesh,
                            capacity: Optional[int]) -> int:
    """Capacity for the one-shot overflow retry: double the EFFECTIVE
    per-device slot count (the shuffle's derived default when the caller
    passed None — mirror of shuffle_by_partition's in-trace formula) and
    re-quantize through the dispatch bucket schedule so the retry shape
    still shares executables with other batches that land in its bucket."""
    import math

    from spark_rapids_jni_tpu.runtime import dispatch

    if capacity is None:
        D = int(mesh.shape[EXEC_AXIS])
        n_local = max(1, math.ceil(table.num_rows / D))
        capacity = dispatch.quantize_capacity(
            max(1, math.ceil(n_local / D) * 2))
    return dispatch.quantize_capacity(max(int(capacity), 1) * 2)


def _distributed_groupby(table, keys, mesh, capacity, local_groupby,
                         cache_key=None):
    """Shared shuffle-then-local-groupby scaffold: hash-exchange rows so
    each device owns whole key groups, run ``local_groupby(shuffled_table,
    keys)`` per device, and pack the sharded GroupByResult.

    ``cache_key`` is a hashable fingerprint of everything ``local_groupby``
    closes over (agg list, percentile qs, ...) — the dispatch executable
    cache keys on it, NOT on the closure's identity. ``None`` means the
    closure is opaque: fall back to an uncached shard_map call rather than
    risk serving a stale executable for different closure contents.

    Shuffle capacity overflow recovers HERE, instead of at every caller:
    ``overflowed`` is a device flag (the in-trace shuffle cannot grow its
    static send-buffer shape), so the host boundary after the call is the
    first place a bigger capacity can be chosen. Escalation is bounded
    geometric through the shared resilience policy — each step doubles
    and re-quantizes through the dispatch bucket schedule, and the final
    allowed attempt jumps to the quantized row count (a per-device
    capacity of n rows always fits, so a recoverable skew never exhausts
    the bound). Still overflowing there — or past
    ``resilience.max_attempts`` — raises a classified
    ``FatalExecutionError`` carrying rows/capacity context. With
    ``resilience.enabled=false`` the historical behavior runs verbatim:
    one retry at doubled quantized capacity, then the flag is returned
    set (fail loud at the caller)."""
    from spark_rapids_jni_tpu.runtime import faults, resilience

    def run(cap):
        def step(local: Table):
            sh = hash_shuffle(local, keys, EXEC_AXIS, capacity=cap)
            res = local_groupby(sh.table, keys)
            return (res.table, res.num_groups.reshape(1),
                    sh.overflowed.reshape(1),
                    jnp.asarray(res.sum_overflow).reshape(1))

        def build():
            return jax.shard_map(
                step,
                mesh=mesh,
                in_specs=(P(EXEC_AXIS),),
                out_specs=(P(EXEC_AXIS), P(EXEC_AXIS), P(EXEC_AXIS),
                           P(EXEC_AXIS)),
            )

        if cache_key is None:
            return build()(table)
        from spark_rapids_jni_tpu.runtime import dispatch

        return dispatch.sharded_call(
            "distributed_groupby", build, (table,),
            statics=(tuple(keys), cap, cache_key,
                     _mesh_fingerprint(mesh)),
        )

    pol = resilience.policy()
    if not pol.enabled:
        out_tbl, num_groups, overflowed, sum_overflow = run(capacity)
        if bool(np.asarray(overflowed).any()):
            retry_cap = _shuffle_retry_capacity(table, mesh, capacity)
            telemetry.record_fallback(
                "distributed_groupby",
                "shuffle capacity overflow: a device received more rows "
                "than its send-buffer slots; retrying once at doubled "
                "quantized capacity",
                rows=table.num_rows, retry_capacity=retry_cap)
            out_tbl, num_groups, overflowed, sum_overflow = run(retry_cap)
        return DistributedGroupBy(out_tbl, num_groups, overflowed,
                                  sum_overflow)

    from spark_rapids_jni_tpu.runtime import dispatch

    max_cap = dispatch.quantize_capacity(max(table.num_rows, 1))
    cap = capacity  # None on attempt 1: hash_shuffle derives it in-trace

    def _run(c):
        faults.fire("shuffle.transport", 0, rows=table.num_rows)
        return run(c)

    attempt = 1
    while True:
        out_tbl, num_groups, overflowed, sum_overflow = resilience.retrying(
            "distributed_groupby", lambda: _run(cap),
            seam="shuffle.transport", pol=pol, rows=table.num_rows)
        if not bool(np.asarray(overflowed).any()):
            if attempt > 1:
                telemetry.record_resilience(
                    "distributed_groupby", "recovered",
                    seam="shuffle.transport", attempt=attempt,
                    rung="grow_capacity", rows=table.num_rows)
            return DistributedGroupBy(out_tbl, num_groups, overflowed,
                                      sum_overflow)
        at_max = cap is not None and int(cap) >= max_cap
        if attempt >= pol.max_attempts or at_max:
            telemetry.record_resilience(
                "distributed_groupby", "fatal", seam="shuffle.transport",
                attempt=attempt, rung="grow_capacity", rows=table.num_rows)
            raise resilience.FatalExecutionError(
                "distributed_groupby: shuffle capacity escalation "
                "exhausted with the overflow flag still set",
                rows=table.num_rows,
                capacity=int(cap) if cap is not None else "derived",
                max_capacity=max_cap, attempts=attempt)
        # final allowed attempt jumps straight to the quantized row count
        # (always sufficient); earlier steps double-and-quantize
        if attempt + 1 >= pol.max_attempts:
            retry_cap = max_cap
        else:
            retry_cap = min(_shuffle_retry_capacity(table, mesh, cap),
                            max_cap)
        telemetry.record_fallback(
            "distributed_groupby",
            "shuffle capacity overflow: a device received more rows than "
            "its send-buffer slots; escalating quantized capacity",
            rows=table.num_rows, retry_capacity=retry_cap)
        telemetry.record_resilience(
            "distributed_groupby", "escalate", seam="shuffle.transport",
            attempt=attempt, rung="grow_capacity", rows=table.num_rows,
            capacity=retry_cap)
        cap = retry_cap
        attempt += 1


def distributed_groupby_percentile(
    table: Table,
    keys: Sequence[int],
    value_col: int,
    qs: Sequence[float],
    mesh: Mesh,
    capacity: Optional[int] = None,
) -> DistributedGroupBy:
    """Global exact percentiles: shuffle rows by key hash (whole groups
    co-locate), then one local sort-based groupby_percentile per device —
    order statistics are group-local, so co-location makes the per-device
    answers globally exact (no sketch merging, unlike t-digest designs)."""
    from spark_rapids_jni_tpu.ops.groupby import groupby_percentile

    qs = [float(q) for q in qs]
    return _distributed_groupby(
        table, list(keys), mesh, capacity,
        lambda sh_tbl, ks: groupby_percentile(sh_tbl, ks, value_col, qs),
        cache_key=("percentile", int(value_col), tuple(qs)))


@jax.jit
def _compact_to_front(table: Table, counts: jnp.ndarray) -> Table:
    """Device-side compaction of a per-device-padded sharded result: gather
    every device's first counts[i] rows into a contiguous prefix. One
    searchsorted-driven gather (the framework's scatter-free routing idiom);
    XLA/GSPMD inserts the cross-shard collective. Rows past the real total
    are clamped repeats of row 0 — the caller slices them off."""
    d = counts.shape[0]
    n = table.num_rows
    per_dev = n // d
    off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts).astype(jnp.int32)]
    )  # (d+1,) exclusive prefix
    j = jnp.arange(n, dtype=jnp.int32)
    dev = jnp.clip(
        jnp.searchsorted(off[1:], j, side="right").astype(jnp.int32), 0, d - 1
    )
    src = dev * per_dev + (j - off[dev])
    src = jnp.where(j < off[-1], src, 0)
    from spark_rapids_jni_tpu.ops.sort import gather

    return gather(table, src)


def collect(table: Table, num_rows_per_device: jnp.ndarray, mesh: Mesh) -> Table:
    """Driver-side collect of a sharded, per-device-padded result into one
    compact host table. The compaction runs on-device in one jitted gather
    (not a per-device host loop), so exactly ``total`` rows cross to the
    host — one bounded transfer per buffer, O(result), not O(padded)."""
    counts = jnp.asarray(num_rows_per_device).reshape(-1).astype(jnp.int32)
    d = int(np.prod(list(mesh.shape.values())))
    if counts.shape[0] != d:
        raise ValueError(
            f"collect: {counts.shape[0]} per-device counts for a "
            f"{d}-device mesh"
        )
    compacted = _compact_to_front(table, counts)
    total = int(np.asarray(counts).astype(np.int64).sum())
    out = []
    for c in compacted.columns:
        valid = np.asarray(c.valid_mask()[:total])
        if c.is_padded_string:
            # back to the Arrow at-rest layout on host: lengths ride the
            # data buffer; flatten the fetched (total, W) char matrix
            lens = np.asarray(c.data[:total])
            mat = np.asarray(c.chars[:total])
            blob = (
                mat.reshape(-1)[
                    (np.arange(mat.shape[1])[None, :] < lens[:, None]).reshape(-1)
                ]
                if lens.size else np.zeros((0,), np.uint8)
            )
            nbytes = int(lens.astype(np.int64).sum())
            if nbytes > np.iinfo(np.int32).max:
                raise ValueError(
                    f"collected string column holds {nbytes} bytes, over the "
                    "int32 Arrow offset bound (2^31-1); collect in batches"
                )
            offsets = np.zeros(lens.size + 1, dtype=np.int32)
            np.cumsum(lens, out=offsets[1:])
            out.append(Column(
                c.dtype, jnp.asarray(offsets), jnp.asarray(valid),
                chars=jnp.asarray(blob),
            ))
            continue
        out.append(Column(c.dtype, jnp.asarray(c.data[:total]), jnp.asarray(valid)))
    return Table(out)


class DistributedWindow(NamedTuple):
    table: Table             # shuffled input rows (padded), sharded
    results: Table           # one column per requested window spec,
                             # aligned row-for-row with ``table``
    row_valid: jnp.ndarray   # bool[D*capacity]: slot holds a real row
    overflowed: jnp.ndarray  # bool[D] shuffle capacity overflow


@func_range("distributed_window")
def distributed_window(
    table: Table,
    partition_by: Sequence[int],
    order_by: Sequence[int],
    specs: Sequence,
    mesh: Mesh,
    row_valid: jnp.ndarray,
    capacity: Optional[int] = None,
) -> DistributedWindow:
    """Global window functions: shuffle rows by partition-key hash so each
    device owns whole partitions, then evaluate partition-local windows —
    window functions are partition-local once partitions are co-located,
    exactly the distributed groupby argument.

    ``specs``: window requests as static tuples —
    ``("row_number",)``, ``("rank",)``, ``("dense_rank",)``,
    ``("lag", col_idx, k)``, ``("lead", col_idx, k)``,
    ``("running_sum", col_idx)``, ``("running_min", col_idx)``,
    ``("running_max", col_idx)``, ``("ntile", buckets)``,
    ``("percent_rank",)``, ``("cume_dist",)``,
    ``("first_value", col_idx)``, ``("last_value", col_idx)``,
    ``("nth_value", col_idx, k)``, and
    ``("rolling_<sum|count|mean|min|max>", col_idx, preceding,
    following)``, and ``("rolling_<var|std>", col_idx, preceding,
    following[, ddof])``, and value-based RANGE frames as
    ``("rolling_<sum|count|mean|min|max>_range", col_idx, preceding,
    following)``. Results come back sharded, aligned to
    the shuffled rows; filter output by the returned ``row_valid``.

    ``row_valid`` is REQUIRED (use ``shard_table(...,
    return_row_valid=True)``): unlike aggregates, window functions give
    null-key rows real results, so a padding row mistaken for a real row
    would pollute the genuine null-key partition — an all-ones default
    would hide exactly that hazard. Phantom shuffle slots are kept out of
    every real partition by an occupancy pseudo-key."""
    from spark_rapids_jni_tpu.ops.window import Window

    pkeys = list(partition_by)
    okeys = list(order_by)
    specs = [tuple(s) for s in specs]

    def step(local: Table, lrv):
        sh = hash_shuffle(local, pkeys, EXEC_AXIS, capacity=capacity,
                          row_valid=lrv)
        from spark_rapids_jni_tpu import types as t_

        # phantom slots must not join the (real) null-key partition:
        # a leading occupancy pseudo-key banishes them to their own
        # trailing partition
        occ = Column(t_.INT8,
                     jnp.where(sh.row_valid, jnp.int8(0), jnp.int8(1)),
                     None)
        wtbl = Table([occ] + list(sh.table.columns))
        w = Window(wtbl, partition_by=[0] + [k + 1 for k in pkeys],
                   order_by=[k + 1 for k in okeys])
        out_cols = []
        for spec in specs:
            kind = spec[0]
            if kind in ("row_number", "rank", "dense_rank",
                        "percent_rank", "cume_dist"):
                out_cols.append(getattr(w, kind)())
            elif kind in ("lag", "lead"):
                out_cols.append(getattr(w, kind)(spec[1] + 1, spec[2]))
            elif kind in ("running_sum", "running_min", "running_max",
                          "first_value", "last_value"):
                out_cols.append(getattr(w, kind)(spec[1] + 1))
            elif kind == "nth_value":
                out_cols.append(w.nth_value(spec[1] + 1, spec[2]))
            elif kind == "ntile":
                out_cols.append(w.ntile(spec[1]))
            elif kind in ("rolling_sum", "rolling_count", "rolling_mean",
                          "rolling_min", "rolling_max"):
                out_cols.append(getattr(w, kind)(
                    spec[1] + 1, spec[2], spec[3]))
            elif kind in ("rolling_sum_range", "rolling_count_range",
                          "rolling_mean_range", "rolling_min_range",
                          "rolling_max_range"):
                out_cols.append(getattr(w, kind[:-6])(
                    spec[1] + 1, spec[2], spec[3], frame="range"))
            elif kind in ("rolling_var", "rolling_std"):
                # optional trailing ddof (default 1 = sample)
                out_cols.append(getattr(w, kind)(
                    spec[1] + 1, spec[2], spec[3],
                    spec[4] if len(spec) > 4 else 1))
            else:
                raise ValueError(f"unknown window spec {spec!r}")
        return (sh.table, Table(out_cols), sh.row_valid,
                sh.overflowed.reshape(1))

    from spark_rapids_jni_tpu.runtime import dispatch

    out_tbl, results, rv, ovf = dispatch.sharded_call(
        "distributed_window",
        lambda: jax.shard_map(
            step,
            mesh=mesh,
            in_specs=(P(EXEC_AXIS), P(EXEC_AXIS)),
            out_specs=(P(EXEC_AXIS),) * 4,
        ),
        (table, row_valid),
        statics=(tuple(pkeys), tuple(okeys), tuple(specs), capacity,
                 _mesh_fingerprint(mesh)),
    )
    return DistributedWindow(out_tbl, results, rv, ovf)


class DistributedJoin(NamedTuple):
    table: Table             # per-device joined rows (padded), sharded
    total: jnp.ndarray       # int64[D] true match count per device
    overflowed: jnp.ndarray  # bool[D] shuffle capacity overflow per device


@func_range("distributed_join")
def distributed_join(
    left: Table,
    right: Table,
    left_on: int | Sequence[int],
    right_on: int | Sequence[int],
    mesh: Mesh,
    out_size_per_device: int,
    how: str = "inner",
    left_capacity: Optional[int] = None,
    right_capacity: Optional[int] = None,
    left_row_valid: Optional[jnp.ndarray] = None,
    right_row_valid: Optional[jnp.ndarray] = None,
) -> DistributedJoin:
    """Repartitioned equi-join — the RapidsShuffleManager join pattern: both
    sides exchange rows by key hash over ICI, after which equal keys live on
    the same device and a device-local sort-merge join finishes the work.

    Both inputs must already be sharded row-wise over ``mesh``. Identical
    routing for both tables is guaranteed because partition_hash depends
    only on the key value and its storage type (join() rejects mismatched
    key storage types). Pass the ``row_valid`` masks from
    ``shard_table(..., return_row_valid=True)`` so padding rows are dropped
    before the exchange — under a left join a padding row would otherwise
    be indistinguishable from a genuine NULL-key row and emit output.
    """
    from spark_rapids_jni_tpu.ops.join import apply_join_maps, join

    left_keys = [left_on] if isinstance(left_on, int) else list(left_on)
    right_keys = [right_on] if isinstance(right_on, int) else list(right_on)

    def step(l: Table, r: Table, lrv, rrv):
        # identical routing for both sides: partition_hash depends only on
        # key content (string hashing is over actual bytes, padding-blind)
        ls = hash_shuffle(l, left_keys, EXEC_AXIS, capacity=left_capacity,
                          row_valid=lrv)
        rs = hash_shuffle(r, right_keys, EXEC_AXIS, capacity=right_capacity,
                          row_valid=rrv)
        # phantom (unoccupied) shuffle slots must not emit outer-join rows
        # on either side
        maps = join(ls.table, rs.table, left_keys, right_keys,
                    out_size_per_device, how=how,
                    left_row_valid=ls.row_valid,
                    right_row_valid=rs.row_valid)
        joined = apply_join_maps(ls.table, rs.table, maps)
        overflow = ls.overflowed | rs.overflowed
        return joined, maps.total.reshape(1), overflow.reshape(1)

    if left_row_valid is None:
        left_row_valid = jnp.ones((left.num_rows,), jnp.bool_)
    if right_row_valid is None:
        right_row_valid = jnp.ones((right.num_rows,), jnp.bool_)
    from spark_rapids_jni_tpu.runtime import dispatch, faults, resilience

    def _exchange():
        # the exchange is the ICI-transport boundary: a transient
        # transport fault here replays the whole (idempotent) step
        faults.fire("shuffle.transport", 0,
                    rows=left.num_rows + right.num_rows)
        return dispatch.sharded_call(
            "distributed_join",
            lambda: jax.shard_map(
                step,
                mesh=mesh,
                in_specs=(P(EXEC_AXIS), P(EXEC_AXIS), P(EXEC_AXIS),
                          P(EXEC_AXIS)),
                out_specs=(P(EXEC_AXIS), P(EXEC_AXIS), P(EXEC_AXIS)),
            ),
            (left, right, left_row_valid, right_row_valid),
            statics=(tuple(left_keys), tuple(right_keys),
                     int(out_size_per_device), how, left_capacity,
                     right_capacity, _mesh_fingerprint(mesh)),
        )

    if resilience.enabled():
        out, total, overflowed = resilience.retrying(
            "distributed_join", _exchange, seam="shuffle.transport",
            rows=left.num_rows + right.num_rows)
    else:
        out, total, overflowed = _exchange()
    return DistributedJoin(out, total, overflowed)


class DistributedCollectList(NamedTuple):
    table: Table             # keys then one LIST column, host-assembled
    overflowed: jnp.ndarray  # bool[D] shuffle capacity overflow


@func_range("distributed_groupby_collect")
def distributed_groupby_collect(
    table: Table,
    keys: Sequence[int],
    value_col: int,
    mesh: Mesh,
    capacity: int,
    distinct: bool = False,
) -> DistributedCollectList:
    """Global collect_list/collect_set: hash-shuffle rows so whole key
    groups co-locate (the shared ``_distributed_groupby`` scaffold), run
    one local ``groupby_collect`` per device, then assemble the
    per-device LIST results on the driver (trim + LIST-aware
    concatenate — the nested-offset analogue of ``collect``). Row order
    across devices is unspecified (sort on the keys afterwards if
    needed).

    Shard padding rows follow the module's phantom-row posture: they
    surface as one all-null-key group (with an empty list) that callers
    discard like local groupby padding."""
    from spark_rapids_jni_tpu.ops.lists import groupby_collect
    from spark_rapids_jni_tpu.ops.groupby import GroupByResult
    from spark_rapids_jni_tpu.ops.table_ops import concatenate, trim_table

    ks = list(keys)

    def local_collect(sh_tbl: Table, kss):
        res = groupby_collect(sh_tbl, kss, value_col, distinct=distinct)
        # adapt to the scaffold's GroupByResult packing (the default
        # overflow flags are static False — collect has no max_groups)
        return GroupByResult(res.table, res.num_groups)

    dist = _distributed_groupby(
        table, ks, mesh, capacity, local_collect,
        cache_key=("collect", int(value_col), bool(distinct)))
    out_tbl, ngs, ovf = dist.table, dist.num_groups, dist.overflowed
    d = int(np.prod(list(mesh.shape.values())))
    counts = np.asarray(ngs).reshape(-1)

    def _host_chunks(c: Column) -> list[Column]:
        """ONE device->host fetch per buffer, then numpy slicing — no
        per-device sync loop (each leaf is evenly divided across the
        mesh by shard_map)."""
        bufs = {}
        for name in ("data", "validity", "chars"):
            arr = getattr(c, name)
            bufs[name] = None if arr is None else np.asarray(arr)
        kid_chunks = (None if c.children is None
                      else [_host_chunks(k) for k in c.children])
        out = []
        for di in range(d):
            def seg(arr):
                if arr is None:
                    return None
                chunk = arr.shape[0] // d
                return jnp.asarray(arr[di * chunk:(di + 1) * chunk])

            kids = (None if kid_chunks is None
                    else [kc[di] for kc in kid_chunks])
            out.append(Column(c.dtype, seg(bufs["data"]),
                              seg(bufs["validity"]),
                              chars=seg(bufs["chars"]), children=kids))
        return out

    col_chunks = [_host_chunks(c) for c in out_tbl.columns]
    per_dev = []
    for di in range(d):
        tbl_d = Table([cc[di] for cc in col_chunks])
        per_dev.append(trim_table(tbl_d, int(counts[di])))
    return DistributedCollectList(concatenate(per_dev), ovf)
