"""Distributed ORDER BY — range-partitioned global sort over the executor
mesh, the Spark `RangePartitioner` + per-partition sort pattern (the engine
half belongs to this layer; cuDF provides the per-partition sort the same
way, capability surface SURVEY.md section 2.2).

TPU-first shape: splitters are planned on HOST from the key sample (range
boundaries are planning metadata, like shuffle capacities), then the mesh
program is fully static — every row's destination is one ``searchsorted``
over the splitter vector, the exchange is the same all_to_all transport as
the hash shuffle (``shuffle_by_partition``), and each device finishes with
a local ``sort_table``. Concatenating device partitions in mesh order IS
the global order; ties on the primary key stay co-located (searchsorted
buckets equal values together), so secondary keys order exactly.

Primary keys may be fixed-width or STRING (strings bucket on an 8-byte
big-endian prefix; equal prefixes co-locate so exactness holds).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.sort import _as_unsigned_key, sort_table
from spark_rapids_jni_tpu.parallel.mesh import EXEC_AXIS
from spark_rapids_jni_tpu.parallel.shuffle import shuffle_by_partition
from spark_rapids_jni_tpu.utils.tracing import func_range


def _encode_primary(col: Column) -> jnp.ndarray:
    """Order-preserving unsigned encoding of the primary sort key; nulls
    encode below every valid value (nulls-first order).

    Strings bucket on their first 8 bytes (big-endian packed): a prefix is
    the major component of memcmp order, and equal prefixes collapse to
    one bucket, so ties stay co-located and the local sort's full-width
    keys keep global order exact."""
    if col.dtype.is_string:
        from spark_rapids_jni_tpu.ops.strings import pad_strings

        p = pad_strings(col)
        mat, lengths = p.chars, p.data
        width = int(mat.shape[1])
        col = p  # valid_mask read from the padded layout below
        enc = jnp.zeros((p.size,), jnp.uint64)
        for b in range(min(8, width)):
            byte = jnp.where(b < lengths, mat[:, b], jnp.uint8(0))
            enc = enc | (byte.astype(jnp.uint64) << jnp.uint64(8 * (7 - b)))
    elif col.dtype.is_decimal128:
        # bucket on the sign-flipped high limb: the major component of
        # 128-bit order; equal-hi values collapse to one bucket, and the
        # local sort's full limb-pair keys keep global order exact (the
        # same tie-collapse argument as the string 8-byte prefix)
        enc = col.data[:, 1].astype(jnp.uint64) ^ jnp.uint64(1 << 63)
    elif col.dtype.storage_dtype == np.float64:
        # route on the float32 truncation: order-preserving bucketing only
        # (exact order is restored by the local sort's full-precision keys)
        enc32 = _as_unsigned_key(
            col.data.astype(jnp.float32), _F32
        ).astype(jnp.uint64)
        enc = enc32 << jnp.uint64(32)
    else:
        enc = _as_unsigned_key(col.data, col.dtype).astype(jnp.uint64)
        bits = col.dtype.storage_dtype.itemsize * 8
        if bits < 64:
            enc = enc << jnp.uint64(64 - bits)
    # shift into [1, 2^64): 0 is reserved for nulls
    enc = jnp.maximum(enc >> jnp.uint64(1), jnp.uint64(1))
    return jnp.where(col.valid_mask(), enc, jnp.uint64(0))


class _F32:  # minimal DType stand-in for the float32 encoding path
    storage_dtype = np.dtype(np.float32)


def plan_splitters(table: Table, key: int, num_partitions: int,
                   sample_size: int = 65536) -> np.ndarray:
    """Host-side range planning: quantiles of a BOUNDED strided sample of
    the encoded primary key -> ``num_partitions - 1`` ascending splitters
    (uint64). Sampling caps the device->host transfer the way Spark's
    RangePartitioner bounds its per-partition sample — quantiles of a 64k
    sample match full-column quantiles to well under one partition width."""
    col = table.column(key)
    n = col.size
    if n == 0:
        return np.zeros(max(num_partitions - 1, 0), dtype=np.uint64)
    if n > sample_size:
        idx = jnp.asarray(
            np.linspace(0, n - 1, sample_size).astype(np.int64)
        )
        if col.dtype.is_string:
            from spark_rapids_jni_tpu.ops.strings import gather_strings

            col = gather_strings(col, idx)
        else:
            col = Column(col.dtype, col.data[idx],
                         None if col.validity is None else col.validity[idx])
    enc = np.asarray(_encode_primary(col))
    qs = np.linspace(0, 1, num_partitions + 1)[1:-1]
    return np.quantile(enc, qs, method="nearest").astype(np.uint64)


class DistributedSort(NamedTuple):
    table: Table             # per-device sorted partitions, mesh order
    num_rows: jnp.ndarray    # int64[D] real rows per device
    overflowed: jnp.ndarray  # bool[D] range-shuffle capacity overflow


@func_range("distributed_sort")
def distributed_sort(
    table: Table,
    keys: Sequence[int],
    mesh,
    ascending: Sequence[bool] | None = None,
    capacity: Optional[int] = None,
    row_valid: Optional[jnp.ndarray] = None,
    splitters: Optional[np.ndarray] = None,
) -> DistributedSort:
    """Global multi-key sort: range-shuffle by the primary key, then local
    sort per device. ``table`` must already be sharded over ``mesh``
    (shard_table); pass its ``row_valid`` so padding rows drop before the
    exchange. Device d's partition holds the d-th ascending key range, so
    ``collect(...)`` concatenation is globally ordered.

    ``ascending[0]`` False is handled by reversing the device ranges at
    collect time being insufficient — this round requires ascending primary
    order (descending composes by reversing the collected result when all
    keys descend)."""
    keys = list(keys)
    if ascending is not None and not all(ascending):
        raise NotImplementedError(
            "distributed_sort is ascending-only this round; reverse the "
            "collected result for all-descending orders"
        )
    d = mesh.shape[EXEC_AXIS]
    if splitters is None:
        splitters = plan_splitters(table, keys[0], d)
    spl = jnp.asarray(np.asarray(splitters, dtype=np.uint64))
    if row_valid is None:
        row_valid = jnp.ones((table.num_rows,), jnp.bool_)

    def step(local: Table, rv):
        enc = _encode_primary(local.column(keys[0]))
        part = jnp.searchsorted(spl, enc, side="right").astype(jnp.int32)
        sh = shuffle_by_partition(local, part, EXEC_AXIS, capacity=capacity,
                                  row_valid=rv)
        # local sort with the occupancy mask as the MOST significant key
        # (descending: real rows first) so phantom slots can never
        # interleave with real null-key rows; the user keys keep Spark's
        # default nulls-first order among real rows
        from spark_rapids_jni_tpu import types as t

        mask_col = Column(t.UINT8, sh.row_valid.astype(jnp.uint8))
        aug = Table([mask_col] + list(sh.table.columns))
        ordered = sort_table(
            aug, [0] + [k + 1 for k in keys],
            ascending=[False] + [True] * len(keys),
        )
        ordered = Table(ordered.columns[1:])
        n_real = jnp.sum(sh.row_valid.astype(jnp.int64))
        return ordered, n_real.reshape(1), sh.overflowed.reshape(1)

    out, n_real, ovf = jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(EXEC_AXIS), P(EXEC_AXIS)),
        out_specs=(P(EXEC_AXIS), P(EXEC_AXIS), P(EXEC_AXIS)),
    )(table, row_valid)
    return DistributedSort(out, n_real, ovf)
