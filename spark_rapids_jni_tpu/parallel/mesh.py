"""Device-mesh construction for the executor model.

The reference's inter-device story is Spark data parallelism: one executor
task per partition, each issuing independent device work (SURVEY.md
section 2.3, PER_THREAD_DEFAULT_STREAM at reference pom.xml:80). On TPU the
executors become positions along one mesh axis; partition exchange between
them is an XLA collective over ICI instead of UCX peer-to-peer blocks.

One axis is enough for the shuffle transport (all-to-all is a full
exchange); wider meshes (e.g. a second axis for within-executor model/row
sharding of a single giant partition) stack on top by reshaping the same
device list.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

# Axis name for the executor/data-parallel dimension of every mesh this
# package builds. Collectives in the shuffle bind to this name.
EXEC_AXIS = "exec"


def executor_mesh(
    num_executors: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A 1-D mesh of ``num_executors`` devices along ``EXEC_AXIS``.

    Defaults to every visible device — one executor per chip, the same
    1 task : 1 device contract Spark's plugin enforces on GPUs.
    """
    if devices is None:
        devices = jax.devices()
    if num_executors is None:
        num_executors = len(devices)
    if num_executors > len(devices):
        raise ValueError(
            f"requested {num_executors} executors but only "
            f"{len(devices)} devices are visible"
        )
    import numpy as np

    return Mesh(np.asarray(devices[:num_executors]), (EXEC_AXIS,))
