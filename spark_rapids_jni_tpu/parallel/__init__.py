"""Multi-chip parallelism: device mesh, ICI all-to-all shuffle transport,
and distributed operators.

This is the TPU-native replacement for the RapidsShuffleManager's UCX/NCCL
block transport (BASELINE.json north_star; absent from the reference repo
itself, SURVEY.md section 2.3): Spark executors map to mesh devices, a
repartition-by-key-hash exchange rides XLA's ``all_to_all`` collective over
ICI, and post-shuffle operators (groupby merge, join) run on the disjoint
key ranges each chip owns afterward.
"""

from spark_rapids_jni_tpu.parallel.mesh import executor_mesh, EXEC_AXIS
from spark_rapids_jni_tpu.parallel.shuffle import (
    ShuffleResult,
    hash_shuffle,
    shuffle_by_partition,
)
from spark_rapids_jni_tpu.parallel.distributed import (
    distributed_groupby_aggregate,
    distributed_join,
    shard_table,
)
from spark_rapids_jni_tpu.parallel.sort import distributed_sort
from spark_rapids_jni_tpu.parallel.wire import BitPack, shuffle_wire_bytes

__all__ = [
    "BitPack",
    "EXEC_AXIS",
    "ShuffleResult",
    "distributed_groupby_aggregate",
    "distributed_join",
    "distributed_sort",
    "executor_mesh",
    "hash_shuffle",
    "shard_table",
    "shuffle_by_partition",
    "shuffle_wire_bytes",
]
