"""Multi-chip parallelism: device mesh, ICI all-to-all shuffle transport,
and distributed operators.

This is the TPU-native replacement for the RapidsShuffleManager's UCX/NCCL
block transport (BASELINE.json north_star; absent from the reference repo
itself, SURVEY.md section 2.3): Spark executors map to mesh devices, a
repartition-by-key-hash exchange rides XLA's ``all_to_all`` collective over
ICI, and post-shuffle operators (groupby merge, join) run on the disjoint
key ranges each chip owns afterward.
"""

from spark_rapids_jni_tpu.parallel.mesh import executor_mesh, EXEC_AXIS
from spark_rapids_jni_tpu.parallel.shuffle import hash_shuffle, ShuffleResult
from spark_rapids_jni_tpu.parallel.distributed import (
    distributed_groupby_aggregate,
    shard_table,
)

__all__ = [
    "EXEC_AXIS",
    "ShuffleResult",
    "distributed_groupby_aggregate",
    "executor_mesh",
    "hash_shuffle",
    "shard_table",
]
