"""Thin logging facade — the slf4j-api equivalent (the reference's only
compile-scope Java dependency, pom.xml:143-153; RMM log level via env,
pom.xml:82). Level comes from the ``log.level`` option
(env SPARK_RAPIDS_TPU_LOG_LEVEL)."""

from __future__ import annotations

import logging

from spark_rapids_jni_tpu.utils.config import get_option

_configured = False


def get_logger(name: str = "spark_rapids_jni_tpu") -> logging.Logger:
    global _configured
    logger = logging.getLogger(name)
    if not _configured:
        level = getattr(logging, str(get_option("log.level")).upper(), logging.WARNING)
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
        root = logging.getLogger("spark_rapids_jni_tpu")
        root.addHandler(handler)
        root.setLevel(level)
        root.propagate = False
        _configured = True
    return logger
