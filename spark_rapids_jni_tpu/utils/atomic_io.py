"""Crash-safe small-state persistence: tmp-file + ``os.replace`` + fsync.

Warm-start state (the serving runtime's learned admission estimates, the
dispatch persistent-cache index) is tiny but load-bearing: a crash mid-write
must never leave a half-file that poisons the next process. The write
protocol here is the classic one — write the FULL payload to a same-directory
temp file, fsync it, atomically rename over the target, then fsync the
directory so the rename itself is durable. Readers treat any unparsable file
as ABSENT: corruption is discarded (with a telemetry event recorded by the
caller), never raised into query serving.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Optional, Tuple

from spark_rapids_jni_tpu.utils.log import get_logger

__all__ = ["atomic_write_json", "load_json"]

_log = get_logger(__name__)


def atomic_write_json(path: str, obj: Any) -> None:
    """Durably replace ``path`` with ``obj`` serialized as JSON.

    The temp file lives in the TARGET directory (``os.replace`` is only
    atomic within one filesystem); both the file and its directory are
    fsynced, so after return either the old complete file or the new
    complete file is on disk — never a truncated hybrid.
    """
    directory = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=directory, prefix=os.path.basename(path) + ".", suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(obj, f, sort_keys=True, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # fsync the directory so the rename survives power loss; some
        # filesystems refuse O_RDONLY dir fds — losing THIS sync only
        # risks re-reading the previous complete file, never corruption
        try:
            dfd = os.open(directory, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_json(path: str) -> Tuple[Optional[Any], Optional[str]]:
    """Read a JSON state file written by :func:`atomic_write_json`.

    Returns ``(obj, None)`` on success, ``(None, None)`` when the file
    does not exist, and ``(None, reason)`` when it exists but cannot be
    parsed — the caller discards it (and records the telemetry event);
    a corrupt warm-start file must cost a cold start, not a crash.
    """
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f), None
    except FileNotFoundError:
        return None, None
    except (OSError, ValueError, UnicodeDecodeError) as exc:
        reason = f"{type(exc).__name__}: {exc}"
        _log.warning("discarding corrupt state file %s (%s)", path, reason)
        return None, reason
