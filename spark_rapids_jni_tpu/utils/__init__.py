from spark_rapids_jni_tpu.utils.tracing import func_range, trace_range
from spark_rapids_jni_tpu.utils.config import get_option, set_option

__all__ = ["func_range", "trace_range", "get_option", "set_option"]
