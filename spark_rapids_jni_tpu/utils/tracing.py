"""Profiler trace annotations — the NVTX-range equivalent.

The reference opens an NVTX range (``CUDF_FUNC_RANGE()``) at the top of every
nontrivial native function (e.g. NativeParquetJni.cpp:191,400,455,508) behind
the ``ai.rapids.cudf.nvtx.enabled`` toggle (pom.xml:85,437). Here the same
granularity is provided with ``jax.profiler.TraceAnnotation``, which lands in
XLA/Perfetto traces captured via ``jax.profiler.trace``. Disabled by default,
toggled by the ``tracing.enabled`` option (env
``SPARK_RAPIDS_TPU_TRACING_ENABLED=1``).

This is the one seam instrumented ops share: the profiler annotation, the
telemetry dispatch record and the query span tree all hang off it. When a
query span is open on this thread (telemetry/spans.py), the range attaches a
child span — so every ``trace_range``-wrapped stage lands in the served
query's causal tree without its own instrumentation. ``record=True``
additionally times the range and records a ``dispatch`` telemetry event
carrying ``wall_ms``; a body that raises still records, with
``status="error"`` and the exception class, so failed dispatches are visible
in the per-op report instead of silently dropping their timing.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Callable, TypeVar

from spark_rapids_jni_tpu import telemetry
from spark_rapids_jni_tpu.telemetry import spans
from spark_rapids_jni_tpu.utils.config import get_option

F = TypeVar("F", bound=Callable)


@contextlib.contextmanager
def trace_range(name: str, record: bool = False):
    """Context manager opening a named profiler range when tracing is on.

    With ``record=True`` (and telemetry enabled), also times the body and
    records a ``dispatch`` telemetry event carrying ``wall_ms`` — with
    ``status="error"`` / ``error=<exception class>`` when the body raises.
    With telemetry enabled and a query span open on this thread, the range
    additionally attaches a child span to the query's tree.
    """
    if record:
        record = telemetry.enabled()
    t0 = time.perf_counter() if record else 0.0
    try:
        with spans.child(name):
            if get_option("tracing.enabled"):
                import jax.profiler

                with jax.profiler.TraceAnnotation(name):
                    yield
            else:
                yield
    except BaseException as exc:
        if record:
            telemetry.record_dispatch(
                name,
                wall_ms=(time.perf_counter() - t0) * 1e3,
                status="error",
                error=type(exc).__name__,
            )
        raise
    if record:
        telemetry.record_dispatch(
            name, wall_ms=(time.perf_counter() - t0) * 1e3
        )


def func_range(name: str, record: bool = False) -> Callable[[F], F]:
    """Decorator form — CUDF_FUNC_RANGE() parity."""

    def deco(fn: F) -> F:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with trace_range(name, record=record):
                return fn(*args, **kwargs)

        return wrapper  # type: ignore[return-value]

    return deco
