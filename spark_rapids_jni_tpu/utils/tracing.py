"""Profiler trace annotations — the NVTX-range equivalent.

The reference opens an NVTX range (``CUDF_FUNC_RANGE()``) at the top of every
nontrivial native function (e.g. NativeParquetJni.cpp:191,400,455,508) behind
the ``ai.rapids.cudf.nvtx.enabled`` toggle (pom.xml:85,437). Here the same
granularity is provided with ``jax.profiler.TraceAnnotation``, which lands in
XLA/Perfetto traces captured via ``jax.profiler.trace``. Disabled by default,
toggled by the ``tracing.enabled`` option (env
``SPARK_RAPIDS_TPU_TRACING_ENABLED=1``).
"""

from __future__ import annotations

import contextlib
import functools
from typing import Callable, TypeVar

from spark_rapids_jni_tpu.utils.config import get_option

F = TypeVar("F", bound=Callable)


@contextlib.contextmanager
def trace_range(name: str):
    """Context manager opening a named profiler range when tracing is on."""
    if not get_option("tracing.enabled"):
        yield
        return
    import jax.profiler

    with jax.profiler.TraceAnnotation(name):
        yield


def func_range(name: str) -> Callable[[F], F]:
    """Decorator form — CUDF_FUNC_RANGE() parity."""

    def deco(fn: F) -> F:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with trace_range(name):
                return fn(*args, **kwargs)

        return wrapper  # type: ignore[return-value]

    return deco
