"""Profiler trace annotations — the NVTX-range equivalent.

The reference opens an NVTX range (``CUDF_FUNC_RANGE()``) at the top of every
nontrivial native function (e.g. NativeParquetJni.cpp:191,400,455,508) behind
the ``ai.rapids.cudf.nvtx.enabled`` toggle (pom.xml:85,437). Here the same
granularity is provided with ``jax.profiler.TraceAnnotation``, which lands in
XLA/Perfetto traces captured via ``jax.profiler.trace``. Disabled by default,
toggled by the ``tracing.enabled`` option (env
``SPARK_RAPIDS_TPU_TRACING_ENABLED=1``).

``record=True`` additionally times the range and records a telemetry dispatch
event (telemetry/events.py) when ``telemetry.enabled`` is on — profiler
annotation and execution accounting share one seam, so instrumented ops get
both for free. Recording happens only on successful exit: a range that raised
did not dispatch.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Callable, TypeVar

from spark_rapids_jni_tpu.utils.config import get_option

F = TypeVar("F", bound=Callable)


@contextlib.contextmanager
def trace_range(name: str, record: bool = False):
    """Context manager opening a named profiler range when tracing is on.

    With ``record=True`` (and telemetry enabled), also times the body and
    records a ``dispatch`` telemetry event carrying ``wall_ms``.
    """
    if record:
        from spark_rapids_jni_tpu import telemetry

        record = telemetry.enabled()
    t0 = time.perf_counter() if record else 0.0
    if get_option("tracing.enabled"):
        import jax.profiler

        with jax.profiler.TraceAnnotation(name):
            yield
    else:
        yield
    if record:
        telemetry.record_dispatch(
            name, wall_ms=(time.perf_counter() - t0) * 1e3
        )


def func_range(name: str, record: bool = False) -> Callable[[F], F]:
    """Decorator form — CUDF_FUNC_RANGE() parity."""

    def deco(fn: F) -> F:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with trace_range(name, record=record):
                return fn(*args, **kwargs)

        return wrapper  # type: ignore[return-value]

    return deco
