"""Platform selection helpers for the axon-tunnelled TPU environment.

The axon plugin pins JAX's platform list at import time, so ``JAX_PLATFORMS``
env vars set after process start do NOT switch it off; the only reliable
switch is ``jax.config.update("jax_platforms", "cpu")`` executed before any
backend initialization (first ``jax.devices()`` / ``device_put`` / ``jit``).
This module is the single home of that workaround (used by tests/conftest.py,
__graft_entry__.dryrun_multichip, and bench.py).
"""

from __future__ import annotations

import os


def force_cpu_platform(n_virtual_devices: int | None = None) -> None:
    """Pin this process to the CPU backend, optionally with a virtual pool.

    Must be called before jax initializes any backend; the pin is process-
    wide and sticky (backend init is one-shot in jax), so callers that need
    the real chip afterwards must use a fresh process.
    """
    if n_virtual_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_virtual_devices}"
            ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
