"""Runtime configuration.

The reference configures at three tiers — Maven -D properties -> CMake cache
vars -> Java system properties (SURVEY.md section 5, "Config / flag system");
at runtime only system properties matter (e.g. ``ai.rapids.cudf.nvtx.enabled``,
reference pom.xml:85,437). The TPU equivalent: env vars
(``SPARK_RAPIDS_TPU_<OPTION>``) overridden by programmatic set_option, with
documented defaults. No config files.
"""

from __future__ import annotations

import os
from typing import Any

_ENV_PREFIX = "SPARK_RAPIDS_TPU_"

# option name -> (default, parser)
_OPTIONS: dict[str, tuple[Any, type]] = {
    # NVTX-equivalent trace annotations (ai.rapids.cudf.nvtx.enabled parity;
    # default false like pom.xml:85).
    "tracing.enabled": (False, bool),
    # Lift the reference's 1.5KB row-size contract check.
    "row_conversion.enforce_row_limit": (True, bool),
    # Log level for the thin runtime logger (slf4j-equivalent).
    "log.level": ("WARNING", str),
    # Memory-layer allocation logging: 0 = off (RMM_LOGGING_LEVEL default
    # OFF parity, reference pom.xml:82), 1 = staging allocs, 2 = +reserves.
    "memory.log_level": (0, int),
    # regexp engine pin: "" = auto (device when compilable, else host),
    # "device" = require the DFA engine, "host" = force java.util.regex
    # emulation (testing / behavior comparison).
    "regex.force_engine": ("", str),
    # Execution telemetry (telemetry/): record op dispatches, device->host
    # fallbacks (with reasons), compile-cache hits, spills, bench staleness.
    # Off by default — same posture as tracing.enabled.
    "telemetry.enabled": (False, bool),
    # JSONL sink for telemetry events; "" = in-process ring buffer only.
    "telemetry.path": ("", str),
    # Flight recorder (telemetry/spans.py): how many recent query span
    # trees (completed roots + explicit dumps) the in-process ring keeps
    # for post-mortem inspection.
    "telemetry.flight_recorder_depth": (16, int),
    # Directory flight-recorder artifacts (full span tree + limiter /
    # queue state, dumped on a classified death, degrade-rung step or
    # cancellation) are written to; "" = in-memory ring only.
    "telemetry.flight_recorder_path": ("", str),
    # Cap on span nodes kept per in-memory query tree (the JSONL sink is
    # unbounded; the tree backs the flight recorder and inspect()).
    # Past the cap, spans still emit records but stop growing the tree.
    "telemetry.max_spans_per_tree": (2048, int),
    # Shape-bucketed dispatch (runtime/dispatch.py): pad the leading row
    # dimension of device-op inputs up to a bucket so one compiled
    # executable serves every batch size inside the bucket (the reference
    # launches per-shape CUDA kernels; XLA instead recompiles per shape,
    # which this layer amortizes).
    "dispatch.enabled": (True, bool),
    # Smallest bucket and bucket granularity (rows). Every bucket is a
    # multiple of this.
    "dispatch.bucket_base": (16, int),
    # Upper bound on padding waste per bucket step: buckets grow
    # geometrically by min(1 + max_waste_frac, 2). 1.0 = power-of-two
    # buckets (<= 50% padded rows); 0.0 = linear base-multiple buckets.
    "dispatch.max_waste_frac": (1.0, float),
    # Directory for JAX's persistent (cross-process) compilation cache;
    # "" = off. The short env var SPARK_RAPIDS_TPU_DISPATCH_CACHE is also
    # honored (checked first by runtime/dispatch.py).
    "dispatch.persistent_cache_dir": ("", str),
    # Pipelined out-of-core execution (runtime/pipeline.py): overlap host
    # read/decode with device transfer+compute through a bounded-queue
    # multi-stage executor. Off by default — the serial path stays the
    # reference implementation; results are bit-identical either way.
    "pipeline.enabled": (False, bool),
    # How many chunks the producer stages may run ahead of the consumer.
    # Also honored via the short env var SPARK_RAPIDS_TPU_PIPELINE_PREFETCH
    # (checked first by runtime/pipeline.py).
    "pipeline.prefetch_depth": (2, int),
    # Worker threads for the host read/decode stage. Decode is mostly
    # C-extension (numpy / native codec) work that releases the GIL, so a
    # small pool overlaps IO with decode without oversubscribing the host.
    "pipeline.decode_threads": (2, int),
    # Whole-stage fusion (runtime/fusion.py): compile each fusible plan
    # region into ONE executable through dispatch.call instead of one
    # executable per op. Off -> the same plan runs op-by-op (the staged
    # reference path); results are bit-identical either way.
    "fusion.enabled": (True, bool),
    # Donate region-input buffers the caller declared dead (intermediate
    # tables between regions, out-of-core chunk tables) into the fused
    # executable so XLA reuses them for outputs instead of
    # double-buffering HBM. Donation never applies to caller-owned scans.
    "fusion.donate": (True, bool),
    # Resilient execution (runtime/resilience.py): the single retry /
    # degradation policy every runtime seam routes transient failure
    # through. Off -> each call site reproduces its pre-resilience
    # behavior exactly (one-shot shuffle retry, unbounded grow loops,
    # raw error propagation).
    "resilience.enabled": (True, bool),
    # Bounded attempts for transient-classified failures at one seam
    # (TransientDeviceError / TransportError). Exhaustion raises a
    # classified FatalExecutionError — never a hang, never a silent
    # wrong result.
    "resilience.max_attempts": (4, int),
    # Geometric factor for capacity escalation (groupby cardinality
    # bound, join output capacity, shuffle slot count) when the failed
    # attempt reports no exact requirement.
    "resilience.growth": (4, int),
    # Base backoff between transient retries, in milliseconds; each
    # further retry multiplies by resilience.backoff_multiplier. 0 (the
    # default) retries immediately — device-local faults clear on
    # replay, not on wall time.
    "resilience.backoff_ms": (0, int),
    "resilience.backoff_multiplier": (2.0, float),
    # Multi-query serving runtime (runtime/server.py): maximum queries
    # executing concurrently across ALL sessions; queued work beyond this
    # waits its round-robin turn.
    "server.max_inflight": (4, int),
    # Default HBM budget (bytes) for a QueryServer built without an
    # explicit MemoryLimiter — every admitted query reserves its estimate
    # against this before it starts.
    "server.hbm_budget_bytes": (1 << 30, int),
    # How long (seconds) an admitted-for-execution query may wait for its
    # HBM reservation before it is rejected instead of held forever.
    "server.admission_timeout_s": (30.0, float),
    # Per-session queue depth: submissions beyond this are rejected at
    # submit time (backpressure to the client, not unbounded memory).
    "server.queue_depth": (64, int),
    # Safety multiplier applied to the input-bytes HBM estimate when the
    # caller does not supply one (intermediates cost more than inputs).
    "server.estimate_headroom": (1.5, float),
    # Per-query wall-clock deadline in milliseconds; 0 = no deadline. A
    # query past its deadline is cancelled cooperatively (region/chunk
    # boundaries, decode pool) and dies classified as QueryCancelled with
    # every reservation and queue slot released.
    "server.deadline_ms": (0, int),
    # Graceful degradation (runtime/degrade.py): when a classified
    # ResourceExhausted / CapacityOverflow escapes the retry/escalate
    # budget, re-execute one rung down the bit-identical tier ladder
    # (fused -> staged -> out-of-core halved chunks -> park-and-retry).
    # Off -> the serving runtime is byte-for-byte the pre-degradation
    # path: the first classified failure propagates.
    "degrade.enabled": (True, bool),
    # Maximum rungs a single query may step down before its original
    # classified failure is re-raised (4 covers the whole ladder).
    "degrade.max_steps": (4, int),
    # Park-and-retry rung: how long (seconds) a parked query waits for
    # the limiter to drain below the low watermark before giving up and
    # re-raising the classified failure.
    "degrade.park_timeout_s": (30.0, float),
    # Out-of-core rung: rows per chunk for the first out-of-core attempt;
    # each further pressure failure on this rung halves it (floor 1).
    "degrade.chunk_rows": (65536, int),
    # Memory-pressure watermarks as fractions of the limiter budget.
    # Crossing high proactively spills the coldest SpillStore entries and
    # pauses admission; admission resumes once usage drains below low.
    "memory.high_watermark": (0.85, float),
    "memory.low_watermark": (0.6, float),
    # Adaptive admission: blend factor for folding the measured peak
    # reservation of a plan signature into future estimates
    # (new = (1-alpha)*old + alpha*measured). 0 disables learning.
    "server.estimate_alpha": (0.4, float),
    # Where learned per-signature estimates persist ("" = beside the
    # dispatch persistent cache when that is configured, else unpersisted).
    # Writes are crash-safe: tmp file + os.replace + fsync.
    "server.estimate_path": ("", str),
    # Minimum seconds between learned-estimate persistence writes on the
    # serving path (the fsync pair is tail latency, not serving work);
    # the first learn saves immediately and close() always flushes.
    # <= 0 writes through on every served query.
    "server.estimate_save_interval_s": (5.0, float),
    # End-to-end data integrity (runtime/integrity.py): length+checksum
    # trailers sealed onto spill payloads, DCN wire frames and
    # out-of-core checkpoints, verified before any read-back byte is
    # decoded, plus structural validation of untrusted Parquet/ORC
    # input. Also honored via the short env var SPARK_RAPIDS_TPU_INTEGRITY
    # (checked first by integrity.enabled()). Off restores today's
    # byte-for-byte behavior at every seam: no trailers, no wire acks,
    # no envelope preflight.
    "integrity.enabled": (True, bool),
    # Directory for disk-tier spill files (SpillStore). "" keeps spilled
    # entries in host memory (today's behavior); a path moves spilled
    # payloads to checksummed files written crash-safe (tmp + os.replace
    # + fsync + read-back verify) so a crash mid-spill can never leave a
    # torn entry a later unspill trusts.
    "memory.spill_dir": ("", str),
    # Plan-signature result & subplan cache (runtime/resultcache.py):
    # memoize final query results and fused-region intermediates keyed by
    # (plan signature, input fingerprint), stored through the SpillStore's
    # integrity-sealed tiers. A hit in QueryServer.submit short-circuits
    # admission, compile and execution. Off restores today's serving path
    # byte-for-byte: no fingerprinting, no cache probes, no extra spans.
    "cache.enabled": (True, bool),
    # LRU capacity of the result cache in logical payload bytes (across
    # all tiers). Resident entries are charged against the MemoryLimiter
    # so cached results can never starve live queries; under pressure the
    # high-watermark spiller sheds cache entries first.
    "cache.max_bytes": (256 << 20, int),
    # Subplan-prefix reuse: hash canonicalized scan+filter+project prefixes
    # of submitted plans so two distinct plans sharing a prefix execute the
    # shared region once and reuse the materialized intermediate. Gated
    # separately because it rewrites plans before execution.
    "cache.subplan_enabled": (True, bool),
    # Columnar compression (runtime/compress.py): dictionary/RLE re-encode
    # + bit-packed validity + optional zstd UNDER the integrity seal on
    # every managed byte path. Off restores byte-for-byte legacy framing
    # at every seam: raw snapshots, flag-0/1 wire buffers, no codec frames.
    "compress.enabled": (True, bool),
    # Per-seam gates (all under compress.enabled): SpillStore host/disk
    # tiers, DCN wire frames, out-of-core checkpoints, result-cache
    # entries. Any one off restores that seam's legacy framing alone.
    "compress.spill": (True, bool),
    "compress.wire": (True, bool),
    "compress.checkpoint": (True, bool),
    "compress.cache": (True, bool),
    # zstd final-stage level over the winning scheme payload; used only
    # when the optional zstandard package is importable. <= 0 disables
    # the final stage (dict/RLE/bitpack still run).
    "compress.zstd_level": (3, int),
    # Serving fleet (runtime/fleet.py): number of QueryServer replica
    # subprocesses the supervisor boots and routes over.
    "fleet.replicas": (2, int),
    # Supervisor -> replica liveness ping cadence, and how long a replica
    # may go without answering before it is declared dead (classified
    # ReplicaDeadError via the fleet.heartbeat seam).
    "fleet.heartbeat_interval_s": (0.5, float),
    "fleet.heartbeat_timeout_s": (5.0, float),
    # How many times one query may be re-dispatched after replica deaths
    # before its in-flight failure is surfaced classified to the caller.
    "fleet.failover_budget": (2, int),
    # Exponential restart backoff for dead replicas: first restart waits
    # backoff_s, each consecutive crash multiplies the wait.
    "fleet.restart_backoff_s": (0.25, float),
    "fleet.restart_backoff_multiplier": (2.0, float),
    # Consecutive crashes (no successfully served query in between) after
    # which a replica's circuit breaker opens: it is quarantined and no
    # longer restarted or routed to.
    "fleet.quarantine_after": (3, int),
    # Supervisor-side result memo keyed by the result-cache idempotency
    # pair (plan signature, input fingerprint): bounds entries kept for
    # failover dedup / bit-identity verification. 0 disables the memo.
    "fleet.result_memo_entries": (64, int),
    # How long a worker subprocess may take to report boot_ok before its
    # boot counts as a crash (feeds the crash-loop circuit breaker).
    "fleet.worker_boot_timeout_s": (60.0, float),
    # How long a submit waits for a healthy replica (all dead/quarantined
    # or still booting) before failing classified.
    "fleet.dispatch_timeout_s": (30.0, float),
    # Pallas kernel tier (ops/pallas/): which device implementation the
    # hot inner loops (bounded-groupby accumulate, join hash probe,
    # row-image transpose) trace into. "xla" = the legacy XLA primitives
    # (byte-for-byte the pre-tier path, and always the bit-identity
    # oracle), "pallas" = the hand-written kernels (interpret-mode on
    # backends without Mosaic, e.g. CPU tier-1), "auto" = pallas on TPU,
    # xla elsewhere. The short env var SPARK_RAPIDS_TPU_KERNEL_TIER is
    # also honored (checked first by ops/pallas).
    "kernels.tier": ("xla", str),
    # Per-op tier overrides: "op=tier,op=tier" (e.g.
    # "groupby.bounded_accumulate=pallas,join.hash_probe=xla"); an op
    # absent here follows kernels.tier.
    "kernels.tier_overrides": ("", str),
    # AOT warmup (QueryServer.warmup): how many of the costliest plan
    # signatures from the learned-estimate file a fresh replica
    # precompiles at boot (fleet _worker_main calls this before
    # reporting boot_ok). 0 = off — boot stays byte-for-byte the
    # pre-warmup path.
    "server.warmup_top_n": (0, int),
    # Replica identity stamped onto every telemetry record/span emitted by
    # this process ("" = unstamped). The fleet supervisor sets this in
    # each worker's environment so a shared JSONL sink attributes every
    # line, and `telemetry report`/`trace` can group by replica.
    "telemetry.replica": ("", str),
    # Host identity stamped next to the replica stamp ("" = unstamped).
    # The cluster supervisor sets this in each remote worker's
    # environment so cross-host records/spans aggregate per host.
    "telemetry.host": ("", str),
    # Default interface for DCN listeners and dials (runtime/cluster
    # gateway, SliceLink.listen/connect when no host is passed).
    # Loopback keeps CI single-machine; a mesh deploy sets the NIC.
    "dcn.bind_host": ("127.0.0.1", str),
    # Cross-host serving mesh (runtime/cluster.py): number of host
    # workers the mesh supervisor boots (localhost-simulated in CI).
    "cluster.hosts": (2, int),
    # How long one shard registration (ship + decode + fingerprint ack)
    # may take before it fails classified.
    "cluster.register_timeout_s": (60.0, float),
    # Distributed exchange (runtime/exchange.py): hard ceiling on the
    # per-destination send-buffer capacity the escalation ladder may
    # grow to before the pack demotes to multi-flight chunking (the
    # spill-aware rung). Quantized through the dispatch bucket schedule.
    "exchange.max_capacity_rows": (1 << 16, int),
    # Device-byte budget for the receive-side chunked merge of exchange
    # flights (MemoryLimiter budget handed to run_chunked_aggregate);
    # partial results beyond it LRU-spill to compressed host memory.
    "exchange.merge_budget_bytes": (64 << 20, int),
    # Direct host-to-host exchange flights: when on, the cluster ships
    # only the routing manifest and sources dial destination peers
    # directly (sealed TPCZ flights, HMAC-signed grants); the
    # router-mediated path stays as the classified fallback rung. Off
    # forces every flight through the supervisor (the PR-19 topology).
    "exchange.direct_enabled": (True, bool),
    # Bounded connect retry for one peer dial (a dead peer must fail
    # fast into the routed fallback, not hang the exchange): attempts x
    # delay ~= the dial budget before TransportError surfaces.
    "exchange.peer_dial_retries": (8, int),
    "exchange.peer_dial_delay_s": (0.05, float),
    # How long a destination waits for all manifest-listed peer flights
    # before the merge fails classified (and the supervisor falls back
    # to the routed path).
    "exchange.direct_timeout_s": (30.0, float),
    # Planner-placed exchanges: when an interior Exchange node carries
    # parts=0 ("auto"), the partition count comes from the learned-
    # selectivity store (rows in x learned pass fraction / target rows
    # per partition, clamped to max_parts); no history falls back to 1.
    "exchange.target_rows_per_part": (4096, int),
    "exchange.max_parts": (64, int),
    # Runtime bloom-join filters (runtime/rtfilter.py): master switch for
    # the planner pass that builds a bloom filter from a selective join's
    # build side and prunes the probe side before it stages. Off by
    # default — results are bit-identical either way (a bloom filter only
    # drops rows the join would drop); on buys fewer rows scanned on
    # chunked/fan-out paths at the cost of the build.
    "rtfilter.enabled": (False, bool),
    # Build sides above this many rows never get a filter (the bloom
    # bits would be large and the join is unlikely to be selective).
    "rtfilter.max_build_rows": (1 << 16, int),
    # Target false-positive probability handed to BloomFilter.optimal
    # when sizing a filter's bits for the observed build cardinality.
    "rtfilter.fpp": (0.03, float),
    # Learned gate: once a (plan, join) signature's observed pass
    # fraction EMA rises above this, the filter is judged non-selective
    # and switched off for that signature (probe overhead with no
    # pruning payoff). Signatures with no history run optimistically.
    "rtfilter.gate_pass_frac": (0.8, float),
    # EMA blend weight for newly observed pass fractions (same role as
    # server.estimate_alpha for admission estimates).
    "rtfilter.alpha": (0.4, float),
    # Where the selectivity EMAs persist ("" = beside the learned
    # admission estimates, i.e. learned_selectivity.json in the dispatch
    # persistent cache dir; in-memory only when neither exists). Shares
    # the flock+merge write discipline with the estimate file.
    "rtfilter.path": ("", str),
    # Debounce for selectivity-state writes, seconds.
    "rtfilter.save_interval_s": (5.0, float),
}

_overrides: dict[str, Any] = {}


def _parse(raw: str, typ: type) -> Any:
    if typ is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    return typ(raw)


def get_option(name: str) -> Any:
    if name not in _OPTIONS:
        raise KeyError(f"unknown option {name!r}")
    if name in _overrides:
        return _overrides[name]
    default, typ = _OPTIONS[name]
    env = os.environ.get(_ENV_PREFIX + name.upper().replace(".", "_"))
    if env is not None:
        return _parse(env, typ)
    return default


def set_option(name: str, value: Any) -> None:
    if name not in _OPTIONS:
        raise KeyError(f"unknown option {name!r}")
    _, typ = _OPTIONS[name]
    # coerce through the same parser env values get, so
    # set_option("tracing.enabled", "off") == env ..._ENABLED=off
    _overrides[name] = _parse(value, typ) if isinstance(value, str) else typ(value)


def reset_option(name: str) -> None:
    _overrides.pop(name, None)
