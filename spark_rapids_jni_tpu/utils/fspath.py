"""Shared bytes-vs-path dispatch for the file readers (Parquet + ORC):
both accept in-memory bytes or a filesystem path, where paths route to
the native mmap storage path. One helper so the readers cannot diverge
on path handling."""

from __future__ import annotations

import os


def as_fs_path(data) -> bytes | None:
    """fsencode'd path when ``data`` names a file, else None (in-memory
    bytes)."""
    if isinstance(data, (str, os.PathLike)):
        return os.fsencode(data)
    return None
