#include "tpudf/protobuf_wire.hpp"

#include <stdexcept>

namespace tpudf {
namespace pb {

namespace {

uint64_t read_varint(uint8_t const* p, uint64_t len, uint64_t* pos) {
  uint64_t out = 0;
  int shift = 0;
  while (*pos < len && shift <= 63) {
    uint8_t b = p[(*pos)++];
    out |= static_cast<uint64_t>(b & 0x7F) << shift;
    if ((b & 0x80) == 0) return out;
    shift += 7;
  }
  throw std::runtime_error("protobuf: bad varint");
}

}  // namespace

Message Message::parse(uint8_t const* buf, uint64_t len) {
  Message m;
  uint64_t pos = 0;
  while (pos < len) {
    uint64_t key = read_varint(buf, len, &pos);
    PbField f;
    f.number = static_cast<uint32_t>(key >> 3);
    f.type = static_cast<WireType>(key & 7);
    switch (f.type) {
      case WireType::VARINT:
        f.varint = read_varint(buf, len, &pos);
        break;
      case WireType::FIXED64: {
        if (pos + 8 > len) throw std::runtime_error("protobuf: short fixed64");
        uint64_t v = 0;
        for (int k = 0; k < 8; ++k) v |= static_cast<uint64_t>(buf[pos + k]) << (8 * k);
        f.varint = v;
        pos += 8;
        break;
      }
      case WireType::FIXED32: {
        if (pos + 4 > len) throw std::runtime_error("protobuf: short fixed32");
        uint64_t v = 0;
        for (int k = 0; k < 4; ++k) v |= static_cast<uint64_t>(buf[pos + k]) << (8 * k);
        f.varint = v;
        pos += 4;
        break;
      }
      case WireType::BYTES: {
        uint64_t n = read_varint(buf, len, &pos);
        if (pos + n > len) throw std::runtime_error("protobuf: short bytes");
        f.bytes = std::string_view(reinterpret_cast<char const*>(buf + pos), n);
        pos += n;
        break;
      }
      default:
        throw std::runtime_error("protobuf: unsupported wire type");
    }
    m.fields_.push_back(f);
  }
  return m;
}

PbField const* Message::field(uint32_t number) const {
  for (auto const& f : fields_) {
    if (f.number == number) return &f;
  }
  return nullptr;
}

std::vector<PbField const*> Message::fields(uint32_t number) const {
  std::vector<PbField const*> out;
  for (auto const& f : fields_) {
    if (f.number == number) out.push_back(&f);
  }
  return out;
}

uint64_t Message::u64(uint32_t number, uint64_t dflt) const {
  auto const* f = field(number);
  return f == nullptr ? dflt : f->varint;
}

std::string_view Message::bytes(uint32_t number) const {
  auto const* f = field(number);
  return f == nullptr ? std::string_view() : f->bytes;
}

}  // namespace pb
}  // namespace tpudf
