#include "tpudf/thrift_compact.hpp"

#include <algorithm>
#include <cstring>

namespace tpudf {
namespace thrift {

Value& Value::operator=(Value const& o) {
  if (this == &o) return *this;
  type = o.type;
  b = o.b;
  i = o.i;
  d = o.d;
  bin = o.bin;
  elem_type = o.elem_type;
  elems = o.elems;
  key_type = o.key_type;
  val_type = o.val_type;
  keys = o.keys;
  vals = o.vals;
  fields.clear();
  fields.reserve(o.fields.size());
  for (auto const& f : o.fields) {
    fields.push_back(Field{f.id, std::make_unique<Value>(*f.value)});
  }
  return *this;
}

Value* Value::field(int16_t id) {
  for (auto& f : fields) {
    if (f.id == id) return f.value.get();
  }
  return nullptr;
}

Value const* Value::field(int16_t id) const {
  for (auto const& f : fields) {
    if (f.id == id) return f.value.get();
  }
  return nullptr;
}

Value& Value::set_field(int16_t id, WireType t) {
  if (Value* existing = field(id)) {
    existing->type = t;
    return *existing;
  }
  auto it = std::find_if(fields.begin(), fields.end(),
                         [id](Field const& f) { return f.id > id; });
  it = fields.insert(it, Field{id, std::make_unique<Value>(t)});
  return *it->value;
}

namespace {

class Reader {
 public:
  Reader(uint8_t const* buf, uint64_t len, Limits const& limits)
      : buf_(buf), len_(len), limits_(limits) {}

  Value read_struct() {
    Value v(WireType::STRUCT);
    if (++depth_ > 64) throw ParseError("struct nesting too deep");
    int16_t last_id = 0;
    for (;;) {
      uint8_t header = read_byte();
      if (header == 0) break;  // STOP
      auto wire = static_cast<WireType>(header & 0x0F);
      int16_t delta = static_cast<int16_t>(header >> 4);
      int16_t id =
          delta != 0 ? static_cast<int16_t>(last_id + delta) : read_zigzag16();
      last_id = id;
      Value fv = read_value(wire);
      v.fields.push_back(Field{id, std::make_unique<Value>(std::move(fv))});
    }
    --depth_;
    return v;
  }

  uint64_t pos() const { return pos_; }

 private:
  Value read_value(WireType wire) {
    switch (wire) {
      case WireType::BOOL_TRUE: {
        Value v(WireType::BOOL_TRUE);
        v.b = true;
        return v;
      }
      case WireType::BOOL_FALSE: {
        Value v(WireType::BOOL_FALSE);
        v.b = false;
        return v;
      }
      case WireType::I8: {
        Value v(WireType::I8);
        v.i = static_cast<int8_t>(read_byte());
        return v;
      }
      case WireType::I16:
      case WireType::I32:
      case WireType::I64: {
        Value v(wire);
        v.i = read_zigzag64();
        return v;
      }
      case WireType::DOUBLE: {
        Value v(WireType::DOUBLE);
        uint64_t raw = 0;
        for (int k = 0; k < 8; ++k) {  // little-endian per compact spec
          raw |= static_cast<uint64_t>(read_byte()) << (8 * k);
        }
        std::memcpy(&v.d, &raw, 8);
        return v;
      }
      case WireType::BINARY: {
        Value v(WireType::BINARY);
        uint64_t n = read_varint();
        if (n > limits_.max_string_size) throw ParseError("string too large");
        require(n);
        v.bin.assign(reinterpret_cast<char const*>(buf_ + pos_), n);
        pos_ += n;
        return v;
      }
      case WireType::LIST:
      case WireType::SET: {
        Value v(wire);
        uint8_t header = read_byte();
        uint64_t n = header >> 4;
        v.elem_type = static_cast<WireType>(header & 0x0F);
        if (n == 0x0F) n = read_varint();
        if (n > limits_.max_container_size) throw ParseError("container too large");
        v.elems.reserve(n);
        for (uint64_t k = 0; k < n; ++k) {
          v.elems.push_back(read_collection_elem(v.elem_type));
        }
        return v;
      }
      case WireType::MAP: {
        Value v(WireType::MAP);
        uint64_t n = read_varint();
        if (n > limits_.max_container_size) throw ParseError("container too large");
        if (n > 0) {
          uint8_t kv = read_byte();
          v.key_type = static_cast<WireType>(kv >> 4);
          v.val_type = static_cast<WireType>(kv & 0x0F);
          v.keys.reserve(n);
          v.vals.reserve(n);
          for (uint64_t k = 0; k < n; ++k) {
            v.keys.push_back(read_collection_elem(v.key_type));
            v.vals.push_back(read_collection_elem(v.val_type));
          }
        }
        return v;
      }
      case WireType::STRUCT:
        return read_struct();
      default:
        throw ParseError("unknown compact wire type");
    }
  }

  // Inside collections, bools are one byte (1=true, 2=false), not encoded
  // in the element-type nibble.
  Value read_collection_elem(WireType t) {
    if (t == WireType::BOOL_TRUE || t == WireType::BOOL_FALSE) {
      uint8_t raw = read_byte();
      Value v(raw == 1 ? WireType::BOOL_TRUE : WireType::BOOL_FALSE);
      v.b = (raw == 1);
      return v;
    }
    return read_value(t);
  }

  void require(uint64_t n) {
    if (pos_ + n > len_) throw ParseError("unexpected end of thrift data");
  }

  uint8_t read_byte() {
    require(1);
    return buf_[pos_++];
  }

  uint64_t read_varint() {
    uint64_t out = 0;
    int shift = 0;
    for (;;) {
      uint8_t byte = read_byte();
      out |= static_cast<uint64_t>(byte & 0x7F) << shift;
      if (!(byte & 0x80)) return out;
      shift += 7;
      if (shift > 63) throw ParseError("varint too long");
    }
  }

  int64_t read_zigzag64() {
    uint64_t u = read_varint();
    return static_cast<int64_t>(u >> 1) ^ -static_cast<int64_t>(u & 1);
  }

  int16_t read_zigzag16() { return static_cast<int16_t>(read_zigzag64()); }

  uint8_t const* buf_;
  uint64_t len_;
  uint64_t pos_ = 0;
  int depth_ = 0;
  Limits limits_;
};

class Writer {
 public:
  void write_struct(Value const& v) {
    int16_t last_id = 0;
    for (auto const& f : v.fields) {
      write_field(f.id, last_id, *f.value);
      last_id = f.id;
    }
    out_.push_back('\0');  // STOP
  }

  std::string take() { return std::move(out_); }

 private:
  void write_field(int16_t id, int16_t last_id, Value const& v) {
    int32_t delta = id - last_id;
    uint8_t wire = static_cast<uint8_t>(v.type);
    if (delta > 0 && delta <= 15) {
      out_.push_back(static_cast<char>((delta << 4) | wire));
    } else {
      out_.push_back(static_cast<char>(wire));
      write_zigzag(id);
    }
    write_value(v);
  }

  void write_value(Value const& v) {
    switch (v.type) {
      case WireType::BOOL_TRUE:
      case WireType::BOOL_FALSE:
        break;  // encoded in the type nibble at field level
      case WireType::I8:
        out_.push_back(static_cast<char>(static_cast<int8_t>(v.i)));
        break;
      case WireType::I16:
      case WireType::I32:
      case WireType::I64:
        write_zigzag(v.i);
        break;
      case WireType::DOUBLE: {
        uint64_t raw;
        std::memcpy(&raw, &v.d, 8);
        for (int k = 0; k < 8; ++k) {
          out_.push_back(static_cast<char>((raw >> (8 * k)) & 0xFF));
        }
        break;
      }
      case WireType::BINARY:
        write_varint(v.bin.size());
        out_.append(v.bin);
        break;
      case WireType::LIST:
      case WireType::SET: {
        uint64_t n = v.elems.size();
        uint8_t et = static_cast<uint8_t>(v.elem_type);
        if (n < 15) {
          out_.push_back(static_cast<char>((n << 4) | et));
        } else {
          out_.push_back(static_cast<char>(0xF0 | et));
          write_varint(n);
        }
        for (auto const& e : v.elems) write_collection_elem(v.elem_type, e);
        break;
      }
      case WireType::MAP: {
        uint64_t n = v.keys.size();
        write_varint(n);
        if (n > 0) {
          out_.push_back(static_cast<char>(
              (static_cast<uint8_t>(v.key_type) << 4) |
              static_cast<uint8_t>(v.val_type)));
          for (uint64_t k = 0; k < n; ++k) {
            write_collection_elem(v.key_type, v.keys[k]);
            write_collection_elem(v.val_type, v.vals[k]);
          }
        }
        break;
      }
      case WireType::STRUCT:
        write_struct(v);
        break;
      default:
        throw ParseError("cannot serialize unknown wire type");
    }
  }

  void write_collection_elem(WireType t, Value const& v) {
    if (t == WireType::BOOL_TRUE || t == WireType::BOOL_FALSE) {
      out_.push_back(v.b ? 1 : 2);
      return;
    }
    write_value(v);
  }

  void write_varint(uint64_t u) {
    while (u >= 0x80) {
      out_.push_back(static_cast<char>((u & 0x7F) | 0x80));
      u >>= 7;
    }
    out_.push_back(static_cast<char>(u));
  }

  void write_zigzag(int64_t s) {
    write_varint((static_cast<uint64_t>(s) << 1) ^
                 static_cast<uint64_t>(s >> 63));
  }

  std::string out_;
};

}  // namespace

Value parse_struct(uint8_t const* buf, uint64_t len, Limits const& limits) {
  Reader r(buf, len, limits);
  return r.read_struct();
}

Value parse_struct(uint8_t const* buf, uint64_t len, uint64_t* consumed,
                   Limits const& limits) {
  Reader r(buf, len, limits);
  Value v = r.read_struct();
  *consumed = r.pos();
  return v;
}

std::string serialize_struct(Value const& v) {
  Writer w;
  w.write_struct(v);
  return w.take();
}

}  // namespace thrift
}  // namespace tpudf
