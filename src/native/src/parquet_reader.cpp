// Parquet data-page decode. See parquet_reader.hpp for the supported
// subset. All structures are parsed with the generic thrift codec
// (thrift_compact.hpp) and addressed by parquet.thrift field id.

#include "tpudf/parquet_reader.hpp"

#include <zlib.h>
#include <zstd.h>

#include <algorithm>
#include <cstring>
#include <stdexcept>
#include <string>

#include "tpudf/thrift_compact.hpp"

namespace tpudf {
namespace parquet {

namespace {

using thrift::Value;

[[noreturn]] void fail(std::string const& msg) {
  throw std::runtime_error("parquet read: " + msg);
}

// ---- thrift field ids (parquet.thrift, public spec) ------------------------

// FileMetaData
constexpr int16_t kFmdSchema = 2;
constexpr int16_t kFmdRowGroups = 4;
// SchemaElement
constexpr int16_t kSeType = 1;
constexpr int16_t kSeTypeLength = 2;
constexpr int16_t kSeRepetition = 3;
constexpr int16_t kSeName = 4;
constexpr int16_t kSeNumChildren = 5;
constexpr int16_t kSeConverted = 6;
constexpr int16_t kSeScale = 7;
constexpr int16_t kSePrecision = 8;
// RowGroup
constexpr int16_t kRgColumns = 1;
constexpr int16_t kRgTotalByteSize = 2;
constexpr int16_t kRgNumRows = 3;
constexpr int16_t kRgTotalCompressed = 6;
// ColumnChunk / ColumnMetaData
constexpr int16_t kCcMeta = 3;
constexpr int16_t kCmType = 1;
constexpr int16_t kCmCodec = 4;
constexpr int16_t kCmNumValues = 5;
constexpr int16_t kCmDataPageOffset = 9;
constexpr int16_t kCmDictPageOffset = 11;
// PageHeader
constexpr int16_t kPhType = 1;
constexpr int16_t kPhUncompressedSize = 2;
constexpr int16_t kPhCompressedSize = 3;
constexpr int16_t kPhDataHeader = 5;
constexpr int16_t kPhDictHeader = 7;
constexpr int16_t kPhDataHeaderV2 = 8;
// DataPageHeader
constexpr int16_t kDphNumValues = 1;
constexpr int16_t kDphEncoding = 2;
constexpr int16_t kDphDefLevelEncoding = 3;
constexpr int16_t kDphRepLevelEncoding = 4;
// DataPageHeaderV2
constexpr int16_t kDph2NumValues = 1;
constexpr int16_t kDph2NumNulls = 2;
constexpr int16_t kDph2Encoding = 4;
constexpr int16_t kDph2DefLevelsByteLen = 5;
constexpr int16_t kDph2RepLevelsByteLen = 6;
constexpr int16_t kDph2IsCompressed = 7;

// enums
constexpr int32_t kPageData = 0;
constexpr int32_t kPageDict = 2;
constexpr int32_t kPageDataV2 = 3;
constexpr int32_t kEncPlain = 0;
constexpr int32_t kEncPlainDict = 2;
constexpr int32_t kEncRle = 3;
constexpr int32_t kEncDeltaBinary = 5;       // DELTA_BINARY_PACKED
constexpr int32_t kEncDeltaLengthBA = 6;     // DELTA_LENGTH_BYTE_ARRAY
constexpr int32_t kEncDeltaBA = 7;           // DELTA_BYTE_ARRAY
constexpr int32_t kEncRleDict = 8;
constexpr int32_t kCodecUncompressed = 0;
constexpr int32_t kCodecSnappy = 1;
constexpr int32_t kCodecGzip = 2;
constexpr int32_t kCodecZstd = 6;

int64_t field_i64(Value const& s, int16_t id, char const* what) {
  auto const* f = s.field(id);
  if (f == nullptr) fail(std::string("missing field: ") + what);
  return f->as_i64();
}

int64_t field_i64_or(Value const& s, int16_t id, int64_t dflt) {
  auto const* f = s.field(id);
  return f == nullptr ? dflt : f->as_i64();
}

// ---- codecs ---------------------------------------------------------------

std::vector<uint8_t> gzip_uncompress(uint8_t const* in, uint64_t n,
                                     uint64_t expected_out) {
  std::vector<uint8_t> out(expected_out);
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  // 32 + MAX_WBITS: auto-detect gzip or zlib framing.
  if (inflateInit2(&zs, 32 + MAX_WBITS) != Z_OK) fail("zlib init failed");
  zs.next_in = const_cast<Bytef*>(in);
  zs.avail_in = static_cast<uInt>(n);
  zs.next_out = out.data();
  zs.avail_out = static_cast<uInt>(out.size());
  int rc = inflate(&zs, Z_FINISH);
  inflateEnd(&zs);
  if (rc != Z_STREAM_END || zs.total_out != expected_out) {
    fail("gzip page did not decompress to the declared size");
  }
  return out;
}

uint64_t read_varint(uint8_t const* p, uint64_t len, uint64_t* pos) {
  uint64_t out = 0;
  int shift = 0;
  while (*pos < len) {
    uint8_t b = p[(*pos)++];
    out |= static_cast<uint64_t>(b & 0x7F) << shift;
    if ((b & 0x80) == 0) return out;
    shift += 7;
    if (shift > 63) break;
  }
  fail("bad varint");
}

std::vector<uint8_t> do_decompress(int32_t codec, uint8_t const* in,
                                   uint64_t n, uint64_t expected) {
  switch (codec) {
    case kCodecUncompressed: {
      if (n != expected) fail("uncompressed page size mismatch");
      return std::vector<uint8_t>(in, in + n);
    }
    case kCodecSnappy:
      return snappy_uncompress(in, n, expected);
    case kCodecGzip:
      return gzip_uncompress(in, n, expected);
    case kCodecZstd: {
      std::vector<uint8_t> out(expected);
      size_t rc = ZSTD_decompress(out.data(), out.size(), in, n);
      if (ZSTD_isError(rc) || rc != expected) {
        fail("zstd page did not decompress to the declared size");
      }
      return out;
    }
    default:
      fail("unsupported compression codec " + std::to_string(codec) +
           " (supported: UNCOMPRESSED, SNAPPY, GZIP, ZSTD)");
  }
}

// ---- DELTA_BINARY_PACKED / DELTA_*_BYTE_ARRAY ------------------------------

int64_t zigzag_decode(uint64_t u) {
  return static_cast<int64_t>(u >> 1) ^ -static_cast<int64_t>(u & 1);
}

// Decode one DELTA_BINARY_PACKED stream starting at *pos; advances *pos to
// the first byte after the stream (required: DELTA_LENGTH_BYTE_ARRAY and
// DELTA_BYTE_ARRAY concatenate further sections behind it).
std::vector<int64_t> decode_delta_binary(uint8_t const* p, uint64_t len,
                                         uint64_t* pos) {
  uint64_t block_size = read_varint(p, len, pos);
  uint64_t miniblocks = read_varint(p, len, pos);
  uint64_t total = read_varint(p, len, pos);
  int64_t value = zigzag_decode(read_varint(p, len, pos));
  if (miniblocks == 0 || block_size % miniblocks != 0 ||
      block_size % 128 != 0) {
    fail("bad DELTA_BINARY_PACKED header");
  }
  uint64_t per_mini = block_size / miniblocks;
  if (per_mini % 32 != 0) fail("miniblock size not a multiple of 32");
  std::vector<int64_t> out;
  out.reserve(total);
  if (total == 0) return out;
  out.push_back(value);
  while (out.size() < total) {
    int64_t min_delta = zigzag_decode(read_varint(p, len, pos));
    if (*pos + miniblocks > len) fail("delta bit widths past end");
    uint8_t const* bws = p + *pos;
    *pos += miniblocks;
    for (uint64_t m = 0; m < miniblocks; ++m) {
      int bw = bws[m];
      if (bw > 64) fail("delta miniblock bit width > 64");
      if (out.size() >= total) {
        // fully-padded trailing miniblock: no data bytes were written
        continue;
      }
      uint64_t nbytes = per_mini * bw / 8;
      if (*pos + nbytes > len) fail("delta miniblock past end of page");
      for (uint64_t i = 0; i < per_mini && out.size() < total; ++i) {
        uint64_t bit = i * bw;
        uint64_t byte = bit >> 3;
        int shift = static_cast<int>(bit & 7);
        // a <=64-bit field spans at most 9 bytes
        unsigned __int128 acc = 0;
        for (int k = 0; k < 9 && byte + k < nbytes; ++k) {
          acc |= static_cast<unsigned __int128>(p[*pos + byte + k])
                 << (8 * k);
        }
        uint64_t mask = bw == 64 ? ~0ull : ((1ull << bw) - 1);
        uint64_t delta = static_cast<uint64_t>(acc >> shift) & mask;
        value += min_delta + static_cast<int64_t>(delta);
        out.push_back(value);
      }
      *pos += nbytes;
    }
  }
  return out;
}

// DELTA_LENGTH_BYTE_ARRAY: delta-packed lengths, then concatenated bytes.
std::vector<std::string> decode_delta_length_ba(uint8_t const* p,
                                                uint64_t len, uint64_t* pos) {
  std::vector<int64_t> lengths = decode_delta_binary(p, len, pos);
  std::vector<std::string> blobs;
  blobs.reserve(lengths.size());
  for (int64_t l : lengths) {
    if (l < 0 || *pos + static_cast<uint64_t>(l) > len) {
      fail("DELTA_LENGTH_BYTE_ARRAY data past end of page");
    }
    blobs.emplace_back(reinterpret_cast<char const*>(p + *pos), l);
    *pos += static_cast<uint64_t>(l);
  }
  return blobs;
}

// DELTA_BYTE_ARRAY: delta-packed shared-prefix lengths + suffixes as
// DELTA_LENGTH_BYTE_ARRAY; value i = value[i-1][:prefix[i]] + suffix[i].
std::vector<std::string> decode_delta_ba(uint8_t const* p, uint64_t len,
                                         uint64_t* pos) {
  std::vector<int64_t> prefixes = decode_delta_binary(p, len, pos);
  std::vector<std::string> suffixes = decode_delta_length_ba(p, len, pos);
  if (prefixes.size() != suffixes.size()) {
    fail("DELTA_BYTE_ARRAY prefix/suffix count mismatch");
  }
  std::vector<std::string> blobs;
  blobs.reserve(prefixes.size());
  std::string prev;
  for (size_t i = 0; i < prefixes.size(); ++i) {
    int64_t pre = prefixes[i];
    if (pre < 0 || static_cast<uint64_t>(pre) > prev.size()) {
      fail("DELTA_BYTE_ARRAY prefix longer than previous value");
    }
    std::string v = prev.substr(0, pre) + suffixes[i];
    blobs.push_back(v);
    prev = std::move(v);
  }
  return blobs;
}

int bits_for_level(int32_t max_level) {
  int bw = 0;
  while ((1 << bw) <= max_level) ++bw;
  return bw;
}

uint32_t read_le32(uint8_t const* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

// ---- RLE / bit-packed hybrid ----------------------------------------------

// Decode up to `count` values from parquet's RLE/bit-packed hybrid format.
// Bit-packed groups may carry padding values past `count`; they are decoded
// and discarded (the spec pads the final group to a multiple of 8).
void decode_rle_hybrid(uint8_t const* p, uint64_t len, int bit_width,
                       int64_t count, std::vector<uint32_t>& out) {
  out.clear();
  out.reserve(count);
  if (bit_width == 0) {
    out.assign(count, 0);
    return;
  }
  if (bit_width > 32) fail("rle bit width > 32");
  uint64_t pos = 0;
  int byte_width = (bit_width + 7) / 8;
  while (static_cast<int64_t>(out.size()) < count) {
    uint64_t header = read_varint(p, len, &pos);
    if (header & 1) {
      // bit-packed run: (header >> 1) groups of 8 values
      uint64_t groups = header >> 1;
      uint64_t nbytes = groups * bit_width;  // == groups*8*bw/8
      if (pos + nbytes > len) fail("bit-packed run past end of level data");
      uint64_t nvals = groups * 8;
      for (uint64_t i = 0;
           i < nvals && static_cast<int64_t>(out.size()) < count; ++i) {
        uint64_t bit = i * bit_width;
        uint64_t byte = bit >> 3;
        int shift = static_cast<int>(bit & 7);
        // a value spans at most 5 bytes for bw <= 32
        uint64_t acc = 0;
        for (int k = 0; k < 5 && byte + k < nbytes; ++k) {
          acc |= static_cast<uint64_t>(p[pos + byte + k]) << (8 * k);
        }
        out.push_back(
            static_cast<uint32_t>((acc >> shift) &
                                  ((bit_width == 32)
                                       ? 0xFFFFFFFFull
                                       : ((1ull << bit_width) - 1))));
      }
      pos += nbytes;
    } else {
      uint64_t run = header >> 1;
      if (pos + byte_width > len) fail("rle run value past end");
      uint32_t v = 0;
      for (int k = 0; k < byte_width; ++k) {
        v |= static_cast<uint32_t>(p[pos + k]) << (8 * k);
      }
      pos += byte_width;
      uint64_t take = std::min<uint64_t>(run, count - out.size());
      out.insert(out.end(), take, v);
    }
  }
}

// ---- PLAIN decode ---------------------------------------------------------

struct Dict {
  // fixed-width entries packed at `width` bytes each, or byte-array blobs
  std::vector<uint8_t> fixed;
  std::vector<std::string> blobs;
  int width = 0;
  int64_t size = 0;
};

int fixed_width_of(int32_t physical, int32_t type_length) {
  switch (static_cast<Physical>(physical)) {
    case Physical::BOOLEAN: return 1;
    case Physical::INT32:
    case Physical::FLOAT: return 4;
    case Physical::INT64:
    case Physical::DOUBLE: return 8;
    case Physical::FIXED_LEN_BYTE_ARRAY:
      if (type_length <= 0) fail("FIXED_LEN_BYTE_ARRAY without type_length");
      return type_length;
    case Physical::INT96:
      fail("INT96 timestamps are not supported (deprecated by the format)");
    default: return 0;  // BYTE_ARRAY
  }
}

// Decode `n` PLAIN values. For fixed-width targets appends n*width bytes to
// `dst`; for BYTE_ARRAY appends blobs. Booleans are bit-packed LSB-first on
// the wire and widen to one byte each.
uint64_t decode_plain(uint8_t const* p, uint64_t len, int64_t n,
                      int32_t physical, int width, std::vector<uint8_t>* dst,
                      std::vector<std::string>* blobs) {
  uint64_t pos = 0;
  if (static_cast<Physical>(physical) == Physical::BOOLEAN) {
    for (int64_t i = 0; i < n; ++i) {
      uint64_t byte = pos + (i >> 3);
      if (byte >= len) fail("boolean data past end of page");
      dst->push_back((p[byte] >> (i & 7)) & 1);
    }
    return pos + ((n + 7) >> 3);
  }
  if (static_cast<Physical>(physical) == Physical::BYTE_ARRAY) {
    for (int64_t i = 0; i < n; ++i) {
      if (pos + 4 > len) fail("byte_array length past end of page");
      uint32_t m = static_cast<uint32_t>(p[pos]) |
                   (static_cast<uint32_t>(p[pos + 1]) << 8) |
                   (static_cast<uint32_t>(p[pos + 2]) << 16) |
                   (static_cast<uint32_t>(p[pos + 3]) << 24);
      pos += 4;
      if (pos + m > len) fail("byte_array value past end of page");
      blobs->emplace_back(reinterpret_cast<char const*>(p + pos), m);
      pos += m;
    }
    return pos;
  }
  uint64_t nbytes = static_cast<uint64_t>(n) * width;
  if (pos + nbytes > len) fail("plain values past end of page");
  dst->insert(dst->end(), p + pos, p + pos + nbytes);
  return pos + nbytes;
}

// ---- column chunk decode --------------------------------------------------

struct LeafInfo {
  std::string name;  // dotted path from the root
  int32_t physical = 0;
  int32_t converted = -1;
  int32_t scale = 0;
  int32_t precision = 0;
  int32_t type_length = 0;
  bool optional = false;
  int32_t max_def = 0;  // definition-level bound (0 = required flat leaf)
  int32_t max_rep = 0;  // repetition-level bound (0 = no lists on the path)
  bool nested = false;  // leaf sits under a group (struct/list ancestor)
};

struct SchemaInfo {
  std::vector<LeafInfo> leaves;
  // one line per schema element, preorder:
  // "name\tnum_children\trepetition\tphysical\tconverted\tscale\t
  //  precision\ttype_length" — the Python surface rebuilds the tree for
  // nested column assembly from this
  std::string desc;
};

constexpr int32_t kMaxSchemaDepth = 64;  // anti-bomb cap (cf. the thrift
                                         // string/container caps); also
                                         // keeps def levels inside uint8

void walk_schema(std::vector<Value> const& elems, uint64_t& idx,
                 std::string const& prefix, int32_t def, int32_t rep,
                 int32_t depth, SchemaInfo& out) {
  if (depth > kMaxSchemaDepth) fail("schema nesting deeper than 64 levels");
  if (idx >= elems.size()) fail("schema tree shorter than declared");
  auto const& se = elems[idx++];
  auto const* nm = se.field(kSeName);
  std::string name = nm ? nm->as_binary() : "";
  int64_t n_children = field_i64_or(se, kSeNumChildren, 0);
  // repetition: 0 REQUIRED, 1 OPTIONAL, 2 REPEATED
  int64_t repetition = field_i64_or(se, kSeRepetition, 0);
  if (repetition != 0) def += 1;  // optional and repeated add a def level
  if (repetition == 2) rep += 1;
  if (rep > 1) fail("nested lists (repetition depth > 1) are not supported");
  int32_t physical = static_cast<int32_t>(field_i64_or(se, kSeType, -1));
  int32_t converted = static_cast<int32_t>(field_i64_or(se, kSeConverted, -1));
  int32_t scale = static_cast<int32_t>(field_i64_or(se, kSeScale, 0));
  int32_t precision = static_cast<int32_t>(field_i64_or(se, kSePrecision, 0));
  int32_t type_length =
      static_cast<int32_t>(field_i64_or(se, kSeTypeLength, 0));
  std::string esc_name;
  for (char ch : name) {  // tab/newline are legal in parquet field names
    if (ch == '\\') esc_name += "\\\\";
    else if (ch == '\t') esc_name += "\\t";
    else if (ch == '\n') esc_name += "\\n";
    else esc_name += ch;
  }
  out.desc += esc_name + "\t" + std::to_string(n_children) + "\t" +
              std::to_string(repetition) + "\t" + std::to_string(physical) +
              "\t" + std::to_string(converted) + "\t" +
              std::to_string(scale) + "\t" + std::to_string(precision) +
              "\t" + std::to_string(type_length) + "\n";
  std::string path = prefix.empty() ? name : prefix + "." + name;
  if (n_children == 0) {
    LeafInfo li;
    li.name = path;
    li.physical = static_cast<int32_t>(field_i64(se, kSeType, "schema type"));
    li.converted = converted;
    li.scale = scale;
    li.precision = precision;
    li.type_length = type_length;
    li.optional = repetition == 1;
    li.max_def = def;
    li.max_rep = rep;
    // a top-level REPEATED leaf (legacy 1-level list) is nested
    // too: its level entries are elements, not rows
    li.nested = !prefix.empty() || rep > 0;
    out.leaves.push_back(std::move(li));
    return;
  }
  for (int64_t c = 0; c < n_children; ++c) {
    walk_schema(elems, idx, path, def, rep, depth + 1, out);
  }
}

SchemaInfo parse_schema(Value const& fmd) {
  auto const* schema = fmd.field(kFmdSchema);
  if (schema == nullptr || schema->elems.empty()) fail("missing schema");
  auto const& root = schema->elems[0];
  int64_t n_children = field_i64_or(root, kSeNumChildren, 0);
  SchemaInfo out;
  uint64_t idx = 1;
  for (int64_t c = 0; c < n_children; ++c) {
    walk_schema(schema->elems, idx, "", 0, 0, 1, out);
  }
  if (idx != schema->elems.size()) {
    fail("schema tree longer than declared children");
  }
  return out;
}

void append_values(ColumnData& col, LeafInfo const& leaf, int width,
                   std::vector<uint8_t> const& vals,
                   std::vector<std::string> const& blobs,
                   std::vector<uint8_t> const& valid_bits, int64_t num_rows,
                   std::vector<uint32_t> const& defs,
                   std::vector<uint32_t> const& reps) {
  bool const is_ba =
      static_cast<Physical>(leaf.physical) == Physical::BYTE_ARRAY;
  bool const nested = leaf.nested;
  if (nested) {
    // Nested leaf: store COMPACT present values + the raw levels; row
    // structure is reconstructed by Dremel assembly on the Python side.
    int64_t top_rows = 0;
    for (int64_t i = 0; i < num_rows; ++i) {
      uint32_t d = defs.empty() ? static_cast<uint32_t>(leaf.max_def)
                                : defs[i];
      col.def_levels.push_back(static_cast<uint8_t>(d));
      if (leaf.max_rep > 0) {
        uint32_t r = reps.empty() ? 0 : reps[i];
        col.rep_levels.push_back(static_cast<uint8_t>(r));
        top_rows += r == 0;
      } else {
        top_rows += 1;
      }
    }
    int64_t n_present = 0;
    for (int64_t i = 0; i < num_rows; ++i) n_present += valid_bits[i];
    if (is_ba) {
      if (col.offsets.empty()) col.offsets.push_back(0);
      for (auto const& b : blobs) {
        int32_t last = col.offsets.back();
        if (static_cast<uint64_t>(last) + b.size() > INT32_MAX) {
          fail("string column exceeds 2^31 chars (reference-parity limit)");
        }
        col.chars.insert(col.chars.end(), b.begin(), b.end());
        col.offsets.push_back(last + static_cast<int32_t>(b.size()));
      }
    } else {
      col.data.insert(col.data.end(), vals.begin(),
                      vals.begin() + n_present * width);
    }
    col.num_rows += top_rows;
    col.n_levels += num_rows;
    col.n_present += n_present;
    return;
  }
  // validity bookkeeping: materialize the byte mask lazily on first null
  bool has_nulls = false;
  for (int64_t i = 0; i < num_rows; ++i) {
    if (!valid_bits[i]) { has_nulls = true; break; }
  }
  if (has_nulls || leaf.optional || !col.validity.empty()) {
    // backfill all-valid prefix for rows appended before the mask existed
    if (col.validity.size() < static_cast<size_t>(col.num_rows)) {
      col.validity.resize(col.num_rows, 1);
    }
    col.validity.insert(col.validity.end(), valid_bits.begin(),
                        valid_bits.end());
  }
  if (is_ba) {
    if (col.offsets.empty()) col.offsets.push_back(0);
    int64_t next = 0;
    for (int64_t i = 0; i < num_rows; ++i) {
      int32_t last = col.offsets.back();
      if (valid_bits[i]) {
        auto const& b = blobs[next++];
        if (static_cast<uint64_t>(last) + b.size() > INT32_MAX) {
          fail("string column exceeds 2^31 chars (reference-parity limit)");
        }
        col.chars.insert(col.chars.end(), b.begin(), b.end());
        col.offsets.push_back(last + static_cast<int32_t>(b.size()));
      } else {
        col.offsets.push_back(last);
      }
    }
  } else {
    int64_t next = 0;
    for (int64_t i = 0; i < num_rows; ++i) {
      if (valid_bits[i]) {
        col.data.insert(col.data.end(), vals.begin() + next * width,
                        vals.begin() + (next + 1) * width);
        ++next;
      } else {
        col.data.insert(col.data.end(), width, 0);
      }
    }
  }
  col.num_rows += num_rows;
  col.n_levels += num_rows;
  col.n_present += num_rows;  // flat: every row materializes a value slot
}

void decode_chunk(uint8_t const* file, uint64_t file_len, Value const& chunk,
                  LeafInfo const& leaf, ColumnData& col) {
  auto const* md = chunk.field(kCcMeta);
  if (md == nullptr) fail("column chunk without metadata");
  int32_t codec = static_cast<int32_t>(field_i64(*md, kCmCodec, "codec"));
  int64_t num_values = field_i64(*md, kCmNumValues, "num_values");
  int64_t data_off = field_i64(*md, kCmDataPageOffset, "data_page_offset");
  int64_t dict_off = field_i64_or(*md, kCmDictPageOffset, 0);
  int64_t pos = data_off;
  if (dict_off > 0 && dict_off < data_off) pos = dict_off;
  if (pos < 0 || static_cast<uint64_t>(pos) >= file_len) {
    fail("column chunk offset out of file bounds");
  }
  int const width = fixed_width_of(leaf.physical, leaf.type_length);
  Dict dict;
  bool have_dict = false;

  int64_t values_seen = 0;
  while (values_seen < num_values) {
    uint64_t consumed = 0;
    Value ph = thrift::parse_struct(file + pos, file_len - pos, &consumed);
    int32_t ptype = static_cast<int32_t>(field_i64(ph, kPhType, "page type"));
    int64_t comp_size = field_i64(ph, kPhCompressedSize, "compressed size");
    int64_t uncomp_size =
        field_i64(ph, kPhUncompressedSize, "uncompressed size");
    // Sign checks before any unsigned arithmetic: a crafted negative size
    // would wrap the bounds check below and also stall the page cursor
    // (pos would stop advancing on skipped page types).
    if (comp_size < 0 || uncomp_size < 0) fail("negative page size");
    uint64_t body = pos + consumed;
    if (body + static_cast<uint64_t>(comp_size) > file_len) {
      fail("page body past end of file");
    }

    if (ptype == kPageDict) {
      auto const* dh = ph.field(kPhDictHeader);
      if (dh == nullptr) fail("dictionary page without header");
      int64_t n = field_i64(*dh, kDphNumValues, "dict num_values");
      auto bytes = do_decompress(codec, file + body, comp_size, uncomp_size);
      dict.width = width;
      dict.size = n;
      uint64_t used = decode_plain(bytes.data(), bytes.size(), n,
                                   leaf.physical, width, &dict.fixed,
                                   &dict.blobs);
      (void)used;
      have_dict = true;
    } else if (ptype == kPageData || ptype == kPageDataV2) {
      int64_t page_values;
      int32_t enc;
      std::vector<uint32_t> defs;
      std::vector<uint8_t> bytes;   // decoded values section
      uint64_t vpos = 0;            // cursor into `bytes`

      std::vector<uint32_t> reps;
      int const def_bw = bits_for_level(leaf.max_def);
      int const rep_bw = bits_for_level(leaf.max_rep);
      if (ptype == kPageData) {
        auto const* dh = ph.field(kPhDataHeader);
        if (dh == nullptr) fail("data page without header");
        page_values = field_i64(*dh, kDphNumValues, "num_values");
        enc = static_cast<int32_t>(field_i64(*dh, kDphEncoding, "encoding"));
        bytes = do_decompress(codec, file + body, comp_size, uncomp_size);
        // v1 layout: [rep levels][def levels][values], each level run
        // length-prefixed (4 bytes LE) and RLE/bit-packed
        if (leaf.max_rep > 0) {
          int32_t renc = static_cast<int32_t>(
              field_i64_or(*dh, kDphRepLevelEncoding, kEncRle));
          if (renc != kEncRle) fail("repetition levels must be RLE-encoded");
          if (bytes.size() < vpos + 4) fail("missing rep-level length");
          uint32_t rl = read_le32(bytes.data() + vpos);
          if (vpos + 4ull + rl > bytes.size()) {
            fail("rep levels past end of page");
          }
          decode_rle_hybrid(bytes.data() + vpos + 4, rl, rep_bw,
                            page_values, reps);
          vpos += 4ull + rl;
        }
        if (leaf.max_def > 0) {
          int32_t denc = static_cast<int32_t>(
              field_i64_or(*dh, kDphDefLevelEncoding, kEncRle));
          if (denc != kEncRle) fail("definition levels must be RLE-encoded");
          if (bytes.size() < vpos + 4) fail("missing def-level length");
          uint32_t dl = read_le32(bytes.data() + vpos);
          if (vpos + 4ull + dl > bytes.size()) {
            fail("def levels past end of page");
          }
          decode_rle_hybrid(bytes.data() + vpos + 4, dl, def_bw,
                            page_values, defs);
          vpos += 4ull + dl;
        }
      } else {
        auto const* dh = ph.field(kPhDataHeaderV2);
        if (dh == nullptr) fail("data page v2 without header");
        page_values = field_i64(*dh, kDph2NumValues, "num_values");
        enc = static_cast<int32_t>(field_i64(*dh, kDph2Encoding, "encoding"));
        int64_t rep_len = field_i64_or(*dh, kDph2RepLevelsByteLen, 0);
        int64_t def_len = field_i64_or(*dh, kDph2DefLevelsByteLen, 0);
        // signed thrift i32s: a crafted negative length would pass the sum
        // bound below and wrap the unsigned cursor arithmetic
        if (rep_len < 0 || def_len < 0) fail("negative v2 level length");
        // is_compressed is a thrift BOOL (carried in Value::b, not ::i)
        auto const* ic = dh->field(kDph2IsCompressed);
        bool compressed =
            ic == nullptr || ic->b ||
            ic->type == thrift::WireType::BOOL_TRUE;
        // v2: levels are NEVER compressed, sit before the data section
        // (rep first, then def), and carry no length prefix
        if (rep_len + def_len > comp_size) {
          fail("v2 level sections longer than page");
        }
        if (leaf.max_rep > 0 && rep_len > 0) {
          decode_rle_hybrid(file + body, rep_len, rep_bw, page_values, reps);
        }
        if (leaf.max_def > 0 && def_len > 0) {
          decode_rle_hybrid(file + body + rep_len, def_len, def_bw,
                            page_values, defs);
        }
        uint64_t lvl = static_cast<uint64_t>(rep_len + def_len);
        uint64_t data_comp = comp_size - lvl;
        uint64_t data_uncomp = uncomp_size - lvl;
        if (compressed) {
          bytes = do_decompress(codec, file + body + lvl, data_comp,
                                data_uncomp);
        } else {
          bytes.assign(file + body + lvl, file + body + lvl + data_comp);
        }
        vpos = 0;
      }

      // present values: def level == max_def (flat optional: def != 0)
      std::vector<uint8_t> valid(page_values, 1);
      int64_t n_present = page_values;
      if (leaf.max_def > 0 && !defs.empty()) {
        n_present = 0;
        for (int64_t i = 0; i < page_values; ++i) {
          valid[i] =
              defs[i] == static_cast<uint32_t>(leaf.max_def) ? 1 : 0;
          n_present += valid[i];
        }
      }

      std::vector<uint8_t> vals;
      std::vector<std::string> blobs;
      if (enc == kEncPlain) {
        decode_plain(bytes.data() + vpos, bytes.size() - vpos, n_present,
                     leaf.physical, width, &vals, &blobs);
      } else if (enc == kEncPlainDict || enc == kEncRleDict) {
        if (!have_dict) fail("dictionary-encoded page before dictionary");
        if (bytes.size() - vpos < 1) fail("missing dict index bit width");
        int bw = bytes[vpos];
        std::vector<uint32_t> idx;
        decode_rle_hybrid(bytes.data() + vpos + 1, bytes.size() - vpos - 1,
                          bw, n_present, idx);
        bool const is_ba =
            static_cast<Physical>(leaf.physical) == Physical::BYTE_ARRAY;
        for (uint32_t id : idx) {
          if (static_cast<int64_t>(id) >= dict.size) {
            fail("dictionary index out of range");
          }
          if (is_ba) {
            blobs.push_back(dict.blobs[id]);
          } else {
            vals.insert(vals.end(), dict.fixed.begin() + id * width,
                        dict.fixed.begin() + (id + 1) * width);
          }
        }
      } else if (enc == kEncDeltaBinary) {
        auto phys = static_cast<Physical>(leaf.physical);
        if (phys != Physical::INT32 && phys != Physical::INT64) {
          fail("DELTA_BINARY_PACKED is only valid for INT32/INT64");
        }
        uint64_t dpos = vpos;
        auto dec = decode_delta_binary(bytes.data(), bytes.size(), &dpos);
        if (static_cast<int64_t>(dec.size()) < n_present) {
          fail("DELTA_BINARY_PACKED stream shorter than page values");
        }
        for (int64_t i = 0; i < n_present; ++i) {
          int64_t v = dec[i];
          for (int k = 0; k < width; ++k) {
            vals.push_back(static_cast<uint8_t>(v >> (8 * k)));
          }
        }
      } else if (enc == kEncDeltaLengthBA || enc == kEncDeltaBA) {
        if (static_cast<Physical>(leaf.physical) != Physical::BYTE_ARRAY) {
          fail("DELTA_*_BYTE_ARRAY is only valid for BYTE_ARRAY");
        }
        uint64_t dpos = vpos;
        blobs = enc == kEncDeltaLengthBA
                    ? decode_delta_length_ba(bytes.data(), bytes.size(), &dpos)
                    : decode_delta_ba(bytes.data(), bytes.size(), &dpos);
        if (static_cast<int64_t>(blobs.size()) < n_present) {
          fail("DELTA byte-array stream shorter than page values");
        }
        blobs.resize(n_present);
      } else {
        fail("unsupported data encoding " + std::to_string(enc) +
             " (supported: PLAIN, PLAIN_DICTIONARY, RLE_DICTIONARY, "
             "DELTA_BINARY_PACKED, DELTA_LENGTH_BYTE_ARRAY, "
             "DELTA_BYTE_ARRAY)");
      }
      append_values(col, leaf, width, vals, blobs, valid, page_values,
                    defs, reps);
      values_seen += page_values;
    } else {
      // index pages etc.: skip
    }
    pos = body + comp_size;
  }
}

Value parse_footer(uint8_t const* file, uint64_t len) {
  if (len < 12 || std::memcmp(file, "PAR1", 4) != 0 ||
      std::memcmp(file + len - 4, "PAR1", 4) != 0) {
    fail("not a Parquet file (missing PAR1 framing)");
  }
  uint32_t flen = static_cast<uint32_t>(file[len - 8]) |
                  (static_cast<uint32_t>(file[len - 7]) << 8) |
                  (static_cast<uint32_t>(file[len - 6]) << 16) |
                  (static_cast<uint32_t>(file[len - 5]) << 24);
  if (8ull + flen > len) fail("footer length larger than file");
  return thrift::parse_struct(file + len - 8 - flen, flen);
}

}  // namespace

std::vector<uint8_t> snappy_uncompress(uint8_t const* in, uint64_t n,
                                       uint64_t expected_out) {
  uint64_t pos = 0;
  uint64_t out_len = read_varint(in, n, &pos);
  if (expected_out != kSnappyNoExpectedSize && out_len != expected_out) {
    fail("snappy stream length != declared page size");
  }
  std::vector<uint8_t> out;
  out.reserve(out_len);
  while (pos < n) {
    uint8_t tag = in[pos++];
    uint32_t kind = tag & 3;
    if (kind == 0) {  // literal
      uint64_t len = (tag >> 2) + 1;
      if (len > 60) {
        uint32_t extra = static_cast<uint32_t>(len - 60);
        if (pos + extra > n) fail("snappy literal header past end");
        uint64_t l = 0;
        for (uint32_t k = 0; k < extra; ++k) {
          l |= static_cast<uint64_t>(in[pos + k]) << (8 * k);
        }
        pos += extra;
        len = l + 1;
      }
      if (pos + len > n) fail("snappy literal past end");
      out.insert(out.end(), in + pos, in + pos + len);
      pos += len;
    } else {
      uint64_t len, offset;
      if (kind == 1) {
        if (pos >= n) fail("snappy copy1 past end");
        len = ((tag >> 2) & 7) + 4;
        offset = (static_cast<uint64_t>(tag >> 5) << 8) | in[pos++];
      } else if (kind == 2) {
        if (pos + 2 > n) fail("snappy copy2 past end");
        len = (tag >> 2) + 1;
        offset = static_cast<uint64_t>(in[pos]) |
                 (static_cast<uint64_t>(in[pos + 1]) << 8);
        pos += 2;
      } else {
        if (pos + 4 > n) fail("snappy copy4 past end");
        len = (tag >> 2) + 1;
        offset = static_cast<uint64_t>(in[pos]) |
                 (static_cast<uint64_t>(in[pos + 1]) << 8) |
                 (static_cast<uint64_t>(in[pos + 2]) << 16) |
                 (static_cast<uint64_t>(in[pos + 3]) << 24);
        pos += 4;
      }
      if (offset == 0 || offset > out.size()) fail("snappy copy bad offset");
      // overlapping copies are byte-by-byte by spec
      uint64_t src = out.size() - offset;
      for (uint64_t k = 0; k < len; ++k) out.push_back(out[src + k]);
    }
  }
  if (out.size() != out_len) fail("snappy output size mismatch");
  return out;
}

std::vector<RowGroupInfo> row_group_infos(uint8_t const* file, uint64_t len) {
  Value fmd = parse_footer(file, len);
  std::vector<RowGroupInfo> out;
  auto const* rgs = fmd.field(kFmdRowGroups);
  if (rgs == nullptr) return out;
  for (auto const& rg : rgs->elems) {
    RowGroupInfo info;
    info.num_rows = field_i64_or(rg, kRgNumRows, 0);
    info.total_byte_size = field_i64_or(rg, kRgTotalCompressed,
                                        field_i64_or(rg, kRgTotalByteSize, 0));
    out.push_back(info);
  }
  return out;
}

std::vector<std::string> column_names(uint8_t const* file, uint64_t len) {
  Value fmd = parse_footer(file, len);
  std::vector<std::string> out;
  for (auto const& leaf : parse_schema(fmd).leaves) out.push_back(leaf.name);
  return out;
}

ReadResult read_file(uint8_t const* file, uint64_t len,
                     std::optional<std::vector<int32_t>> const& column_indices,
                     std::optional<std::vector<int32_t>> const& row_group_indices) {
  Value fmd = parse_footer(file, len);
  auto schema = parse_schema(fmd);
  auto& leaves = schema.leaves;
  auto const* rgs = fmd.field(kFmdRowGroups);
  uint64_t n_rgs = rgs == nullptr ? 0 : rgs->elems.size();

  std::vector<int32_t> cols;
  if (column_indices.has_value()) {
    cols = *column_indices;
  } else {
    for (uint64_t i = 0; i < leaves.size(); ++i) {
      cols.push_back(static_cast<int32_t>(i));
    }
  }
  std::vector<int32_t> groups;
  if (row_group_indices.has_value()) {
    groups = *row_group_indices;
  } else {
    for (uint64_t i = 0; i < n_rgs; ++i) {
      groups.push_back(static_cast<int32_t>(i));
    }
  }

  ReadResult res;
  res.schema_desc = schema.desc;
  for (int32_t c : cols) {
    if (c < 0 || static_cast<uint64_t>(c) >= leaves.size()) {
      fail("column index out of range");
    }
    ColumnData col;
    auto const& leaf = leaves[c];
    col.name = leaf.name;
    col.physical = leaf.physical;
    col.converted = leaf.converted;
    col.scale = leaf.scale;
    col.precision = leaf.precision;
    col.type_length = leaf.type_length;
    col.optional = leaf.optional;
    col.max_def = leaf.max_def;
    col.max_rep = leaf.max_rep;
    col.is_nested = leaf.nested;
    res.columns.push_back(std::move(col));
  }

  for (int32_t g : groups) {
    if (g < 0 || static_cast<uint64_t>(g) >= n_rgs) {
      fail("row group index out of range");
    }
    auto const& rg = rgs->elems[g];
    auto const* chunks = rg.field(kRgColumns);
    if (chunks == nullptr || chunks->elems.size() != leaves.size()) {
      fail("row group chunk count != schema leaf count");
    }
    int64_t rg_rows = field_i64_or(rg, kRgNumRows, -1);
    for (uint64_t k = 0; k < cols.size(); ++k) {
      auto& col = res.columns[k];
      int64_t before = col.num_rows;
      decode_chunk(file, len, chunks->elems[cols[k]], leaves[cols[k]], col);
      if (rg_rows >= 0 && col.num_rows - before != rg_rows) {
        fail("column " + col.name + " decoded " +
             std::to_string(col.num_rows - before) + " rows, row group has " +
             std::to_string(rg_rows));
      }
    }
    res.num_rows += rg_rows >= 0 ? rg_rows : 0;
  }

  // Columns with no nulls anywhere may still carry an all-ones validity if
  // any page allocated one; normalize "all valid" to empty.
  for (auto& col : res.columns) {
    bool all = true;
    for (uint8_t v : col.validity) {
      if (!v) { all = false; break; }
    }
    if (all) col.validity.clear();
    if (static_cast<Physical>(col.physical) == Physical::BYTE_ARRAY &&
        col.offsets.empty()) {
      col.offsets.push_back(0);
    }
  }
  return res;
}

}  // namespace parquet
}  // namespace tpudf
