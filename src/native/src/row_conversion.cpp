#include "tpudf/row_conversion.hpp"

#include <cstring>
#include <stdexcept>

namespace tpudf {
namespace rows {

namespace {
int32_t align_to(int32_t offset, int32_t alignment) {
  return (offset + alignment - 1) & ~(alignment - 1);
}
}  // namespace

Layout fixed_width_layout(std::vector<int32_t> const& sizes) {
  Layout out;
  int32_t at = 0;
  for (int32_t s : sizes) {
    // 16 = DECIMAL128 (__int128_t in the reference's generic layout,
    // row_conversion.cu:462-468): little-endian limb pair, memcpy'd
    // like every other fixed-width element; alignment = element size
    if (s != 1 && s != 2 && s != 4 && s != 8 && s != 16) {
      throw std::invalid_argument(
          "fixed-width element size must be 1/2/4/8/16");
    }
    at = align_to(at, s);
    out.start.push_back(at);
    out.size.push_back(s);
    at += s;
  }
  at += static_cast<int32_t>((sizes.size() + 7) / 8);  // validity bytes
  out.row_size = align_to(at, 8);
  return out;
}

void to_rows(uint8_t const* const* col_data, uint8_t const* const* col_valid,
             std::vector<int32_t> const& sizes, int64_t n_rows, uint8_t* out) {
  Layout const layout = fixed_width_layout(sizes);
  size_t const n_cols = sizes.size();
  int32_t const vbase =
      n_cols ? layout.start[n_cols - 1] + layout.size[n_cols - 1] : 0;
  std::memset(out, 0, static_cast<size_t>(n_rows) * layout.row_size);
  for (int64_t r = 0; r < n_rows; ++r) {
    uint8_t* row = out + r * layout.row_size;
    for (size_t c = 0; c < n_cols; ++c) {
      int32_t const w = layout.size[c];
      std::memcpy(row + layout.start[c], col_data[c] + r * w, w);
      bool const valid =
          col_valid == nullptr || col_valid[c] == nullptr || col_valid[c][r];
      if (valid) row[vbase + c / 8] |= static_cast<uint8_t>(1u << (c % 8));
    }
  }
}

void from_rows(uint8_t const* rows, int64_t n_rows,
               std::vector<int32_t> const& sizes, uint8_t* const* col_data,
               uint8_t* const* col_valid) {
  Layout const layout = fixed_width_layout(sizes);
  size_t const n_cols = sizes.size();
  int32_t const vbase =
      n_cols ? layout.start[n_cols - 1] + layout.size[n_cols - 1] : 0;
  for (int64_t r = 0; r < n_rows; ++r) {
    uint8_t const* row = rows + r * layout.row_size;
    for (size_t c = 0; c < n_cols; ++c) {
      int32_t const w = layout.size[c];
      std::memcpy(col_data[c] + r * w, row + layout.start[c], w);
      col_valid[c][r] = (row[vbase + c / 8] >> (c % 8)) & 1;
    }
  }
}

}  // namespace rows
}  // namespace tpudf
