// JNI shim — the L3' bridge for the Java surface (java/src/main/java/...).
// Same role as the reference's *Jni.cpp files: marshal handles and arrays,
// translate C++ exceptions into Java RuntimeExceptions (the reference's
// CATCH_STD contract, reference RowConversionJni.cpp:40,
// NativeParquetJni.cpp:549). Compiled only where find_package(JNI)
// succeeds (no JDK in the primary build image; the ctypes bindings cover
// the same C++ core in CI).

#include <jni.h>

#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "tpudf/parquet_footer.hpp"
#include "tpudf/row_conversion.hpp"

namespace {

void throw_java(JNIEnv* env, char const* msg) {
  jclass cls = env->FindClass("java/lang/RuntimeException");
  if (cls != nullptr) env->ThrowNew(cls, msg);
}

#define TPUDF_JNI_TRY try
#define TPUDF_JNI_CATCH(env, ret)                \
  catch (std::exception const& e) {              \
    throw_java(env, e.what());                   \
    return ret;                                  \
  }

std::vector<int32_t> to_int_vec(JNIEnv* env, jintArray arr) {
  jsize n = env->GetArrayLength(arr);
  std::vector<int32_t> out(n);
  env->GetIntArrayRegion(arr, 0, n, reinterpret_cast<jint*>(out.data()));
  return out;
}

std::vector<int64_t> to_long_vec(JNIEnv* env, jlongArray arr) {
  jsize n = env->GetArrayLength(arr);
  std::vector<int64_t> out(n);
  env->GetLongArrayRegion(arr, 0, n, reinterpret_cast<jlong*>(out.data()));
  return out;
}

}  // namespace

extern "C" {

// ---- HostMemoryBuffer -----------------------------------------------------

JNIEXPORT jlong JNICALL
Java_com_nvidia_spark_rapids_jni_HostMemoryBuffer_hostAlloc(JNIEnv*, jclass,
                                                            jlong bytes) {
  return reinterpret_cast<jlong>(std::malloc(static_cast<size_t>(bytes)));
}

JNIEXPORT void JNICALL
Java_com_nvidia_spark_rapids_jni_HostMemoryBuffer_hostFree(JNIEnv*, jclass,
                                                           jlong addr) {
  std::free(reinterpret_cast<void*>(addr));
}

JNIEXPORT void JNICALL
Java_com_nvidia_spark_rapids_jni_HostMemoryBuffer_copyIn(JNIEnv* env, jclass,
                                                         jlong addr,
                                                         jbyteArray src) {
  jsize n = env->GetArrayLength(src);
  env->GetByteArrayRegion(src, 0, n, reinterpret_cast<jbyte*>(addr));
}

JNIEXPORT jbyteArray JNICALL
Java_com_nvidia_spark_rapids_jni_HostMemoryBuffer_copyOut(JNIEnv* env, jclass,
                                                          jlong addr,
                                                          jint count) {
  jbyteArray out = env->NewByteArray(count);
  env->SetByteArrayRegion(out, 0, count, reinterpret_cast<jbyte const*>(addr));
  return out;
}

// ---- ParquetFooter --------------------------------------------------------

JNIEXPORT jlong JNICALL
Java_com_nvidia_spark_rapids_jni_ParquetFooter_readAndFilterNative(
    JNIEnv* env, jclass, jlong addr, jlong len, jlong part_offset,
    jlong part_length, jobjectArray names, jintArray num_children,
    jint parent_num_children, jboolean ignore_case) {
  TPUDF_JNI_TRY {
    auto footer = tpudf::parquet::Footer::parse(
        reinterpret_cast<uint8_t const*>(addr), static_cast<uint64_t>(len));
    std::vector<std::string> name_vec;
    jsize n = env->GetArrayLength(names);
    for (jsize i = 0; i < n; ++i) {
      auto jstr = static_cast<jstring>(env->GetObjectArrayElement(names, i));
      char const* c = env->GetStringUTFChars(jstr, nullptr);
      name_vec.emplace_back(c);
      env->ReleaseStringUTFChars(jstr, c);
      env->DeleteLocalRef(jstr);
    }
    footer.prune_columns(name_vec, to_int_vec(env, num_children),
                         parent_num_children, ignore_case == JNI_TRUE);
    if (part_length >= 0) {
      footer.filter_row_groups(part_offset, part_length);
    }
    footer.filter_columns();
    return reinterpret_cast<jlong>(
        new tpudf::parquet::Footer(std::move(footer)));
  }
  TPUDF_JNI_CATCH(env, 0)
}

JNIEXPORT jbyteArray JNICALL
Java_com_nvidia_spark_rapids_jni_ParquetFooter_serializeNative(JNIEnv* env,
                                                               jclass,
                                                               jlong handle) {
  TPUDF_JNI_TRY {
    auto* f = reinterpret_cast<tpudf::parquet::Footer*>(handle);
    std::string framed = f->serialize_framed();
    jbyteArray out = env->NewByteArray(static_cast<jsize>(framed.size()));
    env->SetByteArrayRegion(out, 0, static_cast<jsize>(framed.size()),
                            reinterpret_cast<jbyte const*>(framed.data()));
    return out;
  }
  TPUDF_JNI_CATCH(env, nullptr)
}

JNIEXPORT jlong JNICALL
Java_com_nvidia_spark_rapids_jni_ParquetFooter_numRowsNative(JNIEnv* env,
                                                             jclass,
                                                             jlong handle) {
  TPUDF_JNI_TRY {
    return reinterpret_cast<tpudf::parquet::Footer*>(handle)->num_rows();
  }
  TPUDF_JNI_CATCH(env, -1)
}

JNIEXPORT jint JNICALL
Java_com_nvidia_spark_rapids_jni_ParquetFooter_numColumnsNative(JNIEnv* env,
                                                                jclass,
                                                                jlong handle) {
  TPUDF_JNI_TRY {
    return reinterpret_cast<tpudf::parquet::Footer*>(handle)->num_columns();
  }
  TPUDF_JNI_CATCH(env, -1)
}

JNIEXPORT void JNICALL
Java_com_nvidia_spark_rapids_jni_ParquetFooter_closeNative(JNIEnv*, jclass,
                                                           jlong handle) {
  delete reinterpret_cast<tpudf::parquet::Footer*>(handle);
}

// ---- RowConversion --------------------------------------------------------

JNIEXPORT jint JNICALL
Java_com_nvidia_spark_rapids_jni_HostRowConversion_rowSizeNative(
    JNIEnv* env, jclass, jintArray sizes) {
  TPUDF_JNI_TRY {
    auto layout = tpudf::rows::fixed_width_layout(to_int_vec(env, sizes));
    return layout.row_size;
  }
  TPUDF_JNI_CATCH(env, -1)
}

JNIEXPORT void JNICALL
Java_com_nvidia_spark_rapids_jni_HostRowConversion_toRowsNative(
    JNIEnv* env, jclass, jlongArray data, jlongArray valid, jintArray sizes,
    jlong num_rows, jlong out_addr) {
  TPUDF_JNI_TRY {
    auto d = to_long_vec(env, data);
    auto v = to_long_vec(env, valid);
    std::vector<uint8_t const*> dp, vp;
    for (int64_t a : d) dp.push_back(reinterpret_cast<uint8_t const*>(a));
    for (int64_t a : v) vp.push_back(reinterpret_cast<uint8_t const*>(a));
    tpudf::rows::to_rows(dp.data(), vp.data(), to_int_vec(env, sizes),
                         num_rows, reinterpret_cast<uint8_t*>(out_addr));
    return;
  }
  TPUDF_JNI_CATCH(env, )
}

JNIEXPORT void JNICALL
Java_com_nvidia_spark_rapids_jni_HostRowConversion_fromRowsNative(
    JNIEnv* env, jclass, jlong rows_addr, jlong num_rows, jintArray sizes,
    jlongArray data, jlongArray valid) {
  TPUDF_JNI_TRY {
    auto d = to_long_vec(env, data);
    auto v = to_long_vec(env, valid);
    std::vector<uint8_t*> dp, vp;
    for (int64_t a : d) dp.push_back(reinterpret_cast<uint8_t*>(a));
    for (int64_t a : v) vp.push_back(reinterpret_cast<uint8_t*>(a));
    tpudf::rows::from_rows(reinterpret_cast<uint8_t const*>(rows_addr),
                           num_rows, to_int_vec(env, sizes), dp.data(),
                           vp.data());
    return;
  }
  TPUDF_JNI_CATCH(env, )
}
}
