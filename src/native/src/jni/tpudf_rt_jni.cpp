// JNI bindings for the device-runtime handle model: maps the ai.rapids.cudf
// Java surface (Table / ColumnVector / ColumnView / TpuRuntime) and the
// reference-signature RowConversion natives onto the tpudf_rt C ABI, which
// fronts the embedded CPython/JAX runtime (rt_bridge.cpp).
//
// Parity target: reference RowConversionJni.cpp:24-66 — jlong handles in,
// released jlong handles out, exceptions translated to Java RuntimeException
// (the CATCH_STD contract). Compiled only when both a JDK and the Python
// embed library are found.

#include <jni.h>

#include <cstdint>
#include <string>
#include <vector>

extern "C" {
int32_t tpudf_rt_init(char const* sys_path, char const* platform);
char const* tpudf_rt_last_error();
int64_t tpudf_rt_column_from_host(int32_t type_id, int32_t scale, int64_t n,
                                  uint8_t const* data, int64_t data_len,
                                  uint8_t const* validity);
int64_t tpudf_rt_table_create(int64_t const* cols, int32_t ncols);
int32_t tpudf_rt_table_num_columns(int64_t tbl);
int64_t tpudf_rt_table_num_rows(int64_t tbl);
int64_t tpudf_rt_table_column(int64_t tbl, int32_t i);
int32_t tpudf_rt_column_info(int64_t col, int32_t* type_id, int32_t* scale,
                             int64_t* num_rows);
int32_t tpudf_rt_column_to_host(int64_t col, uint8_t* data_out,
                                int64_t data_cap, uint8_t* validity_out,
                                int64_t validity_cap);
int32_t tpudf_rt_convert_to_rows(int64_t tbl, int64_t* out, int32_t cap,
                                 int32_t* n_out);
int64_t tpudf_rt_convert_from_rows(int64_t rows, int32_t const* type_ids,
                                   int32_t const* scales, int32_t ncols);
int32_t tpudf_rt_rows_info(int64_t rows, int64_t* num_rows, int64_t* row_size);
int32_t tpudf_rt_free(int64_t handle);
}

namespace {

void throw_rt(JNIEnv* env) {
  jclass cls = env->FindClass("java/lang/RuntimeException");
  if (cls != nullptr) env->ThrowNew(cls, tpudf_rt_last_error());
}

}  // namespace

extern "C" {

// ---- TpuRuntime -----------------------------------------------------------

JNIEXPORT void JNICALL Java_ai_rapids_cudf_TpuRuntime_initNative(
    JNIEnv* env, jclass, jstring sys_path, jstring platform) {
  char const* p = env->GetStringUTFChars(sys_path, nullptr);
  char const* plat = env->GetStringUTFChars(platform, nullptr);
  int32_t rc = tpudf_rt_init(p, plat);
  env->ReleaseStringUTFChars(sys_path, p);
  env->ReleaseStringUTFChars(platform, plat);
  if (rc != 0) throw_rt(env);
}

// ---- ColumnView / ColumnVector -------------------------------------------

JNIEXPORT jlong JNICALL Java_ai_rapids_cudf_ColumnView_getRowCountNative(
    JNIEnv* env, jclass, jlong handle) {
  int32_t tid = 0, scale = 0;
  int64_t n = 0;
  if (tpudf_rt_column_info(handle, &tid, &scale, &n) != 0) {
    throw_rt(env);
    return 0;
  }
  return n;
}

JNIEXPORT jint JNICALL Java_ai_rapids_cudf_ColumnView_getTypeIdNative(
    JNIEnv* env, jclass, jlong handle) {
  int32_t tid = 0, scale = 0;
  int64_t n = 0;
  if (tpudf_rt_column_info(handle, &tid, &scale, &n) != 0) {
    throw_rt(env);
    return 0;
  }
  return tid;
}

JNIEXPORT jint JNICALL Java_ai_rapids_cudf_ColumnView_getScaleNative(
    JNIEnv* env, jclass, jlong handle) {
  int32_t tid = 0, scale = 0;
  int64_t n = 0;
  if (tpudf_rt_column_info(handle, &tid, &scale, &n) != 0) {
    throw_rt(env);
    return 0;
  }
  return scale;
}

JNIEXPORT void JNICALL Java_ai_rapids_cudf_ColumnView_freeNative(
    JNIEnv*, jclass, jlong handle) {
  tpudf_rt_free(handle);
}

JNIEXPORT jlong JNICALL Java_ai_rapids_cudf_ColumnVector_fromHostNative(
    JNIEnv* env, jclass, jint type_id, jint scale, jlong rows,
    jbyteArray data, jbyteArray validity) {
  jsize data_len = env->GetArrayLength(data);
  std::vector<uint8_t> dbuf(data_len);
  env->GetByteArrayRegion(data, 0, data_len,
                          reinterpret_cast<jbyte*>(dbuf.data()));
  std::vector<uint8_t> vbuf;
  uint8_t const* vptr = nullptr;
  if (validity != nullptr) {
    vbuf.resize(env->GetArrayLength(validity));
    env->GetByteArrayRegion(validity, 0, static_cast<jsize>(vbuf.size()),
                            reinterpret_cast<jbyte*>(vbuf.data()));
    vptr = vbuf.data();
  }
  int64_t h = tpudf_rt_column_from_host(type_id, scale, rows, dbuf.data(),
                                        data_len, vptr);
  if (h < 0) throw_rt(env);
  return h;
}

JNIEXPORT void JNICALL Java_ai_rapids_cudf_ColumnVector_copyToHostNative(
    JNIEnv* env, jclass, jlong handle, jbyteArray data_out,
    jbyteArray validity_out) {
  jsize data_cap = env->GetArrayLength(data_out);
  jsize valid_cap =
      validity_out == nullptr ? 0 : env->GetArrayLength(validity_out);
  std::vector<uint8_t> dbuf(data_cap);
  std::vector<uint8_t> vbuf(valid_cap);
  if (tpudf_rt_column_to_host(handle, dbuf.data(), data_cap,
                              validity_out == nullptr ? nullptr : vbuf.data(),
                              valid_cap) != 0) {
    throw_rt(env);
    return;
  }
  env->SetByteArrayRegion(data_out, 0, data_cap,
                          reinterpret_cast<jbyte const*>(dbuf.data()));
  if (validity_out != nullptr) {
    env->SetByteArrayRegion(validity_out, 0, valid_cap,
                            reinterpret_cast<jbyte const*>(vbuf.data()));
  }
}

// ---- Table ----------------------------------------------------------------

JNIEXPORT jlong JNICALL Java_ai_rapids_cudf_Table_createTable(
    JNIEnv* env, jclass, jlongArray column_handles) {
  jsize n = env->GetArrayLength(column_handles);
  std::vector<int64_t> cols(n);
  env->GetLongArrayRegion(column_handles, 0, n,
                          reinterpret_cast<jlong*>(cols.data()));
  int64_t h = tpudf_rt_table_create(cols.data(), n);
  if (h < 0) throw_rt(env);
  return h;
}

JNIEXPORT jlong JNICALL Java_ai_rapids_cudf_Table_getRowCountNative(
    JNIEnv* env, jclass, jlong handle) {
  int64_t n = tpudf_rt_table_num_rows(handle);
  if (n < 0) throw_rt(env);
  return n;
}

JNIEXPORT void JNICALL Java_ai_rapids_cudf_Table_freeNative(
    JNIEnv*, jclass, jlong handle) {
  tpudf_rt_free(handle);
}

// ---- RowConversion (reference signatures) ---------------------------------

JNIEXPORT jlongArray JNICALL
Java_com_nvidia_spark_rapids_jni_RowConversion_convertToRows(
    JNIEnv* env, jclass, jlong table_handle) {
  int64_t batches[64];
  int32_t n = 0;
  if (tpudf_rt_convert_to_rows(table_handle, batches, 64, &n) != 0) {
    throw_rt(env);
    return nullptr;
  }
  jlongArray out = env->NewLongArray(n);
  env->SetLongArrayRegion(out, 0, n, reinterpret_cast<jlong*>(batches));
  return out;
}

JNIEXPORT jlongArray JNICALL
Java_com_nvidia_spark_rapids_jni_RowConversion_convertFromRows(
    JNIEnv* env, jclass, jlong rows_handle, jintArray types,
    jintArray scales) {
  jsize n = env->GetArrayLength(types);
  std::vector<int32_t> tvec(n), svec(n);
  env->GetIntArrayRegion(types, 0, n, reinterpret_cast<jint*>(tvec.data()));
  env->GetIntArrayRegion(scales, 0, n, reinterpret_cast<jint*>(svec.data()));
  int64_t tbl = tpudf_rt_convert_from_rows(rows_handle, tvec.data(),
                                           svec.data(), n);
  if (tbl < 0) {
    throw_rt(env);
    return nullptr;
  }
  // release the table's columns to the caller (reference convention: the
  // Java side wraps the returned handles in `new Table(handles)`)
  std::vector<int64_t> cols(n);
  for (jsize i = 0; i < n; ++i) {
    cols[i] = tpudf_rt_table_column(tbl, i);
    if (cols[i] < 0) {
      for (jsize j = 0; j < i; ++j) tpudf_rt_free(cols[j]);
      tpudf_rt_free(tbl);
      throw_rt(env);
      return nullptr;
    }
  }
  tpudf_rt_free(tbl);
  jlongArray out = env->NewLongArray(n);
  env->SetLongArrayRegion(out, 0, n, reinterpret_cast<jlong*>(cols.data()));
  return out;
}

}  // extern "C"
