// Intentionally empty translation unit. Role parity with the reference's
// emptyfile.cpp (reference src/main/cpp/src/emptyfile.cpp, used at
// CMakeLists.txt:189-195): stub shared libraries built from this file do
// nothing except dynamically link the real engine, so consumers that load
// the old library names keep working (the reference ships a fat lib
// deliberately NAMED libcudf.so plus libcudfjni.so stubs for drop-in
// compatibility with the cudf Java bindings).
