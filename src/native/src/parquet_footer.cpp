#include "tpudf/parquet_footer.hpp"

#include <clocale>
#include <cwctype>
#include <locale.h>

#include <map>
#include <stdexcept>

namespace tpudf {
namespace parquet {

using thrift::Value;
using thrift::WireType;

namespace {

// Full-range code-point lowering via towlower_l pinned to a UTF-8 locale
// (deterministic regardless of the process LC_CTYPE, unlike the
// reference's bare towlower after mbstowcs — same mapping table, no
// locale surprise). Falls back to identity above ASCII only if the image
// has no UTF-8 locale at all.
wint_t lower_code_point(wint_t cp) {
  static locale_t loc = [] {
    locale_t l = newlocale(LC_CTYPE_MASK, "C.UTF-8", (locale_t)0);
    if (!l) l = newlocale(LC_CTYPE_MASK, "en_US.UTF-8", (locale_t)0);
    return l;
  }();
  if (loc) return towlower_l(cp, loc);
  // no UTF-8 locale in the image: keep at least the ASCII + Latin-1
  // floor the pre-locale implementation guaranteed (U+00D7 is the
  // multiplication sign, not a letter)
  if (cp < 0x80) return towlower(cp);
  if (cp >= 0xC0 && cp <= 0xDE && cp != 0xD7) return cp + 0x20;
  return cp;
}

}  // namespace

std::string utf8_to_lower(std::string const& in) {
  std::string out;
  out.reserve(in.size());
  size_t i = 0;
  while (i < in.size()) {
    unsigned char c = in[i];
    if (c < 0x80) {
      out.push_back(c >= 'A' && c <= 'Z' ? c + 32 : c);
      ++i;
      continue;
    }
    // Decode one UTF-8 sequence.
    uint32_t cp = 0;
    int extra = 0;
    if ((c & 0xE0) == 0xC0) {
      cp = c & 0x1F;
      extra = 1;
    } else if ((c & 0xF0) == 0xE0) {
      cp = c & 0x0F;
      extra = 2;
    } else if ((c & 0xF8) == 0xF0) {
      cp = c & 0x07;
      extra = 3;
    } else {
      throw std::invalid_argument("invalid character sequence");
    }
    if (i + extra >= in.size()) {
      throw std::invalid_argument("invalid character sequence");
    }
    for (int k = 1; k <= extra; ++k) {
      unsigned char cc = in[i + k];
      if ((cc & 0xC0) != 0x80) {
        throw std::invalid_argument("invalid character sequence");
      }
      cp = (cp << 6) | (cc & 0x3F);
    }
    i += extra + 1;
    // Full wide-char-range simple lowering — the reference's
    // unicode_to_lower goes through towlower for every code point
    // (NativeParquetJni.cpp:45-77), so Greek/Cyrillic/etc column names
    // case-fold identically under case-insensitive matching.
    cp = static_cast<uint32_t>(lower_code_point(static_cast<wint_t>(cp)));
    // Re-encode.
    if (cp < 0x80) {
      out.push_back(static_cast<char>(cp));
    } else if (cp < 0x800) {
      out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else {
      out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    }
  }
  return out;
}

Footer Footer::parse(uint8_t const* buf, uint64_t len) {
  return Footer(thrift::parse_struct(buf, len));
}

namespace {

// The requested-column tree, built depth-first from the JNI-shaped
// (names, num_children) request. s_id numbers nodes in request depth-first
// order (root = 0); c_id numbers leaves only.
struct RequestNode {
  std::map<std::string, RequestNode> children;
  int s_id = 0;
  int c_id = -1;
};

RequestNode build_request_tree(std::vector<std::string> const& names,
                               std::vector<int32_t> const& num_children,
                               int32_t parent_num_children) {
  RequestNode root;
  if (parent_num_children == 0) return root;
  if (names.size() != num_children.size()) {
    throw std::invalid_argument("names and num_children length mismatch");
  }
  int next_s = 0;
  int next_c = -1;
  std::vector<RequestNode*> stack{&root};
  std::vector<int32_t> remaining{parent_num_children};
  for (size_t k = 0; k < names.size(); ++k) {
    if (stack.empty()) {
      throw std::invalid_argument("request tree: too many entries");
    }
    ++next_s;
    RequestNode node;
    node.s_id = next_s;
    if (num_children[k] == 0) node.c_id = ++next_c;
    auto [it, _] = stack.back()->children.try_emplace(names[k], node);
    if (num_children[k] > 0) {
      stack.push_back(&it->second);
      remaining.push_back(num_children[k]);
    } else {
      while (!stack.empty() && --remaining.back() == 0) {
        stack.pop_back();
        remaining.pop_back();
      }
    }
  }
  if (!stack.empty()) {
    throw std::invalid_argument("request tree: not enough entries");
  }
  return root;
}

struct PruneMaps {
  std::vector<int> schema_gather;       // output schema pos -> input index
  std::vector<int> schema_num_children; // new num_children per output pos
  std::vector<int> chunk_gather;        // output chunk pos -> input leaf idx
};

// One pass over the flattened file schema, matching against the request
// tree. Same observable semantics as the reference's column_pruner
// (NativeParquetJni.cpp:122-303): missing requested columns leave gaps
// that are compressed out by the ordered maps.
PruneMaps compute_prune_maps(Value const& schema_list, RequestNode& request,
                             bool ignore_case) {
  auto const& elems = schema_list.elems;
  if (elems.empty()) {
    throw std::invalid_argument("a root schema element must exist");
  }
  std::map<int, int> schema_map;        // s_id -> input schema index
  std::map<int, int> num_children_map;  // s_id -> new num_children
  std::map<int, int> chunk_map;         // c_id -> input leaf index
  schema_map[0] = 0;
  num_children_map[0] = 0;

  std::vector<RequestNode*> stack{&request};
  Value const* root_nc = elems[0].field(fid::kSeNumChildren);
  std::vector<int64_t> remaining{root_nc ? root_nc->i : 0};

  int chunk_index = 0;
  for (size_t idx = 1; idx < elems.size() && !stack.empty(); ++idx) {
    Value const& se = elems[idx];
    Value const* name_f = se.field(fid::kSeName);
    std::string name = name_f ? name_f->bin : std::string();
    if (ignore_case) name = utf8_to_lower(name);
    Value const* nc_f = se.field(fid::kSeNumChildren);
    int64_t n_children = nc_f ? nc_f->i : 0;
    bool is_leaf = se.field(fid::kSeType) != nullptr;

    RequestNode* found = nullptr;
    if (stack.back() != nullptr) {
      auto it = stack.back()->children.find(name);
      if (it != stack.back()->children.end()) {
        found = &it->second;
        ++num_children_map[stack.back()->s_id];
        schema_map[found->s_id] = static_cast<int>(idx);
        num_children_map[found->s_id] = 0;
      }
    }
    if (is_leaf) {
      if (found != nullptr) chunk_map[found->c_id] = chunk_index;
      ++chunk_index;
    }
    if (n_children > 0) {
      stack.push_back(found);
      remaining.push_back(n_children);
    } else {
      while (!stack.empty() && --remaining.back() == 0) {
        stack.pop_back();
        remaining.pop_back();
      }
    }
  }

  PruneMaps maps;
  for (auto const& [_, v] : schema_map) maps.schema_gather.push_back(v);
  for (auto const& [_, v] : num_children_map) {
    maps.schema_num_children.push_back(v);
  }
  for (auto const& [_, v] : chunk_map) maps.chunk_gather.push_back(v);
  return maps;
}

int64_t chunk_start_offset(Value const& chunk) {
  Value const* md = chunk.field(fid::kCcMetaData);
  if (md == nullptr) return 0;
  Value const* data_off = md->field(fid::kCmDataPageOffset);
  int64_t offset = data_off ? data_off->i : 0;
  Value const* dict_off = md->field(fid::kCmDictionaryPageOffset);
  if (dict_off != nullptr && offset > dict_off->i) offset = dict_off->i;
  return offset;
}

}  // namespace

void Footer::prune_columns(std::vector<std::string> const& names,
                           std::vector<int32_t> const& num_children,
                           int32_t parent_num_children, bool ignore_case) {
  Value* schema = meta_.field(fid::kSchema);
  if (schema == nullptr || schema->type != WireType::LIST) {
    throw std::invalid_argument("footer has no schema list");
  }
  RequestNode request =
      build_request_tree(names, num_children, parent_num_children);
  PruneMaps maps = compute_prune_maps(*schema, request, ignore_case);

  // Gather the schema, rewriting num_children where the element carries it
  // (leaves without the field stay without it, like the reference, whose
  // plain member assignment does not flip thrift's __isset flag).
  std::vector<Value> new_schema;
  new_schema.reserve(maps.schema_gather.size());
  for (size_t out = 0; out < maps.schema_gather.size(); ++out) {
    Value se = schema->elems[maps.schema_gather[out]];
    if (Value* nc = se.field(fid::kSeNumChildren)) {
      nc->i = maps.schema_num_children[out];
    }
    new_schema.push_back(std::move(se));
  }
  schema->elems = std::move(new_schema);

  // Gather column_orders by leaf position.
  if (Value* orders = meta_.field(fid::kColumnOrders)) {
    std::vector<Value> new_orders;
    new_orders.reserve(maps.chunk_gather.size());
    for (int src : maps.chunk_gather) {
      if (src < 0 || static_cast<size_t>(src) >= orders->elems.size()) continue;
      new_orders.push_back(orders->elems[src]);
    }
    orders->elems = std::move(new_orders);
  }

  chunk_gather_ = std::move(maps.chunk_gather);
  pruned_ = true;
}

void Footer::filter_columns() {
  if (!pruned_) {
    throw std::logic_error("filter_columns requires prune_columns first");
  }
  Value* groups = meta_.field(fid::kRowGroups);
  if (groups == nullptr) return;
  for (Value& rg : groups->elems) {
    Value* cols = rg.field(fid::kRgColumns);
    if (cols == nullptr) continue;
    std::vector<Value> new_cols;
    new_cols.reserve(chunk_gather_.size());
    for (int src : chunk_gather_) {
      if (src < 0 || static_cast<size_t>(src) >= cols->elems.size()) {
        throw std::out_of_range("chunk index outside row group columns");
      }
      new_cols.push_back(cols->elems[src]);
    }
    cols->elems = std::move(new_cols);
  }
}

void Footer::filter_row_groups(int64_t part_offset, int64_t part_length) {
  if (part_length < 0) return;  // reference gate: NativeParquetJni.cpp:542
  Value* groups = meta_.field(fid::kRowGroups);
  if (groups == nullptr || groups->elems.empty()) return;

  // PARQUET-2078: only the first row group's file_offset is trustworthy;
  // if the first chunk carries metadata, use page offsets instead.
  Value const& first_chunk0 = [&]() -> Value const& {
    Value const* cols = groups->elems[0].field(fid::kRgColumns);
    if (cols == nullptr || cols->elems.empty()) {
      throw std::invalid_argument("row group has no columns");
    }
    return cols->elems[0];
  }();
  bool use_chunk_meta = first_chunk0.field(fid::kCcMetaData) != nullptr;

  int64_t prev_start = 0;
  int64_t prev_compressed = 0;
  std::vector<Value> kept;
  for (Value& rg : groups->elems) {
    int64_t start;
    if (use_chunk_meta) {
      Value const* cols = rg.field(fid::kRgColumns);
      if (cols == nullptr || cols->elems.empty()) {
        throw std::invalid_argument("row group has no columns");
      }
      start = chunk_start_offset(cols->elems[0]);
    } else {
      Value const* fo = rg.field(fid::kRgFileOffset);
      start = fo ? fo->i : 0;
      bool invalid = prev_start == 0
                         ? start != 4
                         : start < prev_start + prev_compressed;
      if (invalid) {
        // first group always starts at 4 (after the PAR1 magic); later
        // groups fall back to the previous end (imprecise under padding
        // but fine for midpoint filtering)
        start = prev_start == 0 ? 4 : prev_start + prev_compressed;
      }
      prev_start = start;
      Value const* tcs = rg.field(fid::kRgTotalCompressedSize);
      prev_compressed = tcs ? tcs->i : 0;
    }

    int64_t total_size = 0;
    if (Value const* tcs = rg.field(fid::kRgTotalCompressedSize)) {
      total_size = tcs->i;
    } else if (Value const* cols = rg.field(fid::kRgColumns)) {
      for (Value const& cc : cols->elems) {
        if (Value const* md = cc.field(fid::kCcMetaData)) {
          if (Value const* sz = md->field(fid::kCmTotalCompressedSize)) {
            total_size += sz->i;
          }
        }
      }
    }

    int64_t mid_point = start + total_size / 2;
    if (mid_point >= part_offset && mid_point < part_offset + part_length) {
      kept.push_back(std::move(rg));
    }
  }
  groups->elems = std::move(kept);
}

int64_t Footer::num_rows() const {
  Value const* groups = meta_.field(fid::kRowGroups);
  if (groups == nullptr) return 0;
  int64_t total = 0;
  for (Value const& rg : groups->elems) {
    if (Value const* n = rg.field(fid::kRgNumRows)) total += n->i;
  }
  return total;
}

int32_t Footer::num_columns() const {
  Value const* schema = meta_.field(fid::kSchema);
  if (schema == nullptr || schema->elems.empty()) return 0;
  Value const* nc = schema->elems[0].field(fid::kSeNumChildren);
  return nc ? static_cast<int32_t>(nc->i) : 0;
}

std::string Footer::serialize_framed() const {
  std::string body = thrift::serialize_struct(meta_);
  std::string out;
  out.reserve(body.size() + 12);
  out.append("PAR1");
  out.append(body);
  uint32_t n = static_cast<uint32_t>(body.size());
  for (int k = 0; k < 4; ++k) {
    out.push_back(static_cast<char>((n >> (8 * k)) & 0xFF));
  }
  out.append("PAR1");
  return out;
}

}  // namespace parquet
}  // namespace tpudf
