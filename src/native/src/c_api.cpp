// C ABI for the native core — the bridge layer (L3') that plays the role of
// the reference's JNI files. Objects cross the boundary as opaque int64
// handles exactly like the reference's jlong pointer-handles
// (RowConversionJni.cpp:31-36, NativeParquetJni.cpp:547), but routed
// through a registry so stale handles fail cleanly instead of crashing.
// Errors follow the reference's CATCH_STD shape (NativeParquetJni.cpp:549):
// every entry point catches, stores a message, returns a sentinel; callers
// fetch the message via tpudf_last_error().
//
// Consumed by ctypes (spark_rapids_jni_tpu.runtime.native) and by the JNI
// shim (java/ bridge, built only where a JDK exists).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <optional>
#include <string>
#include <unordered_map>
#include <vector>

#include "tpudf/get_json_object.hpp"
#include "tpudf/mapped_file.hpp"
#include "tpudf/orc_reader.hpp"
#include "tpudf/parquet_footer.hpp"
#include "tpudf/parquet_reader.hpp"
#include "tpudf/row_conversion.hpp"

namespace {

thread_local std::string g_last_error;

void set_error(std::string msg) { g_last_error = std::move(msg); }

// Generic handle registry: int64 ids -> owned objects. ids start at 1; 0 is
// the null/error sentinel (matching the reference returning 0 on failure).
// Lookups hand out shared_ptr so a concurrent close (e.g. Python GC calling
// __del__ on another thread while ctypes has released the GIL) cannot free
// an object mid-use — the last owner wins.
template <class T>
class Registry {
 public:
  int64_t put(std::shared_ptr<T> obj) {
    std::lock_guard<std::mutex> lock(mu_);
    int64_t id = next_++;
    map_[id] = std::move(obj);
    return id;
  }

  std::shared_ptr<T> get(int64_t id) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = map_.find(id);
    return it == map_.end() ? nullptr : it->second;
  }

  bool erase(int64_t id) {
    std::lock_guard<std::mutex> lock(mu_);
    return map_.erase(id) > 0;
  }

  int64_t size() {
    std::lock_guard<std::mutex> lock(mu_);
    return static_cast<int64_t>(map_.size());
  }

 private:
  std::mutex mu_;
  std::unordered_map<int64_t, std::shared_ptr<T>> map_;
  int64_t next_ = 1;
};

Registry<tpudf::parquet::Footer>& footers() {
  static Registry<tpudf::parquet::Footer> r;
  return r;
}

Registry<tpudf::parquet::ReadResult>& reads() {
  static Registry<tpudf::parquet::ReadResult> r;
  return r;
}

Registry<tpudf::orc::OrcResult>& orc_reads() {
  static Registry<tpudf::orc::OrcResult> r;
  return r;
}

}  // namespace

extern "C" {

char const* tpudf_last_error() { return g_last_error.c_str(); }

// Parse + prune + filter in one call, mirroring the readAndFilter JNI entry
// (reference NativeParquetJni.cpp:499-550). Returns a footer handle, 0 on
// error.
int64_t tpudf_footer_read_and_filter(uint8_t const* buf, uint64_t len,
                                     int64_t part_offset, int64_t part_length,
                                     char const* const* names,
                                     int32_t const* num_children,
                                     int32_t n_names,
                                     int32_t parent_num_children,
                                     int32_t ignore_case) {
  try {
    auto footer = std::make_shared<tpudf::parquet::Footer>(
        tpudf::parquet::Footer::parse(buf, len));
    std::vector<std::string> name_vec;
    std::vector<int32_t> child_vec;
    name_vec.reserve(n_names);
    child_vec.reserve(n_names);
    for (int32_t k = 0; k < n_names; ++k) {
      name_vec.emplace_back(names[k]);
      child_vec.push_back(num_children[k]);
    }
    // Order matters: the midpoint filter reads the file's first column, so
    // row-group filtering runs between schema pruning and chunk gathering
    // (reference NativeParquetJni.cpp:524-545).
    footer->prune_columns(name_vec, child_vec, parent_num_children,
                          ignore_case != 0);
    if (part_length >= 0) {
      footer->filter_row_groups(part_offset, part_length);
    }
    footer->filter_columns();
    return footers().put(std::move(footer));
  } catch (std::exception const& e) {
    set_error(e.what());
    return 0;
  }
}

int64_t tpudf_footer_num_rows(int64_t handle) {
  try {
    auto f = footers().get(handle);
    if (f == nullptr) throw std::invalid_argument("invalid footer handle");
    return f->num_rows();
  } catch (std::exception const& e) {
    set_error(e.what());
    return -1;
  }
}

int32_t tpudf_footer_num_columns(int64_t handle) {
  try {
    auto f = footers().get(handle);
    if (f == nullptr) throw std::invalid_argument("invalid footer handle");
    return f->num_columns();
  } catch (std::exception const& e) {
    set_error(e.what());
    return -1;
  }
}

// Serialize with PAR1 framing into a malloc'd buffer the caller frees with
// tpudf_free_buffer. Returns 0 on success.
int32_t tpudf_footer_serialize(int64_t handle, uint8_t** out,
                               uint64_t* out_len) {
  try {
    auto f = footers().get(handle);
    if (f == nullptr) throw std::invalid_argument("invalid footer handle");
    std::string framed = f->serialize_framed();
    *out = static_cast<uint8_t*>(std::malloc(framed.size()));
    if (*out == nullptr) throw std::bad_alloc();
    std::memcpy(*out, framed.data(), framed.size());
    *out_len = framed.size();
    return 0;
  } catch (std::exception const& e) {
    set_error(e.what());
    return -1;
  }
}

void tpudf_free_buffer(uint8_t* buf) { std::free(buf); }

int32_t tpudf_footer_close(int64_t handle) {
  if (!footers().erase(handle)) {
    set_error("invalid footer handle");
    return -1;
  }
  return 0;
}

// ---- Parquet data reader (chunked at row-group granularity) ---------------

// Decode selected columns / row groups of an in-memory Parquet file into an
// Arrow-layout host result. A null cols/rgs pointer selects all; a non-null
// pointer with count 0 selects none. Returns a read handle, 0 on error.
int64_t tpudf_parquet_read(uint8_t const* buf, uint64_t len,
                           int32_t const* cols, int32_t n_cols,
                           int32_t const* rgs, int32_t n_rgs) {
  try {
    std::optional<std::vector<int32_t>> col_vec;
    if (cols != nullptr) col_vec.emplace(cols, cols + n_cols);
    std::optional<std::vector<int32_t>> rg_vec;
    if (rgs != nullptr) rg_vec.emplace(rgs, rgs + n_rgs);
    auto res = std::make_shared<tpudf::parquet::ReadResult>(
        tpudf::parquet::read_file(buf, len, col_vec, rg_vec));
    return reads().put(std::move(res));
  } catch (std::exception const& e) {
    set_error(e.what());
    return 0;
  }
}

// Storage->decode path without host-visible materialization: mmap the file
// read-only and decode selected columns/row groups straight out of the
// mapping — the cuFile/GDS role (reference CMakeLists.txt:200-222: a direct
// storage->device staging path that bypasses caller-managed buffers). The
// page cursor touches only the byte ranges of the requested chunks, so a
// chunked read of a large file never faults in the rest.
int64_t tpudf_parquet_read_path(char const* path, int32_t const* cols,
                                int32_t n_cols, int32_t const* rgs,
                                int32_t n_rgs) {
  try {
    tpudf::MappedFile map(path);  // RAII mmap; throws with errno detail
    std::optional<std::vector<int32_t>> col_vec;
    if (cols != nullptr) col_vec.emplace(cols, cols + n_cols);
    std::optional<std::vector<int32_t>> rg_vec;
    if (rgs != nullptr) rg_vec.emplace(rgs, rgs + n_rgs);
    auto res = std::make_shared<tpudf::parquet::ReadResult>(
        tpudf::parquet::read_file(map.data(), map.size(), col_vec, rg_vec));
    return reads().put(std::move(res));
  } catch (std::exception const& e) {
    set_error(e.what());
    return 0;
  }
}

// Row-group probe over a file path (mmap; footer pages only are touched).
int32_t tpudf_parquet_row_groups_path(char const* path, int64_t* num_rows,
                                      int64_t* byte_size, int32_t cap) {
  try {
    tpudf::MappedFile map(path);
    auto infos = tpudf::parquet::row_group_infos(map.data(), map.size());
    for (int32_t i = 0; i < cap && i < static_cast<int32_t>(infos.size());
         ++i) {
      num_rows[i] = infos[i].num_rows;
      byte_size[i] = infos[i].total_byte_size;
    }
    return static_cast<int32_t>(infos.size());
  } catch (std::exception const& e) {
    set_error(e.what());
    return -1;
  }
}

// Footer probes for planning chunked reads: fills num_rows/byte_size pairs
// for up to `cap` row groups; returns the total count, -1 on error.
int32_t tpudf_parquet_row_groups(uint8_t const* buf, uint64_t len,
                                 int64_t* num_rows, int64_t* byte_size,
                                 int32_t cap) {
  try {
    auto infos = tpudf::parquet::row_group_infos(buf, len);
    for (int32_t i = 0; i < cap && i < static_cast<int32_t>(infos.size());
         ++i) {
      num_rows[i] = infos[i].num_rows;
      byte_size[i] = infos[i].total_byte_size;
    }
    return static_cast<int32_t>(infos.size());
  } catch (std::exception const& e) {
    set_error(e.what());
    return -1;
  }
}

int64_t tpudf_read_num_rows(int64_t handle) {
  auto r = reads().get(handle);
  if (r == nullptr) {
    set_error("invalid read handle");
    return -1;
  }
  return r->num_rows;
}

int32_t tpudf_read_num_columns(int64_t handle) {
  auto r = reads().get(handle);
  if (r == nullptr) {
    set_error("invalid read handle");
    return -1;
  }
  return static_cast<int32_t>(r->columns.size());
}

// Column metadata: meta = [physical, converted, scale, precision,
// type_length, optional, has_validity] (7 int32s); sizes = [data_bytes,
// chars_bytes, num_rows] (3 int64s). Returns 0 on success.
int32_t tpudf_read_col_meta(int64_t handle, int32_t i, int32_t* meta,
                            int64_t* sizes) {
  auto r = reads().get(handle);
  if (r == nullptr || i < 0 || i >= static_cast<int32_t>(r->columns.size())) {
    set_error("invalid read handle or column index");
    return -1;
  }
  auto const& c = r->columns[i];
  meta[0] = c.physical;
  meta[1] = c.converted;
  meta[2] = c.scale;
  meta[3] = c.precision;
  meta[4] = c.type_length;
  meta[5] = c.optional ? 1 : 0;
  meta[6] = c.validity.empty() ? 0 : 1;
  sizes[0] = static_cast<int64_t>(c.data.size());
  sizes[1] = static_cast<int64_t>(c.chars.size());
  sizes[2] = c.num_rows;
  return 0;
}

// Extended metadata (nested-aware): meta = [physical, converted, scale,
// precision, type_length, optional, has_validity, max_def, max_rep,
// reserved] (10 int32s); sizes = [data_bytes, chars_bytes, num_rows,
// n_levels, n_present] (5 int64s). num_rows counts TOP-LEVEL rows; nested
// leaves carry compact values (n_present) plus n_levels def/rep entries.
int32_t tpudf_read_col_meta2(int64_t handle, int32_t i, int32_t* meta,
                             int64_t* sizes) {
  auto r = reads().get(handle);
  if (r == nullptr || i < 0 || i >= static_cast<int32_t>(r->columns.size())) {
    set_error("invalid read handle or column index");
    return -1;
  }
  auto const& c = r->columns[i];
  meta[0] = c.physical;
  meta[1] = c.converted;
  meta[2] = c.scale;
  meta[3] = c.precision;
  meta[4] = c.type_length;
  meta[5] = c.optional ? 1 : 0;
  meta[6] = c.validity.empty() ? 0 : 1;
  meta[7] = c.max_def;
  meta[8] = c.max_rep;
  meta[9] = c.is_nested ? 1 : 0;
  sizes[0] = static_cast<int64_t>(c.data.size());
  sizes[1] = static_cast<int64_t>(c.chars.size());
  sizes[2] = c.num_rows;
  sizes[3] = c.n_levels;
  sizes[4] = c.n_present;
  return 0;
}

// Copy out a nested leaf's levels: def_out = uint8[n_levels], rep_out =
// uint8[n_levels] (may be null; required only when max_rep > 0).
int32_t tpudf_read_col_levels(int64_t handle, int32_t i, uint8_t* def_out,
                              uint8_t* rep_out) {
  auto r = reads().get(handle);
  if (r == nullptr || i < 0 || i >= static_cast<int32_t>(r->columns.size())) {
    set_error("invalid read handle or column index");
    return -1;
  }
  auto const& c = r->columns[i];
  if (def_out != nullptr && !c.def_levels.empty()) {
    std::memcpy(def_out, c.def_levels.data(), c.def_levels.size());
  }
  if (rep_out != nullptr && !c.rep_levels.empty()) {
    std::memcpy(rep_out, c.rep_levels.data(), c.rep_levels.size());
  }
  return 0;
}

// Preorder schema-tree dump for nested assembly (tab-separated lines; see
// parquet_reader.hpp). Thread-local copy, valid until this thread's next
// call.
char const* tpudf_read_schema_desc(int64_t handle) {
  thread_local std::string desc_buf;
  auto r = reads().get(handle);
  if (r == nullptr) {
    set_error("invalid read handle");
    return nullptr;
  }
  desc_buf = r->schema_desc;
  return desc_buf.c_str();
}

// Pointer to the column's name (NUL-terminated). The string is copied into
// thread-local storage so a concurrent tpudf_read_close on another thread
// cannot free it out from under the caller — valid until this thread's next
// tpudf_read_col_name call.
char const* tpudf_read_col_name(int64_t handle, int32_t i) {
  thread_local std::string name_buf;
  auto r = reads().get(handle);
  if (r == nullptr || i < 0 || i >= static_cast<int32_t>(r->columns.size())) {
    set_error("invalid read handle or column index");
    return nullptr;
  }
  name_buf = r->columns[i].name;
  return name_buf.c_str();
}

// Copy out column buffers; any destination may be null to skip it.
// data: fixed-width payload; offsets: int32[num_rows+1] (BYTE_ARRAY only);
// chars: string payload; validity: uint8[num_rows]. Returns 0 on success.
int32_t tpudf_read_col_copy(int64_t handle, int32_t i, uint8_t* data,
                            int32_t* offsets, uint8_t* chars,
                            uint8_t* validity) {
  auto r = reads().get(handle);
  if (r == nullptr || i < 0 || i >= static_cast<int32_t>(r->columns.size())) {
    set_error("invalid read handle or column index");
    return -1;
  }
  auto const& c = r->columns[i];
  if (data != nullptr && !c.data.empty()) {
    std::memcpy(data, c.data.data(), c.data.size());
  }
  if (offsets != nullptr && !c.offsets.empty()) {
    std::memcpy(offsets, c.offsets.data(), c.offsets.size() * sizeof(int32_t));
  }
  if (chars != nullptr && !c.chars.empty()) {
    std::memcpy(chars, c.chars.data(), c.chars.size());
  }
  if (validity != nullptr && !c.validity.empty()) {
    std::memcpy(validity, c.validity.data(), c.validity.size());
  }
  return 0;
}

int32_t tpudf_read_close(int64_t handle) {
  if (!reads().erase(handle)) {
    set_error("invalid read handle");
    return -1;
  }
  return 0;
}

// ---- ORC reader (chunked at stripe granularity) ---------------------------

int64_t tpudf_orc_read(uint8_t const* buf, uint64_t len, int32_t const* cols,
                       int32_t n_cols, int32_t const* stripes,
                       int32_t n_stripes) {
  try {
    std::optional<std::vector<int32_t>> col_vec;
    if (cols != nullptr) col_vec.emplace(cols, cols + n_cols);
    std::optional<std::vector<int32_t>> st_vec;
    if (stripes != nullptr) st_vec.emplace(stripes, stripes + n_stripes);
    auto res = std::make_shared<tpudf::orc::OrcResult>(
        tpudf::orc::read_file(buf, len, col_vec, st_vec));
    return orc_reads().put(std::move(res));
  } catch (std::exception const& e) {
    set_error(e.what());
    return 0;
  }
}

// ORC half of the mmap storage route (cuFile/GDS role, mirroring
// tpudf_parquet_read_path): decode straight out of a read-only mapping —
// stripe-selective chunked reads fault in only the selected byte ranges.
int64_t tpudf_orc_read_path(char const* path, int32_t const* cols,
                            int32_t n_cols, int32_t const* stripes,
                            int32_t n_stripes) {
  try {
    tpudf::MappedFile map(path);
    std::optional<std::vector<int32_t>> col_vec;
    if (cols != nullptr) col_vec.emplace(cols, cols + n_cols);
    std::optional<std::vector<int32_t>> st_vec;
    if (stripes != nullptr) st_vec.emplace(stripes, stripes + n_stripes);
    auto res = std::make_shared<tpudf::orc::OrcResult>(
        tpudf::orc::read_file(map.data(), map.size(), col_vec, st_vec));
    return orc_reads().put(std::move(res));
  } catch (std::exception const& e) {
    set_error(e.what());
    return 0;
  }
}

// Stripe probe over a file path (mmap; tail pages only are touched).
int32_t tpudf_orc_stripes_path(char const* path, int64_t* num_rows,
                               int64_t* byte_size, int32_t cap) {
  try {
    tpudf::MappedFile map(path);
    auto infos = tpudf::orc::stripe_infos(map.data(), map.size());
    for (int32_t i = 0; i < cap && i < static_cast<int32_t>(infos.size());
         ++i) {
      num_rows[i] = infos[i].num_rows;
      byte_size[i] = infos[i].data_bytes;
    }
    return static_cast<int32_t>(infos.size());
  } catch (std::exception const& e) {
    set_error(e.what());
    return -1;
  }
}

int32_t tpudf_orc_stripes(uint8_t const* buf, uint64_t len, int64_t* num_rows,
                          int64_t* byte_size, int32_t cap) {
  try {
    auto infos = tpudf::orc::stripe_infos(buf, len);
    for (int32_t i = 0; i < cap && i < static_cast<int32_t>(infos.size());
         ++i) {
      num_rows[i] = infos[i].num_rows;
      byte_size[i] = infos[i].data_bytes;
    }
    return static_cast<int32_t>(infos.size());
  } catch (std::exception const& e) {
    set_error(e.what());
    return -1;
  }
}

int32_t tpudf_orc_num_columns(int64_t handle) {
  auto r = orc_reads().get(handle);
  if (r == nullptr) {
    set_error("invalid orc read handle");
    return -1;
  }
  return static_cast<int32_t>(r->columns.size());
}

int64_t tpudf_orc_num_rows(int64_t handle) {
  auto r = orc_reads().get(handle);
  if (r == nullptr) {
    set_error("invalid orc read handle");
    return -1;
  }
  return r->num_rows;
}

// meta = [kind, precision, scale, has_validity] (4 int32); sizes =
// [num_rows, chars_bytes] (2 int64).
int32_t tpudf_orc_col_meta(int64_t handle, int32_t i, int32_t* meta,
                           int64_t* sizes) {
  auto r = orc_reads().get(handle);
  if (r == nullptr || i < 0 || i >= static_cast<int32_t>(r->columns.size())) {
    set_error("invalid orc read handle or column index");
    return -1;
  }
  auto const& c = r->columns[i];
  meta[0] = c.kind;
  meta[1] = c.precision;
  meta[2] = c.scale;
  meta[3] = c.validity.empty() ? 0 : 1;
  sizes[0] = c.num_rows;
  sizes[1] = static_cast<int64_t>(c.chars.size());
  return 0;
}

// the unique StripeFooter.writerTimezone of the decoded stripes ("" =
// none recorded / UTC-family): TIMESTAMP payloads are wall-clock micros
// in this zone and the caller owns the tz-database conversion.
char const* tpudf_orc_writer_timezone(int64_t handle) {
  thread_local std::string tz_buf;
  auto r = orc_reads().get(handle);
  if (r == nullptr) {
    set_error("invalid orc read handle");
    return nullptr;
  }
  tz_buf = r->writer_timezone;
  return tz_buf.c_str();
}

char const* tpudf_orc_col_name(int64_t handle, int32_t i) {
  thread_local std::string name_buf;
  auto r = orc_reads().get(handle);
  if (r == nullptr || i < 0 || i >= static_cast<int32_t>(r->columns.size())) {
    set_error("invalid orc read handle or column index");
    return nullptr;
  }
  name_buf = r->columns[i].name;
  return name_buf.c_str();
}

// data: int64[num_rows] (always, incl. float bit patterns); offsets/chars
// only for string kinds; validity uint8[num_rows]. Null dests skip.
int32_t tpudf_orc_col_copy(int64_t handle, int32_t i, int64_t* data,
                           int32_t* offsets, uint8_t* chars,
                           uint8_t* validity) {
  auto r = orc_reads().get(handle);
  if (r == nullptr || i < 0 || i >= static_cast<int32_t>(r->columns.size())) {
    set_error("invalid orc read handle or column index");
    return -1;
  }
  auto const& c = r->columns[i];
  if (data != nullptr && !c.data.empty()) {
    std::memcpy(data, c.data.data(), c.data.size() * sizeof(int64_t));
  }
  if (offsets != nullptr && !c.offsets.empty()) {
    std::memcpy(offsets, c.offsets.data(), c.offsets.size() * sizeof(int32_t));
  }
  if (chars != nullptr && !c.chars.empty()) {
    std::memcpy(chars, c.chars.data(), c.chars.size());
  }
  if (validity != nullptr && !c.validity.empty()) {
    std::memcpy(validity, c.validity.data(), c.validity.size());
  }
  return 0;
}

int32_t tpudf_orc_close(int64_t handle) {
  if (!orc_reads().erase(handle)) {
    set_error("invalid orc read handle");
    return -1;
  }
  return 0;
}

// RLEv2 decode hook for spec-vector tests.
int32_t tpudf_orc_decode_rle2(uint8_t const* buf, uint64_t len, int64_t count,
                              int32_t is_signed, int64_t* out) {
  try {
    auto vals = tpudf::orc::decode_rle_v2(buf, len, count, is_signed != 0);
    std::memcpy(out, vals.data(), vals.size() * sizeof(int64_t));
    return 0;
  } catch (std::exception const& e) {
    set_error(e.what());
    return -1;
  }
}

// ---- host packed-row codec (C1' native half) ------------------------------

// Layout probe: fills starts[n_cols], returns row_size (or -1 on error).
int32_t tpudf_rows_layout(int32_t const* sizes, int32_t n_cols,
                          int32_t* starts) {
  try {
    std::vector<int32_t> sz(sizes, sizes + n_cols);
    auto layout = tpudf::rows::fixed_width_layout(sz);
    for (int32_t i = 0; i < n_cols; ++i) starts[i] = layout.start[i];
    return layout.row_size;
  } catch (std::exception const& e) {
    set_error(e.what());
    return -1;
  }
}

int32_t tpudf_to_rows(uint8_t const* const* col_data,
                      uint8_t const* const* col_valid, int32_t const* sizes,
                      int32_t n_cols, int64_t n_rows, uint8_t* out) {
  try {
    std::vector<int32_t> sz(sizes, sizes + n_cols);
    tpudf::rows::to_rows(col_data, col_valid, sz, n_rows, out);
    return 0;
  } catch (std::exception const& e) {
    set_error(e.what());
    return -1;
  }
}

int32_t tpudf_from_rows(uint8_t const* rows_buf, int64_t n_rows,
                        int32_t const* sizes, int32_t n_cols,
                        uint8_t* const* col_data, uint8_t* const* col_valid) {
  try {
    std::vector<int32_t> sz(sizes, sizes + n_cols);
    tpudf::rows::from_rows(rows_buf, n_rows, sz, col_data, col_valid);
    return 0;
  } catch (std::exception const& e) {
    set_error(e.what());
    return -1;
  }
}

// ---- get_json_object ------------------------------------------------------

// Extract `path` from each row of an Arrow string column. out_chars is
// malloc'd (free with tpudf_free_buffer); out_offsets has n_rows+1 slots,
// out_valid n_rows. Returns 0, or -1 on error (e.g. unsupported path).
int32_t tpudf_get_json_object(uint8_t const* chars, int32_t const* offsets,
                              uint8_t const* valid, int64_t n_rows,
                              char const* path, uint8_t** out_chars,
                              int64_t* out_chars_len, int32_t* out_offsets,
                              uint8_t* out_valid) {
  try {
    // Compile the path once for the whole column — also surfaces bad-path
    // errors even when every row is NULL (Spark's analyzer behavior).
    auto const steps = tpudf::json::parse_path(path);
    std::string result;
    out_offsets[0] = 0;
    for (int64_t r = 0; r < n_rows; ++r) {
      std::optional<std::string> match;
      if (valid == nullptr || valid[r]) {
        std::string_view row(
            reinterpret_cast<char const*>(chars) + offsets[r],
            static_cast<size_t>(offsets[r + 1] - offsets[r]));
        match = tpudf::json::get_json_object(row, steps);
      }
      if (match.has_value()) {
        result += *match;
        out_valid[r] = 1;
      } else {
        out_valid[r] = 0;
      }
      if (result.size() > static_cast<size_t>(INT32_MAX)) {
        throw std::overflow_error(
            "get_json_object output exceeds 2^31 chars");
      }
      out_offsets[r + 1] = static_cast<int32_t>(result.size());
    }
    *out_chars = static_cast<uint8_t*>(std::malloc(result.size() + 1));
    if (*out_chars == nullptr) throw std::bad_alloc();
    std::memcpy(*out_chars, result.data(), result.size());
    *out_chars_len = static_cast<int64_t>(result.size());
    return 0;
  } catch (std::exception const& e) {
    set_error(e.what());
    return -1;
  }
}

// Open-handle count — backs leak-check tests, the moral equivalent of the
// reference's refcount leak-debugging flag (pom.xml:86,436).
int64_t tpudf_open_handles() {
  return footers().size() + reads().size() + orc_reads().size();
}
}
