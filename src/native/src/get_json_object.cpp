#include "tpudf/get_json_object.hpp"

#include <cstdint>
#include <vector>

namespace tpudf {
namespace json {

namespace {

}  // namespace

// Parse "$.a['b'][3].c" into steps. Throws PathError on anything outside
// the supported grammar (incl. the wildcards Spark allows but we defer).
std::vector<PathStep> parse_path(std::string_view path) {
  if (path.empty() || path[0] != '$') {
    throw PathError("JSONPath must start with '$'");
  }
  std::vector<PathStep> steps;
  size_t i = 1;
  while (i < path.size()) {
    if (path[i] == '.') {
      ++i;
      size_t start = i;
      while (i < path.size() && path[i] != '.' && path[i] != '[') ++i;
      if (start == i) throw PathError("empty field name in JSONPath");
      std::string name(path.substr(start, i - start));
      if (name == "*") throw PathError("wildcard paths are not supported");
      PathStep s;
      s.field = std::move(name);
      steps.push_back(std::move(s));
    } else if (path[i] == '[') {
      ++i;
      if (i < path.size() && (path[i] == '\'' || path[i] == '"')) {
        char const quote = path[i];
        ++i;
        size_t start = i;
        while (i < path.size() && path[i] != quote) ++i;
        if (i >= path.size()) throw PathError("unterminated quoted field");
        PathStep s;
        s.field = std::string(path.substr(start, i - start));
        steps.push_back(std::move(s));
        ++i;
        if (i >= path.size() || path[i] != ']') {
          throw PathError("expected ']' in JSONPath");
        }
        ++i;
      } else {
        size_t start = i;
        while (i < path.size() && path[i] != ']') ++i;
        if (i >= path.size()) throw PathError("unterminated '[' in JSONPath");
        std::string_view idx = path.substr(start, i - start);
        if (idx == "*") throw PathError("wildcard paths are not supported");
        if (idx.empty()) throw PathError("empty index in JSONPath");
        int64_t v = 0;
        for (char c : idx) {
          if (c < '0' || c > '9') throw PathError("non-numeric array index");
          v = v * 10 + (c - '0');
        }
        PathStep s;
        s.is_index = true;
        s.index = v;
        steps.push_back(s);
        ++i;
      }
    } else {
      throw PathError("unexpected character in JSONPath");
    }
  }
  return steps;
}

namespace {

class Cursor {
 public:
  explicit Cursor(std::string_view s) : s_(s) {}

  bool fail() const { return failed_; }
  size_t pos() const { return i_; }
  std::string_view text() const { return s_; }

  void ws() {
    while (i_ < s_.size() &&
           (s_[i_] == ' ' || s_[i_] == '\t' || s_[i_] == '\n' ||
            s_[i_] == '\r')) {
      ++i_;
    }
  }

  char peek() {
    if (i_ >= s_.size()) {
      failed_ = true;
      return '\0';
    }
    return s_[i_];
  }

  bool eat(char c) {
    if (i_ < s_.size() && s_[i_] == c) {
      ++i_;
      return true;
    }
    failed_ = true;
    return false;
  }

  // Skip over one complete value; returns [start,end) of its raw text.
  std::pair<size_t, size_t> skip_value() {
    ws();
    size_t start = i_;
    char c = peek();
    if (failed_) return {start, start};
    if (c == '{') {
      skip_container('{', '}');
    } else if (c == '[') {
      skip_container('[', ']');
    } else if (c == '"') {
      skip_string();
    } else {
      // literal: number / true / false / null
      while (i_ < s_.size() && s_[i_] != ',' && s_[i_] != '}' &&
             s_[i_] != ']' && s_[i_] != ' ' && s_[i_] != '\t' &&
             s_[i_] != '\n' && s_[i_] != '\r') {
        ++i_;
      }
      if (i_ == start) failed_ = true;
    }
    return {start, i_};
  }

  void skip_string() {
    if (!eat('"')) return;
    while (i_ < s_.size()) {
      char c = s_[i_];
      if (c == '\\') {
        i_ += 2;
        continue;
      }
      ++i_;
      if (c == '"') return;
    }
    failed_ = true;  // unterminated
  }

  void skip_container(char open, char close) {
    if (!eat(open)) return;
    int depth = 1;
    while (i_ < s_.size() && depth > 0) {
      char c = s_[i_];
      if (c == '"') {
        skip_string();
        continue;
      }
      if (c == open) ++depth;
      if (c == close) --depth;
      ++i_;
    }
    if (depth != 0) failed_ = true;
  }

  // Decode the string the cursor sits on (must be at '"').
  std::optional<std::string> decode_string() {
    if (!eat('"')) return std::nullopt;
    std::string out;
    while (i_ < s_.size()) {
      char c = s_[i_++];
      if (c == '"') return out;
      if (c != '\\') {
        out.push_back(c);
        continue;
      }
      if (i_ >= s_.size()) return std::nullopt;
      char e = s_[i_++];
      switch (e) {
        case '"': out.push_back('"'); break;
        case '\\': out.push_back('\\'); break;
        case '/': out.push_back('/'); break;
        case 'b': out.push_back('\b'); break;
        case 'f': out.push_back('\f'); break;
        case 'n': out.push_back('\n'); break;
        case 'r': out.push_back('\r'); break;
        case 't': out.push_back('\t'); break;
        case 'u': {
          uint32_t cp = 0;
          if (!hex4(&cp)) return std::nullopt;
          if (cp >= 0xD800 && cp <= 0xDBFF && i_ + 1 < s_.size() &&
              s_[i_] == '\\' && s_[i_ + 1] == 'u') {
            i_ += 2;
            uint32_t lo = 0;
            if (!hex4(&lo)) return std::nullopt;
            if (lo >= 0xDC00 && lo <= 0xDFFF) {
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            } else {
              return std::nullopt;
            }
          }
          append_utf8(out, cp);
          break;
        }
        default: return std::nullopt;
      }
    }
    return std::nullopt;  // unterminated
  }

 private:
  bool hex4(uint32_t* out) {
    if (i_ + 4 > s_.size()) return false;
    uint32_t v = 0;
    for (int k = 0; k < 4; ++k) {
      char c = s_[i_ + k];
      uint32_t d;
      if (c >= '0' && c <= '9') d = c - '0';
      else if (c >= 'a' && c <= 'f') d = 10 + c - 'a';
      else if (c >= 'A' && c <= 'F') d = 10 + c - 'A';
      else return false;
      v = (v << 4) | d;
    }
    i_ += 4;
    *out = v;
    return true;
  }

  static void append_utf8(std::string& out, uint32_t cp) {
    if (cp < 0x80) {
      out.push_back(static_cast<char>(cp));
    } else if (cp < 0x800) {
      out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else {
      out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    }
  }

  std::string_view s_;
  size_t i_ = 0;
  bool failed_ = false;
};

// Position the cursor on the value selected by `steps`; false = no match.
bool navigate(Cursor& cur, std::vector<PathStep> const& steps) {
  for (auto const& step : steps) {
    cur.ws();
    if (!step.is_index) {
      if (!cur.eat('{')) return false;
      bool found = false;
      while (true) {
        cur.ws();
        if (cur.peek() == '}') return false;  // member absent
        auto key = cur.decode_string();
        if (!key.has_value()) return false;
        cur.ws();
        if (!cur.eat(':')) return false;
        if (*key == step.field) {
          found = true;
          break;  // cursor sits on the member's value
        }
        cur.skip_value();
        if (cur.fail()) return false;
        cur.ws();
        if (cur.peek() == ',') {
          cur.eat(',');
          continue;
        }
        return false;  // '}' or garbage: member absent / malformed
      }
      if (!found) return false;
    } else {
      if (!cur.eat('[')) return false;
      cur.ws();
      if (cur.peek() == ']') return false;  // empty array
      for (int64_t k = 0; k < step.index; ++k) {
        cur.skip_value();
        if (cur.fail()) return false;
        cur.ws();
        if (!cur.eat(',')) return false;  // index out of range
      }
    }
  }
  return !cur.fail();
}

}  // namespace

std::optional<std::string> get_json_object(
    std::string_view json, std::vector<PathStep> const& steps) {
  Cursor cur(json);
  if (!navigate(cur, steps)) return std::nullopt;
  cur.ws();
  char c = cur.peek();
  if (cur.fail()) return std::nullopt;
  if (c == '"') {
    return cur.decode_string();  // strings come back unquoted
  }
  auto [start, end] = cur.skip_value();
  if (cur.fail() || end <= start) return std::nullopt;
  std::string_view raw = cur.text().substr(start, end - start);
  if (raw == "null") return std::nullopt;  // JSON null -> SQL NULL
  return std::string(raw);
}

std::optional<std::string> get_json_object(std::string_view json,
                                           std::string_view path) {
  return get_json_object(json, parse_path(path));
}

}  // namespace json
}  // namespace tpudf
