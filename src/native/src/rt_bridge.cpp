// Device-runtime bridge: handle-model C ABI over an embedded CPython/JAX
// runtime — the layer that lets a JVM (or any native caller) drive the TPU
// device runtime the way the reference's JNI drives CUDA/libcudf.
//
// Role parity: reference RowConversionJni.cpp:24-41 marshals jlong table
// handles into cudf device calls inside the JVM process. Here the same
// handle model (int64 -> runtime object) fronts a CPython interpreter that
// owns the JAX/XLA runtime (see spark_rapids_jni_tpu/runtime/bridge.py for
// the documented architecture decision). Threading: every entry point takes
// the GIL via PyGILState_Ensure, so concurrent JVM task threads serialize
// into XLA's single-controller model — the ordering layer SURVEY.md section
// 7 calls out as the hard part of the JNI<->TPU bridge.
//
// Error contract: functions return -1/nonzero and store a message
// retrievable via tpudf_rt_last_error() — the CATCH_STD/jlong convention of
// the reference JNI layer, minus the JVM.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>

namespace {

std::mutex g_mutex;
std::unordered_map<int64_t, PyObject*> g_handles;  // owned references
int64_t g_next_handle = 1;
thread_local std::string g_last_error;
PyObject* g_bridge = nullptr;  // spark_rapids_jni_tpu.runtime.bridge module
bool g_we_initialized_python = false;

int64_t store_handle(PyObject* obj) {  // steals the reference
  std::lock_guard<std::mutex> lock(g_mutex);
  int64_t h = g_next_handle++;
  g_handles[h] = obj;
  return h;
}

PyObject* get_handle(int64_t h) {  // borrowed reference
  std::lock_guard<std::mutex> lock(g_mutex);
  auto it = g_handles.find(h);
  return it == g_handles.end() ? nullptr : it->second;
}

void set_python_error() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      char const* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// RAII GIL hold for every entry point.
struct Gil {
  PyGILState_STATE state;
  Gil() : state(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state); }
};

// Pre-GIL guard: PyGILState_Ensure on an uninitialized interpreter is a
// fatal abort, so every entry point must check this BEFORE taking the GIL
// (the benign unlocked read of g_bridge is a monotonic pointer set once
// under tpudf_rt_init's mutex).
bool rt_ready() {
  if (!Py_IsInitialized() || g_bridge == nullptr) {
    g_last_error = "tpudf_rt_init was not called";
    return false;
  }
  return true;
}

// Call bridge.<fn>(args...) returning a new reference or nullptr (+error).
PyObject* bridge_call(char const* fn, PyObject* args) {  // steals args
  if (g_bridge == nullptr) {
    Py_XDECREF(args);
    g_last_error = "tpudf_rt_init was not called";
    return nullptr;
  }
  PyObject* f = PyObject_GetAttrString(g_bridge, fn);
  if (f == nullptr) {
    Py_XDECREF(args);
    set_python_error();
    return nullptr;
  }
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (out == nullptr) set_python_error();
  return out;
}

}  // namespace

extern "C" {

char const* tpudf_rt_last_error() { return g_last_error.c_str(); }

// Initialize the embedded runtime. sys_path entries (':'-separated) are
// prepended to sys.path (the packaged wheel/jar resource dir); platform ""
// selects the default backend (TPU when present), "cpu" pins host-only.
int32_t tpudf_rt_init(char const* sys_path, char const* platform) {
  // serialize concurrent initializers (the GIL can't do it: it may not
  // exist yet); everything after interpreter creation runs under the GIL
  static std::mutex init_mutex;
  std::lock_guard<std::mutex> init_lock(init_mutex);
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized_python = true;
  }
  int32_t rc = [&]() -> int32_t {
    Gil gil;
    if (g_bridge != nullptr) return 0;  // already initialized
    if (sys_path != nullptr && sys_path[0] != '\0') {
      PyObject* sys_path_list = PySys_GetObject("path");  // borrowed
      std::string paths(sys_path);
      size_t start = 0;
      while (start <= paths.size()) {
        size_t end = paths.find(':', start);
        if (end == std::string::npos) end = paths.size();
        if (end > start) {
          PyObject* p =
              PyUnicode_FromStringAndSize(paths.data() + start, end - start);
          if (p == nullptr || PyList_Insert(sys_path_list, 0, p) != 0) {
            Py_XDECREF(p);
            set_python_error();
            return -1;
          }
          Py_DECREF(p);
        }
        start = end + 1;
      }
    }
    PyObject* mod = PyImport_ImportModule("spark_rapids_jni_tpu.runtime.bridge");
    if (mod == nullptr) {
      set_python_error();
      return -1;
    }
    PyObject* ok = PyObject_CallMethod(
        mod, "init_platform", "(s)", platform == nullptr ? "" : platform);
    if (ok == nullptr) {
      // keep the module unset so callers can retry init
      set_python_error();
      Py_DECREF(mod);
      return -1;
    }
    Py_DECREF(ok);
    g_bridge = mod;
    return 0;
  }();
  if (g_we_initialized_python) {
    // Release the GIL acquired by Py_InitializeEx so any thread can enter.
    // Must run on FAILURE too: returning with the GIL held would deadlock
    // every later bridge call (including an init retry).
    static PyThreadState* main_state = nullptr;
    if (main_state == nullptr) main_state = PyEval_SaveThread();
  }
  return rc;
}

// Build a device column from host bytes. validity: 1 byte per row (0 =
// null) or nullptr for all-valid. Returns a handle or -1.
int64_t tpudf_rt_column_from_host(int32_t type_id, int32_t scale, int64_t n,
                                  uint8_t const* data, int64_t data_len,
                                  uint8_t const* validity) {
  if (!rt_ready()) return -1;
  Gil gil;
  PyObject* vbytes;
  if (validity == nullptr) {
    vbytes = Py_None;
    Py_INCREF(Py_None);
  } else {
    vbytes = PyBytes_FromStringAndSize(
        reinterpret_cast<char const*>(validity), n);
  }
  PyObject* args = Py_BuildValue(
      "(iiLy#N)", type_id, scale, static_cast<long long>(n),
      reinterpret_cast<char const*>(data), static_cast<Py_ssize_t>(data_len),
      vbytes);
  PyObject* col = bridge_call("column_from_host", args);
  if (col == nullptr) return -1;
  return store_handle(col);
}

int64_t tpudf_rt_table_create(int64_t const* cols, int32_t ncols) {
  if (!rt_ready()) return -1;
  Gil gil;
  PyObject* list = PyList_New(ncols);
  for (int32_t i = 0; i < ncols; ++i) {
    PyObject* c = get_handle(cols[i]);
    if (c == nullptr) {
      Py_DECREF(list);
      g_last_error = "invalid column handle";
      return -1;
    }
    Py_INCREF(c);
    PyList_SET_ITEM(list, i, c);
  }
  PyObject* args = Py_BuildValue("(N)", list);
  PyObject* tbl = bridge_call("table_create", args);
  if (tbl == nullptr) return -1;
  return store_handle(tbl);
}

static int64_t call_int(char const* fn, int64_t handle) {
  if (!rt_ready()) return -1;
  Gil gil;
  PyObject* obj = get_handle(handle);
  if (obj == nullptr) {
    g_last_error = "invalid handle";
    return -1;
  }
  Py_INCREF(obj);
  PyObject* args = Py_BuildValue("(N)", obj);
  PyObject* out = bridge_call(fn, args);
  if (out == nullptr) return -1;
  int64_t v = PyLong_AsLongLong(out);
  Py_DECREF(out);
  if (v == -1 && PyErr_Occurred()) {
    set_python_error();  // also clears the pending exception
    return -1;
  }
  return v;
}

int32_t tpudf_rt_table_num_columns(int64_t tbl) {
  return static_cast<int32_t>(call_int("table_num_columns", tbl));
}

int64_t tpudf_rt_table_num_rows(int64_t tbl) {
  return call_int("table_num_rows", tbl);
}

int64_t tpudf_rt_table_column(int64_t tbl, int32_t i) {
  if (!rt_ready()) return -1;
  Gil gil;
  PyObject* obj = get_handle(tbl);
  if (obj == nullptr) {
    g_last_error = "invalid handle";
    return -1;
  }
  Py_INCREF(obj);
  PyObject* args = Py_BuildValue("(Ni)", obj, i);
  PyObject* col = bridge_call("table_column", args);
  if (col == nullptr) return -1;
  return store_handle(col);
}

int32_t tpudf_rt_column_info(int64_t col, int32_t* type_id, int32_t* scale,
                             int64_t* num_rows) {
  if (!rt_ready()) return -1;
  Gil gil;
  PyObject* obj = get_handle(col);
  if (obj == nullptr) {
    g_last_error = "invalid handle";
    return -1;
  }
  Py_INCREF(obj);
  PyObject* args = Py_BuildValue("(N)", obj);
  PyObject* out = bridge_call("column_info", args);
  if (out == nullptr) return -1;
  long long t = 0, s = 0, n = 0;
  if (!PyArg_ParseTuple(out, "LLL", &t, &s, &n)) {
    set_python_error();
    Py_DECREF(out);
    return -1;
  }
  Py_DECREF(out);
  *type_id = static_cast<int32_t>(t);
  *scale = static_cast<int32_t>(s);
  *num_rows = n;
  return 0;
}

// Copy a device column to host: data_out receives n*size_bytes, validity_out
// one byte per row. Either may be nullptr to skip.
int32_t tpudf_rt_column_to_host(int64_t col, uint8_t* data_out,
                                int64_t data_cap, uint8_t* validity_out,
                                int64_t validity_cap) {
  if (!rt_ready()) return -1;
  Gil gil;
  PyObject* obj = get_handle(col);
  if (obj == nullptr) {
    g_last_error = "invalid handle";
    return -1;
  }
  Py_INCREF(obj);
  PyObject* args = Py_BuildValue("(N)", obj);
  PyObject* out = bridge_call("column_to_host", args);
  if (out == nullptr) return -1;
  PyObject *data = nullptr, *valid = nullptr;
  if (!PyArg_ParseTuple(out, "SS", &data, &valid)) {
    set_python_error();
    Py_DECREF(out);
    return -1;
  }
  if (data_out != nullptr) {
    Py_ssize_t len = PyBytes_GET_SIZE(data);
    if (len > data_cap) {
      g_last_error = "data buffer too small";
      Py_DECREF(out);
      return -1;
    }
    std::memcpy(data_out, PyBytes_AS_STRING(data), len);
  }
  if (validity_out != nullptr) {
    Py_ssize_t len = PyBytes_GET_SIZE(valid);
    if (len > validity_cap) {
      g_last_error = "validity buffer too small";
      Py_DECREF(out);
      return -1;
    }
    std::memcpy(validity_out, PyBytes_AS_STRING(valid), len);
  }
  Py_DECREF(out);
  return 0;
}

// Device row conversion: table handle -> batches of packed-rows columns.
// out receives up to cap handles; *n_out the true batch count.
int32_t tpudf_rt_convert_to_rows(int64_t tbl, int64_t* out, int32_t cap,
                                 int32_t* n_out) {
  if (!rt_ready()) return -1;
  Gil gil;
  PyObject* obj = get_handle(tbl);
  if (obj == nullptr) {
    g_last_error = "invalid handle";
    return -1;
  }
  Py_INCREF(obj);
  PyObject* args = Py_BuildValue("(N)", obj);
  PyObject* batches = bridge_call("convert_to_rows", args);
  if (batches == nullptr) return -1;
  Py_ssize_t n = PyList_Size(batches);
  *n_out = static_cast<int32_t>(n);
  if (n > cap) {
    g_last_error = "batch output array too small";
    Py_DECREF(batches);
    return -1;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* b = PyList_GET_ITEM(batches, i);  // borrowed
    Py_INCREF(b);
    out[i] = store_handle(b);
  }
  Py_DECREF(batches);
  return 0;
}

int64_t tpudf_rt_convert_from_rows(int64_t rows, int32_t const* type_ids,
                                   int32_t const* scales, int32_t ncols) {
  if (!rt_ready()) return -1;
  Gil gil;
  PyObject* obj = get_handle(rows);
  if (obj == nullptr) {
    g_last_error = "invalid handle";
    return -1;
  }
  PyObject* tlist = PyList_New(ncols);
  PyObject* slist = PyList_New(ncols);
  for (int32_t i = 0; i < ncols; ++i) {
    PyList_SET_ITEM(tlist, i, PyLong_FromLong(type_ids[i]));
    PyList_SET_ITEM(slist, i, PyLong_FromLong(scales[i]));
  }
  Py_INCREF(obj);
  PyObject* args = Py_BuildValue("(NNN)", obj, tlist, slist);
  PyObject* tbl = bridge_call("convert_from_rows", args);
  if (tbl == nullptr) return -1;
  return store_handle(tbl);
}

int32_t tpudf_rt_rows_info(int64_t rows, int64_t* num_rows,
                           int64_t* row_size) {
  if (!rt_ready()) return -1;
  Gil gil;
  PyObject* obj = get_handle(rows);
  if (obj == nullptr) {
    g_last_error = "invalid handle";
    return -1;
  }
  Py_INCREF(obj);
  PyObject* args = Py_BuildValue("(N)", obj);
  PyObject* out = bridge_call("rows_info", args);
  if (out == nullptr) return -1;
  long long n = 0, sz = 0;
  if (!PyArg_ParseTuple(out, "LL", &n, &sz)) {
    set_python_error();
    Py_DECREF(out);
    return -1;
  }
  Py_DECREF(out);
  *num_rows = n;
  *row_size = sz;
  return 0;
}

int32_t tpudf_rt_rows_to_host(int64_t rows, uint8_t* out, int64_t cap) {
  if (!rt_ready()) return -1;
  Gil gil;
  PyObject* obj = get_handle(rows);
  if (obj == nullptr) {
    g_last_error = "invalid handle";
    return -1;
  }
  Py_INCREF(obj);
  PyObject* args = Py_BuildValue("(N)", obj);
  PyObject* data = bridge_call("rows_to_host", args);
  if (data == nullptr) return -1;
  Py_ssize_t len = PyBytes_GET_SIZE(data);
  if (len > cap) {
    g_last_error = "rows buffer too small";
    Py_DECREF(data);
    return -1;
  }
  std::memcpy(out, PyBytes_AS_STRING(data), len);
  Py_DECREF(data);
  return 0;
}

int64_t tpudf_rt_rows_from_host(int64_t num_rows, int64_t row_size,
                                uint8_t const* data) {
  if (!rt_ready()) return -1;
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(LLy#)", static_cast<long long>(num_rows),
      static_cast<long long>(row_size), reinterpret_cast<char const*>(data),
      static_cast<Py_ssize_t>(num_rows * row_size));
  PyObject* rows = bridge_call("rows_from_host", args);
  if (rows == nullptr) return -1;
  return store_handle(rows);
}

int32_t tpudf_rt_free(int64_t handle) {
  if (!rt_ready()) return -1;
  Gil gil;
  std::lock_guard<std::mutex> lock(g_mutex);
  auto it = g_handles.find(handle);
  if (it == g_handles.end()) return -1;
  Py_DECREF(it->second);
  g_handles.erase(it);
  return 0;
}

}  // extern "C"
