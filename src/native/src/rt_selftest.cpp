// Device-path round trip of the reference's 8-column test table, driven
// entirely from C through the handle-model C ABI — the JNI-level proof the
// JVM bridge works without needing a JDK in the image.
//
// Table parity: reference RowConversionTest.java:30-39 —
//   col0 INT64       {3, 9, 4, 2, 20, null}
//   col1 FLOAT64     {5.0, 9.5, 0.9, 7.23, 2.8, null}
//   col2 INT32       {5, 1, 0, 2, 7, null}
//   col3 BOOL8       {true, false, false, true, false, null}
//   col4 FLOAT32     {1.0, 3.5, 5.9, 7.1, 9.8, null}
//   col5 INT8        {2, 3, 4, 5, 9, null}
//   col6 DECIMAL32(-3) of {5.0, 9.5, 0.9, 7.23, 2.8, null}  (unscaled e3)
//   col7 DECIMAL64(-8) of {3, 9, 4, 2, 20, null}             (unscaled e8)
// Assertions mirror the test: one batch, row count preserved, full table
// equality after convertFromRows (AssertUtils.assertTablesAreEqual
// semantics: per-column dtype, validity, and valid values).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {
int32_t tpudf_rt_init(char const* sys_path, char const* platform);
char const* tpudf_rt_last_error();
int64_t tpudf_rt_column_from_host(int32_t type_id, int32_t scale, int64_t n,
                                  uint8_t const* data, int64_t data_len,
                                  uint8_t const* validity);
int64_t tpudf_rt_table_create(int64_t const* cols, int32_t ncols);
int32_t tpudf_rt_table_num_columns(int64_t tbl);
int64_t tpudf_rt_table_num_rows(int64_t tbl);
int64_t tpudf_rt_table_column(int64_t tbl, int32_t i);
int32_t tpudf_rt_column_info(int64_t col, int32_t* type_id, int32_t* scale,
                             int64_t* num_rows);
int32_t tpudf_rt_column_to_host(int64_t col, uint8_t* data_out,
                                int64_t data_cap, uint8_t* validity_out,
                                int64_t validity_cap);
int32_t tpudf_rt_convert_to_rows(int64_t tbl, int64_t* out, int32_t cap,
                                 int32_t* n_out);
int64_t tpudf_rt_convert_from_rows(int64_t rows, int32_t const* type_ids,
                                   int32_t const* scales, int32_t ncols);
int32_t tpudf_rt_rows_info(int64_t rows, int64_t* num_rows, int64_t* row_size);
int32_t tpudf_rt_free(int64_t handle);
}

namespace {

// cuDF type ids (types.py TypeId)
constexpr int32_t INT8 = 1, INT32 = 3, INT64 = 4, FLOAT32 = 9, FLOAT64 = 10,
                  BOOL8 = 11, DECIMAL32 = 25, DECIMAL64 = 26;
constexpr int64_t N = 6;

int g_failures = 0;

void check(bool ok, char const* what) {
  if (!ok) {
    std::fprintf(stderr, "FAIL: %s (last_error: %s)\n", what,
                 tpudf_rt_last_error());
    ++g_failures;
  }
}

struct Col {
  int32_t type_id;
  int32_t scale;
  std::vector<uint8_t> data;
  std::vector<uint8_t> validity;  // 1 byte per row
};

template <typename T>
Col make_col(int32_t type_id, int32_t scale, std::vector<T> vals,
             std::vector<uint8_t> validity) {
  Col c;
  c.type_id = type_id;
  c.scale = scale;
  c.data.resize(vals.size() * sizeof(T));
  std::memcpy(c.data.data(), vals.data(), c.data.size());
  c.validity = std::move(validity);
  return c;
}

}  // namespace

int main() {
  char const* repo = std::getenv("TPUDF_PY_PATH");
  check(tpudf_rt_init(repo == nullptr ? "" : repo, "cpu") == 0, "rt_init");
  if (g_failures) return 1;

  std::vector<uint8_t> tail_null = {1, 1, 1, 1, 1, 0};
  std::vector<Col> cols;
  cols.push_back(make_col<int64_t>(INT64, 0, {3, 9, 4, 2, 20, 0}, tail_null));
  cols.push_back(
      make_col<double>(FLOAT64, 0, {5.0, 9.5, 0.9, 7.23, 2.8, 0.0}, tail_null));
  cols.push_back(make_col<int32_t>(INT32, 0, {5, 1, 0, 2, 7, 0}, tail_null));
  cols.push_back(make_col<uint8_t>(BOOL8, 0, {1, 0, 0, 1, 0, 0}, tail_null));
  cols.push_back(make_col<float>(
      FLOAT32, 0, {1.0f, 3.5f, 5.9f, 7.1f, 9.8f, 0.0f}, tail_null));
  cols.push_back(make_col<int8_t>(INT8, 0, {2, 3, 4, 5, 9, 0}, tail_null));
  cols.push_back(make_col<int32_t>(
      DECIMAL32, -3, {5000, 9500, 900, 7230, 2800, 0}, tail_null));
  cols.push_back(make_col<int64_t>(
      DECIMAL64, -8,
      {300000000LL, 900000000LL, 400000000LL, 200000000LL, 2000000000LL, 0},
      tail_null));

  std::vector<int64_t> col_handles;
  for (auto const& c : cols) {
    int64_t h = tpudf_rt_column_from_host(
        c.type_id, c.scale, N, c.data.data(),
        static_cast<int64_t>(c.data.size()), c.validity.data());
    check(h > 0, "column_from_host");
    col_handles.push_back(h);
  }
  int64_t tbl = tpudf_rt_table_create(col_handles.data(),
                                      static_cast<int32_t>(col_handles.size()));
  check(tbl > 0, "table_create");
  check(tpudf_rt_table_num_columns(tbl) == 8, "num_columns == 8");
  check(tpudf_rt_table_num_rows(tbl) == N, "num_rows == 6");

  // device row conversion: columnar -> packed rows
  int64_t batches[4] = {0, 0, 0, 0};
  int32_t n_batches = 0;
  check(tpudf_rt_convert_to_rows(tbl, batches, 4, &n_batches) == 0,
        "convert_to_rows");
  check(n_batches == 1, "no batch overflow (rows.length == 1)");
  int64_t rows_n = 0, row_size = 0;
  check(tpudf_rt_rows_info(batches[0], &rows_n, &row_size) == 0, "rows_info");
  check(rows_n == N, "row count preserved");

  // packed rows -> columnar, with the recorded (typeId, scale) schema
  std::vector<int32_t> type_ids, scales;
  for (auto const& c : cols) {
    type_ids.push_back(c.type_id);
    scales.push_back(c.scale);
  }
  int64_t back = tpudf_rt_convert_from_rows(
      batches[0], type_ids.data(), scales.data(),
      static_cast<int32_t>(type_ids.size()));
  check(back > 0, "convert_from_rows");

  // assertTablesAreEqual: dtype + validity + valid values per column
  for (int32_t i = 0; i < 8; ++i) {
    int64_t col = tpudf_rt_table_column(back, i);
    check(col > 0, "table_column");
    int32_t tid = 0, scale = 0;
    int64_t n = 0;
    check(tpudf_rt_column_info(col, &tid, &scale, &n) == 0, "column_info");
    check(tid == cols[i].type_id, "dtype preserved");
    check(scale == cols[i].scale, "scale preserved");
    check(n == N, "column length");
    std::vector<uint8_t> data(cols[i].data.size());
    std::vector<uint8_t> validity(N);
    check(tpudf_rt_column_to_host(col, data.data(),
                                  static_cast<int64_t>(data.size()),
                                  validity.data(), N) == 0,
          "column_to_host");
    check(validity == cols[i].validity, "validity round-trips");
    size_t elem = cols[i].data.size() / N;
    for (int64_t r = 0; r + 1 < N; ++r) {  // last row is null: value unspecified
      check(std::memcmp(data.data() + r * elem,
                        cols[i].data.data() + r * elem, elem) == 0,
            "valid values round-trip");
    }
    tpudf_rt_free(col);
  }

  tpudf_rt_free(back);
  tpudf_rt_free(batches[0]);
  tpudf_rt_free(tbl);
  for (int64_t h : col_handles) tpudf_rt_free(h);

  if (g_failures == 0) {
    std::printf("tpudf_rt_selftest: all checks passed\n");
    return 0;
  }
  std::fprintf(stderr, "tpudf_rt_selftest: %d failures\n", g_failures);
  return 1;
}
