#include "tpudf/orc_reader.hpp"

#include <zlib.h>

#include <algorithm>
#include <cstring>
#include <stdexcept>
#include <utility>

#include "tpudf/parquet_reader.hpp"  // snappy_uncompress
#include "tpudf/protobuf_wire.hpp"

namespace tpudf {
namespace orc {

namespace {

using pb::Message;

[[noreturn]] void fail(std::string const& msg) {
  throw std::runtime_error("orc read: " + msg);
}

// ---- orc_proto.proto field numbers ----------------------------------------

// PostScript
constexpr uint32_t kPsFooterLength = 1;
constexpr uint32_t kPsCompression = 2;
constexpr uint32_t kPsMagic = 8000;
// Footer
constexpr uint32_t kFtStripes = 3;
constexpr uint32_t kFtTypes = 4;
constexpr uint32_t kFtNumRows = 6;
// StripeInformation
constexpr uint32_t kSiOffset = 1;
constexpr uint32_t kSiIndexLength = 2;
constexpr uint32_t kSiDataLength = 3;
constexpr uint32_t kSiFooterLength = 4;
constexpr uint32_t kSiNumRows = 5;
// Type
constexpr uint32_t kTyKind = 1;
constexpr uint32_t kTySubtypes = 2;
constexpr uint32_t kTyFieldNames = 3;
constexpr uint32_t kTyPrecision = 5;
constexpr uint32_t kTyScale = 6;
// StripeFooter
constexpr uint32_t kSfStreams = 1;
constexpr uint32_t kSfColumns = 2;
constexpr uint32_t kSfWriterTimezone = 3;
// Stream
constexpr uint32_t kStKind = 1;
constexpr uint32_t kStColumn = 2;
constexpr uint32_t kStLength = 3;
// ColumnEncoding
constexpr uint32_t kCeKind = 1;
constexpr uint32_t kCeDictSize = 2;

// Stream kinds
constexpr uint64_t kStreamPresent = 0;
constexpr uint64_t kStreamData = 1;
constexpr uint64_t kStreamLength = 2;
constexpr uint64_t kStreamDictData = 3;
constexpr uint64_t kStreamSecondary = 5;

// compression kinds
constexpr uint64_t kCompNone = 0;
constexpr uint64_t kCompZlib = 1;
constexpr uint64_t kCompSnappy = 2;

// encoding kinds
constexpr uint64_t kEncDirect = 0;
constexpr uint64_t kEncDictionary = 1;
constexpr uint64_t kEncDirectV2 = 2;
constexpr uint64_t kEncDictionaryV2 = 3;

// ---- compression (ORC chunk framing) --------------------------------------

std::vector<uint8_t> zlib_raw_inflate(uint8_t const* in, uint64_t n) {
  // ORC ZLIB chunks are raw deflate (no zlib/gzip header)
  std::vector<uint8_t> out;
  out.resize(std::max<uint64_t>(n * 4, 4096));
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, -MAX_WBITS) != Z_OK) fail("zlib init failed");
  zs.next_in = const_cast<Bytef*>(in);
  zs.avail_in = static_cast<uInt>(n);
  size_t written = 0;
  int rc = Z_OK;
  do {
    if (written == out.size()) out.resize(out.size() * 2);
    zs.next_out = out.data() + written;
    zs.avail_out = static_cast<uInt>(out.size() - written);
    rc = inflate(&zs, Z_NO_FLUSH);
    written = zs.total_out;
    if (rc == Z_STREAM_END) break;
    if (rc != Z_OK && rc != Z_BUF_ERROR) {
      inflateEnd(&zs);
      fail("zlib inflate failed");
    }
  } while (zs.avail_in > 0 || rc == Z_BUF_ERROR);
  inflateEnd(&zs);
  out.resize(written);
  return out;
}

// Undo the ORC chunked compression framing for one stream.
std::vector<uint8_t> decode_stream(uint8_t const* p, uint64_t n,
                                   uint64_t compression) {
  if (compression == kCompNone) return std::vector<uint8_t>(p, p + n);
  std::vector<uint8_t> out;
  uint64_t pos = 0;
  while (pos < n) {
    if (pos + 3 > n) fail("truncated compression chunk header");
    uint32_t h = static_cast<uint32_t>(p[pos]) |
                 (static_cast<uint32_t>(p[pos + 1]) << 8) |
                 (static_cast<uint32_t>(p[pos + 2]) << 16);
    pos += 3;
    bool const original = h & 1;
    uint64_t const chunk = h >> 1;
    if (pos + chunk > n) fail("compression chunk past stream end");
    if (original) {
      out.insert(out.end(), p + pos, p + pos + chunk);
    } else if (compression == kCompZlib) {
      auto d = zlib_raw_inflate(p + pos, chunk);
      out.insert(out.end(), d.begin(), d.end());
    } else if (compression == kCompSnappy) {
      // ORC does not declare an uncompressed chunk size anywhere else; the
      // snappy stream's own varint preamble is authoritative.
      auto d = parquet::snappy_uncompress(p + pos, chunk,
                                          parquet::kSnappyNoExpectedSize);
      out.insert(out.end(), d.begin(), d.end());
    } else {
      fail("unsupported compression kind " + std::to_string(compression));
    }
    pos += chunk;
  }
  return out;
}

// ---- primitive decoders ---------------------------------------------------

struct Cursor {
  uint8_t const* p;
  uint64_t len;
  uint64_t pos = 0;

  uint8_t byte() {
    if (pos >= len) fail("stream underrun");
    return p[pos++];
  }

  uint64_t varint_u() {
    uint64_t out = 0;
    int shift = 0;
    while (shift <= 63) {
      uint8_t b = byte();
      out |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) return out;
      shift += 7;
    }
    fail("bad varint");
  }

  int64_t varint_s() {  // zigzag
    uint64_t u = varint_u();
    return static_cast<int64_t>((u >> 1) ^ (~(u & 1) + 1));
  }

  // 128-bit zigzag varint (ORC DECIMAL with precision > 18): returns
  // (lo unsigned, hi signed) little-endian limbs of the two's-complement
  // value — the framework's DECIMAL128 storage layout.
  std::pair<uint64_t, int64_t> varint_s128() {
    uint64_t lo = 0, hi = 0;
    int shift = 0;
    while (shift <= 127) {
      uint8_t b = byte();
      uint64_t g = b & 0x7F;
      if (shift < 64) {
        lo |= g << shift;
        if (shift + 7 > 64) hi |= g >> (64 - shift);
      } else {
        hi |= g << (shift - 64);
      }
      if (!(b & 0x80)) {
        // the 19th byte contributes only 2 bits (shift 126): payload above
        // them means a corrupt stream, not a silently-truncated value
        if (shift == 126 && (g >> 2) != 0) fail("varint128 high-bit garbage");
        break;
      }
      shift += 7;
      if (shift > 127) fail("varint128 overruns 128 bits");
    }
    uint64_t sign = lo & 1;
    uint64_t rlo = (lo >> 1) | (hi << 63);
    uint64_t rhi = hi >> 1;
    if (sign) {
      rlo = ~rlo;
      rhi = ~rhi;
    }
    return {rlo, static_cast<int64_t>(rhi)};
  }
};

// Byte RLE: control c in [0,127] -> run of c+3 copies of next byte;
// c in [128,255] -> 256-c literal bytes.
std::vector<uint8_t> decode_byte_rle(std::vector<uint8_t> const& s,
                                     int64_t count) {
  std::vector<uint8_t> out;
  out.reserve(count);
  Cursor c{s.data(), s.size()};
  while (static_cast<int64_t>(out.size()) < count) {
    uint8_t ctrl = c.byte();
    if (ctrl < 128) {
      uint8_t v = c.byte();
      out.insert(out.end(), ctrl + 3, v);
    } else {
      int n = 256 - ctrl;
      for (int k = 0; k < n; ++k) out.push_back(c.byte());
    }
  }
  out.resize(count);
  return out;
}

// Boolean RLE: byte RLE over bit-packed bytes, MSB first.
std::vector<uint8_t> decode_bool_rle(std::vector<uint8_t> const& s,
                                     int64_t count) {
  auto bytes = decode_byte_rle(s, (count + 7) / 8);
  std::vector<uint8_t> out(count);
  for (int64_t i = 0; i < count; ++i) {
    out[i] = (bytes[i / 8] >> (7 - (i % 8))) & 1;
  }
  return out;
}

// Int RLEv1: control c in [0,127] -> run of c+3 with signed delta byte and
// varint base; c in [128,255] -> 256-c literal varints.
std::vector<int64_t> decode_rle_v1(std::vector<uint8_t> const& s,
                                   int64_t count, bool is_signed) {
  std::vector<int64_t> out;
  out.reserve(count);
  Cursor c{s.data(), s.size()};
  while (static_cast<int64_t>(out.size()) < count) {
    uint8_t ctrl = c.byte();
    if (ctrl < 128) {
      int run = ctrl + 3;
      int8_t delta = static_cast<int8_t>(c.byte());
      int64_t v = is_signed ? c.varint_s()
                            : static_cast<int64_t>(c.varint_u());
      for (int k = 0; k < run; ++k) out.push_back(v + k * delta);
    } else {
      int n = 256 - ctrl;
      for (int k = 0; k < n; ++k) {
        out.push_back(is_signed ? c.varint_s()
                                : static_cast<int64_t>(c.varint_u()));
      }
    }
  }
  out.resize(count);
  return out;
}

// Round a bit count up to the nearest width the RLEv2 table can encode —
// writers pack patch-list entries at getClosestFixedBits(pgw + pw), not at
// the raw sum (e.g. 25 combined bits are packed at 26).
int closest_fixed_bits(int n) {
  if (n <= 24) return n < 1 ? 1 : n;
  if (n <= 26) return 26;
  if (n <= 28) return 28;
  if (n <= 30) return 30;
  if (n <= 32) return 32;
  if (n <= 40) return 40;
  if (n <= 48) return 48;
  if (n <= 56) return 56;
  return 64;
}

// RLEv2 encoded-width table (5-bit codes).
int rle2_width(int code, bool delta_mode) {
  if (code == 0) return delta_mode ? 0 : 1;
  if (code <= 23) return code + 1;
  switch (code) {
    case 24: return 26;
    case 25: return 28;
    case 26: return 30;
    case 27: return 32;
    case 28: return 40;
    case 29: return 48;
    case 30: return 56;
    case 31: return 64;
  }
  fail("bad rle2 width code");
}

// Big-endian bit unpacking, `width` bits per value.
uint64_t read_bits(uint8_t const* p, uint64_t nbytes, uint64_t bit_pos,
                   int width) {
  uint64_t out = 0;
  for (int k = 0; k < width; ++k) {
    uint64_t bit = bit_pos + k;
    uint64_t byte = bit >> 3;
    if (byte >= nbytes) fail("bit-packed run past stream end");
    out = (out << 1) | ((p[byte] >> (7 - (bit & 7))) & 1);
  }
  return out;
}

int64_t unzigzag(uint64_t u) {
  return static_cast<int64_t>((u >> 1) ^ (~(u & 1) + 1));
}

}  // namespace

std::vector<int64_t> decode_rle_v2(uint8_t const* data, uint64_t len,
                                   int64_t count, bool is_signed) {
  std::vector<int64_t> out;
  out.reserve(count);
  Cursor c{data, len};
  while (static_cast<int64_t>(out.size()) < count) {
    uint8_t first = c.byte();
    int mode = first >> 6;
    if (mode == 0) {
      // short repeat: width (bytes) in bits 5-3, count-3 in bits 2-0
      int w = ((first >> 3) & 7) + 1;
      int n = (first & 7) + 3;
      uint64_t v = 0;
      for (int k = 0; k < w; ++k) v = (v << 8) | c.byte();
      int64_t sv = is_signed ? unzigzag(v) : static_cast<int64_t>(v);
      out.insert(out.end(), n, sv);
    } else if (mode == 1) {
      // direct: 5-bit width code, 9-bit length-1
      int w = rle2_width((first >> 1) & 0x1F, false);
      int n = ((first & 1) << 8 | c.byte()) + 1;
      uint64_t nbits = static_cast<uint64_t>(n) * w;
      uint64_t nbytes = (nbits + 7) / 8;
      if (c.pos + nbytes > c.len) fail("rle2 direct run past end");
      for (int k = 0; k < n; ++k) {
        uint64_t v = read_bits(c.p + c.pos, nbytes,
                               static_cast<uint64_t>(k) * w, w);
        out.push_back(is_signed ? unzigzag(v) : static_cast<int64_t>(v));
      }
      c.pos += nbytes;
    } else if (mode == 3) {
      // delta: base varint, delta-base signed varint, packed delta
      // magnitudes at width W (W==0 -> fixed delta)
      int w = rle2_width((first >> 1) & 0x1F, true);
      int n = ((first & 1) << 8 | c.byte()) + 1;
      int64_t base = is_signed ? c.varint_s()
                               : static_cast<int64_t>(c.varint_u());
      int64_t delta_base = c.varint_s();
      out.push_back(base);
      if (n > 1) out.push_back(base + delta_base);
      int64_t prev = base + delta_base;
      int remaining = n - 2;
      int64_t sign = delta_base < 0 ? -1 : 1;
      if (w == 0) {
        for (int k = 0; k < remaining; ++k) {
          prev += delta_base;
          out.push_back(prev);
        }
      } else {
        uint64_t nbits = static_cast<uint64_t>(remaining) * w;
        uint64_t nbytes = (nbits + 7) / 8;
        if (c.pos + nbytes > c.len) fail("rle2 delta run past end");
        for (int k = 0; k < remaining; ++k) {
          uint64_t d = read_bits(c.p + c.pos, nbytes,
                                 static_cast<uint64_t>(k) * w, w);
          prev += sign * static_cast<int64_t>(d);
          out.push_back(prev);
        }
        c.pos += nbytes;
      }
    } else {
      // patched base
      int w = rle2_width((first >> 1) & 0x1F, false);
      int n = ((first & 1) << 8 | c.byte()) + 1;
      uint8_t third = c.byte();
      int bw = ((third >> 5) & 7) + 1;            // base width, bytes
      int pw = rle2_width(third & 0x1F, false);   // patch width, bits
      uint8_t fourth = c.byte();
      int pgw = ((fourth >> 5) & 7) + 1;          // patch gap width, bits
      int pl = fourth & 0x1F;                     // patch list length
      // base: big-endian, MSB of the bw-byte field is the sign bit
      uint64_t raw_base = 0;
      for (int k = 0; k < bw; ++k) raw_base = (raw_base << 8) | c.byte();
      uint64_t sign_mask = 1ull << (bw * 8 - 1);
      int64_t base = (raw_base & sign_mask)
                         ? -static_cast<int64_t>(raw_base & (sign_mask - 1))
                         : static_cast<int64_t>(raw_base);
      uint64_t nbits = static_cast<uint64_t>(n) * w;
      uint64_t nbytes = (nbits + 7) / 8;
      if (c.pos + nbytes > c.len) fail("rle2 patched run past end");
      std::vector<uint64_t> vals(n);
      for (int k = 0; k < n; ++k) {
        vals[k] = read_bits(c.p + c.pos, nbytes,
                            static_cast<uint64_t>(k) * w, w);
      }
      c.pos += nbytes;
      int pbits = closest_fixed_bits(pgw + pw);
      uint64_t pnbits = static_cast<uint64_t>(pl) * pbits;
      uint64_t pnbytes = (pnbits + 7) / 8;
      if (c.pos + pnbytes > c.len) fail("rle2 patch list past end");
      uint64_t idx = 0;
      for (int k = 0; k < pl; ++k) {
        uint64_t entry = read_bits(c.p + c.pos, pnbytes,
                                   static_cast<uint64_t>(k) * pbits, pbits);
        uint64_t gap = entry >> pw;
        uint64_t patch = entry & ((pw == 64) ? ~0ull : ((1ull << pw) - 1));
        idx += gap;
        if (idx >= static_cast<uint64_t>(n)) fail("rle2 patch index oob");
        vals[idx] |= patch << w;
      }
      c.pos += pnbytes;
      for (int k = 0; k < n; ++k) {
        out.push_back(base + static_cast<int64_t>(vals[k]));
      }
    }
  }
  out.resize(count);
  return out;
}

namespace {

std::vector<int64_t> decode_int_stream(std::vector<uint8_t> const& s,
                                       int64_t count, bool is_signed,
                                       bool v2) {
  if (v2) return decode_rle_v2(s.data(), s.size(), count, is_signed);
  return decode_rle_v1(s, count, is_signed);
}

// ---- file structure -------------------------------------------------------

struct TypeInfo {
  int32_t kind = 0;
  int32_t precision = 0;
  int32_t scale = 0;
  std::string name;
};

struct FileMeta {
  uint64_t compression = kCompNone;
  int64_t num_rows = 0;
  std::vector<TypeInfo> leaves;   // flat struct children; leaf i = column id i+1
  std::vector<Message> stripes;   // StripeInformation messages (parsed)
  std::vector<std::string> stripe_bufs;  // backing bytes for `stripes`
};

FileMeta parse_meta(uint8_t const* file, uint64_t len) {
  if (len < 4 || std::memcmp(file, "ORC", 3) != 0) {
    fail("not an ORC file (missing magic)");
  }
  uint8_t ps_len = file[len - 1];
  if (1ull + ps_len > len) fail("bad postscript length");
  Message ps = Message::parse(file + len - 1 - ps_len, ps_len);
  if (ps.bytes(kPsMagic) != "ORC") fail("postscript magic mismatch");
  FileMeta meta;
  meta.compression = ps.u64(kPsCompression, kCompNone);
  uint64_t footer_len = ps.u64(kPsFooterLength);
  // subtraction form: footer_len is an attacker-controlled varint and the
  // additive check would wrap in uint64
  if (footer_len > len - 1 - ps_len) fail("footer length out of bounds");
  uint64_t footer_off = len - 1 - ps_len - footer_len;
  auto footer_bytes =
      decode_stream(file + footer_off, footer_len, meta.compression);
  Message footer = Message::parse(footer_bytes.data(), footer_bytes.size());
  meta.num_rows = static_cast<int64_t>(footer.u64(kFtNumRows));

  auto type_fields = footer.fields(kFtTypes);
  if (type_fields.empty()) fail("missing types");
  Message root = Message::parse(
      reinterpret_cast<uint8_t const*>(type_fields[0]->bytes.data()),
      type_fields[0]->bytes.size());
  if (root.u64(kTyKind) != static_cast<uint64_t>(Kind::STRUCT)) {
    fail("root type must be a struct");
  }
  auto names = root.fields(kTyFieldNames);
  auto subtypes = root.fields(kTySubtypes);
  if (subtypes.size() != type_fields.size() - 1) {
    fail("nested ORC schemas are not supported yet (flat columns only)");
  }
  for (uint64_t i = 1; i < type_fields.size(); ++i) {
    Message ty = Message::parse(
        reinterpret_cast<uint8_t const*>(type_fields[i]->bytes.data()),
        type_fields[i]->bytes.size());
    TypeInfo info;
    info.kind = static_cast<int32_t>(ty.u64(kTyKind));
    info.precision = static_cast<int32_t>(ty.u64(kTyPrecision));
    info.scale = static_cast<int32_t>(ty.u64(kTyScale));
    if (i - 1 < names.size()) info.name = std::string(names[i - 1]->bytes);
    if (ty.field(kTySubtypes) != nullptr) {
      fail("nested ORC schemas are not supported yet (flat columns only)");
    }
    meta.leaves.push_back(std::move(info));
  }
  for (auto const* f : footer.fields(kFtStripes)) {
    meta.stripe_bufs.emplace_back(f->bytes);
  }
  for (auto const& buf : meta.stripe_bufs) {
    meta.stripes.push_back(Message::parse(
        reinterpret_cast<uint8_t const*>(buf.data()), buf.size()));
  }
  return meta;
}

struct StreamEntry {
  uint64_t kind = 0;
  uint64_t col = 0;
  uint64_t offset = 0;  // absolute file offset
  uint64_t length = 0;
};

struct StripeDirectory {
  std::vector<StreamEntry> streams;
  std::vector<uint64_t> encodings;   // ColumnEncoding.kind per column id
  std::vector<uint64_t> dict_sizes;  // ColumnEncoding.dictionarySize
  std::string writer_timezone;       // StripeFooter.writerTimezone
};

// Parse the stripe footer's stream directory ONCE per stripe. The streams
// are laid out back to back from the stripe's start — index-region streams
// (ROW_INDEX etc.) first, inside indexLength, then the data streams — so
// the cursor starts at the stripe offset and walks EVERY listed stream.
StripeDirectory parse_directory(uint64_t file_len, Message const& stripe,
                                Message const& sf) {
  StripeDirectory dir;
  uint64_t pos = stripe.u64(kSiOffset);
  for (auto const* f : sf.fields(kSfStreams)) {
    Message st = Message::parse(
        reinterpret_cast<uint8_t const*>(f->bytes.data()), f->bytes.size());
    StreamEntry e;
    e.kind = st.u64(kStKind);
    e.col = st.u64(kStColumn);
    e.length = st.u64(kStLength);
    e.offset = pos;
    // overflow-safe bounds check (lengths are attacker-controlled varints)
    if (e.offset > file_len || e.length > file_len - e.offset) {
      fail("stream extends past end of file");
    }
    dir.streams.push_back(e);
    pos += e.length;
  }
  for (auto const* f : sf.fields(kSfColumns)) {
    Message enc = Message::parse(
        reinterpret_cast<uint8_t const*>(f->bytes.data()), f->bytes.size());
    dir.encodings.push_back(enc.u64(kCeKind));
    dir.dict_sizes.push_back(enc.u64(kCeDictSize));
  }
  dir.writer_timezone = std::string(sf.bytes(kSfWriterTimezone));
  return dir;
}

struct ColumnStreams {
  std::vector<uint8_t> present, data, length, dict, secondary;
  bool has_present = false;
  uint64_t encoding = kEncDirect;
  uint64_t dict_size = 0;
};

// Slice + un-frame the streams that belong to `col` (1-based; 0 = root).
ColumnStreams gather_streams(uint8_t const* file, FileMeta const& meta,
                             StripeDirectory const& dir, uint64_t col) {
  ColumnStreams out;
  for (auto const& e : dir.streams) {
    if (e.col != col) continue;
    if (e.kind != kStreamPresent && e.kind != kStreamData &&
        e.kind != kStreamLength && e.kind != kStreamDictData &&
        e.kind != kStreamSecondary) {
      continue;  // row indexes, bloom filters, ...
    }
    auto decoded = decode_stream(file + e.offset, e.length, meta.compression);
    if (e.kind == kStreamPresent) {
      out.present = std::move(decoded);
      out.has_present = true;
    } else if (e.kind == kStreamData) {
      out.data = std::move(decoded);
    } else if (e.kind == kStreamLength) {
      out.length = std::move(decoded);
    } else if (e.kind == kStreamDictData) {
      out.dict = std::move(decoded);
    } else {
      out.secondary = std::move(decoded);
    }
  }
  if (col < dir.encodings.size()) {
    out.encoding = dir.encodings[col];
    out.dict_size = dir.dict_sizes[col];
  }
  return out;
}

void decode_stripe_column(uint8_t const* file, FileMeta const& meta,
                          StripeDirectory const& dir, int32_t leaf,
                          int64_t stripe_rows, OrcColumn& out) {
  auto const& ty = meta.leaves[leaf];
  ColumnStreams s =
      gather_streams(file, meta, dir, static_cast<uint64_t>(leaf) + 1);

  std::vector<uint8_t> valid(stripe_rows, 1);
  int64_t n_present = stripe_rows;
  if (s.has_present) {
    valid = decode_bool_rle(s.present, stripe_rows);
    n_present = 0;
    for (uint8_t v : valid) n_present += v;
  }
  bool const v2 =
      s.encoding == kEncDirectV2 || s.encoding == kEncDictionaryV2;
  bool const dict_enc =
      s.encoding == kEncDictionary || s.encoding == kEncDictionaryV2;

  auto scatter_i64 = [&](std::vector<int64_t> const& vals) {
    int64_t next = 0;
    for (int64_t r = 0; r < stripe_rows; ++r) {
      out.data.push_back(valid[r] ? vals[next++] : 0);
    }
  };

  switch (static_cast<Kind>(ty.kind)) {
    case Kind::BOOLEAN: {
      auto bits = decode_bool_rle(s.data, n_present);
      std::vector<int64_t> vals(bits.begin(), bits.end());
      scatter_i64(vals);
      break;
    }
    case Kind::BYTE: {
      auto bytes = decode_byte_rle(s.data, n_present);
      std::vector<int64_t> vals;
      vals.reserve(n_present);
      for (uint8_t b : bytes) vals.push_back(static_cast<int8_t>(b));
      scatter_i64(vals);
      break;
    }
    case Kind::SHORT:
    case Kind::INT:
    case Kind::LONG:
    case Kind::DATE:
      scatter_i64(decode_int_stream(s.data, n_present, true, v2));
      break;
    case Kind::FLOAT: {
      if (s.data.size() < static_cast<uint64_t>(n_present) * 4) {
        fail("float stream underrun");
      }
      std::vector<int64_t> vals;
      vals.reserve(n_present);
      for (int64_t k = 0; k < n_present; ++k) {
        uint32_t bits;
        std::memcpy(&bits, s.data.data() + k * 4, 4);
        vals.push_back(static_cast<int64_t>(bits));
      }
      scatter_i64(vals);
      break;
    }
    case Kind::DOUBLE: {
      if (s.data.size() < static_cast<uint64_t>(n_present) * 8) {
        fail("double stream underrun");
      }
      std::vector<int64_t> vals;
      vals.reserve(n_present);
      for (int64_t k = 0; k < n_present; ++k) {
        uint64_t bits;
        std::memcpy(&bits, s.data.data() + k * 8, 8);
        vals.push_back(static_cast<int64_t>(bits));
      }
      scatter_i64(vals);
      break;
    }
    case Kind::DECIMAL: {
      // unbounded base-128 zigzag varints + scale stream (ignored: the
      // footer scale is authoritative for modern writers)
      Cursor c{s.data.data(), s.data.size()};
      if (ty.precision > 18) {
        // precision 19-38 -> DECIMAL128 limb pairs, two i64 per row
        std::vector<std::pair<uint64_t, int64_t>> vals;
        vals.reserve(n_present);
        for (int64_t k = 0; k < n_present; ++k) {
          vals.push_back(c.varint_s128());
        }
        int64_t next = 0;
        for (int64_t r = 0; r < stripe_rows; ++r) {
          if (valid[r]) {
            out.data.push_back(static_cast<int64_t>(vals[next].first));
            out.data.push_back(vals[next].second);
            ++next;
          } else {
            out.data.push_back(0);
            out.data.push_back(0);
          }
        }
        break;
      }
      std::vector<int64_t> vals;
      vals.reserve(n_present);
      for (int64_t k = 0; k < n_present; ++k) vals.push_back(c.varint_s());
      scatter_i64(vals);
      break;
    }
    case Kind::TIMESTAMP: {
      // data = signed seconds from 2015-01-01 in the WRITER's timezone
      // (stripe footer writerTimezone). Two wire conventions exist for
      // pre-1970 fractional values, both truncating seconds toward zero:
      //   * ORC C++ / pyarrow emit SIGNED nanos with the same sign as the
      //     value (observed on the wire: -1.5s -> secs=-1, nanos=-5e8) —
      //     plain signed addition reconstructs exactly;
      //   * orc-java's TimestampTreeReader convention keeps nanos
      //     POSITIVE and the reader subtracts one second when the total is
      //     negative with nonzero nanos (cuDF's ORC decoder matches).
      // The two are distinguishable per value: negative total seconds with
      // POSITIVE nanos can only come from a java-convention writer, so
      // that exact case gets the -1s adjustment and everything else is
      // signed addition. Wall-clock conversion needs a tz database, so
      // non-UTC writers fail loudly rather than shift silently; secondary
      // = nanos with the removed-trailing-zero count in the low 3 bits
      // (z > 0 means value * 10^(z+1)). Result: int64 unix-epoch
      // microseconds.
      // non-UTC writer zones no longer fail here: the decode emits
      // WALL-CLOCK micros and read_file records the zone; the Python
      // layer owns the tz database (zoneinfo via pyarrow) and converts
      // wall -> UTC there.
      constexpr int64_t kOrcEpochSeconds = 1420070400;
      auto secs = decode_int_stream(s.data, n_present, true, v2);
      auto nenc = decode_int_stream(s.secondary, n_present, false, v2);
      std::vector<int64_t> vals;
      vals.reserve(n_present);
      for (int64_t k = 0; k < n_present; ++k) {
        int64_t v = nenc[k];
        int64_t nanos = v >> 3;
        int z = static_cast<int>(v & 7);
        if (z != 0) {
          for (int q = 0; q < z + 1; ++q) nanos *= 10;
        }
        int64_t total_secs = secs[k] + kOrcEpochSeconds;
        if (total_secs < 0 && nanos > 0) total_secs -= 1;
        vals.push_back(total_secs * 1000000 + nanos / 1000);
      }
      scatter_i64(vals);
      break;
    }
    case Kind::BINARY:
    case Kind::STRING:
    case Kind::VARCHAR:
    case Kind::CHAR: {
      if (out.offsets.empty()) out.offsets.push_back(0);
      if (dict_enc) {
        auto lens = decode_int_stream(s.length, s.dict_size, false, v2);
        std::vector<std::pair<uint64_t, uint64_t>> entries;  // (start, len)
        uint64_t at = 0;
        for (int64_t l : lens) {
          entries.emplace_back(at, l);
          at += l;
        }
        if (at > s.dict.size()) fail("dictionary chars underrun");
        auto idx = decode_int_stream(s.data, n_present, false, v2);
        int64_t next = 0;
        for (int64_t r = 0; r < stripe_rows; ++r) {
          int32_t last = out.offsets.back();
          if (valid[r]) {
            uint64_t id = static_cast<uint64_t>(idx[next++]);
            if (id >= entries.size()) fail("dictionary index oob");
            auto [st, ln] = entries[id];
            out.chars.insert(out.chars.end(), s.dict.data() + st,
                             s.dict.data() + st + ln);
            out.offsets.push_back(last + static_cast<int32_t>(ln));
          } else {
            out.offsets.push_back(last);
          }
        }
      } else {
        auto lens = decode_int_stream(s.length, n_present, false, v2);
        uint64_t at = 0;
        int64_t next = 0;
        for (int64_t r = 0; r < stripe_rows; ++r) {
          int32_t last = out.offsets.back();
          if (valid[r]) {
            uint64_t ln = static_cast<uint64_t>(lens[next++]);
            if (at + ln > s.data.size()) fail("string chars underrun");
            out.chars.insert(out.chars.end(), s.data.data() + at,
                             s.data.data() + at + ln);
            at += ln;
            out.offsets.push_back(last + static_cast<int32_t>(ln));
          } else {
            out.offsets.push_back(last);
          }
        }
      }
      break;
    }
    default:
      fail("unsupported ORC type kind " + std::to_string(ty.kind));
  }

  if (s.has_present || !out.validity.empty()) {
    if (out.validity.size() < static_cast<size_t>(out.num_rows)) {
      out.validity.resize(out.num_rows, 1);
    }
    out.validity.insert(out.validity.end(), valid.begin(), valid.end());
  }
  out.num_rows += stripe_rows;
}

}  // namespace

std::vector<StripeInfo> stripe_infos(uint8_t const* file, uint64_t len) {
  FileMeta meta = parse_meta(file, len);
  std::vector<StripeInfo> out;
  for (auto const& st : meta.stripes) {
    StripeInfo info;
    info.num_rows = static_cast<int64_t>(st.u64(kSiNumRows));
    info.data_bytes = static_cast<int64_t>(
        st.u64(kSiIndexLength) + st.u64(kSiDataLength) +
        st.u64(kSiFooterLength));
    out.push_back(info);
  }
  return out;
}

OrcResult read_file(uint8_t const* file, uint64_t len,
                    std::optional<std::vector<int32_t>> const& columns,
                    std::optional<std::vector<int32_t>> const& stripes) {
  FileMeta meta = parse_meta(file, len);
  std::vector<int32_t> cols;
  if (columns.has_value()) {
    cols = *columns;
  } else {
    for (uint64_t i = 0; i < meta.leaves.size(); ++i) {
      cols.push_back(static_cast<int32_t>(i));
    }
  }
  std::vector<int32_t> strps;
  if (stripes.has_value()) {
    strps = *stripes;
  } else {
    for (uint64_t i = 0; i < meta.stripes.size(); ++i) {
      strps.push_back(static_cast<int32_t>(i));
    }
  }

  OrcResult res;
  bool first_stripe = true;
  for (int32_t cidx : cols) {
    if (cidx < 0 || static_cast<uint64_t>(cidx) >= meta.leaves.size()) {
      fail("column index out of range");
    }
    OrcColumn col;
    auto const& ty = meta.leaves[cidx];
    col.name = ty.name;
    col.kind = ty.kind;
    col.precision = ty.precision;
    col.scale = ty.scale;
    res.columns.push_back(std::move(col));
  }

  for (int32_t sidx : strps) {
    if (sidx < 0 || static_cast<uint64_t>(sidx) >= meta.stripes.size()) {
      fail("stripe index out of range");
    }
    auto const& stripe = meta.stripes[sidx];
    int64_t stripe_rows = static_cast<int64_t>(stripe.u64(kSiNumRows));
    // stripe footer sits after index + data; every addend is an
    // attacker-controlled varint, so check without unsigned wraparound
    uint64_t off = stripe.u64(kSiOffset);
    uint64_t ilen = stripe.u64(kSiIndexLength);
    uint64_t dlen = stripe.u64(kSiDataLength);
    uint64_t sf_len = stripe.u64(kSiFooterLength);
    if (off > len || ilen > len - off || dlen > len - off - ilen ||
        sf_len > len - off - ilen - dlen) {
      fail("stripe footer out of bounds");
    }
    uint64_t sf_off = off + ilen + dlen;
    auto sf_bytes = decode_stream(file + sf_off, sf_len, meta.compression);
    Message sf = Message::parse(sf_bytes.data(), sf_bytes.size());
    StripeDirectory dir = parse_directory(len, stripe, sf);
    if (first_stripe) {
      res.writer_timezone = dir.writer_timezone;
      first_stripe = false;
    } else if (res.writer_timezone != dir.writer_timezone) {
      // includes empty-vs-named mixes: an unrecorded zone reads as UTC
      // here, so silently adopting a sibling stripe's named zone would
      // shift that stripe's values — fail loudly instead
      fail("stripes disagree on writerTimezone ('" +
           res.writer_timezone + "' vs '" + dir.writer_timezone + "')");
    }
    for (uint64_t k = 0; k < cols.size(); ++k) {
      decode_stripe_column(file, meta, dir, cols[k], stripe_rows,
                           res.columns[k]);
    }
    res.num_rows += stripe_rows;
  }

  // normalize all-valid masks to empty
  for (auto& col : res.columns) {
    bool all = true;
    for (uint8_t v : col.validity) {
      if (!v) { all = false; break; }
    }
    if (all) col.validity.clear();
    if ((col.kind == static_cast<int32_t>(Kind::STRING) ||
         col.kind == static_cast<int32_t>(Kind::VARCHAR) ||
         col.kind == static_cast<int32_t>(Kind::CHAR)) &&
        col.offsets.empty()) {
      col.offsets.push_back(0);
    }
  }
  return res;
}

}  // namespace orc
}  // namespace tpudf
