// Minimal native smoke test (run via ctest): build a synthetic footer with
// the generic thrift writer, then parse -> prune -> filter -> serialize and
// check invariants. The thorough oracle tests live in tests/ (Python),
// which cross-check against an independent pure-python compact codec.

#include <cstdio>
#include <cstdlib>
#include <cstring>

// assert() compiles out under -DNDEBUG (Release); this test must be able to
// fail in every build type.
#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,    \
                   __LINE__, #cond);                                   \
      std::exit(1);                                                    \
    }                                                                  \
  } while (0)

#include "tpudf/parquet_footer.hpp"

using tpudf::thrift::Value;
using tpudf::thrift::WireType;
namespace fid = tpudf::parquet::fid;

namespace {

Value schema_element(char const* name, int64_t num_children, bool leaf) {
  Value se(WireType::STRUCT);
  if (leaf) se.set_field(fid::kSeType, WireType::I32).i = 1;  // Type INT32
  se.set_field(fid::kSeName, WireType::BINARY).bin = name;
  if (num_children >= 0) {
    se.set_field(fid::kSeNumChildren, WireType::I32).i = num_children;
  }
  return se;
}

Value column_chunk(int64_t data_page_offset, int64_t total_compressed) {
  Value cc(WireType::STRUCT);
  Value& md = cc.set_field(fid::kCcMetaData, WireType::STRUCT);
  md.set_field(fid::kCmTotalCompressedSize, WireType::I64).i = total_compressed;
  md.set_field(fid::kCmDataPageOffset, WireType::I64).i = data_page_offset;
  return cc;
}

}  // namespace

int main() {
  // footer: root { a: int32, b: int32, c: int32 }, two row groups
  Value meta(WireType::STRUCT);
  Value& schema = meta.set_field(fid::kSchema, WireType::LIST);
  schema.elem_type = WireType::STRUCT;
  schema.elems.push_back(schema_element("root", 3, false));
  schema.elems.push_back(schema_element("a", -1, true));
  schema.elems.push_back(schema_element("b", -1, true));
  schema.elems.push_back(schema_element("c", -1, true));
  meta.set_field(fid::kNumRows, WireType::I64).i = 100;
  Value& groups = meta.set_field(fid::kRowGroups, WireType::LIST);
  groups.elem_type = WireType::STRUCT;
  for (int g = 0; g < 2; ++g) {
    Value rg(WireType::STRUCT);
    Value& cols = rg.set_field(fid::kRgColumns, WireType::LIST);
    cols.elem_type = WireType::STRUCT;
    for (int c = 0; c < 3; ++c) {
      cols.elems.push_back(column_chunk(4 + g * 3000 + c * 1000, 1000));
    }
    rg.set_field(fid::kRgNumRows, WireType::I64).i = 50;
    rg.set_field(fid::kRgTotalCompressedSize, WireType::I64).i = 3000;
    groups.elems.push_back(std::move(rg));
  }

  std::string bytes = tpudf::thrift::serialize_struct(meta);

  // parse -> prune to {c, a} -> keep only the first row group's byte range
  auto footer = tpudf::parquet::Footer::parse(
      reinterpret_cast<uint8_t const*>(bytes.data()), bytes.size());
  footer.prune_columns({"c", "a"}, {0, 0}, 2, false);
  footer.filter_row_groups(0, 3000);
  footer.filter_columns();

  CHECK(footer.num_columns() == 2);
  CHECK(footer.num_rows() == 50);

  std::string framed = footer.serialize_framed();
  CHECK(framed.size() > 12);
  CHECK(std::memcmp(framed.data(), "PAR1", 4) == 0);
  CHECK(std::memcmp(framed.data() + framed.size() - 4, "PAR1", 4) == 0);

  // the framed body re-parses and retains the pruned shape
  auto again = tpudf::parquet::Footer::parse(
      reinterpret_cast<uint8_t const*>(framed.data()) + 4, framed.size() - 12);
  CHECK(again.num_columns() == 2);
  CHECK(again.num_rows() == 50);

  // case-insensitive prune matches mixed-case request
  auto f2 = tpudf::parquet::Footer::parse(
      reinterpret_cast<uint8_t const*>(bytes.data()), bytes.size());
  f2.prune_columns({"A"}, {0}, 1, false);
  f2.filter_columns();
  CHECK(f2.num_columns() == 0);  // case-sensitive: no match
  auto f3 = tpudf::parquet::Footer::parse(
      reinterpret_cast<uint8_t const*>(bytes.data()), bytes.size());
  f3.prune_columns({"a"}, {0}, 1, true);
  f3.filter_columns();
  CHECK(f3.num_columns() == 1);

  std::printf("tpudf selftest OK\n");
  return 0;
}
