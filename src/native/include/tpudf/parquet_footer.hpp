// Parquet footer prune/filter engine (component C3' — TPU-build equivalent
// of reference src/main/cpp/src/NativeParquetJni.cpp, pure CPU).
//
// Behavior parity targets:
//   * schema-tree column pruning from a depth-first (names, num_children)
//     request, case-sensitive or case-insensitive
//     (reference NativeParquetJni.cpp:100-368);
//   * row-group filtering to a partition byte range by the parquet-mr
//     midpoint rule, with the PARQUET-2078 bad-file_offset fallback
//     (reference NativeParquetJni.cpp:370-450);
//   * column_orders and per-row-group chunk gathering
//     (reference NativeParquetJni.cpp:483-492,525-540);
//   * re-serialization with PAR1 magic + footer-length framing
//     (reference NativeParquetJni.cpp:589-623).
//
// Implementation is original: footers are held as a generic thrift value
// tree (thrift_compact.hpp) and edited in place by parquet.thrift field id,
// so unknown/future fields pass through untouched.

#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "tpudf/thrift_compact.hpp"

namespace tpudf {
namespace parquet {

// parquet.thrift field ids used by the engine (public format spec).
namespace fid {
// FileMetaData
constexpr int16_t kSchema = 2;
constexpr int16_t kNumRows = 3;
constexpr int16_t kRowGroups = 4;
constexpr int16_t kColumnOrders = 7;
// SchemaElement
constexpr int16_t kSeType = 1;
constexpr int16_t kSeName = 4;
constexpr int16_t kSeNumChildren = 5;
// RowGroup
constexpr int16_t kRgColumns = 1;
constexpr int16_t kRgNumRows = 3;
constexpr int16_t kRgFileOffset = 5;
constexpr int16_t kRgTotalCompressedSize = 6;
// ColumnChunk
constexpr int16_t kCcMetaData = 3;
// ColumnMetaData
constexpr int16_t kCmTotalCompressedSize = 7;
constexpr int16_t kCmDataPageOffset = 9;
constexpr int16_t kCmDictionaryPageOffset = 11;
}  // namespace fid

// UTF-8-aware lower-casing (ASCII + Latin-1 supplement; other code points
// pass through). The reference's mbstowcs/towlower version is
// locale-dependent and self-described as "probably good enough"
// (NativeParquetJni.cpp:40-77); this one is deterministic.
std::string utf8_to_lower(std::string const& in);

// A parsed footer plus the operations the JNI surface exposes.
class Footer {
 public:
  // Parse from raw thrift bytes (no PAR1 framing). Throws on malformed
  // input; same anti-bomb limits as the reference.
  static Footer parse(uint8_t const* buf, uint64_t len);

  // Prune the schema to the requested column tree: `names` and
  // `num_children` flattened depth-first, root excluded;
  // `parent_num_children` = number of root children requested. Prunes the
  // schema list and column_orders and remembers the chunk gather map for
  // filter_columns(). Does NOT touch row groups: the midpoint filter must
  // see the file's original first column, so call order is
  // prune_columns -> filter_row_groups -> filter_columns (the reference
  // orders readAndFilter the same way, NativeParquetJni.cpp:524-545).
  void prune_columns(std::vector<std::string> const& names,
                     std::vector<int32_t> const& num_children,
                     int32_t parent_num_children, bool ignore_case);

  // Keep only row groups whose midpoint falls in
  // [part_offset, part_offset + part_length). Negative part_length = keep
  // all (reference NativeParquetJni.cpp:542-544 gates on part_length >= 0).
  void filter_row_groups(int64_t part_offset, int64_t part_length);

  // Gather each surviving row group's column chunks to the pruned columns
  // (reference filter_columns, NativeParquetJni.cpp:483-492). Requires a
  // prior prune_columns call.
  void filter_columns();

  int64_t num_rows() const;     // sum of remaining row-group num_rows
  int32_t num_columns() const;  // root schema element's num_children

  // Compact-serialize with PAR1 + length framing:
  // [PAR1][thrift bytes][u32 LE length][PAR1].
  std::string serialize_framed() const;

  thrift::Value& root() { return meta_; }
  thrift::Value const& root() const { return meta_; }

 private:
  explicit Footer(thrift::Value meta) : meta_(std::move(meta)) {}
  thrift::Value meta_;
  std::vector<int> chunk_gather_;
  bool pruned_ = false;
};

}  // namespace parquet
}  // namespace tpudf
