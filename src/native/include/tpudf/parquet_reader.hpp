// Parquet data-page reader (the chunked-reader capability of the vendored
// substrate: the reference links cuDF's Arrow-parquet reader statically,
// build-libcudf.xml:45, CMakeLists.txt:104-119; BASELINE.json's north star
// names the "Parquet chunked reader" explicitly).
//
// CPU decode -> Arrow-layout host buffers, which the Python surface stages
// into HBM; chunking happens at row-group granularity (a chunk = as many
// row groups as fit a byte budget), the same external behavior as cuDF's
// chunked parquet reader.
//
// Supported subset (errors are explicit, never silent):
//   * flat schemas, nested STRUCTs, and single-level LISTs (repetition
//     depth 1; nested lists rejected). Nested leaves surface as compact
//     values + def/rep level streams for Dremel assembly one layer up.
//   * physical types BOOLEAN, INT32, INT64, FLOAT, DOUBLE, BYTE_ARRAY,
//     FIXED_LEN_BYTE_ARRAY (decimals to 16 bytes)
//   * encodings PLAIN, PLAIN_DICTIONARY / RLE_DICTIONARY, the DELTA_*
//     family (+ RLE def/rep levels)
//   * page types DATA_PAGE (v1), DATA_PAGE_V2, DICTIONARY_PAGE
//   * codecs UNCOMPRESSED, SNAPPY (built-in decoder), GZIP (zlib), ZSTD

#pragma once

#include <cstdint>
#include <optional>
#include <string>
#include <vector>

namespace tpudf {
namespace parquet {

// parquet.thrift Type enum values (public format spec).
enum class Physical : int32_t {
  BOOLEAN = 0,
  INT32 = 1,
  INT64 = 2,
  INT96 = 3,
  FLOAT = 4,
  DOUBLE = 5,
  BYTE_ARRAY = 6,
  FIXED_LEN_BYTE_ARRAY = 7,
};

struct ColumnData {
  std::string name;
  int32_t physical = 0;        // Physical enum value
  int32_t converted = -1;      // parquet ConvertedType, -1 = absent
  int32_t scale = 0;           // decimal scale (parquet convention, >= 0)
  int32_t precision = 0;
  int32_t type_length = 0;     // FIXED_LEN_BYTE_ARRAY width
  bool optional = false;

  int32_t max_def = 0;         // definition-level bound for this leaf
  int32_t max_rep = 0;         // repetition-level bound (1 = inside a list)
  bool is_nested = false;      // leaf sits under a group; compact storage

  int64_t num_rows = 0;        // TOP-LEVEL rows (rep==0 entries)
  int64_t n_levels = 0;        // level entries (== num_rows for non-list)
  int64_t n_present = 0;       // values actually materialized (nested only)
  // Flat leaves (max_def <= 1, max_rep == 0): one value per row, nulls
  // zero-filled. BOOLEAN = 1 byte/row; INT32/FLOAT = 4; INT64/DOUBLE = 8;
  // FIXED_LEN_BYTE_ARRAY = type_length bytes/row (raw big-endian).
  // Nested leaves: COMPACT present values only (n_present of them);
  // row structure reconstructs from def/rep levels (Dremel assembly,
  // done by the Python surface).
  std::vector<uint8_t> data;
  // BYTE_ARRAY: offsets[n+1] + chars; data stays empty.
  std::vector<int32_t> offsets;
  std::vector<uint8_t> chars;
  // 1 byte per row, 1 = valid. Empty = all rows valid. (flat leaves only)
  std::vector<uint8_t> validity;
  // Nested leaves only: one entry per level position.
  std::vector<uint8_t> def_levels;
  std::vector<uint8_t> rep_levels;  // only when max_rep > 0
};

struct ReadResult {
  int64_t num_rows = 0;
  std::vector<ColumnData> columns;
  // preorder schema-tree dump (one "name\tnum_children\trepetition\t
  // physical\tconverted\tscale\tprecision\ttype_length" line per element)
  // for nested column assembly
  std::string schema_desc;
};

struct RowGroupInfo {
  int64_t num_rows = 0;
  int64_t total_byte_size = 0;  // compressed on-disk footprint when known
};

// Footer-level probes for planning chunked reads.
std::vector<RowGroupInfo> row_group_infos(uint8_t const* file, uint64_t len);
std::vector<std::string> column_names(uint8_t const* file, uint64_t len);

// Decode selected columns of selected row groups from a complete in-memory
// Parquet file (PAR1 framed). nullopt means "all"; an empty list genuinely
// selects nothing (a planner's filtered-to-empty row-group list must yield
// an empty table, not the whole file). Throws std::runtime_error on
// malformed input or unsupported features.
ReadResult read_file(uint8_t const* file, uint64_t len,
                     std::optional<std::vector<int32_t>> const& column_indices,
                     std::optional<std::vector<int32_t>> const& row_group_indices);

// Raw snappy block-format decompressor (exposed for tests and the ORC
// reader). Pass kSnappyNoExpectedSize when the container format carries no
// independent uncompressed size to cross-check (ORC); parquet callers pass
// the page header's declared size.
constexpr uint64_t kSnappyNoExpectedSize = ~0ull;
std::vector<uint8_t> snappy_uncompress(uint8_t const* in, uint64_t n,
                                       uint64_t expected_out);

}  // namespace parquet
}  // namespace tpudf
