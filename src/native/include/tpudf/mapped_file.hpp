// Read-only mmap wrapper — the storage->decode half of the cuFile/GDS role
// (reference CMakeLists.txt:200-222): the decoder reads pages directly out
// of the page cache instead of a caller-materialized buffer, so chunked
// reads of large files touch only the byte ranges they decode.

#pragma once

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>

namespace tpudf {

class MappedFile {
 public:
  explicit MappedFile(char const* path) {
    int fd = ::open(path, O_RDONLY | O_CLOEXEC);
    if (fd < 0) {
      throw std::runtime_error(std::string("open ") + path + ": " +
                               std::strerror(errno));
    }
    struct stat st;
    if (::fstat(fd, &st) != 0) {
      int e = errno;
      ::close(fd);
      throw std::runtime_error(std::string("fstat ") + path + ": " +
                               std::strerror(e));
    }
    size_ = static_cast<uint64_t>(st.st_size);
    if (size_ > 0) {
      void* p = ::mmap(nullptr, size_, PROT_READ, MAP_PRIVATE, fd, 0);
      if (p == MAP_FAILED) {
        int e = errno;
        ::close(fd);
        throw std::runtime_error(std::string("mmap ") + path + ": " +
                                 std::strerror(e));
      }
      data_ = static_cast<uint8_t const*>(p);
    }
    ::close(fd);  // the mapping outlives the descriptor
  }

  MappedFile(MappedFile const&) = delete;
  MappedFile& operator=(MappedFile const&) = delete;

  ~MappedFile() {
    if (data_ != nullptr) {
      ::munmap(const_cast<uint8_t*>(data_), size_);
    }
  }

  uint8_t const* data() const { return data_; }
  uint64_t size() const { return size_; }

 private:
  uint8_t const* data_ = nullptr;
  uint64_t size_ = 0;
};

}  // namespace tpudf
