// Generic protobuf wire-format reader — the ORC metadata counterpart of the
// generic thrift codec (thrift_compact.hpp): ORC footers are protobuf
// messages (postscript/footer/stripe footer), parsed here into a tagged
// field multimap by field number, with no protoc or generated code in the
// build. Unknown fields are preserved; nested messages are lazily reparsed
// from their bytes.

#pragma once

#include <cstdint>
#include <string>
#include <string_view>
#include <vector>

namespace tpudf {
namespace pb {

enum class WireType : uint8_t {
  VARINT = 0,
  FIXED64 = 1,
  BYTES = 2,
  FIXED32 = 5,
};

struct PbField {
  uint32_t number = 0;
  WireType type = WireType::VARINT;
  uint64_t varint = 0;      // VARINT / FIXED64 / FIXED32 payloads
  std::string_view bytes;   // BYTES payload (view into the parsed buffer)
};

// One parsed message: fields in wire order (repeated fields appear once per
// occurrence). Views point into the caller's buffer — keep it alive.
class Message {
 public:
  static Message parse(uint8_t const* buf, uint64_t len);

  // First field with this number (nullptr if absent).
  PbField const* field(uint32_t number) const;
  // All occurrences (for repeated fields).
  std::vector<PbField const*> fields(uint32_t number) const;

  uint64_t u64(uint32_t number, uint64_t dflt = 0) const;
  std::string_view bytes(uint32_t number) const;  // empty if absent

  std::vector<PbField> const& all() const { return fields_; }

 private:
  std::vector<PbField> fields_;
};

}  // namespace pb
}  // namespace tpudf
