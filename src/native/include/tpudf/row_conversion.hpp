// Host-side packed-row codec (component C1' native half).
//
// Byte-contract-identical to the device path
// (spark_rapids_jni_tpu/ops/row_conversion.py) and to the reference format
// (reference src/main/cpp/src/row_conversion.cu:432-456 layout;
// RowConversion.java:40-99 documented contract):
//   * columns packed in schema order, each aligned to its own size
//   * validity bytes ((ncols+7)//8) directly after the last column,
//     bit col%8 of byte col//8 set <=> valid
//   * rows zero-padded to 8 bytes
//
// This is the CPU half of the bridge: the JNI surface packs/unpacks host
// buffers with it (Spark's UnsafeRow handoff is host-side), while the JAX
// op does the same transform on-device. The two are cross-validated
// byte-for-byte in tests.

#pragma once

#include <cstdint>
#include <vector>

namespace tpudf {
namespace rows {

struct Layout {
  std::vector<int32_t> start;
  std::vector<int32_t> size;
  int32_t row_size = 0;
};

// Element sizes -> packed layout. Throws std::invalid_argument on
// non-power-of-two or out-of-range sizes.
Layout fixed_width_layout(std::vector<int32_t> const& sizes);

// Pack columns into rows. col_data[i] is n_rows*sizes[i] bytes
// (little-endian values); col_valid[i] is n_rows validity bytes (1=valid)
// or nullptr for all-valid. out must hold n_rows*layout.row_size bytes;
// pad bytes are zeroed (same determinism choice as the device path).
void to_rows(uint8_t const* const* col_data, uint8_t const* const* col_valid,
             std::vector<int32_t> const& sizes, int64_t n_rows, uint8_t* out);

// Unpack rows into columns. Buffers must be caller-allocated to
// n_rows*sizes[i] (data) and n_rows (validity; never null — the packed
// form always carries validity bits, reference row_conversion.cu:551-555).
void from_rows(uint8_t const* rows, int64_t n_rows,
               std::vector<int32_t> const& sizes, uint8_t* const* col_data,
               uint8_t* const* col_valid);

}  // namespace rows
}  // namespace tpudf
