// ORC reader (the ORC half of "Parquet/ORC readers incl. chunked reads" in
// the vendored capability surface, SURVEY.md section 2.2 — the reference
// ships cuDF's ORC reader inside libcudf, build-libcudf.xml:34-60).
//
// CPU decode -> Arrow-layout host buffers; chunking at stripe granularity
// (the ORC analogue of row groups). Metadata is protobuf
// (protobuf_wire.hpp); all field/enum numbers follow the public
// orc_proto.proto spec.
//
// Supported subset (explicit errors otherwise):
//   * flat struct root of primitive columns: BOOLEAN, BYTE, SHORT, INT,
//     LONG, FLOAT, DOUBLE, STRING (direct + dictionary), DATE, DECIMAL
//     (<= 18 digits)
//   * integer encodings RLEv1 and RLEv2 (short-repeat / direct / delta /
//     patched-base), byte RLE, boolean RLE
//   * compression NONE, ZLIB, SNAPPY (ORC 3-byte chunk framing)

#pragma once

#include <cstdint>
#include <optional>
#include <string>
#include <vector>

namespace tpudf {
namespace orc {

// orc_proto.proto Type::Kind values.
enum class Kind : int32_t {
  BOOLEAN = 0,
  BYTE = 1,
  SHORT = 2,
  INT = 3,
  LONG = 4,
  FLOAT = 5,
  DOUBLE = 6,
  STRING = 7,
  BINARY = 8,
  TIMESTAMP = 9,
  LIST = 10,
  MAP = 11,
  STRUCT = 12,
  UNION = 13,
  DECIMAL = 14,
  DATE = 15,
  VARCHAR = 16,
  CHAR = 17,
};

struct OrcColumn {
  std::string name;
  int32_t kind = 0;          // Kind enum value
  int32_t precision = 0;     // DECIMAL
  int32_t scale = 0;         // DECIMAL
  int64_t num_rows = 0;
  // numeric/boolean/date/decimal payload: int64 per row (floats bit-stored
  // as their IEEE pattern in i64 for FLOAT/DOUBLE -- python bitcasts back)
  std::vector<int64_t> data;
  // STRING payload
  std::vector<int32_t> offsets;
  std::vector<uint8_t> chars;
  std::vector<uint8_t> validity;  // empty = all valid
};

struct OrcResult {
  int64_t num_rows = 0;
  std::vector<OrcColumn> columns;
  // unique StripeFooter.writerTimezone across the decoded stripes
  // (empty/UTC-family means no conversion is needed). TIMESTAMP payloads
  // are WALL-CLOCK micros in this zone; the Python layer applies the tz
  // database (stripes with conflicting zones fail the decode).
  std::string writer_timezone;
};

struct StripeInfo {
  int64_t num_rows = 0;
  int64_t data_bytes = 0;
};

std::vector<StripeInfo> stripe_infos(uint8_t const* file, uint64_t len);

// Decode selected columns / stripes. nullopt = all, empty list = none
// (same selection contract as the parquet reader).
OrcResult read_file(uint8_t const* file, uint64_t len,
                    std::optional<std::vector<int32_t>> const& columns,
                    std::optional<std::vector<int32_t>> const& stripes);

// RLEv2 decoder exposed for spec-vector tests.
std::vector<int64_t> decode_rle_v2(uint8_t const* p, uint64_t len,
                                   int64_t count, bool is_signed);

}  // namespace orc
}  // namespace tpudf
