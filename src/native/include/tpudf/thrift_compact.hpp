// Generic Apache Thrift compact-protocol value tree.
//
// The reference deserializes Parquet footers into thrift-compiler-generated
// structs (reference src/main/cpp/src/NativeParquetJni.cpp:452-481 via
// TCompactProtocol and generated parquet_types.h). This rebuild instead
// parses the compact wire format (a public, stable spec) into a generic
// tagged tree: every field — known or unknown — survives a
// parse -> edit -> serialize round trip byte-compatibly, with no thrift
// compiler or generated code in the build. Footer-specific logic addresses
// fields by their parquet.thrift ids (see parquet_footer.cpp).
//
// Anti-bomb limits match the reference (NativeParquetJni.cpp:466-471):
// 100MB max string, 1M max container elements.

#pragma once

#include <cstdint>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace tpudf {
namespace thrift {

// Compact-protocol wire type ids (field headers and collection elements).
enum class WireType : uint8_t {
  STOP = 0,
  BOOL_TRUE = 1,
  BOOL_FALSE = 2,
  I8 = 3,
  I16 = 4,
  I32 = 5,
  I64 = 6,
  DOUBLE = 7,
  BINARY = 8,
  LIST = 9,
  SET = 10,
  MAP = 11,
  STRUCT = 12,
};

struct Value;

struct Field {
  int16_t id;
  std::unique_ptr<Value> value;
};

// A parsed thrift value. Exactly one of the members is meaningful,
// discriminated by `type` (BOOL_TRUE doubles as the generic bool kind).
struct Value {
  WireType type = WireType::STOP;

  bool b = false;
  int64_t i = 0;      // I8/I16/I32/I64 (zigzag-decoded)
  double d = 0.0;
  std::string bin;    // BINARY (string or bytes)

  // LIST/SET: element wire type + elements.
  WireType elem_type = WireType::STOP;
  std::vector<Value> elems;

  // MAP: key/value wire types + pairwise entries.
  WireType key_type = WireType::STOP;
  WireType val_type = WireType::STOP;
  std::vector<Value> keys;
  std::vector<Value> vals;

  // STRUCT: fields in original wire order.
  std::vector<Field> fields;

  Value() = default;
  explicit Value(WireType t) : type(t) {}
  Value(Value&&) = default;
  Value& operator=(Value&&) = default;
  Value(Value const& o) { *this = o; }
  Value& operator=(Value const& o);

  // Struct helpers: find a field by parquet.thrift id (nullptr if absent).
  Value* field(int16_t id);
  Value const* field(int16_t id) const;
  // Get-or-insert keeping ascending id order (compact protocol deltas
  // require non-decreasing emit order for maximum compatibility).
  Value& set_field(int16_t id, WireType t);

  int64_t as_i64() const { return i; }
  std::string const& as_binary() const { return bin; }
};

class ParseError : public std::runtime_error {
 public:
  using std::runtime_error::runtime_error;
};

struct Limits {
  uint64_t max_string_size = 100ull * 1000 * 1000;  // reference parity
  uint64_t max_container_size = 1000ull * 1000;
};

// Parse a single struct (e.g. a Parquet FileMetaData) from `buf[0..len)`.
// Throws ParseError on malformed input or limit violations.
Value parse_struct(uint8_t const* buf, uint64_t len, Limits const& limits = {});

// Same, reporting how many bytes the struct occupied — needed when structs
// are embedded mid-stream (Parquet page headers precede page payloads).
Value parse_struct(uint8_t const* buf, uint64_t len, uint64_t* consumed,
                   Limits const& limits = {});

// Serialize a struct value to compact-protocol bytes.
std::string serialize_struct(Value const& v);

}  // namespace thrift
}  // namespace tpudf
