// get_json_object — Spark SQL's JSONPath extractor (north-star component:
// BASELINE.json lists "get_json_object" among the JNI-exposed kernels; the
// reference family ships it as a GPU kernel over string columns).
//
// Supported path subset (Spark's own grammar, minus wildcards this round):
//   $            root
//   .field       object member (also ['field'])
//   [index]      array element, 0-based
// Unsupported ($.* , [*] wildcards) and malformed paths return
// PathError so callers can fail the whole column like Spark's analyzer
// would; malformed JSON or a missing match returns nullopt (SQL NULL).
//
// Match semantics follow Spark's UDF:
//   * string results are returned UNQUOTED (raw value, escapes decoded);
//   * object/array/number/bool results are returned as their literal JSON
//     text (whitespace preserved as-is from the input);
//   * a JSON null matches to SQL NULL.

#pragma once

#include <optional>
#include <stdexcept>
#include <string>
#include <string_view>
#include <vector>

namespace tpudf {
namespace json {

class PathError : public std::invalid_argument {
 public:
  // All messages carry the "JSONPath: " prefix so bindings can classify
  // bad-path errors (caller bug -> ValueError) apart from engine errors.
  explicit PathError(std::string const& msg)
      : std::invalid_argument("JSONPath: " + msg) {}
};

struct PathStep {
  bool is_index = false;
  std::string field;
  int64_t index = 0;
};

// Compile a path once (throws PathError); reuse across a whole column.
std::vector<PathStep> parse_path(std::string_view path);

std::optional<std::string> get_json_object(std::string_view json,
                                           std::vector<PathStep> const& steps);

// Convenience single-shot form (parses the path on every call).
std::optional<std::string> get_json_object(std::string_view json,
                                           std::string_view path);

}  // namespace json
}  // namespace tpudf
