"""Wire codec tests (VERDICT round-2 item 5): frame-of-reference bit-pack
against an independent numpy bit-twiddling oracle, and the compressed
shuffle exchange on the 8-device mesh composing BitPack with dtype
narrowing — correctness plus bytes-on-wire accounting.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.parallel import (
    EXEC_AXIS,
    executor_mesh,
    hash_shuffle,
    shard_table,
)
from spark_rapids_jni_tpu.parallel.wire import (
    BitPack,
    pack_bits,
    shuffle_wire_bytes,
    unpack_bits,
)


@pytest.fixture(scope="module")
def mesh():
    return executor_mesh(8)


def numpy_pack(values, bits, reference):
    """Independent oracle: pack via a python-int bit stream."""
    stream = 0
    for j, v in enumerate(values):
        stream |= (int(v) - reference) << (j * bits)
    n_words = (len(values) * bits + 31) // 32
    return np.array(
        [(stream >> (32 * w)) & 0xFFFFFFFF for w in range(n_words)],
        dtype=np.uint32,
    )


class TestBitPack:
    @pytest.mark.parametrize("bits", [1, 7, 12, 17, 24, 31, 32])
    def test_round_trip_vs_oracle(self, rng, bits):
        n = 257
        ref = 1000 if bits < 31 else 0
        hi = min(1 << bits, 1 << 31)
        vals = (rng.integers(0, hi, n) + ref).astype(np.int64)
        spec = BitPack(bits, ref)
        packed, ovf = pack_bits(jnp.asarray(vals), spec)
        assert not bool(ovf)
        np.testing.assert_array_equal(
            np.asarray(packed), numpy_pack(vals, bits, ref)
        )
        back = unpack_bits(packed, n, spec, jnp.int64)
        np.testing.assert_array_equal(np.asarray(back), vals)

    def test_out_of_range_sets_overflow(self):
        spec = BitPack(8, 100)
        packed, ovf = pack_bits(jnp.asarray([100, 355, 356]), spec)  # 356 = ref+256
        assert bool(ovf)
        packed, ovf = pack_bits(jnp.asarray([99]), spec)  # below reference
        assert bool(ovf)

    def test_batched_blocks_pack_independently(self, rng):
        spec = BitPack(11, 0)
        vals = rng.integers(0, 1 << 11, (4, 64)).astype(np.int64)
        packed, ovf = pack_bits(jnp.asarray(vals), spec)
        assert not bool(ovf)
        for d in range(4):
            np.testing.assert_array_equal(
                np.asarray(packed[d]), numpy_pack(vals[d], 11, 0)
            )


class TestCompressedShuffle:
    def test_bitpack_and_narrow_compose(self, rng, mesh):
        n = 512
        keys = rng.integers(0, 64, n).astype(np.int64)
        dates = rng.integers(8400, 10957, n).astype(np.int32)  # ~12 bits span
        qty = rng.integers(0, 200, n).astype(np.int64)
        valid = rng.random(n) > 0.15
        tbl = Table([
            Column.from_numpy(keys),
            Column.from_numpy(dates, t.TIMESTAMP_DAYS),
            Column.from_numpy(qty, validity=valid),
        ])
        sharded = shard_table(tbl, mesh)
        wire = [None, BitPack(bits=12, reference=8400), t.INT16]

        def step(local):
            sh = hash_shuffle(local, [0], EXEC_AXIS, capacity=n // 8,
                              wire_dtypes=wire)
            return (sh.table, sh.row_valid, sh.overflowed.reshape(1),
                    sh.narrowing_overflow.reshape(1))

        out, rv, ovf, novf = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(P(EXEC_AXIS),),
            out_specs=(P(EXEC_AXIS),) * 4,
        ))(sharded)
        assert not np.asarray(ovf).any()
        assert not np.asarray(novf).any()

        rv = np.asarray(rv)
        got_dates = np.asarray(out.column(1).data)[rv]
        got_qty = np.asarray(out.column(2).data)
        got_qty_valid = np.asarray(out.column(2).valid_mask())
        # every real row's date survived the packed exchange exactly
        assert sorted(got_dates.tolist()) == sorted(dates.tolist())
        # null-masked qty rows stay null; valid values survive narrowing
        assert sorted(got_qty[got_qty_valid].tolist()) == sorted(
            qty[valid].tolist()
        )

    def test_bitpack_overflow_detected_on_mesh(self, rng, mesh):
        n = 256
        keys = rng.integers(0, 8, n).astype(np.int64)
        vals = rng.integers(0, 5000, n).astype(np.int32)
        vals[17] = 100_000  # outside 12-bit range
        tbl = Table([Column.from_numpy(keys), Column.from_numpy(vals)])
        sharded = shard_table(tbl, mesh)

        def step(local):
            sh = hash_shuffle(local, [0], EXEC_AXIS, capacity=n,
                              wire_dtypes=[None, BitPack(13, 0)])
            return sh.narrowing_overflow.reshape(1)

        novf = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(P(EXEC_AXIS),),
            out_specs=P(EXEC_AXIS),
        ))(sharded)
        assert np.asarray(novf).any()

    def test_wire_bytes_accounting(self, rng):
        n = 64
        tbl = Table([
            Column.from_numpy(np.arange(n, dtype=np.int64)),
            Column.from_numpy(
                rng.integers(8400, 10957, n).astype(np.int32),
                t.TIMESTAMP_DAYS),
        ])
        capacity, d = 16, 8
        acct = shuffle_wire_bytes(
            tbl, [None, BitPack(12, 8400)], capacity, d)
        size = capacity * d
        assert acct["per_column_raw"] == [size * 8, size * 4]
        # 12 bits x 16 values = 192 bits = 6 words per block
        assert acct["per_column_wire"][1] == d * 6 * 4
        assert acct["wire_bytes"] < acct["raw_bytes"]
