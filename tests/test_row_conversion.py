"""Row<->column conversion tests.

The round-trip test mirrors the reference's canonical test
(src/test/java/com/nvidia/spark/rapids/jni/RowConversionTest.java:29-59):
8 fixed-width columns with nulls incl. decimal32/decimal64, convert to rows,
assert single batch + row count, convert back, assert table equality.
Layout unit tests pin the byte-format contract from RowConversion.java:40-99.
"""

import numpy as np
import pytest

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Table, Column
from spark_rapids_jni_tpu.ops import (
    RowsColumn,
    compute_fixed_width_layout,
    convert_from_rows,
    convert_to_rows,
)


def _reference_test_table() -> Table:
    # Same shape as Table.TestBuilder in RowConversionTest.java:30-39.
    return Table.from_pylists(
        [
            ([3, 9, 4, 2, 20, None], t.INT64),
            ([5.0, 9.5, 0.9, 7.23, 2.8, None], t.FLOAT64),
            ([5, 1, 0, 2, 7, None], t.INT32),
            ([True, False, False, True, False, None], t.BOOL8),
            ([1.0, 3.5, 5.9, 7.1, 9.8, None], t.FLOAT32),
            ([2, 3, 4, 5, 9, None], t.INT8),
            ([5000, 9500, 900, 7230, 2800, None], t.decimal32(-3)),
            ([3, 9, 4, 2, 20, None], t.decimal64(-8)),
        ]
    )


def test_fixed_width_rows_round_trip():
    table = _reference_test_table()
    rows = convert_to_rows(table)
    assert len(rows) == 1  # no batch overflow
    assert rows[0].num_rows == table.num_rows
    back = convert_from_rows(rows[0], table.schema())
    assert table.equals(back)


def test_layout_javadoc_example():
    # | A BOOL8 | B INT16 | C DURATION_DAYS | from RowConversion.java:60-68:
    # A at 0, pad, B at 2, C at 4, validity byte at 8, row padded to 16.
    starts, sizes, row_size = compute_fixed_width_layout(
        [t.BOOL8, t.INT16, t.DURATION_DAYS]
    )
    assert starts == [0, 2, 4]
    assert sizes == [1, 2, 4]
    assert row_size == 16


def test_layout_ordered_descending_is_tight():
    # C, B, A ordering: |C 4B|B 2B|A 1B|V| = 8 bytes (RowConversion.java:85-89)
    starts, sizes, row_size = compute_fixed_width_layout(
        [t.DURATION_DAYS, t.INT16, t.BOOL8]
    )
    assert starts == [0, 4, 6]
    assert row_size == 8


def test_row_bytes_exact():
    # Pin the exact byte image for a tiny table: int32 col + int8 col.
    table = Table.from_pylists([([0x04030201], t.INT32), ([0x7F], t.INT8)])
    [rows] = convert_to_rows(table)
    assert rows.row_size == 8  # 4 + 1 + 1 validity -> pad to 8
    img = np.asarray(rows.data)
    assert list(img[:4]) == [0x01, 0x02, 0x03, 0x04]  # little-endian int32
    assert img[4] == 0x7F
    assert img[5] == 0b11  # both columns valid
    assert list(img[6:]) == [0, 0]


def test_null_validity_bits():
    table = Table.from_pylists(
        [([1, None], t.INT8), ([None, 2], t.INT8), ([3, 4], t.INT8)]
    )
    [rows] = convert_to_rows(table)
    img = np.asarray(rows.data).reshape(2, rows.row_size)
    # validity byte directly after 3 int8 columns
    assert img[0][3] == 0b101  # col1 null in row 0
    assert img[1][3] == 0b110  # col0 null in row 1


def test_more_than_8_columns_validity():
    n_cols = 11
    cols = [([i, None, i + 1], t.INT32) for i in range(n_cols)]
    table = Table.from_pylists(cols)
    [rows] = convert_to_rows(table)
    # 11 int32 cols = 44 bytes, 2 validity bytes -> 46 -> pad to 48
    assert rows.row_size == 48
    back = convert_from_rows(rows[0] if isinstance(rows, list) else rows, table.schema())
    assert table.equals(back)


def test_offsets_sequence():
    table = Table.from_pylists([([1, 2, 3], t.INT32)])
    [rows] = convert_to_rows(table)
    assert list(np.asarray(rows.offsets)) == [0, 8, 16, 24]


def test_from_rows_layout_validation():
    table = Table.from_pylists([([1, 2, 3], t.INT32)])
    [rows] = convert_to_rows(table)
    with pytest.raises(ValueError, match="layout"):
        convert_from_rows(rows, [t.INT64])


def test_row_size_limit_enforced():
    schema = [([0], t.INT64)] * 200  # 200*8 = 1600 > 1536
    table = Table.from_pylists(schema)
    with pytest.raises(ValueError, match="too large"):
        convert_to_rows(table)
    # and the limit can be lifted on TPU
    out = convert_to_rows(table, enforce_row_limit=False)
    assert out[0].row_size >= 1600


def test_batching_splits_at_int32_max():
    # Use a tiny synthetic check of the batching arithmetic by monkeypatching
    # num_rows handling: directly verify max_rows_per_batch math instead of
    # allocating 2GB.
    from spark_rapids_jni_tpu.ops.row_conversion import INT32_MAX

    _, _, row_size = compute_fixed_width_layout([t.INT64, t.INT32])
    max_rows = (INT32_MAX // row_size) // 32 * 32
    assert max_rows % 32 == 0
    assert max_rows * row_size < INT32_MAX


def test_round_trip_large_random(rng):
    n = 10_000
    table = Table(
        [
            Column.from_numpy(rng.integers(-(2**62), 2**62, n).astype(np.int64),
                              validity=rng.random(n) > 0.1),
            Column.from_numpy(rng.standard_normal(n).astype(np.float32)),
            Column.from_numpy(rng.integers(-128, 127, n).astype(np.int8),
                              validity=rng.random(n) > 0.5),
            Column.from_numpy((rng.random(n) > 0.5).astype(np.uint8), t.BOOL8,
                              validity=rng.random(n) > 0.9),
        ]
    )
    [rows] = convert_to_rows(table)
    back = convert_from_rows(rows, table.schema())
    assert table.equals(back)


def test_empty_table_rows():
    table = Table.from_pylists([([], t.INT32)])
    out = convert_to_rows(table)
    assert len(out) == 1
    assert out[0].num_rows == 0
    back = convert_from_rows(out[0], table.schema())
    assert back.num_rows == 0


# ---------------------------------------------------------------------------
# DECIMAL128 in the packed-row contract (VERDICT r4 item 8): 16-byte
# fixed-width element, 16-byte alignment — the reference's generic rule
# (row_conversion.cu:439-443,462-468) applied to __int128_t; limb pairs
# split/rejoin at the codec boundary.
# ---------------------------------------------------------------------------


def test_layout_decimal128_alignment():
    # | INT8 | DECIMAL128 | INT32 |: d128 aligns to 16, int32 packs after,
    # validity at 36, row padded to 8 -> 40
    starts, sizes, row_size = compute_fixed_width_layout(
        [t.INT8, t.decimal128(-2), t.INT32]
    )
    assert starts == [0, 16, 32]
    assert sizes == [1, 16, 4]
    assert row_size == 40


def test_decimal128_row_bytes_exact():
    vals = [1, -1, (1 << 100) + 7, -(1 << 100) - 7, 0]
    table = Table([Column.from_pylist(vals, t.decimal128(-2))])
    rows = convert_to_rows(table)[0]
    img = np.asarray(rows.data).reshape(rows.num_rows, rows.row_size)
    for i, v in enumerate(vals):
        expect = np.frombuffer(
            int(v).to_bytes(16, "little", signed=True), np.uint8)
        assert (img[i, :16] == expect).all(), v
        assert img[i, 16] == 1  # validity bit


def test_decimal128_round_trip_with_nulls():
    table = Table.from_pylists(
        [
            ([3, None, 4], t.INT64),
            ([(1 << 90) + 123, -(1 << 120), None], t.decimal128(-4)),
            ([True, None, False], t.BOOL8),
        ]
    )
    rows = convert_to_rows(table)
    assert len(rows) == 1
    back = convert_from_rows(rows[0], table.schema())
    assert table.equals(back)


def test_reference_table_plus_decimal128_round_trip():
    """The canonical 8-column reference table extended with a d128 column
    (the cuDF 22.06 generic path accepts decimal128 rows the same way)."""
    base = _reference_test_table()
    d128 = Column.from_pylist(
        [12345678901234567890123456789, -42, 0, 7, -(1 << 126), None],
        t.decimal128(-10))
    table = Table(list(base.columns) + [d128])
    rows = convert_to_rows(table)
    back = convert_from_rows(rows[0], table.schema())
    assert table.equals(back)
