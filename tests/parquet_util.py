"""Pure-Python Parquet file writer — the test oracle for the native data
reader. Independent implementation of the write side of the format (PLAIN +
dictionary encodings, RLE def levels, v1/v2 data pages, UNCOMPRESSED /
SNAPPY / GZIP codecs) so the C++ decoder can't self-validate against a
shared misreading of the spec. Flat schemas only, matching the reader's
supported subset.

Columns are described as ColumnSpec(name, physical, values, ...) where
values is a list with None marking nulls.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field
from typing import Optional

from tests import thrift_util as tu

# parquet.thrift enums
BOOLEAN, INT32, INT64, FLOAT, DOUBLE, BYTE_ARRAY, FLBA = 0, 1, 2, 4, 5, 6, 7
UNCOMPRESSED, SNAPPY, GZIP = 0, 1, 2
PLAIN, PLAIN_DICT, RLE, RLE_DICT = 0, 2, 3, 8
PAGE_DATA, PAGE_DICT, PAGE_DATA_V2 = 0, 2, 3

# PageHeader field ids
PH_TYPE, PH_UNCOMP, PH_COMP, PH_DATA, PH_DICT, PH_DATA_V2 = 1, 2, 3, 5, 7, 8
DPH_NUM_VALUES, DPH_ENCODING, DPH_DEF_ENC, DPH_REP_ENC = 1, 2, 3, 4
DICT_NUM_VALUES, DICT_ENCODING = 1, 2
D2_NUM_VALUES, D2_NUM_NULLS, D2_NUM_ROWS, D2_ENCODING = 1, 2, 3, 4
D2_DEF_LEN, D2_REP_LEN, D2_IS_COMPRESSED = 5, 6, 7


def snappy_compress(raw: bytes) -> bytes:
    """Valid snappy stream using literal elements only."""
    out = bytearray()
    u = len(raw)
    while u >= 0x80:
        out.append((u & 0x7F) | 0x80)
        u >>= 7
    out.append(u)
    pos = 0
    while pos < len(raw):
        n = min(len(raw) - pos, 65536)
        if n <= 60:
            out.append((n - 1) << 2)
        else:
            out.append(61 << 2)  # literal with 2-byte little-endian length
            out += struct.pack("<H", n - 1)
        out += raw[pos : pos + n]
        pos += n
    return bytes(out)


def _compress(raw: bytes, codec: int) -> bytes:
    if codec == UNCOMPRESSED:
        return raw
    if codec == SNAPPY:
        return snappy_compress(raw)
    if codec == GZIP:
        return zlib.compress(raw, 6)  # zlib framing; reader auto-detects
    raise ValueError(f"codec {codec}")


def rle_encode_bits(bits: list[int], bit_width: int = 1) -> bytes:
    """RLE/bit-packed hybrid, RLE runs only (valid for any input)."""
    out = bytearray()
    byte_width = (bit_width + 7) // 8
    i = 0
    while i < len(bits):
        j = i
        while j < len(bits) and bits[j] == bits[i]:
            j += 1
        run = j - i
        header = run << 1
        while header >= 0x80:
            out.append((header & 0x7F) | 0x80)
            header >>= 7
        out.append(header)
        out += int(bits[i]).to_bytes(byte_width, "little")
        i = j
    return bytes(out)


def bitpack_encode(vals: list[int], bit_width: int) -> bytes:
    """RLE/bit-packed hybrid, one bit-packed run (padded to 8 values)."""
    n = len(vals)
    groups = (n + 7) // 8
    header = (groups << 1) | 1
    out = bytearray()
    h = header
    while h >= 0x80:
        out.append((h & 0x7F) | 0x80)
        h >>= 7
    out.append(h)
    padded = vals + [0] * (groups * 8 - n)
    acc = 0
    nbits = 0
    for v in padded:
        acc |= (v & ((1 << bit_width) - 1)) << nbits
        nbits += bit_width
        while nbits >= 8:
            out.append(acc & 0xFF)
            acc >>= 8
            nbits -= 8
    if nbits:
        out.append(acc & 0xFF)
    return bytes(out)


def plain_encode(physical: int, values: list, type_length: int = 0) -> bytes:
    out = bytearray()
    if physical == BOOLEAN:
        acc = 0
        for i, v in enumerate(values):
            if v:
                acc |= 1 << (i & 7)
            if (i & 7) == 7:
                out.append(acc)
                acc = 0
        if len(values) & 7:
            out.append(acc)
        return bytes(out)
    fmt = {INT32: "<i", INT64: "<q", FLOAT: "<f", DOUBLE: "<d"}.get(physical)
    if fmt:
        for v in values:
            out += struct.pack(fmt, v)
        return bytes(out)
    if physical == BYTE_ARRAY:
        for v in values:
            b = v.encode() if isinstance(v, str) else bytes(v)
            out += struct.pack("<I", len(b)) + b
        return bytes(out)
    if physical == FLBA:
        for v in values:  # int -> big-endian two's complement
            out += int(v).to_bytes(type_length, "big", signed=True)
        return bytes(out)
    raise ValueError(f"physical {physical}")


@dataclass
class ColumnSpec:
    name: str
    physical: int
    values: list  # None = null
    converted: Optional[int] = None
    scale: int = 0
    precision: int = 0
    type_length: int = 0
    optional: bool = True
    use_dictionary: bool = False
    extra_schema: dict = field(default_factory=dict)


def _page_v1(spec: ColumnSpec, values: list, codec: int,
             encoding: int, payload: bytes) -> bytes:
    """Assemble one v1 data page: [def levels][payload], compressed whole."""
    body = bytearray()
    if spec.optional:
        defs = rle_encode_bits([0 if v is None else 1 for v in values])
        body += struct.pack("<I", len(defs)) + defs
    body += payload
    comp = _compress(bytes(body), codec)
    header = tu.write_struct({
        PH_TYPE: (tu.I32, PAGE_DATA),
        PH_UNCOMP: (tu.I32, len(body)),
        PH_COMP: (tu.I32, len(comp)),
        PH_DATA: (tu.STRUCT, {
            DPH_NUM_VALUES: (tu.I32, len(values)),
            DPH_ENCODING: (tu.I32, encoding),
            DPH_DEF_ENC: (tu.I32, RLE),
            DPH_REP_ENC: (tu.I32, RLE),
        }),
    })
    return header + comp


def _page_v2(spec: ColumnSpec, values: list, codec: int,
             encoding: int, payload: bytes) -> bytes:
    """v2 page: levels uncompressed up front, data section compressed."""
    defs = b""
    num_nulls = sum(1 for v in values if v is None)
    if spec.optional:
        defs = rle_encode_bits([0 if v is None else 1 for v in values])
    comp = _compress(payload, codec)
    header = tu.write_struct({
        PH_TYPE: (tu.I32, PAGE_DATA_V2),
        PH_UNCOMP: (tu.I32, len(defs) + len(payload)),
        PH_COMP: (tu.I32, len(defs) + len(comp)),
        PH_DATA_V2: (tu.STRUCT, {
            D2_NUM_VALUES: (tu.I32, len(values)),
            D2_NUM_NULLS: (tu.I32, num_nulls),
            D2_NUM_ROWS: (tu.I32, len(values)),
            D2_ENCODING: (tu.I32, encoding),
            D2_DEF_LEN: (tu.I32, len(defs)),
            D2_REP_LEN: (tu.I32, 0),
            D2_IS_COMPRESSED: (tu.BOOL_T, codec != UNCOMPRESSED),
        }),
    })
    return header + defs + comp


def write_parquet(
    columns: list[ColumnSpec],
    row_group_size: Optional[int] = None,
    codec: int = UNCOMPRESSED,
    page_rows: Optional[int] = None,
    data_page_v2: bool = False,
) -> bytes:
    """Serialize a complete flat-schema Parquet file."""
    num_rows = len(columns[0].values)
    for c in columns:
        assert len(c.values) == num_rows
    rg_size = row_group_size or max(num_rows, 1)

    blob = bytearray(b"PAR1")
    row_groups = []
    for rg_start in range(0, max(num_rows, 1), rg_size):
        rg_vals = {
            c.name: c.values[rg_start : rg_start + rg_size] for c in columns
        }
        n_rg_rows = len(rg_vals[columns[0].name])
        chunks = []
        rg_comp_total = 0
        for c in columns:
            values = rg_vals[c.name]
            chunk_start = len(blob)
            dict_off = None
            encodings = [PLAIN, RLE]
            present = [v for v in values if v is not None]
            if c.use_dictionary:
                # dictionary page first, then RLE_DICT-encoded data pages
                uniq = list(dict.fromkeys(present))
                dict_payload = plain_encode(c.physical, uniq, c.type_length)
                comp = _compress(dict_payload, codec)
                dh = tu.write_struct({
                    PH_TYPE: (tu.I32, PAGE_DICT),
                    PH_UNCOMP: (tu.I32, len(dict_payload)),
                    PH_COMP: (tu.I32, len(comp)),
                    PH_DICT: (tu.STRUCT, {
                        DICT_NUM_VALUES: (tu.I32, len(uniq)),
                        DICT_ENCODING: (tu.I32, PLAIN),
                    }),
                })
                dict_off = len(blob)
                blob += dh + comp
                encodings = [RLE_DICT, RLE]
            data_off = len(blob)
            pr = page_rows or max(n_rg_rows, 1)
            for p_start in range(0, max(n_rg_rows, 1), pr):
                pvals = values[p_start : p_start + pr]
                ppresent = [v for v in pvals if v is not None]
                if c.use_dictionary:
                    uniq_index = {v: i for i, v in enumerate(uniq)}
                    bw = max(1, (len(uniq) - 1).bit_length())
                    idx = [uniq_index[v] for v in ppresent]
                    payload = bytes([bw]) + bitpack_encode(idx, bw)
                    enc = RLE_DICT
                else:
                    payload = plain_encode(c.physical, ppresent, c.type_length)
                    enc = PLAIN
                page = (_page_v2 if data_page_v2 else _page_v1)(
                    c, pvals, codec, enc, payload
                )
                blob += page
            chunk_bytes = len(blob) - chunk_start
            rg_comp_total += chunk_bytes
            md = {
                tu.CM_TYPE: (tu.I32, c.physical),
                tu.CM_ENCODINGS: (tu.LIST, (tu.I32, encodings)),
                tu.CM_PATH: (tu.LIST, (tu.BINARY, [c.name])),
                tu.CM_CODEC: (tu.I32, codec),
                tu.CM_NUM_VALUES: (tu.I64, n_rg_rows),
                tu.CM_TOTAL_UNCOMP: (tu.I64, chunk_bytes),
                tu.CM_TOTAL_COMP: (tu.I64, chunk_bytes),
                tu.CM_DATA_PAGE_OFF: (tu.I64, data_off),
            }
            if dict_off is not None:
                md[tu.CM_DICT_PAGE_OFF] = (tu.I64, dict_off)
            chunks.append({
                tu.CC_FILE_OFFSET: (tu.I64, chunk_start),
                tu.CC_META: (tu.STRUCT, md),
            })
        row_groups.append({
            tu.RG_COLUMNS: (tu.LIST, (tu.STRUCT, chunks)),
            tu.RG_TOTAL_BYTE_SIZE: (tu.I64, rg_comp_total),
            tu.RG_NUM_ROWS: (tu.I64, n_rg_rows),
            tu.RG_TOTAL_COMPRESSED: (tu.I64, rg_comp_total),
        })
        if num_rows == 0:
            break

    schema = [tu.schema_element("root", num_children=len(columns))]
    for c in columns:
        extra = dict(c.extra_schema)
        if c.converted is not None:
            extra[tu.SE_CONVERTED] = (tu.I32, c.converted)
        if c.converted == 5:  # DECIMAL
            extra[tu.SE_SCALE] = (tu.I32, c.scale)
            extra[tu.SE_PRECISION] = (tu.I32, c.precision)
        se = {tu.SE_NAME: (tu.BINARY, c.name), tu.SE_TYPE: (tu.I32, c.physical),
              tu.SE_REP: (tu.I32, 1 if c.optional else 0)}
        if c.physical == FLBA:
            se[tu.SE_TYPE_LEN] = (tu.I32, c.type_length)
        se.update(extra)
        schema.append(se)

    footer = tu.file_metadata(schema, row_groups, num_rows=num_rows)
    blob += footer
    blob += struct.pack("<I", len(footer)) + b"PAR1"
    return bytes(blob)
