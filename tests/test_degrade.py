"""Graceful degradation under memory pressure (runtime/degrade, ISSUE 8).

Invariant families:

1. **The ladder preserves bit-identity** — classified pressure failures
   step a query fused -> staged -> out-of-core (chunk halving) -> parked,
   and whichever tier completes produces the serial ``fusion.execute``
   answer (valid rows byte-for-byte), with ZERO leaked reservations and a
   ``degrade.step`` event per transition.

2. **Chaos sweep** — under a seeded fault script, every q1/q3/q6 query at
   ragged row counts either completes bit-identical via SOME tier or dies
   classified (resilience taxonomy / QueryRejected / QueryCancelled);
   afterwards the same server serves a clean query bit-identical — chaos
   leaves no lingering perturbation — and nothing leaks.

3. **Deadlines & cancellation are cooperative and leak-free** — expiry
   (or explicit cancel) resolves the ticket ``cancelled`` within a small
   bound, releasing its reservation so queued work runs.

4. **Watermarks** — crossing high proactively spills the attached store's
   coldest entries, pauses NEW admission, and clears below low; with
   ``degrade.enabled=false`` none of the machinery engages (the verbatim
   pre-degradation path).

5. **Warm-start state is crash-safe** — learned estimates round-trip
   through tmp+``os.replace``; a corrupt file is discarded with a
   telemetry event and a cold start, never a crash.
"""

import threading
import time

import numpy as np
import pytest

from spark_rapids_jni_tpu.models import tpch
from spark_rapids_jni_tpu.runtime import (
    degrade,
    dispatch,
    faults,
    fusion,
    resilience,
    server,
)
from spark_rapids_jni_tpu.runtime.memory import MemoryLimiter, SpillStore
from spark_rapids_jni_tpu.telemetry import REGISTRY
from spark_rapids_jni_tpu.telemetry.events import drain as drain_events
from spark_rapids_jni_tpu.telemetry.events import events as ring_events
from spark_rapids_jni_tpu.utils.atomic_io import atomic_write_json, load_json
from spark_rapids_jni_tpu.utils.config import reset_option, set_option

RAGGED_IN_BUCKET = (600, 700, 801, 1000)

_RESET = (
    "server.max_inflight", "server.hbm_budget_bytes",
    "server.admission_timeout_s", "server.queue_depth",
    "server.estimate_headroom", "server.deadline_ms",
    "server.estimate_alpha", "server.estimate_path",
    "server.estimate_save_interval_s",
    "degrade.enabled", "degrade.max_steps", "degrade.park_timeout_s",
    "degrade.chunk_rows", "memory.high_watermark", "memory.low_watermark",
    "resilience.enabled", "resilience.max_attempts", "telemetry.enabled",
)


@pytest.fixture(autouse=True)
def _isolated():
    dispatch.clear()
    REGISTRY.reset()
    drain_events()
    yield
    for k in _RESET:
        reset_option(k)
    dispatch.clear()


def _q1_bindings(n, seed=0):
    return tpch._q1_plan(), {"lineitem": tpch.lineitem_table(n, seed=seed)}


def _q6_plan():
    return fusion.Plan("tpch_q6", fusion.Project(
        fusion.Scan("lineitem"), tpch._q6_reduce, rowwise=False))


def _q3_bindings(n, seed=0):
    n_ord = max(n // 8, 4)
    n_cust = max(n // 64, 2)
    plan = tpch._q3_plan(0, tpch._Q3_CUTOFF_DAYS, 2)
    bindings = {
        "customer": tpch.customer_table(n_cust, seed=seed),
        "orders": tpch.orders_table(n_ord, n_cust, seed=seed + 1),
        "lineitem": tpch.lineitem_q3_table(n, n_ord, seed=seed + 2),
    }
    return plan, bindings


def _assert_tables_identical(a, b, label=""):
    assert a.num_columns == b.num_columns, f"{label}: column count"
    assert a.num_rows == b.num_rows, f"{label}: row count"
    for i in range(a.num_columns):
        ca, cb = a.column(i), b.column(i)
        av, bv = np.asarray(ca.valid_mask()), np.asarray(cb.valid_mask())
        assert np.array_equal(av, bv), f"{label} col {i}: validity"
        ad = np.where(av, np.asarray(ca.data), 0)
        bd = np.where(bv, np.asarray(cb.data), 0)
        assert np.array_equal(ad, bd), f"{label} col {i}: data"


def _valid_rows(t):
    """The table's REAL rows (row-valid = column-0 validity, the groupby
    padding convention), masked and in table order — shape-independent:
    the fused tier pads its groupby output to the plan's group budget
    while the out-of-core merge is sized by its stacked partials, so
    bit-identity across tiers is over valid rows, not padding."""
    cols = [(np.asarray(t.column(i).valid_mask()),
             np.asarray(t.column(i).data)) for i in range(t.num_columns)]
    out = []
    for r in np.flatnonzero(cols[0][0]):
        out.append(tuple(
            (bool(vm[r]), dm[r].item() if vm[r] else None)
            for vm, dm in cols))
    return out


def _assert_same_answer(a, b, label=""):
    """Bit-identity across tiers: full byte equality when the shapes
    match, valid-row equality when a trimming tier changed the padding."""
    if a.num_rows == b.num_rows:
        _assert_tables_identical(a, b, label)
    else:
        assert a.num_columns == b.num_columns, f"{label}: column count"
        assert _valid_rows(a) == _valid_rows(b), f"{label}: valid rows"


def _degrade_events(event=None):
    out = [r for r in ring_events() if r.get("kind") == "degrade"]
    if event is not None:
        out = [r for r in out if r.get("event") == event]
    return out


def _q1_outofcore_factory(bindings, limiter):
    partial_fn, merge_fn = tpch.q1_row_chunked_fns()
    return degrade.row_chunked_tier(
        bindings, "lineitem", partial_fn, merge_fn, limiter=limiter)


# ---------------------------------------------------------------------------
# 1. the ladder preserves bit-identity
# ---------------------------------------------------------------------------


def test_degrade_seams_registered():
    for seam in ("degrade.step", "memory.pressure", "server.cancel"):
        assert seam in faults.SEAMS


def test_ladder_steps_to_staged_bit_identical():
    set_option("telemetry.enabled", True)
    plan, bindings = _q1_bindings(600)
    want = fusion.execute(plan, bindings).table
    limiter = MemoryLimiter(1 << 26)
    ctrl = degrade.DegradationController(limiter, session="lad")
    q = degrade.DegradableQuery(plan, bindings)
    script = faults.FaultScript([faults.FaultSpec(
        "fusion.region", resilience.ResourceExhausted("injected"), times=1)])
    with faults.inject(script):
        res = ctrl.execute(q)
    _assert_tables_identical(res.table, want, "staged tier")
    assert limiter.used == 0
    steps = _degrade_events("step")
    assert [e["tier"] for e in steps] == ["staged"]
    assert steps[0]["trigger"] == "ResourceExhausted"
    assert steps[0]["rung"] == 1
    assert steps[0]["session"] == "lad"
    assert _degrade_events("completed")[0]["tier"] == "staged"


def test_ladder_reaches_outofcore_and_halves_chunks():
    """fused and staged both die of pressure (the region seam fires at
    seq=0 for the fused attempt, seq=1 for the staged evaluator); the
    out-of-core rung then halves chunk_rows on each further pressure
    failure until the query completes — bit-identical, nothing leaked,
    every attempt visible in degrade.step events."""
    set_option("telemetry.enabled", True)
    set_option("degrade.chunk_rows", 400)
    set_option("degrade.max_steps", 8)
    plan, bindings = _q1_bindings(600)
    want = fusion.execute(plan, bindings).table
    limiter = MemoryLimiter(1 << 26)
    attempts = []
    real = _q1_outofcore_factory(bindings, limiter)

    def runner(chunk_rows, token):
        attempts.append(chunk_rows)
        if chunk_rows > 100:
            raise resilience.ResourceExhausted(
                f"chunk of {chunk_rows} rows does not fit")
        return real(chunk_rows, token)

    ctrl = degrade.DegradationController(limiter)
    q = degrade.DegradableQuery(plan, bindings, outofcore=runner)
    script = faults.FaultScript([
        faults.FaultSpec("fusion.region",
                         resilience.ResourceExhausted("hbm"), seq=0),
        faults.FaultSpec("fusion.region",
                         resilience.CapacityOverflow("staged oom"), seq=1),
    ])
    with faults.inject(script):
        res = ctrl.execute(q)
    _assert_same_answer(res.table, want, "outofcore tier")
    assert limiter.used == 0
    assert attempts == [400, 200, 100]  # halved on each pressure failure
    steps = _degrade_events("step")
    assert [e["tier"] for e in steps] == [
        "staged", "outofcore", "outofcore", "outofcore"]
    assert [e.get("chunk_rows") for e in steps] == [None, 400, 200, 100]
    assert res.meta["degrade.chunk_rows"] == 100


def test_ladder_exhaustion_reraises_original_classified():
    set_option("telemetry.enabled", True)
    set_option("degrade.max_steps", 1)
    plan, bindings = _q1_bindings(600)
    limiter = MemoryLimiter(1 << 26)
    ctrl = degrade.DegradationController(limiter)
    q = degrade.DegradableQuery(plan, bindings)
    first = resilience.ResourceExhausted("the original failure")
    script = faults.FaultScript([
        faults.FaultSpec("fusion.region", first, seq=0),
        faults.FaultSpec("fusion.region",
                         resilience.CapacityOverflow("next"), seq=1,
                         times=50),
    ])
    with faults.inject(script), pytest.raises(
            resilience.ResourceExhausted) as ei:
        ctrl.execute(q)
    assert ei.value is first  # the ORIGINAL, not the last straw
    assert limiter.used == 0
    assert _degrade_events("exhausted")


def test_park_rung_waits_for_drain_then_retries():
    """No out-of-core runner: fused and staged die, the query parks until
    the limiter drains below low, then retries staged and completes."""
    set_option("telemetry.enabled", True)
    set_option("degrade.park_timeout_s", 20.0)
    plan, bindings = _q1_bindings(600)
    want = fusion.execute(plan, bindings).table
    limiter = MemoryLimiter(1000, high_watermark=0.8, low_watermark=0.3)
    limiter.reserve(900)  # keeps usage above low until the helper releases
    ctrl = degrade.DegradationController(limiter)
    q = degrade.DegradableQuery(plan, bindings)
    script = faults.FaultScript([
        faults.FaultSpec("fusion.region",
                         resilience.ResourceExhausted("hbm"), seq=0),
        faults.FaultSpec("fusion.region",
                         resilience.ResourceExhausted("staged oom"), seq=1),
    ])
    releaser = threading.Timer(0.3, limiter.release, args=(900,))
    releaser.start()
    try:
        with faults.inject(script):
            res = ctrl.execute(q)
    finally:
        releaser.cancel()
        releaser.join()
    _assert_tables_identical(res.table, want, "post-park retry")
    assert limiter.used == 0
    assert _degrade_events("parked")
    assert _degrade_events("resumed")
    assert _degrade_events("completed")


def test_park_rung_timeout_exhausts_with_original_error():
    set_option("telemetry.enabled", True)
    set_option("degrade.park_timeout_s", 0.1)
    plan, bindings = _q1_bindings(600)
    limiter = MemoryLimiter(1000, high_watermark=0.8, low_watermark=0.3)
    limiter.reserve(900)  # never drains
    try:
        ctrl = degrade.DegradationController(limiter)
        q = degrade.DegradableQuery(plan, bindings)
        first = resilience.ResourceExhausted("original")
        script = faults.FaultScript([
            faults.FaultSpec("fusion.region", first, seq=0),
            faults.FaultSpec("fusion.region",
                             resilience.ResourceExhausted("staged oom"),
                             seq=1),
        ])
        with faults.inject(script), pytest.raises(
                resilience.ResourceExhausted) as ei:
            ctrl.execute(q)
        assert ei.value is first
        assert _degrade_events("exhausted")
    finally:
        limiter.release(900)
    assert limiter.used == 0


def test_park_rung_drains_past_own_reservation():
    """The parked rung discounts the query's OWN admission reservation:
    a query whose estimate alone exceeds the low watermark still observes
    everyone else's drain (here: nothing else is held, so the drain is
    immediate) instead of burning the whole park timeout."""
    set_option("telemetry.enabled", True)
    set_option("degrade.park_timeout_s", 20.0)
    plan, bindings = _q1_bindings(600)
    want = fusion.execute(plan, bindings).table
    limiter = MemoryLimiter(1000, high_watermark=0.8, low_watermark=0.3)
    limiter.reserve(500)  # the query's own admission hold: 500 > low=300
    ctrl = degrade.DegradationController(limiter)
    q = degrade.DegradableQuery(plan, bindings)
    script = faults.FaultScript([
        faults.FaultSpec("fusion.region",
                         resilience.ResourceExhausted("hbm"), seq=0),
        faults.FaultSpec("fusion.region",
                         resilience.ResourceExhausted("staged oom"), seq=1),
    ])
    t0 = time.monotonic()
    try:
        with faults.inject(script):
            res = ctrl.execute(q, held_bytes=500)
    finally:
        limiter.release(500)
    # the drain is observed immediately, not after park_timeout_s
    assert time.monotonic() - t0 < 10.0
    _assert_tables_identical(res.table, want, "own-reservation park")
    assert _degrade_events("parked")
    assert _degrade_events("resumed")
    assert limiter.used == 0


def test_donated_dead_bindings_die_classified_not_replayed():
    """With donate_inputs=True, a pressure failure that lands AFTER the
    donated input buffers were consumed must re-raise classified — a
    lower tier replaying against dead buffers would compute garbage."""

    class _DeadArray:
        ndim = 1
        shape = (4,)

        @staticmethod
        def is_deleted():
            return True

    class _DeadColumn:
        data = _DeadArray()
        validity = None
        chars = None
        children = None

    class _DeadTable:
        columns = [_DeadColumn()]
        num_rows = 4

    assert degrade._bindings_live({"t": _DeadTable()}) is False
    set_option("telemetry.enabled", True)
    plan, _ = _q1_bindings(600)
    limiter = MemoryLimiter(1 << 26)
    ctrl = degrade.DegradationController(limiter)
    q = degrade.DegradableQuery(
        plan, {"lineitem": _DeadTable()}, donate_inputs=True)
    boom = resilience.ResourceExhausted("oom after donation")
    script = faults.FaultScript([
        faults.FaultSpec("fusion.region", boom, times=1)])
    with faults.inject(script), pytest.raises(
            resilience.ResourceExhausted) as ei:
        ctrl.execute(q)
    assert ei.value is boom  # classified, not a dead-buffer crash
    ev = _degrade_events("exhausted")
    assert ev and ev[0].get("donated") is True
    assert _degrade_events("step") == []  # no tier ever replayed
    assert limiter.used == 0


def test_row_chunked_tier_unsliceable_scan_has_no_rung2():
    """String/nested scans are screened out EAGERLY: the factory returns
    None (query has no rung 2), never a lazy mid-degrade ValueError."""
    from spark_rapids_jni_tpu import types as t
    from spark_rapids_jni_tpu.columnar import Column, Table
    from spark_rapids_jni_tpu.ops.lists import make_list_column

    limiter = MemoryLimiter(1 << 20)
    ident = lambda x: x  # noqa: E731
    strings = Table([Column.from_pylist(["a", "bb", "ccc"], t.STRING)])
    assert degrade.row_chunked_tier(
        {"scan": strings}, "scan", ident, ident, limiter=limiter) is None
    nested = Table([make_list_column([[1], [2, 3]], t.INT64)])
    assert degrade.row_chunked_tier(
        {"scan": nested}, "scan", ident, ident, limiter=limiter) is None
    # a flat numeric scan still builds a runner
    _, bindings = _q1_bindings(64)
    assert _q1_outofcore_factory(bindings, limiter) is not None


def test_degrade_step_seam_can_inject_mid_degrade():
    """A fault injected AT the degrade.step seam propagates — one
    recovery at a time, never a recursive ladder."""
    plan, bindings = _q1_bindings(600)
    limiter = MemoryLimiter(1 << 26)
    ctrl = degrade.DegradationController(limiter)
    q = degrade.DegradableQuery(plan, bindings)
    boom = RuntimeError("mid-degrade fault")
    script = faults.FaultScript([
        faults.FaultSpec("fusion.region",
                         resilience.ResourceExhausted("hbm"), times=1),
        faults.FaultSpec("degrade.step", boom, times=1),
    ])
    with faults.inject(script), pytest.raises(RuntimeError) as ei:
        ctrl.execute(q)
    assert ei.value is boom
    assert limiter.used == 0


def test_disabled_is_verbatim_plain_execute():
    """degrade.enabled=false: the controller IS fusion.execute — the
    pre-degradation staged fallback still absorbs the fault silently,
    and no degrade machinery runs (no events, no pressure state)."""
    set_option("telemetry.enabled", True)
    set_option("degrade.enabled", False)
    plan, bindings = _q1_bindings(600)
    want = fusion.execute(plan, bindings).table
    limiter = MemoryLimiter(1 << 26)
    ctrl = degrade.DegradationController(limiter)
    q = degrade.DegradableQuery(plan, bindings)
    # clean run: identical result, zero degrade events
    res = ctrl.execute(q)
    _assert_tables_identical(res.table, want, "disabled clean")
    # the pre-degradation staged fallback absorbs a fused-region fault
    script = faults.FaultScript([faults.FaultSpec(
        "fusion.region", resilience.ResourceExhausted("hbm"), times=1)])
    with faults.inject(script):
        res2 = ctrl.execute(q)
    _assert_tables_identical(res2.table, want, "disabled fallback")
    assert _degrade_events() == []
    assert limiter.used == 0
    assert limiter.pressure_crossings == 0


# ---------------------------------------------------------------------------
# 2. watermarks
# ---------------------------------------------------------------------------


def test_high_watermark_spills_coldest_and_pauses_admission():
    set_option("telemetry.enabled", True)
    limiter = MemoryLimiter(100_000, high_watermark=0.5, low_watermark=0.25)
    store = SpillStore(1 << 20)
    limiter.attach_spill_store(store)
    cold = tpch.lineitem_table(100, seed=1)
    warm = tpch.lineitem_table(100, seed=2)
    h_cold = store.put(cold)
    h_warm = store.put(warm)
    store.get(h_warm)  # warm's tick is now newer: cold spills first
    pressures = []

    def probe(seam, seq, ctx):
        if seam == "memory.pressure":
            pressures.append(dict(ctx))

    with faults.inject(probe):
        limiter.reserve(60_000)  # crosses high (50k)
    assert limiter.pressure
    assert limiter.pressure_crossings == 1
    assert pressures and pressures[0]["used"] == 60_000
    assert store.stats()["spills"] >= 1  # the proactive spill engaged
    ev = [r for r in _degrade_events("pressure") if r["tier"] == "high"]
    assert ev and ev[0]["trigger"] == "watermark"
    assert ev[0]["proactive_spill_bytes"] > 0
    # NEW admission parks while pressure holds; plain reserves do not
    assert limiter.reserve_blocking(
        1000, timeout=0.2, admission=True) is False
    assert limiter.reserve_blocking(1000, timeout=0.2) is True
    limiter.release(1000)
    # draining below low clears pressure and admission resumes
    limiter.release(60_000)
    assert not limiter.pressure
    assert limiter.reserve_blocking(
        1000, timeout=0.2, admission=True) is True
    limiter.release(1000)
    assert limiter.used == 0
    # the spilled entry restores bit-identical
    _assert_tables_identical(store.get(h_cold), cold, "unspilled")


def test_inflight_reservation_bypasses_parked_admission():
    """A pressure-parked admission ticket must NOT hold the FIFO line:
    non-admission chunk reservations from in-flight queries flow past it
    (their releases are the only thing that can drain the pressure), and
    the parked admission keeps its position for when pressure clears."""
    limiter = MemoryLimiter(100_000, high_watermark=0.5, low_watermark=0.25)
    limiter.attach_spill_store(SpillStore(1 << 20))
    limiter.reserve(60_000)  # crosses high (50k) -> pressure
    assert limiter.pressure
    admitted = []
    parked = threading.Thread(
        target=lambda: admitted.append(
            limiter.reserve_blocking(10_000, admission=True, timeout=20)))
    parked.start()
    deadline = time.monotonic() + 5
    while not limiter._waiters and time.monotonic() < deadline:
        time.sleep(0.01)  # wait until the admission ticket is queued
    assert limiter._waiters, "admission ticket never queued"
    # the in-flight (non-admission) reservation is NOT stuck behind it
    assert limiter.reserve_blocking(5_000, timeout=1.0) is True
    limiter.release(5_000)
    # draining below low clears pressure and the parked admission admits
    limiter.release(60_000)
    parked.join(timeout=10)
    assert admitted == [True]
    limiter.release(10_000)
    assert limiter.used == 0


def test_watermarks_inert_without_store_or_when_disabled():
    # no store attached: the pre-degradation limiter, byte-for-byte
    limiter = MemoryLimiter(1000, high_watermark=0.5, low_watermark=0.25)
    limiter.reserve(900)
    assert not limiter.pressure
    assert limiter.pressure_crossings == 0
    limiter.release(900)
    # store attached but degradation disabled: still inert
    set_option("degrade.enabled", False)
    limiter.attach_spill_store(SpillStore(1 << 20))
    limiter.reserve(900)
    assert not limiter.pressure
    assert limiter.pressure_crossings == 0
    limiter.release(900)
    assert limiter.used == 0


# ---------------------------------------------------------------------------
# 3. deadlines & cancellation
# ---------------------------------------------------------------------------


def test_deadline_expiry_cancels_within_bound_and_frees_budget():
    """One slow query holds the single worker past a queued query's
    deadline; the queued query resolves cancelled (classified, within a
    scheduling bound of the worker freeing) WITHOUT reserving, and the
    server keeps serving afterwards."""
    plan, bindings = _q1_bindings(600)
    release_worker = threading.Event()

    def probe(seam, seq, ctx):
        if seam == "server.execute" and ctx.get("session") == "slow":
            release_worker.wait(20)

    lim = MemoryLimiter(1 << 28)
    with faults.inject(probe), server.QueryServer(
            limiter=lim, max_inflight=1) as srv:
        slow = srv.session("slow").submit(plan, bindings)
        quick = srv.session("quick").submit(plan, bindings, deadline_ms=100)
        time.sleep(0.3)  # deadline passes while quick is still queued
        release_worker.set()
        with pytest.raises(resilience.QueryCancelled):
            quick.result(timeout=30)
        resolved_at = time.monotonic()
        assert quick.status == "cancelled"
        slow.result(timeout=30)
        # the worker was freed moments ago; cancellation resolved within
        # a scheduling bound of pickup, not after a full execution
        assert time.monotonic() - resolved_at < 5.0
        after = srv.session("quick").submit(plan, bindings)
        after.result(timeout=30)
        assert after.status == "served"
        assert srv.stats()["cancelled"] == 1
    assert lim.used == 0


def test_explicit_cancel_unblocks_admission_wait():
    """A query blocked INSIDE reserve_blocking cancels cooperatively:
    the wait wakes within its poll interval, the ticket resolves
    cancelled, and nothing leaks."""
    plan, bindings = _q1_bindings(600)
    lim = MemoryLimiter(1000)
    lim.reserve(900)
    with server.QueryServer(limiter=lim, max_inflight=1,
                            admission_timeout_s=30.0) as srv:
        t = srv.session("s").submit(plan, bindings, estimate_bytes=500)
        time.sleep(0.2)  # let the worker park in reserve_blocking
        t.cancel("client gave up")
        start = time.monotonic()
        with pytest.raises(resilience.QueryCancelled) as ei:
            t.result(timeout=10)
        assert time.monotonic() - start < 2.0
        assert t.status == "cancelled"
        assert ei.value.context.get("reason") == "client gave up"
    assert lim.used == 900  # only the external hold remains
    lim.release(900)


def test_deadline_cancels_running_query_cooperatively():
    """Deadline expiry mid-execution stops the query at its next
    cooperative checkpoint (region or chunk boundary) and releases every
    reservation it held."""
    set_option("telemetry.enabled", True)
    plan, bindings = _q1_bindings(600)
    limiter = MemoryLimiter(1 << 26)
    ctrl = degrade.DegradationController(limiter)
    real = _q1_outofcore_factory(bindings, limiter)
    token = resilience.CancelToken(150, label="mid-exec")

    def runner(chunk_rows, tok):
        time.sleep(0.3)  # outlive the deadline before chunking starts
        return real(chunk_rows, tok)

    q = degrade.DegradableQuery(plan, bindings, outofcore=runner)
    script = faults.FaultScript([
        faults.FaultSpec("fusion.region",
                         resilience.ResourceExhausted("hbm"), seq=0),
        faults.FaultSpec("fusion.region",
                         resilience.ResourceExhausted("staged oom"), seq=1),
    ])
    with faults.inject(script), pytest.raises(resilience.QueryCancelled):
        ctrl.execute(q, cancel_token=token)
    assert limiter.used == 0


# ---------------------------------------------------------------------------
# 4. chaos sweep through the server
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [7, 23])
def test_chaos_sweep_completes_or_dies_classified(seed):
    """Seeded pressure chaos over q1/q3/q6 at ragged row counts through
    the full server, interleaved across two sessions: every ticket is
    either served bit-identical to its serial reference or fails with a
    CLASSIFIED error (never silent, never unclassified); afterwards the
    SAME server serves a clean query bit-identical, and zero
    reservations leak."""
    set_option("telemetry.enabled", True)
    queries = []
    for i, n in enumerate(RAGGED_IN_BUCKET):
        plan, bindings = _q1_bindings(n, seed=i)
        queries.append((plan, bindings, True))
        queries.append((_q6_plan(),
                        {"lineitem": tpch.lineitem_table(n, seed=i + 10)},
                        False))
        plan3, b3 = _q3_bindings(n, seed=i)
        queries.append((plan3, b3, False))
    refs = [fusion.execute(p, b).table for p, b, _ in queries]

    script = faults.FaultScript(
        seed=seed, rate=0.08, max_faults=6,
        seams=("fusion.region", "dispatch.execute", "memory.reserve"),
        exc=resilience.CapacityOverflow)
    lim = MemoryLimiter(1 << 28)
    classified = (resilience.ResilienceError, server.QueryRejected,
                  MemoryError)
    with server.QueryServer(limiter=lim, max_inflight=4) as srv:
        with faults.inject(script):
            tickets = []
            for i, (plan, bindings, ooc) in enumerate(queries):
                sess = srv.session("chaos-a" if i % 2 == 0 else "chaos-b")
                tickets.append(sess.submit(
                    plan, bindings,
                    outofcore=_q1_outofcore_factory if ooc else None))
            served = failed = 0
            for i, (t, ref) in enumerate(zip(tickets, refs)):
                try:
                    res = t.result(timeout=180)
                    _assert_same_answer(res.table, ref, f"chaos[{i}]")
                    served += 1
                except classified:
                    failed += 1  # died classified: loud, never silent
        assert served + failed == len(queries)
        # chaos over: the same server still serves bit-identical
        plan0, b0, _ = queries[0]
        res = srv.session("after").submit(plan0, b0).result(timeout=60)
        _assert_tables_identical(res.table, refs[0], "post-chaos")
    assert lim.used == 0


def test_server_degrades_query_bit_identical_with_events():
    """End to end through the server: an injected pressure fault degrades
    the query (visible degrade.step, stamped with the session), the
    result is still bit-identical, and stats count the step."""
    set_option("telemetry.enabled", True)
    plan, bindings = _q1_bindings(600)
    want = fusion.execute(plan, bindings).table
    lim = MemoryLimiter(1 << 28)
    script = faults.FaultScript([faults.FaultSpec(
        "fusion.region", resilience.ResourceExhausted("hbm"), times=1)])
    with faults.inject(script), server.QueryServer(
            limiter=lim, max_inflight=2) as srv:
        t = srv.session("d1").submit(plan, bindings)
        res = t.result(timeout=60)
        _assert_tables_identical(res.table, want, "server degrade")
        steps = _degrade_events("step")
        assert steps and steps[0]["tier"] == "staged"
        assert steps[0]["session"] == "d1"
        assert srv.session_stats("d1")["degrade_steps"] >= 1
        assert srv.stats()["degrade_steps"] >= 1
    assert lim.used == 0


# ---------------------------------------------------------------------------
# 5. crash-safe warm-start state
# ---------------------------------------------------------------------------


def test_atomic_write_and_corrupt_discard(tmp_path):
    path = str(tmp_path / "state.json")
    atomic_write_json(path, {"a": 1.5})
    obj, err = load_json(path)
    assert obj == {"a": 1.5} and err is None
    with open(path, "w") as f:
        f.write('{"a": 1.')  # a torn write
    obj, err = load_json(path)
    assert obj is None and err
    obj, err = load_json(str(tmp_path / "absent.json"))
    assert obj is None and err is None


def test_learned_estimate_saves_are_debounced(tmp_path):
    """Persistence is off the hot path: the first learn writes through,
    later learns within the save interval only dirty the in-memory state,
    and close() flushes whatever is pending."""
    est_path = str(tmp_path / "learned_estimates.json")
    set_option("server.estimate_path", est_path)
    set_option("server.estimate_save_interval_s", 3600.0)
    plan_a, bindings_a = _q1_bindings(600)
    plan_b, bindings_b = _q1_bindings(1400)  # a different pow2 signature
    with server.QueryServer(budget_bytes=1 << 28, max_inflight=1) as srv:
        srv.session("a").submit(plan_a, bindings_a).result(timeout=60)
        srv.session("a").submit(plan_b, bindings_b).result(timeout=60)
        on_disk, err = load_json(est_path)
        assert err is None
        # first learn wrote through; the second is debounced (dirty only)
        assert set(on_disk) == {srv._plan_signature(plan_a, bindings_a)}
        assert len(srv._learned) == 2
        final = dict(srv._learned)
    on_disk, err = load_json(est_path)  # close() flushed the dirty state
    assert err is None and on_disk == pytest.approx(final)


def test_learned_estimates_persist_and_survive_corruption(tmp_path):
    set_option("telemetry.enabled", True)
    est_path = str(tmp_path / "learned_estimates.json")
    set_option("server.estimate_path", est_path)
    plan, bindings = _q1_bindings(600)
    with server.QueryServer(budget_bytes=1 << 28, max_inflight=1) as srv:
        srv.session("a").submit(plan, bindings).result(timeout=60)
        learned = dict(srv._learned)
        assert learned  # a measured working set was recorded
    state, err = load_json(est_path)
    assert err is None and state == pytest.approx(learned)
    # a fresh process loads measured truth and admits from it
    with server.QueryServer(budget_bytes=1 << 28, max_inflight=1) as srv2:
        assert srv2._learned == pytest.approx(learned)
        sig = srv2._plan_signature(plan, bindings)
        est = srv2._default_estimate(plan, bindings)
        assert est == int(srv2.estimate_headroom * learned[sig])
    # corruption is discarded with a telemetry event, not a crash
    with open(est_path, "w") as f:
        f.write("{not json")
    drain_events()
    with server.QueryServer(budget_bytes=1 << 28, max_inflight=1) as srv3:
        assert srv3._learned == {}
        t = srv3.session("a").submit(plan, bindings)
        t.result(timeout=60)
        assert t.status == "served"
    ev = _degrade_events("state_discarded")
    assert ev and ev[0]["trigger"] == "corrupt"
