"""Whole-stage fusion (runtime/fusion, ISSUE 5).

Four invariant families:

1. **Bit-identity** — a fused region must be byte-for-byte identical to
   the staged op-by-op reference: ``fusion.enabled = False`` runs the
   SAME plan through the same node walk with each op dispatching itself,
   so the comparison holds the query constant and flips only the fusion
   layer. Pinned at 1, 2^k-1, 2^k, 2^k+1 rows with null tails for
   q1/q3/q6, the planned q3, and the planned-q1 ``domain_miss``
   fallback.

2. **Executable economy** — the acceptance claim: one compile per fused
   REGION per bucket (``dispatch.compile.fusion.<plan>``), not one per
   op, and strictly fewer executables than the staged path compiles for
   the same work.

3. **Donation** — ``donate_inputs=True`` accounts freed intermediate
   bytes (``dispatch.donated_bytes``) and never changes results;
   ``fusion.donate = False`` turns the accounting off.

4. **IR discipline** — unbound scans, inconsistent bucket flags, local
   callables, and unresolvable row specs fail loud at plan-build /
   execute time, never inside a trace.
"""

import jax
import numpy as np
import pytest

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.models import tpch
from spark_rapids_jni_tpu.runtime import dispatch, fusion
from spark_rapids_jni_tpu.telemetry import REGISTRY
from spark_rapids_jni_tpu.utils.config import reset_option, set_option

# row counts straddling the power-of-two bucket edges of the default
# base-16 schedule (same family test_dispatch.py pins)
EDGE_COUNTS = (1, 15, 16, 17, 33)


@pytest.fixture(autouse=True)
def _isolated_fusion():
    """Each test sees a fresh executable cache and counter namespace and
    leaves the fusion/dispatch config at its defaults."""
    dispatch.clear()
    REGISTRY.reset()
    yield
    for k in ("fusion.enabled", "fusion.donate", "dispatch.enabled"):
        reset_option(k)
    dispatch.clear()


def _staged(fn):
    """Run ``fn()`` on the staged op-by-op path (same plan, fusion off)."""
    set_option("fusion.enabled", False)
    dispatch.clear()
    try:
        return fn()
    finally:
        reset_option("fusion.enabled")


def _with_null_tail(tbl: Table, cols=(0,)) -> Table:
    """Null the LAST row's validity in ``cols`` — nulls adjacent to where
    bucket-padding phantoms live, the spot a masking bug corrupts first."""
    out = list(tbl.columns)
    for i in cols:
        c = out[i]
        v = np.asarray(c.valid_mask()).copy()
        v[-1] = False
        out[i] = Column(c.dtype, c.data, v, chars=c.chars)
    return Table(out)


def _assert_cols_identical(a: Column, b: Column, label=""):
    av, bv = np.asarray(a.valid_mask()), np.asarray(b.valid_mask())
    assert np.array_equal(av, bv), f"{label}: validity diverged"
    ad = np.where(av, np.asarray(a.data), 0)
    bd = np.where(bv, np.asarray(b.data), 0)
    assert np.array_equal(ad, bd), f"{label}: data diverged"


def _assert_tables_identical(a: Table, b: Table, label=""):
    assert a.num_columns == b.num_columns
    assert a.num_rows == b.num_rows
    for i in range(a.num_columns):
        _assert_cols_identical(a.column(i), b.column(i), f"{label} col {i}")


# ---------------------------------------------------------------------------
# bit-identity: fused == staged at the bucket edges, null tails included
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", EDGE_COUNTS)
def test_q1_fused_matches_staged(n):
    li = _with_null_tail(tpch.lineitem_table(n), cols=(0, 3))
    fused = tpch.tpch_q1(li)
    staged = _staged(lambda: tpch.tpch_q1(li))
    _assert_tables_identical(fused, staged, f"q1 n={n}")


@pytest.mark.parametrize("n", EDGE_COUNTS)
def test_q6_fused_matches_staged(n):
    li = _with_null_tail(tpch.lineitem_table(n), cols=(2,))
    fused = tpch.tpch_q6(li)
    staged = _staged(lambda: tpch.tpch_q6(li))
    _assert_cols_identical(fused, staged, f"q6 n={n}")
    if bool(np.asarray(fused.valid_mask())[0]):
        assert int(fused.data[0]) == tpch.tpch_q6_numpy(li)


@pytest.mark.parametrize("n", (1, 15, 16, 17))
def test_q3_fused_matches_staged(n):
    cust = tpch.customer_table(max(n // 2, 1))
    orders = tpch.orders_table(n, cust.num_rows)
    li = _with_null_tail(
        tpch.lineitem_q3_table(2 * n, n), cols=(1,))

    fused = tpch.tpch_q3(cust, orders, li)
    staged = _staged(lambda: tpch.tpch_q3(cust, orders, li))
    _assert_tables_identical(fused.result.table, staged.result.table,
                             f"q3 n={n}")
    assert int(fused.result.num_groups) == int(staged.result.num_groups)
    assert int(fused.join_total) == int(staged.join_total)
    assert fused.out_cap == staged.out_cap


@pytest.mark.parametrize("n", (1, 16, 17))
def test_q3_planned_fused_matches_staged(n):
    cust = tpch.customer_table(max(n // 2, 1))
    orders = tpch.orders_table(n, cust.num_rows)
    li = tpch.lineitem_q3_table(2 * n, n)

    fused = tpch.tpch_q3_planned(cust, orders, li)
    staged = _staged(lambda: tpch.tpch_q3_planned(cust, orders, li))
    _assert_tables_identical(fused.result.table, staged.result.table,
                             f"q3_planned n={n}")
    assert int(fused.join_total) == int(staged.join_total)
    assert bool(fused.pk_violation) == bool(staged.pk_violation)
    assert not bool(fused.pk_violation)


def test_q1_planned_domain_miss_replans_identically():
    """Out-of-domain flag bytes must raise domain_miss on BOTH paths, and
    the checked wrapper's re-plan onto the general pipeline must stay
    bit-identical fused vs staged."""
    li = tpch.lineitem_table(33)
    rf = np.asarray(li.column(tpch.L_RETURNFLAG).data).copy()
    rf[5] = ord("X")  # outside the declared 'A'/'N'/'R' domain
    cols = list(li.columns)
    cols[tpch.L_RETURNFLAG] = Column.from_numpy(rf, t.INT8)
    li = Table(cols)

    fused = tpch.tpch_q1_planned_result(li)
    staged = _staged(lambda: tpch.tpch_q1_planned_result(li))
    assert bool(fused.domain_miss) and bool(staged.domain_miss)
    assert fused.lowered == staged.lowered == "bounded"

    replanned = tpch.tpch_q1_planned_checked(li)
    replanned_staged = _staged(lambda: tpch.tpch_q1_planned_checked(li))
    _assert_tables_identical(replanned, replanned_staged, "q1 re-plan")


def test_q1_in_domain_planned_has_no_miss():
    li = tpch.lineitem_table(64)
    res = tpch.tpch_q1_planned_result(li)
    assert not bool(res.domain_miss)
    _assert_tables_identical(
        tpch.tpch_q1_planned_checked(li),
        _staged(lambda: tpch.tpch_q1_planned_checked(li)),
        "q1 planned")


def test_fused_query_composes_under_jit():
    """Inside an outer jit the bindings are tracers: dispatch's inline
    path folds the whole region into the caller's trace, same results."""
    li = tpch.lineitem_table(48)
    eager = tpch.tpch_q1(li)
    jitted = jax.jit(tpch.tpch_q1)(li)
    _assert_tables_identical(eager, jitted, "q1 under jit")


# ---------------------------------------------------------------------------
# executable economy: one compile per region per bucket, not per op
# ---------------------------------------------------------------------------


def test_one_executable_per_region_per_bucket():
    """Four row counts inside one bucket (17..32 pad to 32) must compile
    the q1 region exactly ONCE — the fused region inherits dispatch's
    shape bucketing wholesale."""
    for n in (17, 20, 31, 32):
        tpch.tpch_q1(tpch.lineitem_table(n))
    st = fusion.stats()
    assert st["regions"] == 4 and st["staged_regions"] == 0
    assert st["executables"] == 1, st
    assert st["executables_per_query"] == {"tpch_q1": 1}
    assert REGISTRY.counter("dispatch.hit").value == 3


def test_fused_compiles_fewer_executables_than_staged():
    """The whole point: the staged q1 pays one executable per op
    (groupby machinery, sort, gather...); the fused region pays ONE."""
    li = tpch.lineitem_table(40)
    tpch.tpch_q1(li)
    fused_compiles = sum(
        REGISTRY.counters("dispatch.compile.").values())
    assert fused_compiles == 1

    REGISTRY.reset()
    _staged(lambda: tpch.tpch_q1(li))
    staged_compiles = sum(
        REGISTRY.counters("dispatch.compile.").values())
    assert staged_compiles > fused_compiles, (
        f"staged path compiled {staged_compiles} executables; fusion "
        f"must beat it (got {fused_compiles})")


def test_staged_region_counter_accounts_disabled_runs():
    li = tpch.lineitem_table(16)
    _staged(lambda: tpch.tpch_q1(li))
    st = fusion.stats()
    assert st["staged_regions"] == 1 and st["regions"] == 0


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------


def _double_col(tbl: Table) -> Table:
    c = tbl.column(0)
    return Table([Column(c.dtype, c.data * 2, c.valid_mask())])


def test_donated_intermediates_are_accounted():
    """donate_inputs=True on a caller-owned intermediate accounts the
    donated buffer bytes and leaves results identical."""
    vals = np.arange(64, dtype=np.int64)
    plan = fusion.Plan("donate_probe", fusion.Project(
        fusion.Scan("t"), _double_col))

    expected = vals * 2
    res = fusion.execute(
        plan, {"t": Table([Column.from_numpy(vals.copy())])},
        donate_inputs=True)
    got = np.asarray(res.table.column(0).data)
    assert np.array_equal(got, expected)
    assert fusion.stats()["donated_bytes"] > 0


def test_fusion_donate_config_gates_donation():
    set_option("fusion.donate", False)
    vals = np.arange(64, dtype=np.int64)
    plan = fusion.Plan("donate_probe", fusion.Project(
        fusion.Scan("t"), _double_col))
    fusion.execute(plan, {"t": Table([Column.from_numpy(vals)])},
                   donate_inputs=True)
    assert fusion.stats()["donated_bytes"] == 0


def test_undeclared_inputs_are_never_donated():
    vals = np.arange(64, dtype=np.int64)
    plan = fusion.Plan("donate_probe", fusion.Project(
        fusion.Scan("t"), _double_col))
    fusion.execute(plan, {"t": Table([Column.from_numpy(vals)])})
    assert fusion.stats()["donated_bytes"] == 0


# ---------------------------------------------------------------------------
# IR discipline: misuse fails loud, outside any trace
# ---------------------------------------------------------------------------


def _keep_evens(tbl: Table) -> jax.Array:
    return tbl.column(0).data % 2 == 0


def test_filter_and_limit_nodes_fused_match_staged():
    vals = np.arange(1, 41, dtype=np.int64)
    tbl = Table([Column.from_numpy(vals)])
    plan = fusion.Plan("filter_limit", fusion.Limit(
        fusion.Filter(fusion.Scan("t"), _keep_evens), 100))

    fused = fusion.execute(plan, {"t": tbl}).table
    staged = _staged(lambda: fusion.execute(plan, {"t": tbl}).table)
    # Limit clamps to the TRUE row count, not the bucket
    assert fused.num_rows == staged.num_rows == 40
    _assert_tables_identical(fused, staged, "filter+limit")
    valid = np.asarray(fused.column(0).valid_mask())
    assert np.array_equal(valid, vals % 2 == 0)


def test_unbound_scan_raises():
    plan = fusion.Plan("p", fusion.Scan("missing"))
    with pytest.raises(KeyError, match="unbound table 'missing'"):
        fusion.execute(plan, {})


def test_inconsistent_bucket_flags_raise():
    a, b = fusion.Scan("t"), fusion.Scan("t", bucket=False)
    plan = fusion.Plan("p", fusion.Join(
        a, b, (0,), (0,), fusion.rows_of("t")))
    with pytest.raises(ValueError, match="both bucketed and exact"):
        fusion.execute(plan, {"t": Table([Column.from_numpy(
            np.arange(4, dtype=np.int64))])})


def test_local_callables_are_rejected():
    plan = fusion.Plan("p", fusion.Project(
        fusion.Scan("t"), lambda tbl: tbl))
    with pytest.raises(ValueError, match="module-level"):
        fusion.execute(plan, {"t": Table([Column.from_numpy(
            np.arange(4, dtype=np.int64))])})


def test_unresolvable_row_spec_raises():
    plan = fusion.Plan("p", fusion.Join(
        fusion.Scan("t"), fusion.Scan("t"), (0,), (0,),
        ("bogus_spec", "t", 1)))
    with pytest.raises(ValueError, match="unresolvable row spec"):
        fusion.execute(plan, {"t": Table([Column.from_numpy(
            np.arange(4, dtype=np.int64))])})


def test_row_specs_resolve_from_true_rows():
    assert fusion._resolve(fusion.rows_of("t", 3), {"t": 10}) == 30
    assert fusion._resolve(fusion.min_rows_of("t", 7), {"t": 10}) == 7
    assert fusion._resolve(fusion.min_rows_of("t", 7), {"t": 4}) == 4
    assert fusion._resolve(None, {}) is None
    assert fusion._resolve(12, {}) == 12
