"""End-to-end data integrity layer (runtime/integrity.py, ISSUE 10).

Five invariant families:

1. **Trailer primitives** — ``seal``/``verify`` roundtrip; every
   corruption shape (bit flip, truncation, trailer clobber, magic
   clobber, length-field lie) raises a classified ``CorruptDataError``
   before a payload byte reaches a decoder; the masked checksum never
   equals the raw crc32 it wraps.

2. **At-rest seams** — SpillStore detects drifted host snapshots
   (in-memory crc) and corrupt disk payloads (sealed files) at unspill,
   with the entry left spilled; ``write_payload_file`` is crash-safe
   (tmp + ``os.replace``: an interrupted write leaves the old file
   intact and no tmp litter).

3. **On-wire seam** — a corrupted DCN frame is NAK'd and refetched to a
   bit-identical delivery; refetch exhaustion dies classified on BOTH
   sides; with integrity disabled the wire framing is byte-for-byte the
   legacy ``<Q length> + blob`` with no trailer and no acknowledgement.

4. **Checkpoint seam** — a corrupt out-of-core partial is discarded and
   its chunk replayed from source to a bit-identical result with zero
   leaked reservations; the serial path (no re-enterable source list)
   propagates the classified error instead.

5. **Untrusted ingestion** — malformed Parquet/ORC envelopes are
   rejected as ``MalformedFileError`` (``MalformedInputError`` for the
   serving stack, ``NativeError`` for legacy catches) by pure-Python
   preflight, no native lib needed; the server rejects that one query
   cleanly — never retried, zero leaked reservations, other sessions
   unperturbed.
"""

import os
import pickle
import socket
import struct
import threading
import zlib

import numpy as np
import pytest

from spark_rapids_jni_tpu import telemetry
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.parquet.footer import MalformedFileError, NativeError
from spark_rapids_jni_tpu.runtime import faults, integrity, resilience
from spark_rapids_jni_tpu.runtime.memory import (
    MemoryLimiter,
    SpillStore,
    _col_to_host,
    _table_nbytes,
)
from spark_rapids_jni_tpu.runtime.outofcore import run_chunked_aggregate
from spark_rapids_jni_tpu.runtime.resilience import (
    CorruptDataError,
    FatalExecutionError,
    MalformedInputError,
)
from spark_rapids_jni_tpu.telemetry import REGISTRY
from spark_rapids_jni_tpu.utils import config


@pytest.fixture(autouse=True)
def _reset():
    telemetry.drain()
    REGISTRY.reset()
    config.set_option("telemetry.enabled", True)
    yield
    telemetry.drain()
    REGISTRY.reset()
    for name in list(config._overrides):
        config.reset_option(name)


def _tables_bit_identical(a, b):
    if a.num_rows != b.num_rows or a.num_columns != b.num_columns:
        return False
    for ca, cb in zip(a.columns, b.columns):
        if ca.dtype != cb.dtype:
            return False
        if not np.array_equal(np.asarray(ca.data), np.asarray(cb.data)):
            return False
        if not np.array_equal(np.asarray(ca.valid_mask()),
                              np.asarray(cb.valid_mask())):
            return False
    return True


def _small_table(n=64, seed=3):
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(rng.integers(0, 9, n).astype(np.int64)),
        Column.from_numpy(rng.integers(-100, 100, n).astype(np.int64),
                          validity=rng.random(n) > 0.2),
    ])


# ---------------------------------------------------------------------------
# 1. trailer primitives
# ---------------------------------------------------------------------------


def test_seal_verify_roundtrip():
    for payload in (b"", b"x", b"payload bytes under test", bytes(4096)):
        blob = integrity.seal(payload)
        assert len(blob) == len(payload) + integrity.TRAILER_SIZE
        assert integrity.verify(blob, seam="integrity.spill") == payload
    assert REGISTRY.counter("integrity.mismatch").value == 0
    assert REGISTRY.counter("integrity.bytes_verified").value > 0


@pytest.mark.parametrize("mutate, reason", [
    (lambda b: bytes([b[0] ^ 0x40]) + b[1:], "checksum mismatch"),
    (lambda b: b[:-5], "trailer"),  # truncation eats the trailer
    (lambda b: b[: len(b) // 2], "trailer"),
    (lambda b: b[:-16] + b"XXXX" + b[-12:], "magic clobbered"),
    (lambda b: b[:-12] + struct.pack("<Q", 10 ** 9) + b[-4:],
     "length disagrees"),
    (lambda b: b[:-4] + bytes([b[-4] ^ 1]) + b[-3:], "checksum mismatch"),
], ids=["payload-flip", "truncate-5", "truncate-half", "magic-clobber",
        "length-lie", "crc-flip"])
def test_verify_detects_every_corruption_shape(mutate, reason):
    blob = integrity.seal(b"the payload the trailer protects" * 8)
    with pytest.raises(CorruptDataError, match=reason):
        integrity.verify(mutate(blob), seam="integrity.wire",
                         op="test.verify")
    assert REGISTRY.counter("integrity.mismatch").value == 1
    assert REGISTRY.counter("integrity.mismatch.integrity.wire").value == 1
    evs = [e for e in telemetry.events() if e.get("kind") == "integrity"]
    assert evs and evs[-1]["event"] == "mismatch"
    assert evs[-1]["seam"] == "integrity.wire"


def test_blob_shorter_than_trailer_is_classified():
    with pytest.raises(CorruptDataError, match="shorter than"):
        integrity.verify(b"tiny", seam="integrity.spill")


def test_checksum_is_masked_crc32():
    for payload in (b"", b"abc", bytes(range(256))):
        raw = zlib.crc32(payload) & 0xFFFFFFFF
        masked = integrity.checksum(payload)
        assert masked != raw  # a blob embedding its own crc32 never verifies
        assert 0 <= masked <= 0xFFFFFFFF
    # deterministic: same bytes, same checksum
    assert integrity.checksum(b"abc") == integrity.checksum(b"abc")


def test_corrupt_data_error_transience_is_seam_specific():
    exc = CorruptDataError("bad frame", seam="integrity.wire")
    # refetchable only at transport seams (a pristine copy exists there)
    assert resilience.is_transient(exc, seam="dcn.transport")
    assert resilience.is_transient(exc, seam="shuffle.transport")
    assert not resilience.is_transient(exc, seam="spill.unspill")
    assert not resilience.is_transient(exc)
    # malformed input is never retried anywhere
    malformed = MalformedInputError("bad file")
    assert not resilience.is_transient(malformed, seam="dcn.transport")


def test_snaps_checksum_detects_drift():
    tbl = _small_table(128, seed=5)
    snaps = [_col_to_host(c) for c in tbl.columns]
    crc = integrity.snaps_checksum(snaps)
    integrity.verify_snaps(snaps, crc, seam="integrity.spill")  # no raise
    # drift one byte of one buffer: the fold must notice
    data = np.asarray(snaps[0][1]).copy()
    data.view(np.uint8)[3] ^= 0x10
    snaps[0] = (snaps[0][0], data, snaps[0][2], snaps[0][3], snaps[0][4])
    assert integrity.snaps_checksum(snaps) != crc
    with pytest.raises(CorruptDataError, match="snapshot checksum"):
        integrity.verify_snaps(snaps, crc, seam="integrity.spill")


def test_record_integrity_validates_seam_and_reserved_fields():
    with pytest.raises(ValueError, match="seam must be non-empty"):
        telemetry.record_integrity("op", "mismatch", seam="")
    with pytest.raises(ValueError, match="reserved"):
        telemetry.record_integrity("op", "mismatch",
                                   seam="integrity.spill", kind="x")


def test_enabled_env_var_overrides_option(monkeypatch):
    config.set_option("integrity.enabled", True)
    monkeypatch.setenv("SPARK_RAPIDS_TPU_INTEGRITY", "0")
    assert not integrity.enabled()
    config.set_option("integrity.enabled", False)
    monkeypatch.setenv("SPARK_RAPIDS_TPU_INTEGRITY", "on")
    assert integrity.enabled()
    monkeypatch.delenv("SPARK_RAPIDS_TPU_INTEGRITY")
    assert not integrity.enabled()
    config.reset_option("integrity.enabled")
    assert integrity.enabled()  # default is on


# ---------------------------------------------------------------------------
# 2. at-rest seams: payload files and the SpillStore tiers
# ---------------------------------------------------------------------------


def test_write_payload_file_roundtrip_and_no_tmp_litter(tmp_path):
    path = str(tmp_path / "payload.bin")
    blob = integrity.seal(b"spill bytes" * 100)
    assert integrity.write_payload_file(path, blob) == len(blob)
    assert integrity.read_payload_file(
        path, seam="integrity.spill", sealed=True) == b"spill bytes" * 100
    # crash-safety hygiene: the tmp file was consumed by os.replace
    assert [p for p in os.listdir(tmp_path)
            if p.startswith(".integrity-")] == []


def test_write_payload_file_interrupted_replace_keeps_old_file(
        tmp_path, monkeypatch):
    """A crash between tmp-write and rename must leave the previous
    payload intact and unlink the tmp — never a torn hybrid."""
    path = str(tmp_path / "payload.bin")
    integrity.write_payload_file(path, integrity.seal(b"generation one"))

    def boom(src, dst):
        raise OSError("simulated crash at rename")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError, match="simulated crash"):
        integrity.write_payload_file(path, integrity.seal(b"generation two"))
    monkeypatch.undo()
    assert integrity.read_payload_file(
        path, seam="integrity.spill", sealed=True) == b"generation one"
    assert [p for p in os.listdir(tmp_path)
            if p.startswith(".integrity-")] == []


def test_read_payload_file_detects_on_disk_corruption(tmp_path):
    path = str(tmp_path / "payload.bin")
    integrity.write_payload_file(path, integrity.seal(b"pristine" * 64))
    raw = bytearray(open(path, "rb").read())
    raw[7] ^= 0x80  # bitrot after the write-verify passed
    with open(path, "wb") as fh:
        fh.write(raw)
    with pytest.raises(CorruptDataError):
        integrity.read_payload_file(path, seam="integrity.spill", sealed=True)


def test_read_payload_file_unsealed_returns_raw_bytes(tmp_path):
    path = str(tmp_path / "raw.bin")
    integrity.write_payload_file(path, b"no trailer here")
    assert integrity.read_payload_file(
        path, seam="integrity.spill", sealed=False) == b"no trailer here"


def _evicting_store(tbl, **kw):
    """A store whose budget fits exactly one table: the second put evicts
    the first, exercising the spill tier under test."""
    return SpillStore(budget_bytes=_table_nbytes(tbl), **kw)


def test_spill_memory_tier_clean_roundtrip_bit_identical():
    tbl = _small_table(256, seed=7)
    store = _evicting_store(tbl)
    h = store.put(tbl)
    store.put(_small_table(256, seed=8))  # evicts h to host
    assert store.stats()["host_bytes"] > 0
    got = store.get(h)
    assert _tables_bit_identical(got, tbl)
    assert REGISTRY.counter("integrity.verified.integrity.spill").value == 1
    store.close()


def test_spill_memory_tier_detects_drift_and_stays_spilled():
    tbl = _small_table(256, seed=7)
    store = _evicting_store(tbl)
    script = faults.FaultScript(
        corruptions=[faults.CorruptionSpec("integrity.spill", mode="flip")])
    with faults.inject(script):
        h = store.put(tbl)
        store.put(_small_table(256, seed=8))
    assert script.fired, "corruption window never fired"
    for _ in range(2):  # deterministic: the same bytes fail every read
        with pytest.raises(CorruptDataError, match="snapshot checksum"):
            store.get(h)
    assert REGISTRY.counter(
        "integrity.mismatch.integrity.spill").value == 2
    store.close()


@pytest.mark.parametrize("mode", faults.CorruptionSpec.MODES)
def test_spill_disk_tier_detects_every_mode(tmp_path, mode):
    tbl = _small_table(256, seed=7)
    store = _evicting_store(tbl, spill_dir=str(tmp_path))
    script = faults.FaultScript(
        corruptions=[faults.CorruptionSpec(
            "integrity.spill", mode=mode, seed=11)])
    with faults.inject(script):
        h = store.put(tbl)
        store.put(_small_table(256, seed=8))
    assert store.stats()["disk_bytes"] > 0
    assert script.fired
    with pytest.raises(CorruptDataError):
        store.get(h)
    store.close()
    assert [p for p in os.listdir(tmp_path) if p.startswith("spill-")] == []


def test_spill_disk_tier_clean_roundtrip_unlinks_file(tmp_path):
    tbl = _small_table(256, seed=7)
    store = _evicting_store(tbl, spill_dir=str(tmp_path))
    h = store.put(tbl)
    store.put(_small_table(256, seed=8))
    files = [p for p in os.listdir(tmp_path) if p.startswith("spill-")]
    assert len(files) == 1
    # the sealed file carries the trailer right at EOF
    blob = open(str(tmp_path / files[0]), "rb").read()
    assert blob[-integrity.TRAILER_SIZE:][:4] == integrity.TRAILER_MAGIC
    got = store.get(h)
    assert _tables_bit_identical(got, tbl)
    # h's file is consumed on unspill (staging h back evicted the OTHER
    # table to a fresh file); close() sweeps everything
    assert not any(p.endswith(f"-{h}.bin") for p in os.listdir(tmp_path))
    store.close()
    assert [p for p in os.listdir(tmp_path) if p.startswith("spill-")] == []


def test_spill_disabled_path_has_no_trailer_no_crc(tmp_path):
    config.set_option("integrity.enabled", False)
    tbl = _small_table(256, seed=7)
    store = _evicting_store(tbl, spill_dir=str(tmp_path))
    h = store.put(tbl)
    store.put(_small_table(256, seed=8))
    files = [p for p in os.listdir(tmp_path) if p.startswith("spill-")]
    blob = open(str(tmp_path / files[0]), "rb").read()
    # byte-for-byte legacy behavior: the file IS the pickled snapshot
    assert blob[-integrity.TRAILER_SIZE:][:4] != integrity.TRAILER_MAGIC
    pickle.loads(blob)  # decodes directly, no framing
    got = store.get(h)
    assert _tables_bit_identical(got, tbl)
    assert REGISTRY.counter("integrity.mismatch").value == 0
    assert REGISTRY.counter("integrity.bytes_verified").value == 0
    store.close()


# ---------------------------------------------------------------------------
# 3. on-wire seam: DCN loopback
# ---------------------------------------------------------------------------


def _loopback_links():
    from spark_rapids_jni_tpu.parallel.dcn import SliceLink

    a, b = socket.socketpair()
    return SliceLink(a), SliceLink(b)


def _send_recv(tbl, script=None):
    tx, rx = _loopback_links()
    out, err = {}, {}

    def _rx():
        try:
            out["tbl"] = rx.recv_table()
        except BaseException as exc:  # noqa: BLE001 - surfaced below
            err["rx"] = exc

    t = threading.Thread(target=_rx)
    try:
        ctx = faults.inject(script) if script is not None else None
        if ctx is not None:
            ctx.__enter__()
        try:
            t.start()
            try:
                tx.send_table(tbl, compress_level=0)
            except BaseException as exc:  # noqa: BLE001 - surfaced below
                err["tx"] = exc
            t.join(30)
            assert not t.is_alive(), "receiver hung"
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)
    finally:
        tx.close()
        rx.close()
    return out.get("tbl"), err


def test_wire_clean_roundtrip_verifies_and_acks():
    tbl = _small_table()
    got, err = _send_recv(tbl)
    assert not err
    assert _tables_bit_identical(got, tbl)
    assert REGISTRY.counter("integrity.verified.integrity.wire").value == 1
    assert REGISTRY.counter("integrity.bytes_verified").value > 0
    assert REGISTRY.counter("integrity.refetch").value == 0


@pytest.mark.parametrize("mode", faults.CorruptionSpec.MODES)
def test_wire_corruption_refetches_to_bit_identical(mode):
    tbl = _small_table()
    script = faults.FaultScript(
        corruptions=[faults.CorruptionSpec(
            "integrity.wire", mode=mode, seed=23)])
    got, err = _send_recv(tbl, script)
    assert not err, f"refetch should have recovered: {err}"
    assert script.fired == [("integrity.wire", 1)]
    assert _tables_bit_identical(got, tbl)
    assert REGISTRY.counter("integrity.refetch").value == 1
    assert REGISTRY.counter("integrity.mismatch.integrity.wire").value == 1
    evs = [e for e in telemetry.events() if e.get("kind") == "integrity"]
    assert [e["event"] for e in evs] == ["mismatch", "refetch", "recovered"]


def test_wire_refetch_exhaustion_dies_classified_on_both_sides():
    config.set_option("resilience.max_attempts", 2)
    tbl = _small_table()
    script = faults.FaultScript(
        corruptions=[faults.CorruptionSpec(
            "integrity.wire", mode="flip", times=10, seed=31)])
    got, err = _send_recv(tbl, script)
    assert got is None
    assert isinstance(err.get("tx"), FatalExecutionError)
    assert isinstance(err.get("rx"), FatalExecutionError)
    assert "corrupt" in str(err["rx"])
    assert isinstance(err["rx"].__cause__, CorruptDataError)
    assert REGISTRY.counter("integrity.refetch").value == 2
    # every attempt hit the corruption window: 2 sends, both mutated
    assert len(script.fired) == 2


def test_wire_disabled_framing_is_byte_identical_legacy():
    """integrity.enabled=false: the sender writes exactly the legacy
    ``<Q length> + serialized blob`` — no trailer, no ACK wait — so a
    pre-integrity peer interoperates byte-for-byte."""
    from spark_rapids_jni_tpu.parallel.dcn import SliceLink, serialize_table

    config.set_option("integrity.enabled", False)
    tbl = _small_table()
    want = serialize_table(tbl, 0)
    sa, sb = socket.socketpair()
    tx = SliceLink(sa)
    try:
        sent = tx.send_table(tbl, compress_level=0)  # returns: no ACK wait
        assert sent == len(want)
        sb.settimeout(10)
        raw = b""
        while len(raw) < 8 + len(want):
            raw += sb.recv(1 << 20)
        assert raw == struct.pack("<Q", len(want)) + want
        assert integrity.TRAILER_MAGIC not in raw[-integrity.TRAILER_SIZE:]
    finally:
        tx.close()
        sb.close()


# ---------------------------------------------------------------------------
# 4. checkpoint seam: out-of-core replay
# ---------------------------------------------------------------------------

_CHUNK_ROWS = 96
_N_CHUNKS = 4


def _chunks():
    rng = np.random.default_rng(17)
    return [Table([
        Column.from_numpy(
            rng.integers(0, 50, _CHUNK_ROWS).astype(np.int64)),
    ]) for _ in range(_N_CHUNKS)]


def _partial_fn(chunk):
    s = int(np.asarray(chunk.columns[0].data).sum())
    return Table([Column.from_numpy(np.asarray([s], dtype=np.int64))])


def _merge_fn(partials):
    s = int(np.asarray(partials.columns[0].data).sum())
    return Table([Column.from_numpy(np.asarray([s], dtype=np.int64))])


def _checkpoint_run(chunks, limiter, store, **kw):
    return run_chunked_aggregate(
        list(chunks), _partial_fn, _merge_fn,
        limiter=limiter, spill=store, pipeline=True, **kw)


def test_corrupt_checkpoint_replays_chunk_bit_identical():
    chunks = _chunks()
    want = _merge_fn(Table([Column.from_numpy(np.concatenate(
        [np.asarray([_partial_fn(c).columns[0].data[0]])
         for c in chunks]).astype(np.int64))]))
    limiter = MemoryLimiter(1 << 24)
    # budget == one partial: every checkpoint put evicts its predecessor,
    # so the corruption window sees every partial
    store = SpillStore(budget_bytes=_table_nbytes(_partial_fn(chunks[0])))
    script = faults.FaultScript(
        corruptions=[faults.CorruptionSpec(
            "integrity.checkpoint", mode="flip", times=2, seed=41)])
    with faults.inject(script):
        res = _checkpoint_run(chunks, limiter, store)
    assert len(script.fired) == 2
    assert _tables_bit_identical(res.table, want)
    assert limiter.used == 0, "replay leaked a reservation"
    s = telemetry.summary()["integrity"]
    assert s.get("replay") == 2 and s.get("recovered") == 2
    assert REGISTRY.counter(
        "integrity.mismatch.integrity.checkpoint").value == 2
    store.close()


def test_corrupt_checkpoint_serial_path_propagates_classified():
    """A generator input stream is consumed — there is no source list to
    replay from, so the classified error is the answer."""
    chunks = _chunks()
    limiter = MemoryLimiter(1 << 24)
    store = SpillStore(budget_bytes=_table_nbytes(_partial_fn(chunks[0])))
    script = faults.FaultScript(
        corruptions=[faults.CorruptionSpec(
            "integrity.checkpoint", mode="flip", seed=43)])
    with faults.inject(script):
        with pytest.raises(CorruptDataError):
            run_chunked_aggregate(
                iter(chunks), _partial_fn, _merge_fn,
                limiter=limiter, spill=store, pipeline=False)
    assert limiter.used == 0, "classified failure leaked a reservation"
    store.close()


# ---------------------------------------------------------------------------
# 5. untrusted ingestion: parquet/orc envelopes + the serving stack
# ---------------------------------------------------------------------------


def _parquet_bytes(n=32):
    from tests.parquet_util import ColumnSpec, write_parquet

    return write_parquet([
        ColumnSpec("a", 2, list(range(n))),  # INT64
        ColumnSpec("b", 5, [float(i) / 3 for i in range(n)]),  # DOUBLE
    ])


def _orc_bytes(n=32):
    from tests.orc_util import ColumnSpec, write_orc

    return write_orc([ColumnSpec("a", 4, list(range(n)))])  # LONG


def test_parquet_envelope_malformed_variants_classified():
    from spark_rapids_jni_tpu.parquet.reader import read_table

    good = _parquet_bytes()
    variants = {
        "too-short": good[:8],
        "bad-head-magic": b"XXXX" + good[4:],
        "bad-tail-magic": good[:-4] + b"XXXX",
        "footer-length-lie": good[:-8]
        + struct.pack("<I", len(good) * 2) + good[-4:],
    }
    for name, blob in variants.items():
        with pytest.raises(MalformedFileError) as ei:
            read_table(blob)
        # dual classification: serving stack AND legacy native catches
        assert isinstance(ei.value, MalformedInputError), name
        assert isinstance(ei.value, NativeError), name
    assert REGISTRY.counter(
        "integrity.malformed.parquet.envelope").value == len(variants)
    evs = [e for e in telemetry.events() if e.get("kind") == "integrity"]
    assert all(e["seam"] == "integrity.ingest" for e in evs)


def test_orc_envelope_malformed_variants_classified():
    from spark_rapids_jni_tpu.orc.reader import read_table

    good = _orc_bytes()
    variants = {
        "too-short": good[:5],
        "bad-head-magic": b"XXX" + good[3:],
        "bad-tail-magic": good[:-4] + b"XXXA",
        "ps-length-lie": good[:-1] + bytes([251]),
    }
    for name, blob in variants.items():
        with pytest.raises(MalformedFileError) as ei:
            read_table(blob)
        assert isinstance(ei.value, MalformedInputError), name
        assert isinstance(ei.value, NativeError), name
    assert REGISTRY.counter(
        "integrity.malformed.orc.envelope").value == len(variants)


def test_valid_envelopes_pass_pure_python_preflight():
    """A well-formed file must NOT be rejected by the preflight; on this
    build it then reaches the native loader, which is absent (OSError) —
    the acceptable needs-native outcome, never a MalformedFileError."""
    from spark_rapids_jni_tpu.orc.reader import read_table as orc_read
    from spark_rapids_jni_tpu.parquet.reader import read_table as pq_read

    for reader, blob in ((pq_read, _parquet_bytes()),
                         (orc_read, _orc_bytes())):
        try:
            reader(blob)
        except MalformedInputError:  # pragma: no cover - the regression
            pytest.fail("preflight rejected a well-formed file")
        except OSError:
            pass  # libtpudf.so not built here: preflight already passed
    assert REGISTRY.counter("integrity.malformed").value == 0


def test_envelope_checks_also_cover_path_inputs(tmp_path):
    from spark_rapids_jni_tpu.parquet.reader import read_table

    path = tmp_path / "broken.parquet"
    path.write_bytes(b"PAR1" + b"\x00" * 16)  # no trailing magic
    with pytest.raises(MalformedFileError):
        read_table(str(path))


def test_ingest_preflight_disabled_is_passthrough():
    """integrity.enabled=false: no preflight — malformed bytes reach the
    native loader exactly as before this layer existed."""
    from spark_rapids_jni_tpu.parquet.reader import read_table

    config.set_option("integrity.enabled", False)
    with pytest.raises(OSError):  # load_native, not MalformedFileError
        read_table(b"not parquet at all")
    assert REGISTRY.counter("integrity.malformed").value == 0


def _malformed_ingest(tbl, *args):
    """Module-level plan callable (the executable cache keys on the
    qualified name): reading a malformed customer file mid-query."""
    from spark_rapids_jni_tpu.parquet.reader import read_table

    read_table(b"PAR1 this is not a parquet file")  # MalformedFileError
    return tbl


def test_server_rejects_malformed_query_cleanly():
    """The end-to-end contract: one session submits a query over a
    malformed file — that query fails classified (never retried), the
    bystander session's result is untouched, and zero reservations
    leak."""
    from spark_rapids_jni_tpu.models import tpch
    from spark_rapids_jni_tpu.runtime import dispatch, fusion, server

    dispatch.clear()
    doomed_plan = fusion.Plan("malformed_ingest", fusion.Project(
        fusion.Scan("lineitem"), _malformed_ingest, rowwise=False))
    good_plan = tpch._q1_plan()
    bindings = {"lineitem": tpch.lineitem_table(600, seed=0)}
    ref = fusion.execute(good_plan, bindings)

    with server.QueryServer(budget_bytes=1 << 28, max_inflight=4) as srv:
        doomed = srv.session("victim").submit(doomed_plan, bindings)
        fine = srv.session("bystander").submit(good_plan, bindings)
        with pytest.raises(MalformedInputError):
            doomed.result(timeout=60)
        assert doomed.status == "failed"
        res = fine.result(timeout=60)
        assert fine.status == "served"
        assert _tables_bit_identical(res.table, ref.table)
        # the bystander's cached result legitimately holds a residency
        # charge until close(); anything beyond that is a leak
        assert srv.limiter.used == srv.result_cache.evictable_bytes, \
            "malformed rejection leaked bytes"
        assert srv.session_stats("victim")["failed"] == 1
        assert srv.session_stats("bystander")["failed"] == 0
    assert srv.limiter.used == 0, "close() left reservations behind"
    assert REGISTRY.counter("integrity.malformed_rejects").value == 1
    # never retried: a malformed file is wrong forever
    retries = [e for e in telemetry.events()
               if e.get("kind") == "resilience" and e.get("event") == "retry"]
    assert retries == []
    dispatch.clear()


def test_telemetry_report_has_integrity_section(tmp_path):
    import json

    from spark_rapids_jni_tpu.telemetry.report import report

    blob = integrity.seal(b"x" * 64)
    with pytest.raises(CorruptDataError):
        integrity.verify(blob[:-3], seam="integrity.spill", op="test")
    path = tmp_path / "run.jsonl"
    path.write_text("".join(
        json.dumps(e) + "\n" for e in telemetry.events()))
    text = report(str(path))
    assert "integrity events:" in text
    assert "mismatch seams:" in text
    assert "integrity.spill=1" in text
