"""String relational-core tests: padded layout round trip, memcmp sort
order, groupby on string keys, and full variable-length XXH64 parity with
the independent host oracle (tests/xxh64_ref.py).

Mirrors the reference's oracle pattern (SURVEY.md section 4: round-trip /
golden-equality against the host representation): cuDF handles STRING keys
in sort/groupby/join (capability surface, reference build-libcudf.xml:34-60);
these tests pin the same behavior for the TPU substrate.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops import strings as s
from spark_rapids_jni_tpu.ops.groupby import groupby_aggregate
from spark_rapids_jni_tpu.ops.hash import table_xxhash64
from spark_rapids_jni_tpu.ops.sort import sort_table
from tests.xxh64_ref import xxh64


def random_strings(rng, n, max_len=20, alphabet=b"abcXYZ019 \x00\xc3\xa9"):
    out = []
    for _ in range(n):
        k = int(rng.integers(0, max_len + 1))
        out.append(bytes(rng.choice(list(alphabet), size=k)).decode("latin1"))
    return out


class TestPaddedLayout:
    def test_round_trip(self, rng):
        vals = ["", "a", "hello world", None, "abc\x00def", "x" * 31]
        col = Column.from_pylist(vals, t.STRING)
        padded = s.pad_strings(col)
        assert padded.is_padded_string
        assert padded.to_pylist() == vals
        back = s.unpad_strings(padded)
        assert not back.is_padded_string
        assert back.to_pylist() == vals

    def test_round_trip_random(self, rng):
        vals = random_strings(rng, 257)
        vals[13] = None
        col = Column.from_pylist(vals, t.STRING)
        assert s.unpad_strings(s.pad_strings(col)).to_pylist() == vals

    def test_empty_column(self):
        col = Column.from_pylist([], t.STRING)
        padded = s.pad_strings(col)
        assert padded.size == 0
        assert s.unpad_strings(padded).to_pylist() == []

    def test_gather(self, rng):
        vals = ["bb", "a", None, "ddd", ""]
        col = Column.from_pylist(vals, t.STRING)
        g = s.gather_strings(col, jnp.asarray([3, 0, 2, 1, 4, 0]))
        assert g.to_pylist() == ["ddd", "bb", None, "a", "", "bb"]


class TestStringSort:
    def test_memcmp_order(self, rng):
        vals = ["b", "ab", "", "abc", "a", "ab\x00", "aa", "B", None, "ab"]
        tbl = Table([
            Column.from_pylist(vals, t.STRING),
            Column.from_pylist(list(range(len(vals))), t.INT32),
        ])
        out = sort_table(tbl, keys=[0], nulls_first=[True])
        got = out.column(0).to_pylist()
        expect = [None] + sorted(v for v in vals if v is not None)
        assert got == expect

    def test_desc_nulls_last(self, rng):
        vals = random_strings(rng, 101)
        vals[7] = None
        tbl = Table([Column.from_pylist(vals, t.STRING)])
        out = sort_table(tbl, keys=[0], ascending=[False], nulls_first=[False])
        got = out.column(0).to_pylist()
        expect = sorted((v for v in vals if v is not None), reverse=True) + [None]
        assert got == expect

    def test_string_secondary_key(self, rng):
        k1 = ["x", "x", "y", "y", "x"]
        k2 = ["b", "a", "c", "a", "a"]
        tbl = Table([
            Column.from_pylist(k1, t.STRING),
            Column.from_pylist(k2, t.STRING),
            Column.from_pylist([0, 1, 2, 3, 4], t.INT32),
        ])
        out = sort_table(tbl, keys=[0, 1])
        assert out.column(2).to_pylist() == [1, 4, 0, 3, 2]


class TestStringGroupBy:
    def test_q1_style_string_keys(self, rng):
        # TPC-H q1 grouping shape on real STRING flags (VERDICT round-2 #2)
        n = 4000
        flags = ["A", "N", "R"]
        status = ["F", "O"]
        f = [flags[i] for i in rng.integers(0, 3, n)]
        st = [status[i] for i in rng.integers(0, 2, n)]
        qty = rng.integers(1, 50, n).astype(np.int64)
        tbl = Table([
            Column.from_pylist(f, t.STRING),
            Column.from_pylist(st, t.STRING),
            Column.from_numpy(qty),
        ])
        res = groupby_aggregate(tbl, keys=[0, 1], aggs=[(2, "sum"), (2, "count")])
        out = res.compact()
        got = {
            (out.column(0).to_pylist()[i], out.column(1).to_pylist()[i]):
                (out.column(2).to_pylist()[i], out.column(3).to_pylist()[i])
            for i in range(int(res.num_groups))
        }
        expect = {}
        for fi, si, qi in zip(f, st, qty):
            tot, cnt = expect.get((fi, si), (0, 0))
            expect[(fi, si)] = (tot + int(qi), cnt + 1)
        assert got == expect

    def test_null_string_group(self):
        vals = ["a", None, "a", None, "b"]
        x = [1, 2, 3, 4, 5]
        tbl = Table([
            Column.from_pylist(vals, t.STRING),
            Column.from_pylist(x, t.INT64),
        ])
        res = groupby_aggregate(tbl, [0], [(1, "sum")])
        out = res.compact()
        got = dict(zip(out.column(0).to_pylist(), out.column(1).to_pylist()))
        assert got == {None: 6, "a": 4, "b": 5}

    def test_max_groups_overflow_and_auto(self, rng):
        from spark_rapids_jni_tpu.ops.groupby import groupby_aggregate_auto

        n = 512
        keys = [f"k{i:03d}" for i in rng.integers(0, 100, n)]
        tbl = Table([
            Column.from_pylist(keys, t.STRING),
            Column.from_pylist([1] * n, t.INT64),
        ])
        small = groupby_aggregate(tbl, [0], [(1, "count")], max_groups=8)
        assert bool(small.overflowed)
        auto = groupby_aggregate_auto(tbl, [0], [(1, "count")],
                                      initial_max_groups=8)
        assert not bool(auto.overflowed)
        assert int(auto.num_groups) == len(set(keys))


class TestXXH64Bytes:
    @pytest.mark.parametrize("width", [8, 31, 32, 40, 100])
    def test_matches_reference_all_lengths(self, rng, width):
        # every length 0..width crosses each phase boundary of the algorithm
        # (empty / <4 / <8 / <32 / stripes+tails)
        raw = [bytes(rng.integers(0, 256, size=k, dtype=np.uint8))
               for k in range(width + 1)]
        n = len(raw)
        mat = np.zeros((n, width if width else 1), dtype=np.uint8)
        for i, b in enumerate(raw):
            mat[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
        lengths = np.array([len(b) for b in raw], dtype=np.int32)
        seeds = np.asarray(rng.integers(0, 1 << 63, size=n), dtype=np.uint64)
        got = np.asarray(
            s.xxhash64_bytes(jnp.asarray(mat), jnp.asarray(lengths),
                             jnp.asarray(seeds))
        )
        expect = np.array(
            [xxh64(b, seed=int(sd)) for b, sd in zip(raw, seeds)],
            dtype=np.uint64,
        )
        np.testing.assert_array_equal(got, expect)

    def test_table_hash_with_string_column(self, rng):
        vals = ["", "spark", "a longer string that crosses 32 bytes easily!",
                None, "xyz"]
        ints = [7, None, 9, 10, 11]
        tbl = Table([
            Column.from_pylist(ints, t.INT32),
            Column.from_pylist(vals, t.STRING),
        ])
        got = np.asarray(table_xxhash64(tbl)).astype(np.uint64)
        # host oracle: chain per column, null passes seed through
        expect = []
        for iv, sv in zip(ints, vals):
            h = 42
            if iv is not None:
                h = xxh64(int(np.int32(iv)).to_bytes(4, "little", signed=True),
                          seed=h)
            if sv is not None:
                h = xxh64(sv.encode(), seed=h)
            expect.append(h)
        np.testing.assert_array_equal(got, np.array(expect, dtype=np.uint64))


class TestReviewRegressions:
    def test_empty_table_groupby_with_max_groups(self):
        tbl = Table([
            Column.from_pylist([], t.STRING),
            Column.from_pylist([], t.INT64),
        ])
        res = groupby_aggregate(tbl, [0], [(1, "sum")], max_groups=4)
        assert int(res.num_groups) == 0
        assert not bool(res.overflowed)

    def test_compact_on_overflow_raises(self, rng):
        tbl = Table([
            Column.from_pylist(["a", "b", "c"], t.STRING),
            Column.from_pylist([1, 2, 3], t.INT64),
        ])
        res = groupby_aggregate(tbl, [0], [(1, "sum")], max_groups=2)
        assert bool(res.overflowed)
        with pytest.raises(ValueError, match="overflowed"):
            res.compact()

    def test_jit_over_padded_strings(self):
        import jax

        col = s.pad_strings(Column.from_pylist(["b", "a", "c"], t.STRING))
        tbl = Table([col])

        @jax.jit
        def run(tb):
            from spark_rapids_jni_tpu.ops.sort import sort_table

            return sort_table(tb, [0])

        out = run(tbl)
        assert out.column(0).to_pylist() == ["a", "b", "c"]

    def test_pad_inside_jit_without_width_raises(self):
        import jax

        col = Column.from_pylist(["b", "a"], t.STRING)

        @jax.jit
        def run(c):
            return s.pad_strings(c).data

        with pytest.raises(ValueError, match="static width"):
            run(col)


class TestStringMinMax:
    def test_min_max_matches_oracle(self, rng):
        n = 400
        keys = [int(v) for v in rng.integers(0, 12, n)]
        words = [f"w{v:03d}" for v in rng.integers(0, 500, n)]
        for i in range(0, n, 23):
            words[i] = None
        tbl = Table([
            Column.from_pylist(keys, t.INT32),
            Column.from_pylist(words, t.STRING),
        ])
        res = groupby_aggregate(tbl, [0], [(1, "min"), (1, "max")])
        out = res.compact()
        got = {
            out.column(0).to_pylist()[i]: (
                out.column(1).to_pylist()[i], out.column(2).to_pylist()[i])
            for i in range(int(res.num_groups))
        }
        want = {}
        for k, w in zip(keys, words):
            lo, hi = want.get(k, (None, None))
            if w is not None:
                lo = w if lo is None else min(lo, w)
                hi = w if hi is None else max(hi, w)
            want[k] = (lo, hi)
        assert got == want

    def test_all_null_group_is_null(self):
        tbl = Table([
            Column.from_pylist([1, 1, 2], t.INT32),
            Column.from_pylist([None, None, "z"], t.STRING),
        ])
        res = groupby_aggregate(tbl, [0], [(1, "min")])
        out = res.compact()
        assert out.column(1).to_pylist() == [None, "z"]


# ---- search predicates -----------------------------------------------------


def _rand_strings(rng, n, alphabet="abc%_x", maxlen=12):
    out = []
    for _ in range(n):
        ln = int(rng.integers(0, maxlen))
        out.append("".join(rng.choice(list(alphabet)) for _ in range(ln)))
    return out


def test_contains_starts_ends_vs_python(rng):
    from spark_rapids_jni_tpu.ops import strings as s

    vals = _rand_strings(rng, 300) + [None, "", "abc"]
    col = Column.from_pylist(vals, t.STRING)
    for needle in ["a", "ab", "abc", "", "bca", "xxxxxxxxxxxxxxxxx"]:
        got_c = s.contains(col, needle).to_pylist()
        got_s = s.starts_with(col, needle).to_pylist()
        got_e = s.ends_with(col, needle).to_pylist()
        for i, v in enumerate(vals):
            if v is None:
                assert got_c[i] is None and got_s[i] is None
                continue
            assert got_c[i] == (needle in v), (v, needle)
            assert got_s[i] == v.startswith(needle), (v, needle)
            assert got_e[i] == v.endswith(needle), (v, needle)


def test_like_vs_regex_oracle(rng):
    import re

    from spark_rapids_jni_tpu.ops import strings as s

    def like_re(pat):
        out = []
        i = 0
        while i < len(pat):
            c = pat[i]
            if c == "\\" and i + 1 < len(pat):
                out.append(re.escape(pat[i + 1]))
                i += 2
                continue
            if c == "%":
                out.append(".*")
            elif c == "_":
                out.append(".")
            else:
                out.append(re.escape(c))
            i += 1
        return re.compile("".join(out), re.DOTALL)

    vals = _rand_strings(rng, 250) + ["", "abc", "a%b", "axxb", None]
    col = Column.from_pylist(vals, t.STRING)
    patterns = ["%", "", "a%", "%a", "%ab%", "a_c", "_", "__", "a%b%c",
                "abc", "%abc", "abc%", "a\\%b", "%a_c%", "a%%b", "_%_"]
    for pat in patterns:
        rx = like_re(pat)
        got = s.like(col, pat).to_pylist()
        for i, v in enumerate(vals):
            if v is None:
                assert got[i] is None
                continue
            want = rx.fullmatch(v) is not None
            assert got[i] == want, (v, pat, got[i], want)


def test_like_underscore_multibyte_utf8_char_semantics():
    """'_' matches one CHARACTER, not one byte (Spark semantics) —
    multi-byte UTF-8 no longer fails loudly, it works."""
    from spark_rapids_jni_tpu.ops import strings as s

    col = Column.from_pylist(["aéc", "abc", "axyc", "日本語"], t.STRING)
    assert s.like(col, "a_c").to_pylist() == [True, True, False, False]
    assert s.like(col, "___").to_pylist() == [True, True, False, True]
    assert s.like(col, "_本_").to_pylist() == [False, False, False, True]
    assert s.like(col, "__").to_pylist() == [False, False, False, False]
    assert s.like(col, "_%").to_pylist() == [True, True, True, True]
    # '%' and literal patterns stay byte-exact on the same data
    assert s.like(col, "a%c").to_pylist() == [True, True, True, False]
    assert s.contains(col, "é").to_pylist() == [True, False, False, False]


def test_like_multibyte_vs_regex_oracle(rng):
    """Random UTF-8 strings x '_'-bearing patterns against Python's
    character-level regex engine."""
    import re

    from spark_rapids_jni_tpu.ops import strings as s

    alphabet = list("abéλ日x")
    vals = ["".join(rng.choice(alphabet,
                               size=int(rng.integers(0, 7))))
            for _ in range(200)]
    col = Column.from_pylist(vals, t.STRING)
    for pat in ["_", "__", "a_", "_é", "%_", "_%_", "a_%", "%日_",
                "___%", "_b_"]:
        rx = re.compile(
            "".join(".*" if c == "%" else "." if c == "_"
                    else re.escape(c) for c in pat), re.DOTALL)
        got = s.like(col, pat).to_pylist()
        for v, g in zip(vals, got):
            want = rx.fullmatch(v) is not None
            assert g == want, (v, pat, g, want)


def test_like_invalid_escape_patterns_raise():
    """Spark's checkLikePattern posture: the escape char must precede
    '%', '_', or itself; a trailing escape or escape of an ordinary char
    is an invalid pattern, not a silent literal (ADVICE r3)."""
    from spark_rapids_jni_tpu.ops import strings as s

    col = Column.from_pylist(["abc\\", "abc"], t.STRING)
    for bad in ["abc\\", "\\", "a\\bc", "%\\x"]:
        with pytest.raises(ValueError, match="escape"):
            s.like(col, bad)
    # the three legal escape targets still work
    assert s.like(col, "abc\\\\").to_pylist() == [True, False]
    assert s.like(col, "ab\\%").to_pylist() == [False, False]
    assert s.like(col, "ab\\_").to_pylist() == [False, False]


def test_predicates_keep_validity_none_fast_path():
    from spark_rapids_jni_tpu.ops import strings as s

    col = Column.from_pylist(["ab", "cd"], t.STRING)
    assert col.validity is None
    assert s.contains(col, "a").validity is None
    assert s.like(col, "%a%").validity is None


def test_substring_vs_python(rng):
    from spark_rapids_jni_tpu.ops import strings as s

    vals = _rand_strings(rng, 200, alphabet="abcdef", maxlen=10) + ["", None]
    col = Column.from_pylist(vals, t.STRING)
    for start, ln in [(0, 3), (2, None), (5, 2), (-3, 2), (-1, None),
                      (0, 0), (9, 5), (-20, 3), (-20, None)]:
        got = unpad(s.substring(col, start, ln))
        for i, v in enumerate(vals):
            if v is None:
                assert got[i] is None
                continue
            if start < 0:
                # Spark substringSQL: end from the UNCLAMPED position
                raw = len(v) + start
                b = max(raw, 0)
                e = len(v) if ln is None else min(max(raw + ln, 0), len(v))
                want = v[b:e] if e > b else ""
            else:
                want = v[start:] if ln is None else v[start:start + ln]
            assert got[i] == want, (v, start, ln, got[i], want)


def unpad(col):
    from spark_rapids_jni_tpu.ops.strings import unpad_strings

    return unpad_strings(col).to_pylist()


def test_upper_lower_ascii_and_guard():
    from spark_rapids_jni_tpu.ops import strings as s

    col = Column.from_pylist(["aBc9!", "", None, "XYZ"], t.STRING)
    assert unpad(s.upper(col)) == ["ABC9!", "", None, "XYZ"]
    assert unpad(s.lower(col)) == ["abc9!", "", None, "xyz"]
    # non-ASCII no longer fails loudly: host Unicode engine takes over
    assert s.upper(Column.from_pylist(["é"], t.STRING)).to_pylist() == ["É"]


def test_upper_lower_non_ascii_host_fallback():
    """Non-ASCII no longer fails loudly: it routes through the host
    Unicode engine (Java Locale.ROOT behavior, incl. one-to-many like
    ß -> SS)."""
    from spark_rapids_jni_tpu.ops import strings as s

    col = Column.from_pylist(["Straße", "ΣΊΓΜΑ", "abC", None], t.STRING)
    assert s.upper(col).to_pylist() == ["STRASSE", "ΣΊΓΜΑ", "ABC", None]
    assert s.lower(col).to_pylist() == ["straße", "σίγμα", "abc", None]
    # pure-ASCII columns still take the vectorized path (chars stay bytes)
    a = Column.from_pylist(["Mixed", "CASE"], t.STRING)
    assert s.upper(a).to_pylist() == ["MIXED", "CASE"]


def test_regexp_contains_extract_replace():
    from spark_rapids_jni_tpu.ops import strings as s

    col = Column.from_pylist(
        ["foo123bar", "nope", "a99", None, ""], t.STRING)
    got = s.regexp_contains(col, r"\d+").to_pylist()
    assert got == [True, False, True, None, False]

    ext = s.regexp_extract(col, r"([a-z]+)(\d+)", 2).to_pylist()
    assert ext == ["123", "", "99", None, ""]

    rep = s.regexp_replace(col, r"(\d+)", "<$1>").to_pylist()
    assert rep == ["foo<123>bar", "nope", "a<99>", None, ""]

    # literal dollar via Java escape
    rep2 = s.regexp_replace(col, r"\d+", "\\$").to_pylist()
    assert rep2 == ["foo$bar", "nope", "a$", None, ""]


def test_regexp_java_semantics_edges():
    from spark_rapids_jni_tpu.ops import strings as s

    # $10 with two groups: Java binds greedily but only to VALID group
    # numbers -> 10 > 2 stops the scan, so $1 ('a') then literal '0'
    col = Column.from_pylist(["a123"], t.STRING)
    assert s.regexp_replace(col, r"([a-z])(\d+)", "$10").to_pylist() == \
        ["a0"]
    # \n in a Java replacement is the LITERAL letter n, not a newline
    assert s.regexp_replace(col, r"\d+", "\\n").to_pylist() == ["an"]
    # \d is ASCII [0-9] like java.util.regex, not Unicode digits
    arabic = Column.from_pylist(["٣", "3"], t.STRING)
    assert s.regexp_contains(arabic, r"\d").to_pylist() == [False, True]
    # group number beyond the pattern's groups fails loudly
    with pytest.raises(ValueError, match="group"):
        s.regexp_replace(col, r"(\d+)", "$7")
    # possessive quantifiers compile natively (Python 3.11+ re supports
    # Java's *+ semantics)
    assert s.regexp_contains(
        Column.from_pylist(["aaab", "aaa"], t.STRING), r"a*+b"
    ).to_pylist() == [True, False]


def test_regexp_rejects_java_class_syntax_and_bad_groups():
    from spark_rapids_jni_tpu.ops import strings as s

    col = Column.from_pylist(["ab"], t.STRING)
    with pytest.raises(ValueError, match="intersection"):
        s.regexp_contains(col, r"[a-c&&[b]]")
    with pytest.raises(ValueError, match="nested"):
        s.regexp_contains(col, r"[a[b]]")
    # escaped brackets and class-internal literals stay fine
    assert s.regexp_contains(col, r"[ab]\[?").to_pylist() == [True]
    assert s.regexp_contains(col, r"a&&?b").to_pylist() == [False]
    with pytest.raises(ValueError, match="out of range"):
        s.regexp_extract(col, r"(\w)", 2)
