"""Execution telemetry & fallback accounting (spark_rapids_jni_tpu/telemetry).

Four layers under test:

1. **Registry semantics** — counters/gauges/bounded histograms are pure
   stdlib and always usable (no option flip needed).
2. **JSONL event schema** — with ``telemetry.enabled`` + ``telemetry.path``
   set, every record parses, carries kind/ts/platform, and fallback/spill
   records carry a non-empty ``reason`` (mandatory even when disabled).
3. **Instrumented seams** — the regex NUL byteset, unsupported-atom,
   force_engine pin, cast-strings host assembly, compile caches and the
   SpillStore all emit events with the reasons the ISSUE requires.
4. **Report CLI** — ``python -m spark_rapids_jni_tpu.telemetry report``
   renders the per-op device/host table from a golden ledger.
"""

import json
import os

import pytest

from spark_rapids_jni_tpu import telemetry
from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column
from spark_rapids_jni_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
)
from spark_rapids_jni_tpu.utils import config


@pytest.fixture(autouse=True)
def _reset():
    telemetry.drain()
    telemetry.REGISTRY.reset()
    yield
    telemetry.drain()
    telemetry.REGISTRY.reset()
    for name in list(config._overrides):
        config.reset_option(name)


@pytest.fixture
def enabled(tmp_path):
    path = tmp_path / "run.jsonl"
    config.set_option("telemetry.enabled", True)
    config.set_option("telemetry.path", str(path))
    return path


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


def test_counter_monotonic_and_negative_rejected():
    c = Counter("x")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)
    assert c.value == 5


def test_gauge_set_add():
    g = Gauge("staged_bytes")
    g.set(10)
    g.add(-4)
    assert g.value == 6.0


def test_histogram_buckets_and_percentiles():
    h = Histogram("wall", bounds=(1.0, 10.0, 100.0))
    for v in (0.5, 0.5, 5.0, 50.0, 500.0):  # last lands in overflow
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(556.0)
    snap = h.snapshot()
    assert snap["counts"] == [2, 1, 1, 1]
    assert snap["max"] == 500.0
    # percentiles are bucket-interpolated estimates: monotone, bounded
    p50, p95 = h.percentile(50.0), h.percentile(95.0)
    assert 0.0 < p50 <= 10.0
    assert p50 <= p95 <= 500.0
    with pytest.raises(ValueError):
        h.percentile(101.0)


def test_histogram_rejects_unsorted_bounds():
    with pytest.raises(ValueError):
        Histogram("bad", bounds=(10.0, 1.0))


def test_registry_create_on_first_use_and_prefix():
    r = Registry()
    r.counter("fallback.regexp_contains").inc()
    r.counter("fallback.regexp_contains").inc()
    r.counter("dispatch.sort").inc()
    assert r.counter("fallback.regexp_contains").value == 2
    assert r.counters("fallback.") == {"fallback.regexp_contains": 2}
    snap = r.snapshot()
    assert snap["counters"]["dispatch.sort"] == 1
    r.reset()
    assert r.counters() == {}


# ---------------------------------------------------------------------------
# event schema + config round trip
# ---------------------------------------------------------------------------


def test_disabled_records_nothing_by_default():
    assert config.get_option("telemetry.enabled") is False
    assert telemetry.record_dispatch("op") is False
    assert telemetry.events() == []


def test_fallback_reason_mandatory_even_when_disabled():
    assert config.get_option("telemetry.enabled") is False
    with pytest.raises(ValueError):
        telemetry.record_fallback("op", "")
    with pytest.raises(ValueError):
        telemetry.record_fallback("op", "   ")
    with pytest.raises(ValueError):
        telemetry.record_spill("op", "", bytes_moved=1)


def test_jsonl_schema(enabled):
    telemetry.record_dispatch(
        "sort", rows=128, dtype_widths=[8, 4], wall_ms=1.5)
    telemetry.record_fallback("regexp_contains", "unsupported atom", rows=3)
    telemetry.record_compile_cache("regex_dfa", hit=False)
    telemetry.record_spill(
        "spill_store", "budget exceeded", bytes_moved=4096, rows=10)
    telemetry.record_bench_stale(
        "groupby", stale_s=12.5, reason="TPU probe failed")
    lines = enabled.read_text().splitlines()
    assert len(lines) == 5
    recs = [json.loads(ln) for ln in lines]  # every line parses
    for rec in recs:
        assert rec["kind"] in (
            "dispatch", "fallback", "compile_cache", "spill", "bench_stale")
        assert rec["op"]
        assert isinstance(rec["ts"], float)
        assert isinstance(rec["platform"], str)
        if rec["kind"] in ("fallback", "spill", "bench_stale"):
            assert rec["reason"].strip()
    by_kind = {r["kind"]: r for r in recs}
    assert by_kind["dispatch"]["rows"] == 128
    assert by_kind["dispatch"]["dtype_widths"] == [8, 4]
    assert by_kind["dispatch"]["wall_ms"] == 1.5
    assert by_kind["fallback"]["engine"] == "host"
    assert by_kind["spill"]["bytes_moved"] == 4096
    assert by_kind["bench_stale"]["stale_s"] == 12.5
    # the ring mirrors the file
    assert [r["kind"] for r in telemetry.events()] == [r["kind"] for r in recs]
    # registry counters track the event stream
    assert telemetry.REGISTRY.counter("fallbacks_total").value == 1
    assert telemetry.REGISTRY.counter("events_total").value == 5


def test_env_round_trip(monkeypatch, tmp_path):
    """Satellite: SPARK_RAPIDS_TPU_TELEMETRY_* env vars drive the options."""
    p = tmp_path / "env.jsonl"
    monkeypatch.setenv("SPARK_RAPIDS_TPU_TELEMETRY_ENABLED", "1")
    monkeypatch.setenv("SPARK_RAPIDS_TPU_TELEMETRY_PATH", str(p))
    assert config.get_option("telemetry.enabled") is True
    assert config.get_option("telemetry.path") == str(p)
    assert telemetry.enabled() is True
    telemetry.record_dispatch("env_op", rows=1)
    assert json.loads(p.read_text())["op"] == "env_op"
    monkeypatch.setenv("SPARK_RAPIDS_TPU_TELEMETRY_ENABLED", "off")
    assert telemetry.enabled() is False


def test_sink_io_failure_never_raises(tmp_path):
    config.set_option("telemetry.enabled", True)
    config.set_option("telemetry.path", str(tmp_path))  # a directory: open fails
    assert telemetry.record_dispatch("op") is True
    assert telemetry.REGISTRY.counter("dropped_writes").value == 1


def test_summary_counts(enabled):
    telemetry.record_dispatch("sort", wall_ms=2.0)
    telemetry.record_fallback("regexp_contains", "r1")
    telemetry.record_fallback("regexp_contains", "r2")
    telemetry.record_spill("spill_store", "lru", bytes_moved=100)
    telemetry.record_compile_cache("regex_dfa", hit=True)
    s = telemetry.summary()
    assert s["events"] == 5
    assert s["dispatches"] == 1
    assert s["fallbacks"] == {"regexp_contains": 2}
    assert s["fallbacks_total"] == 2
    assert s["spill_bytes_total"] == 100
    assert s["compile_cache"] == {"hit": 1, "miss": 0}


# ---------------------------------------------------------------------------
# instrumented seams: every fallback path emits a non-empty reason
# ---------------------------------------------------------------------------


def _fallbacks(op=None):
    recs = [r for r in telemetry.events() if r["kind"] == "fallback"]
    return [r for r in recs if op is None or r["op"] == op]


def test_regex_nul_byteset_fallback(enabled):
    from spark_rapids_jni_tpu.ops import strings as s

    col = Column.from_pylist(["a\x00b", "plain"], t.STRING)
    got = s.regexp_contains(col, r"a").to_pylist()
    assert got == [True, True]
    fbs = _fallbacks("regexp_contains")
    assert len(fbs) == 1
    assert "NUL" in fbs[0]["reason"]
    assert fbs[0]["rows"] == 2


def test_regex_unsupported_atom_fallback(enabled):
    from spark_rapids_jni_tpu.ops import strings as s

    col = Column.from_pylist(["abab", "xy"], t.STRING)
    got = s.regexp_contains(col, r"(ab)\1").to_pylist()  # backref: host only
    assert got == [True, False]
    fbs = _fallbacks("regexp_contains")
    assert len(fbs) == 1
    assert "unsupported regex atom" in fbs[0]["reason"]


def test_regex_force_host_pin_fallback(enabled):
    from spark_rapids_jni_tpu.ops import strings as s

    config.set_option("regex.force_engine", "host")
    col = Column.from_pylist(["a1"], t.STRING)
    assert s.regexp_contains(col, r"\d").to_pylist() == [True]
    fbs = _fallbacks("regexp_contains")
    assert len(fbs) == 1
    assert "force_engine=host" in fbs[0]["reason"]


def test_cast_strings_host_assembly_fallback(enabled):
    from spark_rapids_jni_tpu.ops.cast_strings import integer_to_string

    col = Column.from_pylist([1, -22, None], t.INT64)
    assert integer_to_string(col).to_pylist() == ["1", "-22", None]
    fbs = _fallbacks("integer_to_string")
    assert len(fbs) == 1
    assert "host-side Arrow string assembly" in fbs[0]["reason"]


def test_compile_cache_hit_miss_events(enabled):
    from spark_rapids_jni_tpu.ops import regex_device as rd

    rd._compile_pattern_cached.cache_clear()
    rd.compile_pattern(r"zq[0-9]+x")   # miss
    rd.compile_pattern(r"zq[0-9]+x")   # hit
    recs = [r for r in telemetry.events()
            if r["kind"] == "compile_cache" and r["op"] == "regex_dfa"]
    assert [r["hit"] for r in recs] == [False, True]


def test_spill_store_emits_spill_events(enabled):
    from spark_rapids_jni_tpu.columnar import Table
    from spark_rapids_jni_tpu.runtime.memory import SpillStore, _table_nbytes

    tbl = Table([Column.from_pylist(list(range(256)), t.INT64)])
    store = SpillStore(_table_nbytes(tbl) + 8)  # room for exactly one
    h1 = store.put(tbl)
    store.put(Table([Column.from_pylist(list(range(256)), t.INT64)]))
    spills = [r for r in telemetry.events() if r["kind"] == "spill"]
    assert len(spills) == 1
    assert spills[0]["direction"] == "device_to_host"
    assert spills[0]["bytes_moved"] == _table_nbytes(tbl)
    assert spills[0]["reason"].strip()
    store.get(h1)  # staging back emits the mirror event
    spills = [r for r in telemetry.events() if r["kind"] == "spill"]
    assert [s["direction"] for s in spills] == [
        "device_to_host", "device_to_host", "host_to_device"]


def test_outofcore_spill_fallback(enabled):
    from spark_rapids_jni_tpu.columnar import Table
    from spark_rapids_jni_tpu.runtime.memory import MemoryLimiter, _table_nbytes
    from spark_rapids_jni_tpu.runtime.outofcore import run_chunked_aggregate

    chunks = [Table([Column.from_pylist(list(range(128)), t.INT64)])
              for _ in range(2)]
    nb = _table_nbytes(chunks[0])
    out = run_chunked_aggregate(
        chunks, lambda tb: tb, lambda tb: tb,
        limiter=MemoryLimiter(10 * nb),
        spill_budget_bytes=nb + 8,  # room for one partial: second one spills
    )
    assert out.spill_stats["spills"] >= 1
    fbs = _fallbacks("run_chunked_aggregate")
    assert len(fbs) == 1
    assert "spill budget" in fbs[0]["reason"]
    # SpillStore's own per-table byte accounting rides alongside
    spills = [r for r in telemetry.events() if r["kind"] == "spill"]
    assert spills and all(r["reason"].strip() for r in spills)


def test_shuffle_flag_accounting_at_jit_boundary(enabled):
    import numpy as np

    from spark_rapids_jni_tpu.parallel.shuffle import report_shuffle_telemetry

    report_shuffle_telemetry(
        overflowed=np.array(False), narrowing_overflow=np.array(False),
        rows=8)
    report_shuffle_telemetry(
        overflowed=np.array(True), narrowing_overflow=np.array(True),
        rows=8)
    kinds = [r["kind"] for r in telemetry.events()]
    assert kinds == ["dispatch", "fallback", "fallback"]
    fbs = _fallbacks("hash_shuffle")
    assert any("capacity overflow" in r["reason"] for r in fbs)
    assert any("narrowing overflow" in r["reason"] for r in fbs)


def test_trace_range_record_emits_timed_dispatch(enabled):
    from spark_rapids_jni_tpu.utils.tracing import trace_range

    with trace_range("unit_op", record=True):
        pass
    recs = [r for r in telemetry.events() if r["kind"] == "dispatch"]
    assert len(recs) == 1
    assert recs[0]["op"] == "unit_op"
    assert recs[0]["wall_ms"] >= 0.0


# ---------------------------------------------------------------------------
# report CLI on a golden ledger
# ---------------------------------------------------------------------------

_GOLDEN = [
    {"kind": "dispatch", "op": "regexp_contains", "wall_ms": 2.0},
    {"kind": "dispatch", "op": "regexp_contains", "wall_ms": 4.0},
    {"kind": "dispatch", "op": "regexp_contains", "wall_ms": 6.0},
    {"kind": "fallback", "op": "regexp_contains",
     "reason": "embedded NUL bytes alias the 0x00 padding sentinel"},
    {"kind": "spill", "op": "spill_store",
     "reason": "device spill budget exceeded: LRU eviction to host",
     "bytes_moved": 2048},
    {"kind": "compile_cache", "op": "regex_dfa", "hit": True},
]


def _write_golden(tmp_path):
    p = tmp_path / "golden.jsonl"
    lines = [json.dumps(r) for r in _GOLDEN]
    lines.insert(2, "{torn line that never finished writ")  # must be skipped
    p.write_text("\n".join(lines) + "\n")
    return p


def test_report_aggregate_golden(tmp_path):
    from spark_rapids_jni_tpu.telemetry.report import aggregate, load_jsonl

    per_op = aggregate(load_jsonl(str(_write_golden(tmp_path))))
    rc = per_op["regexp_contains"]
    # 3 calls, 1 of which fell back: 2 device / 1 host
    assert (rc["calls"], rc["device"], rc["host"]) == (3, 2, 1)
    assert rc["p50_ms"] == 4.0
    assert rc["p95_ms"] == 6.0
    assert per_op["spill_store"]["bytes_moved"] == 2048


def test_report_cli_renders_table(tmp_path, capsys):
    from spark_rapids_jni_tpu.telemetry.__main__ import main

    rc = main(["report", str(_write_golden(tmp_path))])
    out = capsys.readouterr().out
    assert rc == 0
    assert "regexp_contains" in out
    assert "device" in out and "host" in out
    assert "TOTAL" in out
    assert "embedded NUL bytes" in out  # reasons section
    assert "2.0KiB" in out


def test_report_cli_errors(tmp_path, capsys):
    from spark_rapids_jni_tpu.telemetry.__main__ import main

    assert main(["report", str(tmp_path / "missing.jsonl")]) == 1
    assert main(["not-a-command"]) == 2
    assert main([]) == 2


# ---------------------------------------------------------------------------
# session attribution (multi-query serving)
# ---------------------------------------------------------------------------


def test_session_scope_stamps_events(enabled):
    with telemetry.session_scope("tenant-a"):
        assert telemetry.current_session() == "tenant-a"
        telemetry.record_fallback("regexp", "scoped probe")
        with telemetry.session_scope("tenant-b"):  # shadow-nests
            telemetry.record_fallback("regexp", "inner probe")
        telemetry.record_fallback("regexp", "outer again")
    assert telemetry.current_session() is None
    telemetry.record_fallback("regexp", "unscoped")
    sids = [r.get("session") for r in telemetry.events()
            if r["kind"] == "fallback"]
    assert sids == ["tenant-a", "tenant-b", "tenant-a", None]


def test_session_scope_rejects_empty_id():
    with pytest.raises(ValueError):
        with telemetry.session_scope(""):
            pass


def test_record_server_event_schema(enabled):
    telemetry.record_server("tpch_q1", "served", session="s1",
                            rows=100, wall_ms=1.5)
    (rec,) = [r for r in telemetry.events() if r["kind"] == "server"]
    assert rec["event"] == "served"
    assert rec["session"] == "s1"
    assert rec["rows"] == 100
    # record_server does NOT touch counters: the serving runtime owns
    # server.* accounting unconditionally (admission must hold with
    # telemetry off), so a counter here would double-count
    assert telemetry.REGISTRY.counters("server.") == {}
    summary = telemetry.summary()
    assert summary["server"] == {"served": 1}


def test_record_server_session_mandatory_even_when_disabled():
    # disabled-path validation, same contract as record_fallback's reason
    with pytest.raises(ValueError):
        telemetry.record_server("tpch_q1", "served", session="")


# ---------------------------------------------------------------------------
# fleet events & replica attribution (runtime/fleet.py's contract)
# ---------------------------------------------------------------------------


def test_record_fleet_event_schema(enabled):
    telemetry.record_fleet("fleet.supervise", "replica_death",
                           replica="r0", error_kind="ReplicaDeadError")
    (rec,) = [r for r in telemetry.events() if r["kind"] == "fleet"]
    assert rec["event"] == "replica_death"
    assert rec["replica"] == "r0"
    assert rec["error_kind"] == "ReplicaDeadError"
    # the supervisor owns fleet.* counters unconditionally; the recorder
    # must not double-count (same contract as record_server)
    assert telemetry.REGISTRY.counters("fleet.") == {}
    assert telemetry.summary()["fleet"] == {"replica_death": 1}


def test_record_fleet_replica_mandatory_even_when_disabled():
    with pytest.raises(ValueError):
        telemetry.record_fleet("fleet.supervise", "boot", replica="")
    with pytest.raises(ValueError):
        telemetry.record_fleet("fleet.supervise", "boot", replica="r0",
                               kind="smuggled")


def test_replica_option_stamps_every_record(enabled):
    config.set_option("telemetry.replica", "r7")
    telemetry.record_server("tpch_q1", "served", session="s1")
    telemetry.record_spill("spill", nbytes=10, tier="host", reason="x")
    for rec in telemetry.events():
        assert rec["replica"] == "r7", rec


def test_two_process_shared_sink_no_torn_lines(tmp_path):
    """N replica processes appending to ONE JSONL path concurrently: every
    record lands as a single O_APPEND write(2), so a reader must see
    exactly writers x records parseable lines, each stamped with its
    writer's replica id — never two lines torn into each other."""
    import subprocess
    import sys

    path = tmp_path / "shared.jsonl"
    per_writer = 400
    code = (
        "import sys\n"
        "from spark_rapids_jni_tpu import telemetry\n"
        "for i in range(%d):\n"
        "    telemetry.record_server('tpch_q1', 'served',\n"
        "                            session='s%%d' %% i, rows=i)\n"
        % per_writer)
    procs = []
    for rid in ("r0", "r1"):
        env = dict(os.environ)
        env.update({
            "SPARK_RAPIDS_TPU_TELEMETRY_ENABLED": "1",
            "SPARK_RAPIDS_TPU_TELEMETRY_PATH": str(path),
            "SPARK_RAPIDS_TPU_TELEMETRY_REPLICA": rid,
        })
        procs.append(subprocess.Popen([sys.executable, "-c", code],
                                      env=env))
    for p in procs:
        assert p.wait(timeout=120) == 0
    lines = path.read_text().splitlines()
    assert len(lines) == 2 * per_writer
    by_replica = {}
    for line in lines:
        rec = json.loads(line)  # a torn line would fail to parse
        by_replica[rec["replica"]] = by_replica.get(rec["replica"], 0) + 1
    assert by_replica == {"r0": per_writer, "r1": per_writer}
