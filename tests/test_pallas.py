"""Pallas kernel tier tests (ops/pallas/).

The tier contract from the package docstring, pinned here:

- every maintained kernel is bit-identical to its XLA oracle twin at
  bucket-edge row counts (1, 2^k-1, 2^k, 2^k+1) including null tails —
  forcing ``kernels.tier=xla`` reproduces the pre-tier bytes exactly;
- on a backend without Mosaic support (this CPU tier) ``pallas`` runs
  the interpreter and ``auto`` falls back to XLA, both with a recorded
  reason — tier decisions are never silent (``kernels.*`` counters);
- unsupported shapes/dtypes/aggregates fall back to the oracle with the
  specific reason counted under ``kernels.fallback.<reason>``.
"""

import contextlib

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops import pallas as pallas_tier
from spark_rapids_jni_tpu.ops.groupby import groupby_aggregate_bounded
from spark_rapids_jni_tpu.ops.join import join
from spark_rapids_jni_tpu.ops.row_conversion import convert_to_rows
from spark_rapids_jni_tpu.telemetry import REGISTRY
from spark_rapids_jni_tpu.utils.config import reset_option, set_option

# bucket edges for the two kernel block sizes (groupby/probe pad to
# 2048, row transpose tiles 256 rows), plus the degenerate single row.
# Interpret-mode cost is per-trace, not per-row, so tier-1 keeps only a
# representative edge pair; the exhaustive sweep rides the slow tier.
EDGE_ROWS = [1, 255, 256, 257, 2047, 2048, 2049]
FAST_ROWS = (1, 257)


def _edge_params(sizes):
    return [n if n in FAST_ROWS
            else pytest.param(n, marks=pytest.mark.slow)
            for n in sizes]


@contextlib.contextmanager
def _tier(value, overrides=None):
    set_option("kernels.tier", value)
    if overrides is not None:
        set_option("kernels.tier_overrides", overrides)
    try:
        yield
    finally:
        reset_option("kernels.tier")
        reset_option("kernels.tier_overrides")


def _kcount(name):
    return REGISTRY.counters("kernels").get(name, 0)


def _fallback_total():
    # decide() counts a pallas pick even when the launch plan then falls
    # back, so "pallas counter grew" alone does not prove the kernel ran;
    # "no new kernels.fallback.* during the pallas run" does.
    return sum(v for k, v in REGISTRY.counters("kernels.fallback").items())


def _column_bytes(col):
    vb = b"" if col.validity is None else np.asarray(col.validity).tobytes()
    return np.asarray(col.data).tobytes() + vb


def _table_bytes(tbl):
    return [_column_bytes(c) for c in tbl.columns]


# ---------------------------------------------------------------------------
# bounded groupby accumulate
# ---------------------------------------------------------------------------

def _groupby_input(n, seed=0):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 3, n).astype(np.int32) * 5        # domain {0,5,10}
    kvalid = np.ones(n, bool)
    kvalid[-max(1, n // 4):] = False                         # null tail
    v64 = rng.integers(-(2 ** 40), 2 ** 40, n).astype(np.int64)
    v64_valid = np.ones(n, bool)
    v64_valid[-max(1, n // 8):] = False
    v8 = rng.integers(-128, 128, n).astype(np.int8)
    tbl = Table([
        Column.from_numpy(keys, validity=kvalid),
        Column.from_numpy(v64, validity=v64_valid),
        Column.from_numpy(v8),
    ])
    aggs = [(1, "sum"), (1, "count"), (1, "mean"),
            (2, "min"), (2, "max"), (2, "sum")]
    return tbl, aggs


def _run_groupby(tbl, aggs):
    res = groupby_aggregate_bounded(
        tbl, [0], aggs, key_domains=[(0, 5, 10)])
    assert not bool(res.domain_miss)
    return _table_bytes(res.table)


@pytest.mark.parametrize("n", _edge_params(EDGE_ROWS))
def test_groupby_accumulate_bit_identity_at_bucket_edges(n):
    tbl, aggs = _groupby_input(n, seed=n)
    before = _kcount("kernels.groupby.bounded_accumulate.pallas")
    fb_before = _fallback_total()
    with _tier("pallas"):
        got = _run_groupby(tbl, aggs)
    assert _kcount("kernels.groupby.bounded_accumulate.pallas") > before, \
        "pallas tier configured but the kernel never decided pallas"
    assert _fallback_total() == fb_before, \
        "pallas launch fell back: parity would compare XLA to XLA"
    with _tier("xla"):
        oracle = _run_groupby(tbl, aggs)
    assert got == oracle  # byte-for-byte, every column incl. validity


def test_groupby_tier_switch_matches_default_path():
    # the xla tier IS the legacy path: default config vs forced xla
    tbl, aggs = _groupby_input(500, seed=7)
    default = _run_groupby(tbl, aggs)
    with _tier("xla"):
        forced = _run_groupby(tbl, aggs)
    assert default == forced


# ---------------------------------------------------------------------------
# hash probe (join lo/hi bounds)
# ---------------------------------------------------------------------------

def _join_input(n_left, n_right, seed=0, key_dtype=np.int32):
    # int32 keys: the probe kernel's eligible width (int64 -> key_width)
    rng = np.random.default_rng(seed)
    lk = rng.integers(0, max(2, n_left // 2 + 1), n_left).astype(key_dtype)
    rk = rng.integers(0, max(2, n_left // 2 + 1), n_right).astype(key_dtype)
    lvalid = np.ones(n_left, bool)
    lvalid[-max(1, n_left // 4):] = False                    # null tail
    left = Table([Column.from_numpy(lk, validity=lvalid)])
    right = Table([Column.from_numpy(rk)])
    return left, right


def _run_join(left, right, how):
    out_size = (left.num_rows + 1) * (right.num_rows + 1)
    maps = join(left, right, 0, 0, min(out_size, 1 << 20), how=how)
    return [np.asarray(f).tobytes() for f in maps]


@pytest.mark.parametrize(
    "how, n_right",
    # 2049 exceeds MAX_BUILD; tier-1 keeps every `how` at one edge pair,
    # the full build-size sweep per `how` is slow-tier
    [pytest.param(how, n,
                  marks=() if n == 257 or (how, n) == ("inner", 1)
                  else pytest.mark.slow)
     for how in ("inner", "left", "full") for n in EDGE_ROWS[:-1]])
def test_hash_probe_bit_identity_at_bucket_edges(n_right, how):
    left, right = _join_input(257, n_right, seed=n_right)
    fb_before = _fallback_total()
    with _tier("pallas"):
        got = _run_join(left, right, how)
    # a cached executable may replay without re-deciding, but a fresh
    # trace must never have silently fallen back under the pallas tier
    assert _fallback_total() == fb_before
    with _tier("xla"):
        oracle = _run_join(left, right, how)
    assert got == oracle


def _probe_side_sweep(sizes):
    # probe-side row counts sweep the tile edges too
    for n_left in sizes:
        left, right = _join_input(n_left, 256, seed=n_left)
        with _tier("pallas"):
            got = _run_join(left, right, "inner")
        with _tier("xla"):
            oracle = _run_join(left, right, "inner")
        assert got == oracle, f"n_left={n_left}"


def test_hash_probe_probe_side_edges():
    _probe_side_sweep(FAST_ROWS)


@pytest.mark.slow
def test_hash_probe_probe_side_edges_full_sweep():
    _probe_side_sweep([n for n in EDGE_ROWS if n not in FAST_ROWS])


# ---------------------------------------------------------------------------
# ragged row transpose (to-rows assembly)
# ---------------------------------------------------------------------------

def _rows_input(n, seed=0):
    rng = np.random.default_rng(seed)
    valid = np.ones(n, bool)
    valid[-max(1, n // 4):] = False                          # null tail
    return Table([
        Column.from_numpy(rng.integers(-(2 ** 60), 2 ** 60, n)
                          .astype(np.int64), validity=valid),
        Column.from_numpy(rng.integers(-100, 100, n).astype(np.int8)),
        Column.from_numpy(rng.random(n).astype(np.float64)),
        Column.from_numpy((rng.random(n) > 0.5).astype(np.uint8),
                          dtype=t.BOOL8, validity=valid),
        Column.from_numpy(rng.integers(-1000, 1000, n).astype(np.int16),
                          validity=valid),
    ])


def _run_to_rows(tbl):
    batches = convert_to_rows(tbl)
    return [(b.num_rows, b.row_size, np.asarray(b.data).tobytes())
            for b in batches]


@pytest.mark.parametrize("n", _edge_params(EDGE_ROWS[:4]))  # 256-row tiles
def test_row_transpose_bit_identity_at_bucket_edges(n):
    tbl = _rows_input(n, seed=n)
    fb_before = _fallback_total()
    with _tier("pallas"):
        got = _run_to_rows(tbl)
    assert _fallback_total() == fb_before
    with _tier("xla"):
        oracle = _run_to_rows(tbl)
    assert got == oracle


# ---------------------------------------------------------------------------
# tier decisions, fallbacks, telemetry
# ---------------------------------------------------------------------------

def test_decide_on_cpu_backend():
    # pallas off-TPU -> interpreter, recorded; auto -> recorded xla fallback
    with _tier("pallas"):
        before = _kcount("kernels.interpret")
        d = pallas_tier.decide("groupby.bounded_accumulate")
        assert d.tier == "pallas" and d.mode == "interpret"
        assert d.reason == "no_pallas_backend"
        assert _kcount("kernels.interpret") == before + 1
    with _tier("auto"):
        before = _kcount("kernels.fallback.no_pallas_backend")
        d = pallas_tier.decide("groupby.bounded_accumulate")
        assert d.tier == "xla" and d.mode == "oracle"
        assert _kcount("kernels.fallback.no_pallas_backend") == before + 1
    with _tier("xla"):
        d = pallas_tier.decide("groupby.bounded_accumulate")
        assert d.tier == "xla" and d.reason == "config"


def test_tier_overrides_are_per_op():
    with _tier("xla", overrides="join.hash_probe=pallas"):
        assert pallas_tier.resolved_tier("join.hash_probe") == "pallas"
        assert pallas_tier.resolved_tier(
            "groupby.bounded_accumulate") == "xla"


def test_env_var_wins_over_config(monkeypatch):
    monkeypatch.setenv("SPARK_RAPIDS_TPU_KERNEL_TIER", "pallas")
    with _tier("xla"):
        assert pallas_tier.resolved_tier("join.hash_probe") == "pallas"


def test_invalid_tier_rejected():
    with _tier("warp"):
        with pytest.raises(ValueError, match="kernels.tier"):
            pallas_tier.resolved_tier("join.hash_probe")


def test_fallback_minmax_width_recorded():
    # min/max on an int64 column exceeds the int32 lane: whole launch
    # routes to the oracle with the reason counted, bytes unchanged
    tbl, _ = _groupby_input(300, seed=3)
    aggs = [(1, "sum"), (1, "max")]
    before = _kcount("kernels.fallback.minmax_width")
    with _tier("pallas"):
        got = _run_groupby(tbl, aggs)
    assert _kcount("kernels.fallback.minmax_width") == before + 1
    with _tier("xla"):
        oracle = _run_groupby(tbl, aggs)
    assert got == oracle


def test_fallback_build_too_large_recorded():
    left, right = _join_input(64, 2049, seed=9)              # > MAX_BUILD
    before = _kcount("kernels.fallback.build_too_large")
    with _tier("pallas"):
        got = _run_join(left, right, "inner")
    assert _kcount("kernels.fallback.build_too_large") >= before + 1
    with _tier("xla"):
        oracle = _run_join(left, right, "inner")
    assert got == oracle


def test_fallback_key_width_recorded():
    # int64 keys exceed the probe kernel's int32 lane width
    left, right = _join_input(48, 96, seed=13, key_dtype=np.int64)
    before = _kcount("kernels.fallback.key_width")
    with _tier("pallas"):
        got = _run_join(left, right, "inner")
    assert _kcount("kernels.fallback.key_width") >= before + 1
    with _tier("xla"):
        oracle = _run_join(left, right, "inner")
    assert got == oracle


def test_fresh_trace_counts_pallas_decisions():
    # shapes unseen anywhere else in this module so dispatch must trace
    # fresh (a cached executable replays without re-deciding): each
    # kernel's decide() lands exactly in the pallas column, no fallback
    fb_before = _fallback_total()
    probes = {
        "kernels.groupby.bounded_accumulate.pallas":
            _kcount("kernels.groupby.bounded_accumulate.pallas"),
        "kernels.join.hash_probe.pallas":
            _kcount("kernels.join.hash_probe.pallas"),
        "kernels.row_conversion.to_rows.pallas":
            _kcount("kernels.row_conversion.to_rows.pallas"),
        "kernels.interpret": _kcount("kernels.interpret"),
    }
    with _tier("pallas"):
        tbl, aggs = _groupby_input(77, seed=77)
        _run_groupby(tbl, aggs)
        left, right = _join_input(39, 83, seed=77)
        _run_join(left, right, "inner")
        _run_to_rows(_rows_input(91, seed=77))
    for name, before in probes.items():
        assert _kcount(name) > before, name
    assert _fallback_total() == fb_before


def test_fallback_row_too_wide_recorded():
    # 33 int64 columns -> 264 data bytes/row, over the 256-byte tile
    rng = np.random.default_rng(11)
    tbl = Table([
        Column.from_numpy(rng.integers(-100, 100, 16).astype(np.int64))
        for _ in range(33)
    ])
    before = _kcount("kernels.fallback.row_too_wide")
    with _tier("pallas"):
        got = _run_to_rows(tbl)
    assert _kcount("kernels.fallback.row_too_wide") == before + 1
    with _tier("xla"):
        oracle = _run_to_rows(tbl)
    assert got == oracle


def test_registry_declares_oracles():
    specs = pallas_tier.registered()
    for op in ("groupby.bounded_accumulate", "join.hash_probe",
               "row_conversion.to_rows"):
        assert op in specs, f"{op} never registered"
        assert specs[op].oracle.strip(), f"{op} registered without oracle"

    import spark_rapids_jni_tpu.ops.pallas_q1  # noqa: F401  (registers q1)

    specs = pallas_tier.registered()
    assert "tpch_q1.fused" in specs
    assert specs["tpch_q1.fused"].oracle.strip()
