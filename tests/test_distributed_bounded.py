"""Shuffle-free distributed bounded groupby (parallel/distributed.py).

The bounded plan's static slot table makes the cross-device merge a
psum/pmin/pmax over m rows instead of a row shuffle — these tests pin
oracle equality on the 8-device CPU mesh, string-key encoding under
shard_map, min/max sentinel handling, domain-miss propagation from a
single shard, the replicated-output contract, and the scope guards.
"""

import numpy as np
import pytest

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.planner import scalar_domain, string_domain
from spark_rapids_jni_tpu.parallel.distributed import (
    distributed_groupby_bounded,
    shard_table,
)
from spark_rapids_jni_tpu.parallel.mesh import executor_mesh


def _result_rows(res, nkeys=1):
    out = {}
    cols = [c.to_pylist() for c in res.table.columns]
    present = np.asarray(res.present)
    for i in range(len(cols[0])):
        key = tuple(cols[k][i] for k in range(nkeys))
        if not present[i] or any(k is None for k in key):
            continue
        out[key] = tuple(cols[k][i] for k in range(nkeys, len(cols)))
    return out


def test_scalar_keys_match_oracle(rng):
    n = 1000
    k = rng.integers(0, 4, n).astype(np.int32)
    v = rng.integers(-100, 100, n).astype(np.int64)
    tbl = Table([Column.from_numpy(k), Column.from_numpy(v)])
    mesh = executor_mesh()
    sharded = shard_table(tbl, mesh)
    res = distributed_groupby_bounded(
        sharded, [0], [(1, "sum"), (1, "count"), (1, "min"), (1, "max")],
        [scalar_domain(range(4))], mesh)
    assert not bool(res.domain_miss)
    got = _result_rows(res)
    oracle = {}
    for i in range(n):
        key = (int(k[i]),)
        s, c, lo, hi = oracle.get(key, (0, 0, 10**9, -10**9))
        oracle[key] = (s + int(v[i]), c + 1, min(lo, int(v[i])),
                       max(hi, int(v[i])))
    assert got == oracle


def test_string_keys_under_shard_map(rng):
    n = 640
    modes = ["AIR", "MAIL", "SHIP"]
    idx = rng.integers(0, 3, n)
    v = rng.integers(0, 50, n).astype(np.int64)
    tbl = Table([
        Column.from_pylist([modes[i] for i in idx], t.STRING),
        Column.from_numpy(v),
    ])
    mesh = executor_mesh()
    sharded = shard_table(tbl, mesh)
    res = distributed_groupby_bounded(
        sharded, [0], [(1, "sum")], [string_domain(modes)], mesh)
    got = {k[0]: s[0] for k, s in _result_rows(res).items()}
    oracle = {}
    for i in range(n):
        oracle[modes[idx[i]]] = oracle.get(modes[idx[i]], 0) + int(v[i])
    assert got == oracle


def test_domain_miss_propagates_from_one_shard(rng):
    n = 64
    k = np.zeros(n, np.int32)
    k[-1] = 99  # out of domain, lands on the last device's shard
    tbl = Table([Column.from_numpy(k),
                 Column.from_numpy(np.ones(n, np.int64))])
    mesh = executor_mesh()
    res = distributed_groupby_bounded(
        shard_table(tbl, mesh), [0], [(1, "sum")],
        [scalar_domain([0, 1])], mesh)
    assert bool(res.domain_miss)


def test_groups_absent_everywhere_not_present(rng):
    tbl = Table([
        Column.from_numpy(np.zeros(16, np.int32)),
        Column.from_numpy(np.ones(16, np.int64)),
    ])
    mesh = executor_mesh()
    res = distributed_groupby_bounded(
        shard_table(tbl, mesh), [0], [(1, "sum")],
        [scalar_domain([0, 1, 2])], mesh)
    got = _result_rows(res)
    assert got == {(0,): (16,)}


def test_mean_and_decimal128_rejected():
    tbl = Table([
        Column.from_numpy(np.zeros(8, np.int32)),
        Column.from_numpy(np.ones(8, np.int64)),
        Column.from_pylist([1 << 70] * 8, t.decimal128(-2)),
    ])
    mesh = executor_mesh()
    sharded = shard_table(tbl, mesh)
    with pytest.raises(ValueError, match="decompose mean"):
        distributed_groupby_bounded(
            sharded, [0], [(1, "mean")], [scalar_domain([0])], mesh)
    with pytest.raises(NotImplementedError, match="DECIMAL128"):
        distributed_groupby_bounded(
            sharded, [0], [(2, "sum")], [scalar_domain([0])], mesh)


def test_output_replicated_not_sharded(rng):
    """The result is the same m-slot table on every device — consumable
    by the next stage without a broadcast."""
    n = 256
    tbl = Table([
        Column.from_numpy(rng.integers(0, 3, n).astype(np.int32)),
        Column.from_numpy(rng.integers(0, 9, n).astype(np.int64)),
    ])
    mesh = executor_mesh()
    res = distributed_groupby_bounded(
        shard_table(tbl, mesh), [0], [(1, "sum")],
        [scalar_domain(range(3))], mesh)
    # a replicated array's global shape equals its per-device shape (m
    # slots + null slot = 4), NOT devices * m
    assert res.table.column(0).data.shape[0] == 4
    assert res.present.shape[0] == 4


def test_nondivisible_rows_no_phantom_null_group(rng):
    """n not a multiple of the device count: shard_table padding rows
    must NOT surface as a present null-key slot when the row_valid mask
    is passed (regression: padding rows landed in the null slot and
    rows_per_group counted them)."""
    n = 1001  # 8 devices -> 7 padding rows
    k = rng.integers(0, 3, n).astype(np.int32)
    v = rng.integers(0, 10, n).astype(np.int64)
    tbl = Table([Column.from_numpy(k), Column.from_numpy(v)])
    mesh = executor_mesh()
    sharded, rv = shard_table(tbl, mesh, return_row_valid=True)
    res = distributed_groupby_bounded(
        sharded, [0], [(1, "sum"), (1, "count")],
        [scalar_domain(range(3))], mesh, row_valid=rv)
    assert not bool(res.domain_miss)
    # the null slot (key validity False) must not be present
    present = np.asarray(res.present)
    kvalid = np.asarray(res.table.column(0).valid_mask())
    assert not (present & ~kvalid).any()
    got = _result_rows(res)
    oracle = {}
    for i in range(n):
        key = (int(k[i]),)
        s, c = oracle.get(key, (0, 0))
        oracle[key] = (s + int(v[i]), c + 1)
    assert got == oracle


def test_missing_domain_raises_eagerly():
    tbl = Table([Column.from_numpy(np.zeros(8, np.int32)),
                 Column.from_numpy(np.ones(8, np.int64))])
    mesh = executor_mesh()
    sharded = shard_table(tbl, mesh)
    with pytest.raises(ValueError, match="declared Domain"):
        distributed_groupby_bounded(sharded, [0], [(1, "sum")],
                                    [None], mesh)
    with pytest.raises(ValueError, match="exceeds the bounded budget"):
        distributed_groupby_bounded(
            sharded, [0], [(1, "sum")],
            [scalar_domain(range(100))], mesh, budget=10)


def test_q72_planned_distributed_zero_shuffle_matches_oracle():
    """Distributed planned q72: replicated dims, per-device dense-PK
    lookups + dense-id counts, one psum — no shuffle anywhere. Oracle
    equality on the 8-device mesh with non-divisible row counts."""
    from spark_rapids_jni_tpu.models import tpcds

    n = 3001  # not divisible by 8: exercises the row_valid padding path
    cs = tpcds.catalog_sales_table(n, num_items=40, num_days=300)
    dd = tpcds.date_dim_table(300)
    it = tpcds.item_table(40)
    inv = tpcds.inventory_table(num_items=40, num_weeks=50)
    mesh = executor_mesh()
    res = tpcds.tpcds_q72_planned_distributed(cs, dd, it, inv, mesh)
    assert not bool(res.pk_violation)
    oracle = tpcds.tpcds_q72_numpy(cs, dd, it, inv)
    tbl = res.table
    sk = tbl.column(0).to_pylist()
    br = tbl.column(1).to_pylist()
    ct = tbl.column(2).to_pylist()
    got = {(sk[i], br[i]): ct[i] for i in range(tbl.num_rows)
           if sk[i] is not None and ct[i] and ct[i] > 0}
    assert got == oracle
    # and the single-device planned plan agrees
    single = tpcds.tpcds_q72_planned(cs, dd, it, inv)
    s_sk = single.table.column(0).to_pylist()
    s_ct = single.table.column(2).to_pylist()
    s_got = {s_sk[i]: s_ct[i] for i in range(single.table.num_rows)
             if s_sk[i] is not None and s_ct[i] and s_ct[i] > 0}
    assert s_got == {k[0]: v for k, v in got.items()}


def test_q3_planned_distributed_broadcast_plan_matches_oracle():
    """Broadcast-plan distributed q3: replicated dims, per-device
    dense-PK lookups, one partial-aggregate exchange — vs the general
    plan's two row exchanges. Oracle equality, non-divisible rows."""
    from spark_rapids_jni_tpu.models.tpch import (
        customer_table,
        lineitem_q3_table,
        orders_table,
        tpch_q3_numpy,
        tpch_q3_planned_distributed,
    )

    n_cust, n_ord, n = 32, 120, 1003
    c = customer_table(n_cust)
    o = orders_table(n_ord, n_cust)
    li = lineitem_q3_table(n, n_ord)
    mesh = executor_mesh()
    out = tpch_q3_planned_distributed(c, o, li, mesh)
    oracle = tpch_q3_numpy(c, o, li)
    keys = out.column(0).to_pylist()
    dates = out.column(1).to_pylist()
    prios = out.column(2).to_pylist()
    revs = out.column(3).to_pylist()
    got = {keys[i]: (revs[i], dates[i], prios[i])
           for i in range(out.num_rows) if keys[i] is not None}
    assert got == oracle


def test_q5_distributed_zero_shuffle_matches_single_and_oracle():
    from spark_rapids_jni_tpu.models.tpch import (
        customer_q5_table,
        lineitem_q5_table,
        nation_table,
        orders_table,
        supplier_table,
        tpch_q5,
        tpch_q5_distributed,
        tpch_q5_numpy,
    )

    n_cust, n_ord, n_supp, n = 48, 160, 24, 1405  # non-divisible by 8
    c = customer_q5_table(n_cust)
    o = orders_table(n_ord, n_cust)
    li = lineitem_q5_table(n, n_ord, n_supp)
    su = supplier_table(n_supp)
    na = nation_table()
    mesh = executor_mesh()
    res = tpch_q5_distributed(c, o, li, su, na, mesh)
    assert not bool(res.pk_violation) and not bool(res.domain_miss)
    oracle = tpch_q5_numpy(c, o, li, su, na)
    keys = res.table.column(0).to_pylist()
    revs = res.table.column(1).to_pylist()
    present = np.asarray(res.present)
    got = {keys[i]: revs[i] for i in range(res.table.num_rows)
           if present[i] and keys[i] is not None and revs[i]}
    assert got == {k: v for k, v in oracle.items() if v}
    single = tpch_q5(c, o, li, su, na)
    s_keys = single.table.column(0).to_pylist()
    s_revs = single.table.column(1).to_pylist()
    s_present = np.asarray(single.present)
    s_got = {s_keys[i]: s_revs[i]
             for i in range(single.table.num_rows)
             if s_present[i] and s_keys[i] is not None and s_revs[i]}
    assert s_got == got


def test_outofcore_times_distributed_composition(tmp_path):
    """The SF1000 execution model in miniature: a Parquet file larger
    than the budget streams in row-group chunks, EACH chunk runs the
    shuffle-free bounded q1 groupby across the 8-device mesh, and the
    static slot tables merge across chunks by addition (the same
    associativity that made the mesh merge a psum makes the chunk
    merge a running sum) — out-of-core over TIME composed with
    distribution over SPACE, no shuffle in either axis."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_jni_tpu.models.tpch import lineitem_table
    from spark_rapids_jni_tpu.ops.planner import scalar_domain
    from spark_rapids_jni_tpu.parallel.distributed import (
        distributed_groupby_bounded,
    )
    from spark_rapids_jni_tpu.parquet.reader import ParquetChunkedReader
    from spark_rapids_jni_tpu.runtime.memory import (
        MemoryLimiter,
        _table_nbytes,
    )
    from spark_rapids_jni_tpu.runtime.outofcore import run_chunked_aggregate

    n = 48_000
    li = lineitem_table(n)
    pa_table = pa.table({
        "l_quantity": pa.array(np.asarray(li.column(0).data),
                               type=pa.int64()),
        "l_returnflag": pa.array(np.asarray(li.column(4).data),
                                 type=pa.int8()),
    })
    path = str(tmp_path / "li.parquet")
    pq.write_table(pa_table, path, row_group_size=6_000)  # 8 chunks
    mesh = executor_mesh()
    dom = [scalar_domain([ord("A"), ord("N"), ord("R")])]

    def partial_fn(chunk):
        sharded, rv = shard_table(
            Table([chunk.column(1), chunk.column(0)]), mesh,
            return_row_valid=True)
        res = distributed_groupby_bounded(
            sharded, [0], [(1, "sum"), (1, "count")], dom, mesh,
            row_valid=rv)
        assert not bool(res.domain_miss)
        return res.table  # replicated 4-slot table

    def merge_fn(partials):
        # k stacked 4-slot tables: per-slot running sums (associative)
        k = partials.num_rows // 4
        key = partials.column(0).data.reshape(k, 4)[0]
        kv = partials.column(0).valid_mask().reshape(k, 4).any(axis=0)
        sums = partials.column(1)
        cnts = partials.column(2)
        import jax.numpy as jnp

        s = jnp.where(sums.valid_mask(), sums.data, 0) \
            .reshape(k, 4).sum(axis=0)
        c = jnp.where(cnts.valid_mask(), cnts.data, 0) \
            .reshape(k, 4).sum(axis=0)
        live = c > 0
        return Table([
            Column(partials.column(0).dtype, key, kv & live),
            Column(sums.dtype, s, live),
            Column(cnts.dtype, c, live),
        ])

    budget = _table_nbytes(li)  # generous vs the 2-col stream
    res = run_chunked_aggregate(
        iter(ParquetChunkedReader(path, chunk_read_limit=1)),
        partial_fn, merge_fn, limiter=MemoryLimiter(budget))
    assert res.chunks == 8
    keys = res.table.column(0).to_pylist()
    sums = res.table.column(1).to_pylist()
    cnts = res.table.column(2).to_pylist()
    got = {keys[i]: (sums[i], cnts[i]) for i in range(4)
           if keys[i] is not None and cnts[i]}
    qty = np.asarray(li.column(0).data)
    rf = np.asarray(li.column(4).data)
    oracle = {}
    for f in (ord("A"), ord("N"), ord("R")):
        m = rf == f
        if m.any():
            oracle[f] = (int(qty[m].sum()), int(m.sum()))
    assert got == oracle
