"""Device JSONPath engine vs the native host engine (the semantic oracle):
randomized well-formed documents plus adversarial structural cases, same
column through both engines, exact equality required (SURVEY.md section 4
round-trip/golden-equality shape)."""

import json
import random

import numpy as np
import pytest

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column
from spark_rapids_jni_tpu.ops import json_device as jd
from spark_rapids_jni_tpu.ops.get_json_object import (
    get_json_object,
    get_json_object_host,
)


def string_column(values):
    return Column.from_pylist(values, t.STRING)


def _rand_value(rng, depth):
    r = rng.random()
    if depth >= 3 or r < 0.35:
        return rng.choice([
            17, -3, 2.5, 1e3, True, False, None, "plain", "", "x y",
            "été",  # utf-8 multibyte, no escapes
        ])
    if r < 0.6:
        return {k: _rand_value(rng, depth + 1)
                for k in rng.sample(["a", "b", "field", "nm", "z9"],
                                    rng.randint(0, 4))}
    return [_rand_value(rng, depth + 1) for _ in range(rng.randint(0, 4))]


def _dumps(rng, obj):
    # vary whitespace: compact, spaced, or sprinkled newlines
    style = rng.random()
    if style < 0.4:
        return json.dumps(obj, separators=(",", ":"), ensure_ascii=False)
    if style < 0.8:
        return json.dumps(obj, ensure_ascii=False)
    return json.dumps(obj, indent=1, ensure_ascii=False)


PATHS = ["$", "$.a", "$.field", "$.nm.a", "$.a.b", "$.a[0]", "$.a[1]",
         "$['field']", "$.a[2].b", "$.b.field", "$.z9"]


def test_device_engine_matches_native_randomized():
    rng = random.Random(1234)
    docs = []
    for _ in range(300):
        docs.append(_dumps(rng, {
            k: _rand_value(rng, 1)
            for k in rng.sample(["a", "b", "field", "nm", "z9"],
                                rng.randint(0, 5))
        }))
    docs += [None, "", "   "]
    col = string_column(docs)
    assert bool(jd.device_eligible(col))
    for path in PATHS:
        dev = jd.get_json_object_device(col, path).to_pylist()
        host = get_json_object_host(col, path).to_pylist()
        assert dev == host, f"path {path}: {dev[:8]} != {host[:8]}"


def test_device_engine_adversarial_structurals():
    docs = [
        '{"x":"field","field":1}',          # value string shadows a key
        '{"x":"field"}',                    # only the shadow, no real key
        '{"a":{"field":0},"field":2}',      # deeper same-name key first
        '{"field":{"field":3}}',            # same name chained
        '{"a":[{"field":1},{"field":2}]}',  # keys inside array elements
        '{"field":[]}',                     # empty array
        '{"field":{}}',                     # empty object
        '{"field":""}',                     # empty string value
        '{"field":null}',                   # JSON null -> SQL NULL
        '{ "field" : 42 }',                 # spaced
        '{"field":[1,[2,3],{"a":4}]}',      # nested array mix
        '{"fiel":1,"fielded":2,"field":3}', # prefix/suffix name confusion
        '[1,2,3]',                          # root array
        '"rootstr"',                        # root string
        '17',                               # root scalar
        'null',                             # root null
        '{}',                               # empty root
    ]
    col = string_column(docs)
    assert bool(jd.device_eligible(col))
    for path in ["$", "$.field", "$.field[1]", "$.a[1]", "$.a[1].field",
                 "$.field.field", "$.a[2].a", "$[1]"]:
        dev = jd.get_json_object_device(col, path).to_pylist()
        host = get_json_object_host(col, path).to_pylist()
        assert dev == host, f"path {path}: {dev} != {host}"


def test_trailing_garbage_routes_to_host():
    # balanced-but-invalid grammar (content past the root value) is exactly
    # what the device sanity check must exclude; the dispatcher then gives
    # the host engine's answer
    docs = ['{"a":1}garbage', '17 garbage', '"s" x', '{"a":2}']
    col = string_column(docs)
    assert not bool(jd.device_eligible(col))
    assert (get_json_object(col, "$.a").to_pylist()
            == get_json_object_host(col, "$.a").to_pylist())


def test_dispatcher_routes_escapes_to_host():
    col = string_column(['{"s": "es\\"caped"}', '{"s": 1}'])
    assert not bool(jd.device_eligible(col))
    assert get_json_object(col, "$.s").to_pylist() == ['es"caped', "1"]


def test_dispatcher_rejects_bad_paths_before_engine_choice():
    col = string_column(['{"a": 1}'])
    with pytest.raises(ValueError):
        get_json_object(col, "$.a[*]")
    with pytest.raises(ValueError):
        get_json_object(col, "no-dollar")


def test_device_engine_width_edge():
    # rows whose key window sits at the very end of the char matrix
    docs = ['{"k":1}', '{"kk":22}', '{"a":1,"k":9}']
    col = string_column(docs)
    dev = jd.get_json_object_device(col, "$.k").to_pylist()
    host = get_json_object_host(col, "$.k").to_pylist()
    assert dev == host == ["1", None, "9"]


def test_json_tuple_fields():
    from spark_rapids_jni_tpu import types as t
    from spark_rapids_jni_tpu.columnar import Column
    from spark_rapids_jni_tpu.ops.get_json_object import json_tuple

    docs = ['{"a": 1, "b": "x"}', '{"b": "y"}', None, '{"a": null}']
    col = Column.from_pylist(docs, t.STRING)
    a, b = json_tuple(col, "a", "b")
    assert a.to_pylist() == ["1", None, None, None]
    assert b.to_pylist() == ["x", "y", None, None]
    import pytest as _pt

    with _pt.raises(ValueError, match="at least one"):
        json_tuple(col)
    with _pt.raises(ValueError, match="plain top-level"):
        json_tuple(col, "a.b")
    with _pt.raises(ValueError, match="plain top-level"):
        json_tuple(col, "*")
